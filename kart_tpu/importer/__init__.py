"""Import sources (reference: kart/import_source.py, ogr_import_source.py,
sqlalchemy_import_source.py).

No OGR in this stack: GPKG is read directly with stdlib sqlite3 (the format
the reference's test data uses), GeoJSON/CSV with stdlib parsers. Each source
exposes schema, meta items, CRS definitions and a feature stream.
"""

import csv
import json
import logging
import os
import sqlite3

from kart_tpu.adapters import gpkg as gpkg_adapter
from kart_tpu.core.serialise import ensure_text
from kart_tpu.crs import get_identifier_str
from kart_tpu.geometry import Geometry, geojson_to_geometry
from kart_tpu.models.schema import ColumnSchema, Schema

L = logging.getLogger(__name__)


class ImportSourceError(ValueError):
    pass


class ImportSource:
    """A table to import: schema + streamed features + meta."""

    dest_path = None

    def default_dest_path(self):
        raise NotImplementedError

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    def features(self):
        raise NotImplementedError

    def get_features(self, pks, ignore_missing=False):
        """Yield the features with the given (single-column) primary keys
        (reference: import_source.py get_features). Default: one scan of
        features(); sources with indexed storage override with point reads.
        Order of the result is not significant."""
        wanted = set(pks)
        if not wanted:
            return
        pk_col = self.schema.pk_columns[0].name
        found = set()
        for feature in self.features():
            pk = feature.get(pk_col)
            if pk in wanted:
                found.add(pk)
                yield feature
        if not ignore_missing and found != wanted:
            missing = sorted(wanted - found, key=str)[:5]
            raise ImportSourceError(
                f"Source has no feature(s) with id: {missing}"
            )

    @property
    def feature_count(self):
        return sum(1 for _ in self.features())

    def meta_items(self):
        """{'title': ..., 'description': ..., 'crs/<id>.wkt': ...}"""
        return {}

    def post_import_meta_items(self):
        """Meta items only known after features() has been consumed
        (e.g. generated-pks.json)."""
        return {}

    def crs_definitions(self):
        """{identifier: wkt}"""
        return {}

    def with_primary_key(self, pk_name):
        """This source with ``pk_name`` as the primary key instead of its
        natural/synthesized one (`kart import --primary-key`; reference:
        kart/init.py:166-169 + sqlalchemy_import_source.py). The named
        column must exist; the previous pk column stays as ordinary data."""
        cols = list(self.schema.columns)
        if pk_name not in {c.name for c in cols}:
            raise ImportSourceError(
                f"--primary-key: no column named {pk_name!r} in "
                f"{self.dest_path!r} (columns: "
                f"{', '.join(c.name for c in cols)})"
            )
        if [c.name for c in self.schema.pk_columns] == [pk_name]:
            # already the pk: keep the native source (and its fast paths)
            return self

        def extra_for(c):
            extra = dict(c.extra_type_info or {})
            if c.name == pk_name and c.data_type == "integer":
                # pk integers are stored as size 64 everywhere (the GPKG
                # WC roundtrips them as INTEGER PRIMARY KEY) — match the
                # natural pk-producing paths or checkout shows a permanent
                # spurious schema diff
                extra["size"] = 64
            return extra

        new_cols = [
            ColumnSchema(
                c.id,
                c.name,
                c.data_type,
                0 if c.name == pk_name else None,
                extra_for(c),
            )
            for c in cols
        ]
        # pk first, like every natural source emits
        new_cols.sort(key=lambda c: (c.pk_index is None, ))
        return _PrimaryKeyOverrideSource(self, Schema(new_cols))

    @classmethod
    def open(cls, spec, table=None):
        """Sniff a path/spec -> list of ImportSource (one per table)
        (reference: import_source.py:26)."""
        lowered = spec.lower()
        if lowered.endswith(".gpkg"):
            return GPKGImportSource.open_all(spec, table=table)
        if lowered.endswith((".geojsonl", ".ndjson", ".geojsons")):
            return [GeoJSONSeqImportSource(spec)]
        if lowered.endswith((".geojson", ".json")):
            return [GeoJSONImportSource(spec)]
        if lowered.endswith(".csv"):
            return [CSVImportSource(spec)]
        if lowered.endswith(".shp"):
            from kart_tpu.importer.shapefile import ShapefileImportSource

            return [ShapefileImportSource(spec)]
        if lowered.endswith(".fgb"):
            from kart_tpu.importer.flatgeobuf import FlatGeobufImportSource

            return [FlatGeobufImportSource(spec)]
        if lowered.endswith(".zip"):
            return [_open_zipped_shapefile(spec)]
        if spec.startswith(("postgresql://", "postgres://")):
            from kart_tpu.importer.postgres import PostgresImportSource

            return PostgresImportSource.open_all(spec, table=table)
        if spec.startswith("mysql://"):
            from kart_tpu.importer.mysql import MySqlImportSource

            return MySqlImportSource.open_all(spec, table=table)
        if spec.startswith(("mssql://", "sqlserver://")):
            from kart_tpu.importer.sqlserver import SqlServerImportSource

            return SqlServerImportSource.open_all(spec, table=table)
        raise ImportSourceError(
            f"Don't know how to import {spec!r} — supported: .gpkg, .shp, "
            f".zip (shapefile), .fgb, .geojson, .geojsonl/.ndjson, .csv, "
            f"postgresql://, mysql://, mssql://"
        )


class _PrimaryKeyOverrideSource(ImportSource):
    """Delegating wrapper produced by :meth:`ImportSource.with_primary_key`:
    identical feature stream, re-keyed schema."""

    def __init__(self, inner, schema):
        self.inner = inner
        self._schema = schema
        self.dest_path = inner.dest_path

    @property
    def schema(self) -> Schema:
        return self._schema

    def features(self):
        return self.inner.features()

    @property
    def feature_count(self):
        return self.inner.feature_count

    def meta_items(self):
        return self.inner.meta_items()

    def post_import_meta_items(self):
        return self.inner.post_import_meta_items()

    def crs_definitions(self):
        return self.inner.crs_definitions()


def _open_zipped_shapefile(spec):
    """A .zip containing a shapefile (the common distribution form OGR's
    /vsizip/ handles): extract the sidecar set to a temp dir that lives as
    long as the source object."""
    import tempfile
    import zipfile

    from kart_tpu.importer.shapefile import ShapefileImportSource

    try:
        zf = zipfile.ZipFile(spec)
    except (OSError, zipfile.BadZipFile) as e:
        raise ImportSourceError(f"Cannot read {spec!r}: {e}")
    with zf:
        shp_names = [
            n for n in zf.namelist()
            if n.lower().endswith(".shp") and not n.startswith("__MACOSX")
        ]
        if len(shp_names) != 1:
            raise ImportSourceError(
                f"{spec!r} must contain exactly one .shp (found {len(shp_names)})"
            )
        stem = os.path.splitext(shp_names[0])[0]
        tmp = tempfile.TemporaryDirectory(prefix="kart-zip-import-")
        extracted_shp = None
        for name in zf.namelist():
            base, ext = os.path.splitext(name)
            if base != stem or name.endswith("/"):
                continue
            # flatten to the temp root; reject path traversal
            target = os.path.join(tmp.name, os.path.basename(name))
            with zf.open(name) as src, open(target, "wb") as dst:
                dst.write(src.read())
            if ext.lower() == ".shp":
                extracted_shp = target
    # schema ids seed from the zip spec + inner name, not the random temp
    # path — re-opens of the same archive must yield the same column ids
    source = ShapefileImportSource(
        extracted_shp, schema_id_seed=f"{spec}!{shp_names[0]}"
    )
    source.dest_path = os.path.splitext(os.path.basename(spec))[0]
    source._tmpdir = tmp  # keep the extraction alive with the source
    return source


class GPKGImportSource(ImportSource):
    def __init__(self, gpkg_path, table_name, dest_path=None):
        if not os.path.exists(gpkg_path):
            raise ImportSourceError(f"No such file: {gpkg_path}")
        self.gpkg_path = gpkg_path
        self.table_name = table_name
        self.dest_path = dest_path or table_name
        self._schema = None
        self._geom_col = None
        self._crs_defs = None

    @classmethod
    def open_all(cls, gpkg_path, table=None):
        con = sqlite3.connect(gpkg_path)
        try:
            tables = [
                row[0]
                for row in con.execute(
                    "SELECT table_name FROM gpkg_contents "
                    "WHERE data_type IN ('features', 'attributes') ORDER BY table_name"
                )
            ]
        except sqlite3.OperationalError:
            raise ImportSourceError(f"{gpkg_path} is not a GeoPackage")
        finally:
            con.close()
        if table is not None:
            if table not in tables:
                raise ImportSourceError(
                    f"Table {table!r} not found in {gpkg_path}; has: {tables}"
                )
            tables = [table]
        return [cls(gpkg_path, t) for t in tables]

    def _connect(self):
        con = sqlite3.connect(self.gpkg_path)
        con.row_factory = sqlite3.Row
        return con

    def _geom_info(self, con):
        try:
            row = con.execute(
                "SELECT column_name, geometry_type_name, srs_id, z, m "
                "FROM gpkg_geometry_columns WHERE table_name = ?",
                (self.table_name,),
            ).fetchone()
        except sqlite3.OperationalError:
            return None
        return dict(row) if row else None

    def _load_schema(self):
        con = self._connect()
        try:
            geom_info = self._geom_info(con)
            crs_identifier = None
            crs_defs = {}
            if geom_info and geom_info["srs_id"] is not None:
                srs = con.execute(
                    "SELECT * FROM gpkg_spatial_ref_sys WHERE srs_id = ?",
                    (geom_info["srs_id"],),
                ).fetchone()
                if srs is not None and srs["srs_id"] > 0:
                    wkt = srs["definition"]
                    crs_identifier = (
                        f"{srs['organization'].upper()}:{srs['organization_coordsys_id']}"
                        if srs["organization"]
                        else get_identifier_str(wkt)
                    )
                    crs_defs[crs_identifier] = wkt
            cols = []
            for row in con.execute(f"PRAGMA table_info({gpkg_adapter.quote(self.table_name)})"):
                name, decl_type = row["name"], row["type"]
                is_geom = geom_info is not None and name == geom_info["column_name"]
                data_type, extra = gpkg_adapter.sqlite_type_to_v2(
                    decl_type,
                    geom_info={**geom_info, "crs_identifier": crs_identifier}
                    if is_geom
                    else None,
                )
                # table_info's pk column is 1-based pk ordinal (0 = not pk);
                # composite pks map to contiguous pk_index values and get the
                # hash-distributed path encoder automatically.
                pk_index = row["pk"] - 1 if row["pk"] > 0 else None
                if pk_index is not None and data_type == "integer":
                    extra = {**extra, "size": 64}
                cols.append(
                    ColumnSchema(
                        ColumnSchema.deterministic_id(self.gpkg_path, self.table_name, name),
                        name,
                        data_type,
                        pk_index,
                        extra,
                    )
                )
            self._schema = Schema(cols)
            self._crs_defs = crs_defs
            self._geom_col = geom_info["column_name"] if geom_info else None
        finally:
            con.close()

    @property
    def schema(self):
        if self._schema is None:
            self._load_schema()
        return self._schema

    def crs_definitions(self):
        if self._crs_defs is None:
            self._load_schema()
        return self._crs_defs

    def meta_items(self):
        con = self._connect()
        try:
            out = {}
            row = con.execute(
                "SELECT identifier, description FROM gpkg_contents WHERE table_name = ?",
                (self.table_name,),
            ).fetchone()
            if row:
                if row["identifier"]:
                    out["title"] = row["identifier"]
                if row["description"]:
                    out["description"] = row["description"]
            return out
        finally:
            con.close()

    @property
    def feature_count(self):
        con = self._connect()
        try:
            return con.execute(
                f"SELECT COUNT(*) FROM {gpkg_adapter.quote(self.table_name)}"
            ).fetchone()[0]
        finally:
            con.close()

    def features(self):
        schema = self.schema
        con = self._connect()
        try:
            cursor = con.execute(
                f"SELECT * FROM {gpkg_adapter.quote(self.table_name)}"
            )
            cursor.arraysize = 10000
            while True:
                rows = cursor.fetchmany()
                if not rows:
                    break
                for row in rows:
                    yield {
                        col.name: gpkg_adapter.value_to_v2(row[col.name], col)
                        for col in schema.columns
                    }
        finally:
            con.close()

    def encoded_feature_batches(self, schema):
        """Fast single-pass import stream: yields ``(pk_list, blob_list)``
        batches with blobs bit-identical to ``schema.encode_feature_blob``
        over ``features()`` (tested), or None when this table can't use it
        (composite/non-int pk).

        The generic path costs ~30us/feature of pure Python before any IO:
        a name-keyed dict per row, a ``value_to_v2`` dispatch per cell, a
        second id-keyed dict in ``encode_feature_blob``, and a strict-types
        msgpack hook call per tuple/geometry. This streams sqlite rows in
        schema column order and packs each blob incrementally on one reused
        Packer (geometry goes through the single-pass canonicaliser
        ``geometry.normalise_gpkg_bytes`` straight into ``pack_ext_type`` —
        no ExtType objects, no value lists, no per-row tuples).
        KART_IMPORT_FAST=0 disables."""
        if os.environ.get("KART_IMPORT_FAST") == "0":
            return None
        pk_cols = schema.pk_columns
        if len(pk_cols) != 1 or pk_cols[0].data_type != "integer":
            return None
        return self._encoded_batch_gen(schema)

    # column handling kinds for batch_row_encoder's inner loop
    _K_PLAIN, _K_GEOM, _K_BOOL, _K_FLOAT, _K_TS = range(5)

    def _select_sql(self, schema, where=""):
        """The raw-row SELECT both the fused generator and the pipeline
        read stage run: schema column order, streamed in pk order (free for
        the rowid-aliased int pks this path requires — and the sorted
        stream feeds the presorted bulk tree build + sidecar directly)."""
        sel = ", ".join(gpkg_adapter.quote(c.name) for c in schema.columns)
        pk = gpkg_adapter.quote(schema.pk_columns[0].name)
        return (
            f"SELECT {sel} FROM {gpkg_adapter.quote(self.table_name)}"
            f"{where} ORDER BY {pk}"
        )

    def raw_row_batches(self, schema, batch_rows=10000):
        """Stream raw sqlite row-tuple batches (schema column order, pk
        order) — the pipeline's *read* stage. Opens its own connection so
        it can run on the reader thread (sqlite3 objects are not shareable
        across threads). check_same_thread=False only so an *abandoned*
        generator (aborted pipeline) can still be closed from another
        thread — all reads stay on the one thread that drives the
        generator."""
        con = sqlite3.connect(
            self.gpkg_path, check_same_thread=False
        )  # tuple rows: index access
        try:
            cursor = con.execute(self._select_sql(schema))
            cursor.arraysize = batch_rows
            while True:
                rows = cursor.fetchmany()
                if not rows:
                    break
                yield rows
        finally:
            con.close()

    def batch_row_encoder(self, schema):
        """-> ``encode(rows) -> (pk_list, blob_list)`` over raw sqlite row
        tuples in schema column order — the pipeline's *encode* stage, and
        the encode half of :meth:`encoded_feature_batches`. Blobs are
        bit-identical to ``schema.encode_feature_blob`` over ``features()``
        (tested). One reused Packer: NOT thread-safe, one encoder per
        thread (geometry goes through the single-pass canonicaliser
        ``geometry.normalise_gpkg_bytes`` straight into ``pack_ext_type`` —
        no ExtType objects, no value lists, no per-row tuples)."""
        import msgpack

        from kart_tpu.core.serialise import GEOMETRY_EXT_CODE
        from kart_tpu.geometry import normalise_gpkg_bytes

        kind_of = {
            "geometry": self._K_GEOM,
            "boolean": self._K_BOOL,
            "float": self._K_FLOAT,
            "timestamp": self._K_TS,
        }
        cols = list(schema.columns)
        by_id = {c.id: j for j, c in enumerate(cols)}
        # blob value order is the legend's non-pk column-id order — exactly
        # what Legend.to_value_tuples produces in encode_feature_blob
        non_pk = [
            (by_id[cid], kind_of.get(cols[by_id[cid]].data_type, self._K_PLAIN))
            for cid in schema.legend.non_pk_columns
        ]
        n_vals = len(non_pk)
        pk_j = by_id[schema.legend.pk_columns[0]]
        # autoreset=False: the blob is composed incrementally (array header,
        # hash, values); with the default autoreset every pack() call would
        # flush and clear the buffer mid-record
        packer = msgpack.Packer(use_bin_type=True, autoreset=False)
        legend_hash = schema.legend_hash
        # local bindings of the class constants (fast loop lookups with one
        # source of truth)
        K_PLAIN, K_GEOM, K_BOOL, K_FLOAT, K_TS = (
            self._K_PLAIN, self._K_GEOM, self._K_BOOL, self._K_FLOAT, self._K_TS,
        )

        def encode(rows):
            pks = []
            blobs = []
            for row in rows:
                packer.pack_array_header(2)
                packer.pack(legend_hash)
                packer.pack_array_header(n_vals)
                for j, kind in non_pk:
                    v = row[j]
                    if kind == K_PLAIN or v is None:
                        packer.pack(v)
                    elif kind == K_GEOM:
                        packer.pack_ext_type(
                            GEOMETRY_EXT_CODE, normalise_gpkg_bytes(v)
                        )
                    elif kind == K_FLOAT:
                        packer.pack(float(v))
                    elif kind == K_BOOL:
                        packer.pack(bool(v))
                    else:
                        packer.pack(
                            v.replace(" ", "T") if isinstance(v, str) else v
                        )
                pks.append(row[pk_j])
                blobs.append(packer.bytes())
                packer.reset()
            return pks, blobs

        return encode

    def _encoded_batch_gen(self, schema):
        # per-phase accumulators for the import phase breakdown (read by
        # the serial importer; the bench records them), mirrored as
        # importer.read / importer.encode spans for `kart --trace import`
        import time as _time

        from kart_tpu import telemetry as tm

        encode = self.batch_row_encoder(schema)
        phases = self.phase_seconds = {"source_read": 0.0, "encode": 0.0}
        batches = self.raw_row_batches(schema)
        while True:
            t0 = _time.perf_counter()
            with tm.span("importer.read"):
                rows = next(batches, None)
            phases["source_read"] += _time.perf_counter() - t0
            if rows is None:
                break
            t0 = _time.perf_counter()
            with tm.span("importer.encode"):
                out = encode(rows)
            phases["encode"] += _time.perf_counter() - t0
            yield out

    def native_encoded_batches(self, schema, batch_rows=10000):
        """The pipeline's native fused read+encode producer: a generator of
        ``("enc", pks int64, buf uint8, offsets int64)`` batches where blob
        i is ``buf[offsets[i]:offsets[i+1]]`` — the SELECT is stepped and
        every row msgpack-encoded inside ONE GIL-free native call per batch
        (native/kart_io.cpp io_gpkg_*), bit-identical to
        :meth:`batch_row_encoder` output (property-tested). None when the
        native IO lib / sqlite3 runtime is unavailable, the table isn't
        single-int-pk, or ``KART_IMPORT_NATIVE_READ=0`` /
        ``KART_IMPORT_FAST=0`` disables it.

        Mid-stream rows the native encoder can't reproduce bit-identically
        (a geometry needing the full re-encode path, an unexpected storage
        class) raise :class:`~kart_tpu.native.GpkgReaderFallback` out of the
        generator; the pipelined importer catches it and restarts the whole
        run through the Python encoder against fresh collector state
        (already-written blobs dedupe in the pack writer) — tested."""
        import time as _time

        from kart_tpu import native
        from kart_tpu import telemetry as tm
        from kart_tpu.core.serialise import GEOMETRY_EXT_CODE

        if os.environ.get("KART_IMPORT_NATIVE_READ") == "0":
            return None
        if os.environ.get("KART_IMPORT_FAST") == "0":
            return None
        pk_cols = schema.pk_columns
        if len(pk_cols) != 1 or pk_cols[0].data_type != "integer":
            return None
        kind_of = {
            "geometry": 1, "boolean": 2, "float": 3, "timestamp": 4,
        }
        cols = list(schema.columns)
        by_id = {c.id: j for j, c in enumerate(cols)}
        legend = schema.legend
        val_cols = [by_id[cid] for cid in legend.non_pk_columns]
        kinds = [kind_of.get(cols[j].data_type, 0) for j in val_cols]
        pk_col = by_id[legend.pk_columns[0]]
        import msgpack

        p = msgpack.Packer(use_bin_type=True, autoreset=False)
        p.pack_array_header(2)
        p.pack(schema.legend_hash)
        p.pack_array_header(len(val_cols))
        prefix = p.bytes()
        reader = native.open_gpkg_reader(
            self.gpkg_path, self._select_sql(schema), val_cols, kinds,
            pk_col, prefix, GEOMETRY_EXT_CODE,
        )
        if reader is None:
            return None

        def gen():
            phases = self.phase_seconds = {"source_read": 0.0, "encode": 0.0}
            try:
                while True:
                    t0 = _time.perf_counter()
                    with tm.span("importer.read"):
                        out = reader.next_batch(batch_rows)
                    phases["source_read"] += _time.perf_counter() - t0
                    if out is None:
                        return
                    yield ("enc",) + out
            finally:
                reader.close()

        return gen()

    def get_features(self, pks, ignore_missing=False):
        """Point reads by pk (indexed sqlite lookup, not a table scan)."""
        schema = self.schema
        pk_col = schema.pk_columns[0].name
        con = self._connect()
        try:
            for pk in pks:
                row = con.execute(
                    f"SELECT * FROM {gpkg_adapter.quote(self.table_name)} "
                    f"WHERE {gpkg_adapter.quote(pk_col)} = ?",
                    (pk,),
                ).fetchone()
                if row is None:
                    if ignore_missing:
                        continue
                    raise ImportSourceError(
                        f"Source has no feature with id: {pk!r}"
                    )
                yield {
                    col.name: gpkg_adapter.value_to_v2(row[col.name], col)
                    for col in schema.columns
                }
        finally:
            con.close()

    def default_dest_path(self):
        return self.table_name


class GeoJSONImportSource(ImportSource):
    """A GeoJSON FeatureCollection file. Properties define the schema
    (sniffed from values); an ``id``/``fid`` property becomes the pk, else one
    is auto-assigned."""

    def __init__(self, path, dest_path=None, crs="EPSG:4326"):
        if not os.path.exists(path):
            raise ImportSourceError(f"No such file: {path}")
        self.path = path
        base = os.path.splitext(os.path.basename(path))[0]
        self.dest_path = dest_path or base
        self.crs = crs
        self._features_json = self._load_features(path)
        self._schema_cache = None

    @property
    def _schema(self):
        # lazy: the CLI may override self.crs after construction (--crs)
        if self._schema_cache is None:
            self._schema_cache = self._sniff_schema()
        return self._schema_cache

    @staticmethod
    def _load_features(path):
        with open(path) as f:
            doc = json.load(f)
        if doc.get("type") != "FeatureCollection":
            raise ImportSourceError(f"{path} is not a GeoJSON FeatureCollection")
        return doc.get("features", [])

    def _sniff_schema(self):
        prop_types = {}
        has_geom = False
        pk_name = None
        for feat in self._features_json:
            if feat.get("geometry") is not None:
                has_geom = True
            for key, value in (feat.get("properties") or {}).items():
                if value is None:
                    prop_types.setdefault(key, None)
                    continue
                t = {bool: "boolean", int: "integer", float: "float", str: "text"}.get(
                    type(value), "text"
                )
                prev = prop_types.get(key)
                if prev in (None, "integer") and t == "float":
                    prop_types[key] = "float"
                elif prev is None or prev == t:
                    prop_types[key] = t
                elif {prev, t} == {"integer", "float"}:
                    prop_types[key] = "float"
                else:
                    prop_types[key] = "text"
        for candidate in ("id", "fid"):
            if prop_types.get(candidate) == "integer":
                pk_name = candidate
                break
        # no natural key -> emit a PK-less schema; the importer wraps the
        # source in PkGeneratingImportSource for *stable* generated PKs
        # (row-order PKs would reshuffle on every re-import)
        cols = []
        self._pk_name = pk_name
        for name, t in prop_types.items():
            cols.append(
                ColumnSchema(
                    ColumnSchema.deterministic_id(self.path, name),
                    name,
                    t or "text",
                    0 if name == pk_name else None,
                    # JSON numbers are 64-bit; explicit size also makes the
                    # schema roundtrip the GPKG WC cleanly (INTEGER/REAL read
                    # back as 64-bit)
                    {"size": 64} if (t or "text") in ("integer", "float") else {},
                )
            )
        if has_geom:
            cols.append(
                ColumnSchema(
                    ColumnSchema.deterministic_id(self.path, "__geom__"),
                    "geom",
                    "geometry",
                    None,
                    {"geometryType": "GEOMETRY", "geometryCRS": self.crs},
                )
            )
        # pk column first
        cols.sort(key=lambda c: 0 if c.pk_index is not None else 1)
        return Schema(cols)

    @property
    def schema(self):
        return self._schema

    def crs_definitions(self):
        from kart_tpu.crs import make_crs

        if any(c.data_type == "geometry" for c in self._schema.columns):
            try:
                return {self.crs: make_crs(self.crs).wkt}
            except Exception:
                return {}
        return {}

    @property
    def feature_count(self):
        return len(self._features_json)

    def features(self):
        for feat in self._features_json:
            props = feat.get("properties") or {}
            out = {}
            for col in self._schema.columns:
                if col.name == "geom" and col.data_type == "geometry":
                    geom = feat.get("geometry")
                    out["geom"] = geojson_to_geometry(geom) if geom else None
                else:
                    value = props.get(col.name)
                    if col.data_type == "float" and isinstance(value, int):
                        value = float(value)
                    out[col.name] = value
            yield out


class GeoJSONSeqImportSource(GeoJSONImportSource):
    """Newline-delimited GeoJSON (.geojsonl / .ndjson / GeoJSONSeq, incl.
    RFC 8142 RS-prefixed records): one Feature object per line (the OGR
    GeoJSONSeq driver's format; reference imports it via OGR,
    kart/ogr_import_source.py:30-40)."""

    @staticmethod
    def _load_features(path):
        with open(path) as f:
            text = f.read()
        if "\x1e" in text:
            # RFC 8142: RS-delimited records, each of which may span lines
            # (pretty-printed GeoJSONSeq is valid)
            records = [
                (i, chunk) for i, chunk in enumerate(text.split("\x1e"), 0)
                if chunk.strip()
            ]
            label = "record"
        else:
            records = [
                (i, line) for i, line in enumerate(text.splitlines(), 1)
                if line.strip()
            ]
            label = "line"

        features = []
        for no, chunk in records:
            try:
                obj = json.loads(chunk)
            except ValueError as e:
                raise ImportSourceError(
                    f"{path}:{no}: not a GeoJSON Feature {label}: {e}"
                )
            if obj.get("type") == "FeatureCollection":
                features.extend(obj.get("features", []))
            elif obj.get("type") == "Feature":
                features.append(obj)
            else:
                raise ImportSourceError(
                    f"{path}:{no}: expected a Feature, got {obj.get('type')!r}"
                )
        return features


class CSVImportSource(ImportSource):
    """CSV with a header row; all columns text unless values parse as
    int/float (or WKT geometry) across the whole file. First column named
    id/fid (int) is pk; a column of WKT values becomes the geometry column
    (the OGR CSV driver's convention), assumed EPSG:4326."""

    def crs_definitions(self):
        from kart_tpu.crs import make_crs

        if any(c.data_type == "geometry" for c in self._schema.columns):
            try:
                return {self.crs: make_crs(self.crs).wkt}
            except Exception:
                return {}
        return {}

    def __init__(self, path, dest_path=None, crs="EPSG:4326"):
        if not os.path.exists(path):
            raise ImportSourceError(f"No such file: {path}")
        self.path = path
        self.crs = crs
        self.dest_path = dest_path or os.path.splitext(os.path.basename(path))[0]
        with open(path, newline="") as f:
            reader = csv.reader(f)
            self.header = next(reader)
            self.rows = list(reader)
        self._schema_cache = None

    _WKT_PREFIXES = (
        "POINT", "LINESTRING", "POLYGON", "MULTIPOINT", "MULTILINESTRING",
        "MULTIPOLYGON", "GEOMETRYCOLLECTION",
    )

    @classmethod
    def _sniff_type(cls, values):
        saw_float = False
        saw_number = False
        saw_wkt = False
        wkt_checked = 0
        for v in values:
            if v == "":
                continue
            if v.lstrip().upper().startswith(cls._WKT_PREFIXES):
                if wkt_checked < 100:  # validity sample; features() parses for real
                    try:
                        Geometry.from_wkt(v)
                    except Exception:
                        return "text"
                    wkt_checked += 1
                saw_wkt = True
                continue
            try:
                int(v)
                saw_number = True
            except ValueError:
                try:
                    float(v)
                    saw_number = saw_float = True
                except ValueError:
                    return "text"
        if saw_wkt:
            # any non-WKT value (numeric rows included, wherever they appear)
            # demotes the column to text — geometry must be all-or-nothing
            return "text" if saw_number else "geometry"
        return "float" if saw_float else "integer"

    def _sniff_schema(self):
        types = {}
        for i, name in enumerate(self.header):
            types[name] = self._sniff_type([r[i] for r in self.rows if i < len(r)])
        pk_name = None
        for candidate in ("id", "fid", self.header[0]):
            if types.get(candidate) == "integer":
                pk_name = candidate
                break
        # no natural key -> emit a PK-less schema; the importer wraps the
        # source in PkGeneratingImportSource for *stable* generated PKs
        # (row-order PKs would reshuffle on every re-import)
        cols = []
        self._pk_name = pk_name
        for name in self.header:
            t = types[name]
            if t == "geometry":
                extra = {"geometryType": "GEOMETRY", "geometryCRS": self.crs}
            elif t in ("integer", "float"):
                extra = {"size": 64}
            else:
                extra = {}
            cols.append(
                ColumnSchema(
                    ColumnSchema.deterministic_id(self.path, name),
                    name,
                    t,
                    0 if name == pk_name else None,
                    extra,
                )
            )
        cols.sort(key=lambda c: 0 if c.pk_index is not None else 1)
        return Schema(cols)

    @property
    def _schema(self):
        # lazy: the CLI may override self.crs after construction (--crs)
        # and the geometry column's geometryCRS must reflect that
        if self._schema_cache is None:
            self._schema_cache = self._sniff_schema()
        return self._schema_cache

    @property
    def schema(self):
        return self._schema

    @property
    def feature_count(self):
        return len(self.rows)

    def features(self):
        # row values follow the *header* order, not the pk-first schema order
        cols_by_name = {c.name: c for c in self._schema.columns}
        for row in self.rows:
            out = {}
            for j, name in enumerate(self.header):
                col = cols_by_name[name]
                raw = row[j] if j < len(row) else ""
                if raw == "":
                    out[name] = None
                elif col.data_type == "integer":
                    out[name] = int(raw)
                elif col.data_type == "float":
                    out[name] = float(raw)
                elif col.data_type == "geometry":
                    out[name] = Geometry.from_wkt(raw)
                else:
                    out[name] = raw
            yield out
