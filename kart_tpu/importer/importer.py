"""Bulk import: sources -> dataset trees -> one commit
(reference: kart/fast_import.py).

The reference shards features over N ``git fast-import`` subprocesses and
merges the resulting trees (fast_import.py:286-399). Here all object writes
go into packfiles, not per-feature loose files: serial imports append every
blob/tree into one new pack (``ObjectDb.bulk_pack``); shardable sources
(int-pk GPKG, see importer/parallel.py) fan out over N worker processes that
each write their own pack of feature blobs + leaf trees, joined by one
TreeBuilder spine rewrite. The commit object is written loose *after* the
packs are fsync'd, so a crash mid-import never leaves a dangling ref.
"""

import gc
import logging
import time

import numpy as np

from kart_tpu import telemetry as tm
from kart_tpu.core.structure import RepoStructure
from kart_tpu.core.tree_builder import TreeBuilder
from kart_tpu.models.dataset import Dataset3
from kart_tpu.models.paths import encoder_for_schema
from kart_tpu.utils import chunked, paused_gc

L = logging.getLogger(__name__)

BATCH_SIZE = 10000
# below this, the tree-walk diff path is so cheap that a sidecar isn't worth
# the disk; above it, first-diff latency matters
SIDECAR_MIN_FEATURES = 10000

#: per-phase *self* seconds of the most recent import in this process —
#: {"source_read", "encode", "hash_deflate", "tree_build", "total"}.
#: Populated by the serial streaming path (the bench's phase-breakdown
#: record); the parallel fan-out interleaves phases across workers and
#: reports only the total. Accounting runs on a telemetry span stack
#: (:class:`kart_tpu.telemetry.Phases`): nested phases book wall-clock into
#: the innermost phase only, so the recorded self-times can never sum past
#: the total (the old ``phases[key] +=`` dict pattern double-booked
#: whenever phases overlapped).
LAST_IMPORT_PHASES = None

#: the phase keys the bench's ``import_phase_*`` record reads — stable
#: across the telemetry refactor
PHASE_KEYS = ("source_read", "encode", "hash_deflate", "tree_build")

#: per-stage *busy* seconds of the most recent pipelined import —
#: {"read", "encode", "hash", "pack", "tree", "wall"}. Unlike LAST_IMPORT_PHASES
#: (a single-threaded span stack whose self-times sum <= total by
#: construction), these are measured on four concurrent stage threads, so
#: their sum EXCEEDING wall is the overlap working; the bench records the
#: ratio. None when the last import ran serial/parallel.
LAST_IMPORT_PIPELINE = None


def _new_phases():
    p = tm.Phases("importer")
    for key in PHASE_KEYS:  # every key present even when a path is skipped
        p.self_s.setdefault(key, 0.0)
        p.cum_s.setdefault(key, 0.0)
    return p


class ImportError_(RuntimeError):
    pass


def _timed_iter(it, phases, key="source_read"):
    """Wrap an iterator, accumulating its pull time into phase ``key``
    (leaf accounting: two clock reads per pull, no span objects in the
    per-item loop)."""
    it = iter(it)
    while True:
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            phases.add(key, time.perf_counter() - t0)
            return
        phases.add(key, time.perf_counter() - t0)
        yield item


def import_sources(
    repo,
    sources,
    *,
    message=None,
    replace_existing=False,
    replace_ids=None,
    log=None,
):
    """Import each source as a dataset; -> the new commit oid.

    replace_ids: iterable of pk values — incremental re-import (reference:
    fast_import.py:462-476): the existing dataset tree is kept, each listed
    id is deleted and then re-imported when the source still has it (so a
    listed id absent from the source becomes a delete). Implies
    replace_existing; an empty list re-imports nothing but still updates
    meta."""
    sources = list(sources)
    head_tree = repo.head_tree_oid
    structure = repo.structure("HEAD") if not repo.head_is_unborn else None
    existing_paths = (
        set(structure.datasets.paths()) if structure is not None else set()
    )

    from kart_tpu.importer.pk_generation import PkGeneratingImportSource

    from kart_tpu.diff.sidecar import SidecarCapture

    if replace_ids is not None:
        replace_existing = True  # implied, as in the reference CLI
        if len(sources) != 1:
            raise ImportError_(
                "--replace-ids requires a single-table import (the id list "
                "would be applied to every table)"
            )
    tb = TreeBuilder(repo.odb, head_tree)
    ds_paths = []
    captures = {}
    total = 0
    phases = _new_phases()
    global LAST_IMPORT_PIPELINE
    LAST_IMPORT_PIPELINE = None  # set by _run_import_pipeline when taken
    t0 = time.monotonic()
    with tm.span("importer.import_sources", sources=len(sources)), repo.odb.bulk_pack():
        for source in sources:
            # PK-less sources get stable generated PKs
            # (reference: kart/pk_generation.py)
            source = PkGeneratingImportSource.wrap_if_needed(source, repo)
            ds_path = source.dest_path.strip("/")
            if ds_path in existing_paths and not replace_existing:
                raise ImportError_(
                    f"Dataset {ds_path!r} already exists — use --replace-existing"
                )
            if replace_existing and replace_ids is None:
                tb.remove(ds_path)
            existing_ds = (
                structure.datasets.get(ds_path) if structure is not None else None
            )
            capture = (
                SidecarCapture() if replace_ids is None else ReplaceIdsCapture()
            )
            count = _import_single_source(
                repo,
                tb,
                source,
                ds_path,
                log=log,
                capture=capture,
                replace_ids=replace_ids,
                existing_ds=existing_ds,
                phases=phases,
            )
            total += count
            ds_paths.append(ds_path)
            captures[ds_path] = (capture, existing_ds)

        with phases.span("tree_build"):
            new_tree = tb.flush()

    # commit + ref update only after the pack is durable (fsync'd) on disk:
    # a crash mid-import leaves an aborted tmp pack and an untouched HEAD,
    # never a dangling ref (reference analog: temp refs refs/kart-import/,
    # fast_import.py:307)
    if message is None:
        message = f"Import {len(ds_paths)} dataset(s): " + ", ".join(ds_paths)
    parents = [repo.head_commit_oid] if repo.head_commit_oid else []
    commit_oid = repo.create_commit("HEAD", new_tree, message, parents)

    # columnar sidecars, straight from the captured import stream — big
    # datasets get O(1) FeatureBlock loads on their first diff. replace-ids
    # imports derive the new sidecar from the old one + the change set
    # (O(changed)), so incremental re-imports keep the columnar cache.
    from kart_tpu.diff import sidecar as sidecar_mod

    root = repo.odb.tree(new_tree)
    for ds_path, (capture, existing_ds) in captures.items():
        node = root.get_or_none(
            f"{ds_path}/{Dataset3.DATASET_DIRNAME}/feature"
        )
        if node is None:
            continue
        if isinstance(capture, ReplaceIdsCapture):
            enc = getattr(existing_ds, "path_encoder", None) if existing_ds else None
            if enc is None or enc.scheme != "int":
                continue  # hash-keyed: would need per-path bookkeeping
            old_block = sidecar_mod.load_block(repo, existing_ds)
            if old_block is None:
                continue  # no cache to derive from; rebuilt lazily on use
            sidecar_mod.derive_sidecar(
                repo,
                old_block,
                node.oid,
                capture.removed_pks,
                dict(capture.added),
            )
            continue
        if capture.count < SIDECAR_MIN_FEATURES:
            continue
        capture.save(repo, node.oid)
    dt = time.monotonic() - t0
    global LAST_IMPORT_PHASES
    LAST_IMPORT_PHASES = {**phases.self_seconds(), "total": dt}
    tm.incr("importer.features_imported", total)
    if log:
        rate = total / dt if dt > 0 else float("inf")
        log(f"Imported {total} features in {dt:.2f}s ({rate:.0f} features/s)")
    return commit_oid


def _sanitise_pk(schema, pk):
    """CLI-supplied id (a string) -> the pk column's value type."""
    col = schema.pk_columns[0]
    if col.data_type == "integer":
        try:
            return int(pk)
        except (TypeError, ValueError):
            raise ImportError_(f"Invalid integer primary key: {pk!r}")
    return pk


def _check_replace_ids_compatible(existing_ds, schema, encoder):
    """--replace-ids keeps the existing tree, so the new feature paths must
    land where the old ones live: the path encoder and pk column must match
    the existing dataset, or deletes silently miss and unlisted features
    become unreachable under the rewritten meta."""
    if existing_ds is None:
        return
    old_enc = getattr(existing_ds, "path_encoder", None)
    if old_enc is not None and old_enc.to_dict() != encoder.to_dict():
        raise ImportError_(
            "--replace-ids cannot change the feature path encoding "
            f"({old_enc.to_dict()} -> {encoder.to_dict()}); re-import the "
            "whole dataset with --replace-existing instead"
        )
    old_pks = existing_ds.schema.pk_columns
    new_pks = schema.pk_columns
    if [(c.name, c.data_type) for c in old_pks] != [
        (c.name, c.data_type) for c in new_pks
    ]:
        raise ImportError_(
            "--replace-ids cannot change the primary key "
            f"({[(c.name, c.data_type) for c in old_pks]} -> "
            f"{[(c.name, c.data_type) for c in new_pks]}); re-import the "
            "whole dataset with --replace-existing instead"
        )


class ReplaceIdsCapture:
    """What a --replace-ids import changed, for the O(changed) sidecar
    derivation (the incremental-import workflow must not lose the columnar
    cache and fall back to full tree walks)."""

    def __init__(self):
        self.removed_pks = []
        self.added = []  # (pk int, oid hex)


def _import_replace_ids(
    repo, tb, source, schema, encoder, prefix, replace_ids, *,
    log=None, existing_ds=None, capture=None,
):
    """Incremental re-import: delete every listed id's path, re-import the
    ones the source still has. Everything unlisted keeps its existing blob
    and subtree (reference: fast_import.py:462-476 — 'D <path>' per id, then
    stream source.get_features(ids, ignore_missing=True))."""
    if len(schema.pk_columns) != 1:
        raise ImportError_(
            "--replace-ids requires the dataset to have a single-column "
            "primary key"
        )
    _check_replace_ids_compatible(existing_ds, schema, encoder)
    pks = [_sanitise_pk(schema, pk) for pk in replace_ids]
    for pk in pks:
        tb.remove(prefix + encoder.encode_pks_to_path((pk,)))
    if capture is not None:
        capture.removed_pks = pks

    count = 0
    for batch in chunked(
        source.get_features(pks, ignore_missing=True), BATCH_SIZE
    ):
        encoded = [schema.encode_feature_blob(f) for f in batch]
        rel_paths = [encoder.encode_pks_to_path(pkv) for pkv, _ in encoded]
        oids = repo.odb.write_blobs([blob for _, blob in encoded])
        tb.insert_many((prefix + rel for rel in rel_paths), oids)
        if capture is not None:
            capture.added.extend(
                (pkv[0], oid) for (pkv, _), oid in zip(encoded, oids)
            )
        count += len(batch)
    if log:
        log(
            f"  replaced {count} of {len(pks)} listed id(s); "
            f"{len(pks) - count} deleted"
        )
    return count


def _import_single_source(
    repo, tb, source, ds_path, *, log=None, capture=None, replace_ids=None,
    existing_ds=None, phases=None,
):
    from kart_tpu.diff.sidecar import SidecarCapture

    if phases is None:
        phases = _new_phases()

    schema = source.schema
    encoder = encoder_for_schema(schema)
    meta = source.meta_items()
    meta_blobs = Dataset3.new_dataset_meta_blobs(
        ds_path,
        schema,
        title=meta.get("title"),
        description=meta.get("description"),
        crs_defs=source.crs_definitions(),
        path_encoder=encoder,
    )
    for path, data in meta_blobs:
        tb.insert(path, repo.odb.write_blob(data))

    from kart_tpu.importer import parallel as par
    from kart_tpu.importer import pipeline as pipe

    prefix = f"{ds_path}/{Dataset3.DATASET_DIRNAME}/{Dataset3.FEATURE_PATH}"

    if replace_ids is not None:
        return _import_replace_ids(
            repo, tb, source, schema, encoder, prefix, replace_ids,
            log=log, existing_ds=existing_ds, capture=capture,
        )

    # --- path routing: pipelined, parallel fan-out, or serial ------------
    # A native-read-capable source takes the pipeline: its fused
    # read+encode stage runs GIL-free at >1M rows/s, which beats the
    # process fan-out's per-worker interpreter encode on any core count we
    # can measure (teaching the fan-out workers to use the native reader
    # per shard is the open item). Fan-out remains the big-box path for
    # python-encoded sources.
    mode = pipe.pipeline_mode()
    n_workers = par.default_workers()
    if n_workers > 1:
        # satellite fix: never more workers than the import has work for —
        # a pool member costs a spawned interpreter + full module import
        n_workers = par.clamp_workers(n_workers, source.feature_count)
    native_pipe = mode != "off" and pipe.native_read_capable(source, encoder)
    if (
        mode != "force"
        and not native_pipe
        and n_workers > 1
        and par.shardable(source, encoder, n_workers)
    ):
        count = par.run_parallel_import(
            repo, tb, source, ds_path, encoder, prefix, n_workers,
            log=log, capture=capture,
        )
        return count

    use_pipeline = mode == "force" or (
        mode == "auto" and source.feature_count >= pipe.PIPELINE_MIN_FEATURES
    )
    if use_pipeline and repo.odb._bulk_writer is None:
        use_pipeline = False  # the pipeline pack stage needs the bulk writer

    count = 0
    use_batch_paths = encoder.scheme == "int"
    # int-pk fast path: (pks, oid bytes) -> vectorized tree build. When a
    # SidecarCapture is running it already holds these columns; only
    # accumulate separately without one (a 100M import must not hold two
    # 2.8GB copies)
    collect_local = use_batch_paths and not isinstance(capture, SidecarCapture)
    pk_chunks = []
    oid_chunks = []
    # the streaming loop allocates short-lived, acyclic objects by the
    # million: pause the cyclic collector (~8% measured). Source adapters
    # may create cycles internally, so bound their growth with a manual
    # collection every ~1M rows rather than trusting full acyclicity.
    # Fast pre-encoded stream (int-pk GPKG): the source yields whole
    # (pk_list, blob_list) batches and oids stay columnar end-to-end — no
    # per-feature dicts, no per-row tuples, no hex round trips (see
    # GPKGImportSource.encoded_feature_batches).
    fast_batches = None
    if use_batch_paths and not use_pipeline:
        fast = getattr(source, "encoded_feature_batches", None)
        if fast is not None:
            fast_batches = fast(schema)

    stream_root = None
    with paused_gc():
        gc_batch = 0
        if use_pipeline:
            count, stream_root = _run_import_pipeline(
                repo, tb, source, schema, encoder, prefix,
                capture=capture,
                collect_local=collect_local,
                pk_chunks=pk_chunks,
                oid_chunks=oid_chunks,
                use_batch_paths=use_batch_paths,
                log=log,
                ds_path=ds_path,
            )
        elif fast_batches is not None:
            # phase timing: the generator fuses source read + encode; its
            # own phase_seconds split (the GPKG source keeps one) is folded
            # in below — here the generator pull is accounted as encode
            # and rebalanced from the source's accumulators afterwards
            for pk_list, blobs in _timed_iter(fast_batches, phases, "encode"):
                gc_batch += 1
                if gc_batch % 100 == 0:
                    gc.collect()
                with phases.span("hash_deflate"):
                    oids_u8 = repo.odb.write_blobs_raw(blobs)
                pks = np.asarray(pk_list, dtype=np.int64)
                if collect_local:
                    pk_chunks.append(pks)
                    oid_chunks.append(oids_u8.tobytes())
                if capture is not None:
                    capture.add_int_raw(pks, oids_u8.tobytes())
                count += len(pk_list)
                if log and count % 100000 == 0:
                    log(f"  {ds_path}: {count} features...")
            src_phases = getattr(source, "phase_seconds", None)
            if src_phases:
                read_s = min(
                    src_phases.get("source_read", 0.0),
                    phases.self_s.get("encode", 0.0),
                )
                phases.move("encode", "source_read", read_s)
        else:
            for batch in chunked(_timed_iter(source.features(), phases), BATCH_SIZE):
                gc_batch += 1
                if gc_batch % 100 == 0:
                    gc.collect()
                with phases.span("encode"):
                    encoded = [schema.encode_feature_blob(f) for f in batch]
                with phases.span("hash_deflate"):
                    oids = repo.odb.write_blobs([blob for _, blob in encoded])
                if use_batch_paths:
                    pks = np.fromiter(
                        (pk_values[0] for pk_values, _ in encoded),
                        dtype=np.int64,
                        count=len(encoded),
                    )
                    # no per-path TreeBuilder inserts: the whole feature tree
                    # is built in one vectorized pass after the stream
                    if collect_local:
                        pk_chunks.append(pks)
                        oid_chunks.append(bytes.fromhex("".join(oids)))
                else:
                    rel_paths = [
                        encoder.encode_pks_to_path(pk_values)
                        for pk_values, _ in encoded
                    ]
                    tb.insert_many((prefix + rel for rel in rel_paths), oids)
                if capture is not None:
                    if use_batch_paths:
                        capture.add_int_batch(pks, oids)
                    else:
                        capture.add_path_batch(rel_paths, oids)
                count += len(batch)
                if log and count % 100000 == 0:
                    log(f"  {ds_path}: {count} features...")

    if use_batch_paths and count and stream_root is not None:
        # the pipeline already built (and wrote) the feature tree from the
        # sorted stream — the strictly-increasing pk guarantee it enforces
        # also rules out duplicate pks, so no last-wins resolution needed
        from kart_tpu.core.objects import MODE_TREE

        with phases.span("tree_build"):
            tb.insert(
                f"{ds_path}/{Dataset3.DATASET_DIRNAME}/feature",
                stream_root,
                mode=MODE_TREE,
            )
    elif use_batch_paths and count:
        from kart_tpu.core.feature_tree import build_int_feature_tree
        from kart_tpu.core.objects import MODE_TREE

        cols = capture.int_columns() if isinstance(capture, SidecarCapture) else None
        if cols is not None:
            pks_arr, oids_u8 = cols
        else:
            pks_arr = np.concatenate(pk_chunks)
            oids_u8 = np.frombuffer(b"".join(oid_chunks), dtype=np.uint8).reshape(
                -1, 20
            )
        # duplicate pks in the source: last occurrence wins (git fast-import
        # semantics, matching the TreeBuilder dict path). One stable sort
        # both detects and resolves them.
        if len(pks_arr) > 1:
            order = np.argsort(pks_arr, kind="stable")
            sorted_pks = pks_arr[order]
            is_last = np.append(sorted_pks[1:] != sorted_pks[:-1], True)
            if not is_last.all():
                keep = np.sort(order[is_last])
                pks_arr = pks_arr[keep]
                oids_u8 = oids_u8[keep]
                if isinstance(capture, SidecarCapture):
                    # the sidecar must mirror the committed tree, not the
                    # raw stream — a stale duplicate row would later pair
                    # against the live head in the columnar merge-join and
                    # surface as a spurious UPDATE
                    capture.replace_int_columns(pks_arr, oids_u8)
        with phases.span("tree_build"):
            ftree = build_int_feature_tree(repo.odb, pks_arr, oids_u8, encoder)
            tb.insert(
                f"{ds_path}/{Dataset3.DATASET_DIRNAME}/feature",
                ftree,
                mode=MODE_TREE,
            )

    # meta items that only exist after the feature stream has run (e.g.
    # generated-pks.json from PK synthesis)
    late_meta = source.post_import_meta_items()
    if late_meta:
        from kart_tpu.core.serialise import json_pack

        inner = f"{ds_path}/{Dataset3.DATASET_DIRNAME}"
        for name, value in late_meta.items():
            data = json_pack(value) if not isinstance(value, bytes) else value
            tb.insert(f"{inner}/{Dataset3.META_PATH}{name}", repo.odb.write_blob(data))

    if log:
        log(f"  {ds_path}: {count} features")
    return count


def _run_import_pipeline(
    repo, tb, source, schema, encoder, prefix, *,
    capture, collect_local, pk_chunks, oid_chunks, use_batch_paths,
    log, ds_path,
):
    """Stream one source through the bounded 4-stage pipeline
    (:mod:`kart_tpu.importer.pipeline`): fused read+encode (ONE native
    call per batch for GPKG int-pk sources — io_gpkg_*) || native
    hash+deflate || pack write, with (pk, oid) columns collected on this
    thread in stream order. The sorted pk stream also drives the leaf-tree
    build *during* the stream: completed leaves are serialised here
    (:class:`~kart_tpu.core.feature_tree.StreamingLeafEmitter`) and
    injected through the hash/pack stages on the pipeline's side channel,
    so the Merkle build that used to run as a serial tail overlaps the
    feature stream. Byte-identical to the serial path (same blobs, same
    leaf payloads, same root oid — property tested); stage busy seconds
    land in :data:`LAST_IMPORT_PIPELINE`.
    -> (feature count, stream-built feature-root hex oid or None)."""
    import time as _time

    from kart_tpu import native
    from kart_tpu.core.feature_tree import StreamingLeafEmitter
    from kart_tpu.core.packs import TYPE_CODES
    from kart_tpu.importer.pipeline import run_pipeline

    writer = repo.odb._bulk_writer
    level = writer.level
    blob_code = TYPE_CODES["blob"]
    tree_code = TYPE_CODES["tree"]

    # --- fused read+encode producer ---------------------------------------
    # Read and encode share one thread on purpose. For native-capable GPKG
    # sources both run inside one GIL-free ctypes call per batch
    # (native_encoded_batches). The Python producers fuse them too: both
    # are GIL-bound, so a thread split buys no parallelism and costs a GIL
    # ping-pong per batch (see kart_tpu/importer/pipeline.py); the split is
    # preserved in *accounting* via phase_seconds and read/encode spans.
    def _make_producer(allow_native):
        if use_batch_paths and allow_native:
            nat = getattr(source, "native_encoded_batches", None)
            if nat is not None:
                from kart_tpu.importer.pipeline import batch_rows

                # None when native read is unavailable
                producer = nat(schema, batch_rows=batch_rows())
                if producer is not None:
                    return producer
        fast = getattr(source, "encoded_feature_batches", None)
        fb = fast(schema) if (use_batch_paths and fast is not None) else None
        if fb is not None:
            return (("py",) + tuple(item) for item in fb)
        # generic sources: stream features, encode through the compiled
        # per-legend blob serialiser (models/dataset.py)
        from kart_tpu.models.dataset import compiled_blob_encoder

        blob_enc = compiled_blob_encoder(schema)
        enc_path = encoder.encode_pks_to_path

        def _generic_producer():
            for batch in chunked(source.features(), BATCH_SIZE):
                with tm.span("importer.encode", rows=len(batch)):
                    keys = []
                    blobs = []
                    for feature in batch:
                        pk_values, blob = blob_enc(feature)
                        keys.append(
                            pk_values[0] if use_batch_paths
                            else enc_path(pk_values)
                        )
                        blobs.append(blob)
                yield ("py", keys, blobs)

        return _generic_producer()

    # --- streamed leaf-tree build -----------------------------------------
    # only engaged when the native IO core can hash/deflate the injected
    # payload batches; without it the end-of-stream build is just as fast
    # as a Python side channel would be
    leaf_stream = None
    if use_batch_paths and native.load_io() is not None:
        leaf_stream = StreamingLeafEmitter(encoder)
        if not leaf_stream.ok:
            leaf_stream = None

    # --- hash + pack stage functions --------------------------------------
    def hash_fn(item):
        tag = item[0]
        if tag == "enc":
            _, pks, buf, offs = item
            framed = native.pack_records_base("blob", blob_code, buf, offs, level)
            if framed is not None:
                return ("bf", pks, framed)
            # native lib lost mid-run (never in practice): slice + retry
            blobs = [
                buf[offs[i] : offs[i + 1]].tobytes() for i in range(len(pks))
            ]
            return (
                "pyf", pks, blobs,
                native.pack_records_batch("blob", blob_code, blobs, level),
            )
        if tag == "py":
            _, keys, blobs = item
            return (
                "pyf", keys, blobs,
                native.pack_records_batch("blob", blob_code, blobs, level),
            )
        # "tree": an injected leaf-payload batch from the side channel
        _, buf, offs, leaf_ids = item
        framed = native.pack_records_base("tree", tree_code, buf, offs, level)
        return ("tf", leaf_ids, framed, buf, offs)

    def pack_fn(item):
        tag = item[0]
        if tag == "bf":
            _, pks, framed = item
            return ("f", pks, writer.append_framed(framed))
        if tag == "pyf":
            _, keys, blobs, framed = item
            if framed is not None:
                return ("f", keys, writer.append_framed(framed))
            hexes = [writer.add("blob", b) for b in blobs]
            return (
                "f", keys,
                np.frombuffer(
                    bytes.fromhex("".join(hexes)), dtype=np.uint8
                ).reshape(-1, 20),
            )
        # "tf"
        _, leaf_ids, framed, buf, offs = item
        if framed is not None:
            return ("t", leaf_ids, writer.append_framed(framed))
        payloads = [
            buf[offs[i] : offs[i + 1]].tobytes()
            for i in range(len(leaf_ids))
        ]
        hexes = writer.add_batch("tree", payloads)
        return (
            "t", leaf_ids,
            np.frombuffer(
                bytes.fromhex("".join(hexes)), dtype=np.uint8
            ).reshape(-1, 20),
        )

    # --- main-thread collector --------------------------------------------
    count = 0
    gc_batch = 0
    tree_oid_chunks = []  # (n, 20) leaf oids, in leaf emission order
    tree_busy = 0.0

    def consume(item, inject=None):
        nonlocal count, gc_batch, tree_busy
        if item[0] == "t":
            tree_oid_chunks.append(item[2])
            return
        _, keys, oids_u8 = item
        gc_batch += 1
        if gc_batch % 100 == 0:
            gc.collect()  # bound any source-adapter cycles (gc is paused)
        if use_batch_paths:
            pks = (
                keys if isinstance(keys, np.ndarray)
                else np.asarray(keys, dtype=np.int64)
            )
            if collect_local:
                pk_chunks.append(pks)
                oid_chunks.append(oids_u8.tobytes())
            if capture is not None:
                capture.add_int_raw(pks, oids_u8.tobytes())
            if leaf_stream is not None and leaf_stream.ok and inject is not None:
                t0 = _time.perf_counter()
                with tm.span("importer.tree"):
                    out = leaf_stream.feed(pks, oids_u8)
                tree_busy += _time.perf_counter() - t0
                if out is not None:
                    buf, offs, leaf_ids = out
                    inject(("tree", buf, offs, leaf_ids))
        else:
            hexes = oids_u8.tobytes().hex()
            oid_list = [hexes[i : i + 40] for i in range(0, len(hexes), 40)]
            tb.insert_many((prefix + rel for rel in keys), oid_list)
            if capture is not None:
                capture.add_path_batch(keys, oid_list)
        count += len(keys)
        if log and count % 100000 < len(keys):
            log(f"  {ds_path}: {count} features...")

    def on_feat_done(inject):
        nonlocal tree_busy
        if leaf_stream is not None and leaf_stream.ok:
            t0 = _time.perf_counter()
            out = leaf_stream.finish()
            tree_busy += _time.perf_counter() - t0
            if out is not None:
                buf, offs, leaf_ids = out
                inject(("tree", buf, offs, leaf_ids))

    # --- drive the pipeline (one native-reader fallback retry) ------------
    # A row the native fused reader can't reproduce bit-identically raises
    # GpkgReaderFallback out of the producer mid-stream: reset every
    # collector the partial run touched and re-stream through the Python
    # encoder. Blobs already appended dedupe in the pack writer, so the
    # restart costs only the re-read, never a corrupt repo.
    cap_mark = capture.mark() if capture is not None else None
    base_pk_chunks = len(pk_chunks)
    base_oid_chunks = len(oid_chunks)

    def _reset_collectors():
        nonlocal count, gc_batch, tree_busy, leaf_stream
        count = 0
        gc_batch = 0
        tree_busy = 0.0
        tree_oid_chunks.clear()
        del pk_chunks[base_pk_chunks:]
        del oid_chunks[base_oid_chunks:]
        if capture is not None:
            capture.rewind(cap_mark)
        if leaf_stream is not None:
            # leaves already emitted reference the abandoned stream: start a
            # fresh emitter (stale leaf objects in the pack are benign)
            leaf_stream = StreamingLeafEmitter(encoder)
            if not leaf_stream.ok:
                leaf_stream = None

    t0 = _time.perf_counter()
    with tm.span("importer.pipeline", source=type(source).__name__):
        allow_native = True
        while True:
            producer = _make_producer(allow_native)
            try:
                stage_s = run_pipeline(
                    producer,
                    [("hash", hash_fn), ("pack", pack_fn)],
                    consume,
                    producer_span=False,
                    side_stage="hash" if leaf_stream is not None else None,
                    on_feat_done=(
                        on_feat_done if leaf_stream is not None else None
                    ),
                )
                break
            except native.GpkgReaderFallback:
                if not allow_native:
                    raise  # the Python encoder never raises this
                allow_native = False
                L.warning(
                    "native GPKG reader met a row it cannot reproduce "
                    "bit-identically; restarting import stream through "
                    "the Python encoder"
                )
                _reset_collectors()
    wall = _time.perf_counter() - t0

    # the stream-built feature tree: every leaf the emitter serialised came
    # back hashed; the upper spine is built here (cheap — branches^-1 of
    # the leaf count) now the stage threads have quiesced
    stream_root = None
    if leaf_stream is not None and leaf_stream.ok and count:
        n_leaves = sum(len(c) for c in leaf_stream.leaf_id_chunks)
        n_hashed = sum(len(c) for c in tree_oid_chunks)
        assert n_leaves == n_hashed, (n_leaves, n_hashed)
        with tm.span("importer.tree"):
            stream_root = leaf_stream.build_root(repo.odb, tree_oid_chunks)

    # split the fused producer's busy time back into read/encode when the
    # source kept its own phase accounting (the fast GPKG generators do)
    produce_s = stage_s.get("produce", 0.0)
    src_phases = getattr(source, "phase_seconds", None) or {}
    read_s = min(src_phases.get("source_read", 0.0), produce_s)
    global LAST_IMPORT_PIPELINE
    LAST_IMPORT_PIPELINE = {
        "read": read_s,
        "encode": produce_s - read_s,
        "hash": stage_s.get("hash", 0.0),
        "pack": stage_s.get("pack", 0.0),
        "tree": tree_busy,
        "wall": wall,
    }
    tm.incr("importer.pipeline_batches", gc_batch)
    return count, stream_root
