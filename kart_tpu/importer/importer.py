"""Bulk import: sources -> dataset trees -> one commit
(reference: kart/fast_import.py).

The reference shards features over N ``git fast-import`` subprocesses and
merges the resulting trees (fast_import.py:286-399). Here all object writes
go into packfiles, not per-feature loose files: serial imports append every
blob/tree into one new pack (``ObjectDb.bulk_pack``); shardable sources
(int-pk GPKG, see importer/parallel.py) fan out over N worker processes that
each write their own pack of feature blobs + leaf trees, joined by one
TreeBuilder spine rewrite. The commit object is written loose *after* the
packs are fsync'd, so a crash mid-import never leaves a dangling ref.
"""

import gc
import time

import numpy as np

from kart_tpu import telemetry as tm
from kart_tpu.core.structure import RepoStructure
from kart_tpu.core.tree_builder import TreeBuilder
from kart_tpu.models.dataset import Dataset3
from kart_tpu.models.paths import encoder_for_schema
from kart_tpu.utils import chunked, paused_gc

BATCH_SIZE = 10000
# below this, the tree-walk diff path is so cheap that a sidecar isn't worth
# the disk; above it, first-diff latency matters
SIDECAR_MIN_FEATURES = 10000

#: per-phase *self* seconds of the most recent import in this process —
#: {"source_read", "encode", "hash_deflate", "tree_build", "total"}.
#: Populated by the serial streaming path (the bench's phase-breakdown
#: record); the parallel fan-out interleaves phases across workers and
#: reports only the total. Accounting runs on a telemetry span stack
#: (:class:`kart_tpu.telemetry.Phases`): nested phases book wall-clock into
#: the innermost phase only, so the recorded self-times can never sum past
#: the total (the old ``phases[key] +=`` dict pattern double-booked
#: whenever phases overlapped).
LAST_IMPORT_PHASES = None

#: the phase keys the bench's ``import_phase_*`` record reads — stable
#: across the telemetry refactor
PHASE_KEYS = ("source_read", "encode", "hash_deflate", "tree_build")


def _new_phases():
    p = tm.Phases("importer")
    for key in PHASE_KEYS:  # every key present even when a path is skipped
        p.self_s.setdefault(key, 0.0)
        p.cum_s.setdefault(key, 0.0)
    return p


class ImportError_(RuntimeError):
    pass


def _timed_iter(it, phases, key="source_read"):
    """Wrap an iterator, accumulating its pull time into phase ``key``
    (leaf accounting: two clock reads per pull, no span objects in the
    per-item loop)."""
    it = iter(it)
    while True:
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            phases.add(key, time.perf_counter() - t0)
            return
        phases.add(key, time.perf_counter() - t0)
        yield item


def import_sources(
    repo,
    sources,
    *,
    message=None,
    replace_existing=False,
    replace_ids=None,
    log=None,
):
    """Import each source as a dataset; -> the new commit oid.

    replace_ids: iterable of pk values — incremental re-import (reference:
    fast_import.py:462-476): the existing dataset tree is kept, each listed
    id is deleted and then re-imported when the source still has it (so a
    listed id absent from the source becomes a delete). Implies
    replace_existing; an empty list re-imports nothing but still updates
    meta."""
    sources = list(sources)
    head_tree = repo.head_tree_oid
    structure = repo.structure("HEAD") if not repo.head_is_unborn else None
    existing_paths = (
        set(structure.datasets.paths()) if structure is not None else set()
    )

    from kart_tpu.importer.pk_generation import PkGeneratingImportSource

    from kart_tpu.diff.sidecar import SidecarCapture

    if replace_ids is not None:
        replace_existing = True  # implied, as in the reference CLI
        if len(sources) != 1:
            raise ImportError_(
                "--replace-ids requires a single-table import (the id list "
                "would be applied to every table)"
            )
    tb = TreeBuilder(repo.odb, head_tree)
    ds_paths = []
    captures = {}
    total = 0
    phases = _new_phases()
    t0 = time.monotonic()
    with tm.span("importer.import_sources", sources=len(sources)), repo.odb.bulk_pack():
        for source in sources:
            # PK-less sources get stable generated PKs
            # (reference: kart/pk_generation.py)
            source = PkGeneratingImportSource.wrap_if_needed(source, repo)
            ds_path = source.dest_path.strip("/")
            if ds_path in existing_paths and not replace_existing:
                raise ImportError_(
                    f"Dataset {ds_path!r} already exists — use --replace-existing"
                )
            if replace_existing and replace_ids is None:
                tb.remove(ds_path)
            existing_ds = (
                structure.datasets.get(ds_path) if structure is not None else None
            )
            capture = (
                SidecarCapture() if replace_ids is None else ReplaceIdsCapture()
            )
            count = _import_single_source(
                repo,
                tb,
                source,
                ds_path,
                log=log,
                capture=capture,
                replace_ids=replace_ids,
                existing_ds=existing_ds,
                phases=phases,
            )
            total += count
            ds_paths.append(ds_path)
            captures[ds_path] = (capture, existing_ds)

        with phases.span("tree_build"):
            new_tree = tb.flush()

    # commit + ref update only after the pack is durable (fsync'd) on disk:
    # a crash mid-import leaves an aborted tmp pack and an untouched HEAD,
    # never a dangling ref (reference analog: temp refs refs/kart-import/,
    # fast_import.py:307)
    if message is None:
        message = f"Import {len(ds_paths)} dataset(s): " + ", ".join(ds_paths)
    parents = [repo.head_commit_oid] if repo.head_commit_oid else []
    commit_oid = repo.create_commit("HEAD", new_tree, message, parents)

    # columnar sidecars, straight from the captured import stream — big
    # datasets get O(1) FeatureBlock loads on their first diff. replace-ids
    # imports derive the new sidecar from the old one + the change set
    # (O(changed)), so incremental re-imports keep the columnar cache.
    from kart_tpu.diff import sidecar as sidecar_mod

    root = repo.odb.tree(new_tree)
    for ds_path, (capture, existing_ds) in captures.items():
        node = root.get_or_none(
            f"{ds_path}/{Dataset3.DATASET_DIRNAME}/feature"
        )
        if node is None:
            continue
        if isinstance(capture, ReplaceIdsCapture):
            enc = getattr(existing_ds, "path_encoder", None) if existing_ds else None
            if enc is None or enc.scheme != "int":
                continue  # hash-keyed: would need per-path bookkeeping
            old_block = sidecar_mod.load_block(repo, existing_ds)
            if old_block is None:
                continue  # no cache to derive from; rebuilt lazily on use
            sidecar_mod.derive_sidecar(
                repo,
                old_block,
                node.oid,
                capture.removed_pks,
                dict(capture.added),
            )
            continue
        if capture.count < SIDECAR_MIN_FEATURES:
            continue
        capture.save(repo, node.oid)
    dt = time.monotonic() - t0
    global LAST_IMPORT_PHASES
    LAST_IMPORT_PHASES = {**phases.self_seconds(), "total": dt}
    tm.incr("importer.features_imported", total)
    if log:
        rate = total / dt if dt > 0 else float("inf")
        log(f"Imported {total} features in {dt:.2f}s ({rate:.0f} features/s)")
    return commit_oid


def _sanitise_pk(schema, pk):
    """CLI-supplied id (a string) -> the pk column's value type."""
    col = schema.pk_columns[0]
    if col.data_type == "integer":
        try:
            return int(pk)
        except (TypeError, ValueError):
            raise ImportError_(f"Invalid integer primary key: {pk!r}")
    return pk


def _check_replace_ids_compatible(existing_ds, schema, encoder):
    """--replace-ids keeps the existing tree, so the new feature paths must
    land where the old ones live: the path encoder and pk column must match
    the existing dataset, or deletes silently miss and unlisted features
    become unreachable under the rewritten meta."""
    if existing_ds is None:
        return
    old_enc = getattr(existing_ds, "path_encoder", None)
    if old_enc is not None and old_enc.to_dict() != encoder.to_dict():
        raise ImportError_(
            "--replace-ids cannot change the feature path encoding "
            f"({old_enc.to_dict()} -> {encoder.to_dict()}); re-import the "
            "whole dataset with --replace-existing instead"
        )
    old_pks = existing_ds.schema.pk_columns
    new_pks = schema.pk_columns
    if [(c.name, c.data_type) for c in old_pks] != [
        (c.name, c.data_type) for c in new_pks
    ]:
        raise ImportError_(
            "--replace-ids cannot change the primary key "
            f"({[(c.name, c.data_type) for c in old_pks]} -> "
            f"{[(c.name, c.data_type) for c in new_pks]}); re-import the "
            "whole dataset with --replace-existing instead"
        )


class ReplaceIdsCapture:
    """What a --replace-ids import changed, for the O(changed) sidecar
    derivation (the incremental-import workflow must not lose the columnar
    cache and fall back to full tree walks)."""

    def __init__(self):
        self.removed_pks = []
        self.added = []  # (pk int, oid hex)


def _import_replace_ids(
    repo, tb, source, schema, encoder, prefix, replace_ids, *,
    log=None, existing_ds=None, capture=None,
):
    """Incremental re-import: delete every listed id's path, re-import the
    ones the source still has. Everything unlisted keeps its existing blob
    and subtree (reference: fast_import.py:462-476 — 'D <path>' per id, then
    stream source.get_features(ids, ignore_missing=True))."""
    if len(schema.pk_columns) != 1:
        raise ImportError_(
            "--replace-ids requires the dataset to have a single-column "
            "primary key"
        )
    _check_replace_ids_compatible(existing_ds, schema, encoder)
    pks = [_sanitise_pk(schema, pk) for pk in replace_ids]
    for pk in pks:
        tb.remove(prefix + encoder.encode_pks_to_path((pk,)))
    if capture is not None:
        capture.removed_pks = pks

    count = 0
    for batch in chunked(
        source.get_features(pks, ignore_missing=True), BATCH_SIZE
    ):
        encoded = [schema.encode_feature_blob(f) for f in batch]
        rel_paths = [encoder.encode_pks_to_path(pkv) for pkv, _ in encoded]
        oids = repo.odb.write_blobs([blob for _, blob in encoded])
        tb.insert_many((prefix + rel for rel in rel_paths), oids)
        if capture is not None:
            capture.added.extend(
                (pkv[0], oid) for (pkv, _), oid in zip(encoded, oids)
            )
        count += len(batch)
    if log:
        log(
            f"  replaced {count} of {len(pks)} listed id(s); "
            f"{len(pks) - count} deleted"
        )
    return count


def _import_single_source(
    repo, tb, source, ds_path, *, log=None, capture=None, replace_ids=None,
    existing_ds=None, phases=None,
):
    from kart_tpu.diff.sidecar import SidecarCapture

    if phases is None:
        phases = _new_phases()

    schema = source.schema
    encoder = encoder_for_schema(schema)
    meta = source.meta_items()
    meta_blobs = Dataset3.new_dataset_meta_blobs(
        ds_path,
        schema,
        title=meta.get("title"),
        description=meta.get("description"),
        crs_defs=source.crs_definitions(),
        path_encoder=encoder,
    )
    for path, data in meta_blobs:
        tb.insert(path, repo.odb.write_blob(data))

    from kart_tpu.importer.parallel import (
        default_workers,
        run_parallel_import,
        shardable,
    )

    prefix = f"{ds_path}/{Dataset3.DATASET_DIRNAME}/{Dataset3.FEATURE_PATH}"

    if replace_ids is not None:
        return _import_replace_ids(
            repo, tb, source, schema, encoder, prefix, replace_ids,
            log=log, existing_ds=existing_ds, capture=capture,
        )

    n_workers = default_workers()
    if shardable(source, encoder, n_workers):
        count = run_parallel_import(
            repo, tb, source, ds_path, encoder, prefix, n_workers,
            log=log, capture=capture,
        )
        return count

    count = 0
    use_batch_paths = encoder.scheme == "int"
    # int-pk fast path: (pks, oid bytes) -> vectorized tree build. When a
    # SidecarCapture is running it already holds these columns; only
    # accumulate separately without one (a 100M import must not hold two
    # 2.8GB copies)
    collect_local = use_batch_paths and not isinstance(capture, SidecarCapture)
    pk_chunks = []
    oid_chunks = []
    # the streaming loop allocates short-lived, acyclic objects by the
    # million: pause the cyclic collector (~8% measured). Source adapters
    # may create cycles internally, so bound their growth with a manual
    # collection every ~1M rows rather than trusting full acyclicity.
    # Fast pre-encoded stream (int-pk GPKG): the source yields whole
    # (pk_list, blob_list) batches and oids stay columnar end-to-end — no
    # per-feature dicts, no per-row tuples, no hex round trips (see
    # GPKGImportSource.encoded_feature_batches).
    fast_batches = None
    if use_batch_paths:
        fast = getattr(source, "encoded_feature_batches", None)
        if fast is not None:
            fast_batches = fast(schema)

    with paused_gc():
        gc_batch = 0
        if fast_batches is not None:
            # phase timing: the generator fuses source read + encode; its
            # own phase_seconds split (the GPKG source keeps one) is folded
            # in below — here the generator pull is accounted as encode
            # and rebalanced from the source's accumulators afterwards
            for pk_list, blobs in _timed_iter(fast_batches, phases, "encode"):
                gc_batch += 1
                if gc_batch % 100 == 0:
                    gc.collect()
                with phases.span("hash_deflate"):
                    oids_u8 = repo.odb.write_blobs_raw(blobs)
                pks = np.asarray(pk_list, dtype=np.int64)
                if collect_local:
                    pk_chunks.append(pks)
                    oid_chunks.append(oids_u8.tobytes())
                if capture is not None:
                    capture.add_int_raw(pks, oids_u8.tobytes())
                count += len(pk_list)
                if log and count % 100000 == 0:
                    log(f"  {ds_path}: {count} features...")
            src_phases = getattr(source, "phase_seconds", None)
            if src_phases:
                read_s = min(
                    src_phases.get("source_read", 0.0),
                    phases.self_s.get("encode", 0.0),
                )
                phases.move("encode", "source_read", read_s)
        else:
            for batch in chunked(_timed_iter(source.features(), phases), BATCH_SIZE):
                gc_batch += 1
                if gc_batch % 100 == 0:
                    gc.collect()
                with phases.span("encode"):
                    encoded = [schema.encode_feature_blob(f) for f in batch]
                with phases.span("hash_deflate"):
                    oids = repo.odb.write_blobs([blob for _, blob in encoded])
                if use_batch_paths:
                    pks = np.fromiter(
                        (pk_values[0] for pk_values, _ in encoded),
                        dtype=np.int64,
                        count=len(encoded),
                    )
                    # no per-path TreeBuilder inserts: the whole feature tree
                    # is built in one vectorized pass after the stream
                    if collect_local:
                        pk_chunks.append(pks)
                        oid_chunks.append(bytes.fromhex("".join(oids)))
                else:
                    rel_paths = [
                        encoder.encode_pks_to_path(pk_values)
                        for pk_values, _ in encoded
                    ]
                    tb.insert_many((prefix + rel for rel in rel_paths), oids)
                if capture is not None:
                    if use_batch_paths:
                        capture.add_int_batch(pks, oids)
                    else:
                        capture.add_path_batch(rel_paths, oids)
                count += len(batch)
                if log and count % 100000 == 0:
                    log(f"  {ds_path}: {count} features...")

    if use_batch_paths and count:
        from kart_tpu.core.feature_tree import build_int_feature_tree
        from kart_tpu.core.objects import MODE_TREE

        cols = capture.int_columns() if isinstance(capture, SidecarCapture) else None
        if cols is not None:
            pks_arr, oids_u8 = cols
        else:
            pks_arr = np.concatenate(pk_chunks)
            oids_u8 = np.frombuffer(b"".join(oid_chunks), dtype=np.uint8).reshape(
                -1, 20
            )
        # duplicate pks in the source: last occurrence wins (git fast-import
        # semantics, matching the TreeBuilder dict path). One stable sort
        # both detects and resolves them.
        if len(pks_arr) > 1:
            order = np.argsort(pks_arr, kind="stable")
            sorted_pks = pks_arr[order]
            is_last = np.append(sorted_pks[1:] != sorted_pks[:-1], True)
            if not is_last.all():
                keep = np.sort(order[is_last])
                pks_arr = pks_arr[keep]
                oids_u8 = oids_u8[keep]
                if isinstance(capture, SidecarCapture):
                    # the sidecar must mirror the committed tree, not the
                    # raw stream — a stale duplicate row would later pair
                    # against the live head in the columnar merge-join and
                    # surface as a spurious UPDATE
                    capture.replace_int_columns(pks_arr, oids_u8)
        with phases.span("tree_build"):
            ftree = build_int_feature_tree(repo.odb, pks_arr, oids_u8, encoder)
            tb.insert(
                f"{ds_path}/{Dataset3.DATASET_DIRNAME}/feature",
                ftree,
                mode=MODE_TREE,
            )

    # meta items that only exist after the feature stream has run (e.g.
    # generated-pks.json from PK synthesis)
    late_meta = source.post_import_meta_items()
    if late_meta:
        from kart_tpu.core.serialise import json_pack

        inner = f"{ds_path}/{Dataset3.DATASET_DIRNAME}"
        for name, value in late_meta.items():
            data = json_pack(value) if not isinstance(value, bytes) else value
            tb.insert(f"{inner}/{Dataset3.META_PATH}{name}", repo.odb.write_blob(data))

    if log:
        log(f"  {ds_path}: {count} features")
    return count
