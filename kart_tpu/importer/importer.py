"""Bulk import: sources -> dataset trees -> one commit
(reference: kart/fast_import.py).

The reference shards features over N ``git fast-import`` subprocesses and
merges the resulting trees (fast_import.py:286-399). Here all object writes
go into packfiles, not per-feature loose files: serial imports append every
blob/tree into one new pack (``ObjectDb.bulk_pack``); shardable sources
(int-pk GPKG, see importer/parallel.py) fan out over N worker processes that
each write their own pack of feature blobs + leaf trees, joined by one
TreeBuilder spine rewrite. The commit object is written loose *after* the
packs are fsync'd, so a crash mid-import never leaves a dangling ref.
"""

import gc
import time

import numpy as np

from kart_tpu.core.structure import RepoStructure
from kart_tpu.core.tree_builder import TreeBuilder
from kart_tpu.models.dataset import Dataset3
from kart_tpu.models.paths import encoder_for_schema
from kart_tpu.utils import chunked, paused_gc

BATCH_SIZE = 10000
# below this, the tree-walk diff path is so cheap that a sidecar isn't worth
# the disk; above it, first-diff latency matters
SIDECAR_MIN_FEATURES = 10000


class ImportError_(RuntimeError):
    pass


def import_sources(
    repo,
    sources,
    *,
    message=None,
    replace_existing=False,
    log=None,
):
    """Import each source as a dataset; -> the new commit oid."""
    head_tree = repo.head_tree_oid
    structure = repo.structure("HEAD") if not repo.head_is_unborn else None
    existing_paths = (
        set(structure.datasets.paths()) if structure is not None else set()
    )

    from kart_tpu.importer.pk_generation import PkGeneratingImportSource

    from kart_tpu.diff.sidecar import SidecarCapture

    tb = TreeBuilder(repo.odb, head_tree)
    ds_paths = []
    captures = {}
    total = 0
    t0 = time.monotonic()
    with repo.odb.bulk_pack():
        for source in sources:
            # PK-less sources get stable generated PKs
            # (reference: kart/pk_generation.py)
            source = PkGeneratingImportSource.wrap_if_needed(source, repo)
            ds_path = source.dest_path.strip("/")
            if ds_path in existing_paths and not replace_existing:
                raise ImportError_(
                    f"Dataset {ds_path!r} already exists — use --replace-existing"
                )
            if replace_existing:
                tb.remove(ds_path)
            capture = SidecarCapture()
            count = _import_single_source(
                repo, tb, source, ds_path, log=log, capture=capture
            )
            total += count
            ds_paths.append(ds_path)
            captures[ds_path] = capture

        new_tree = tb.flush()

    # commit + ref update only after the pack is durable (fsync'd) on disk:
    # a crash mid-import leaves an aborted tmp pack and an untouched HEAD,
    # never a dangling ref (reference analog: temp refs refs/kart-import/,
    # fast_import.py:307)
    if message is None:
        message = f"Import {len(ds_paths)} dataset(s): " + ", ".join(ds_paths)
    parents = [repo.head_commit_oid] if repo.head_commit_oid else []
    commit_oid = repo.create_commit("HEAD", new_tree, message, parents)

    # columnar sidecars, straight from the captured import stream — big
    # datasets get O(1) FeatureBlock loads on their first diff
    root = repo.odb.tree(new_tree)
    for ds_path, capture in captures.items():
        if capture.count < SIDECAR_MIN_FEATURES:
            continue
        node = root.get_or_none(
            f"{ds_path}/{Dataset3.DATASET_DIRNAME}/feature"
        )
        if node is not None:
            capture.save(repo, node.oid)
    if log:
        dt = time.monotonic() - t0
        rate = total / dt if dt > 0 else float("inf")
        log(f"Imported {total} features in {dt:.2f}s ({rate:.0f} features/s)")
    return commit_oid


def _import_single_source(repo, tb, source, ds_path, *, log=None, capture=None):
    schema = source.schema
    encoder = encoder_for_schema(schema)
    meta = source.meta_items()
    meta_blobs = Dataset3.new_dataset_meta_blobs(
        ds_path,
        schema,
        title=meta.get("title"),
        description=meta.get("description"),
        crs_defs=source.crs_definitions(),
        path_encoder=encoder,
    )
    for path, data in meta_blobs:
        tb.insert(path, repo.odb.write_blob(data))

    from kart_tpu.importer.parallel import (
        default_workers,
        run_parallel_import,
        shardable,
    )

    prefix = f"{ds_path}/{Dataset3.DATASET_DIRNAME}/{Dataset3.FEATURE_PATH}"
    n_workers = default_workers()
    if shardable(source, encoder, n_workers):
        count = run_parallel_import(
            repo, tb, source, ds_path, encoder, prefix, n_workers,
            log=log, capture=capture,
        )
        return count

    count = 0
    use_batch_paths = encoder.scheme == "int"
    # the streaming loop allocates short-lived, acyclic objects by the
    # million: pause the cyclic collector (~8% measured). Source adapters
    # may create cycles internally, so bound their growth with a manual
    # collection every ~1M rows rather than trusting full acyclicity.
    with paused_gc():
        gc_batch = 0
        for batch in chunked(source.features(), BATCH_SIZE):
            gc_batch += 1
            if gc_batch % 100 == 0:
                gc.collect()
            encoded = [schema.encode_feature_blob(f) for f in batch]
            if use_batch_paths:
                pks = np.fromiter(
                    (pk_values[0] for pk_values, _ in encoded),
                    dtype=np.int64,
                    count=len(encoded),
                )
                rel_paths = encoder.encode_paths_batch(pks)
            else:
                rel_paths = [
                    encoder.encode_pks_to_path(pk_values)
                    for pk_values, _ in encoded
                ]
            oids = repo.odb.write_blobs([blob for _, blob in encoded])
            tb.insert_many((prefix + rel for rel in rel_paths), oids)
            if capture is not None:
                if use_batch_paths:
                    capture.add_int_batch(pks, oids)
                else:
                    capture.add_path_batch(rel_paths, oids)
            count += len(batch)
            if log and count % 100000 == 0:
                log(f"  {ds_path}: {count} features...")

    # meta items that only exist after the feature stream has run (e.g.
    # generated-pks.json from PK synthesis)
    late_meta = source.post_import_meta_items()
    if late_meta:
        from kart_tpu.core.serialise import json_pack

        inner = f"{ds_path}/{Dataset3.DATASET_DIRNAME}"
        for name, value in late_meta.items():
            data = json_pack(value) if not isinstance(value, bytes) else value
            tb.insert(f"{inner}/{Dataset3.META_PATH}{name}", repo.odb.write_blob(data))

    if log:
        log(f"  {ds_path}: {count} features")
    return count
