"""Changed-block CDC: which tiles changed between two commits, computed
from sidecar columns alone (docs/EVENTS.md §2).

The whole pipeline is a re-use of machinery earlier PRs already proved at
100M-row scale, composed into a new question:

1. **Row delta** — both tips' sorted (key, oid) sidecar columns feed the
   diff engine's block classifier
   (:func:`kart_tpu.ops.diff_kernel.classify_blocks`, the 160M rows/s
   merge-join): a row is *changed* when its key was inserted, deleted, or
   kept with a different oid. No feature blob is ever read — the oid IS
   the value identity (content addressing).
2. **Changed envelopes** — the changed rows' wsen rectangles come from the
   same sidecar envelope columns (PR 1) the tile encoder selects rows by.
3. **Tile cover** — each changed envelope maps through the WebMercator
   cover math of :mod:`kart_tpu.tiles.grid` to the tile addresses whose
   *membership rectangle* it intersects, per zoom.

Exactness (the acceptance property, tests/test_events.py): for any layer
set that includes ``geojson``, the dirty set equals — superset-free AND
subset-free — the set of tiles whose payload **content** differs between
the two commits (payload headers embed the commit oid by design, so
"content" means the layer bytes + feature count). The argument:

* tile membership is purely envelope-based (`clip.py`'s exact refine runs
  against the envelope columns, not decoded geometry), so a tile's row set
  is a deterministic function of (keys, envelopes);
* a changed oid means a changed blob means a changed geojson line (the
  compiled serialisers are deterministic), and a changed envelope implies
  a changed geometry implies a changed oid;
* therefore a tile's payload differs **iff** some row whose envelope
  intersects the tile was inserted/deleted/oid-changed — exactly the set
  computed here. (``bin``-only payloads can coincide across an
  attribute-only change — for those the set is a documented superset.)

The cover math mirrors :func:`kart_tpu.ops.bbox.bbox_intersects_np`'s
closed/cyclic semantics exactly, including touching edges, the
anti-meridian seam, the polar extension of edge rows, and degenerate
(n < s) rectangles — the exactness property is only as good as this
correspondence, and the property test hammers it with random edits.
"""

import numpy as np

from kart_tpu import faults
from kart_tpu import telemetry as tm
from kart_tpu.tiles.grid import merc_xy_cols

#: zoom levels an event's dirty-tile set enumerates (deeper zooms are
#: derivable client-side: a z+1 tile is dirty only if its z parent is).
DEFAULT_EVENT_ZOOMS = tuple(range(0, 9))

#: ceiling on enumerated dirty tiles per dataset per event: past this the
#: event carries per-zoom counts + the changed-region bbox only
#: (``truncated``) — an invalidation message must stay a message, not a
#: payload.
MAX_EVENT_TILES = 4096


def _normalise_lon(w, e):
    """Longitude columns -> (w', e', wraps) matching the cyclic range
    semantics of :mod:`kart_tpu.ops.bbox`: values folded into [-180, 180],
    ``wraps`` marking ranges that cross the anti-meridian (including
    out-of-range inputs whose folded ends swap). Full-width (>= 360°)
    ranges come back as (-180, 180, False)."""
    full = (e - w) >= 360.0
    wf = np.mod(w + 180.0, 360.0) - 180.0
    ef = np.mod(e + 180.0, 360.0) - 180.0
    # the fold maps +180 to -180; keep an exact east bound at the seam
    ef = np.where((ef == -180.0) & (e != w), 180.0, ef)
    wraps = (ef < wf) & ~full
    w2 = np.where(full, -180.0, wf)
    e2 = np.where(full, 180.0, ef)
    return w2, e2, wraps


def _merc_rows(lat):
    """Vectorized latitude degrees -> normalized mercator y (0 = north
    clamp), ±inf clipped to the poles first (matching the closed lat
    compare, where an infinite bound matches everything on its side)."""
    return merc_xy_cols(np.zeros_like(lat), np.clip(lat, -90.0, 90.0))[1]


def tile_cover_ranges(z, envelopes):
    """(M, 4) f64 wsen envelopes -> list of inclusive tile ranges
    ``(x0, x1, y0, y1)`` arrays, one entry per contiguous x-range (a
    wrapping envelope contributes two; seam-touching envelopes gain the
    opposite edge column). A range with ``y0 > y1`` (degenerate rect no
    tile row spans) selects nothing. The ranges reproduce — closed edges,
    poles, seam — which tiles' cover rectangles
    (:func:`kart_tpu.tiles.grid.tile_cover_wsen`) each envelope
    intersects under :func:`~kart_tpu.ops.bbox.bbox_intersects_np`."""
    env = np.asarray(envelopes, dtype=np.float64).reshape(-1, 4)
    n = 1 << z
    w, s, e, nl = env[:, 0], env[:, 1], env[:, 2], env[:, 3]
    # rows the engine's own scans place in no tile: NaN anywhere kills the
    # closed compares; a non-finite longitude NaN-poisons the cyclic math
    keep = (
        np.isfinite(w) & np.isfinite(e) & ~np.isnan(s) & ~np.isnan(nl)
    )
    if not keep.all():
        env = env[keep]
        w, s, e, nl = env[:, 0], env[:, 1], env[:, 2], env[:, 3]
    if not len(env):
        return []
    w, e, wraps = _normalise_lon(w, e)

    # closed-edge tile ranges: tile x covers [x/n*360-180, (x+1)/n*360-180],
    # so x intersects [w, e] iff ceil(fx_w)-1 <= x <= floor(fx_e)
    fx_w = (w + 180.0) / 360.0 * n
    fx_e = (e + 180.0) / 360.0 * n
    x0 = np.ceil(fx_w).astype(np.int64) - 1
    x1 = np.floor(fx_e).astype(np.int64)
    # mercator rows, same closed algebra (monotonic decreasing in lat).
    # The clip of the fractional row into [0, n] is the polar extension
    # of the edge rows: a latitude at/beyond the WebMercator clamp maps
    # to y ≈ ±1e-17 in floating point, and without the clip a -1e-17
    # would floor to row -1 and silently drop a polar feature's tiles —
    # the exact bug class tile_cover_wsen exists to prevent
    fy_n = np.clip(_merc_rows(nl) * n, 0.0, float(n))
    fy_s = np.clip(_merc_rows(s) * n, 0.0, float(n))
    y0 = np.maximum(np.ceil(fy_n).astype(np.int64) - 1, 0)
    y0 = np.minimum(y0, n - 1)
    y1 = np.minimum(np.floor(fy_s).astype(np.int64), n - 1)
    # NOTE: y1 may end < y0 for degenerate (n < s) rects — that's the
    # correct empty selection, so no clamp of y1 up to 0

    ranges = []
    plain = ~wraps
    if plain.any():
        ranges.append(
            (
                np.clip(x0[plain], 0, n - 1),
                np.clip(x1[plain], 0, n - 1),
                y0[plain],
                y1[plain],
            )
        )
        # the anti-meridian seam: 180 and -180 are the same meridian, so
        # an envelope touching one edge touches the tile column at the
        # other (bbox_intersects_np's mod-360 math; measure-zero for real
        # data, but exactness is exactness)
        seam_e = plain & (e == 180.0) & (w > -180.0)
        if seam_e.any():
            zeros = np.zeros(int(seam_e.sum()), dtype=np.int64)
            ranges.append((zeros, zeros, y0[seam_e], y1[seam_e]))
        seam_w = plain & (w == -180.0) & (e < 180.0)
        if seam_w.any():
            last = np.full(int(seam_w.sum()), n - 1, dtype=np.int64)
            ranges.append((last, last, y0[seam_w], y1[seam_w]))
    if wraps.any():
        # wrapping range [w, 180] ∪ [-180, e]: two contiguous x-ranges
        xw = np.clip(x0[wraps], 0, n - 1)
        xe = np.clip(x1[wraps], 0, n - 1)
        hi = np.full(len(xw), n - 1, dtype=np.int64)
        lo = np.zeros(len(xe), dtype=np.int64)
        ranges.append((xw, hi, y0[wraps], y1[wraps]))
        ranges.append((lo, xe, y0[wraps], y1[wraps]))
    return ranges


def tiles_for_envelopes(z, envelopes, cap=None):
    """-> (sorted unique (k, 2) int64 ``[x, y]`` tile addresses at zoom
    ``z`` whose cover intersects any envelope, unique count, capped
    bool). ``capped=True`` means the enumeration stopped early — the
    address list is INCOMPLETE and the caller must treat the result as
    truncated regardless of the unique count (overlapping envelopes can
    dedup below the cap while un-enumerated ranges remain; publishing
    such a list as exact would silently drop invalidations)."""
    n = 1 << z
    packed = []
    total = 0
    capped = False
    for x0, x1, y0, y1 in tile_cover_ranges(z, envelopes):
        nx = x1 - x0 + 1
        ny = y1 - y0 + 1
        valid = (nx > 0) & (ny > 0)
        if not valid.any():
            continue
        x0, nx, y0, ny = x0[valid], nx[valid], y0[valid], ny[valid]
        sizes = nx * ny
        for i in range(len(x0)):
            xs = np.arange(x0[i], x0[i] + nx[i], dtype=np.int64)
            ys = np.arange(y0[i], y0[i] + ny[i], dtype=np.int64)
            packed.append(
                (xs[:, None] * n + ys[None, :]).ravel()
            )
            total += int(sizes[i])
            if cap is not None and total > cap:
                capped = True
                break
        if capped:
            break
    if not packed:
        return np.zeros((0, 2), dtype=np.int64), 0, False
    uniq = np.unique(np.concatenate(packed))
    out = np.empty((len(uniq), 2), dtype=np.int64)
    out[:, 0] = uniq // n
    out[:, 1] = uniq % n
    return out, len(uniq), capped


def _source_or_none(repo, commit_oid, ds_path):
    from kart_tpu.tiles.source import TileSourceError, source_for

    if commit_oid is None:
        return None
    try:
        return source_for(repo, commit_oid, ds_path)
    except TileSourceError:
        return None


# ---------------------------------------------------------------------------
# O(changed) sidecar derivation for freshly-pushed tips
#
# A pushed commit arrives with no sidecar on the server (sidecars are a
# local cache, packs don't ship them), and letting ensure_block rebuild it
# is an O(N) feature-tree walk — at 100M rows that walk, not the CDC,
# would dominate the push→announce latency. The tree-level delta between
# the two feature trees is O(changed × depth) (unchanged subtrees share
# oids and are skipped whole), and the PR 1 derive_sidecar turns the old
# block + that delta into the new sidecar with O(changed) array ops. The
# only blob reads are the added/changed features' own blobs — they carry
# the new envelopes and exist nowhere else; everything untouched rides
# over from the old sidecar.
# ---------------------------------------------------------------------------


def _tree_delta(odb, old_tree_oid, new_tree_oid):
    """-> (removed {path: oid}, added {path: oid}) of blob leaves between
    two feature trees, walking only subtrees whose oids differ."""
    from kart_tpu.core.odb import ObjectMissing

    removed, added = {}, {}
    stack = [(old_tree_oid, new_tree_oid, "")]
    while stack:
        old_oid, new_oid, prefix = stack.pop()
        if old_oid == new_oid:
            continue
        try:
            old_entries = (
                {e.name: e for e in odb.read_tree_entries(old_oid)}
                if old_oid
                else {}
            )
            new_entries = (
                {e.name: e for e in odb.read_tree_entries(new_oid)}
                if new_oid
                else {}
            )
        except (ObjectMissing, KeyError, ValueError):
            raise _DeltaUnavailable()
        for name in set(old_entries) | set(new_entries):
            o, n = old_entries.get(name), new_entries.get(name)
            path = f"{prefix}{name}"
            o_tree = o is not None and o.is_tree
            n_tree = n is not None and n.is_tree
            if o_tree or n_tree:
                stack.append(
                    (
                        o.oid if o_tree else None,
                        n.oid if n_tree else None,
                        f"{path}/",
                    )
                )
                if o is not None and not o_tree:
                    removed[path] = o.oid
                if n is not None and not n_tree:
                    added[path] = n.oid
                continue
            if o is not None and n is not None and o.oid == n.oid:
                continue
            if o is not None:
                removed[path] = o.oid
            if n is not None:
                added[path] = n.oid
    return removed, added


class _DeltaUnavailable(Exception):
    """The tree delta can't be computed (shallow/partial history) — fall
    back to the full sidecar build."""


def ensure_derived_sidecar(repo, old_ds, new_ds):
    """Make sure ``new_ds``'s feature tree has a sidecar, deriving it
    O(changed) from ``old_ds``'s when possible (int-pk dataset, old
    sidecar with envelope columns present). -> True when a sidecar exists
    afterwards without an O(N) walk having run here (the fallback build
    is left to the tile source's ensure_block)."""
    from kart_tpu.diff import sidecar

    if new_ds is None or new_ds.feature_tree is None:
        return False
    if sidecar.has_sidecar(repo, new_ds):
        return True
    if (
        old_ds is None
        or old_ds.feature_tree is None
        or old_ds.path_encoder.scheme != "int"
    ):
        return False
    old_block = sidecar.load_block(repo, old_ds, pad=False)
    if old_block is None:
        return False
    try:
        removed_paths, added_paths = _tree_delta(
            repo.odb, old_ds.feature_tree.oid, new_ds.feature_tree.oid
        )
    except _DeltaUnavailable:
        return False
    with tm.span("events.derive_sidecar", changed=len(added_paths)):
        decode = new_ds.decode_path_to_pks
        removed = {int(decode(p)[0]) for p in removed_paths}
        added = {}
        added_envs = {} if old_block.envelopes is not None else None
        geom_col = new_ds.geom_column_name
        if added_envs is not None and added_paths:
            paths = sorted(added_paths)
            oids = [added_paths[p] for p in paths]
            blobs = repo.odb.read_blobs_data_ordered(
                [bytes.fromhex(o) for o in oids]
            )
            for path, oid, blob in zip(paths, oids, blobs):
                pk = int(decode(path)[0])
                added[pk] = oid
                if blob is None:
                    blob = repo.odb.read_blob(oid)
                feature = new_ds.get_feature(
                    (pk,), data=blob
                )
                added_envs[pk] = sidecar._feature_envelope_wsen(
                    feature, geom_col
                )
        else:
            added = {
                int(decode(p)[0]): oid for p, oid in added_paths.items()
            }
        sidecar.derive_sidecar(
            repo, old_block, new_ds.feature_tree.oid, removed, added,
            added_envs,
        )
    return True


def changed_envelopes(old_source, new_source):
    """-> ((M, 4) f64 changed-row envelopes drawn from both tips, counts
    dict) via the diff engine's sorted merge-join over the two sidecar
    (key, oid) columns. ``None`` on either side means the dataset
    appeared/vanished — every row of the other side is changed."""
    from kart_tpu.ops.diff_kernel import changed_indices, classify_blocks

    if old_source is None and new_source is None:
        return np.zeros((0, 4), dtype=np.float64), {}
    if old_source is None or new_source is None:
        src = new_source if old_source is None else old_source
        envs = np.asarray(src.envelopes(), dtype=np.float64)
        kind = "inserts" if old_source is None else "deletes"
        return envs, {kind: src.block.count}
    old_block, new_block = old_source.block, new_source.block
    with tm.span("events.cdc_classify",
                 rows=max(old_block.count, new_block.count)):
        old_class, new_class, counts = classify_blocks(old_block, new_block)
        old_idx, new_idx = changed_indices(old_class, new_class)
    parts = []
    if len(old_idx):
        parts.append(np.asarray(old_source.envelopes(), dtype=np.float64)[old_idx])
    if len(new_idx):
        parts.append(np.asarray(new_source.envelopes(), dtype=np.float64)[new_idx])
    envs = (
        np.concatenate(parts)
        if parts
        else np.zeros((0, 4), dtype=np.float64)
    )
    return envs, {
        k: int(v)
        for k, v in counts.items()
        if k in ("inserts", "deletes", "updates") and v
    }


def _bbox_of(envelopes):
    """Union wsen of the changed envelopes (finite members only; wrapping
    members widen to full longitude) — the coarse invalidation rectangle a
    truncated event still carries."""
    env = np.asarray(envelopes, dtype=np.float64).reshape(-1, 4)
    finite = np.isfinite(env).all(axis=1)
    env = env[finite]
    if not len(env):
        return None
    wraps = env[:, 2] < env[:, 0]
    w = -180.0 if wraps.any() else float(env[:, 0].min())
    e = 180.0 if wraps.any() else float(env[:, 2].max())
    return [w, float(env[:, 1].min()), e, float(env[:, 3].max())]


def dirty_tiles(repo, old_oid, new_oid, *, zooms=DEFAULT_EVENT_ZOOMS,
                max_tiles=MAX_EVENT_TILES):
    """The CDC verb: -> the per-dataset dirty-tile summary dict between
    two commits of ``repo`` (either side ``None`` for ref create/delete).

        {ds_path: {"changed": {"inserts": i, "deletes": d, "updates": u},
                   "zooms": [z0, z1, ...],
                   "tiles": {"z": [[x, y], ...], ...} | None,
                   "tile_count": total unique tiles across zooms,
                   "bbox": [w, s, e, n] | None,
                   "truncated": bool}}

    ``tiles`` is ``None`` for non-spatial / un-diffable datasets (the
    subscriber invalidates the whole dataset) and for truncated events
    (invalidate by ``bbox``). Datasets whose feature trees are identical
    are omitted entirely. Fires the ``events.emit`` frame-1 fault before
    any computation (the injectable CDC crash)."""
    faults.fire("events.emit")  # frame 1: the CDC computation
    summary = {}
    old_sets = _datasets_at(repo, old_oid)
    new_sets = _datasets_at(repo, new_oid)
    paths = set(old_sets.paths() if old_sets else ()) | set(
        new_sets.paths() if new_sets else ()
    )
    with tm.span("events.cdc", datasets=len(paths)):
        for ds_path in sorted(paths):
            old_ds = old_sets.get(ds_path) if old_sets else None
            new_ds = new_sets.get(ds_path) if new_sets else None
            old_tree = _tree_oid(old_ds)
            new_tree = _tree_oid(new_ds)
            if old_tree == new_tree:
                continue  # identical content: clean by construction
            if new_ds is not None:
                # a freshly-pushed tip has no sidecar: derive it
                # O(changed) from the old tip's instead of letting the
                # tile source pay the O(N) feature-tree rebuild
                ensure_derived_sidecar(repo, old_ds, new_ds)
            old_src = _source_or_none(repo, old_oid, ds_path)
            new_src = _source_or_none(repo, new_oid, ds_path)
            if old_src is None and new_src is None:
                # non-spatial (or unreadable) on both sides: no tile space
                # to be exact in — the subscriber invalidates the dataset
                summary[ds_path] = {
                    "changed": None, "zooms": list(zooms), "tiles": None,
                    "tile_count": None, "bbox": None, "truncated": False,
                }
                continue
            envs, counts = changed_envelopes(old_src, new_src)
            entry = {
                "changed": counts,
                "zooms": list(zooms),
                "bbox": _bbox_of(envs),
                "truncated": False,
            }
            tiles = {}
            total = 0
            capped = False
            for z in zooms:
                addrs, k, capped = tiles_for_envelopes(
                    z, envs, cap=max_tiles
                )
                tiles[str(z)] = addrs.tolist()
                total += k
                if capped or total > max_tiles:
                    break
            if capped or total > max_tiles:
                entry["tiles"] = None
                entry["truncated"] = True
                entry["tile_count"] = None
            else:
                entry["tiles"] = tiles
                entry["tile_count"] = total
                tm.incr("events.dirty_tiles", total)
            summary[ds_path] = entry
    return summary


def _datasets_at(repo, commit_oid):
    from kart_tpu.core.structure import RepoStructure

    if commit_oid is None:
        return None
    try:
        return RepoStructure(repo, commit_oid).datasets
    except (KeyError, ValueError):
        return None


def _tree_oid(ds):
    """Feature-tree oid of a dataset, or None — the O(1) "did anything
    change" probe run before any sidecar is loaded."""
    if ds is None or ds.feature_tree is None:
        return None
    return ds.feature_tree.oid
