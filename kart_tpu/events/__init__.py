"""Live-update events: changed-block CDC from push to subscriber
(ISSUE 14; docs/EVENTS.md).

The push path (PR 8) lands writes and the tile/fleet paths (PR 9/12)
serve reads; this package connects them. When a ref update lands, the
per-repo :class:`EventEmitter`:

1. **books** a sequence number for the transition inside the push's
   critical section (cheap: one counter bump — the CDC never runs under
   the push locks), and the push response carries it (``event_seq``) so a
   read-your-writes client can wait on a *sequence* instead of a tip
   containment walk;
2. computes the **exact dirty-tile set** old-tip → new-tip from sidecar
   columns alone (:mod:`kart_tpu.events.cdc` — no blob reads);
3. **pre-warms** the commit-addressed tile cache for those tiles
   (:mod:`kart_tpu.events.warm`) while the old tip keeps serving — tile
   keys pin commits, so nothing is dropped and nothing goes stale;
4. only then **announces**: appends the event to the persistent bounded
   log (:mod:`kart_tpu.events.log`) and wakes every long-poll watcher
   (``GET /api/v1/events?since=<seq>``, the stdio ``events`` op, and the
   fleet's :class:`~kart_tpu.fleet.sync.ReplicaSync` subscription).

Crash discipline mirrors the caches: booking state is in-memory only, the
log append is the single announce frame, and a crash anywhere between CAS
and announce leaves the tip un-announced — the reconcile pass (run at
emitter construction and on every watcher poll slice) compares the
announced tips against the actual refs and replays any missed emission,
which also makes cross-process pushes (an ssh ``serve-stdio`` landing next
to the HTTP server) visible to watchers within one poll slice.

``KART_SERVE_EVENTS=0`` disables the whole subsystem; only serving
processes ever construct an emitter (a plain ``kart push`` target books
nothing and pays no import).
"""

import logging
import os
import threading
import time
from collections import OrderedDict, deque

from kart_tpu import telemetry as tm
from kart_tpu.events.cdc import dirty_tiles
from kart_tpu.events.log import EventLog
from kart_tpu.events.warm import warm_dirty_tiles

L = logging.getLogger("kart_tpu.events")

#: how long a long-poll events request waits for news before answering
#: empty (the client immediately re-polls; bounded so shed-lane slots and
#: dead sockets turn over)
LONG_POLL_SECONDS = 25.0

#: the wait loop's re-check slice: cross-process announcements and
#: reconcile-detected pushes become visible within one slice
POLL_SLICE_SECONDS = 1.0


def events_enabled(environ=os.environ):
    """Is the live-update subsystem on (``KART_SERVE_EVENTS``; default
    yes, like tile serving)?"""
    return environ.get("KART_SERVE_EVENTS", "1") not in ("0", "false")


class EventEmitter:
    """One served repo's live-update pipeline: booking → CDC → warm →
    announce → fan-out, with a single background worker draining bookings
    in FIFO order (announcements therefore happen in booking order)."""

    def __init__(self, repo):
        self.repo = repo
        self.log = EventLog(repo.gitdir)
        self._cond = threading.Condition()
        self._queue = deque()
        self._pending_refs = {}  # ref -> queued/in-flight booking count
        self._booked_tips = self.log.tips()
        self._next_seq = self.log.head() + 1
        self._watchers = 0
        self._last_fanout = None
        self._last_warm = None
        self._stopped = False
        self._worker = None
        if not self.log.head() and not self._booked_tips:
            # first boot over a repo with history: adopt the current tips
            # silently — subscribers care about transitions from now on,
            # not a synthetic replay of every preexisting branch
            current = self._current_tips()
            if current:
                self._booked_tips = dict(current)
                self.log.adopt_tips(current)
        else:
            # restart: any tip that moved while no emitter was running
            # (crash between CAS and announce, or a push landed by a
            # process without an emitter) is a missed emission — replay it
            self.reconcile()

    # -- lifecycle -----------------------------------------------------------

    def _ensure_worker_locked(self):
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._run, name="kart-events-worker", daemon=True
            )
            self._worker.start()

    def stop(self, timeout=5.0, *, drain=True):
        """Stop the worker. ``drain=False`` additionally discards queued
        bookings — the path for an emitter that lost the registry race or
        was evicted: its pending replays belong to the surviving
        instance, and announcing them here would duplicate sequences."""
        with self._cond:
            self._stopped = True
            if not drain:
                self._queue.clear()
                self._pending_refs.clear()
            worker = self._worker
            self._cond.notify_all()
        if worker is not None:
            worker.join(timeout)

    # -- booking (the push-side hook) ----------------------------------------

    def _current_tips(self):
        return dict(self.repo.refs.iter_refs("refs/"))

    def book_many(self, changes):
        """Book one event per ref transition; -> the highest booked
        sequence (what the push response reports), or None for an empty
        change list. Runs inside the push critical section, so it must
        stay a counter bump + queue append — the CDC/warm/announce all
        happen on the worker thread."""
        last = None
        with self._cond:
            for ref, old, new in changes:
                if old == new:
                    continue
                last = self._book_locked(ref, old, new)
        return last

    def _book_locked(self, ref, old, new, replay=False):
        seq = self._next_seq
        self._next_seq += 1
        self._queue.append(
            {
                "seq": seq,
                "ref": ref,
                "old": old,
                "new": new,
                "cas_ts": time.time(),
                "replay": replay,
            }
        )
        if new:
            self._booked_tips[ref] = new
        else:
            self._booked_tips.pop(ref, None)
        self._pending_refs[ref] = self._pending_refs.get(ref, 0) + 1
        tm.gauge_set("events.queue_depth", len(self._queue))
        if not self._stopped:
            self._ensure_worker_locked()
        self._cond.notify_all()
        return seq

    def reconcile(self):
        """Book transitions for every ref whose current value differs from
        the booked tips — the missed-emission replay (server restart after
        a crash, cross-process pushes). -> bookings made.

        The on-disk log is re-read first and its announced state folded
        into the booking state (refs without a pending booking adopt the
        disk tips; the sequence counter jumps past the disk head), so a
        second emitter on the same gitdir — an ssh ``serve-stdio`` events
        op next to the HTTP server — converges on the other's
        announcements instead of double-booking them with colliding
        sequences. Truly simultaneous reconciles in two processes can
        still both book (announcement is not cross-process atomic); the
        flocked append keeps the log intact and a duplicated invalidation
        is idempotent for every subscriber."""
        self.log.refresh_from_disk()
        booked = 0
        with self._cond:
            disk_head = self.log.head()
            if disk_head >= self._next_seq:
                self._next_seq = disk_head + 1
            announced = self.log.tips()
            for ref in set(self._booked_tips) | set(announced):
                if not self._pending_refs.get(ref):
                    # no in-flight booking of ours: the announced state —
                    # possibly another process's — is the truth
                    if ref in announced:
                        self._booked_tips[ref] = announced[ref]
                    else:
                        self._booked_tips.pop(ref, None)
            current = self._current_tips()
            for ref, oid in sorted(current.items()):
                if self._booked_tips.get(ref) != oid:
                    self._book_locked(
                        ref, self._booked_tips.get(ref), oid, replay=True
                    )
                    booked += 1
            for ref in sorted(
                r for r in self._booked_tips if r not in current
            ):
                self._book_locked(
                    ref, self._booked_tips[ref], None, replay=True
                )
                booked += 1
        if booked:
            tm.incr("events.replays", booked)
        return booked

    # -- the worker: CDC → warm → announce -----------------------------------

    def _run(self):
        while True:
            with self._cond:
                while not self._queue and not self._stopped:
                    self._cond.wait(60.0)
                if self._stopped and not self._queue:
                    return
                booking = self._queue.popleft()
                tm.gauge_set("events.queue_depth", len(self._queue))
            self._process(booking)

    def _process(self, booking):
        ref, old, new = booking["ref"], booking["old"], booking["new"]
        try:
            summary = (
                dirty_tiles(self.repo, old, new) if new is not None else None
            )
        except Exception as e:
            self._emission_failed(booking, "cdc", e)
            return
        warm_stats = None
        if new is not None:
            try:
                warm_stats = warm_dirty_tiles(self.repo, new, summary)
            except Exception as e:
                # warming is best-effort: the announcement must not be
                # lost to a warm crash (tests/test_faults.py events.warm)
                warm_stats = {"tiles": 0, "already_hot": 0, "errors": 1,
                              "seconds": 0.0}
                tm.incr("events.warm_errors")
                L.warning("tile warm for %s failed: %s", ref, e)
        event = {
            "seq": booking["seq"],
            "ref": ref,
            "old": old,
            "new": new,
            "cas_ts": round(booking["cas_ts"], 6),
            "ts": round(time.time(), 6),
            "dirty": summary,
            "warm": warm_stats,
        }
        if booking.get("replay"):
            event["replay"] = True
        try:
            self.log.append_event(event)
        except Exception as e:
            self._emission_failed(booking, "announce", e)
            return
        with self._cond:
            self._unpend_locked(ref)
            self._last_warm = warm_stats
            self._cond.notify_all()
        tm.observe(
            "events.announce_seconds", max(0.0, event["ts"] - booking["cas_ts"])
        )

    def _unpend_locked(self, ref):
        n = self._pending_refs.get(ref, 0) - 1
        if n > 0:
            self._pending_refs[ref] = n
        else:
            self._pending_refs.pop(ref, None)

    def _emission_failed(self, booking, frame, exc):
        ref = booking["ref"]
        tm.incr("events.emit_errors")
        L.warning(
            "event emission (%s) for %s seq %d failed: %s — the tip stays "
            "un-announced; reconcile will replay it",
            frame, ref, booking["seq"], exc,
        )
        with self._cond:
            self._unpend_locked(ref)
            if not self._pending_refs.get(ref):
                # no later booking supersedes this ref: reset the booked
                # tip to what was actually announced, so the reconcile
                # pass (next watcher poll, or the restarted server's
                # constructor) sees the gap and re-books it
                announced = self.log.tips().get(ref)
                if announced is None:
                    self._booked_tips.pop(ref, None)
                else:
                    self._booked_tips[ref] = announced
            self._cond.notify_all()

    # -- the subscription surface --------------------------------------------

    def events_since(self, since):
        """-> (events, head, reset) — the non-blocking read."""
        return self.log.since(since)

    def wait_events(self, since, timeout=LONG_POLL_SECONDS):
        """Long-poll: block until events with seq > ``since`` exist (or
        the timeout passes); -> (events, head, reset). Each poll slice
        re-reads the log file and reconciles against the refs, so
        announcements from other processes and pushes landed without an
        emitter both surface within one slice."""
        deadline = time.monotonic() + max(0.0, timeout)
        t_enter = time.time()
        while True:
            self.reconcile()  # re-reads the disk log first
            events, head, reset = self.log.since(since)
            if events or reset is not None:
                now = time.time()
                for event in events:
                    if event.get("ts", 0) >= t_enter and "cas_ts" in event:
                        # fresh fan-out: ref-CAS to watcher delivery
                        latency = max(0.0, now - event["cas_ts"])
                        tm.observe("events.fanout_seconds", latency)
                        self._last_fanout = latency
                return events, head, reset
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return [], head, None
            with self._cond:
                self._cond.wait(min(POLL_SLICE_SECONDS, remaining))

    class _Watching:
        def __init__(self, emitter):
            self._emitter = emitter

        def __enter__(self):
            with self._emitter._cond:
                self._emitter._watchers += 1
                tm.gauge_set("events.watchers", self._emitter._watchers)
            return self

        def __exit__(self, *exc):
            with self._emitter._cond:
                self._emitter._watchers -= 1
                tm.gauge_set("events.watchers", self._emitter._watchers)
            return False

    def watching(self):
        """Context manager counting a connected watcher (the
        ``events.watchers`` gauge + the stats document)."""
        return EventEmitter._Watching(self)

    # -- serving-side integration --------------------------------------------

    def tile_pin(self, ref):
        """The warm-then-announce read side: while a booking for ``ref``
        is pending (CDC/warm in flight), branch-name tile requests resolve
        to the *announced* tip — the old commit keeps serving, hot, until
        the warmer finishes and the announcement advances the tip.
        -> the announced commit oid to pin to, or None (no pin: resolve
        normally)."""
        with self._cond:
            if not self._pending_refs:
                return None
            candidates = (ref, f"refs/heads/{ref}", f"refs/tags/{ref}")
            pending = next(
                (c for c in candidates if self._pending_refs.get(c)), None
            )
        if pending is None:
            return None
        return self.log.tips().get(pending)

    def status_dict(self):
        """The ``events`` block of ``/api/v1/stats?format=json`` (what
        ``kart top`` renders)."""
        with self._cond:
            watchers = self._watchers
            queue_depth = len(self._queue)
            pending = sum(self._pending_refs.values())
            last_fanout = self._last_fanout
            last_warm = self._last_warm
        return {
            "watchers": watchers,
            "head_seq": self.log.head(),
            "oldest_seq": self.log.oldest(),
            "queue_depth": queue_depth,
            "pending_refs": pending,
            "last_fanout_seconds": (
                round(last_fanout, 6) if last_fanout is not None else None
            ),
            "last_warm": last_warm,
        }


# ---------------------------------------------------------------------------
# the per-process emitter registry (bounded, like the cache registries;
# an evicted emitter's worker drains and parks — correctness lives in the
# on-disk log + reconcile, never in which instance happened to be cached)
# ---------------------------------------------------------------------------

_EMITTERS = OrderedDict()
_EMITTERS_MAX = 64
_emitters_lock = threading.Lock()


def emitter_for(repo):
    """Get-or-create the emitter serving ``repo`` (serving processes
    only: ``make_server`` and the stdio ``events`` op call this; a plain
    push path never creates one)."""
    key = os.path.realpath(repo.gitdir)
    with _emitters_lock:
        emitter = _EMITTERS.get(key)
        if emitter is not None:
            _EMITTERS.move_to_end(key)
            return emitter
    # construction replays the log + reconciles — do it outside the
    # registry lock, then publish (two racing creators: one instance wins,
    # the loser's constructor was idempotent reads + booked replays that
    # the winner's reconcile would also have made)
    built = EventEmitter(repo)
    evicted = []
    with _emitters_lock:
        emitter = _EMITTERS.get(key)
        if emitter is None:
            emitter = _EMITTERS[key] = built
        _EMITTERS.move_to_end(key)
        while len(_EMITTERS) > _EMITTERS_MAX:
            evicted.append(_EMITTERS.popitem(last=False)[1])
    if emitter is not built:
        # the registry race's loser: its booked replays belong to the
        # winner (whose own reconcile makes them), so discard, not drain
        built.stop(timeout=0.5, drain=False)
    for old in evicted:
        # an evicted emitter must not keep a worker thread (and the repo
        # it pins) alive forever; its on-disk log state survives and a
        # re-created emitter reconciles from it
        old.stop(timeout=0.5)
    return emitter


def active_emitter(gitdir):
    """The already-created emitter for ``gitdir``, or None — the push-side
    hook must never *create* one (a non-serving process books nothing)."""
    with _emitters_lock:
        return _EMITTERS.get(os.path.realpath(gitdir))


def notify_ref_updates(repo, changes):
    """The ref-update hook (:data:`kart_tpu.analysis.registry.EVENT_EMIT_HOOK`),
    called from ``_apply_validated_updates``: book one event per landed
    transition. ``changes``: ``[(ref, old_oid|None, new_oid|None)]``.
    -> the highest booked sequence, or None (events off / not serving)."""
    if not changes or not events_enabled():
        return None
    emitter = active_emitter(repo.gitdir)
    if emitter is None:
        return None
    return emitter.book_many(changes)


def drop_emitters(gitdir=None):
    """Tests: forget cached emitters (state persists in the log files)."""
    with _emitters_lock:
        if gitdir is None:
            doomed = list(_EMITTERS.values())
            _EMITTERS.clear()
        else:
            real = os.path.realpath(gitdir)
            doomed = [
                _EMITTERS.pop(k) for k in list(_EMITTERS) if k == real
            ]
    for emitter in doomed:
        emitter.stop(timeout=0.5)
