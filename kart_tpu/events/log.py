"""The per-repo live-update event log (docs/EVENTS.md §3).

An ordered, bounded, *persistent* record of announced ref transitions:
one JSON line per event under ``<gitdir>/events/log.jsonl``, monotonic
``seq`` numbers, plus a ``tips.json`` checkpoint of the ref tips the log
has announced so far. Three properties the protocol leans on:

* **resume-by-sequence** — ``since(seq)`` returns exactly the announced
  events with a larger sequence number, so a disconnected watcher replays
  what it missed; a watcher older than the retention window is told to
  reset (``oldest``) instead of being silently fed a gap.
* **crash atomicity** — an event is announced by a single buffered
  ``write()`` of its line + flush; a torn trailing line (the classic
  kill-mid-append) is detected and ignored on load, so the tip it carried
  is simply *not announced* and the emitter's reconcile pass re-emits it
  (tests/test_faults.py: the ``events.emit`` frame-2 kill).
* **derived tips** — the announced-tips map is the checkpoint plus a
  replay of every logged event after it, so the checkpoint write (a
  separate atomic replace) can lag or be lost without the log lying about
  what was announced.

Writers (append + rotation, as one unit) serialise across processes on
an ``fcntl`` lock file (``.events-lock``, the ``.push-lock`` idiom) so a
second process landing a push against the same gitdir (an ssh
``serve-stdio`` push next to the HTTP server) can neither interleave
half-lines nor have its append erased by a concurrent rotation; sequence
coordination across processes stays with the emitter's reconcile pass,
which re-reads the file before trusting its in-memory head.
"""

import json
import logging
import os
import threading
from collections import deque
from contextlib import contextmanager

from kart_tpu import faults
from kart_tpu import telemetry as tm

L = logging.getLogger("kart_tpu.events.log")

#: default number of events retained (``KART_EVENTS_LOG_SIZE`` overrides);
#: the on-disk file is rewritten down to this size when it doubles it
DEFAULT_LOG_SIZE = 1024

LOG_SUBDIR = "events"
LOG_FILE = "log.jsonl"
TIPS_FILE = "tips.json"


def log_size(environ=os.environ):
    try:
        value = int(environ.get("KART_EVENTS_LOG_SIZE", ""))
    except (TypeError, ValueError):
        return DEFAULT_LOG_SIZE
    return value if value > 0 else DEFAULT_LOG_SIZE


def _parse_lines(raw):
    """Log file bytes -> list of event dicts; a torn trailing line (no
    newline, or unparseable) is dropped — that event was never fully
    announced."""
    events = []
    lines = raw.split(b"\n")
    # a complete file ends with a newline: the final split element is
    # empty; anything else is the torn tail of a killed append
    for line in lines[:-1]:
        if not line.strip():
            continue
        try:
            event = json.loads(line.decode())
        except (ValueError, UnicodeDecodeError):
            L.warning("events log: dropping corrupt line (%d bytes)", len(line))
            continue
        if isinstance(event, dict) and isinstance(event.get("seq"), int):
            events.append(event)
    return events


class EventLog:
    """One repo's announced-event history, memory-fronted and disk-backed.

    ``append`` is the announce frame: the event becomes visible to
    ``since``/``head`` only once its line is durably in the file (and the
    ``events.emit`` frame-2 fault fires *before* the write, so an injected
    crash announces nothing)."""

    def __init__(self, gitdir, max_events=None):
        self.gitdir = gitdir
        self.dir = os.path.join(gitdir, LOG_SUBDIR)
        self.path = os.path.join(self.dir, LOG_FILE)
        self.tips_path = os.path.join(self.dir, TIPS_FILE)
        self.max_events = max_events if max_events else log_size()
        self._lock = threading.Lock()
        events, tips = self._load()
        self._events = deque(events, maxlen=self.max_events)
        self._tips = tips
        self._seen_size = self._file_size()

    def _file_size(self):
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    # -- load ----------------------------------------------------------------

    def _load(self):
        try:
            with open(self.path, "rb") as f:
                events = _parse_lines(f.read())
        except OSError:
            events = []
        checkpoint_seq, tips = 0, {}
        try:
            with open(self.tips_path) as f:
                doc = json.load(f)
            checkpoint_seq = int(doc.get("seq", 0))
            tips = dict(doc.get("tips", {}))
        except (OSError, ValueError, TypeError):
            # no checkpoint (first boot) or corrupt: rebuild from the log
            # alone — worst case the emitter replays a little history
            checkpoint_seq, tips = 0, {}
        for event in events:
            if event["seq"] <= checkpoint_seq:
                continue
            ref = event.get("ref")
            if not ref:
                continue
            if event.get("new"):
                tips[ref] = event["new"]
            else:
                tips.pop(ref, None)
        return events[-self.max_events:], tips

    # -- reads ---------------------------------------------------------------

    def head(self):
        with self._lock:
            return self._events[-1]["seq"] if self._events else 0

    def oldest(self):
        with self._lock:
            return self._events[0]["seq"] if self._events else 0

    def tips(self):
        with self._lock:
            return dict(self._tips)

    def since(self, seq):
        """-> (events with ``seq`` strictly greater, head, reset_marker).
        ``reset_marker`` is the oldest retained sequence when the caller's
        position predates the retention window (it missed events it can
        never replay — re-sync from scratch), else None."""
        with self._lock:
            head = self._events[-1]["seq"] if self._events else 0
            oldest = self._events[0]["seq"] if self._events else 0
            reset = oldest - 1 if (self._events and seq < oldest - 1) else None
            out = [e for e in self._events if e["seq"] > seq]
            return out, head, reset

    # -- the announce frame --------------------------------------------------

    def append_event(self, event):
        """Announce one event: write its line, absorb it into memory +
        tips, rotate the file when it has doubled the retention bound.
        The append AND the rotation run under one cross-process write
        lock (``.events-lock``, the ``.push-lock`` idiom) — a rotation
        that merely flocked the data file could read, lose the lock, and
        ``os.replace`` over a line another process appended in between,
        silently erasing an announced event."""
        line = (json.dumps(event, sort_keys=True) + "\n").encode()
        # frame 2: the log append — an injected crash here announces
        # nothing (the line is never written; the emitter's reconcile
        # replays the emission on restart). Fired OUTSIDE the log lock:
        # nothing that can raise or block belongs inside it.
        faults.fire("events.emit")
        with self._lock:
            os.makedirs(self.dir, exist_ok=True)
            with self._write_lock():
                with open(self.path, "ab") as f:
                    f.write(line)
                    f.flush()
                self._events.append(event)
                ref = event.get("ref")
                if ref:
                    if event.get("new"):
                        self._tips[ref] = event["new"]
                    else:
                        self._tips.pop(ref, None)
                self._write_tips_locked(event["seq"])
                self._maybe_rotate_locked()
            self._seen_size = self._file_size()
        tm.gauge_set("events.log_head", event["seq"])
        tm.incr("events.emitted")

    def adopt_tips(self, tips):
        """First-boot adoption: checkpoint the current refs at sequence 0
        without emitting events — subscribers care about transitions from
        now on, not a synthetic replay of preexisting branches."""
        with self._lock:
            self._tips = dict(tips)
            os.makedirs(self.dir, exist_ok=True)
            self._write_tips_locked(0)

    @contextmanager
    def _write_lock(self):
        """The cross-process writer lock (an ssh ``serve-stdio`` push's
        emitter next to the HTTP server's): held for append + rotation as
        one unit. Best-effort on non-POSIX, like ``push_file_lock``."""
        with open(os.path.join(self.dir, ".events-lock"), "w") as lock:
            try:
                import fcntl

                fcntl.flock(lock, fcntl.LOCK_EX)
            except ImportError:
                pass
            yield

    def _write_tips_locked(self, seq):
        tmp = self.tips_path + f".tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump({"seq": seq, "tips": self._tips}, f)
            os.replace(tmp, self.tips_path)
        except OSError as e:
            # the checkpoint is an optimisation (tips replay from the log);
            # a full disk here must not fail the announce itself
            L.warning("events log: tips checkpoint failed: %s", e)

    def _maybe_rotate_locked(self):
        """Rewrite the file down to the retention bound. Caller holds
        both the instance lock and the cross-process write lock — the
        read-modify-replace is atomic against every other writer."""
        try:
            if os.path.getsize(self.path) < 256 * (2 * self.max_events):
                # cheap size gate: lines are a few hundred bytes; only
                # stat + compare on the common path
                return
            with open(self.path, "rb") as f:
                events = _parse_lines(f.read())
            if len(events) <= 2 * self.max_events:
                return
            keep = events[-self.max_events:]
            tmp = self.path + f".tmp{os.getpid()}"
            with open(tmp, "wb") as f:
                for event in keep:
                    f.write((json.dumps(event, sort_keys=True) + "\n").encode())
            os.replace(tmp, self.path)
        except OSError as e:
            L.warning("events log: rotation failed: %s", e)

    def refresh_from_disk(self):
        """Re-read the file (another process may have appended — the ssh
        push case); -> the new head. Memory state is rebuilt from disk so
        cross-process announcements become visible to this server's
        watchers on the next poll slice."""
        with self._lock:
            size = self._file_size()
            if size == self._seen_size:
                # nobody appended since we last looked: skip the re-read
                # (this runs once per watcher poll slice)
                return self._events[-1]["seq"] if self._events else 0
            self._seen_size = size
            disk_head = 0
            try:
                with open(self.path, "rb") as f:
                    raw = f.read()
            except OSError:
                return self._events[-1]["seq"] if self._events else 0
            events = _parse_lines(raw)
            if events:
                disk_head = events[-1]["seq"]
            mem_head = self._events[-1]["seq"] if self._events else 0
            if disk_head > mem_head:
                self._events = deque(
                    events[-self.max_events:], maxlen=self.max_events
                )
                for event in events:
                    if event["seq"] <= mem_head:
                        continue
                    ref = event.get("ref")
                    if not ref:
                        continue
                    if event.get("new"):
                        self._tips[ref] = event["new"]
                    else:
                        self._tips.pop(ref, None)
            return max(disk_head, mem_head)
