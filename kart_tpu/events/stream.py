"""Client side of the event subscription surface (docs/EVENTS.md §5).

One small long-poll client over ``GET /api/v1/events``: ``kart watch``
streams its JSON lines from it, and the fleet's
:class:`~kart_tpu.fleet.sync.ReplicaSync` subscription uses it to learn
about pushes in fan-out latency instead of a poll period. Resume is by
sequence number: every response carries ``head``, the next request sends
``since=<head>``, and a reconnect after any failure replays exactly the
missed events (the server log is bounded — a ``reset`` marker means the
watcher slept past the retention window and must re-sync from scratch).
"""

import json
import os
import time
from urllib.error import HTTPError
from urllib.parse import urlencode
from urllib.request import Request, urlopen

#: seconds the client asks the server to hold a long poll open; kept
#: under the server's own LONG_POLL_SECONDS ceiling
DEFAULT_POLL_SECONDS = 20.0

#: default overall silence budget for `kart watch` (``KART_WATCH_TIMEOUT``;
#: 0 = watch forever)
DEFAULT_WATCH_TIMEOUT = 0.0


class EventStreamUnsupported(Exception):
    """The server has no events endpoint (an old primary, or
    ``KART_SERVE_EVENTS=0``) — callers fall back to polling."""


def watch_timeout(environ=os.environ):
    try:
        value = float(environ.get("KART_WATCH_TIMEOUT", ""))
    except (TypeError, ValueError):
        return DEFAULT_WATCH_TIMEOUT
    return value if value >= 0 else DEFAULT_WATCH_TIMEOUT


def fetch_events(base_url, since=None, *, poll_seconds=0.0, timeout=None):
    """One ``GET /api/v1/events`` round-trip; -> the response document
    (``{"events": [...], "head": N, ...}``). ``since=None`` asks for the
    current head without waiting (the subscribe handshake).
    Raises :class:`EventStreamUnsupported` on 404/501, and lets other
    transport failures propagate (callers pace their own retries)."""
    from kart_tpu.transport.http import API, http_timeout

    params = {}
    if since is not None:
        params["since"] = str(int(since))
    if poll_seconds:
        params["timeout"] = f"{poll_seconds:.3f}"
    query = f"?{urlencode(params)}" if params else ""
    url = f"{base_url.rstrip('/')}{API}/events{query}"
    if timeout is None:
        # the socket budget must outlive the server-held poll window
        timeout = max(http_timeout(), poll_seconds + 10.0)
    try:
        with urlopen(Request(url), timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except HTTPError as e:
        with e:
            detail = e.read()[:200]
        if e.code in (404, 501):
            raise EventStreamUnsupported(
                f"{base_url} has no events endpoint (HTTP {e.code})"
            )
        raise OSError(f"events poll failed: HTTP {e.code} {detail!r}")


def iter_events(base_url, *, since=None, poll_seconds=DEFAULT_POLL_SECONDS,
                idle_timeout=None, retry_seconds=1.0, max_retries=30):
    """Yield event dicts from ``base_url`` forever (or until
    ``idle_timeout`` seconds pass with no event; 0/None = forever).

    The subscribe handshake: with ``since=None`` the first request learns
    the current head and only *transitions from now on* stream. Transient
    transport failures reconnect with the same sequence position (paced by
    ``retry_seconds``); :class:`EventStreamUnsupported` propagates
    immediately so callers can fall back to polling."""
    if since is None:
        since = int(fetch_events(base_url).get("head", 0))
    failures = 0
    last_event = time.monotonic()
    while True:
        wait = poll_seconds
        if idle_timeout:
            remaining = idle_timeout - (time.monotonic() - last_event)
            if remaining <= 0:
                return
            # never hold a poll past the idle budget — the caller asked
            # to give up after that much silence
            wait = max(0.0, min(poll_seconds, remaining))
        try:
            doc = fetch_events(base_url, since, poll_seconds=wait)
        except EventStreamUnsupported:
            raise
        except OSError:
            failures += 1
            if failures > max_retries:
                raise
            time.sleep(retry_seconds)
            continue
        failures = 0
        for event in doc.get("events", ()):
            last_event = time.monotonic()
            yield event
        # a reset marker (slept past the retention window) needs no
        # special handling here: the replayed events start at the oldest
        # retained sequence and head advances past it — the caller sees
        # the seq gap in the yielded events (a replica re-syncs refs
        # from the advertisement regardless)
        since = max(since, int(doc.get("head", since)))
        if idle_timeout and time.monotonic() - last_event > idle_timeout:
            return
