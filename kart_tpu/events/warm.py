"""Pre-warm the commit-addressed tile cache for an event's dirty tiles
(docs/EVENTS.md §4).

The warm-then-announce protocol: after a push lands, the server keeps
serving the *old* tip's tiles (they are commit-addressed and immutable, so
nothing needs to be dropped) while this module re-encodes the dirty tiles
of the *new* tip into the tile cache — and only then is the event
announced and the new tip fanned out to subscribers. A viewer that
switches commits on the announcement therefore finds every invalidated
tile already hot: zero cold-tile storms on hot layers.

Warming is strictly best-effort and budget-bounded
(``KART_EVENTS_WARM_BUDGET`` tiles per event): an oversized dirty set
warms shallow zooms first (the tiles most viewers are looking at), and a
failed warm — missing blobs on a partial store, an over-ceiling tile, an
injected ``events.warm`` fault — is counted and skipped, never allowed to
block or lose the announcement itself.
"""

import logging
import os
import time

from kart_tpu import faults
from kart_tpu import telemetry as tm

L = logging.getLogger("kart_tpu.events.warm")

#: default tiles re-encoded per event (``KART_EVENTS_WARM_BUDGET``
#: overrides; 0 disables warming entirely)
DEFAULT_WARM_BUDGET = 256


#: the blob-free fallback layer set (see :func:`warm_layers`)
WARM_LAYERS = ("bin",)


def warm_layers():
    """The layer set warmed per dirty tile: the server's *negotiated
    default* (``KART_TILE_ENCODING``-aware — warming cache keys nobody's
    default request computes would make every warm fill a miss), filtered
    to the blob-free layers. ``geojson``/``props`` stay lazily encoded on
    first request (they need every feature blob in the tile, which a
    just-pushed partial store may not hold); when the default is entirely
    blob-needing, warm the columnar ``bin`` layer (BENCH_r10's serving
    hot path)."""
    from kart_tpu.tiles.encode import default_layers

    blob_free = tuple(
        name for name in default_layers() if name not in ("geojson", "props")
    )
    return blob_free or WARM_LAYERS


def warm_budget(environ=os.environ):
    try:
        value = int(environ.get("KART_EVENTS_WARM_BUDGET", ""))
    except (TypeError, ValueError):
        return DEFAULT_WARM_BUDGET
    return value if value >= 0 else DEFAULT_WARM_BUDGET


def iter_warm_tiles(summary, budget):
    """Yield ``(ds_path, z, x, y)`` warm targets from a CDC summary,
    shallow zooms first across datasets, bounded by ``budget``. Truncated
    / non-spatial entries contribute nothing (there is no exact tile list
    to warm — those subscribers re-fetch lazily)."""
    if budget <= 0:
        return
    emitted = 0
    by_zoom = []
    for ds_path, entry in sorted((summary or {}).items()):
        tiles = entry.get("tiles")
        if not tiles:
            continue
        for z_str, addrs in tiles.items():
            by_zoom.append((int(z_str), ds_path, addrs))
    by_zoom.sort(key=lambda t: t[0])
    for z, ds_path, addrs in by_zoom:
        for x, y in addrs:
            yield ds_path, z, int(x), int(y)
            emitted += 1
            if emitted >= budget:
                return


def warm_dirty_tiles(repo, new_oid, summary, *, budget=None):
    """Encode the dirty tiles of ``new_oid`` into the tile cache.

    -> stats dict ``{"tiles", "already_hot", "errors", "seconds"}``
    (``tiles`` = fresh fills; ``already_hot`` = cache hits — another
    request got there first). The ``events.warm`` fault point fires once
    per warm pass, before any tile is encoded: an injected crash abandons
    the remaining warm but must not poison the cache or lose the
    announcement (the caller catches and announces anyway —
    tests/test_faults.py)."""
    from kart_tpu import tiles

    stats = {"tiles": 0, "already_hot": 0, "errors": 0, "seconds": 0.0}
    if new_oid is None or not summary:
        return stats
    budget = warm_budget() if budget is None else budget
    t0 = time.perf_counter()
    layers = warm_layers()
    with tm.span("events.warm", commit=new_oid[:12]):
        faults.fire("events.warm")
        for ds_path, z, x, y in iter_warm_tiles(summary, budget):
            try:
                _payload, _etag, cached = tiles.serve_tile(
                    repo, new_oid, ds_path, z, x, y, commit_oid=new_oid,
                    layers=layers,
                )
            except (tiles.TileSourceError, tiles.TileEncodeError) as e:
                # an unwarmable tile (over the feature ceiling, blobs not
                # local) falls back to a lazy cold encode on first request
                stats["errors"] += 1
                L.warning(
                    "tile warm %s %d/%d/%d at %s failed: %s",
                    ds_path, z, x, y, new_oid[:12], e,
                )
                continue
            if cached:
                stats["already_hot"] += 1
            else:
                stats["tiles"] += 1
    stats["seconds"] = round(time.perf_counter() - t0, 6)
    tm.incr("events.warm_tiles", stats["tiles"])
    if stats["errors"]:
        tm.incr("events.warm_errors", stats["errors"])
    tm.observe("events.warm_seconds", stats["seconds"])
    return stats
