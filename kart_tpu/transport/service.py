"""Transport-agnostic server-side operations.

The four verbs every kart_tpu transport speaks — ls-refs, fetch-pack,
fetch-blobs, receive-pack — implemented once over a repo, shared by the HTTP
server (:mod:`kart_tpu.transport.http`) and the stdio/ssh server
(:mod:`kart_tpu.transport.stdio`). The reference gets the same sharing from
git itself: upload-pack/receive-pack behave identically whether invoked by
``git daemon``, ssh, or https (kart/cli.py:211-253).
"""

from kart_tpu.core.odb import ObjectMissing
from kart_tpu.core.refs import RefError, check_ref_format
from kart_tpu.transport.protocol import ObjectEnumerator


def ls_refs_info(repo):
    """The advertisement: branch/tag tips, HEAD branch, shallow set."""
    from kart_tpu.transport.remote import read_shallow

    heads = {
        ref[len("refs/heads/"):]: oid
        for ref, oid in repo.refs.iter_refs("refs/heads/")
    }
    tags = {
        ref[len("refs/tags/"):]: oid
        for ref, oid in repo.refs.iter_refs("refs/tags/")
    }
    kind, target = repo.refs.head_target()
    head_branch = (
        target[len("refs/heads/"):]
        if kind == "symbolic" and target.startswith("refs/heads/")
        else None
    )
    return {
        "heads": heads,
        "tags": tags,
        "head_branch": head_branch,
        "shallow": sorted(read_shallow(repo)),
    }


def make_fetch_enum(repo, req):
    """fetch-pack request dict -> (ObjectEnumerator, header_fn). The header
    callable reads the enumerator's counters, so evaluate it only after the
    pack drain."""
    from kart_tpu.transport.remote import read_shallow
    from kart_tpu.transport.http import have_closure

    blob_filter = None
    if req.get("filter"):
        from kart_tpu.spatial_filter import blob_filter_for_spec

        blob_filter = blob_filter_for_spec(repo, req["filter"])
    has = None
    if req.get("haves"):
        closure = have_closure(repo.odb, req["haves"], req.get("have_shallow", ()))
        has = closure.__contains__
    enum = ObjectEnumerator(
        repo.odb,
        req.get("wants", []),
        has=has,
        depth=req.get("depth"),
        blob_filter=blob_filter,
        sender_shallow=read_shallow(repo),
    )

    def header():
        return {
            "shallow_boundary": sorted(enum.shallow_boundary),
            "object_count": enum.object_count,
            "omitted_blob_count": enum.omitted_blob_count,
        }

    return enum, header


def collect_blobs(repo, oids):
    """fetch-blobs (promisor backfill): -> (header, [(type, content)])."""
    missing = []
    objects = []
    for oid in oids:
        try:
            objects.append(repo.odb.read_raw(oid))
        except ObjectMissing:
            missing.append(oid)
    return {"missing": missing}, objects


def current_branch_ref(repo):
    kind, target = repo.refs.head_target()
    return target if kind == "symbolic" else None


def locked_ref_updates(repo, header):
    """apply_ref_updates under a cross-process gitdir file lock: every ssh
    push spawns its own serve-stdio process, so an in-process lock can't
    serialise the compare-and-swap (two concurrent pushes would both pass
    the CAS check and one would be silently lost). The HTTP server holds
    this too, so mixed http+ssh pushes against one repo stay safe."""
    import os

    lock_path = os.path.join(repo.gitdir, ".push-lock")
    with open(lock_path, "w") as lock:
        try:
            import fcntl

            fcntl.flock(lock, fcntl.LOCK_EX)
        except ImportError:  # non-POSIX: best effort
            pass
        return apply_ref_updates(repo, header)


def apply_ref_updates(repo, header):
    """CAS-validate then apply a receive-pack's ref updates (the pack must
    already be drained into the odb). All updates are validated before any
    is applied, so a rejected request leaves no ref moved. The caller holds
    whatever lock serialises concurrent pushes.

    -> ("ok", {ref: oid|None}) | ("conflict", msg) | ("bad", msg)."""
    from kart_tpu.transport.remote import _update_shallow

    deny_current = (
        repo.workdir is not None
        and (repo.config.get("receive.denyCurrentBranch") or "refuse").lower()
        not in ("ignore", "false")
    )

    updates = header.get("updates", [])
    for upd in updates:
        ref, old, new = upd["ref"], upd.get("old"), upd.get("new")
        # wire-supplied names must be real refs — git's receive-pack rejects
        # non-refs/ names via check_refname_format; without this a push with
        # ref='config' or 'HEAD' would overwrite arbitrary gitdir files.
        try:
            check_ref_format(ref, require_refs_prefix=True)
        except RefError as e:
            return "bad", str(e)
        if deny_current and ref == current_branch_ref(repo):
            return (
                "conflict",
                f"Refusing to update checked-out branch {ref} (the server's "
                f"working copy would go out of sync). Serve a bare repo, or "
                f"set receive.denyCurrentBranch=ignore there.",
            )
        current = repo.refs.get(ref)
        if not upd.get("force") and current != old:
            return (
                "conflict",
                f"Ref {ref} moved (expected {old}, is {current}); "
                f"fetch first or use --force",
            )
        if new is not None and not repo.odb.contains(new):
            return "bad", f"Push incomplete: {new} not received"

    updated = {}
    for upd in updates:
        ref, new = upd["ref"], upd.get("new")
        if new is None:
            if repo.refs.get(ref) is not None:
                repo.refs.delete(ref)
            updated[ref] = None
        else:
            repo.refs.set(ref, new, log_message="push")
            updated[ref] = new
    if header.get("shallow"):
        _update_shallow(repo, header["shallow"])
    return "ok", updated
