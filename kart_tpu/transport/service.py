"""Transport-agnostic server-side operations.

The four verbs every kart_tpu transport speaks — ls-refs, fetch-pack,
fetch-blobs, receive-pack — implemented once over a repo, shared by the HTTP
server (:mod:`kart_tpu.transport.http`) and the stdio/ssh server
(:mod:`kart_tpu.transport.stdio`). The reference gets the same sharing from
git itself: upload-pack/receive-pack behave identically whether invoked by
``git daemon``, ssh, or https (kart/cli.py:211-253).

Receive-pack is *quarantined* (the analog of git's tmp_objdir): the pushed
pack drains into a temporary objects dir that borrows the main store via
alternates, and objects migrate into the live store only after the pack
checksum and every ref-update precondition pass — a failed, torn or
rejected push leaves the served store byte-identical.

Contended pushes are *auto-rebased server-side* (docs/SERVING.md §6): a
receive-pack that passes its checksum but loses the ref CAS — a contending
writer moved the tip first — is three-way merged against the new tip by the
merge-index classifier, still inside the quarantine, and re-validated under
the push locks; real conflicts reject with a structured report the client
renders exactly like a local ``kart merge`` conflict (and never blindly
retries). K contending writers are serialised through a per-ref FIFO merge
queue instead of convoying on the push lock.
"""

import hashlib
import io
import json
import os
import shutil
import tempfile
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager, nullcontext

from kart_tpu import faults
from kart_tpu import telemetry as tm
from kart_tpu.core.odb import ObjectMissing
from kart_tpu.core.refs import RefError, check_ref_format
from kart_tpu.core.repo import KartRepo
from kart_tpu.core.singleflight import SingleFlightLRU
from kart_tpu.transport.protocol import ObjectEnumerator, Rejection

#: subdirectory of <gitdir>/objects holding in-flight push quarantines
QUARANTINE_SUBDIR = "quarantine"

#: how many times a contended push's CAS is re-validated (each failed
#: re-check costing one server-side rebase onto the newest tip) before the
#: server gives up and sheds the push back to the paced-retry lane
#: (``KART_SERVE_REBASE_ATTEMPTS`` overrides)
DEFAULT_REBASE_ATTEMPTS = 3

#: per-ref merge-queue depth bound: more than this many writers waiting on
#: one ref sheds the newcomer with 429 + Retry-After instead of growing the
#: line without bound (``KART_SERVE_MERGE_QUEUE`` overrides; 0 = unbounded)
DEFAULT_MERGE_QUEUE_DEPTH = 32

#: a writer queued behind a wedged merge-queue holder stops waiting after
#: this long and sheds as busy — the line must never wedge harder than the
#: push it is ordering
MERGE_QUEUE_TIMEOUT = 600.0

#: default byte budget for the per-repo pack-enumeration cache
#: (``KART_SERVE_ENUM_CACHE`` overrides; ``0`` disables caching entirely)
DEFAULT_ENUM_CACHE_BYTES = 256 * 1024 * 1024

#: how long a request waits on another request's in-flight walk for the
#: same cache key before giving up and walking independently (a wedged
#: filler must not wedge every client behind it)
SINGLEFLIGHT_TIMEOUT = 600.0


def ls_refs_info(repo):
    """The advertisement: branch/tag tips, HEAD branch, shallow set."""
    from kart_tpu.transport.remote import read_shallow

    tm.incr("transport.server.requests", verb="ls-refs")

    heads = {
        ref[len("refs/heads/"):]: oid
        for ref, oid in repo.refs.iter_refs("refs/heads/")
    }
    tags = {
        ref[len("refs/tags/"):]: oid
        for ref, oid in repo.refs.iter_refs("refs/tags/")
    }
    kind, target = repo.refs.head_target()
    head_branch = (
        target[len("refs/heads/"):]
        if kind == "symbolic" and target.startswith("refs/heads/")
        else None
    )
    return {
        "heads": heads,
        "tags": tags,
        "head_branch": head_branch,
        "shallow": sorted(read_shallow(repo)),
    }


def make_fetch_enum(repo, req, *, count_request=True, record_emitted=False):
    """fetch-pack request dict -> (ObjectEnumerator, header_fn). The header
    callable reads the enumerator's counters, so evaluate it only after the
    pack drain. ``count_request=False`` skips the request counters (the
    enum-cache front end :func:`serve_fetch_pack` counts them itself so a
    cache hit still shows up as a request)."""
    from kart_tpu.transport.remote import read_shallow
    from kart_tpu.transport.http import have_closure

    if count_request:
        _count_fetch_request(req)
    blob_filter = None
    if req.get("filter"):
        from kart_tpu.spatial_filter import blob_filter_for_spec

        blob_filter = blob_filter_for_spec(repo, req["filter"])
    has = None
    if req.get("haves"):
        closure = have_closure(repo.odb, req["haves"], req.get("have_shallow", ()))
        has = closure.__contains__
    enum = ObjectEnumerator(
        repo.odb,
        req.get("wants", []),
        has=has,
        depth=req.get("depth"),
        blob_filter=blob_filter,
        sender_shallow=read_shallow(repo),
        # the resume protocol: exact oids the client already holds (salvaged
        # from a torn earlier transfer). Unlike `haves` these carry no
        # closure guarantee, so they suppress shipping object-by-object
        # without pruning the walk — a resumed fetch ships only the missing
        # remainder.
        exclude=frozenset(req.get("exclude") or ()),
        record_emitted=record_emitted,
    )

    def header():
        return {
            "shallow_boundary": sorted(enum.shallow_boundary),
            "object_count": enum.object_count,
            "omitted_blob_count": enum.omitted_blob_count,
        }

    return enum, header


def _count_fetch_request(req):
    tm.incr("transport.server.requests", verb="fetch-pack")
    if req.get("exclude"):
        # a non-empty exclusion list IS the resume protocol: the client is
        # completing a torn earlier transfer (docs/ROBUSTNESS.md §3)
        tm.incr("transport.server.fetch_resumes")
        tm.incr("transport.server.excluded_oids", len(req["exclude"]))


def collect_blobs(repo, oids):
    """fetch-blobs (promisor backfill): -> (header, [(type, content)])."""
    tm.incr("transport.server.requests", verb="fetch-blobs")
    missing = []
    objects = []
    for oid in oids:
        try:
            objects.append(repo.odb.read_raw(oid))
        except ObjectMissing:
            missing.append(oid)
    return {"missing": missing}, objects


# ---------------------------------------------------------------------------
# pack-enumeration cache (docs/SERVING.md §2)
#
# The expensive half of serving a fetch is the reachability walk + tree
# recursion, and under concurrent clones of a hot repo every client used to
# re-pay it. The cache memoizes, per (wants, haves, shallow, depth, filter,
# excludes, ref-tips fingerprint) key: the final response header, a size
# estimate, and either the complete framed response bytes (small packs — a
# hit is a memcpy) or the ordered (type, oid) list the walk emitted (big
# packs — a hit replays object reads in order, no walk). Concurrent
# requests for an in-flight key block on the first walk (single-flight)
# instead of duplicating it. Ref updates invalidate: the fingerprint is
# part of the key, and apply_ref_updates additionally drops every entry so
# stale keys don't linger in the LRU.
# ---------------------------------------------------------------------------


class _CacheEntry:
    __slots__ = ("header", "data", "emitted", "nbytes", "etag")

    def __init__(self, header, data, emitted, etag):
        self.header = header
        self.data = data          # complete framed response bytes, or None
        self.emitted = emitted    # ordered (type, oid) replay list, or None
        self.etag = etag
        if data is not None:
            self.nbytes = len(data)
        else:
            # oid-list replay entry, charged at measured CPython cost:
            # ~89B hex-oid str + 56B tuple + interned type ref + list slot
            self.nbytes = 160 * len(emitted) + 1024


class PackEnumCache(SingleFlightLRU):
    """LRU-by-byte-budget memo of fetch-pack enumerations with
    single-flight fill (one instance per served repo). The concurrency
    machinery — single-flight tokens, the wedged-filler bypass, the
    poison-barrier publish, LRU eviction — is the shared
    :class:`~kart_tpu.core.singleflight.SingleFlightLRU` (the tile cache
    runs the same core); this class contributes the entry shape
    (:class:`_CacheEntry`), the telemetry names and the fault point.

    A fill publishes a complete ``_CacheEntry``; a filler wedged past
    ``SINGLEFLIGHT_TIMEOUT`` stops gating (waiters walk uncached)."""

    SINGLEFLIGHT_TIMEOUT = SINGLEFLIGHT_TIMEOUT

    def __init__(self, budget_bytes):
        super().__init__(budget_bytes)
        # a single entry may use at most budget/8 bytes as raw framed
        # bytes; larger packs store the oid replay list instead, so one
        # huge clone can't evict every hot entry
        self.bytes_cap = max(1, budget_bytes // 8)

    def entry_nbytes(self, entry):
        return entry.nbytes

    def publish_fault(self):
        # the injectable failure of the cache-fill frame: a fault here must
        # poison nothing — the entry is never inserted (tests/test_faults.py)
        faults.fire("server.enum_cache")

    def count(self, event, n=1):
        if event == "hits":
            tm.incr("server.enum_cache.hits", n)
        elif event == "misses":
            tm.incr("server.enum_cache.misses", n)
        elif event == "singleflight_waits":
            tm.incr("server.enum_cache.singleflight_waits", n)
        elif event == "evictions":
            tm.incr("server.enum_cache.evictions", n)

    def gauge(self, total):
        tm.gauge_set("server.enum_cache.bytes", total)


#: gitdir -> PackEnumCache for every repo this process serves (bounded: a
#: long-lived test process churning tmp repos must not accrete caches)
_ENUM_CACHES = OrderedDict()
_ENUM_CACHES_MAX = 64
_enum_caches_lock = threading.Lock()


def enum_cache_for(repo):
    """The (process-wide) enumeration cache serving ``repo``, or None when
    disabled via ``KART_SERVE_ENUM_CACHE=0``."""
    from kart_tpu.transport.retry import _env_int

    budget = _env_int("KART_SERVE_ENUM_CACHE", DEFAULT_ENUM_CACHE_BYTES)
    if budget <= 0:
        return None
    key = os.path.realpath(repo.gitdir)
    with _enum_caches_lock:
        cache = _ENUM_CACHES.get(key)
        if cache is None or cache.budget != budget:
            cache = _ENUM_CACHES[key] = PackEnumCache(budget)
        _ENUM_CACHES.move_to_end(key)
        while len(_ENUM_CACHES) > _ENUM_CACHES_MAX:
            _ENUM_CACHES.popitem(last=False)
    return cache


def refs_fingerprint(repo):
    """Digest of every (ref, oid) pair: part of each cache key, so a ref
    update — even by another process (an ssh push landing while the HTTP
    server runs) — changes every key rather than serving a stale walk."""
    h = hashlib.sha256()
    for ref, oid in sorted(repo.refs.iter_refs("refs/")):
        h.update(f"{ref}\0{oid}\n".encode())
    return h.hexdigest()


def _enum_cache_key(repo, req):
    payload = json.dumps(
        {
            # wants stay ordered: the walk order (and so the pack bytes)
            # follows them; everything set-like is canonicalised
            "wants": list(req.get("wants") or ()),
            "haves": sorted(req.get("haves") or ()),
            "have_shallow": sorted(req.get("have_shallow") or ()),
            "depth": req.get("depth"),
            "filter": req.get("filter"),
            "exclude": sorted(req.get("exclude") or ()),
            "refs": refs_fingerprint(repo),
        },
        sort_keys=True,
    )
    return hashlib.sha256(payload.encode()).hexdigest()


def _etag_for(key):
    """The strong validator for byte-range resume (If-Range): same key ⇒
    byte-identical response, and the key embeds the ref fingerprint."""
    return f'"{key[:32]}"'


class FetchPlan:
    """How to answer one fetch-pack request, produced by
    :func:`serve_fetch_pack`:

    * ``data`` set — a cache hit on stored framed bytes; send as-is.
    * otherwise — drain ``source`` through ``write_framed`` (``header`` is
      the deferred header callable), then ``publish()`` the spool /
      ``abandon()`` on failure. ``cached`` marks whether ``source`` is a
      cache replay (no walk ran).

    ``etag`` is the strong validator the transports hand out for
    byte-range resume; identical for hit, replay and fresh walks of the
    same key."""

    __slots__ = ("header", "data", "source", "etag", "cached", "_token", "_enum")

    def __init__(self, header, data, source, etag, cached, token=None, enum=None):
        self.header = header
        self.data = data
        self.source = source
        self.etag = etag
        self.cached = cached
        self._token = token
        self._enum = enum

    def publish(self, spool, length):
        """Memoize a freshly-spooled walk: small responses as their framed
        bytes, big ones as the ordered oid list (``spool`` is left at EOF;
        the caller rewinds)."""
        if self._token is None:
            return
        header = self.header() if callable(self.header) else self.header
        cache = self._token.cache
        etag = _etag_for(self._token.key)
        if length <= cache.bytes_cap:
            spool.seek(0)
            self._token.publish(
                _CacheEntry(header, spool.read(length), None, etag)
            )
        elif self._enum is not None and self._enum.emitted is not None:
            self._token.publish(
                _CacheEntry(header, None, list(self._enum.emitted), etag)
            )
        else:
            self._token.abandon()

    def abandon(self):
        if self._token is not None:
            self._token.abandon()


def iter_recorded(odb, emitted):
    """Replay an enumeration from its recorded ``(type, oid)`` list:
    byte-identical object stream, zero walk. Blob runs go through the
    batched pack reader exactly like the original walk's flush."""
    i, n = 0, len(emitted)
    while i < n:
        obj_type, oid = emitted[i]
        if obj_type != "blob":
            yield obj_type, odb.read_raw(oid)[1]
            i += 1
            continue
        j = i
        while j < n and emitted[j][0] == "blob":
            j += 1
        run = [oid for _, oid in emitted[i:j]]
        SLICE = 1000
        for k in range(0, len(run), SLICE):
            chunk = run[k : k + SLICE]
            batch = odb.read_blobs_batch(chunk)
            for o in chunk:
                blob = batch.get(o)
                if blob is None:
                    _, blob = odb.read_raw(o)
                yield "blob", blob
        i = j


def _replay_source(cache, key, odb, emitted):
    """iter_recorded, with poisoned-entry hygiene: an entry whose objects
    have vanished (gc raced the cache) is evicted and the error surfaces —
    the next request re-walks instead of re-hitting the corpse."""
    try:
        yield from iter_recorded(odb, emitted)
    except Exception:
        cache.evict(key)
        raise


def serve_fetch_pack(repo, req, *, use_cache=True):
    """The cache-fronted fetch-pack verb: -> :class:`FetchPlan`.

    First request for a key runs (and records) the walk; concurrent
    requests for the same key block on it and hit; later requests hit
    the memo. With the cache disabled (``KART_SERVE_ENUM_CACHE=0``, or
    ``use_cache=False`` for single-connection servers where a memo could
    never be re-hit) the plan is a plain fresh walk — still carrying the
    deterministic etag, so byte-range resume works regardless."""
    _count_fetch_request(req)
    # an exclusion-bearing request is a one-shot resume: its key embeds the
    # exact oids that happened to land before a tear, so no second request
    # can ever hit it — memoizing would only evict hot repeatable entries.
    # The etag/deterministic-replay contract holds regardless.
    if req.get("exclude"):
        use_cache = False
    cache = enum_cache_for(repo) if use_cache else None
    key = _enum_cache_key(repo, req)
    etag = _etag_for(key)
    if cache is None:
        enum, header = make_fetch_enum(repo, req, count_request=False)
        return FetchPlan(header, None, enum, etag, False)
    mode, got = cache.lookup_or_begin(key)
    if mode == "hit":
        # the cache decision joins this request's access-log record
        tm.annotate(enum_cache="hit")
        if got.data is not None:
            return FetchPlan(got.header, got.data, None, got.etag, True)
        return FetchPlan(
            got.header,
            None,
            _replay_source(cache, key, repo.odb, got.emitted),
            got.etag,
            True,
        )
    try:
        tm.annotate(enum_cache="miss")
        enum, header = make_fetch_enum(
            repo, req, count_request=False, record_emitted=True
        )
    except BaseException:
        # a pre-walk failure (malformed filter spec, unreadable shallow
        # file) must release the fill token, or every later request for
        # this key would block on an event nobody will ever set
        if got is not None:
            got.abandon()
        raise
    return FetchPlan(header, None, enum, etag, False, token=got, enum=enum)


def materialise_plan(plan):
    """-> (file-like at position 0, total length) of the complete framed
    response for ``plan``; fresh walks are spooled, published into the
    cache, and rewound. The caller owns (and must close) the handle."""
    from kart_tpu.transport.http import write_framed

    if plan.data is not None:
        with tm.span("server.enum_replay"):
            return io.BytesIO(plan.data), len(plan.data)
    span = "server.enum_replay" if plan.cached else "server.enum_walk"
    buf = tempfile.SpooledTemporaryFile(max_size=64 * 1024 * 1024)
    try:
        with tm.span(span):
            write_framed(buf, plan.header, plan.source)
        length = buf.tell()
        plan.publish(buf, length)
    except BaseException:
        plan.abandon()
        buf.close()
        raise
    buf.seek(0)
    return buf, length


# ---------------------------------------------------------------------------
# the per-ref merge queue (docs/SERVING.md §6)
#
# K writers racing one branch used to convoy on the push lock: every CAS
# loser re-validated at a random position and could lose again, unbounded.
# The queue turns the race into an ordered line per ref — each writer waits
# its turn, rebases exactly once onto its predecessor's tip, and lands.
# Depth and wait are measured; overflow sheds into the 429 + Retry-After
# lane the client RetryPolicy already paces itself against.
# ---------------------------------------------------------------------------


class MergeQueueFull(Exception):
    """The per-ref line is at its depth bound — shed, don't queue."""


class MergeQueue:
    """FIFO ticket line per contended ref (one instance per served repo).

    ``slot(ref)`` is a context manager: entering takes the next ticket and
    blocks until every earlier ticket for the same ref released; the body
    runs the CAS/rebase/migrate sequence with no same-ref writer racing it
    in this process (cross-process safety stays with ``push_file_lock`` —
    the queue is the *ordering* layer, not the correctness layer). Yields
    the seconds spent waiting."""

    def __init__(self):
        self._lock = threading.Lock()
        self._lines = {}  # ref -> {"cond", "next", "serving", "cancelled"}

    def _depth_locked(self):
        return sum(l["next"] - l["serving"] for l in self._lines.values())

    @contextmanager
    def slot(self, ref, *, depth_limit=None, timeout=MERGE_QUEUE_TIMEOUT):
        from kart_tpu.transport.retry import _env_int

        if depth_limit is None:
            depth_limit = _env_int(
                "KART_SERVE_MERGE_QUEUE", DEFAULT_MERGE_QUEUE_DEPTH
            )
        with self._lock:
            line = self._lines.get(ref)
            if line is None:
                line = self._lines[ref] = {
                    "cond": threading.Condition(self._lock),
                    "next": 0,
                    "serving": 0,
                    "cancelled": set(),
                }
            queued = line["next"] - line["serving"]
            if depth_limit > 0 and queued >= depth_limit:
                tm.incr("server.merge_queue.shed")
                raise MergeQueueFull(
                    f"Merge queue for {ref} is full "
                    f"({queued} writers waiting); retry"
                )
            ticket = line["next"]
            line["next"] += 1
            tm.gauge_set("server.merge_queue.depth", self._depth_locked())
            t0 = time.monotonic()
            deadline = t0 + timeout
            waited = line["serving"] != ticket
            if waited:
                tm.incr("server.merge_queue.waits")
            while line["serving"] != ticket:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # a wedged predecessor must not wedge the whole line:
                    # cancel this ticket (release skips it) and shed
                    line["cancelled"].add(ticket)
                    tm.gauge_set(
                        "server.merge_queue.depth", self._depth_locked()
                    )
                    tm.incr("server.merge_queue.shed")
                    raise MergeQueueFull(
                        f"Merge queue for {ref} stalled for {timeout:.0f}s; retry"
                    )
                line["cond"].wait(min(remaining, 60.0))
            wait_s = time.monotonic() - t0
            if waited:
                tm.observe("server.merge_queue.wait_seconds", wait_s)
        try:
            yield wait_s
        finally:
            with self._lock:
                line["serving"] += 1
                while line["serving"] in line["cancelled"]:
                    line["cancelled"].discard(line["serving"])
                    line["serving"] += 1
                if line["serving"] >= line["next"]:
                    self._lines.pop(ref, None)
                else:
                    line["cond"].notify_all()
                tm.gauge_set("server.merge_queue.depth", self._depth_locked())


#: gitdir -> MergeQueue, mirroring _ENUM_CACHES' bounds. Eviction of a
#: still-waiting queue only de-links it from *new* pushes (waiters keep the
#: instance alive via their slot closure; push_file_lock keeps two queues
#: for one repo correct, merely unordered) — and only past 64 served repos.
_MERGE_QUEUES = OrderedDict()
_merge_queues_lock = threading.Lock()


def merge_queue_for(repo):
    key = os.path.realpath(repo.gitdir)
    with _merge_queues_lock:
        queue = _MERGE_QUEUES.get(key)
        if queue is None:
            queue = _MERGE_QUEUES[key] = MergeQueue()
        _MERGE_QUEUES.move_to_end(key)
        while len(_MERGE_QUEUES) > _ENUM_CACHES_MAX:
            _MERGE_QUEUES.popitem(last=False)
    return queue


# ---------------------------------------------------------------------------
# server-side rebase of a CAS-losing push (docs/SERVING.md §6)
# ---------------------------------------------------------------------------


class _QuarantineRepoView:
    """Just enough of the KartRepo surface for a server-side three-way
    merge: every object read and write routes through the quarantine's odb
    (live store wired in as an alternate), so the incoming — not yet
    migrated — commits are visible, and everything the rebase produces
    (merged trees, the merge commit) lands in the quarantine and migrates,
    or is discarded, together with the push itself."""

    def __init__(self, repo, odb):
        self._repo = repo
        self.odb = odb
        self.refs = repo.refs
        self.config = repo.config
        self.workdir = repo.workdir
        self.gitdir = repo.gitdir

    @property
    def version(self):
        return self._repo.version

    def signature(self, role="committer"):
        return self._repo.signature(role)

    # history helpers re-bound onto this view so revision resolution and
    # ancestry/merge-base walks read through the quarantine odb, not only
    # the live store
    resolve_refish = KartRepo.resolve_refish
    _resolve_plain = KartRepo._resolve_plain
    _peel_to_commit_oid = KartRepo._peel_to_commit_oid
    merge_base = KartRepo.merge_base
    _ancestor_set = KartRepo._ancestor_set
    is_ancestor = KartRepo.is_ancestor


def _rebaseable_update(header):
    """The single branch update a lost CAS may auto-rebase: exactly one
    update, non-force, creating/moving (not deleting) a ``refs/heads/``
    ref. Multi-ref transactions and force/delete updates keep the plain
    reject-on-stale behaviour — a human asked for something atomic or
    destructive; the server must not reinterpret it."""
    updates = header.get("updates", [])
    if len(updates) != 1:
        return None
    upd = updates[0]
    if upd.get("force") or not upd.get("new"):
        return None
    if not upd["ref"].startswith("refs/heads/"):
        return None
    return upd


#: clock-skew slack for the containment walk's commit-time pruning: a
#: commit this much older than the target may still (with skewed clocks)
#: have the target below it, so it is still descended
_CONTAINS_TIME_SLACK = 86_400


def _commit_contains(view, tip_oid, target_oid):
    """Is ``target_oid`` an ancestor of (or equal to) ``tip_oid``? A DFS
    from the tip that stops at the target and prunes commits meaningfully
    older than it — O(commits since the target) on real pushes, never the
    O(entire history) ancestor-set walk. Pruning errs safe: a skew-induced
    false negative merely sends the push through the rebase path, whose
    own ff/noop detection lands it identically."""
    if tip_oid == target_oid:
        return True
    try:
        target_time = view.odb.read_commit(target_oid).committer.time
    except (ObjectMissing, KeyError, ValueError):
        return False
    floor = target_time - _CONTAINS_TIME_SLACK
    seen = set()
    stack = [tip_oid]
    while stack:
        oid = stack.pop()
        if oid == target_oid:
            return True
        if oid in seen:
            continue
        seen.add(oid)
        try:
            commit = view.odb.read_commit(oid)
        except (ObjectMissing, KeyError, ValueError):
            continue  # shallow/partial boundary
        if commit.committer.time >= floor:
            stack.extend(commit.parents)
    return False


def _ff_precheck(view, repo, header):
    """-> ``({ref: observed tip}, first non-ff update or None)``.

    The server-side half of the fast-forward rule the client used to
    enforce alone: the CAS cannot see divergence that predates the
    advertisement the client pushed against (old matches, yet the incoming
    commit doesn't contain the tip). The ancestry walks run OUTSIDE the
    push locks — the caller re-verifies every observed tip under the locks
    and loops if one moved meanwhile."""
    observed = {}
    stale = None
    for upd in header.get("updates", []):
        new = upd.get("new")
        if not new or upd.get("force") or not upd["ref"].startswith("refs/heads/"):
            continue
        current = repo.refs.get(upd["ref"])
        observed[upd["ref"]] = current
        if (
            stale is None
            and current is not None
            and current != new
            and not _commit_contains(view, new, current)
        ):
            stale = upd
    return observed, stale


def _rebase_onto(repo, q, upd, current_tip):
    """Three-way merge of the incoming commit against the tip that beat it,
    computed entirely inside the quarantine.

    -> ``("ff"|"noop"|"merge", oid)`` — the oid the contended ref should
    land at; ``("conflict", report)`` — real conflicts, with the structured
    report document; ``None`` — not auto-mergeable (unrelated histories).

    Every frame is an injectable crash (``KART_FAULTS=server.rebase:<n>``):
    1 = the ancestry/classifier run, 2 = the merge-commit write, 3 = the
    quarantine-side temp-ref write. A kill at any of them propagates out,
    the quarantine is discarded, and the live store stays byte-identical
    (tests/test_faults.py kill matrix)."""
    from kart_tpu.core.objects import Commit
    from kart_tpu.core.structure import RepoStructure
    from kart_tpu.merge import merge_trees_vectorized

    ref, incoming = upd["ref"], upd["new"]
    view = _QuarantineRepoView(repo, q.odb)
    faults.fire("server.rebase")  # frame 1: ancestry + classifier run
    if current_tip is None:
        # the contended branch vanished between CAS checks: recreate it at
        # the incoming commit — a plain fast-forward of the create case
        return "ff", incoming
    # EXACT ancestry here, not the time-pruned precheck walk: this is the
    # backstop that turns a precheck false negative (clock skew) back into
    # the identical ff/noop landing instead of a spurious merge commit
    if view.is_ancestor(current_tip, incoming):
        return "ff", incoming  # incoming already contains the tip
    if view.is_ancestor(incoming, current_tip):
        return "noop", current_tip  # nothing new to land
    ancestor = view.merge_base(current_tip, incoming)
    if ancestor is None:
        return None  # unrelated histories: humans decide
    with tm.span("server.rebase", ref=ref):
        merged_tree, conflicts, stats = merge_trees_vectorized(
            view,
            RepoStructure(view, ancestor),
            # ours = the incoming commit, theirs = the tip that beat it:
            # the exact orientation the losing client would get from a
            # local `kart merge <tip>`, so the conflict report below is
            # byte-identical to that dry run (one source of truth —
            # tests/test_merge_service.py parity test)
            RepoStructure(view, incoming),
            RepoStructure(view, current_tip),
        )
    if conflicts:
        from kart_tpu.cli.merge_cmds import merge_conflict_report

        tm.incr("server.rebase.conflicts")
        return "conflict", {
            "ref": ref,
            "ancestor": ancestor,
            "ours": incoming,
            "theirs": current_tip,
            "conflicts_total": len(conflicts),
            # the exact `kart merge <theirs> --dry-run -o json` document
            "merge": merge_conflict_report(conflicts),
        }
    faults.fire("server.rebase")  # frame 2: the merge-commit write
    sig = view.signature()
    short = ref[len("refs/heads/"):] if ref.startswith("refs/heads/") else ref
    commit = Commit(
        tree=merged_tree,
        parents=(current_tip, incoming),
        author=sig,
        committer=sig,
        message=(
            f"Merge {incoming[:8]} into {short} "
            f"(server-side rebase onto {current_tip[:8]})\n"
        ),
    )
    merged_oid = q.odb.write_commit(commit)
    faults.fire("server.rebase")  # frame 3: quarantine temp-ref write
    q.write_temp_ref(ref, merged_oid)
    return "merge", merged_oid


def current_branch_ref(repo):
    kind, target = repo.refs.head_target()
    return target if kind == "symbolic" else None


@contextmanager
def push_file_lock(repo):
    """Cross-process push lock over the gitdir: every ssh push spawns its
    own serve-stdio process, so an in-process lock can't serialise the
    compare-and-swap (two concurrent pushes would both pass the CAS check
    and one would be silently lost). The HTTP server holds its thread lock
    too, so mixed http+ssh pushes against one repo stay safe."""
    lock_path = os.path.join(repo.gitdir, ".push-lock")
    with open(lock_path, "w") as lock:
        try:
            import fcntl

            fcntl.flock(lock, fcntl.LOCK_EX)
        except ImportError:  # non-POSIX: best effort
            pass
        yield


def locked_ref_updates(repo, header):
    """apply_ref_updates under the cross-process push lock (back-compat
    entry point for callers that drained objects into the live store
    themselves; the servers use :func:`quarantined_receive`)."""
    with push_file_lock(repo):
        return apply_ref_updates(repo, header)


class ReceiveQuarantine:
    """A temporary objects dir under ``<gitdir>/objects/quarantine/``
    holding a pushed pack until it earns its way into the live store (the
    analog of git's receive-pack ``tmp_objdir``). The main store is wired
    in as an alternate, so connectivity/containment checks see quarantined
    + live objects together while the live store stays untouched."""

    def __init__(self, repo):
        from kart_tpu.core.odb import ObjectDb

        self.repo = repo
        base = os.path.join(repo.gitdir, "objects", QUARANTINE_SUBDIR)
        os.makedirs(base, exist_ok=True)
        self.dir = tempfile.mkdtemp(prefix="incoming-", dir=base)
        self.odb = ObjectDb(self.dir)
        self.odb.add_alternate(os.path.join(repo.gitdir, "objects"))

    def discard(self):
        """Drop everything received — the live store is byte-identical to
        before the push started."""
        shutil.rmtree(self.dir, ignore_errors=True)

    def write_temp_ref(self, ref, oid):
        """Record an in-flight server-side rebase result on a quarantine-
        side temp ref (``<quarantine>/refs/<mangled-name>``): visible to
        crash forensics, swept with the quarantine, never under the live
        ``refs/`` tree — so a rejected or crashed rebase leaves zero ref
        debris for gc to misread."""
        refs_dir = os.path.join(self.dir, "refs")
        os.makedirs(refs_dir, exist_ok=True)
        with open(os.path.join(refs_dir, ref.replace("/", "+")), "w") as f:
            f.write(oid + "\n")

    def migrate(self):
        """Move the quarantined pack(s) (and any loose strays) into the live
        store. Only called after the pack checksum and every ref-update
        precondition passed. Same-filesystem renames; ``.pack`` moves before
        its ``.idx`` so a concurrent reader never sees an idx without its
        pack."""
        objects_dir = self.repo.odb.objects_dir
        qpack = os.path.join(self.dir, "pack")
        if os.path.isdir(qpack):
            dst_pack = os.path.join(objects_dir, "pack")
            os.makedirs(dst_pack, exist_ok=True)
            names = sorted(
                os.listdir(qpack), key=lambda n: (n.endswith(".idx"), n)
            )
            for name in names:
                if name.startswith("."):
                    continue  # writer temp files never migrate
                os.replace(
                    os.path.join(qpack, name), os.path.join(dst_pack, name)
                )
        for prefix in os.listdir(self.dir):
            if len(prefix) != 2:
                continue
            src_d = os.path.join(self.dir, prefix)
            dst_d = os.path.join(objects_dir, prefix)
            os.makedirs(dst_d, exist_ok=True)
            for name in os.listdir(src_d):
                os.replace(
                    os.path.join(src_d, name), os.path.join(dst_d, name)
                )
        self.repo.odb.packs.refresh()
        self.discard()


def quarantined_receive(repo, header, pack_fp, *, thread_lock=None):
    """The full receive-pack verb: drain the pushed pack into quarantine,
    validate the ref updates, migrate, apply — and, when the CAS was lost
    to a contending writer, auto-rebase the incoming commit onto the new
    tip before re-validating (docs/SERVING.md §6). A torn pack, a checksum
    mismatch, any rejected precondition, or a crash at any rebase frame
    leaves the live store byte-identical (the quarantine is discarded);
    objects reach the live store only in the success path, under the push
    locks.

    -> ``("ok", {"updated": {ref: oid|None}, "rebase": {...}})`` |
    ``(kind, rejection)`` where ``rejection`` is a
    :class:`~kart_tpu.transport.protocol.Rejection` (tuple-compatible with
    the old ``(kind, msg)``; ``kind`` gains ``"busy"`` for the paced-retry
    lane). Transfer-level failures (torn/corrupt pack) raise instead, so
    each server reports them the same way as any other I/O failure."""
    from kart_tpu.transport.pack import read_pack

    tm.incr("transport.server.requests", verb="receive-pack")
    q = ReceiveQuarantine(repo)
    try:
        with tm.span("transport.receive_drain"), q.odb.bulk_pack():
            for obj_type, content in read_pack(pack_fp):
                q.odb.write_raw(obj_type, content)
    except BaseException:
        tm.incr("transport.server.receive_outcomes", outcome="torn")
        q.discard()
        raise
    try:
        return _land_quarantined(repo, q, header, thread_lock)
    except BaseException:
        q.discard()  # no-op after a successful migrate
        raise


def _land_quarantined(repo, q, header, thread_lock):
    """Validate + (rebase-as-needed) + migrate + apply a drained quarantine.

    The CAS re-validation loop is bounded by ``KART_SERVE_REBASE_ATTEMPTS``
    and — for the single-branch-update pushes that can rebase — ordered
    through the per-ref merge queue, so K contending writers form a line
    and each rebases exactly once onto its predecessor's tip."""
    from kart_tpu.transport.retry import _env_int

    upd = _rebaseable_update(header)
    attempts_cap = max(
        1, _env_int("KART_SERVE_REBASE_ATTEMPTS", DEFAULT_REBASE_ATTEMPTS)
    )
    retry_after = max(0, _env_int("KART_SERVE_RETRY_AFTER", 1))
    info = {"rebased": 0, "cas_attempts": 0, "queue_wait_seconds": 0.0}

    def reject(rejection):
        tm.incr("transport.server.receive_outcomes", outcome=rejection[0])
        tm.annotate(
            rejected=getattr(rejection, "code", None) or rejection[0],
            ref=getattr(rejection, "ref", None),
        )
        q.discard()
        return rejection

    try:
        slot = (
            merge_queue_for(repo).slot(upd["ref"])
            if upd is not None
            else nullcontext(0.0)
        )
        with slot as waited:
            info["queue_wait_seconds"] = round(waited or 0.0, 6)
            if upd is not None:
                tm.annotate(
                    ref=upd["ref"],
                    queue_wait_seconds=info["queue_wait_seconds"] or None,
                )
            view = _QuarantineRepoView(repo, q.odb)
            for attempt in range(1, attempts_cap + 1):
                info["cas_attempts"] = attempt
                # the (potentially deep) fast-forward ancestry walk runs
                # before the locks; the observed tips are re-verified under
                # them, and movement in between just restarts the loop
                observed, stale = _ff_precheck(view, repo, header)
                with (thread_lock if thread_lock is not None else nullcontext()):
                    with push_file_lock(repo):
                        # injectable frame 1: the CAS (re-)check under both
                        # push locks
                        faults.fire("server.ref_cas")
                        rejection = validate_ref_updates(
                            repo, header, contains=q.odb.contains
                        )
                        if rejection is None:
                            for ref, tip in observed.items():
                                if repo.refs.get(ref) != tip:
                                    # a writer landed between the precheck
                                    # and the locks: the ff verdict is
                                    # stale, go around again
                                    rejection = Rejection(
                                        "conflict",
                                        f"Ref {ref} moved during validation",
                                        code="cas_stale",
                                        ref=ref,
                                    )
                                    break
                        if rejection is None and stale is not None:
                            # old matched but history diverged before the
                            # advertisement: same contended-write situation
                            # as a lost CAS
                            rejection = Rejection(
                                "conflict",
                                f"Ref {stale['ref']} update is not a "
                                f"fast-forward; fetch first or use --force",
                                code="cas_stale" if stale is upd else "non_ff",
                                ref=stale["ref"],
                                terminal=stale is not upd,
                            )
                        if rejection is None:
                            # injectable frame 2: quarantine migrate into
                            # the live store
                            faults.fire("server.ref_cas")
                            q.migrate()
                            tm.incr(
                                "transport.server.receive_outcomes",
                                outcome="ok",
                            )
                            if info["rebased"]:
                                tm.incr("server.rebase.landed")
                                tm.annotate(
                                    rebased=True,
                                    rebase_mode=info.get("mode"),
                                )
                            out = {}
                            updated = _apply_validated_updates(
                                repo, header, out
                            )
                            payload = {"updated": updated, "rebase": info}
                            # the booked live-update sequence (absent on
                            # non-serving processes / events off): a
                            # read-your-writes client pins on it
                            payload.update(out)
                            return "ok", payload
                        current = (
                            repo.refs.get(upd["ref"]) if upd is not None else None
                        )
                if upd is None or getattr(rejection, "code", None) != "cas_stale":
                    return reject(rejection)
                if attempt >= attempts_cap:
                    break
                # CAS lost to a contending writer: rebase outside the locks
                # (the classifier run must not extend the critical section)
                tm.incr("server.rebase.attempts")
                outcome = _rebase_onto(repo, q, upd, current)
                if outcome is None:
                    return reject(
                        Rejection(
                            "conflict",
                            f"Push to {upd['ref']} rejected (non-fast-forward: "
                            f"no common ancestor with the current tip); fetch "
                            f"first or use --force",
                            code="non_ff",
                            ref=upd["ref"],
                            terminal=True,
                        )
                    )
                kind, value = outcome
                if kind == "conflict":
                    return reject(
                        Rejection(
                            "conflict",
                            f"Push to {upd['ref']} rejected: merging the "
                            f"incoming commit with the current tip conflicts "
                            f"({value['conflicts_total']} conflicts); pull and "
                            f"resolve locally, then push the merge",
                            code="merge_conflict",
                            ref=upd["ref"],
                            conflict_report=value,
                            terminal=True,
                        )
                    )
                info["rebased"] = 1
                info["mode"] = kind  # "merge" | "ff" | "noop"
                upd["old"], upd["new"] = current, value
            tm.incr("server.rebase.exhausted")
            return reject(
                Rejection(
                    "busy",
                    f"Ref {upd['ref']} kept moving through {attempts_cap} CAS "
                    f"attempts; retry shortly",
                    code="cas_busy",
                    ref=upd["ref"],
                    retry_after=retry_after,
                    shed=True,
                )
            )
    except MergeQueueFull as e:
        return reject(
            Rejection(
                "busy",
                str(e),
                code="queue_full",
                retry_after=retry_after,
                shed=True,
            )
        )


def _df_collision(repo, ref):
    """A ref name colliding with an existing ref at a directory/file
    boundary (``refs/heads/a`` vs ``refs/heads/a/b``) can never be created
    — the loose-ref store would need ``a`` to be both a file and a
    directory, and ``refs.set`` would die half-way with debris. A
    server-constructed rebased ref must trip this cleanly, not crash.
    -> message, or None. O(path depth), not O(refs): this runs under the
    push locks."""
    existing = repo.refs.df_conflict(ref)
    if existing is not None:
        return (
            f"Ref {ref} conflicts with existing ref {existing} "
            f"(directory/file collision); delete it first"
        )
    return None


def validate_ref_updates(repo, header, *, contains=None):
    """Check every precondition of a receive-pack's ref updates without
    moving anything: refname hygiene (including names shaped like crash
    debris and directory/file collisions with existing refs),
    checked-out-branch protection, CAS against the current tips, and
    object connectivity via ``contains`` (a quarantine's combined
    live+incoming check during a push).

    -> None when everything passes, else a
    :class:`~kart_tpu.transport.protocol.Rejection` — tuple-compatible
    ``("conflict"|"bad", msg)`` carrying a machine-readable ``code`` the
    rebase loop keys on (only ``cas_stale`` is recoverable)."""
    contains = contains or repo.odb.contains
    deny_current = (
        repo.workdir is not None
        and (repo.config.get("receive.denyCurrentBranch") or "refuse").lower()
        not in ("ignore", "false")
    )

    for upd in header.get("updates", []):
        ref, old, new = upd["ref"], upd.get("old"), upd.get("new")
        # wire-supplied names must be real refs — git's receive-pack rejects
        # non-refs/ names via check_refname_format; without this a push with
        # ref='config' or 'HEAD' would overwrite arbitrary gitdir files.
        try:
            check_ref_format(ref, require_refs_prefix=True)
        except RefError as e:
            return Rejection("bad", str(e), code="bad_ref", ref=ref,
                             terminal=True)
        if deny_current and ref == current_branch_ref(repo):
            return Rejection(
                "conflict",
                f"Refusing to update checked-out branch {ref} (the server's "
                f"working copy would go out of sync). Serve a bare repo, or "
                f"set receive.denyCurrentBranch=ignore there.",
                code="denied",
                ref=ref,
                terminal=True,
            )
        if new is not None:
            collision = _df_collision(repo, ref)
            if collision is not None:
                return Rejection(
                    "conflict", collision, code="df_conflict", ref=ref,
                    terminal=True,
                )
        current = repo.refs.get(ref)
        if not upd.get("force") and current != old:
            return Rejection(
                "conflict",
                f"Ref {ref} moved (expected {old}, is {current}); "
                f"fetch first or use --force",
                code="cas_stale",
                ref=ref,
            )
        if new is not None and not contains(new):
            return Rejection(
                "bad", f"Push incomplete: {new} not received",
                code="incomplete", ref=ref,
            )
    return None


def _apply_validated_updates(repo, header, out=None):
    """Apply pre-validated ref updates; -> {ref: oid|None}. ``out`` (a
    dict) receives ``event_seq`` when the live-update subsystem booked an
    event for the transition (docs/EVENTS.md §3) — the receive payload
    carries it so read-your-writes clients can pin on a sequence."""
    import sys

    from kart_tpu.transport.remote import _update_shallow

    updated = {}
    changes = []
    for upd in header.get("updates", []):
        ref, new = upd["ref"], upd.get("new")
        prev = repo.refs.get(ref)
        if new is None:
            if prev is not None:
                repo.refs.delete(ref)
            updated[ref] = None
        else:
            repo.refs.set(ref, new, log_message="push")
            updated[ref] = new
        if prev != new:
            changes.append((ref, prev, new))
    if header.get("shallow"):
        _update_shallow(repo, header["shallow"])
    # a ref moved: enumeration keys embed the ref fingerprint so new
    # requests re-key anyway, but drop the stale entries now rather than
    # letting them squat in the LRU until evicted
    with _enum_caches_lock:
        cache = _ENUM_CACHES.get(os.path.realpath(repo.gitdir))
    if cache is not None:
        cache.invalidate()
    # live-update events (docs/EVENTS.md): book the CDC emission for this
    # transition. sys.modules guard like the tile drop below — only a
    # serving process ever constructs an emitter, and a plain push target
    # must not pay the package import
    events_mod = sys.modules.get("kart_tpu.events")
    emitter_active = (
        events_mod is not None
        and events_mod.events_enabled()
        and events_mod.active_emitter(repo.gitdir) is not None
    )
    if emitter_active:
        seq = events_mod.notify_ref_updates(repo, changes)
        if seq is not None and out is not None:
            out["event_seq"] = seq
    # tile-cache keys are commit-pinned and can never go stale, but tiles
    # of a commit a ref just moved away from are probably dead weight —
    # the explicit drop hook releases their budget now (docs/TILES.md §3).
    # EXCEPT under an active event emitter: the warm-then-announce
    # protocol (docs/EVENTS.md §4) keeps serving the old tip's tiles until
    # the new tip's dirty tiles are pre-warmed, so dropping them here
    # would be the exact cold-tile storm the warmer exists to prevent.
    # sys.modules guard: a process that never imported the tiles machinery
    # cannot hold tile caches, and a push must not pay the package import
    tiles_cache = sys.modules.get("kart_tpu.tiles.cache")
    if tiles_cache is not None and not emitter_active:
        tiles_cache.invalidate_tile_caches(repo.gitdir)
    # query-result keys are commit-pinned too: same reasoning, same drop
    # (no warm-then-announce exemption — there is no query warmer)
    query_cache = sys.modules.get("kart_tpu.query.cache")
    if query_cache is not None:
        query_cache.invalidate_query_caches(repo.gitdir)
    return updated


def apply_ref_updates(repo, header):
    """CAS-validate then apply a receive-pack's ref updates (the pack must
    already be drained into the odb). All updates are validated before any
    is applied, so a rejected request leaves no ref moved. The caller holds
    whatever lock serialises concurrent pushes.

    -> ("ok", {ref: oid|None}) | ("conflict", msg) | ("bad", msg)."""
    rejection = validate_ref_updates(repo, header)
    if rejection is not None:
        return rejection
    return "ok", _apply_validated_updates(repo, header)
