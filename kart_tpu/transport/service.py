"""Transport-agnostic server-side operations.

The four verbs every kart_tpu transport speaks — ls-refs, fetch-pack,
fetch-blobs, receive-pack — implemented once over a repo, shared by the HTTP
server (:mod:`kart_tpu.transport.http`) and the stdio/ssh server
(:mod:`kart_tpu.transport.stdio`). The reference gets the same sharing from
git itself: upload-pack/receive-pack behave identically whether invoked by
``git daemon``, ssh, or https (kart/cli.py:211-253).

Receive-pack is *quarantined* (the analog of git's tmp_objdir): the pushed
pack drains into a temporary objects dir that borrows the main store via
alternates, and objects migrate into the live store only after the pack
checksum and every ref-update precondition pass — a failed, torn or
rejected push leaves the served store byte-identical.
"""

import os
import shutil
import tempfile
from contextlib import contextmanager, nullcontext

from kart_tpu import telemetry as tm
from kart_tpu.core.odb import ObjectMissing
from kart_tpu.core.refs import RefError, check_ref_format
from kart_tpu.transport.protocol import ObjectEnumerator

#: subdirectory of <gitdir>/objects holding in-flight push quarantines
QUARANTINE_SUBDIR = "quarantine"


def ls_refs_info(repo):
    """The advertisement: branch/tag tips, HEAD branch, shallow set."""
    from kart_tpu.transport.remote import read_shallow

    tm.incr("transport.server.requests", verb="ls-refs")

    heads = {
        ref[len("refs/heads/"):]: oid
        for ref, oid in repo.refs.iter_refs("refs/heads/")
    }
    tags = {
        ref[len("refs/tags/"):]: oid
        for ref, oid in repo.refs.iter_refs("refs/tags/")
    }
    kind, target = repo.refs.head_target()
    head_branch = (
        target[len("refs/heads/"):]
        if kind == "symbolic" and target.startswith("refs/heads/")
        else None
    )
    return {
        "heads": heads,
        "tags": tags,
        "head_branch": head_branch,
        "shallow": sorted(read_shallow(repo)),
    }


def make_fetch_enum(repo, req):
    """fetch-pack request dict -> (ObjectEnumerator, header_fn). The header
    callable reads the enumerator's counters, so evaluate it only after the
    pack drain."""
    from kart_tpu.transport.remote import read_shallow
    from kart_tpu.transport.http import have_closure

    tm.incr("transport.server.requests", verb="fetch-pack")
    if req.get("exclude"):
        # a non-empty exclusion list IS the resume protocol: the client is
        # completing a torn earlier transfer (docs/ROBUSTNESS.md §3)
        tm.incr("transport.server.fetch_resumes")
        tm.incr("transport.server.excluded_oids", len(req["exclude"]))
    blob_filter = None
    if req.get("filter"):
        from kart_tpu.spatial_filter import blob_filter_for_spec

        blob_filter = blob_filter_for_spec(repo, req["filter"])
    has = None
    if req.get("haves"):
        closure = have_closure(repo.odb, req["haves"], req.get("have_shallow", ()))
        has = closure.__contains__
    enum = ObjectEnumerator(
        repo.odb,
        req.get("wants", []),
        has=has,
        depth=req.get("depth"),
        blob_filter=blob_filter,
        sender_shallow=read_shallow(repo),
        # the resume protocol: exact oids the client already holds (salvaged
        # from a torn earlier transfer). Unlike `haves` these carry no
        # closure guarantee, so they suppress shipping object-by-object
        # without pruning the walk — a resumed fetch ships only the missing
        # remainder.
        exclude=frozenset(req.get("exclude") or ()),
    )

    def header():
        return {
            "shallow_boundary": sorted(enum.shallow_boundary),
            "object_count": enum.object_count,
            "omitted_blob_count": enum.omitted_blob_count,
        }

    return enum, header


def collect_blobs(repo, oids):
    """fetch-blobs (promisor backfill): -> (header, [(type, content)])."""
    tm.incr("transport.server.requests", verb="fetch-blobs")
    missing = []
    objects = []
    for oid in oids:
        try:
            objects.append(repo.odb.read_raw(oid))
        except ObjectMissing:
            missing.append(oid)
    return {"missing": missing}, objects


def current_branch_ref(repo):
    kind, target = repo.refs.head_target()
    return target if kind == "symbolic" else None


@contextmanager
def push_file_lock(repo):
    """Cross-process push lock over the gitdir: every ssh push spawns its
    own serve-stdio process, so an in-process lock can't serialise the
    compare-and-swap (two concurrent pushes would both pass the CAS check
    and one would be silently lost). The HTTP server holds its thread lock
    too, so mixed http+ssh pushes against one repo stay safe."""
    lock_path = os.path.join(repo.gitdir, ".push-lock")
    with open(lock_path, "w") as lock:
        try:
            import fcntl

            fcntl.flock(lock, fcntl.LOCK_EX)
        except ImportError:  # non-POSIX: best effort
            pass
        yield


def locked_ref_updates(repo, header):
    """apply_ref_updates under the cross-process push lock (back-compat
    entry point for callers that drained objects into the live store
    themselves; the servers use :func:`quarantined_receive`)."""
    with push_file_lock(repo):
        return apply_ref_updates(repo, header)


class ReceiveQuarantine:
    """A temporary objects dir under ``<gitdir>/objects/quarantine/``
    holding a pushed pack until it earns its way into the live store (the
    analog of git's receive-pack ``tmp_objdir``). The main store is wired
    in as an alternate, so connectivity/containment checks see quarantined
    + live objects together while the live store stays untouched."""

    def __init__(self, repo):
        from kart_tpu.core.odb import ObjectDb

        self.repo = repo
        base = os.path.join(repo.gitdir, "objects", QUARANTINE_SUBDIR)
        os.makedirs(base, exist_ok=True)
        self.dir = tempfile.mkdtemp(prefix="incoming-", dir=base)
        self.odb = ObjectDb(self.dir)
        self.odb.add_alternate(os.path.join(repo.gitdir, "objects"))

    def discard(self):
        """Drop everything received — the live store is byte-identical to
        before the push started."""
        shutil.rmtree(self.dir, ignore_errors=True)

    def migrate(self):
        """Move the quarantined pack(s) (and any loose strays) into the live
        store. Only called after the pack checksum and every ref-update
        precondition passed. Same-filesystem renames; ``.pack`` moves before
        its ``.idx`` so a concurrent reader never sees an idx without its
        pack."""
        objects_dir = self.repo.odb.objects_dir
        qpack = os.path.join(self.dir, "pack")
        if os.path.isdir(qpack):
            dst_pack = os.path.join(objects_dir, "pack")
            os.makedirs(dst_pack, exist_ok=True)
            names = sorted(
                os.listdir(qpack), key=lambda n: (n.endswith(".idx"), n)
            )
            for name in names:
                if name.startswith("."):
                    continue  # writer temp files never migrate
                os.replace(
                    os.path.join(qpack, name), os.path.join(dst_pack, name)
                )
        for prefix in os.listdir(self.dir):
            if len(prefix) != 2:
                continue
            src_d = os.path.join(self.dir, prefix)
            dst_d = os.path.join(objects_dir, prefix)
            os.makedirs(dst_d, exist_ok=True)
            for name in os.listdir(src_d):
                os.replace(
                    os.path.join(src_d, name), os.path.join(dst_d, name)
                )
        self.repo.odb.packs.refresh()
        self.discard()


def quarantined_receive(repo, header, pack_fp, *, thread_lock=None):
    """The full receive-pack verb: drain the pushed pack into quarantine,
    validate the ref updates, migrate, apply. A torn pack, a checksum
    mismatch, or any rejected precondition leaves the live store
    byte-identical (the quarantine is discarded); objects reach the live
    store only in the success path, under the push locks.

    -> ("ok", {ref: oid|None}) | ("conflict", msg) | ("bad", msg);
    transfer-level failures (torn/corrupt pack) raise instead, so each
    server reports them the same way as any other I/O failure."""
    from kart_tpu.transport.pack import read_pack

    tm.incr("transport.server.requests", verb="receive-pack")
    q = ReceiveQuarantine(repo)
    try:
        with tm.span("transport.receive_drain"), q.odb.bulk_pack():
            for obj_type, content in read_pack(pack_fp):
                q.odb.write_raw(obj_type, content)
    except BaseException:
        tm.incr("transport.server.receive_outcomes", outcome="torn")
        q.discard()
        raise
    try:
        with (thread_lock if thread_lock is not None else nullcontext()):
            with push_file_lock(repo):
                rejection = validate_ref_updates(
                    repo, header, contains=q.odb.contains
                )
                if rejection is not None:
                    tm.incr(
                        "transport.server.receive_outcomes",
                        outcome=rejection[0],
                    )
                    q.discard()
                    return rejection
                q.migrate()
                tm.incr("transport.server.receive_outcomes", outcome="ok")
                return "ok", _apply_validated_updates(repo, header)
    except BaseException:
        q.discard()  # no-op after a successful migrate
        raise


def validate_ref_updates(repo, header, *, contains=None):
    """Check every precondition of a receive-pack's ref updates without
    moving anything: refname hygiene, checked-out-branch protection, CAS
    against the current tips, and object connectivity via ``contains``
    (a quarantine's combined live+incoming check during a push).

    -> None when everything passes, else ("conflict"|"bad", msg)."""
    contains = contains or repo.odb.contains
    deny_current = (
        repo.workdir is not None
        and (repo.config.get("receive.denyCurrentBranch") or "refuse").lower()
        not in ("ignore", "false")
    )

    for upd in header.get("updates", []):
        ref, old, new = upd["ref"], upd.get("old"), upd.get("new")
        # wire-supplied names must be real refs — git's receive-pack rejects
        # non-refs/ names via check_refname_format; without this a push with
        # ref='config' or 'HEAD' would overwrite arbitrary gitdir files.
        try:
            check_ref_format(ref, require_refs_prefix=True)
        except RefError as e:
            return "bad", str(e)
        if deny_current and ref == current_branch_ref(repo):
            return (
                "conflict",
                f"Refusing to update checked-out branch {ref} (the server's "
                f"working copy would go out of sync). Serve a bare repo, or "
                f"set receive.denyCurrentBranch=ignore there.",
            )
        current = repo.refs.get(ref)
        if not upd.get("force") and current != old:
            return (
                "conflict",
                f"Ref {ref} moved (expected {old}, is {current}); "
                f"fetch first or use --force",
            )
        if new is not None and not contains(new):
            return "bad", f"Push incomplete: {new} not received"
    return None


def _apply_validated_updates(repo, header):
    """Apply pre-validated ref updates; -> {ref: oid|None}."""
    from kart_tpu.transport.remote import _update_shallow

    updated = {}
    for upd in header.get("updates", []):
        ref, new = upd["ref"], upd.get("new")
        if new is None:
            if repo.refs.get(ref) is not None:
                repo.refs.delete(ref)
            updated[ref] = None
        else:
            repo.refs.set(ref, new, log_message="push")
            updated[ref] = new
    if header.get("shallow"):
        _update_shallow(repo, header["shallow"])
    return updated


def apply_ref_updates(repo, header):
    """CAS-validate then apply a receive-pack's ref updates (the pack must
    already be drained into the odb). All updates are validated before any
    is applied, so a rejected request leaves no ref moved. The caller holds
    whatever lock serialises concurrent pushes.

    -> ("ok", {ref: oid|None}) | ("conflict", msg) | ("bad", msg)."""
    rejection = validate_ref_updates(repo, header)
    if rejection is not None:
        return rejection
    return "ok", _apply_validated_updates(repo, header)
