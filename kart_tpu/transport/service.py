"""Transport-agnostic server-side operations.

The four verbs every kart_tpu transport speaks — ls-refs, fetch-pack,
fetch-blobs, receive-pack — implemented once over a repo, shared by the HTTP
server (:mod:`kart_tpu.transport.http`) and the stdio/ssh server
(:mod:`kart_tpu.transport.stdio`). The reference gets the same sharing from
git itself: upload-pack/receive-pack behave identically whether invoked by
``git daemon``, ssh, or https (kart/cli.py:211-253).

Receive-pack is *quarantined* (the analog of git's tmp_objdir): the pushed
pack drains into a temporary objects dir that borrows the main store via
alternates, and objects migrate into the live store only after the pack
checksum and every ref-update precondition pass — a failed, torn or
rejected push leaves the served store byte-identical.
"""

import hashlib
import io
import json
import os
import shutil
import tempfile
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager, nullcontext

from kart_tpu import faults
from kart_tpu import telemetry as tm
from kart_tpu.core.odb import ObjectMissing
from kart_tpu.core.refs import RefError, check_ref_format
from kart_tpu.transport.protocol import ObjectEnumerator

#: subdirectory of <gitdir>/objects holding in-flight push quarantines
QUARANTINE_SUBDIR = "quarantine"

#: default byte budget for the per-repo pack-enumeration cache
#: (``KART_SERVE_ENUM_CACHE`` overrides; ``0`` disables caching entirely)
DEFAULT_ENUM_CACHE_BYTES = 256 * 1024 * 1024

#: how long a request waits on another request's in-flight walk for the
#: same cache key before giving up and walking independently (a wedged
#: filler must not wedge every client behind it)
SINGLEFLIGHT_TIMEOUT = 600.0


def ls_refs_info(repo):
    """The advertisement: branch/tag tips, HEAD branch, shallow set."""
    from kart_tpu.transport.remote import read_shallow

    tm.incr("transport.server.requests", verb="ls-refs")

    heads = {
        ref[len("refs/heads/"):]: oid
        for ref, oid in repo.refs.iter_refs("refs/heads/")
    }
    tags = {
        ref[len("refs/tags/"):]: oid
        for ref, oid in repo.refs.iter_refs("refs/tags/")
    }
    kind, target = repo.refs.head_target()
    head_branch = (
        target[len("refs/heads/"):]
        if kind == "symbolic" and target.startswith("refs/heads/")
        else None
    )
    return {
        "heads": heads,
        "tags": tags,
        "head_branch": head_branch,
        "shallow": sorted(read_shallow(repo)),
    }


def make_fetch_enum(repo, req, *, count_request=True, record_emitted=False):
    """fetch-pack request dict -> (ObjectEnumerator, header_fn). The header
    callable reads the enumerator's counters, so evaluate it only after the
    pack drain. ``count_request=False`` skips the request counters (the
    enum-cache front end :func:`serve_fetch_pack` counts them itself so a
    cache hit still shows up as a request)."""
    from kart_tpu.transport.remote import read_shallow
    from kart_tpu.transport.http import have_closure

    if count_request:
        _count_fetch_request(req)
    blob_filter = None
    if req.get("filter"):
        from kart_tpu.spatial_filter import blob_filter_for_spec

        blob_filter = blob_filter_for_spec(repo, req["filter"])
    has = None
    if req.get("haves"):
        closure = have_closure(repo.odb, req["haves"], req.get("have_shallow", ()))
        has = closure.__contains__
    enum = ObjectEnumerator(
        repo.odb,
        req.get("wants", []),
        has=has,
        depth=req.get("depth"),
        blob_filter=blob_filter,
        sender_shallow=read_shallow(repo),
        # the resume protocol: exact oids the client already holds (salvaged
        # from a torn earlier transfer). Unlike `haves` these carry no
        # closure guarantee, so they suppress shipping object-by-object
        # without pruning the walk — a resumed fetch ships only the missing
        # remainder.
        exclude=frozenset(req.get("exclude") or ()),
        record_emitted=record_emitted,
    )

    def header():
        return {
            "shallow_boundary": sorted(enum.shallow_boundary),
            "object_count": enum.object_count,
            "omitted_blob_count": enum.omitted_blob_count,
        }

    return enum, header


def _count_fetch_request(req):
    tm.incr("transport.server.requests", verb="fetch-pack")
    if req.get("exclude"):
        # a non-empty exclusion list IS the resume protocol: the client is
        # completing a torn earlier transfer (docs/ROBUSTNESS.md §3)
        tm.incr("transport.server.fetch_resumes")
        tm.incr("transport.server.excluded_oids", len(req["exclude"]))


def collect_blobs(repo, oids):
    """fetch-blobs (promisor backfill): -> (header, [(type, content)])."""
    tm.incr("transport.server.requests", verb="fetch-blobs")
    missing = []
    objects = []
    for oid in oids:
        try:
            objects.append(repo.odb.read_raw(oid))
        except ObjectMissing:
            missing.append(oid)
    return {"missing": missing}, objects


# ---------------------------------------------------------------------------
# pack-enumeration cache (docs/SERVING.md §2)
#
# The expensive half of serving a fetch is the reachability walk + tree
# recursion, and under concurrent clones of a hot repo every client used to
# re-pay it. The cache memoizes, per (wants, haves, shallow, depth, filter,
# excludes, ref-tips fingerprint) key: the final response header, a size
# estimate, and either the complete framed response bytes (small packs — a
# hit is a memcpy) or the ordered (type, oid) list the walk emitted (big
# packs — a hit replays object reads in order, no walk). Concurrent
# requests for an in-flight key block on the first walk (single-flight)
# instead of duplicating it. Ref updates invalidate: the fingerprint is
# part of the key, and apply_ref_updates additionally drops every entry so
# stale keys don't linger in the LRU.
# ---------------------------------------------------------------------------


class _CacheEntry:
    __slots__ = ("header", "data", "emitted", "nbytes", "etag")

    def __init__(self, header, data, emitted, etag):
        self.header = header
        self.data = data          # complete framed response bytes, or None
        self.emitted = emitted    # ordered (type, oid) replay list, or None
        self.etag = etag
        if data is not None:
            self.nbytes = len(data)
        else:
            # oid-list replay entry, charged at measured CPython cost:
            # ~89B hex-oid str + 56B tuple + interned type ref + list slot
            self.nbytes = 160 * len(emitted) + 1024


class _FillToken:
    """The right to publish one cache entry: handed to the single request
    that runs the walk for a key; every other request for that key waits on
    ``event`` until publish/abandon."""

    __slots__ = ("cache", "key", "event")

    def __init__(self, cache, key, event):
        self.cache = cache
        self.key = key
        self.event = event

    def publish(self, header, *, data=None, emitted=None):
        self.cache._publish(self, header, data, emitted)

    def abandon(self):
        self.cache._abandon(self)


class PackEnumCache:
    """LRU-by-byte-budget memo of fetch-pack enumerations with
    single-flight fill (one instance per served repo)."""

    def __init__(self, budget_bytes):
        self.budget = budget_bytes
        # a single entry may use at most budget/8 bytes as raw framed
        # bytes; larger packs store the oid replay list instead, so one
        # huge clone can't evict every hot entry
        self.bytes_cap = max(1, budget_bytes // 8)
        self._lock = threading.Lock()
        self._entries = OrderedDict()  # key -> _CacheEntry
        self._inflight = {}            # key -> threading.Event
        self._total = 0

    # -- lookup / single-flight --------------------------------------------

    def lookup_or_begin(self, key, timeout=SINGLEFLIGHT_TIMEOUT):
        """-> ("hit", entry) | ("fill", token) | ("fill", None).

        A miss returns a fill token (the caller runs the walk and must
        publish or abandon). While another request holds the token for the
        same key, callers block here; a publish turns them into hits. A
        filler wedged past ``timeout`` stops gating: waiters proceed with
        their own uncached walk (token None — nothing to publish)."""
        deadline = time.monotonic() + timeout
        waited = False
        while True:
            with self._lock:
                entry = self._entries.get(key)
                if entry is not None:
                    self._entries.move_to_end(key)
                    tm.incr("server.enum_cache.hits")
                    return "hit", entry
                event = self._inflight.get(key)
                if event is None:
                    self._inflight[key] = event = threading.Event()
                    tm.incr("server.enum_cache.misses")
                    return "fill", _FillToken(self, key, event)
            if not waited:
                waited = True
                tm.incr("server.enum_cache.singleflight_waits")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                tm.incr("server.enum_cache.misses")
                return "fill", None
            event.wait(min(remaining, 60.0))

    # -- fill side ----------------------------------------------------------

    def _publish(self, token, header, data, emitted):
        # the injectable failure of the cache-fill frame: a fault here must
        # poison nothing — the entry is never inserted (tests/test_faults.py)
        try:
            faults.fire("server.enum_cache")
        except BaseException:
            self._abandon(token)
            raise
        entry = _CacheEntry(header, data, emitted, _etag_for(token.key))
        with self._lock:
            self._inflight.pop(token.key, None)
            self._entries[token.key] = entry
            self._entries.move_to_end(token.key)
            self._total += entry.nbytes
            while self._total > self.budget and len(self._entries) > 1:
                _, evicted = self._entries.popitem(last=False)
                self._total -= evicted.nbytes
                tm.incr("server.enum_cache.evictions")
            tm.gauge_set("server.enum_cache.bytes", self._total)
        token.event.set()

    def _abandon(self, token):
        with self._lock:
            self._inflight.pop(token.key, None)
        token.event.set()

    # -- invalidation -------------------------------------------------------

    def evict(self, key):
        """Drop one entry (a replay that hit missing objects is poisoned —
        evicted, never served again)."""
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None:
                self._total -= entry.nbytes
                tm.incr("server.enum_cache.evictions")
                tm.gauge_set("server.enum_cache.bytes", self._total)

    def invalidate(self):
        """Drop everything (a ref update changed what any key may serve)."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._total = 0
            if n:
                tm.incr("server.enum_cache.evictions", n)
            tm.gauge_set("server.enum_cache.bytes", 0)


#: gitdir -> PackEnumCache for every repo this process serves (bounded: a
#: long-lived test process churning tmp repos must not accrete caches)
_ENUM_CACHES = OrderedDict()
_ENUM_CACHES_MAX = 64
_enum_caches_lock = threading.Lock()


def enum_cache_for(repo):
    """The (process-wide) enumeration cache serving ``repo``, or None when
    disabled via ``KART_SERVE_ENUM_CACHE=0``."""
    from kart_tpu.transport.retry import _env_int

    budget = _env_int("KART_SERVE_ENUM_CACHE", DEFAULT_ENUM_CACHE_BYTES)
    if budget <= 0:
        return None
    key = os.path.realpath(repo.gitdir)
    with _enum_caches_lock:
        cache = _ENUM_CACHES.get(key)
        if cache is None or cache.budget != budget:
            cache = _ENUM_CACHES[key] = PackEnumCache(budget)
        _ENUM_CACHES.move_to_end(key)
        while len(_ENUM_CACHES) > _ENUM_CACHES_MAX:
            _ENUM_CACHES.popitem(last=False)
    return cache


def refs_fingerprint(repo):
    """Digest of every (ref, oid) pair: part of each cache key, so a ref
    update — even by another process (an ssh push landing while the HTTP
    server runs) — changes every key rather than serving a stale walk."""
    h = hashlib.sha256()
    for ref, oid in sorted(repo.refs.iter_refs("refs/")):
        h.update(f"{ref}\0{oid}\n".encode())
    return h.hexdigest()


def _enum_cache_key(repo, req):
    payload = json.dumps(
        {
            # wants stay ordered: the walk order (and so the pack bytes)
            # follows them; everything set-like is canonicalised
            "wants": list(req.get("wants") or ()),
            "haves": sorted(req.get("haves") or ()),
            "have_shallow": sorted(req.get("have_shallow") or ()),
            "depth": req.get("depth"),
            "filter": req.get("filter"),
            "exclude": sorted(req.get("exclude") or ()),
            "refs": refs_fingerprint(repo),
        },
        sort_keys=True,
    )
    return hashlib.sha256(payload.encode()).hexdigest()


def _etag_for(key):
    """The strong validator for byte-range resume (If-Range): same key ⇒
    byte-identical response, and the key embeds the ref fingerprint."""
    return f'"{key[:32]}"'


class FetchPlan:
    """How to answer one fetch-pack request, produced by
    :func:`serve_fetch_pack`:

    * ``data`` set — a cache hit on stored framed bytes; send as-is.
    * otherwise — drain ``source`` through ``write_framed`` (``header`` is
      the deferred header callable), then ``publish()`` the spool /
      ``abandon()`` on failure. ``cached`` marks whether ``source`` is a
      cache replay (no walk ran).

    ``etag`` is the strong validator the transports hand out for
    byte-range resume; identical for hit, replay and fresh walks of the
    same key."""

    __slots__ = ("header", "data", "source", "etag", "cached", "_token", "_enum")

    def __init__(self, header, data, source, etag, cached, token=None, enum=None):
        self.header = header
        self.data = data
        self.source = source
        self.etag = etag
        self.cached = cached
        self._token = token
        self._enum = enum

    def publish(self, spool, length):
        """Memoize a freshly-spooled walk: small responses as their framed
        bytes, big ones as the ordered oid list (``spool`` is left at EOF;
        the caller rewinds)."""
        if self._token is None:
            return
        header = self.header() if callable(self.header) else self.header
        cache = self._token.cache
        if length <= cache.bytes_cap:
            spool.seek(0)
            self._token.publish(header, data=spool.read(length))
        elif self._enum is not None and self._enum.emitted is not None:
            self._token.publish(header, emitted=list(self._enum.emitted))
        else:
            self._token.abandon()

    def abandon(self):
        if self._token is not None:
            self._token.abandon()


def iter_recorded(odb, emitted):
    """Replay an enumeration from its recorded ``(type, oid)`` list:
    byte-identical object stream, zero walk. Blob runs go through the
    batched pack reader exactly like the original walk's flush."""
    i, n = 0, len(emitted)
    while i < n:
        obj_type, oid = emitted[i]
        if obj_type != "blob":
            yield obj_type, odb.read_raw(oid)[1]
            i += 1
            continue
        j = i
        while j < n and emitted[j][0] == "blob":
            j += 1
        run = [oid for _, oid in emitted[i:j]]
        SLICE = 1000
        for k in range(0, len(run), SLICE):
            chunk = run[k : k + SLICE]
            batch = odb.read_blobs_batch(chunk)
            for o in chunk:
                blob = batch.get(o)
                if blob is None:
                    _, blob = odb.read_raw(o)
                yield "blob", blob
        i = j


def _replay_source(cache, key, odb, emitted):
    """iter_recorded, with poisoned-entry hygiene: an entry whose objects
    have vanished (gc raced the cache) is evicted and the error surfaces —
    the next request re-walks instead of re-hitting the corpse."""
    try:
        yield from iter_recorded(odb, emitted)
    except Exception:
        cache.evict(key)
        raise


def serve_fetch_pack(repo, req, *, use_cache=True):
    """The cache-fronted fetch-pack verb: -> :class:`FetchPlan`.

    First request for a key runs (and records) the walk; concurrent
    requests for the same key block on it and hit; later requests hit
    the memo. With the cache disabled (``KART_SERVE_ENUM_CACHE=0``, or
    ``use_cache=False`` for single-connection servers where a memo could
    never be re-hit) the plan is a plain fresh walk — still carrying the
    deterministic etag, so byte-range resume works regardless."""
    _count_fetch_request(req)
    # an exclusion-bearing request is a one-shot resume: its key embeds the
    # exact oids that happened to land before a tear, so no second request
    # can ever hit it — memoizing would only evict hot repeatable entries.
    # The etag/deterministic-replay contract holds regardless.
    if req.get("exclude"):
        use_cache = False
    cache = enum_cache_for(repo) if use_cache else None
    key = _enum_cache_key(repo, req)
    etag = _etag_for(key)
    if cache is None:
        enum, header = make_fetch_enum(repo, req, count_request=False)
        return FetchPlan(header, None, enum, etag, False)
    mode, got = cache.lookup_or_begin(key)
    if mode == "hit":
        if got.data is not None:
            return FetchPlan(got.header, got.data, None, got.etag, True)
        return FetchPlan(
            got.header,
            None,
            _replay_source(cache, key, repo.odb, got.emitted),
            got.etag,
            True,
        )
    try:
        enum, header = make_fetch_enum(
            repo, req, count_request=False, record_emitted=True
        )
    except BaseException:
        # a pre-walk failure (malformed filter spec, unreadable shallow
        # file) must release the fill token, or every later request for
        # this key would block on an event nobody will ever set
        if got is not None:
            got.abandon()
        raise
    return FetchPlan(header, None, enum, etag, False, token=got, enum=enum)


def materialise_plan(plan):
    """-> (file-like at position 0, total length) of the complete framed
    response for ``plan``; fresh walks are spooled, published into the
    cache, and rewound. The caller owns (and must close) the handle."""
    from kart_tpu.transport.http import write_framed

    if plan.data is not None:
        with tm.span("server.enum_replay"):
            return io.BytesIO(plan.data), len(plan.data)
    span = "server.enum_replay" if plan.cached else "server.enum_walk"
    buf = tempfile.SpooledTemporaryFile(max_size=64 * 1024 * 1024)
    try:
        with tm.span(span):
            write_framed(buf, plan.header, plan.source)
        length = buf.tell()
        plan.publish(buf, length)
    except BaseException:
        plan.abandon()
        buf.close()
        raise
    buf.seek(0)
    return buf, length


def current_branch_ref(repo):
    kind, target = repo.refs.head_target()
    return target if kind == "symbolic" else None


@contextmanager
def push_file_lock(repo):
    """Cross-process push lock over the gitdir: every ssh push spawns its
    own serve-stdio process, so an in-process lock can't serialise the
    compare-and-swap (two concurrent pushes would both pass the CAS check
    and one would be silently lost). The HTTP server holds its thread lock
    too, so mixed http+ssh pushes against one repo stay safe."""
    lock_path = os.path.join(repo.gitdir, ".push-lock")
    with open(lock_path, "w") as lock:
        try:
            import fcntl

            fcntl.flock(lock, fcntl.LOCK_EX)
        except ImportError:  # non-POSIX: best effort
            pass
        yield


def locked_ref_updates(repo, header):
    """apply_ref_updates under the cross-process push lock (back-compat
    entry point for callers that drained objects into the live store
    themselves; the servers use :func:`quarantined_receive`)."""
    with push_file_lock(repo):
        return apply_ref_updates(repo, header)


class ReceiveQuarantine:
    """A temporary objects dir under ``<gitdir>/objects/quarantine/``
    holding a pushed pack until it earns its way into the live store (the
    analog of git's receive-pack ``tmp_objdir``). The main store is wired
    in as an alternate, so connectivity/containment checks see quarantined
    + live objects together while the live store stays untouched."""

    def __init__(self, repo):
        from kart_tpu.core.odb import ObjectDb

        self.repo = repo
        base = os.path.join(repo.gitdir, "objects", QUARANTINE_SUBDIR)
        os.makedirs(base, exist_ok=True)
        self.dir = tempfile.mkdtemp(prefix="incoming-", dir=base)
        self.odb = ObjectDb(self.dir)
        self.odb.add_alternate(os.path.join(repo.gitdir, "objects"))

    def discard(self):
        """Drop everything received — the live store is byte-identical to
        before the push started."""
        shutil.rmtree(self.dir, ignore_errors=True)

    def migrate(self):
        """Move the quarantined pack(s) (and any loose strays) into the live
        store. Only called after the pack checksum and every ref-update
        precondition passed. Same-filesystem renames; ``.pack`` moves before
        its ``.idx`` so a concurrent reader never sees an idx without its
        pack."""
        objects_dir = self.repo.odb.objects_dir
        qpack = os.path.join(self.dir, "pack")
        if os.path.isdir(qpack):
            dst_pack = os.path.join(objects_dir, "pack")
            os.makedirs(dst_pack, exist_ok=True)
            names = sorted(
                os.listdir(qpack), key=lambda n: (n.endswith(".idx"), n)
            )
            for name in names:
                if name.startswith("."):
                    continue  # writer temp files never migrate
                os.replace(
                    os.path.join(qpack, name), os.path.join(dst_pack, name)
                )
        for prefix in os.listdir(self.dir):
            if len(prefix) != 2:
                continue
            src_d = os.path.join(self.dir, prefix)
            dst_d = os.path.join(objects_dir, prefix)
            os.makedirs(dst_d, exist_ok=True)
            for name in os.listdir(src_d):
                os.replace(
                    os.path.join(src_d, name), os.path.join(dst_d, name)
                )
        self.repo.odb.packs.refresh()
        self.discard()


def quarantined_receive(repo, header, pack_fp, *, thread_lock=None):
    """The full receive-pack verb: drain the pushed pack into quarantine,
    validate the ref updates, migrate, apply. A torn pack, a checksum
    mismatch, or any rejected precondition leaves the live store
    byte-identical (the quarantine is discarded); objects reach the live
    store only in the success path, under the push locks.

    -> ("ok", {ref: oid|None}) | ("conflict", msg) | ("bad", msg);
    transfer-level failures (torn/corrupt pack) raise instead, so each
    server reports them the same way as any other I/O failure."""
    from kart_tpu.transport.pack import read_pack

    tm.incr("transport.server.requests", verb="receive-pack")
    q = ReceiveQuarantine(repo)
    try:
        with tm.span("transport.receive_drain"), q.odb.bulk_pack():
            for obj_type, content in read_pack(pack_fp):
                q.odb.write_raw(obj_type, content)
    except BaseException:
        tm.incr("transport.server.receive_outcomes", outcome="torn")
        q.discard()
        raise
    try:
        with (thread_lock if thread_lock is not None else nullcontext()):
            with push_file_lock(repo):
                rejection = validate_ref_updates(
                    repo, header, contains=q.odb.contains
                )
                if rejection is not None:
                    tm.incr(
                        "transport.server.receive_outcomes",
                        outcome=rejection[0],
                    )
                    q.discard()
                    return rejection
                q.migrate()
                tm.incr("transport.server.receive_outcomes", outcome="ok")
                return "ok", _apply_validated_updates(repo, header)
    except BaseException:
        q.discard()  # no-op after a successful migrate
        raise


def validate_ref_updates(repo, header, *, contains=None):
    """Check every precondition of a receive-pack's ref updates without
    moving anything: refname hygiene, checked-out-branch protection, CAS
    against the current tips, and object connectivity via ``contains``
    (a quarantine's combined live+incoming check during a push).

    -> None when everything passes, else ("conflict"|"bad", msg)."""
    contains = contains or repo.odb.contains
    deny_current = (
        repo.workdir is not None
        and (repo.config.get("receive.denyCurrentBranch") or "refuse").lower()
        not in ("ignore", "false")
    )

    for upd in header.get("updates", []):
        ref, old, new = upd["ref"], upd.get("old"), upd.get("new")
        # wire-supplied names must be real refs — git's receive-pack rejects
        # non-refs/ names via check_refname_format; without this a push with
        # ref='config' or 'HEAD' would overwrite arbitrary gitdir files.
        try:
            check_ref_format(ref, require_refs_prefix=True)
        except RefError as e:
            return "bad", str(e)
        if deny_current and ref == current_branch_ref(repo):
            return (
                "conflict",
                f"Refusing to update checked-out branch {ref} (the server's "
                f"working copy would go out of sync). Serve a bare repo, or "
                f"set receive.denyCurrentBranch=ignore there.",
            )
        current = repo.refs.get(ref)
        if not upd.get("force") and current != old:
            return (
                "conflict",
                f"Ref {ref} moved (expected {old}, is {current}); "
                f"fetch first or use --force",
            )
        if new is not None and not contains(new):
            return "bad", f"Push incomplete: {new} not received"
    return None


def _apply_validated_updates(repo, header):
    """Apply pre-validated ref updates; -> {ref: oid|None}."""
    from kart_tpu.transport.remote import _update_shallow

    updated = {}
    for upd in header.get("updates", []):
        ref, new = upd["ref"], upd.get("new")
        if new is None:
            if repo.refs.get(ref) is not None:
                repo.refs.delete(ref)
            updated[ref] = None
        else:
            repo.refs.set(ref, new, log_message="push")
            updated[ref] = new
    if header.get("shallow"):
        _update_shallow(repo, header["shallow"])
    # a ref moved: enumeration keys embed the ref fingerprint so new
    # requests re-key anyway, but drop the stale entries now rather than
    # letting them squat in the LRU until evicted
    with _enum_caches_lock:
        cache = _ENUM_CACHES.get(os.path.realpath(repo.gitdir))
    if cache is not None:
        cache.invalidate()
    return updated


def apply_ref_updates(repo, header):
    """CAS-validate then apply a receive-pack's ref updates (the pack must
    already be drained into the odb). All updates are validated before any
    is applied, so a rejected request leaves no ref moved. The caller holds
    whatever lock serialises concurrent pushes.

    -> ("ok", {ref: oid|None}) | ("conflict", msg) | ("bad", msg)."""
    rejection = validate_ref_updates(repo, header)
    if rejection is not None:
        return rejection
    return "ok", _apply_validated_updates(repo, header)
