"""HTTP transport: serve and consume the kartpack wire format over HTTP.

The reference speaks the git smart protocol over https/ssh via its vendored
git (kart/cli.py:211-253, git upload-pack / receive-pack).  This module is
the native equivalent over plain HTTP — a deliberately small JSON + kartpack
API that preserves the same semantics:

* want/have negotiation (client declares its ref tips; the server walks
  reachability and ships only what's missing),
* shallow clone/fetch (client shallow set respected; new boundary returned),
* server-side spatially-filtered partial clone (the filter argument is
  evaluated on the server against its envelope index — the analog of the
  reference's ``filter_extension_spatial`` upload-pack plugin,
  vendor/spatial-filter/spatial_filter.cpp:212-260),
* promisor backfill (batch blob fetch by oid).

Endpoints (all JSON unless noted):

    GET  <base>/api/v1/refs
        -> {"heads": {...}, "tags": {...}, "head_branch": ..., "shallow": [...]}
    POST <base>/api/v1/fetch-pack
        {"wants": [...], "haves": [...], "have_shallow": [...],
         "depth": N|null, "filter": "w,s,e,n"|null}
        -> framed response: 8-byte big-endian header length, JSON header
           {"shallow_boundary": [...], "object_count": N}, kartpack bytes
    POST <base>/api/v1/fetch-blobs
        {"oids": [...]} -> framed response (header + kartpack)
    POST <base>/api/v1/receive-pack
        framed request: 8-byte header length, JSON header
        {"updates": [{"ref", "old", "new", "force"}], "shallow": [...]},
        kartpack bytes -> {"updated": {...}} (409 on a rejected update)

There is no authentication — this is a LAN/localhost collaboration server,
like ``git daemon``. Put a reverse proxy in front for anything else.
"""

import json
import os
import struct
import tempfile
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.error import HTTPError
from urllib.parse import urlsplit
from urllib.request import Request, urlopen

from kart_tpu import telemetry as tm
from kart_tpu.core.odb import ObjectMissing
from kart_tpu.transport.pack import read_pack, write_pack

API = "/api/v1"
_HEADER_LEN = struct.Struct(">Q")

#: default per-socket timeout (connect + each recv) for the quick JSON GETs
#: — a dead server fails fast instead of hanging forever. Every verb flow
#: starts with ls_refs, so this is the fail-fast gate for the whole fetch/
#: push/clone. Env KART_HTTP_TIMEOUT overrides both this and the POST
#: budget below.
DEFAULT_HTTP_TIMEOUT = 30.0

#: default for the pack-carrying POSTs: the server spools its ENTIRE
#: response pack (and, for receive-pack, quarantines + migrates + applies
#: refs) before its first response byte, so the time-to-first-byte scales
#: with repo size — a 30s budget would abort healthy large transfers, and a
#: push timed out client-side after the server committed would report a
#: false failure with refs already moved.
DEFAULT_HTTP_POST_TIMEOUT = 600.0

#: HTTP statuses that recur only transiently (proxy reload, backend
#: restart, throttling) — the module recommends a reverse proxy for
#: production, so these must stay retryable
_TRANSIENT_HTTP_STATUSES = (429, 502, 503, 504)


def http_timeout(default=DEFAULT_HTTP_TIMEOUT):
    try:
        return float(os.environ.get("KART_HTTP_TIMEOUT", default))
    except (TypeError, ValueError):
        return default


class HttpTransportError(ValueError):
    """Transport failure. ``transient`` marks connection-level failures a
    bounded retry may recover from (vs server-reported op errors, which
    recur deterministically); ``pre_write`` marks failures that provably
    happened before any request byte reached the server, the only kind a
    non-idempotent verb retries."""

    transient = False
    pre_write = False

    def __init__(self, message, *, transient=None, pre_write=None):
        super().__init__(message)
        if transient is not None:
            self.transient = transient
        if pre_write is not None:
            self.pre_write = pre_write


# ---------------------------------------------------------------------------
# framing: [8-byte header length][JSON header][kartpack bytes]
# ---------------------------------------------------------------------------


def write_framed(fp, header, pack_source):
    """pack_source: iterable of (type, content) -> frames header + pack into
    fp. The pack is buffered (spooled) first, and a callable header is only
    evaluated after that drain — so the header can carry enumeration results
    (shallow boundary, counts) without materialising the objects in RAM."""
    with tempfile.SpooledTemporaryFile(max_size=64 * 1024 * 1024) as buf:
        write_pack(buf, iter(pack_source))
        if callable(header):
            header = header()
        raw_header = json.dumps(header).encode()
        fp.write(_HEADER_LEN.pack(len(raw_header)))
        fp.write(raw_header)
        buf.seek(0)
        while True:
            chunk = buf.read(1 << 20)
            if not chunk:
                break
            fp.write(chunk)


def read_framed(fp):
    """-> (header dict, file-like positioned at the pack)."""
    raw = fp.read(_HEADER_LEN.size)
    if len(raw) != _HEADER_LEN.size:
        raise HttpTransportError("Truncated framed response", transient=True)
    (n,) = _HEADER_LEN.unpack(raw)
    if n > 1 << 24:
        raise HttpTransportError("Framed header implausibly large")
    header = json.loads(fp.read(n).decode())
    return header, fp


# ---------------------------------------------------------------------------
# negotiation helper: what does the peer (claim to) have?
# ---------------------------------------------------------------------------


def have_closure(odb, haves, have_shallow=()):
    """Object oids the peer has, given its declared ref tips: every commit
    reachable from the tips (stopping at the peer's shallow boundary, where
    its history is known-truncated), plus the full tree closure of each tip
    commit — tip trees prune the bulk of unchanged subtrees/blobs from a
    typical tip-to-tip transfer."""
    have_shallow = set(have_shallow)
    closure = set()
    frontier = [o for o in haves if o]
    tips = list(frontier)
    while frontier:
        oid = frontier.pop()
        if oid in closure:
            continue
        try:
            commit = odb.read_commit(oid)
        except (ObjectMissing, KeyError, ValueError):
            continue
        closure.add(oid)
        if oid in have_shallow:
            continue  # peer's history stops here
        frontier.extend(commit.parents)

    def add_tree(tree_oid):
        if tree_oid in closure:
            return
        closure.add(tree_oid)
        try:
            entries = odb.read_tree_entries(tree_oid)
        except (ObjectMissing, KeyError, ValueError):
            return
        for e in entries:
            if e.is_tree:
                add_tree(e.oid)
            else:
                closure.add(e.oid)

    for tip in tips:
        try:
            add_tree(odb.read_commit(tip).tree)
        except (ObjectMissing, KeyError, ValueError):
            continue
    return closure


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class KartRequestHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "kart-tpu-serve/1"

    @property
    def repo(self):
        return self.server.kart_repo

    def log_message(self, fmt, *args):  # route through logging, not stderr
        import logging

        logging.getLogger("kart_tpu.serve").debug(fmt, *args)

    # -- plumbing -----------------------------------------------------------

    def _json(self, status, payload):
        raw = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _framed(self, header, pack_source):
        # spool to disk past 64MB — never hold a whole pack in RAM per
        # request (ThreadingHTTPServer multiplies that by concurrent clients)
        with tempfile.SpooledTemporaryFile(max_size=64 * 1024 * 1024) as buf:
            write_framed(buf, header, pack_source)
            length = buf.tell()
            tm.incr("transport.server.bytes_sent", length)
            buf.seek(0)
            self.send_response(200)
            self.send_header("Content-Type", "application/x-kartpack")
            self.send_header("Content-Length", str(length))
            self.end_headers()
            while True:
                chunk = buf.read(1 << 20)
                if not chunk:
                    break
                self.wfile.write(chunk)

    def _read_body(self):
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n)

    def _read_body_spooled(self):
        n = int(self.headers.get("Content-Length", 0))
        tm.incr("transport.server.bytes_received", n)
        buf = tempfile.SpooledTemporaryFile(max_size=64 * 1024 * 1024)
        remaining = n
        while remaining > 0:
            chunk = self.rfile.read(min(remaining, 1 << 20))
            if not chunk:
                break
            buf.write(chunk)
            remaining -= len(chunk)
        buf.seek(0)
        return buf

    # -- routes -------------------------------------------------------------

    def do_GET(self):
        try:
            path = urlsplit(self.path).path.rstrip("/")
            if path == f"{API}/refs":
                return self._handle_refs()
            if path == f"{API}/stats":
                return self._handle_stats()
            self._json(404, {"error": f"No such endpoint: {self.path}"})
        except Exception as e:
            self._json(500, {"error": f"{type(e).__name__}: {e}"})

    def do_POST(self):
        path = urlsplit(self.path).path.rstrip("/")
        try:
            if path == f"{API}/fetch-pack":
                return self._handle_fetch_pack()
            if path == f"{API}/fetch-blobs":
                return self._handle_fetch_blobs()
            if path == f"{API}/receive-pack":
                return self._handle_receive_pack()
            self._json(404, {"error": f"No such endpoint: {self.path}"})
        except Exception as e:  # surface server errors to the client
            self._json(500, {"error": f"{type(e).__name__}: {e}"})

    def _handle_refs(self):
        from kart_tpu.transport.service import ls_refs_info

        self._json(200, ls_refs_info(self.repo))

    def _handle_stats(self):
        """Prometheus-style text exposition of this server process's metric
        registry (`kart stats <url>` reads this)."""
        from kart_tpu.telemetry import sinks

        tm.incr("transport.server.requests", verb="stats")
        raw = sinks.prometheus_text().encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _handle_fetch_pack(self):
        from kart_tpu.transport.service import make_fetch_enum

        req = json.loads(self._read_body().decode() or "{}")
        # the enumerator streams straight into the spooled pack; the header
        # callable reads its counters only after the drain
        enum, header = make_fetch_enum(self.repo, req)
        self._framed(header, enum)

    def _handle_fetch_blobs(self):
        from kart_tpu.transport.service import collect_blobs

        req = json.loads(self._read_body().decode() or "{}")
        header, objects = collect_blobs(self.repo, req.get("oids", []))
        self._framed(header, objects)

    def _handle_receive_pack(self):
        from kart_tpu.transport.service import quarantined_receive

        # the pack drains into a quarantine objects dir and migrates into
        # the live store only after checksum + ref preconditions pass — a
        # torn or rejected push leaves the store byte-identical. The CAS is
        # atomic across handler threads AND across processes (an ssh push
        # is a separate serve-stdio process): thread lock + gitdir file
        # lock, both held inside quarantined_receive.
        with self._read_body_spooled() as body:
            header, pack_fp = read_framed(body)
            status, payload = quarantined_receive(
                self.repo, header, pack_fp, thread_lock=self.server.push_lock
            )
        if status == "ok":
            self._json(200, {"updated": payload})
        else:
            self._json(409 if status == "conflict" else 400, {"error": payload})


def make_server(repo, host="127.0.0.1", port=0):
    """-> ThreadingHTTPServer serving `repo`; port 0 picks a free port.

    Serving turns metrics on (a server without observable counters is
    undebuggable in production — the registry feeds ``GET /api/v1/stats``)
    and configures the shared ``kart_tpu`` logger so a spawned server
    honours ``KART_LOG`` without the CLI having run."""
    tm.configure_logging()
    tm.enable(metrics=True)
    server = ThreadingHTTPServer((host, port), KartRequestHandler)
    server.kart_repo = repo
    server.push_lock = threading.Lock()
    return server


def serve(repo, host="127.0.0.1", port=8470, *, in_thread=False):
    """Run the collaboration server (blocking unless in_thread)."""
    server = make_server(repo, host, port)
    if in_thread:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        return server
    try:
        server.serve_forever()
    finally:
        server.server_close()


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class HttpRemote:
    """Client for the API above; the HTTP implementation of the transport
    verbs remote.py's fetch/push/clone are written against.

    Fault tolerance: every verb runs under ``retry`` (a
    :class:`~kart_tpu.transport.retry.RetryPolicy`). The idempotent verbs
    (``ls_refs``, ``fetch_pack``, ``fetch_blobs``) retry on any transient
    failure — and ``fetch_pack`` *resumes*: objects salvaged from a torn
    stream are excluded from the re-negotiation, so a retry transfers only
    the missing remainder. ``receive_pack`` retries only when the
    connection was never established (the server provably saw nothing)."""

    def __init__(self, url, retry=None):
        from kart_tpu.transport.retry import RetryPolicy

        self.base = url.rstrip("/")
        self.retry = retry if retry is not None else RetryPolicy.from_config()

    def close(self):
        """No persistent connection; symmetric with StdioRemote so callers
        can close any network client unconditionally."""

    def reset(self, *_):
        """No per-connection state to tear down between retries."""

    def _get(self, path):
        try:
            with urlopen(Request(self.base + path), timeout=http_timeout()) as resp:
                return json.loads(resp.read().decode())
        except HTTPError as e:
            raise HttpTransportError(
                f"Remote {self.base!r} error: {e}",
                transient=e.code in _TRANSIENT_HTTP_STATUSES,
            )
        except OSError as e:
            # connection-level (refused / DNS / socket timeout): transient,
            # and for GETs necessarily pre-write
            raise HttpTransportError(
                f"Cannot reach remote {self.base!r}: {e}",
                transient=True,
                pre_write=True,
            )

    def _post(self, path, data, *, raw=False, length=None):
        """data: JSON-able object, or (raw=True) bytes / a file-like with an
        explicit length."""
        headers = {
            "Content-Type": "application/x-kartpack" if raw else "application/json"
        }
        body = data if raw else json.dumps(data).encode()
        if length is not None:
            headers["Content-Length"] = str(length)
        req = Request(self.base + path, data=body, headers=headers, method="POST")
        try:
            return urlopen(req, timeout=http_timeout(DEFAULT_HTTP_POST_TIMEOUT))
        except HTTPError as e:
            # the server answered: usually a deterministic op error, except
            # the proxy-layer statuses that recur only transiently
            detail = ""
            try:
                detail = json.loads(e.read().decode()).get("error", "")
            except (OSError, ValueError, AttributeError):
                # non-JSON / unreadable error body: the HTTP status below
                # is still reported
                pass
            raise HttpTransportError(
                f"Remote {self.base!r} error: {detail or e}",
                transient=e.code in _TRANSIENT_HTTP_STATUSES,
            )
        except OSError as e:
            reason = getattr(e, "reason", e)
            raise HttpTransportError(
                f"Remote {self.base!r} error: {e}",
                transient=True,
                # connect refused ⇒ no request byte ever left this process,
                # so even a non-idempotent verb may safely retry
                pre_write=isinstance(reason, ConnectionRefusedError),
            )

    # -- verbs --------------------------------------------------------------

    def ls_refs(self):
        return self.retry.call(
            lambda: self._get(f"{API}/refs"), label="ls-refs", on_retry=self.reset
        )

    def fetch_pack(self, dst_repo, wants, *, haves=(), have_shallow=(),
                   depth=None, filter_spec=None, exclude=None):
        """-> header dict; objects are written straight into dst_repo.

        Resumable: objects landed before a disconnect are salvaged into a
        finished pack, and the retry re-negotiates with those oids excluded
        so the server ships only the remainder. ``exclude`` seeds the
        exclusion set (a cross-process resume passes the oids salvaged by
        the earlier, killed process)."""
        from kart_tpu.transport.retry import drain_pack_salvaging, exclude_arg

        # a set is shared in place, so the caller sees everything salvaged
        # even when every attempt fails (cross-process resume records it)
        received = exclude if isinstance(exclude, set) else set(exclude or ())

        def attempt():
            resp = self._post(
                f"{API}/fetch-pack",
                {
                    "wants": list(wants),
                    "haves": list(haves),
                    "have_shallow": sorted(have_shallow),
                    "depth": depth,
                    "filter": filter_spec,
                    "exclude": exclude_arg(received),
                },
            )
            with resp:
                header, pack_fp = read_framed(resp)
                drain_pack_salvaging(dst_repo.odb, pack_fp, received)
            return header

        return self.retry.call(attempt, label="fetch-pack", on_retry=self.reset)

    def fetch_blobs(self, dst_repo, oids):
        from kart_tpu.transport.retry import drain_pack_salvaging

        received = set()

        def attempt():
            # a retry re-requests only what the torn attempt didn't land
            want = [o for o in oids if o not in received]
            if not want:
                return {}
            resp = self._post(f"{API}/fetch-blobs", {"oids": want})
            with resp:
                header, pack_fp = read_framed(resp)
                drain_pack_salvaging(dst_repo.odb, pack_fp, received)
            return header

        header = self.retry.call(attempt, label="fetch-blobs", on_retry=self.reset)
        if header.get("missing"):
            raise HttpTransportError(
                f"Remote is missing promised objects: {header['missing'][:5]}"
            )
        return len(received)

    def receive_pack(self, objects, updates, *, shallow=()):
        """objects: iterable of (type, content); updates: [{ref, old, new,
        force}]; shallow: oids or a callable evaluated after the objects
        drain (an ObjectEnumerator's boundary is only final then).
        -> {ref: oid|None} from the server.

        Not idempotent: only pre-write failures (connect refused — the
        server saw no byte of this request) are retried."""
        from kart_tpu.transport.retry import is_pre_write

        with tempfile.SpooledTemporaryFile(max_size=64 * 1024 * 1024) as buf:
            write_framed(
                buf,
                lambda: {
                    "updates": updates,
                    "shallow": sorted(shallow() if callable(shallow) else shallow),
                },
                objects,
            )
            length = buf.tell()

            def attempt():
                buf.seek(0)
                return self._post(
                    f"{API}/receive-pack", buf, raw=True, length=length
                )

            resp = self.retry.call(
                attempt, label="receive-pack", retryable=is_pre_write,
                on_retry=self.reset,
            )
        with resp:
            return json.loads(resp.read().decode())["updated"]
