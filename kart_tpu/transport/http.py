"""HTTP transport: serve and consume the kartpack wire format over HTTP.

The reference speaks the git smart protocol over https/ssh via its vendored
git (kart/cli.py:211-253, git upload-pack / receive-pack).  This module is
the native equivalent over plain HTTP — a deliberately small JSON + kartpack
API that preserves the same semantics:

* want/have negotiation (client declares its ref tips; the server walks
  reachability and ships only what's missing),
* shallow clone/fetch (client shallow set respected; new boundary returned),
* server-side spatially-filtered partial clone (the filter argument is
  evaluated on the server against its envelope index — the analog of the
  reference's ``filter_extension_spatial`` upload-pack plugin,
  vendor/spatial-filter/spatial_filter.cpp:212-260),
* promisor backfill (batch blob fetch by oid).

Endpoints (all JSON unless noted):

    GET  <base>/api/v1/refs
        -> {"heads": {...}, "tags": {...}, "head_branch": ..., "shallow": [...]}
    POST <base>/api/v1/fetch-pack
        {"wants": [...], "haves": [...], "have_shallow": [...],
         "depth": N|null, "filter": "w,s,e,n"|null}
        -> framed response: 8-byte big-endian header length, JSON header
           {"shallow_boundary": [...], "object_count": N}, kartpack bytes
    POST <base>/api/v1/fetch-blobs
        {"oids": [...]} -> framed response (header + kartpack)
    POST <base>/api/v1/receive-pack
        framed request: 8-byte header length, JSON header
        {"updates": [{"ref", "old", "new", "force"}], "shallow": [...]},
        kartpack bytes -> {"updated": {...}} (409 on a rejected update)

There is no authentication — this is a LAN/localhost collaboration server,
like ``git daemon``. Put a reverse proxy in front for anything else.
"""

import json
import struct
import tempfile
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlsplit
from urllib.request import Request, urlopen

from kart_tpu.core.odb import ObjectMissing
from kart_tpu.transport.pack import read_pack, write_pack

API = "/api/v1"
_HEADER_LEN = struct.Struct(">Q")


class HttpTransportError(ValueError):
    pass


# ---------------------------------------------------------------------------
# framing: [8-byte header length][JSON header][kartpack bytes]
# ---------------------------------------------------------------------------


def write_framed(fp, header, pack_source):
    """pack_source: iterable of (type, content) -> frames header + pack into
    fp. The pack is buffered (spooled) first, and a callable header is only
    evaluated after that drain — so the header can carry enumeration results
    (shallow boundary, counts) without materialising the objects in RAM."""
    with tempfile.SpooledTemporaryFile(max_size=64 * 1024 * 1024) as buf:
        write_pack(buf, iter(pack_source))
        if callable(header):
            header = header()
        raw_header = json.dumps(header).encode()
        fp.write(_HEADER_LEN.pack(len(raw_header)))
        fp.write(raw_header)
        buf.seek(0)
        while True:
            chunk = buf.read(1 << 20)
            if not chunk:
                break
            fp.write(chunk)


def read_framed(fp):
    """-> (header dict, file-like positioned at the pack)."""
    raw = fp.read(_HEADER_LEN.size)
    if len(raw) != _HEADER_LEN.size:
        raise HttpTransportError("Truncated framed response")
    (n,) = _HEADER_LEN.unpack(raw)
    if n > 1 << 24:
        raise HttpTransportError("Framed header implausibly large")
    header = json.loads(fp.read(n).decode())
    return header, fp


# ---------------------------------------------------------------------------
# negotiation helper: what does the peer (claim to) have?
# ---------------------------------------------------------------------------


def have_closure(odb, haves, have_shallow=()):
    """Object oids the peer has, given its declared ref tips: every commit
    reachable from the tips (stopping at the peer's shallow boundary, where
    its history is known-truncated), plus the full tree closure of each tip
    commit — tip trees prune the bulk of unchanged subtrees/blobs from a
    typical tip-to-tip transfer."""
    have_shallow = set(have_shallow)
    closure = set()
    frontier = [o for o in haves if o]
    tips = list(frontier)
    while frontier:
        oid = frontier.pop()
        if oid in closure:
            continue
        try:
            commit = odb.read_commit(oid)
        except (ObjectMissing, KeyError, ValueError):
            continue
        closure.add(oid)
        if oid in have_shallow:
            continue  # peer's history stops here
        frontier.extend(commit.parents)

    def add_tree(tree_oid):
        if tree_oid in closure:
            return
        closure.add(tree_oid)
        try:
            entries = odb.read_tree_entries(tree_oid)
        except (ObjectMissing, KeyError, ValueError):
            return
        for e in entries:
            if e.is_tree:
                add_tree(e.oid)
            else:
                closure.add(e.oid)

    for tip in tips:
        try:
            add_tree(odb.read_commit(tip).tree)
        except (ObjectMissing, KeyError, ValueError):
            continue
    return closure


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class KartRequestHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "kart-tpu-serve/1"

    @property
    def repo(self):
        return self.server.kart_repo

    def log_message(self, fmt, *args):  # route through logging, not stderr
        import logging

        logging.getLogger("kart_tpu.serve").debug(fmt, *args)

    # -- plumbing -----------------------------------------------------------

    def _json(self, status, payload):
        raw = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _framed(self, header, pack_source):
        # spool to disk past 64MB — never hold a whole pack in RAM per
        # request (ThreadingHTTPServer multiplies that by concurrent clients)
        with tempfile.SpooledTemporaryFile(max_size=64 * 1024 * 1024) as buf:
            write_framed(buf, header, pack_source)
            length = buf.tell()
            buf.seek(0)
            self.send_response(200)
            self.send_header("Content-Type", "application/x-kartpack")
            self.send_header("Content-Length", str(length))
            self.end_headers()
            while True:
                chunk = buf.read(1 << 20)
                if not chunk:
                    break
                self.wfile.write(chunk)

    def _read_body(self):
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n)

    def _read_body_spooled(self):
        n = int(self.headers.get("Content-Length", 0))
        buf = tempfile.SpooledTemporaryFile(max_size=64 * 1024 * 1024)
        remaining = n
        while remaining > 0:
            chunk = self.rfile.read(min(remaining, 1 << 20))
            if not chunk:
                break
            buf.write(chunk)
            remaining -= len(chunk)
        buf.seek(0)
        return buf

    # -- routes -------------------------------------------------------------

    def do_GET(self):
        try:
            if urlsplit(self.path).path.rstrip("/") == f"{API}/refs":
                return self._handle_refs()
            self._json(404, {"error": f"No such endpoint: {self.path}"})
        except Exception as e:
            self._json(500, {"error": f"{type(e).__name__}: {e}"})

    def do_POST(self):
        path = urlsplit(self.path).path.rstrip("/")
        try:
            if path == f"{API}/fetch-pack":
                return self._handle_fetch_pack()
            if path == f"{API}/fetch-blobs":
                return self._handle_fetch_blobs()
            if path == f"{API}/receive-pack":
                return self._handle_receive_pack()
            self._json(404, {"error": f"No such endpoint: {self.path}"})
        except Exception as e:  # surface server errors to the client
            self._json(500, {"error": f"{type(e).__name__}: {e}"})

    def _handle_refs(self):
        from kart_tpu.transport.service import ls_refs_info

        self._json(200, ls_refs_info(self.repo))

    def _handle_fetch_pack(self):
        from kart_tpu.transport.service import make_fetch_enum

        req = json.loads(self._read_body().decode() or "{}")
        # the enumerator streams straight into the spooled pack; the header
        # callable reads its counters only after the drain
        enum, header = make_fetch_enum(self.repo, req)
        self._framed(header, enum)

    def _handle_fetch_blobs(self):
        from kart_tpu.transport.service import collect_blobs

        req = json.loads(self._read_body().decode() or "{}")
        header, objects = collect_blobs(self.repo, req.get("oids", []))
        self._framed(header, objects)

    def _handle_receive_pack(self):
        from kart_tpu.transport.service import locked_ref_updates

        repo = self.repo
        with self._read_body_spooled() as body:
            header, pack_fp = read_framed(body)
            with repo.odb.bulk_pack():
                for obj_type, content in read_pack(pack_fp):
                    repo.odb.write_raw(obj_type, content)

        # compare-and-swap must be atomic across handler threads AND across
        # processes (an ssh push is a separate serve-stdio process): thread
        # lock here, gitdir file lock inside locked_ref_updates.
        with self.server.push_lock:
            status, payload = locked_ref_updates(repo, header)
        if status == "ok":
            self._json(200, {"updated": payload})
        else:
            self._json(409 if status == "conflict" else 400, {"error": payload})


def make_server(repo, host="127.0.0.1", port=0):
    """-> ThreadingHTTPServer serving `repo`; port 0 picks a free port."""
    server = ThreadingHTTPServer((host, port), KartRequestHandler)
    server.kart_repo = repo
    server.push_lock = threading.Lock()
    return server


def serve(repo, host="127.0.0.1", port=8470, *, in_thread=False):
    """Run the collaboration server (blocking unless in_thread)."""
    server = make_server(repo, host, port)
    if in_thread:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        return server
    try:
        server.serve_forever()
    finally:
        server.server_close()


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class HttpRemote:
    """Client for the API above; the HTTP implementation of the transport
    verbs remote.py's fetch/push/clone are written against."""

    def __init__(self, url):
        self.base = url.rstrip("/")

    def close(self):
        """No persistent connection; symmetric with StdioRemote so callers
        can close any network client unconditionally."""

    def _get(self, path):
        try:
            with urlopen(Request(self.base + path), timeout=60) as resp:
                return json.loads(resp.read().decode())
        except OSError as e:
            raise HttpTransportError(f"Cannot reach remote {self.base!r}: {e}")

    def _post(self, path, data, *, raw=False, length=None):
        """data: JSON-able object, or (raw=True) bytes / a file-like with an
        explicit length."""
        headers = {
            "Content-Type": "application/x-kartpack" if raw else "application/json"
        }
        body = data if raw else json.dumps(data).encode()
        if length is not None:
            headers["Content-Length"] = str(length)
        req = Request(self.base + path, data=body, headers=headers, method="POST")
        try:
            return urlopen(req, timeout=600)
        except OSError as e:
            detail = ""
            if hasattr(e, "read"):
                try:
                    detail = json.loads(e.read().decode()).get("error", "")
                except Exception:
                    pass
            raise HttpTransportError(
                f"Remote {self.base!r} error: {detail or e}"
            )

    # -- verbs --------------------------------------------------------------

    def ls_refs(self):
        return self._get(f"{API}/refs")

    def fetch_pack(self, dst_repo, wants, *, haves=(), have_shallow=(),
                   depth=None, filter_spec=None):
        """-> header dict; objects are written straight into dst_repo."""
        resp = self._post(
            f"{API}/fetch-pack",
            {
                "wants": list(wants),
                "haves": list(haves),
                "have_shallow": sorted(have_shallow),
                "depth": depth,
                "filter": filter_spec,
            },
        )
        with resp:
            header, pack_fp = read_framed(resp)
            with dst_repo.odb.bulk_pack():
                for obj_type, content in read_pack(pack_fp):
                    dst_repo.odb.write_raw(obj_type, content)
        return header

    def fetch_blobs(self, dst_repo, oids):
        resp = self._post(f"{API}/fetch-blobs", {"oids": list(oids)})
        fetched = 0
        with resp:
            header, pack_fp = read_framed(resp)
            with dst_repo.odb.bulk_pack():
                for obj_type, content in read_pack(pack_fp):
                    dst_repo.odb.write_raw(obj_type, content)
                    fetched += 1
        if header.get("missing"):
            raise HttpTransportError(
                f"Remote is missing promised objects: {header['missing'][:5]}"
            )
        return fetched

    def receive_pack(self, objects, updates, *, shallow=()):
        """objects: iterable of (type, content); updates: [{ref, old, new,
        force}]; shallow: oids or a callable evaluated after the objects
        drain (an ObjectEnumerator's boundary is only final then).
        -> {ref: oid|None} from the server."""
        with tempfile.SpooledTemporaryFile(max_size=64 * 1024 * 1024) as buf:
            write_framed(
                buf,
                lambda: {
                    "updates": updates,
                    "shallow": sorted(shallow() if callable(shallow) else shallow),
                },
                objects,
            )
            length = buf.tell()
            buf.seek(0)
            resp = self._post(
                f"{API}/receive-pack", buf, raw=True, length=length
            )
        with resp:
            return json.loads(resp.read().decode())["updated"]
