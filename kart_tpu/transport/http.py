"""HTTP transport: serve and consume the kartpack wire format over HTTP.

The reference speaks the git smart protocol over https/ssh via its vendored
git (kart/cli.py:211-253, git upload-pack / receive-pack).  This module is
the native equivalent over plain HTTP — a deliberately small JSON + kartpack
API that preserves the same semantics:

* want/have negotiation (client declares its ref tips; the server walks
  reachability and ships only what's missing),
* shallow clone/fetch (client shallow set respected; new boundary returned),
* server-side spatially-filtered partial clone (the filter argument is
  evaluated on the server against its envelope index — the analog of the
  reference's ``filter_extension_spatial`` upload-pack plugin,
  vendor/spatial-filter/spatial_filter.cpp:212-260),
* promisor backfill (batch blob fetch by oid).

Endpoints (all JSON unless noted):

    GET  <base>/api/v1/refs
        -> {"heads": {...}, "tags": {...}, "head_branch": ..., "shallow": [...]}
    GET  <base>/api/v1/events[?since=N][&timeout=S][&stream=sse]
        -> the live-update subscription surface (docs/EVENTS.md §5):
        long-poll (or SSE) for announced ref transitions with their exact
        per-dataset dirty-tile summaries, resume-by-sequence. Without
        ``since`` it is the subscribe handshake (current head, no wait).
        Behind the shed lane; ``KART_SERVE_EVENTS=0`` disables (404).
    GET  <base>/api/v1/tiles/<ref>/<dataset>/<z>/<x>/<y>[?layers=...][&format=mvt]
        -> one framed tile payload (docs/TILES.md): vector tile of the
        named ref's commit, served straight off the columnar sidecar —
        block-pruned, commit-addressed-cached, strong ETag (the ref is
        pinned to its commit oid at request time, so the validator never
        needs revalidation). ``<ref>`` is URL-encoded (refs/heads/main →
        refs%2Fheads%2Fmain); bare branch/tag names and commit oids work
        unescaped. Layer negotiation (docs/TILES.md §5): ``?layers=``
        picks from bin/geojson/ktb2/mvt/props; absent, the server default
        (``KART_TILE_ENCODING``) applies; ``?format=mvt`` — or an
        ``Accept: application/vnd.mapbox-vector-tile`` header — serves
        the **bare MVT protobuf body** (no kart framing, its own strong
        ETag) so off-the-shelf MapLibre clients can point a tile URL
        template here. Responses carry ``Vary: Accept``. Tile requests
        ARE load-shed (429 + Retry-After past the inflight ceiling) —
        unlike /api/v1/stats, a tile is ordinary work.
        ``KART_SERVE_TILES=0`` (or ``kart serve --no-tiles``) disables
        the endpoint (404).
    POST <base>/api/v1/fetch-pack
        {"wants": [...], "haves": [...], "have_shallow": [...],
         "depth": N|null, "filter": "w,s,e,n"|null}
        -> framed response: 8-byte big-endian header length, JSON header
           {"shallow_boundary": [...], "object_count": N}, kartpack bytes.
        Responses carry a strong ETag; a retry may send
        ``Range: bytes=N-`` + ``If-Range: <etag>`` with the *identical*
        body to resume a torn stream mid-pack (206; docs/SERVING.md §3).
        Enumerations are cached + single-flighted per request key
        (docs/SERVING.md §2), and the server sheds load with
        429 + Retry-After past ``KART_SERVE_MAX_INFLIGHT``.
    POST <base>/api/v1/fetch-blobs
        {"oids": [...]} -> framed response (header + kartpack)
    POST <base>/api/v1/receive-pack
        framed request: 8-byte header length, JSON header
        {"updates": [{"ref", "old", "new", "force"}], "shallow": [...]},
        kartpack bytes -> {"updated": {...}} (409 on a rejected update)

There is no authentication — this is a LAN/localhost collaboration server,
like ``git daemon``. Put a reverse proxy in front for anything else.
"""

import json
import os
import re
import struct
import sys
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.error import HTTPError
from urllib.parse import urlsplit
from urllib.request import Request, urlopen

from kart_tpu import faults
from kart_tpu import telemetry as tm
from kart_tpu.core.odb import ObjectMissing
from kart_tpu.core.singleflight import SingleFlightLRU
from kart_tpu.telemetry import access as rq_access
from kart_tpu.telemetry import context as rq_context
from kart_tpu.transport.pack import read_pack, write_pack

API = "/api/v1"

#: the Mapbox Vector Tile media type: requesting it (Accept header or
#: ``?format=mvt``) negotiates the bare protobuf representation of a tile
_MVT_MIME = "application/vnd.mapbox-vector-tile"
_HEADER_LEN = struct.Struct(">Q")

#: raw-MVT unwrap memo: strong validator -> bare protobuf body. Payloads
#: are immutable per ETag (the commit oid is in the key), so a hit skips
#: the per-request frame reparse on the hot MapLibre path. Byte-budgeted
#: LRU with single-flight fill — the same discipline as the TileCache,
#: which holds the framed representation of these bytes.
_RAW_MVT_MEMO_BUDGET = 16 << 20
_RAW_MVT_MEMO = SingleFlightLRU(_RAW_MVT_MEMO_BUDGET)


def _raw_mvt_body(payload, etag):
    """The framed tile payload's bare ``mvt`` layer bytes, memoized by its
    (immutable) strong validator."""
    status, got = _RAW_MVT_MEMO.lookup_or_begin(etag)
    if status == "hit":
        return got
    from kart_tpu import tiles

    try:
        _header, layer_bytes = tiles.parse_payload(payload)
        body = layer_bytes["mvt"]
    except BaseException:
        if got is not None:
            got.abandon()
        raise
    if got is not None:
        got.publish(body)
    return body

#: default per-socket timeout (connect + each recv) for the quick JSON GETs
#: — a dead server fails fast instead of hanging forever. Every verb flow
#: starts with ls_refs, so this is the fail-fast gate for the whole fetch/
#: push/clone. Env KART_HTTP_TIMEOUT overrides both this and the POST
#: budget below.
DEFAULT_HTTP_TIMEOUT = 30.0

#: default for the pack-carrying POSTs: the server spools its ENTIRE
#: response pack (and, for receive-pack, quarantines + migrates + applies
#: refs) before its first response byte, so the time-to-first-byte scales
#: with repo size — a 30s budget would abort healthy large transfers, and a
#: push timed out client-side after the server committed would report a
#: false failure with refs already moved.
DEFAULT_HTTP_POST_TIMEOUT = 600.0

#: HTTP statuses that recur only transiently (proxy reload, backend
#: restart, throttling) — the module recommends a reverse proxy for
#: production, so these must stay retryable
_TRANSIENT_HTTP_STATUSES = (429, 502, 503, 504)


def http_timeout(default=DEFAULT_HTTP_TIMEOUT):
    try:
        return float(os.environ.get("KART_HTTP_TIMEOUT", default))
    except (TypeError, ValueError):
        return default


class HttpTransportError(ValueError):
    """Transport failure. ``transient`` marks connection-level failures a
    bounded retry may recover from (vs server-reported op errors, which
    recur deterministically); ``pre_write`` marks failures that provably
    happened before any request byte reached the server, the only kind a
    non-idempotent verb retries. ``retry_after`` carries a server-sent
    ``Retry-After`` (seconds) — the load-shedding 429 path — which the
    retry policy honours as a backoff floor. ``shed`` marks an HTTP 429:
    by its semantics the server refused the request *before applying
    anything*, so even a non-idempotent verb (push) may safely retry — the
    paced-queue behaviour load shedding (and the contended-push busy lane)
    is designed for. ``terminal`` marks an application-level final verdict
    the retry policy never overrides, and ``conflict_report`` carries the
    structured three-way conflict document of a rejected contended push
    (docs/SERVING.md §6) for the client to render like a local merge."""

    transient = False
    pre_write = False
    retry_after = None
    shed = False
    terminal = False
    conflict_report = None

    def __init__(self, message, *, transient=None, pre_write=None,
                 retry_after=None, shed=None, terminal=None,
                 conflict_report=None):
        super().__init__(message)
        if transient is not None:
            self.transient = transient
        if pre_write is not None:
            self.pre_write = pre_write
        if retry_after is not None:
            self.retry_after = retry_after
        if shed is not None:
            self.shed = shed
        if terminal is not None:
            self.terminal = terminal
        if conflict_report is not None:
            self.conflict_report = conflict_report


def _retry_after_of(http_error):
    """Seconds from an HTTPError's Retry-After header (seconds form only;
    an HTTP-date or garbage is ignored), or None."""
    try:
        value = float(http_error.headers.get("Retry-After", ""))
    except (AttributeError, TypeError, ValueError):
        return None
    return value if value >= 0 else None


# ---------------------------------------------------------------------------
# framing: [8-byte header length][JSON header][kartpack bytes]
# ---------------------------------------------------------------------------


def write_framed(fp, header, pack_source):
    """pack_source: iterable of (type, content) -> frames header + pack into
    fp. The pack is buffered (spooled) first, and a callable header is only
    evaluated after that drain — so the header can carry enumeration results
    (shallow boundary, counts) without materialising the objects in RAM."""
    with tempfile.SpooledTemporaryFile(max_size=64 * 1024 * 1024) as buf:
        write_pack(buf, iter(pack_source))
        if callable(header):
            header = header()
        raw_header = json.dumps(header).encode()
        fp.write(_HEADER_LEN.pack(len(raw_header)))
        fp.write(raw_header)
        buf.seek(0)
        while True:
            chunk = buf.read(1 << 20)
            if not chunk:
                break
            fp.write(chunk)


def read_framed(fp):
    """-> (header dict, file-like positioned at the pack)."""
    raw = fp.read(_HEADER_LEN.size)
    if len(raw) != _HEADER_LEN.size:
        raise HttpTransportError("Truncated framed response", transient=True)
    (n,) = _HEADER_LEN.unpack(raw)
    if n > 1 << 24:
        raise HttpTransportError("Framed header implausibly large")
    body = fp.read(n)
    if len(body) != n:
        raise HttpTransportError("Truncated framed header", transient=True)
    try:
        header = json.loads(body.decode())
    except (ValueError, UnicodeDecodeError):
        # the declared escape for crafted bytes is HttpTransportError;
        # json/unicode errors leaking here broke the wire-fuzz contract
        raise HttpTransportError("Malformed framed header") from None
    if not isinstance(header, dict):
        raise HttpTransportError("Malformed framed header")
    return header, fp


# ---------------------------------------------------------------------------
# negotiation helper: what does the peer (claim to) have?
# ---------------------------------------------------------------------------


def have_closure(odb, haves, have_shallow=()):
    """Object oids the peer has, given its declared ref tips: every commit
    reachable from the tips (stopping at the peer's shallow boundary, where
    its history is known-truncated), plus the full tree closure of each tip
    commit — tip trees prune the bulk of unchanged subtrees/blobs from a
    typical tip-to-tip transfer."""
    have_shallow = set(have_shallow)
    closure = set()
    frontier = [o for o in haves if o]
    tips = list(frontier)
    while frontier:
        oid = frontier.pop()
        if oid in closure:
            continue
        try:
            commit = odb.read_commit(oid)
        except (ObjectMissing, KeyError, ValueError):
            continue
        closure.add(oid)
        if oid in have_shallow:
            continue  # peer's history stops here
        frontier.extend(commit.parents)

    def add_tree(tree_oid):
        if tree_oid in closure:
            return
        closure.add(tree_oid)
        try:
            entries = odb.read_tree_entries(tree_oid)
        except (ObjectMissing, KeyError, ValueError):
            return
        for e in entries:
            if e.is_tree:
                add_tree(e.oid)
            else:
                closure.add(e.oid)

    for tip in tips:
        try:
            add_tree(odb.read_commit(tip).tree)
        except (ObjectMissing, KeyError, ValueError):
            continue
    return closure


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class KartRequestHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "kart-tpu-serve/1"
    # buffered response writes: headers + a small body leave as ONE
    # sendall instead of two (BaseHTTPRequestHandler defaults to an
    # unbuffered wfile); large pack/tile streams still flush per chunk
    # past the buffer, and handle_one_request flushes at request end
    wbufsize = 64 * 1024

    @property
    def repo(self):
        return self.server.kart_repo

    def log_message(self, fmt, *args):  # route through logging, not stderr
        import logging

        logging.getLogger("kart_tpu.serve").debug(fmt, *args)

    # -- plumbing -----------------------------------------------------------

    def send_response(self, code, message=None):
        # status capture for the access log + trace-context echo: every
        # response carries the request's traceparent back to the client
        self._kart_status = code
        super().send_response(code, message)
        traceparent = rq_context.current_traceparent()
        if traceparent:
            self.send_header(rq_context.TRACEPARENT_HEADER, traceparent)

    def send_header(self, keyword, value):
        if keyword.lower() == "content-length":
            try:
                self._kart_bytes_out = int(value)
            except (TypeError, ValueError):
                pass
        super().send_header(keyword, value)

    def _json(self, status, payload, headers=None):
        raw = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(raw)

    def _framed(self, header, pack_source):
        # spool to disk past 64MB — never hold a whole pack in RAM per
        # request (ThreadingHTTPServer multiplies that by concurrent clients)
        with tempfile.SpooledTemporaryFile(max_size=64 * 1024 * 1024) as buf:
            write_framed(buf, header, pack_source)
            length = buf.tell()
            tm.incr("transport.server.bytes_sent", length)
            buf.seek(0)
            self.send_response(200)
            self.send_header("Content-Type", "application/x-kartpack")
            self.send_header("Content-Length", str(length))
            self.end_headers()
            while True:
                chunk = buf.read(1 << 20)
                if not chunk:
                    break
                self.wfile.write(chunk)

    def _read_body(self):
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n)

    def _read_body_spooled(self):
        n = int(self.headers.get("Content-Length", 0))
        tm.incr("transport.server.bytes_received", n)
        buf = tempfile.SpooledTemporaryFile(max_size=64 * 1024 * 1024)
        remaining = n
        while remaining > 0:
            chunk = self.rfile.read(min(remaining, 1 << 20))
            if not chunk:
                break
            buf.write(chunk)
            remaining -= len(chunk)
        buf.seek(0)
        return buf

    # -- admission: inflight gauge + load shedding --------------------------

    def _admit(self):
        """Count this request in; shed with 429 + Retry-After when the
        inflight ceiling (``KART_SERVE_MAX_INFLIGHT``; 0/unset = unlimited)
        is breached — the client RetryPolicy treats 429 as transient and
        honours Retry-After as its backoff floor, so a storm decays into a
        paced queue instead of a pile-up. -> False when shed (the caller
        must return without handling)."""
        from kart_tpu.transport.retry import _env_int

        server = self.server
        with server.inflight_lock:
            server.inflight += 1
            n = server.inflight
        tm.gauge_set("server.inflight", n)
        limit = _env_int("KART_SERVE_MAX_INFLIGHT", 0)
        shed = limit > 0 and n > limit
        if not shed:
            try:
                # the injectable storm: shed this request regardless of load
                faults.fire("server.shed")
            except faults.InjectedFault:
                shed = True
        if not shed:
            return True
        self._leave()
        tm.incr("server.shed")  # exposition: kart_server_shed_total
        tm.annotate(shed=True)  # access-log: this request was refused
        retry_after = _env_int("KART_SERVE_RETRY_AFTER", 1)
        raw = json.dumps(
            {"error": f"Server over capacity ({limit} inflight); retry"}
        ).encode()
        self.send_response(429)
        self.send_header("Retry-After", str(max(0, retry_after)))
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)
        return False

    def _leave(self):
        server = self.server
        with server.inflight_lock:
            server.inflight -= 1
            n = server.inflight
        tm.gauge_set("server.inflight", n)

    # -- routes -------------------------------------------------------------

    #: route -> access-log verb (matches the transport.server.requests
    #: verb labels, so rates and latency histograms join up)
    _VERBS = {
        f"{API}/stats": "stats",
        f"{API}/refs": "ls-refs",
        f"{API}/events": "events",
        f"{API}/query": "query",
        f"{API}/fetch-pack": "fetch-pack",
        f"{API}/fetch-blobs": "fetch-blobs",
        f"{API}/receive-pack": "receive-pack",
    }

    def _verb_for(self, path):
        if path.startswith(f"{API}/tiles/"):
            return "tiles"
        return self._VERBS.get(path, "other")

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def _dispatch(self, method):
        """Every request runs inside a request scope (trace context adopted
        from the client's ``traceparent`` header, or minted here), under a
        ``transport.request`` span, and books one access-log record +
        latency observation on the way out — whatever the handler did."""
        try:
            path = urlsplit(self.path).path.rstrip("/")
        except ValueError:
            # a malformed request line (e.g. a broken IPv6 literal) must
            # still get an answer and an access-log record, not a dead
            # handler thread
            path = None
        verb = self._verb_for(path) if path is not None else "other"
        self._kart_status = None
        self._kart_bytes_out = 0
        t0 = time.perf_counter()
        with rq_context.request_scope(
            verb=verb,
            traceparent=self.headers.get(rq_context.TRACEPARENT_HEADER),
            record=rq_access.slow_threshold() is not None,
            # a request without a traceparent mints a fresh trace (handler
            # threads start context-free anyway; this pins the contract)
            inherit=False,
        ) as ctx:
            try:
                with tm.span("transport.request", verb=verb):
                    if path is None:
                        self._json(
                            400,
                            {"error": f"Malformed request path: {self.path!r}"},
                        )
                    else:
                        self._route(method, path)
            except Exception as e:  # surface server errors to the client
                self._json(500, {"error": f"{type(e).__name__}: {e}"})
            finally:
                try:
                    bytes_in = int(self.headers.get("Content-Length") or 0)
                except (TypeError, ValueError):
                    bytes_in = 0  # a bogus header must not kill the record
                rq_access.record_request(
                    verb=verb,
                    status=self._kart_status,
                    bytes_in=bytes_in,
                    bytes_out=self._kart_bytes_out,
                    seconds=time.perf_counter() - t0,
                    ctx=ctx,
                )

    def _route(self, method, path):
        if method == "GET":
            if path == f"{API}/stats":
                # never shed the stats endpoint: observability of a server
                # in overload is the whole point of having it
                return self._handle_stats()
            if not self._admit():
                return
            try:
                if self._replica_gate():
                    return  # read pinned to the primary; already answered
                if path == f"{API}/refs":
                    return self._handle_refs()
                if path == f"{API}/events":
                    return self._handle_events()
                if path == f"{API}/query":
                    return self._handle_query()
                if path.startswith(f"{API}/tiles/"):
                    return self._handle_tile(path)
                self._json(404, {"error": f"No such endpoint: {self.path}"})
            finally:
                self._leave()
        else:
            if not self._admit():
                return
            try:
                if path == f"{API}/receive-pack":
                    return self._handle_receive_pack()
                if self._replica_gate():
                    return  # read pinned to the primary; already answered
                if path == f"{API}/fetch-pack":
                    return self._handle_fetch_pack()
                if path == f"{API}/fetch-blobs":
                    return self._handle_fetch_blobs()
                self._json(404, {"error": f"No such endpoint: {self.path}"})
            finally:
                self._leave()

    # -- fleet routing (docs/FLEET.md §3) -----------------------------------

    def _fleet(self):
        return getattr(self.server, "fleet", None)

    def _is_peer_fill(self):
        """Is this request another replica's peer-cache fill? Such a
        request must be answered from local state — consulting our own
        peer tier would recurse between mutually-peered replicas."""
        from kart_tpu.fleet.peercache import PEER_FILL_HEADER

        return bool(self.headers.get(PEER_FILL_HEADER))

    def _replica_gate(self):
        """Read-your-writes on a replica: a request carrying
        ``X-Kart-Min-Commit`` must not be answered from a view older than
        that commit. Stall (bounded by ``KART_REPLICA_MAX_LAG``) while the
        sync loop catches up; past the bound, pin the read to the primary
        instead. -> True when the request was answered here (pinned)."""
        fleet = self._fleet()
        if fleet is None or not fleet.is_replica:
            return False
        from kart_tpu import fleet as fleet_mod

        # the sequence pin (docs/EVENTS.md §6) outranks the commit pin
        # when the event subscription is live: satisfying it is one
        # integer compare against the applied watermark, no ancestry walk
        min_seq = (self.headers.get(fleet_mod.MIN_SEQ_HEADER) or "").strip()
        if min_seq.isdigit() and fleet.sync.subscribed():
            seq = int(min_seq)
            if fleet.sync.applied_seq() >= seq:
                return False  # already applied: serve locally, no stall
            if fleet.sync.wait_for_seq(seq, fleet_mod.max_lag_seconds()):
                tm.incr("fleet.ryw_stalls")
                tm.annotate(ryw="stalled")
                fleet.note_ryw(pinned=False)
                return False
            return self._pin_to_primary(fleet)
        min_commit = self.headers.get(fleet_mod.MIN_COMMIT_HEADER)
        if not min_commit:
            return False
        min_commit = min_commit.strip()
        if not re.fullmatch(r"[0-9a-f]{40}", min_commit):
            # a malformed pin must not stall every read for the lag bound
            return False
        if fleet.sync.tips_contain(min_commit):
            return False  # already visible: serve locally, no stall
        if fleet.sync.wait_for_commit(min_commit, fleet_mod.max_lag_seconds()):
            tm.incr("fleet.ryw_stalls")
            tm.annotate(ryw="stalled")
            fleet.note_ryw(pinned=False)
            return False
        return self._pin_to_primary(fleet)

    def _pin_to_primary(self, fleet):
        """The replica cannot catch up inside the lag bound (primary
        down, transfer still draining): answer from the primary itself
        rather than serve a view the client has proven is stale.
        -> True (the request was answered here)."""
        tm.incr("fleet.ryw_pins")
        tm.annotate(ryw="pinned")
        fleet.note_ryw(pinned=True)
        from kart_tpu.fleet import router

        try:
            if self.command == "POST":
                # the POST data-fetch verbs (fetch-pack/fetch-blobs) are
                # reads too: relay them body-and-all — a GET relay would
                # hit a route the primary doesn't serve
                with self._read_body_spooled() as body:
                    length = body.seek(0, 2)
                    body.seek(0)
                    status, headers, payload = router.proxy_post(
                        fleet, self.path, body, length,
                        content_type=self.headers.get("Content-Type"),
                    )
            else:
                status, headers, payload = router.proxy_get(
                    fleet, self.path, request_headers=self.headers
                )
        except router.ProxyUpstreamError as e:
            self._json(
                502, {"error": f"Replica is behind and its primary is "
                               f"unreachable: {e}"}
            )
            return True
        self._respond_relayed(status, headers, payload)
        return True

    def _respond_relayed(self, status, headers, payload, extra=None):
        """Answer with a response relayed from the primary, byte-for-byte
        (status, selected headers, entire payload)."""
        self.send_response(status)
        for name, value in headers.items():
            self.send_header(name, value)
        for name, value in (extra or {}).items():
            self.send_header(name, value)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        if payload:
            self.wfile.write(payload)
        tm.incr("transport.server.bytes_sent", len(payload))

    def _handle_refs(self):
        from kart_tpu.transport.service import ls_refs_info

        self._json(200, ls_refs_info(self.repo))

    #: ceiling on one SSE session — turns the inflight slot over so a
    #: forgotten browser tab can't hold admission forever; the client
    #: reconnects with its last seen sequence and misses nothing
    SSE_SESSION_SECONDS = 3600.0

    def _handle_events(self):
        """``GET /api/v1/events?since=<seq>``: the live-update
        subscription surface (docs/EVENTS.md §5). Long-poll by default —
        the response returns as soon as events with a larger sequence are
        announced, or empty after ``timeout`` seconds; ``stream=sse`` (or
        ``Accept: text/event-stream``) switches to a server-sent-events
        stream. Without ``since`` the request is the subscribe handshake:
        it answers the current head immediately. Behind the shed lane —
        an invalidation feed is ordinary work, unlike /api/v1/stats."""
        from urllib.parse import parse_qs

        from kart_tpu import events as events_mod

        if not events_mod.events_enabled():
            return self._json(
                404, {"error": "Event serving is disabled on this server"}
            )
        tm.incr("transport.server.requests", verb="events")
        params = parse_qs(urlsplit(self.path).query)
        emitter = events_mod.emitter_for(self.repo)
        raw_since = params.get("since", [None])[0]
        if raw_since is None:
            # the subscribe handshake: current head, no wait (reconcile
            # first so a push landed by another process is in the head)
            emitter.reconcile()
            return self._json(200, {"events": [], "head": emitter.log.head()})
        try:
            since = int(raw_since)
        except ValueError:
            return self._json(
                400, {"error": f"Bad since={raw_since!r} (sequence number)"}
            )
        try:
            timeout = float(params.get("timeout", ["nan"])[0])
        except ValueError:
            timeout = events_mod.LONG_POLL_SECONDS
        if not (0 <= timeout <= events_mod.LONG_POLL_SECONDS):
            timeout = events_mod.LONG_POLL_SECONDS
        sse = (
            params.get("stream", [""])[0] == "sse"
            or "text/event-stream" in (self.headers.get("Accept") or "")
        )
        if sse:
            return self._events_sse(emitter, since)
        with emitter.watching():
            events, head, reset = emitter.wait_events(since, timeout)
        doc = {"events": events, "head": head, "since": since}
        if reset is not None:
            doc["reset"] = reset
        self._json(200, doc)

    def _events_sse(self, emitter, since):
        """The SSE variant: one frame per event (``id:`` = sequence, so a
        reconnecting EventSource resumes by Last-Event-ID semantics on the
        client side), comment keep-alives while idle."""
        import logging

        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-store")
        self.end_headers()
        # no Content-Length: the stream ends when the connection closes
        self.close_connection = True
        deadline = time.monotonic() + self.SSE_SESSION_SECONDS
        try:
            with emitter.watching():
                while time.monotonic() < deadline:
                    events, head, reset = emitter.wait_events(since, 15.0)
                    if reset is not None:
                        self.wfile.write(
                            f"event: reset\ndata: {reset}\n\n".encode()
                        )
                    for event in events:
                        raw = json.dumps(event, sort_keys=True)
                        self.wfile.write(
                            f"id: {event['seq']}\ndata: {raw}\n\n".encode()
                        )
                        self._kart_bytes_out += len(raw)
                    since = max(since, head)
                    if not events:
                        self.wfile.write(b": keep-alive\n\n")
                    self.wfile.flush()
        except OSError as e:
            # the normal end of an SSE session: the watcher went away
            logging.getLogger("kart_tpu.serve").debug(
                "SSE watcher disconnected: %s", e
            )

    @staticmethod
    def _if_none_match_hits(header_value, etag):
        """RFC 9110 If-None-Match: a comma-separated validator list, each
        optionally weak-prefixed (``W/``), or ``*``. A browser/proxy that
        coalesced several stored responses sends the list form — exact
        string equality would silently kill the 304 fast path for it."""
        if not header_value:
            return False
        if header_value.strip() == "*":
            return True
        for part in header_value.split(","):
            candidate = part.strip()
            if candidate.startswith("W/"):
                candidate = candidate[2:]
            if candidate == etag:
                return True
        return False

    def _handle_tile(self, path):
        """``GET /api/v1/tiles/<ref>/<dataset>/<z>/<x>/<y>``: serve one
        vector tile of the named revision straight off the columnar store
        (kart_tpu/tiles; docs/TILES.md). Dataset paths may contain slashes;
        the last three segments are always z/x/y and the first is the
        (URL-encoded) ref."""
        from urllib.parse import parse_qs, unquote

        from kart_tpu import tiles

        if os.environ.get("KART_SERVE_TILES", "1") in ("0", "false"):
            return self._json(
                404, {"error": "Tile serving is disabled on this server"}
            )
        tm.incr("transport.server.requests", verb="tiles")
        parts = [unquote(p) for p in path[len(f"{API}/tiles/"):].split("/")]
        if len(parts) < 5 or not all(parts):
            return self._json(
                400,
                {"error": "Tile address must be <ref>/<dataset>/<z>/<x>/<y>"},
            )
        ref, ds_path = parts[0], "/".join(parts[1:-3])
        z, x, y = parts[-3:]
        tm.annotate(ref=ref, dataset=ds_path, tile=f"{z}/{x}/{y}")
        # warm-then-announce (docs/EVENTS.md §4): while a push's dirty
        # tiles are still warming, branch-name requests stay pinned to the
        # announced (old) tip — the hot tiles keep serving; commit-oid
        # requests are unaffected (commit-addressed by construction).
        # sys.modules guard: only a process already running the events
        # machinery can have a pin to honour
        events_mod = sys.modules.get("kart_tpu.events")
        if events_mod is not None and events_mod.events_enabled():
            emitter = events_mod.active_emitter(self.repo.gitdir)
            if emitter is not None:
                pinned = emitter.tile_pin(ref)
                if pinned is not None:
                    tm.annotate(tile_pin=True)
                    ref = pinned
        query = urlsplit(self.path).query
        params = parse_qs(query) if query else {}
        layers = params.get("layers", [None])[0]
        fmt = params.get("format", [None])[0]
        # content negotiation (docs/TILES.md §5): ?format=mvt — or, with
        # no explicit layer spec, an MVT Accept header — means the client
        # wants the bare protobuf body an off-the-shelf MapLibre renderer
        # can consume; everything else gets the framed multi-layer payload
        raw_mvt = False
        if fmt is not None:
            if fmt != "mvt":
                return self._json(
                    400, {"error": f"Unknown tile format {fmt!r} (try mvt)"}
                )
            raw_mvt = True
            if layers is None:
                layers = "mvt"
        elif layers is None and self._accepts_mvt(self.headers.get("Accept")):
            layers, raw_mvt = "mvt", True
        try:
            # the validator derives from the request key alone (commit oid
            # + address + layers): a revalidating client is answered 304
            # before any source is built or payload encoded — even on a
            # cold cache, a conditional GET is near-free
            key, etag, commit_oid, (zi, xi, yi), norm_layers = (
                tiles.tile_request_key(
                    self.repo, ref, ds_path, z, x, y, layers=layers
                )
            )
            if raw_mvt:
                if norm_layers != ("mvt",):
                    return self._json(
                        400,
                        {"error": "format=mvt serves exactly one layer: "
                                  "mvt (drop layers= or set layers=mvt)"},
                    )
                # different representation bytes => different strong
                # validator, even though one cache key backs both
                etag = tiles.etag_for(key, raw=True)
            if self._if_none_match_hits(self.headers.get("If-None-Match"), etag):
                # commit-addressed: a matching validator can never be stale
                tm.annotate(revalidated=True)
                self.send_response(304)
                self.send_header("ETag", etag)
                self.send_header("Vary", "Accept")
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            peer_fill = None
            fleet = self._fleet()
            if fleet is not None and fleet.peers and not self._is_peer_fill():
                from kart_tpu.fleet import peercache

                # the peer cache tier (docs/FLEET.md §4): hot peer-held
                # tiles answer from one lock-hold read; cold tiles are
                # fetched from a fleet peer — validated by ETag equality —
                # before this process pays the block-pruned encode
                payload = peercache.peek_tile_payload(fleet.peer_cache(), key)
                if payload is not None:
                    tm.annotate(tile_cache="peer")
                    tm.incr("tiles.served")
                    tm.incr("tiles.bytes_out", len(payload))
                    return self._send_tile(payload, etag, raw_mvt=raw_mvt)
                peer_fill = peercache.tile_peer_fill(
                    self.repo, fleet.peers, commit_oid, ds_path, zi, xi, yi,
                    norm_layers,
                )
            payload, framed_etag, _cached = tiles.serve_tile(
                self.repo, ref, ds_path, zi, xi, yi, layers=norm_layers,
                commit_oid=commit_oid, peer_fill=peer_fill,
            )
            if not raw_mvt:
                etag = framed_etag
        except tiles.TileTooLarge as e:
            return self._json(
                413, {"error": str(e), "count": e.count, "limit": e.limit}
            )
        except tiles.TileDataUnavailable as e:
            return self._json(422, {"error": str(e)})
        except tiles.TileSourceError as e:
            return self._json(404, {"error": str(e)})
        except (tiles.TileAddressError, tiles.TileEncodeError) as e:
            return self._json(400, {"error": str(e)})
        self._send_tile(payload, etag, raw_mvt=raw_mvt)

    @staticmethod
    def _accepts_mvt(accept):
        """Does the Accept header positively request the MVT media type?
        RFC 9110 list form with q-values: a client sending
        ``application/vnd.mapbox-vector-tile;q=0`` is *refusing* the type
        — a substring test would hand it the bare protobuf anyway."""
        if not accept:
            return False
        for part in accept.split(","):
            media, _, params = part.partition(";")
            if media.strip().lower() != _MVT_MIME:
                continue
            q = 1.0
            for param in params.split(";"):
                name, _, value = param.partition("=")
                if name.strip().lower() == "q":
                    try:
                        q = float(value.strip())
                    except ValueError:
                        q = 1.0
            return q > 0.0
        return False

    def _send_tile(self, payload, etag, raw_mvt=False):
        if raw_mvt:
            # unwrap the framed payload: the bare MVT body is what an
            # off-the-shelf renderer consumes (the frame — and the cache
            # entry behind it — still carries the layer). The unwrap
            # (json header decode + slice) is memoized by strong validator
            # — payloads are immutable per ETag — so cache-hit raw-MVT
            # requests skip the reparse on the hot MapLibre path. Note
            # tiles.bytes_out deliberately counts the FRAMED bytes (the
            # cache-entry size, consistent across representations); wire
            # egress is transport.server.bytes_sent below.
            payload = _raw_mvt_body(payload, etag)
        tm.incr("transport.server.bytes_sent", len(payload))
        self.send_response(200)
        self.send_header(
            "Content-Type", _MVT_MIME if raw_mvt else "application/x-kart-tile"
        )
        self.send_header("ETag", etag)
        # the payload is immutable for its key (the commit oid is in it):
        # downstream HTTP caches may keep it as long as they like
        self.send_header("Cache-Control", "public, max-age=31536000, immutable")
        # the Accept header can negotiate the representation (bare MVT vs
        # framed): shared caches must key on it
        self.send_header("Vary", "Accept")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _handle_query(self):
        """``GET /api/v1/query``: the serving face of the query engine
        (docs/QUERY.md §5) — predicate-pushdown scans and spatial joins over
        one commit. Results are commit-addressed (the strong ETag derives
        from the resolved oid(s) + the normalized request), so a matching
        validator can never be stale, responses cache forever, and join
        ``count`` queries scatter their probe side across fleet peers as
        block-aligned ``part=lo:hi`` partials (docs/QUERY.md §6)."""
        from urllib.parse import parse_qs

        from kart_tpu import query as query_mod
        from kart_tpu.query import cache as qcache

        tm.incr("transport.server.requests", verb="query")
        params = parse_qs(urlsplit(self.path).query)

        def one(name, default=None):
            return params.get(name, [default])[0]

        ref, ds_path = one("ref"), one("dataset")
        if not ref or not ds_path:
            return self._json(
                400, {"error": "query needs ref= and dataset= parameters"}
            )
        where, bbox = one("where"), one("bbox")
        raw_intersects = one("intersects")
        output = one("output", "count")
        count_by = one("count_by")
        raw_part = one("part")
        # fold the *effective* mode into the key: a server pinned to
        # envelope semantics (KART_GEOM_REFINE=0) serves different bytes
        # and must never share a validator with an exact answer
        from kart_tpu.geom import geom_refine_enabled

        approx = (
            one("approx") in ("1", "true") or not geom_refine_enabled()
        )
        try:
            page = int(one("page")) if one("page") is not None else None
            page_size = (
                int(one("page_size")) if one("page_size") is not None else None
            )
        except ValueError:
            return self._json(
                400, {"error": "page/page_size must be integers"}
            )
        try:
            commit1 = query_mod.resolve_query_commit(self.repo, ref)
            intersects = commit2 = ds_path2 = None
            if raw_intersects:
                refish2, sep, ds2 = raw_intersects.partition(":")
                if not sep or not refish2 or not ds2:
                    raise query_mod.QueryError(
                        f"intersects wants <refish>:<dataset>,"
                        f" got {raw_intersects!r}"
                    )
                commit2 = query_mod.resolve_query_commit(self.repo, refish2)
                ds_path2 = ds2
                intersects = (commit2, ds_path2)
            part = part_str = None
            if raw_part:
                m = re.fullmatch(r"(\d+):(\d+)", raw_part)
                if m is None:
                    raise query_mod.QueryError(
                        f"part wants <lo>:<hi> row numbers, got {raw_part!r}"
                    )
                part = (int(m.group(1)), int(m.group(2)))
                part_str = f"{part[0]}:{part[1]}"
        except query_mod.QueryError as e:
            return self._json(400, {"error": str(e)})
        tm.annotate(ref=ref, dataset=ds_path)

        # the validator derives from the request key alone: a revalidating
        # client is answered 304 before any scan or join runs
        key = qcache.query_request_key(
            commit1, ds_path, where=where, bbox=bbox, commit_oid2=commit2,
            ds_path2=ds_path2, output=output, count_by=count_by, page=page,
            page_size=page_size, part=part_str, approx=approx,
        )
        etag = qcache.etag_for(key)
        if self._if_none_match_hits(self.headers.get("If-None-Match"), etag):
            tm.annotate(revalidated=True)
            self.send_response(304)
            self.send_header("ETag", etag)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return

        fleet = self._fleet()
        scatter_ok = (
            intersects is not None
            and output == "count"
            and part is None
            and fleet is not None
            and bool(fleet.peers)
            and not self._is_peer_fill()
            and os.environ.get("KART_QUERY_SCATTER", "1") != "0"
        )

        def compute():
            doc = None
            if scatter_ok:
                doc = self._scattered_join(
                    query_mod, qcache, fleet, commit1, ds_path, commit2,
                    ds_path2, bbox, approx,
                )
            if doc is None:
                doc = query_mod.run_query(
                    self.repo, commit1, ds_path, where=where, bbox=bbox,
                    intersects=intersects, output=output, count_by=count_by,
                    page=page, page_size=page_size, part=part, approx=approx,
                )
            return json.dumps(doc, sort_keys=True).encode()

        try:
            payload = qcache.query_filled(
                qcache.query_cache_for(self.repo), key, compute
            )
        except query_mod.QueryError as e:
            return self._json(400, {"error": str(e)})
        tm.incr("transport.server.bytes_sent", len(payload))
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("ETag", etag)
        # immutable for its key (the commit oids are in it): downstream
        # HTTP caches may keep it as long as they like
        self.send_header("Cache-Control", "public, max-age=31536000, immutable")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _scattered_join(self, query_mod, qcache, fleet, commit1, ds_path,
                        commit2, ds_path2, bbox, approx):
        """The fleet scatter of a join ``count`` query (docs/QUERY.md §6):
        split the probe side into block-aligned row ranges, fetch parts
        1..N-1 from peers as commit-addressed ``part=lo:hi`` partials
        (ETag-validated, peer-cached) *while* part 0 computes here — the
        overlap is the speedup — then compute any failed part locally and
        merge by ordered addition. -> merged result doc, or None when the
        probe side is too small to split."""
        from urllib.parse import quote

        from kart_tpu.diff import sidecar
        from kart_tpu.fleet import peercache
        from kart_tpu.query import _bump

        ds = query_mod.load_query_dataset(self.repo, commit1, ds_path)
        block = sidecar.ensure_block(self.repo, ds, pad=False)
        n = int(block.count) if block is not None else 0
        n_parts = len(fleet.peers) + 1
        per = -(-max(n, 1) // n_parts)
        per = max(
            -(-per // sidecar.AGG_BLOCK_ROWS) * sidecar.AGG_BLOCK_ROWS,
            sidecar.AGG_BLOCK_ROWS,
        )
        parts = [(lo, min(lo + per, n)) for lo in range(0, n, per)]
        if len(parts) < 2:
            return None
        tm.incr("query.scatter_requests")
        tm.incr("query.scatter_parts", len(parts))
        _bump("scatter_requests")
        _bump("scatter_parts", len(parts))
        def _local(lo, hi):
            return query_mod.run_query(
                self.repo, commit1, ds_path, bbox=bbox,
                intersects=(commit2, ds_path2), output="count",
                part=(lo, hi), approx=approx,
            )

        def _from_peer(lo, hi):
            part_str = f"{lo}:{hi}"
            # the approx mode folds into the part key AND the part URL
            # consistently — a peer must never serve an exact partial
            # into an approx merge or vice versa
            pkey = qcache.query_request_key(
                commit1, ds_path, bbox=bbox, commit_oid2=commit2,
                ds_path2=ds_path2, output="count", part=part_str,
                approx=approx,
            )
            path_and_query = (
                f"{API}/query?ref={commit1}"
                f"&dataset={quote(ds_path, safe='')}"
                f"&intersects={commit2}:{quote(ds_path2, safe='')}"
                f"&output=count&part={part_str}"
            )
            if bbox:
                path_and_query += f"&bbox={quote(bbox, safe='')}"
            if approx:
                path_and_query += "&approx=1"
            return peercache.query_from_peers(
                self.repo, fleet.peers, path_and_query,
                qcache.etag_for(pkey),
            )

        # peer parts in flight first, so the remote computes overlap the
        # local part-0 compute — the overlap IS the scatter speedup
        payloads = [None] * len(parts)
        threads = []
        for i, (lo, hi) in enumerate(parts[1:], start=1):
            def _fetch(i=i, lo=lo, hi=hi):
                try:
                    payloads[i] = _from_peer(lo, hi)
                except Exception:
                    payloads[i] = None  # degraded, not failed: compute here
            t = threading.Thread(target=_fetch, daemon=True)
            t.start()
            threads.append(t)
        docs = [_local(*parts[0])]
        for t in threads:
            t.join()
        for i, (lo, hi) in enumerate(parts[1:], start=1):
            if payloads[i] is None:
                docs.append(_local(lo, hi))
            else:
                docs.append(json.loads(payloads[i]))
        merged = dict(docs[0])
        merged["part"] = None
        merged["pairs"] = sum(d["pairs"] for d in docs)
        merged["count"] = sum(d["count"] for d in docs)
        stats = dict(docs[0]["stats"])
        for name in (
            "tiles", "blocks_pruned", "block_tests", "batches",
            "pairs_refined", "refine_dropped",
        ):
            stats[name] = sum(d["stats"].get(name, 0) for d in docs)
        stats["scatter_parts"] = len(parts)
        merged["stats"] = stats
        return merged

    def _handle_stats(self):
        """Prometheus-style text exposition of this server process's metric
        registry (`kart stats <url>` reads this). ``?format=json`` returns
        the structured stats document instead — bucketed histograms with
        quantile estimates, windowed rates, the slow-request exemplar ring
        and live inflight/queue depth (what ``kart top`` renders)."""
        from urllib.parse import parse_qs

        from kart_tpu.telemetry import sinks

        tm.incr("transport.server.requests", verb="stats")
        params = parse_qs(urlsplit(self.path).query)
        if params.get("format", [""])[0] == "json":
            extra = {"inflight": self.server.inflight}
            fleet = self._fleet()
            if fleet is not None:
                # the fleet operator's staleness view: replication lag,
                # proxied writes, read-your-writes decisions per replica
                extra["fleet"] = fleet.status_dict()
            # the live-update operator view (docs/EVENTS.md §7): connected
            # watchers, log head, last fan-out latency, warm queue depth —
            # present once any watcher/push has touched the events path
            events_mod = sys.modules.get("kart_tpu.events")
            if events_mod is not None and events_mod.events_enabled():
                emitter = events_mod.active_emitter(self.repo.gitdir)
                if emitter is not None:
                    extra["events"] = emitter.status_dict()
            # the query-engine operator view (docs/QUERY.md §7): scans,
            # joins, pruning and scatter counters — present once any
            # query has run in this process
            query_mod = sys.modules.get("kart_tpu.query")
            if query_mod is not None:
                extra["query"] = query_mod.status_dict()
            return self._json(200, rq_access.stats_payload(extra=extra))
        raw = sinks.prometheus_text().encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _range_offset(self, etag, length):
        """The validated resume offset of a ``Range: bytes=N-`` request
        (0 = serve the full response). If-Range must present the exact
        strong validator we handed out — the etag embeds the ref-tips
        fingerprint, so a ref update between attempts forces a clean full
        response instead of splicing bytes from two different packs."""
        rng = self.headers.get("Range")
        if not rng or self.headers.get("If-Range") != etag:
            return 0
        m = re.match(r"bytes=(\d+)-$", rng.strip())
        if not m:
            return 0
        offset = int(m.group(1))
        return offset if 0 < offset < length else 0

    def _handle_fetch_pack(self):
        from contextlib import closing

        from kart_tpu.transport.service import materialise_plan, serve_fetch_pack

        req = json.loads(self._read_body().decode() or "{}")
        # cache-fronted enumeration: a hit (or a single-flight wait on a
        # concurrent identical request) skips the ObjectEnumerator walk;
        # a fresh walk spools, publishes, then streams
        plan = serve_fetch_pack(self.repo, req)
        plan = self._peer_filled_plan(req, plan)
        fp, length = materialise_plan(plan)
        with closing(fp):
            offset = self._range_offset(plan.etag, length)
            if offset:
                tm.incr("server.range_resumes")
                tm.annotate(range_resume=True)
                # a validated byte-range request IS a resumed fetch, same
                # as a non-empty oid-exclusion list on the wire field —
                # but count each resumed request once (a range retry of an
                # exclusion-seeded body was already counted)
                if not req.get("exclude"):
                    tm.incr("transport.server.fetch_resumes")
                fp.seek(offset)
                self.send_response(206)
                self.send_header(
                    "Content-Range", f"bytes {offset}-{length - 1}/{length}"
                )
            else:
                self.send_response(200)
            self.send_header("Content-Type", "application/x-kartpack")
            self.send_header("ETag", plan.etag)
            self.send_header("Accept-Ranges", "bytes")
            self.send_header("Content-Length", str(length - offset))
            self.end_headers()
            tm.incr("transport.server.bytes_sent", length - offset)
            fault = faults.hook("server.enum_cache") if plan.cached else None
            while True:
                try:
                    if fault is not None:
                        fault()
                    chunk = fp.read(1 << 20)
                except faults.InjectedFault:
                    # the injected mid-cached-stream kill: truncate the
                    # response like a dying server would (no trailing 500
                    # junk that would pad out Content-Length) — the client
                    # salvages and resumes (tests/test_faults.py)
                    self.close_connection = True
                    return
                if not chunk:
                    break
                self.wfile.write(chunk)

    def _peer_filled_plan(self, req, plan):
        """The peer cache tier for enumerations (docs/FLEET.md §4): a plan
        about to pay a fresh walk may instead fetch the complete framed
        response from a fleet peer — accepted only when the peer's strong
        validator equals ours (same key ⇒ byte-identical response). One
        cold walk per fleet, not one per replica. Exclusion-bearing
        one-shot resume requests stay local (their keys can never re-hit
        — peer-caching them would only evict hot entries)."""
        fleet = self._fleet()
        if (
            fleet is None
            or not fleet.peers
            or plan.cached
            or plan.data is not None
            or req.get("exclude")
            # a fill from another replica must be answered from local
            # state — mutually-peered replicas would otherwise recurse
            or self._is_peer_fill()
        ):
            return plan
        from kart_tpu.fleet import peercache
        from kart_tpu.transport.service import FetchPlan

        peer_bytes = peercache.fetch_pack_from_peers(
            self.repo, fleet.peers, req, plan.etag
        )
        if peer_bytes is None:
            return plan
        # release the enum-cache fill token: the payload lives in the peer
        # cache, and waiters on this key will hit it there
        plan.abandon()
        tm.annotate(enum_cache="peer")
        return FetchPlan(None, peer_bytes, None, plan.etag, True)

    def _handle_fetch_blobs(self):
        from kart_tpu.transport.service import collect_blobs

        req = json.loads(self._read_body().decode() or "{}")
        header, objects = collect_blobs(self.repo, req.get("oids", []))
        self._framed(header, objects)

    def _handle_proxy_receive_pack(self):
        """A replica never lands writes itself: the framed push body is
        relayed to the primary byte-for-byte (same traceparent, so the
        primary's trace joins the client's), and the primary's response —
        including the structured rebase/rejection payload — is relayed
        back unmodified, plus the ``X-Kart-Replica-Proxied`` marker the
        client pins its next reads on (docs/FLEET.md §3)."""
        from kart_tpu import fleet as fleet_mod
        from kart_tpu.fleet import router
        from kart_tpu.transport.remote import is_http_url

        fleet = self._fleet()
        tm.incr("transport.server.requests", verb="receive-pack")
        if not is_http_url(fleet.primary_url):
            # replication pulls work over any transport, but the byte-level
            # write relay needs an HTTP primary (docs/FLEET.md §3)
            return self._json(
                501,
                {"error": f"This replica cannot proxy pushes (primary "
                          f"{fleet.primary_url!r} is not http(s)); push to "
                          f"the primary directly"},
            )
        with self._read_body_spooled() as body:
            length = body.seek(0, 2)
            body.seek(0)
            try:
                status, headers, payload = router.proxy_receive_pack(
                    fleet, body, length
                )
            except router.ProxyUpstreamError as e:
                # 502 is in the client's transient set: the push retries
                # against a recovered primary, nothing half-applied
                return self._json(
                    502, {"error": f"Replica cannot reach its primary: {e}"}
                )
        tm.annotate(proxied=True)
        self._respond_relayed(
            status, headers, payload, extra={fleet_mod.PROXIED_HEADER: "1"}
        )

    def _handle_receive_pack(self):
        from kart_tpu.transport.protocol import rejection_wire_fields
        from kart_tpu.transport.service import quarantined_receive

        fleet = self._fleet()
        if fleet is not None and fleet.is_replica:
            return self._handle_proxy_receive_pack()

        # the pack drains into a quarantine objects dir and migrates into
        # the live store only after checksum + ref preconditions pass — a
        # torn or rejected push leaves the store byte-identical; a push
        # that lost its CAS to a contending writer is auto-rebased against
        # the new tip before re-validating (docs/SERVING.md §6). The CAS is
        # atomic across handler threads AND across processes (an ssh push
        # is a separate serve-stdio process): thread lock + gitdir file
        # lock, both held inside quarantined_receive.
        with self._read_body_spooled() as body:
            header, pack_fp = read_framed(body)
            result = quarantined_receive(
                self.repo, header, pack_fp, thread_lock=self.server.push_lock
            )
        if result[0] == "ok":
            self._json(200, result[1])
            return
        # a structured rejection: conflict -> 409 (terminal ones carry the
        # report), busy (merge queue full / CAS budget exhausted) -> the
        # same paced 429 + Retry-After lane the load shedder uses
        status = {"conflict": 409, "busy": 429}.get(result[0], 400)
        payload = {"error": result[1]}
        payload.update(rejection_wire_fields(result))
        headers = None
        retry_after = payload.get("retry_after")
        if status == 429 and retry_after is not None:
            headers = {"Retry-After": str(max(0, int(retry_after)))}
        self._json(status, payload, headers)


def make_server(repo, host="127.0.0.1", port=0, *, fleet=None):
    """-> ThreadingHTTPServer serving `repo`; port 0 picks a free port.

    Serving turns metrics on (a server without observable counters is
    undebuggable in production — the registry feeds ``GET /api/v1/stats``)
    and configures the shared ``kart_tpu`` logger so a spawned server
    honours ``KART_LOG`` without the CLI having run. ``fleet``: a
    :class:`kart_tpu.fleet.FleetNode` making this server a replica and/or
    peer-cache member (docs/FLEET.md); the caller starts/stops it."""
    tm.configure_logging()
    tm.enable(metrics=True)
    server = ThreadingHTTPServer((host, port), KartRequestHandler)
    server.kart_repo = repo
    server.fleet = fleet
    # narrow write lock: held only around ref validation + quarantine
    # migrate inside quarantined_receive — concurrent pushes drain their
    # (per-push) quarantines in parallel and serialise only at the CAS
    server.push_lock = threading.Lock()
    # admission control: live request gauge feeding the load shedder
    server.inflight = 0
    server.inflight_lock = threading.Lock()
    return server


def serve(repo, host="127.0.0.1", port=8470, *, in_thread=False):
    """Run the collaboration server (blocking unless in_thread).

    Fleet membership is environment-configured (``KART_REPLICA_OF``,
    ``KART_PEER_CACHE`` — docs/FLEET.md): a replica starts its background
    sync loop here and stops it with the server. With ``in_thread=True``
    the caller owns shutdown: stop the loop via ``server.fleet.stop()``
    alongside ``server.shutdown()``."""
    from kart_tpu import fleet as fleet_mod

    node = fleet_mod.node_from_env(repo)
    server = make_server(repo, host, port, fleet=node)
    if node is not None:
        node.start()
    if in_thread:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        return server
    try:
        server.serve_forever()
    finally:
        if node is not None:
            node.stop()
        server.server_close()


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class _CountingReader:
    """Byte-counting file pass-through. Two users: the fetch client
    measures the framed-header prefix exactly (``read_framed`` reads exact
    sizes, no read-ahead) to anchor ``Range: bytes=N-`` resume offsets;
    the stdio server wraps both pipe ends so per-op deltas feed the
    access-log bytes_in/bytes_out fields (write/flush pass through with
    the same accounting)."""

    __slots__ = ("_fp", "count")

    def __init__(self, fp, start=0):
        self._fp = fp
        self.count = start

    def read(self, n=-1):
        data = self._fp.read(n)
        self.count += len(data)
        return data

    def write(self, data):
        self.count += len(data)
        return self._fp.write(data)

    def flush(self):
        self._fp.flush()


def _pack_body_source(resp):
    """-> file-like over the rest of ``resp``'s body (the pack stream): a
    large C-level read-ahead buffer under the per-record parser (cuts the
    Python stream-layer cost ~2.5x), while still *streaming* — consuming
    at drain speed keeps the socket's backpressure, which under a client
    storm is what staggers concurrent drains instead of letting every
    client buffer its whole pack and then fight for the same cores."""
    import io

    return io.BufferedReader(resp, buffer_size=1 << 20)


class HttpRemote:
    """Client for the API above; the HTTP implementation of the transport
    verbs remote.py's fetch/push/clone are written against.

    Fault tolerance: every verb runs under ``retry`` (a
    :class:`~kart_tpu.transport.retry.RetryPolicy`). The idempotent verbs
    (``ls_refs``, ``fetch_pack``, ``fetch_blobs``) retry on any transient
    failure — and ``fetch_pack`` *resumes*: objects salvaged from a torn
    stream are excluded from the re-negotiation, so a retry transfers only
    the missing remainder. ``receive_pack`` retries only when the
    connection was never established (the server provably saw nothing)."""

    def __init__(self, url, retry=None):
        from kart_tpu.transport.retry import RetryPolicy

        self.base = url.rstrip("/")
        self.retry = retry if retry is not None else RetryPolicy.from_config()
        # read-your-writes pin (docs/FLEET.md §3): set after a push that a
        # replica proxied to its primary; subsequent reads through this
        # client carry it so the replica stalls (or pins to the primary)
        # until its view contains the pushed commit
        self._min_commit = None
        # the sequence twin (docs/EVENTS.md §6): the push's booked
        # live-update event sequence — a subscribed replica satisfies the
        # pin with an integer compare instead of an ancestry walk
        self._min_seq = None

    def close(self):
        """No persistent connection; symmetric with StdioRemote so callers
        can close any network client unconditionally."""

    def reset(self, *_):
        """No per-connection state to tear down between retries."""

    @staticmethod
    def _trace_headers():
        """The cross-process trace-context header for the active request
        scope (docs/OBSERVABILITY.md §8): the server adopts the id, so its
        spans and access-log lines name *this* logical request."""
        traceparent = rq_context.current_traceparent()
        if traceparent is None:
            return {}
        return {rq_context.TRACEPARENT_HEADER: traceparent}

    def _pin_headers(self):
        """The read-your-writes pin headers (commit containment + event
        sequence) every read after a proxied push carries."""
        if self._min_commit is None and self._min_seq is None:
            return {}
        from kart_tpu import fleet as fleet_mod

        headers = {}
        if self._min_commit is not None:
            headers[fleet_mod.MIN_COMMIT_HEADER] = self._min_commit
        if self._min_seq is not None:
            headers[fleet_mod.MIN_SEQ_HEADER] = str(self._min_seq)
        return headers

    def _get(self, path):
        headers = self._trace_headers()
        headers.update(self._pin_headers())
        try:
            req = Request(self.base + path, headers=headers)
            with urlopen(req, timeout=http_timeout()) as resp:
                return json.loads(resp.read().decode())
        except HTTPError as e:
            raise HttpTransportError(
                f"Remote {self.base!r} error: {e}",
                transient=e.code in _TRANSIENT_HTTP_STATUSES,
                retry_after=_retry_after_of(e),
                shed=e.code == 429,
            )
        except OSError as e:
            # connection-level (refused / DNS / socket timeout): transient,
            # and for GETs necessarily pre-write
            raise HttpTransportError(
                f"Cannot reach remote {self.base!r}: {e}",
                transient=True,
                pre_write=True,
            )

    def _post(self, path, data, *, raw=False, length=None, headers=None):
        """data: JSON-able object, or (raw=True) bytes / a file-like with an
        explicit length. ``headers``: extra request headers (the byte-range
        resume path sends Range/If-Range)."""
        all_headers = {
            "Content-Type": "application/x-kartpack" if raw else "application/json"
        }
        all_headers.update(self._trace_headers())
        # the POST data-fetch verbs must carry the read-your-writes pins
        # too: a pinned ls-refs advertising the new tip followed by an
        # ungated fetch-pack from the stale store would fail on exactly
        # the objects the pin exists to guarantee
        all_headers.update(self._pin_headers())
        if headers:
            all_headers.update(headers)
        body = data if raw else json.dumps(data).encode()
        if length is not None:
            all_headers["Content-Length"] = str(length)
        req = Request(
            self.base + path, data=body, headers=all_headers, method="POST"
        )
        try:
            return urlopen(req, timeout=http_timeout(DEFAULT_HTTP_POST_TIMEOUT))
        except HTTPError as e:
            # the server answered: usually a deterministic op error, except
            # the proxy-layer statuses that recur only transiently
            from kart_tpu.transport.protocol import error_attrs_from_wire

            body = None
            try:
                body = json.loads(e.read().decode())
            except (OSError, ValueError, AttributeError):
                # non-JSON / unreadable error body: the HTTP status below
                # is still reported
                pass
            detail = body.get("error", "") if isinstance(body, dict) else ""
            attrs = {
                "transient": e.code in _TRANSIENT_HTTP_STATUSES,
                "retry_after": _retry_after_of(e),
                "shed": e.code == 429,
            }
            # structured-rejection fields from the body (terminal verdicts,
            # the conflict report, busy pacing) — the header/status values
            # above win where both are present
            for name, value in error_attrs_from_wire(body).items():
                if attrs.get(name) in (None, False):
                    attrs[name] = value
            raise HttpTransportError(
                f"Remote {self.base!r} error: {detail or e}", **attrs
            )
        except OSError as e:
            reason = getattr(e, "reason", e)
            raise HttpTransportError(
                f"Remote {self.base!r} error: {e}",
                transient=True,
                # connect refused ⇒ no request byte ever left this process,
                # so even a non-idempotent verb may safely retry
                pre_write=isinstance(reason, ConnectionRefusedError),
            )

    # -- verbs --------------------------------------------------------------

    def ls_refs(self):
        # one request scope per verb call: every retry attempt carries the
        # same request id on the wire, so the server's access log shows one
        # logical request with N attempts, not N anonymous requests
        with rq_context.request_scope(verb="ls-refs"):
            return self.retry.call(
                lambda: self._get(f"{API}/refs"), label="ls-refs",
                on_retry=self.reset,
            )

    def fetch_pack(self, dst_repo, wants, *, haves=(), have_shallow=(),
                   depth=None, filter_spec=None, exclude=None):
        """-> header dict; objects are written straight into dst_repo.

        Resumable, twice over. In-process retries resume *mid-pack* by byte
        range: every attempt tracks the absolute offset of the last
        complete record it consumed, and the retry re-sends the identical
        request with ``Range: bytes=N-`` + the server's strong validator
        (``If-Range``), so the server — whose enumeration is deterministic
        per key, cache or no cache — ships only the unseen tail. If the
        validator no longer matches (a ref moved, the entry was evicted)
        the server answers 200 with a fresh full response, and the salvaged
        objects still suppress re-writing. Cross-process resume stays
        oid-exclusion based: ``exclude`` seeds the exclusion set (the oids
        salvaged by the earlier, killed process), and the set is shared in
        place so the caller sees everything salvaged even when every
        attempt fails."""
        from kart_tpu.transport.retry import drain_pack_salvaging, exclude_arg

        received = exclude if isinstance(exclude, set) else set(exclude or ())
        # byte-range resume state across retry attempts: the validator, the
        # exact body that produced it (byte-identical key on the server),
        # the response header already read, and the committed byte offset
        state = {"etag": None, "body": None, "header": None, "offset": 0}

        def attempt():
            resp = None
            if state["etag"] and state["offset"] > 0:
                resp = self._post(
                    f"{API}/fetch-pack",
                    state["body"],
                    headers={
                        "Range": f"bytes={state['offset']}-",
                        "If-Range": state["etag"],
                    },
                )
                if getattr(resp, "status", 200) == 206:
                    tm.incr("transport.range_resumes")
                    with resp:
                        base = state["offset"]
                        drain_pack_salvaging(
                            dst_repo.odb,
                            # read-ahead is safe: the response body IS the
                            # pack remainder, bounded by Content-Length
                            _pack_body_source(resp),
                            received,
                            mid_stream=True,
                            commit=lambda off: state.update(offset=base + off),
                        )
                    return state["header"]
                # validator mismatch: the server sent a fresh full response
                # — fall through and consume it as one
            if resp is None:
                body = {
                    "wants": list(wants),
                    "haves": list(haves),
                    "have_shallow": sorted(have_shallow),
                    "depth": depth,
                    "filter": filter_spec,
                    "exclude": exclude_arg(received),
                }
                resp = self._post(f"{API}/fetch-pack", body)
                state["body"] = body
            with resp:
                counting = _CountingReader(resp)
                header, _ = read_framed(counting)
                prefix = counting.count  # 8-byte length + JSON header
                state.update(
                    etag=resp.headers.get("ETag"), header=header, offset=0
                )
                drain_pack_salvaging(
                    dst_repo.odb,
                    _pack_body_source(resp),
                    received,
                    commit=lambda off: state.update(offset=prefix + off),
                )
            return header

        with rq_context.request_scope(verb="fetch-pack"):
            return self.retry.call(
                attempt, label="fetch-pack", on_retry=self.reset
            )

    def fetch_blobs(self, dst_repo, oids):
        from kart_tpu.transport.retry import drain_pack_salvaging

        received = set()

        def attempt():
            # a retry re-requests only what the torn attempt didn't land
            want = [o for o in oids if o not in received]
            if not want:
                return {}
            resp = self._post(f"{API}/fetch-blobs", {"oids": want})
            with resp:
                header, pack_fp = read_framed(resp)
                drain_pack_salvaging(dst_repo.odb, pack_fp, received)
            return header

        with rq_context.request_scope(verb="fetch-blobs"):
            header = self.retry.call(
                attempt, label="fetch-blobs", on_retry=self.reset
            )
        if header.get("missing"):
            raise HttpTransportError(
                f"Remote is missing promised objects: {header['missing'][:5]}"
            )
        return len(received)

    def receive_pack(self, objects, updates, *, shallow=()):
        """objects: iterable of (type, content); updates: [{ref, old, new,
        force}]; shallow: oids or a callable evaluated after the objects
        drain (an ObjectEnumerator's boundary is only final then).
        -> the server's full receive payload: ``{"updated": {ref:
        oid|None}, "rebase": {...}}`` (``rebase`` reports whether the
        server auto-rebased a contended push, its CAS attempt count and
        merge-queue wait; docs/SERVING.md §6).

        Not idempotent: only pre-write failures (connect refused — the
        server saw no byte of this request) and paced 429s — load shedding
        or the contended-push busy lane, both of which provably applied
        nothing — are retried. A structured conflict rejection is
        ``terminal``: surfaced once, never blindly re-pushed."""
        from kart_tpu.transport.retry import is_pre_write

        def retryable(exc):
            return is_pre_write(exc) or getattr(exc, "shed", False)

        with tempfile.SpooledTemporaryFile(max_size=64 * 1024 * 1024) as buf:
            write_framed(
                buf,
                lambda: {
                    "updates": updates,
                    "shallow": sorted(shallow() if callable(shallow) else shallow),
                },
                objects,
            )
            length = buf.tell()

            def attempt():
                buf.seek(0)
                return self._post(
                    f"{API}/receive-pack", buf, raw=True, length=length
                )

            with rq_context.request_scope(verb="receive-pack"):
                resp = self.retry.call(
                    attempt, label="receive-pack", retryable=retryable,
                    on_retry=self.reset,
                )
        from kart_tpu import fleet as fleet_mod

        with resp:
            proxied = resp.headers.get(fleet_mod.PROXIED_HEADER)
            payload = json.loads(resp.read().decode())
        if proxied:
            from kart_tpu.fleet import router as fleet_router

            # the server was a replica relaying to its primary: pin this
            # client's next reads on the landed branch tip
            # (read-your-writes; heads only — a tag oid would never
            # satisfy the replica's tip-containment check)
            landed = fleet_router.landed_head_oids(payload)
            if landed:
                self._min_commit = landed[-1]
            seq = payload.get("event_seq")
            if isinstance(seq, int) and seq > 0:
                # the sequence pin: set alongside the commit pin so a
                # subscribed replica takes the integer fast path and an
                # old replica still honours the containment pin
                self._min_seq = max(self._min_seq or 0, seq)
        return payload
