"""Fault tolerance for the wire transports: retry with capped exponential
backoff, transient-error classification, and the salvaging pack drain that
makes fetch resumable.

The reference inherits all of this from git (curl retries, packfile
quarantine, ``http.lowSpeedLimit``); our native transports implement the
same production posture directly:

* **RetryPolicy** — attempts / base-delay / cap, configured per remote
  (``remote.<name>.retries`` etc.), globally via env, or per client. Only
  *idempotent* verbs (``ls_refs``, ``fetch_pack``, ``fetch_blobs``) retry
  automatically; ``receive_pack`` retries only on pre-write failures (the
  connection was never established, so the server saw nothing).
* **Transient classification** — connection-level failures (OSError,
  injected faults, torn packstreams) are retryable; server-reported op
  errors (bad filter spec, CAS conflict, HTTP status errors) are not.
  Errors carry an optional ``transient`` attribute that overrides the
  class-based default, and ``pre_write=True`` marks failures that provably
  happened before any request byte reached the server. ``terminal=True``
  marks an application-level final verdict (a structured merge-conflict
  rejection) that no retryable predicate may override — see
  :func:`is_terminal`.
* **drain_pack_salvaging** — objects are content-addressed and each pack
  record is individually length/zlib-checked, so everything received before
  a disconnect is durable: on a torn stream the partial pack is *finalised*
  (not discarded) and the error re-raised. A retry then excludes the
  salvaged oids from the re-negotiation and the server ships only the
  remainder.
"""

import logging
import os
import time

from kart_tpu import telemetry as tm
from kart_tpu.transport.pack import PackFormatError, read_pack

L = logging.getLogger("kart_tpu.transport.retry")

#: largest oid-exclusion list a resuming fetch sends; beyond this the tail
#: is simply not excluded (exclusions are an optimisation — dropping some
#: re-transfers a little, never corrupts) so request headers stay bounded
#: (the stdio server caps request headers at 16MB).
EXCLUDE_CAP = 100_000

#: ceiling on how far a server-sent Retry-After may stretch one backoff
#: sleep: the header is honoured as a *floor* on the computed exponential
#: delay (a shedding server knows its own recovery horizon better than our
#: guess), but a hostile/buggy header must not park a client for an hour.
RETRY_AFTER_CAP = 60.0


def is_transient(exc):
    """Should a bounded retry be attempted after ``exc``?

    An explicit ``transient`` attribute wins; otherwise OS-level errors and
    torn packstreams are transient, everything else (server-reported op
    errors, protocol violations) is not."""
    t = getattr(exc, "transient", None)
    if t is not None:
        return bool(t)
    return isinstance(exc, (OSError, PackFormatError))


def is_pre_write(exc):
    """True when the failure provably happened before any request byte
    reached the server (e.g. TCP connect refused, spawn failure) — the only
    failures a non-idempotent verb may retry."""
    return bool(getattr(exc, "pre_write", False))


def is_terminal(exc):
    """True for an application-level *final* verdict — the server examined
    the request and rejected it deterministically (the structured
    merge-conflict report of a contended push: a human must resolve it).
    Terminal errors are never retried, whatever the per-verb ``retryable``
    predicate says: a blind re-push of the same commits is guaranteed to
    conflict again, and that retry amplification is exactly the failure
    mode the server-side rebase exists to remove (docs/SERVING.md §6)."""
    return bool(getattr(exc, "terminal", False))


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class RetryPolicy:
    """Capped exponential backoff: attempt *k* failing transiently sleeps
    ``min(max_delay, base_delay * 2**(k-1))`` before attempt *k+1*, up to
    ``attempts`` total attempts. ``sleep`` is injectable for tests."""

    def __init__(self, attempts=3, base_delay=0.2, max_delay=10.0, sleep=time.sleep):
        self.attempts = max(1, int(attempts))
        self.base_delay = max(0.0, float(base_delay))
        self.max_delay = max(0.0, float(max_delay))
        self.sleep = sleep

    @classmethod
    def from_config(cls, config=None, remote_name=None):
        """Resolve the policy for a remote: env (operational override) >
        ``remote.<name>.*`` config > defaults.

        Config keys: ``remote.<name>.retries``, ``.retrybasedelay``,
        ``.retrymaxdelay``. Env: ``KART_TRANSPORT_RETRIES``,
        ``KART_TRANSPORT_RETRY_BASE``, ``KART_TRANSPORT_RETRY_CAP``."""
        attempts, base, cap = 3, 0.2, 10.0
        if config is not None and remote_name is not None:
            prefix = f"remote.{remote_name}."
            try:
                attempts = config.get_int(prefix + "retries", attempts)
                base = float(config.get(prefix + "retrybasedelay", base))
                cap = float(config.get(prefix + "retrymaxdelay", cap))
            except (TypeError, ValueError):
                pass
        attempts = _env_int("KART_TRANSPORT_RETRIES", attempts)
        base = _env_float("KART_TRANSPORT_RETRY_BASE", base)
        cap = _env_float("KART_TRANSPORT_RETRY_CAP", cap)
        return cls(attempts, base, cap)

    def delay_for(self, attempt):
        """Backoff before attempt ``attempt + 1`` (1-based attempts)."""
        return min(self.max_delay, self.base_delay * (2 ** (attempt - 1)))

    def call(self, fn, *, retryable=is_transient, label="", on_retry=None):
        """Run ``fn()`` with up to ``attempts`` tries. ``retryable(exc)``
        gates each retry; ``on_retry(exc, attempt)`` runs before the backoff
        sleep (transports use it to reset a desynced connection)."""
        for attempt in range(1, self.attempts + 1):
            try:
                return fn()
            except Exception as e:
                # a terminal verdict outranks every retryable classification
                # — "conflicts, human required" must surface exactly once,
                # while "CAS lost, server still rebasing" stays in the
                # paced-retry lane below
                if attempt >= self.attempts or is_terminal(e) or not retryable(e):
                    raise
                delay = self.delay_for(attempt)
                # a server-sent Retry-After (the 429/503 shedding path) is
                # the backoff floor — capped, and never *lowering* a larger
                # exponential delay
                retry_after = getattr(e, "retry_after", None)
                try:
                    retry_after = float(retry_after)
                except (TypeError, ValueError):
                    retry_after = None
                if retry_after is not None and retry_after > 0:
                    floored = max(delay, min(retry_after, RETRY_AFTER_CAP))
                    if floored > delay:
                        tm.incr("transport.retry_after_honoured")
                    delay = floored
                tm.incr("transport.retries", verb=label or "operation")
                tm.incr("transport.backoff_seconds", delay)
                # the retry ladder joins the request's trace: all attempts
                # run inside one verb scope (one request id on the wire)
                # and the warning below carries it as rid= — the server's
                # access log shows one logical request with N attempts
                L.warning(
                    "transport %s failed (%s: %s); retrying %d/%d in %.2fs",
                    label or "operation",
                    type(e).__name__,
                    e,
                    attempt,
                    self.attempts - 1,
                    delay,
                )
                if on_retry is not None:
                    on_retry(e, attempt)
                if delay > 0:
                    self.sleep(delay)


def drain_pack_salvaging(odb, pack_fp, received=None, *, mid_stream=False,
                         commit=None):
    """Drain a kartpack stream into ``odb`` as one new pack, *keeping* what
    arrived if the stream tears.

    Every record is individually zlib- and length-verified by
    ``read_pack``, and oids are recomputed from content on write, so the
    objects landed before a disconnect are exactly as trustworthy as a
    complete transfer's — the stream checksum trailer only guards the
    record *framing* we already re-derive. On any failure the partial pack
    is finalised (fsck-clean, immediately readable) and the error
    re-raised; ``received`` (if given) accumulates the hex oids written so
    a retry can exclude them from re-negotiation.

    ``mid_stream=True`` consumes a byte-range-resumed stream (starts at a
    record boundary, not the magic); ``commit(pack_bytes)`` (if given) is
    called each time a run of records has landed in the writer, with the
    exact pack-stream bytes consumed through the last *written* record —
    the range-resume path derives its next ``Range:`` offset from it, so a
    resume can never skip a record that was read but still buffered when
    the stream tore.

    Records are written in same-type runs through the writer's batched
    path (one native hash+deflate+frame call per run) — at clone scale the
    per-object Python of ``PackWriter.add`` dominated the whole drain.
    Runs are bounded (count and bytes) so a tear forfeits at most one
    run's worth of already-verified records.

    -> number of objects written this drain."""
    w = odb.pack_writer()
    count = 0
    run_type = None
    run = []  # contents of the current same-type run
    run_bytes = 0
    consumed = [0]   # stream offset after the last record *read*
    run_end = 0      # stream offset after the last record in `run`

    def flush():
        nonlocal count, run, run_bytes
        if not run:
            return
        oids = w.add_batch(run_type, run)
        count += len(run)
        if received is not None:
            received.update(oids)
        run = []
        run_bytes = 0
        if commit is not None:
            commit(run_end)

    try:
        with tm.span("transport.pack_drain"):
            for obj_type, content in read_pack(
                pack_fp, mid_stream=mid_stream, consumed=consumed
            ):
                if (
                    obj_type != run_type
                    or len(run) >= _DRAIN_RUN_OBJECTS
                    or run_bytes >= _DRAIN_RUN_BYTES
                ):
                    flush()
                    run_type = obj_type
                run.append(content)
                run_bytes += len(content)
                run_end = consumed[0]
            flush()
    except BaseException:
        try:
            flush()  # the tail run is fully verified — salvage it too
        except Exception:
            L.warning("drain salvage: tail run write failed; kept %d", count)
        tm.incr("transport.salvage_events")
        tm.incr("transport.objects_salvaged", count)
        try:
            if w.finish() is not None:
                odb.packs.refresh()
        except Exception:
            w.abort()
        raise
    tm.incr("transport.objects_received", count)
    if w.finish() is not None:
        odb.packs.refresh()
    return count


#: drain run bounds: big enough that the native batch call amortises the
#: per-call overhead, small enough that a tear forfeits little and huge
#: blobs can't balloon the buffered run
_DRAIN_RUN_OBJECTS = 4096
_DRAIN_RUN_BYTES = 8 << 20


def exclude_arg(received):
    """The ``exclude`` list a resuming fetch sends: sorted for determinism,
    capped so request headers stay bounded (see EXCLUDE_CAP)."""
    if not received:
        return []
    out = sorted(received)
    return out[:EXCLUDE_CAP]
