"""Want/have negotiation: decide which objects to ship.

The sender walks history from the *want* tips, pruning at anything the
receiver already *has* (the local analog of git's have/want exchange), then
walks each new commit's tree, pruning whole subtrees the receiver has — the
same reachability shape `git rev-list --objects A ^B` computes, re-expressed
over our object store (reference transport: kart/cli.py:211-253).

Two extra axes the reference gets from its forked git:

* **depth** — shallow clone/fetch (`kart clone --depth`, kart/clone.py:72-75):
  the commit walk is cut N commits below each tip; the cut points are
  reported as ``shallow_boundary`` for the receiver to record.
* **blob_filter** — partial clone (`--filter=extension:spatial=…`,
  vendor/spatial-filter/spatial_filter.cpp:212-260): a callback may veto
  individual blobs (by path + oid); vetoed blobs are *omitted* and the
  receiver records the remote as a promisor so later reads raise
  ObjectPromised instead of hard-failing.

A third axis backs resumable fetch: **exclude** — exact oids the receiver
already holds, salvaged from a torn earlier transfer. Unlike ``has`` these
carry *no* closure guarantee (a disconnect delivers commits before their
trees' blobs), so they suppress shipping object-by-object while the walk
still descends through them to find the missing remainder.

This module also defines the **structured rejection frame** both servers
speak when a receive-pack is refused (docs/SERVING.md §6): a
:class:`Rejection` stays tuple-compatible with the PR 2 ``(kind, message)``
API while carrying machine-readable extras — a ``conflict_report`` the
client renders exactly like a local ``kart merge`` conflict, a ``terminal``
flag the retry policy obeys (no blind re-push of commits that will conflict
again), and the ``retry_after``/``shed`` pacing fields of the 429 lane.
"""

from kart_tpu.core.odb import ObjectMissing

#: wire fields a structured rejection may carry beyond "error" — one list
#: so the HTTP JSON body and the stdio response frame can never drift
REJECTION_WIRE_FIELDS = (
    "code", "ref", "terminal", "conflict_report", "retry_after", "shed"
)


class Rejection(tuple):
    """A ``(kind, message)`` receive-pack rejection with structured extras.

    ``kind``: ``"conflict"`` (precondition failed against current state),
    ``"bad"`` (malformed/incomplete request), or ``"busy"`` (back-pressure:
    merge queue overflow / CAS re-validation budget exhausted — retryable
    with pacing, the 429 lane). Tuple compatibility keeps every PR 2 caller
    (``status, msg = rejection``) working unchanged.

    Extras: ``code`` — machine-readable cause (``cas_stale`` /
    ``merge_conflict`` / ``non_ff`` / ``denied`` / ``df_conflict`` /
    ``queue_full`` / ``cas_busy``); ``ref`` — the ref that tripped it;
    ``terminal`` — a deterministic application-level verdict no retry
    policy may override; ``conflict_report`` — the structured three-way
    conflict document (byte-identical JSON to a local
    ``kart merge <tip> --dry-run -o json``); ``retry_after``/``shed`` —
    pacing for the busy lane."""

    def __new__(cls, kind, message, *, code=None, ref=None, terminal=False,
                conflict_report=None, retry_after=None, shed=False):
        self = super().__new__(cls, (kind, message))
        self.kind = kind
        self.message = message
        self.code = code
        self.ref = ref
        self.terminal = bool(terminal)
        self.conflict_report = conflict_report
        self.retry_after = retry_after
        self.shed = bool(shed)
        return self


def rejection_wire_fields(rejection):
    """The extra response fields ``rejection`` puts on the wire (beyond the
    kind/message every server already sends) — shared by the HTTP error
    body and the stdio error frame so the two transports report a conflict
    identically. Plain ``(kind, msg)`` tuples contribute nothing."""
    out = {}
    for name in REJECTION_WIRE_FIELDS:
        value = getattr(rejection, name, None)
        # identity checks: retry_after=0 ("retry immediately") must ride
        # the wire — `0 in (None, False)` would be True and drop it
        if value is None or value is False:
            continue
        out[name] = value
    return out


def error_attrs_from_wire(body):
    """Inverse of :func:`rejection_wire_fields` on the client: the keyword
    attrs a transport error should carry for a structured rejection body
    (``terminal``/``conflict_report``/``retry_after``/``shed``). Works on
    any dict-shaped error payload; unknown/absent fields contribute
    nothing."""
    if not isinstance(body, dict):
        return {}
    out = {}
    if body.get("terminal"):
        out["terminal"] = True
    if body.get("conflict_report") is not None:
        out["conflict_report"] = body["conflict_report"]
    if body.get("retry_after") is not None:
        out["retry_after"] = body["retry_after"]
    if body.get("shed"):
        out["shed"] = True
    return out


class ObjectEnumerator:
    """Iterable over the ``(type, content)`` pairs a receiver is missing.

    After iteration, inspect:
      * ``object_count`` — objects yielded
      * ``omitted_blob_count`` — blobs vetoed by blob_filter
      * ``shallow_boundary`` — commit oids shipped without their parents
      * ``commit_count`` — commits shipped
      * ``emitted`` — with ``record_emitted=True``, the ordered
        ``(type, oid)`` pairs yielded: the walk-free replay script the
        server's pack-enumeration cache memoizes (docs/SERVING.md §2) —
        re-reading those oids in that order reproduces the pack
        byte-identically without re-walking reachability.
    """

    def __init__(
        self,
        odb,
        wants,
        *,
        has=None,
        depth=None,
        blob_filter=None,
        sender_shallow=frozenset(),
        exclude=frozenset(),
        record_emitted=False,
    ):
        self.odb = odb
        self.wants = list(wants)
        self.has = has or (lambda oid: False)
        self.depth = depth
        self.blob_filter = blob_filter
        self.sender_shallow = set(sender_shallow)
        self.exclude = frozenset(exclude)

        self.object_count = 0
        self.omitted_blob_count = 0
        self.commit_count = 0
        self.shallow_boundary = set()
        self.emitted = [] if record_emitted else None

    # blobs are read through the native batch inflate in chunks of this many
    # (kartpack has no deltas and receivers write objects independently, so
    # stream order is free — batching is pure win for serve/clone)
    BLOB_BATCH = 10000

    def __iter__(self):
        shipped_trees = set()
        pending = []
        for commit_oid in self._select_commits():
            # excluded commits aren't re-shipped, but their trees are still
            # walked: the receiver salvaged the commit object itself, not
            # necessarily anything below it
            if commit_oid not in self.exclude:
                obj_type, content = self.odb.read_raw(commit_oid)
                if self.emitted is not None:
                    self.emitted.append((obj_type, commit_oid))
                yield obj_type, content
                self.object_count += 1
                self.commit_count += 1
            tree_oid = self._tree_oid_of(commit_oid)
            if tree_oid is not None:
                yield from self._walk_tree(tree_oid, "", shipped_trees, pending)
        yield from self._flush_blobs(pending)

    # -- commit selection --------------------------------------------------

    def _select_commits(self):
        """Commit (and tag) oids to ship, newest-first per BFS layer.
        Tag objects are shipped inline and peeled to their targets."""
        out = []
        visited = set()
        # (oid, depth) — depth counts commits from the tip, tip = 1
        frontier = []
        for want in self.wants:
            peeled = self._peel_want(want, out)
            if peeled is not None:
                frontier.append((peeled, 1))
        while frontier:
            next_frontier = []
            for oid, d in frontier:
                if oid in visited:
                    continue
                visited.add(oid)
                # with an explicit depth, keep walking even through commits
                # the receiver has — that's how a shallow clone deepens
                if self.has(oid) and self.depth is None:
                    continue
                try:
                    commit = self.odb.read_commit(oid)
                except ObjectMissing:
                    continue  # sender-side shallow/partial boundary
                if not self.has(oid):
                    out.append(oid)
                at_depth_limit = self.depth is not None and d >= self.depth
                at_sender_boundary = oid in self.sender_shallow
                if (at_depth_limit or at_sender_boundary) and commit.parents:
                    self.shallow_boundary.add(oid)
                    continue
                for p in commit.parents:
                    next_frontier.append((p, d + 1))
            frontier = next_frontier
        return out

    def _peel_want(self, oid, out):
        """Resolve a want tip to a commit oid; tag objects along the way are
        appended to ``out`` for shipping."""
        while True:
            if self.has(oid) and self.depth is None:
                return None  # with depth set, keep walking (deepening fetch)
            try:
                obj_type, content = self.odb.read_raw(oid)
            except ObjectMissing:
                return None
            if obj_type == "commit":
                return oid
            if obj_type == "tag":
                from kart_tpu.core.objects import Tag

                out.append(oid)
                oid = Tag.parse(content).target
                continue
            # tree/blob want (unusual): ship nothing here; tree walk covers it
            return None

    def _tree_oid_of(self, commit_oid):
        try:
            return self.odb.read_commit(commit_oid).tree
        except ObjectMissing:
            return None

    # -- tree walk ---------------------------------------------------------

    def _walk_tree(self, tree_oid, prefix, shipped, pending):
        if tree_oid in shipped or self.has(tree_oid):
            return
        shipped.add(tree_oid)
        try:
            entries = self.odb.read_tree_entries(tree_oid)
            _, content = self.odb.read_raw(tree_oid)
        except ObjectMissing:
            return
        # an excluded tree still recurses: the receiver may hold the tree
        # object while its blobs were lost to the disconnect (blobs ship in
        # deferred batches behind the trees that reference them)
        if tree_oid not in self.exclude:
            if self.emitted is not None:
                self.emitted.append(("tree", tree_oid))
            yield "tree", content
            self.object_count += 1
        for e in entries:
            path = f"{prefix}{e.name}"
            if e.is_tree:
                yield from self._walk_tree(e.oid, path + "/", shipped, pending)
            else:
                if e.oid in shipped or self.has(e.oid) or e.oid in self.exclude:
                    continue
                if self.blob_filter is not None and not self.blob_filter(path, e.oid):
                    self.omitted_blob_count += 1
                    continue
                shipped.add(e.oid)
                pending.append(e.oid)
                if len(pending) >= self.BLOB_BATCH:
                    yield from self._flush_blobs(pending)

    def _flush_blobs(self, pending):
        """Drain the pending blob oids: batch pack reads in bounded slices
        (so huge-blob datasets can't materialise the whole flush in RAM at
        once — the server spools the pack to disk for exactly that reason),
        per-object fallback for whatever a batch couldn't resolve (loose,
        delta, promised — promised blobs on a serving partial clone are
        omitted, as before)."""
        if not pending:
            return
        SLICE = 1000
        for i in range(0, len(pending), SLICE):
            chunk = pending[i : i + SLICE]
            batch = self.odb.read_blobs_batch(chunk)
            for oid in chunk:
                blob = batch.get(oid)
                if blob is None:
                    try:
                        _, blob = self.odb.read_raw(oid)
                    except ObjectMissing:
                        self.omitted_blob_count += 1
                        continue
                if self.emitted is not None:
                    self.emitted.append(("blob", oid))
                yield "blob", blob
                self.object_count += 1
        pending.clear()
