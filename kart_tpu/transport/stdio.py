"""SSH / stdio transport: the four transport verbs over a spawned process's
stdin/stdout.

The reference reaches ssh remotes by exec'ing its vendored git, which spawns
``ssh host git-upload-pack/receive-pack`` and speaks the smart protocol over
the pipe (kart/cli.py:211-253). The native equivalent here: the client
spawns ``ssh [user@]host kart serve-stdio <path>`` (override the ssh binary
with $KART_SSH, the remote-side kart executable with $KART_SSH_KART) and
exchanges the same framed messages the HTTP transport uses —
[8-byte header length][JSON header][kartpack bytes] — one request frame, one
response frame, any number of exchanges per connection. Promisor fetch,
shallow clones and server-side spatial filtering all ride the shared
service layer (:mod:`kart_tpu.transport.service`), so semantics are
byte-identical to the HTTP server's.

URL forms (git's own):

    ssh://[user@]host[:port]/abs/path
    [user@]host:path        (scp-like)
"""

import json
import os
import shlex
import subprocess
import time

from kart_tpu.telemetry import access as rq_access
from kart_tpu.telemetry import context as rq_context
from kart_tpu.transport.http import (
    _HEADER_LEN,
    _CountingReader,
    HttpTransportError,
    read_framed,
    write_framed,
)
from kart_tpu.transport.pack import read_pack

#: how long the client waits for a response frame to *start* before the
#: hung-ssh watchdog kills the transport process (the server spools its
#: whole pack before the first response byte, so keep this generous);
#: env KART_STDIO_TIMEOUT overrides, <= 0 disables.
DEFAULT_STDIO_TIMEOUT = 600.0


def stdio_timeout():
    try:
        return float(os.environ.get("KART_STDIO_TIMEOUT", DEFAULT_STDIO_TIMEOUT))
    except (TypeError, ValueError):
        return DEFAULT_STDIO_TIMEOUT


class StdioTransportError(HttpTransportError):
    """Transport failure over the spawned-process pipe. Subclasses the HTTP
    error so remote.py's error handling covers both wire transports."""


def parse_ssh_url(url):
    """-> (userhost, port|None, path) for an ssh URL, or None.

    A userhost or path beginning with '-' is rejected: it would reach the
    spawned ssh as an option (the git CVE-2017-1000117 class — e.g.
    '-oProxyCommand=...' executing locally)."""

    def checked(userhost, port, path):
        if userhost.startswith("-") or path.startswith("-"):
            return None
        if port is not None and not str(port).isdigit():
            # the port rides ssh's argv after '-p'; digits-only keeps any
            # crafted string from reaching ssh as something else entirely
            return None
        return userhost, port, path

    if url.startswith("ssh://"):
        rest = url[len("ssh://"):]
        hostpart, slash, path = rest.partition("/")
        if not slash:
            return None
        port = None
        userhost = hostpart
        user, at, host = hostpart.rpartition("@")
        if host.startswith("["):  # bracketed IPv6: [::1] or [::1]:2222
            addr, bracket, tail = host.partition("]")
            if not bracket:
                return None
            userhost = (user + at if at else "") + addr[1:]
            if tail.startswith(":"):
                port = tail[1:]
            elif tail:
                return None
        elif ":" in host:
            hostonly, _, port = host.rpartition(":")
            userhost = (user + at if at else "") + hostonly
        return checked(userhost, port, "/" + path)
    if "://" in url:
        return None
    # scp-like [user@]host:path — no '/' before the colon, and not a
    # one-letter head (Windows drive)
    head, sep, path = url.partition(":")
    if sep and "/" not in head and len(head) > 1 and path:
        return checked(head, None, path)
    return None


def is_ssh_url(url):
    return parse_ssh_url(url) is not None


class StdioRemote:
    """Client half: mirrors HttpRemote's verb API over one spawned process.
    The subprocess starts lazily and is reused across calls (one ssh
    connection per remote instance, like git).

    Fault tolerance mirrors HttpRemote: idempotent verbs retry under
    ``retry`` (the connection is respawned between attempts — a failed RPC
    leaves the pipe desynced), ``fetch_pack`` resumes via oid exclusion,
    ``receive_pack`` retries only on spawn failure (pre-write). A hung ssh
    (dead relay, wedged server) is bounded by a watchdog that kills the
    transport process when a response frame doesn't start within
    $KART_STDIO_TIMEOUT seconds."""

    def __init__(self, url, retry=None):
        from kart_tpu.transport.retry import RetryPolicy

        self.url = url
        parsed = parse_ssh_url(url)
        if parsed is None:
            raise StdioTransportError(f"Not an ssh remote: {url!r}")
        self.userhost, self.port, self.path = parsed
        self.retry = retry if retry is not None else RetryPolicy.from_config()
        self._proc = None

    # -- process management --------------------------------------------------

    def _command(self):
        ssh = shlex.split(os.environ.get("KART_SSH", "ssh"))
        kart = os.environ.get("KART_SSH_KART", "kart")
        cmd = list(ssh)
        if self.port:
            cmd += ["-p", str(self.port)]
        cmd += [self.userhost, f"{kart} serve-stdio {shlex.quote(self.path)}"]
        return cmd

    def _ensure(self):
        if self._proc is not None and self._proc.poll() is None:
            return self._proc
        try:
            self._proc = subprocess.Popen(
                self._command(),
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                # stderr passes through: ssh auth prompts/errors stay visible
            )
        except OSError as e:
            raise StdioTransportError(
                f"Cannot spawn transport for {self.url!r}: {e}",
                transient=True,
                pre_write=True,  # nothing was spawned: no byte reached anyone
            )
        return self._proc

    def close(self, timeout=5.0):
        """Shut the transport process down, bounded: close the pipes, wait
        up to ``timeout`` for a clean exit, then kill. Never raises from
        callers' cleanup paths, never leaves a zombie (the post-kill wait
        reaps), and a second close() is a no-op."""
        proc, self._proc = self._proc, None
        if proc is None:
            return
        for fp in (proc.stdin, proc.stdout):
            try:
                if fp is not None:
                    fp.close()
            except OSError:
                pass
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            # a wedged remote must not leak an ssh process or hang us
            proc.kill()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:  # pragma: no cover - kernel lag
                pass

    def reset(self, *_):
        """Between retries: a failed RPC leaves the pipe desynced, so drop
        the process; the next RPC respawns."""
        self.close(timeout=1.0)

    def __del__(self):  # best-effort; close() is the real API
        try:
            # interpreter shutdown must not stall behind a wedged ssh —
            # give it a moment, then kill
            self.close(timeout=0.5)
        except Exception:  # kart: noqa(KTL006): __del__ at interpreter shutdown — modules may already be torn down; close() is the real API and raises normally
            pass

    # -- framing -------------------------------------------------------------

    def _watchdog_kill(self):
        proc = self._proc
        if proc is not None and proc.poll() is None:
            proc.kill()

    class _TouchReader:
        """File wrapper marking watchdog progress on every completed read,
        so the hung-ssh bound is an *inactivity* timeout over the whole
        response — header AND pack body — not a cap on transfer time."""

        __slots__ = ("_fp", "_wd")

        def __init__(self, fp, wd):
            self._fp = fp
            self._wd = wd

        def read(self, n=-1):
            data = self._fp.read(n)
            self._wd.touch()
            return data

    def _rpc(self, header, objects=(), drain=None):
        """Send one framed request; -> (response header, drain result).
        ``drain(pack_fp)`` consumes the response pack *inside* the
        hung-transport watchdog (re-armed on every read, so a stalled peer
        dies within the budget of its last byte while a slow-but-flowing
        transfer runs to completion); by default the (empty) pack is
        discarded."""
        from kart_tpu.runtime import Watchdog

        # trace-context wire field (docs/OBSERVABILITY.md §8): the server
        # adopts this request's id for its spans/access-log lines
        traceparent = rq_context.current_traceparent()
        if traceparent is not None:
            if callable(header):
                inner = header
                header = lambda: {  # noqa: E731 - deferred header, same shape
                    **inner(),
                    rq_context.TRACEPARENT_HEADER: traceparent,
                }
            else:
                header = {
                    **header, rq_context.TRACEPARENT_HEADER: traceparent
                }
        proc = self._ensure()
        try:
            write_framed(proc.stdin, header, objects)
            proc.stdin.flush()
        except (OSError, ValueError) as e:
            raise StdioTransportError(
                f"Transport for {self.url!r} died while sending: {e}",
                transient=True,
            )
        with Watchdog(stdio_timeout(), self._watchdog_kill) as wd:
            guarded = self._TouchReader(proc.stdout, wd)

            def stalled():
                return StdioTransportError(
                    f"Remote {self.url!r} did not respond within "
                    f"{stdio_timeout():.0f}s (killed; set "
                    f"KART_STDIO_TIMEOUT to wait longer)",
                    transient=True,
                )

            try:
                resp, pack_fp = read_framed(guarded)
            except HttpTransportError:
                if wd.fired:
                    raise stalled()
                rc = proc.poll()
                raise StdioTransportError(
                    f"Remote {self.url!r} closed the connection"
                    + (f" (exit code {rc})" if rc is not None else ""),
                    transient=True,
                )
            if "error" in resp:
                # drain the (empty) pack so the pipe stays usable
                for _ in read_pack(pack_fp):
                    pass
                from kart_tpu.transport.protocol import error_attrs_from_wire

                # structured-rejection fields (terminal verdicts, the
                # conflict report, busy pacing) ride the error frame so the
                # ssh transport reports a contended push exactly like HTTP
                raise StdioTransportError(
                    f"Remote {self.url!r} error: {resp['error']}",
                    **error_attrs_from_wire(resp),
                )
            try:
                if drain is None:
                    for _ in read_pack(pack_fp):
                        pass
                    result = None
                else:
                    result = drain(pack_fp)
            except (OSError, ValueError) as e:
                if wd.fired:
                    raise stalled() from e
                raise
        return resp, result

    # -- verbs (HttpRemote-compatible) ---------------------------------------

    def ls_refs(self):
        # one request scope per verb call (retry attempts share the id on
        # the wire — the server logs one logical request, N attempts)
        with rq_context.request_scope(verb="ls-refs"):
            return self.retry.call(
                lambda: self._rpc({"op": "refs"})[0],
                label="ls-refs",
                on_retry=self.reset,
            )

    def events(self, since=None, *, timeout=5.0):
        """One live-update events poll (the ``events`` op;
        docs/EVENTS.md §5): -> the response document (``events``/``head``
        and optional ``reset``). ``since=None`` is the subscribe
        handshake (current head, no wait)."""
        frame = {"op": "events", "timeout": timeout}
        if since is not None:
            frame["since"] = int(since)
        with rq_context.request_scope(verb="events"):
            resp = self.retry.call(
                lambda: self._rpc(frame)[0],
                label="events",
                on_retry=self.reset,
            )
        if resp.get("error"):
            raise StdioTransportError(resp["error"])
        return resp

    def fetch_pack(self, dst_repo, wants, *, haves=(), have_shallow=(),
                   depth=None, filter_spec=None, exclude=None):
        from kart_tpu.transport.retry import drain_pack_salvaging, exclude_arg

        received = exclude if isinstance(exclude, set) else set(exclude or ())

        def attempt():
            resp, _ = self._rpc(
                {
                    "op": "fetch-pack",
                    "wants": list(wants),
                    "haves": list(haves),
                    "have_shallow": sorted(have_shallow),
                    "depth": depth,
                    "filter": filter_spec,
                    "exclude": exclude_arg(received),
                },
                drain=lambda fp: drain_pack_salvaging(dst_repo.odb, fp, received),
            )
            return resp

        with rq_context.request_scope(verb="fetch-pack"):
            return self.retry.call(
                attempt, label="fetch-pack", on_retry=self.reset
            )

    def fetch_blobs(self, dst_repo, oids):
        from kart_tpu.transport.retry import drain_pack_salvaging

        received = set()

        def attempt():
            want = [o for o in oids if o not in received]
            if not want:
                return {}
            resp, _ = self._rpc(
                {"op": "fetch-blobs", "oids": want},
                drain=lambda fp: drain_pack_salvaging(dst_repo.odb, fp, received),
            )
            return resp

        with rq_context.request_scope(verb="fetch-blobs"):
            resp = self.retry.call(
                attempt, label="fetch-blobs", on_retry=self.reset
            )
        if resp.get("missing"):
            raise StdioTransportError(
                f"Remote is missing promised objects: {resp['missing'][:5]}"
            )
        return len(received)

    def receive_pack(self, objects, updates, *, shallow=()):
        """Not idempotent: only spawn failures (pre-write — no byte reached
        the server) and the server's paced busy rejections (merge queue
        full / CAS budget exhausted — provably applied nothing) are
        retried; a structured conflict rejection is terminal. -> the full
        receive payload ``{"updated": ..., "rebase": ...}``, like
        HttpRemote."""
        from kart_tpu.transport.retry import is_pre_write

        def retryable(exc):
            return is_pre_write(exc) or getattr(exc, "shed", False)

        def attempt():
            resp, _ = self._rpc(
                lambda: {
                    "op": "receive-pack",
                    "updates": updates,
                    "shallow": sorted(shallow() if callable(shallow) else shallow),
                },
                objects,
            )
            return resp

        with rq_context.request_scope(verb="receive-pack"):
            return self.retry.call(
                attempt, label="receive-pack", retryable=retryable,
                on_retry=self.reset,
            )


# ---------------------------------------------------------------------------
# server side: `kart serve-stdio <path>`
# ---------------------------------------------------------------------------


#: known stdio ops -> the HTTP server's verb labels (one name per verb
#: across transports); anything else books as "other"
_STDIO_VERBS = {
    "refs": "ls-refs",
    "stats": "stats",
    "events": "events",
    "fetch-pack": "fetch-pack",
    "fetch-blobs": "fetch-blobs",
    "receive-pack": "receive-pack",
}


def serve_stdio(repo, in_fp, out_fp):
    """Serve one connection: read framed requests from ``in_fp`` until EOF,
    answer each on ``out_fp``. stdout discipline is absolute — anything else
    the process prints must go to stderr or the frames corrupt.

    Every op runs inside a request scope adopted from the frame's
    ``traceparent`` field (echoed back on the response frame), under a
    ``transport.request`` span, and books one access-log record — the
    stdio server reports requests exactly like the HTTP server."""
    from kart_tpu import telemetry as tm
    from kart_tpu.transport.pack import PackFormatError
    from kart_tpu.transport.service import (
        collect_blobs,
        ls_refs_info,
        quarantined_receive,
        serve_fetch_pack,
    )

    # a spawned server honours KART_LOG (stderr only — stdout is frames)
    # and serves its metric registry via the "stats" op
    tm.configure_logging()
    tm.enable(metrics=True)
    in_c = _CountingReader(in_fp)
    out_c = _CountingReader(out_fp)

    while True:
        raw = in_c.read(_HEADER_LEN.size)
        if not raw:
            return  # clean EOF: client closed the connection
        if len(raw) != _HEADER_LEN.size:
            raise StdioTransportError("Truncated request frame")
        (n,) = _HEADER_LEN.unpack(raw)
        if n > 1 << 24:
            raise StdioTransportError("Request header implausibly large")
        try:
            header = json.loads(in_c.read(n).decode())
        except ValueError as e:
            # stream position is unknowable now: answer + close
            write_framed(out_c, {"error": f"Bad request header: {e}"}, ())
            out_c.flush()
            return
        op = header.get("op")
        # access-log/histogram verb labels: known ops map to the HTTP
        # server's names (the "refs" op is the ls-refs verb); anything
        # else is "other" — a client-chosen junk op must not mint
        # unbounded metric label values or write itself into the access
        # log (the HTTP side gets the same from _verb_for)
        verb = _STDIO_VERBS.get(op, "other")

        t0 = time.perf_counter()
        in0, out0 = in_c.count, out_c.count
        status = "ok"
        keep_serving = True
        with rq_context.request_scope(
            verb=verb,
            traceparent=header.get(rq_context.TRACEPARENT_HEADER),
            record=rq_access.slow_threshold() is not None,
            # a frame without a traceparent mints a fresh trace — it must
            # not inherit this process's own CLI root context
            inherit=False,
        ) as ctx:
            # the response frame echoes the context back to the client —
            # both directions of the wire carry the same request id
            echo = {rq_context.TRACEPARENT_HEADER: ctx.traceparent()}

            def respond(frame_header, objects=()):
                if callable(frame_header):
                    inner = frame_header
                    write_framed(
                        out_c, lambda: {**inner(), **echo}, objects
                    )
                else:
                    write_framed(out_c, {**frame_header, **echo}, objects)

            try:
                with tm.span("transport.request", verb=verb):
                    if op == "receive-pack":
                        # the request pack drains into quarantine and
                        # migrates only after checksum + ref preconditions
                        # pass (a torn push leaves the store byte-identical
                        # and desyncs the stream, handled by the
                        # PackFormatError close below); a CAS lost to a
                        # contending writer is auto-rebased server-side,
                        # and a structured rejection's extras ride the
                        # error frame
                        from kart_tpu.transport.protocol import (
                            rejection_wire_fields,
                        )

                        result = quarantined_receive(repo, header, in_c)
                        if result[0] == "ok":
                            respond(result[1])
                        else:
                            status = result[0]
                            frame = {"error": result[1], "status": result[0]}
                            frame.update(rejection_wire_fields(result))
                            respond(frame)
                    else:
                        # every other op carries an empty request pack
                        for _ in read_pack(in_c):
                            pass
                        if op == "refs":
                            respond(ls_refs_info(repo))
                        elif op == "stats":
                            from kart_tpu.telemetry import sinks

                            tm.incr(
                                "transport.server.requests", verb="stats"
                            )
                            if header.get("format") == "json":
                                import sys as _sys

                                extra = {}
                                events_mod = _sys.modules.get(
                                    "kart_tpu.events"
                                )
                                if (
                                    events_mod is not None
                                    and events_mod.events_enabled()
                                ):
                                    emitter = events_mod.active_emitter(
                                        repo.gitdir
                                    )
                                    if emitter is not None:
                                        extra["events"] = (
                                            emitter.status_dict()
                                        )
                                query_mod = _sys.modules.get(
                                    "kart_tpu.query"
                                )
                                if query_mod is not None:
                                    extra["query"] = (
                                        query_mod.status_dict()
                                    )
                                respond(
                                    {
                                        "stats": rq_access.stats_payload(
                                            extra=extra
                                        )
                                    }
                                )
                            else:
                                respond({"metrics": sinks.prometheus_text()})
                        elif op == "events":
                            # the stdio twin of GET /api/v1/events
                            # (docs/EVENTS.md §5): resume-by-sequence with
                            # a bounded wait — each ssh exchange is one
                            # poll; true long-holding streams are the HTTP
                            # transport's job
                            from kart_tpu import events as events_mod

                            tm.incr(
                                "transport.server.requests", verb="events"
                            )
                            if not events_mod.events_enabled():
                                status = "error"
                                respond({"error": "Event serving is "
                                                  "disabled on this server"})
                            else:
                                emitter = events_mod.emitter_for(repo)
                                since = header.get("since")
                                if since is None:
                                    emitter.reconcile()
                                    respond({"events": [],
                                             "head": emitter.log.head()})
                                else:
                                    try:
                                        wait_s = min(
                                            float(header.get("timeout", 5.0)),
                                            events_mod.LONG_POLL_SECONDS,
                                        )
                                    except (TypeError, ValueError):
                                        wait_s = 5.0
                                    evs, head, reset = emitter.wait_events(
                                        int(since), max(0.0, wait_s)
                                    )
                                    frame = {"events": evs, "head": head}
                                    if reset is not None:
                                        frame["reset"] = reset
                                    respond(frame)
                        elif op == "fetch-pack":
                            # same code path and counters as the HTTP
                            # server, but uncached: a serve-stdio process
                            # serves exactly one connection and a client
                            # retry respawns it, so a memo could never be
                            # re-hit. The plan streams straight to the pipe
                            # (no materialise spool — stdio has no
                            # byte-range to serve from an offset)
                            plan = serve_fetch_pack(
                                repo, header, use_cache=False
                            )
                            respond(plan.header, plan.source)
                        elif op == "fetch-blobs":
                            resp_header, objects = collect_blobs(
                                repo, header.get("oids", [])
                            )
                            respond(resp_header, objects)
                        else:
                            status = "error"
                            respond({"error": f"Unknown op {op!r}"})
            except PackFormatError as e:
                # a corrupt request pack desyncs the stream: answer + close
                status = "error"
                keep_serving = False
                respond({"error": f"Bad request pack: {e}"})
            except Exception as e:
                # op-level failure (bad filter spec, missing object, ...):
                # the request was fully read, so report and keep serving —
                # the HTTP server's 500 equivalent
                status = "error"
                respond({"error": f"{type(e).__name__}: {e}"})
            finally:
                rq_access.record_request(
                    verb=verb,
                    status=status,
                    bytes_in=in_c.count - in0,
                    bytes_out=out_c.count - out0,
                    seconds=time.perf_counter() - t0,
                    ctx=ctx,
                )
        out_c.flush()
        if not keep_serving:
            return
