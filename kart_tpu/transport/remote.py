"""Remote operations: clone / fetch / push / pull over local-path remotes.

The reference delegates these verbs to its forked git via execvp
(kart/cli.py:211-253) and layers kart semantics (spatial-filtered partial
clone, promisor fetch) on top (kart/clone.py, kart/repo.py:269-343,
kart/promisor_utils.py).  Here they are native: a remote is any URL
``open_remote`` can turn into an object store + ref store; local directories
and ``file://`` URLs are the built-in transport (exactly what the
reference's own tests use as remotes, SURVEY.md §4).

Every transfer — even store-to-store on one machine — is routed through the
kartpack wire format, so the byte path is the same one a network transport
would use.
"""

import os
import sys
import tempfile

from kart_tpu import telemetry as tm
from kart_tpu.core.odb import ObjectMissing
from kart_tpu.core.refs import RefError, check_ref_format
from kart_tpu.core.repo import KartRepo, KartConfigKeys, NotFound
from kart_tpu.transport.pack import PackFormatError, read_pack, write_pack
from kart_tpu.transport.protocol import ObjectEnumerator

SHALLOW_FILE = "shallow"

#: gitdir marker for an in-flight network fetch — like git's shallow
#: machinery, its survival past process death is the signal that the local
#: store may hold a salvaged partial transfer, so the next fetch resumes
#: (excluding every object already present) instead of starting over.
FETCH_RESUME_FILE = "FETCH_RESUME"


class RemoteError(ValueError):
    pass


class Remote:
    """A named remote from repo config (remote.<name>.*)."""

    def __init__(self, repo, name):
        self.repo = repo
        self.name = name

    @property
    def url(self):
        url = self.repo.config.get(f"remote.{self.name}.url")
        if url is None:
            raise RemoteError(f"No such remote: {self.name!r}")
        return url

    @property
    def is_promisor(self):
        return self.repo.config.get_bool(f"remote.{self.name}.promisor")

    @property
    def partial_clone_filter(self):
        return self.repo.config.get(f"remote.{self.name}.partialclonefilter")

    def open(self) -> KartRepo:
        return open_remote(self.url)


def is_http_url(url):
    return url.startswith("http://") or url.startswith("https://")


def network_remote(url, retry=None):
    """The wire client for a network URL — HttpRemote for http(s),
    StdioRemote for ssh:// / scp-like — or None for local paths. Both
    clients speak the same verb API (ls_refs / fetch_pack / fetch_blobs /
    receive_pack), so every caller is transport-agnostic. ``retry``: a
    RetryPolicy (defaults to env/config resolution inside the client)."""
    if is_http_url(url):
        from kart_tpu.transport.http import HttpRemote

        return HttpRemote(url, retry=retry)
    from kart_tpu.transport.stdio import StdioRemote, is_ssh_url

    if is_ssh_url(url):
        return StdioRemote(url, retry=retry)
    return None


def open_remote(url) -> KartRepo:
    """Resolve a *local* remote URL to a repository (local paths + file://).
    Network remotes don't open as repos — the fetch/push/clone verbs route
    them through their wire client instead."""
    if url.startswith("file://"):
        url = url[len("file://") :]
    from kart_tpu.transport.stdio import is_ssh_url

    if is_http_url(url) or is_ssh_url(url):
        raise RemoteError(
            f"Network remote {url!r} has no local repository to open"
        )
    if "://" in url:
        raise RemoteError(
            f"Unsupported remote URL scheme: {url!r} "
            f"(local paths, file://, http(s):// and ssh:// only)"
        )
    try:
        repo = KartRepo(url)
    except NotFound:
        raise RemoteError(f"Remote repository not found: {url!r}")
    # the URL must BE the repo, not merely live inside one — KartRepo's
    # parent-directory search must not silently resolve a bad remote path to
    # whatever repo happens to enclose it
    target = os.path.realpath(url)
    if os.path.realpath(repo.workdir or repo.gitdir) != target:
        raise RemoteError(f"Remote repository not found: {url!r}")
    return repo


def normalise_url(url):
    """Local-path URLs are stored absolute, so the remote resolves no matter
    what directory later commands run from."""
    from kart_tpu.transport.stdio import is_ssh_url

    if url.startswith("file://") or "://" in url or is_ssh_url(url):
        return url
    return os.path.abspath(url)


def add_remote(repo, name, url):
    if repo.config.get(f"remote.{name}.url") is not None:
        raise RemoteError(f"Remote {name!r} already exists")
    repo.config.set_many(
        {
            f"remote.{name}.url": normalise_url(url),
            f"remote.{name}.fetch": f"+refs/heads/*:refs/remotes/{name}/*",
        }
    )


def remove_remote(repo, name):
    import shutil

    if repo.config.get(f"remote.{name}.url") is None:
        raise RemoteError(f"No such remote: {name!r}")
    for key in list(repo.config.keys(f"remote.{name}.")):
        del repo.config[key]
    # remove the whole tracking-ref directory (iter_refs skips symref files
    # like refs/remotes/<name>/HEAD, so per-ref deletion would leave it)
    shutil.rmtree(
        os.path.join(repo.gitdir, "refs", "remotes", name), ignore_errors=True
    )


# -- shallow bookkeeping ---------------------------------------------------


def read_shallow(repo):
    content = repo.read_gitdir_file(SHALLOW_FILE)
    if not content:
        return set()
    return {line.strip() for line in content.splitlines() if line.strip()}


def write_shallow(repo, oids):
    if oids:
        repo.write_gitdir_file(SHALLOW_FILE, "".join(o + "\n" for o in sorted(oids)))
    else:
        repo.remove_gitdir_file(SHALLOW_FILE)


def _update_shallow(repo, new_boundary):
    """Recompute the shallow file after a transfer: a commit is shallow iff
    any of its parents is still absent — so a deepening fetch un-shallows
    commits whose parents just arrived."""
    candidates = read_shallow(repo) | set(new_boundary)
    if not candidates:
        return
    still_shallow = set()
    for oid in candidates:
        try:
            parents = repo.odb.read_commit(oid).parents
        except ObjectMissing:
            continue  # the boundary commit itself is gone; drop the entry
        if any(not repo.odb.contains(p) for p in parents):
            still_shallow.add(oid)
    write_shallow(repo, still_shallow)


def _retry_policy(repo, remote_name):
    """The retry/backoff policy for this remote (env > remote.<name>.*
    config > defaults; see kart_tpu.transport.retry)."""
    from kart_tpu.transport.retry import RetryPolicy

    return RetryPolicy.from_config(repo.config, remote_name)


_OID_RE = None


def _write_resume_marker(repo, remote_name, salvaged):
    """Record the in-flight fetch + the oids salvaged so far (bounded) so a
    later process can resume without rescanning the store."""
    from kart_tpu.transport.retry import EXCLUDE_CAP

    lines = [remote_name, *sorted(salvaged or ())[:EXCLUDE_CAP]]
    repo.write_gitdir_file(FETCH_RESUME_FILE, "\n".join(lines))


def _read_resume_exclusions(repo):
    """-> the exclusion seed for this fetch: oids recorded in a surviving
    FETCH_RESUME marker; if the marker exists but carries none (the
    process was hard-killed before it could record them), fall back to
    scanning the local store (bounded — exclusions are an optimisation,
    missing some merely re-ships a little)."""
    import itertools
    import re

    from kart_tpu.transport.retry import EXCLUDE_CAP

    content = repo.read_gitdir_file(FETCH_RESUME_FILE)
    if content is None:
        return set()
    global _OID_RE
    if _OID_RE is None:
        _OID_RE = re.compile(r"^[0-9a-f]{40}$")
    oids = {
        line for line in content.splitlines()[1:] if _OID_RE.fullmatch(line)
    }
    if oids:
        return oids
    return set(itertools.islice(repo.odb.iter_oids(), EXCLUDE_CAP))


# -- the wire --------------------------------------------------------------


def _transfer(src_odb, dst_odb, wants, *, depth=None, blob_filter=None, sender_shallow=frozenset()):
    """Ship objects reachable from wants (minus what dst has) src→dst through
    a kartpack stream. Returns the ObjectEnumerator (for counts/boundary)."""
    enum = ObjectEnumerator(
        src_odb,
        wants,
        has=dst_odb.contains,
        depth=depth,
        blob_filter=blob_filter,
        sender_shallow=sender_shallow,
    )
    with tempfile.SpooledTemporaryFile(max_size=64 * 1024 * 1024) as wire:
        write_pack(wire, iter(enum))
        wire.seek(0)
        # received objects land in one new pack, not a loose file each (a
        # 1M-feature clone would otherwise create a million files)
        with dst_odb.bulk_pack():
            for obj_type, content in read_pack(wire):
                dst_odb.write_raw(obj_type, content)
    return enum


# -- fetch -----------------------------------------------------------------


def fetch(repo, remote_name="origin", *, depth=None, filter_spec=None, quiet=True):
    """Fetch all branches + tags from the remote into refs/remotes/<name>/*.
    Returns {local_ref: oid} of updated refs.

    filter_spec: 'w,s,e,n' spatial filter argument evaluated on the sending
    side (local remotes build the callable here; HTTP remotes evaluate it on
    the server, like the reference's upload-pack filter extension)."""
    remote = Remote(repo, remote_name)

    if filter_spec is None and remote.is_promisor:
        # re-fetch from a promisor remote keeps filtering (reference:
        # remote.*.partialclonefilter persists after clone)
        spec = remote.partial_clone_filter
        if spec and spec.startswith("extension:spatial="):
            filter_spec = spec[len("extension:spatial=") :]

    net = network_remote(remote.url, retry=_retry_policy(repo, remote_name))
    if net is not None:
        from kart_tpu.transport.http import HttpTransportError

        # A FETCH_RESUME marker surviving from an earlier process means that
        # fetch died mid-transfer and its salvage is sitting in our store:
        # seed the exclusion set so the server ships only the remainder
        # (content addressing makes the salvaged objects exactly as
        # trustworthy as a completed transfer's). The client mutates the
        # set in place, so even a failed retry chain leaves us knowing
        # everything that landed. This is the *cross-process* resume lane;
        # within one process the HTTP client's retry loop additionally
        # resumes mid-pack by byte range, sending the offset it already
        # holds (docs/SERVING.md §3).
        exclude = _read_resume_exclusions(repo)
        if exclude:
            tm.incr("transport.resume_seeded_oids", len(exclude))
        # one fetch = one trace: the verb calls below (ls-refs, fetch-pack
        # and each retry attempt inside them) inherit this scope's trace
        # id, so the whole retry ladder correlates with the server's
        # access-log/span records (docs/OBSERVABILITY.md §8) even when no
        # CLI root context exists (library use, bench workers)
        try:
            with tm.request_scope(verb="fetch", remote=remote_name):
                info = net.ls_refs()
                branch_tips = info["heads"]
                tag_tips = info["tags"]
                head_branch = info.get("head_branch")
                wants = list(branch_tips.values()) + list(tag_tips.values())
                repo.write_gitdir_file(FETCH_RESUME_FILE, remote_name)
                header = net.fetch_pack(
                    repo,
                    wants,
                    haves=[oid for _, oid in repo.refs.iter_refs("refs/")],
                    have_shallow=read_shallow(repo),
                    depth=depth,
                    filter_spec=filter_spec,
                    exclude=exclude,
                )
        except (HttpTransportError, PackFormatError, OSError) as e:
            # the marker stays — now carrying the salvaged oids, so the
            # next `kart fetch` resumes without rescanning the store
            _write_resume_marker(repo, remote_name, exclude)
            raise RemoteError(str(e))
        finally:
            net.close()
        repo.remove_gitdir_file(FETCH_RESUME_FILE)
        shallow_boundary = set(header.get("shallow_boundary", ()))
    else:
        src = remote.open()
        branch_tips = {}  # branch name -> oid
        tag_tips = {}
        for ref, oid in src.refs.iter_refs("refs/heads/"):
            branch_tips[ref[len("refs/heads/") :]] = oid
        for ref, oid in src.refs.iter_refs("refs/tags/"):
            tag_tips[ref[len("refs/tags/") :]] = oid
        wants = list(branch_tips.values()) + list(tag_tips.values())

        blob_filter = None
        if filter_spec is not None:
            from kart_tpu.spatial_filter import blob_filter_for_spec

            blob_filter = blob_filter_for_spec(src, filter_spec)

        enum = _transfer(
            src.odb,
            repo.odb,
            wants,
            depth=depth,
            blob_filter=blob_filter,
            sender_shallow=read_shallow(src),
        )
        shallow_boundary = enum.shallow_boundary
        kind, target = src.refs.head_target()
        head_branch = (
            target[len("refs/heads/") :]
            if kind == "symbolic" and target.startswith("refs/heads/")
            else None
        )

    updated = {}
    skipped = []
    for branch, oid in branch_tips.items():
        local_ref = f"refs/remotes/{remote_name}/{branch}"
        # Server-supplied names get the same refname-format rules the
        # receive-pack side enforces — a hostile/buggy server must not be
        # able to plant 'x.lock'/'..'/control-char names under refs/.
        try:
            check_ref_format(local_ref, require_refs_prefix=True)
        except RefError:
            skipped.append(branch)
            continue
        if repo.refs.get(local_ref) != oid:
            repo.refs.set(local_ref, oid, log_message=f"fetch {remote_name}")
            updated[local_ref] = oid
    for tag, oid in tag_tips.items():
        local_ref = f"refs/tags/{tag}"
        try:
            check_ref_format(local_ref, require_refs_prefix=True)
        except RefError:
            skipped.append(tag)
            continue
        if repo.refs.get(local_ref) is None:
            repo.refs.set(local_ref, oid, log_message=f"fetch {remote_name}")
            updated[local_ref] = oid
    if skipped:
        print(
            f"warning: ignored {len(skipped)} invalid remote ref name(s): "
            + ", ".join(repr(s) for s in skipped[:5]),
            file=sys.stderr,
        )

    _update_shallow(repo, shallow_boundary)

    # remote HEAD symref, so clone knows the default branch
    if head_branch is not None:
        head_path = os.path.join(
            repo.gitdir, "refs", "remotes", remote_name, "HEAD"
        )
        os.makedirs(os.path.dirname(head_path), exist_ok=True)
        with open(head_path, "w") as f:
            f.write(f"ref: refs/remotes/{remote_name}/{head_branch}\n")
    return updated


# -- push ------------------------------------------------------------------


def parse_refspec(repo, refspec):
    """'+src:dst' / 'src:dst' / 'src' / ':dst'(delete) -> (src, dst, force)."""
    force = refspec.startswith("+")
    if force:
        refspec = refspec[1:]
    src, sep, dst = refspec.partition(":")
    if not sep:
        dst = src
    return src or None, dst or src, force


def _resolve_push_source(repo, src_name):
    src_ref = src_name if src_name.startswith("refs/") else f"refs/heads/{src_name}"
    new_oid = repo.refs.get(src_ref)
    if new_oid is None:
        try:
            new_oid = repo.resolve_refish(src_name)[0]
        except NotFound:
            new_oid = None
    if new_oid is None:
        raise RemoteError(f"Unknown ref to push: {src_name!r}")
    return src_ref, new_oid


def _record_push_tracking(repo, remote_name, src_ref, dst_ref, new_oid, set_upstream):
    """Mirror a successful push into refs/remotes/<name>/* (+ upstream cfg)."""
    if not dst_ref.startswith("refs/heads/"):
        return
    track = f"refs/remotes/{remote_name}/{dst_ref[len('refs/heads/'):]}"
    repo.refs.set(track, new_oid, log_message="update by push")
    if set_upstream and src_ref.startswith("refs/heads/"):
        b = src_ref[len("refs/heads/") :]
        repo.config.set_many(
            {f"branch.{b}.remote": remote_name, f"branch.{b}.merge": dst_ref}
        )


def render_push_conflict(report):
    """The client-side rendering of a server's structured conflict report:
    the same hierarchical text a local ``kart merge`` prints for the same
    two commits (one renderer — docs/SERVING.md §6)."""
    from kart_tpu.cli.merge_cmds import conflict_report_as_text

    ref = report.get("ref", "the remote branch")
    lines = [
        f"Push to {ref} rejected: merging your commit "
        f"{report.get('ours', '?')[:8]} with the remote tip "
        f"{report.get('theirs', '?')[:8]} results in "
        f"{report.get('conflicts_total', '?')} conflicts:",
    ]
    summary = (report.get("merge") or {}).get("kart.merge/v1", {}).get(
        "conflicts"
    )
    if summary:
        import click

        # unstyle: the renderer colours version headers for terminals, but
        # this text travels inside an exception message
        lines.append(
            click.unstyle(conflict_report_as_text(summary).rstrip("\n"))
        )
    lines.append(
        "Fetch, merge and resolve locally (`kart fetch` + `kart merge`), "
        "then push the result. Re-pushing unchanged commits will conflict "
        "again."
    )
    return "\n".join(lines)


def _push_network(repo, remote_name, net, refspecs, *, force, set_upstream):
    """Push over a wire transport (HTTP or ssh/stdio): client-side
    enumeration against the server's declared tips, compare-and-swap ref
    updates server-side. A CAS lost to a contending writer — or a tip that
    had already moved past us when we looked — is resolved by the
    *server's* auto-rebase (docs/SERVING.md §6): clean merges land without
    any client round-trip, real conflicts come back as one terminal
    structured report rendered like a local ``kart merge`` conflict."""
    # one push = one trace (see the matching scope in fetch())
    with tm.request_scope(verb="push", remote=remote_name):
        return _push_network_traced(
            repo, remote_name, net, refspecs, force=force,
            set_upstream=set_upstream,
        )


def _push_network_traced(repo, remote_name, net, refspecs, *, force,
                         set_upstream):
    from kart_tpu.transport.http import HttpTransportError, have_closure

    try:
        info = net.ls_refs()
    except HttpTransportError as e:
        raise RemoteError(str(e))
    server_refs = {f"refs/heads/{b}": o for b, o in info["heads"].items()}
    server_refs.update({f"refs/tags/{t}": o for t, o in info["tags"].items()})
    # one reachability walk for all refspecs — the server's tips don't
    # change between them
    has_set = None

    updated = {}
    for spec in refspecs:
        src_name, dst_name, spec_force = parse_refspec(repo, spec)
        spec_force = spec_force or force
        dst_ref = (
            dst_name if dst_name.startswith("refs/") else f"refs/heads/{dst_name}"
        )
        try:
            if src_name is None:  # delete
                if dst_ref not in server_refs:
                    raise RemoteError(f"Remote ref does not exist: {dst_ref}")
                result = net.receive_pack(
                    [],
                    [
                        {
                            "ref": dst_ref,
                            "old": server_refs[dst_ref],
                            "new": None,
                            "force": spec_force,
                        }
                    ],
                )
                updated.update(result.get("updated", result))
                continue

            src_ref, new_oid = _resolve_push_source(repo, src_name)
            old_oid = server_refs.get(dst_ref)
            # No client-side fast-forward veto any more: a diverged or
            # stale push is sent with the observed tip as its CAS base and
            # the server merges or rejects with a structured report — the
            # client can't see contention that happens after this look
            # anyway, and pre-rejecting here is what forced the manual
            # pull/merge/re-push cycle the merge service removes.
            if has_set is None:
                # the server also provably holds everything our remote-
                # tracking refs name (we fetched it from there, or pushed
                # it there): without these, a diverged push against a tip
                # we never fetched finds none of the advertised oids in our
                # odb, computes an EMPTY closure, and re-uploads the whole
                # history. A server that has since rewound and gc'd those
                # objects rejects deterministically with "Push incomplete"
                # — far rarer than contention itself.
                known = [
                    oid
                    for _, oid in repo.refs.iter_refs(
                        f"refs/remotes/{remote_name}/"
                    )
                ]
                has_set = have_closure(
                    repo.odb,
                    list(server_refs.values()) + known,
                    info.get("shallow", ()),
                )
            enum = ObjectEnumerator(
                repo.odb,
                [new_oid],
                has=has_set.__contains__,
                sender_shallow=read_shallow(repo),
            )
            result = net.receive_pack(
                enum,
                [
                    {
                        "ref": dst_ref,
                        "old": old_oid,
                        "new": new_oid,
                        "force": spec_force,
                    }
                ],
                shallow=lambda: enum.shallow_boundary,
            )
            landed = result.get("updated", result)
            updated.update(landed)
            rebase = result.get("rebase") or {}
            if rebase.get("rebased"):
                tm.incr("transport.push_rebased")
        except HttpTransportError as e:
            if getattr(e, "conflict_report", None):
                raise RemoteError(render_push_conflict(e.conflict_report))
            raise RemoteError(str(e))
        # track the oid the server actually landed (a rebased push lands a
        # server-made merge commit, not our local tip) — but never a commit
        # this store doesn't hold: a dangling tracking ref would crash every
        # reader that resolves it. Falling back to our own commit leaves the
        # ref merely behind (it IS an ancestor of the true tip); the next
        # fetch fast-forwards it.
        track_oid = landed.get(dst_ref, new_oid)
        if track_oid is not None and not repo.odb.contains(track_oid):
            track_oid = new_oid
        _record_push_tracking(
            repo, remote_name, src_ref, dst_ref, track_oid, set_upstream
        )
    return updated


def push(repo, remote_name="origin", refspecs=(), *, force=False, set_upstream=False):
    """Push refs to the remote. Default: current branch to its same name.
    Returns {remote_ref: oid}."""
    remote = Remote(repo, remote_name)

    if not refspecs:
        branch = repo.refs.head_branch()
        if branch is None:
            raise RemoteError("Cannot push: HEAD is detached and no refspec given")
        refspecs = [f"{branch}:{branch}"]

    net = network_remote(remote.url, retry=_retry_policy(repo, remote_name))
    if net is not None:
        try:
            return _push_network(
                repo,
                remote_name,
                net,
                refspecs,
                force=force,
                set_upstream=set_upstream,
            )
        finally:
            net.close()
    dst = remote.open()

    updated = {}
    for spec in refspecs:
        src_name, dst_name, spec_force = parse_refspec(repo, spec)
        spec_force = spec_force or force
        dst_ref = (
            dst_name if dst_name.startswith("refs/") else f"refs/heads/{dst_name}"
        )

        if src_name is None:  # delete
            if dst.refs.get(dst_ref) is None:
                raise RemoteError(f"Remote ref does not exist: {dst_ref}")
            dst.refs.delete(dst_ref)
            updated[dst_ref] = None
            continue

        src_ref, new_oid = _resolve_push_source(repo, src_name)

        old_oid = dst.refs.get(dst_ref)
        if old_oid and not spec_force:
            # fast-forward check: remote tip must be known + an ancestor
            if not repo.odb.contains(old_oid) or not repo.is_ancestor(
                old_oid, new_oid
            ):
                raise RemoteError(
                    f"Push to {dst_ref} rejected (non-fast-forward); "
                    "fetch first or use --force"
                )

        enum = _transfer(
            repo.odb, dst.odb, [new_oid], sender_shallow=read_shallow(repo)
        )
        # pushing from a shallow clone truncates the remote's history too —
        # record the boundary there so its walkers know it's deliberate
        _update_shallow(dst, enum.shallow_boundary)
        dst.refs.set(dst_ref, new_oid, log_message=f"push from {repo.gitdir}")
        updated[dst_ref] = new_oid

        _record_push_tracking(
            repo, remote_name, src_ref, dst_ref, new_oid, set_upstream
        )
    return updated


# -- clone -----------------------------------------------------------------


def clone(
    url,
    directory,
    *,
    bare=False,
    depth=None,
    spatial_filter_spec=None,
    wc_location=None,
    do_checkout=True,
    branch=None,
):
    """Clone a repository. spatial_filter_spec (a ResolvedSpatialFilterSpec
    or None) makes this a filtered partial clone: non-matching feature blobs
    stay on the server, the remote becomes a promisor, and later reads of
    missing features fetch on demand (reference: kart/clone.py:108-153,
    kart/repo.py:269-343)."""
    directory = os.path.abspath(directory)
    repo = KartRepo.init_repository(directory, bare=bare)
    try:
        add_remote(repo, "origin", url)

        filter_spec = None
        if spatial_filter_spec is not None:
            filter_spec = spatial_filter_spec.filter_arg
            repo.config.set_many(
                {
                    "remote.origin.promisor": "true",
                    "remote.origin.partialclonefilter": "extension:spatial="
                    + filter_spec,
                    **spatial_filter_spec.config_items(),
                }
            )

        fetch(repo, "origin", depth=depth, filter_spec=filter_spec)

        # pick the branch to check out: requested, remote HEAD (the symref
        # fetch recorded), or first
        if branch is None:
            head_file = os.path.join(
                repo.gitdir, "refs", "remotes", "origin", "HEAD"
            )
            if os.path.exists(head_file):
                with open(head_file) as f:
                    target = f.read().strip()
                prefix = "ref: refs/remotes/origin/"
                if target.startswith(prefix):
                    branch = target[len(prefix) :]
        if branch is None:
            heads = [r for r, _ in repo.refs.iter_refs("refs/remotes/origin/")]
            branch = heads[0].split("/")[-1] if heads else "main"

        tip = repo.refs.get(f"refs/remotes/origin/{branch}")
        if tip is not None:
            repo.refs.set(f"refs/heads/{branch}", tip, log_message="clone")
            repo.config.set_many(
                {
                    f"branch.{branch}.remote": "origin",
                    f"branch.{branch}.merge": f"refs/heads/{branch}",
                }
            )
        repo.refs.set_head(f"refs/heads/{branch}", log_message="clone")

        if not bare and tip is not None and do_checkout:
            from kart_tpu.workingcopy import default_location, get_working_copy

            repo.config[
                KartConfigKeys.KART_WORKINGCOPY_LOCATION
            ] = wc_location or default_location(repo)
            wc = get_working_copy(repo, allow_uncreated=True)
            if wc is not None:
                wc.create_and_initialise()
                structure = repo.structure("HEAD")
                wc.write_full(structure, *structure.datasets)
        return repo
    except BaseException as e:
        import shutil

        # A transfer that died mid-fetch leaves a FETCH_RESUME marker and a
        # salvaged partial store — keep it: `kart fetch` in the directory
        # resumes from what arrived instead of recloning from zero. Every
        # other failure removes the half-made repo as before.
        if isinstance(e, (RemoteError, OSError)) and (
            repo.read_gitdir_file(FETCH_RESUME_FILE) is not None
        ):
            raise RemoteError(
                f"{e} — partial clone kept at {directory!r}; run `kart "
                f"fetch` there to resume the transfer"
            ) from e
        shutil.rmtree(repo.gitdir, ignore_errors=True)
        raise


# -- promisor fetch --------------------------------------------------------


def fetch_promised_blobs(repo, oids):
    """Backfill promised blobs from the promisor remote (reference:
    FetchPromisedBlobsProcess, kart/promisor_utils.py:75-124). Returns the
    number fetched."""
    oids = [o for o in oids if not repo.odb.contains(o)]
    if not oids:
        return 0
    promisor = None
    for name in repo.remotes():
        if repo.config.get_bool(f"remote.{name}.promisor"):
            promisor = Remote(repo, name)
            break
    if promisor is None:
        raise RemoteError("No promisor remote configured")
    net = network_remote(promisor.url, retry=_retry_policy(repo, promisor.name))
    if net is not None:
        from kart_tpu.transport.http import HttpTransportError

        try:
            return net.fetch_blobs(repo, oids)
        except HttpTransportError as e:
            raise RemoteError(str(e))
        finally:
            net.close()
    src = promisor.open()
    fetched = 0
    with tempfile.SpooledTemporaryFile(max_size=64 * 1024 * 1024) as wire:

        def pull():
            for oid in oids:
                try:
                    yield src.odb.read_raw(oid)
                except ObjectMissing:
                    raise RemoteError(
                        f"Promisor remote {promisor.name!r} is missing promised "
                        f"object {oid}"
                    )

        write_pack(wire, pull())
        wire.seek(0)
        with repo.odb.bulk_pack():
            for obj_type, content in read_pack(wire):
                repo.odb.write_raw(obj_type, content)
                fetched += 1
    return fetched
