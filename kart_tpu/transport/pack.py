"""kartpack v1 — the wire format for object exchange.

A packstream is a self-delimiting sequence of git-format objects:

    MAGIC ("KARTPACK1\\0")
    repeated: 1-byte type code | uint32 raw-len | uint32 deflate-len | deflate
    end record (type code 0) | 32-byte sha256 trailer over everything prior

Unlike git's packfiles there is no delta compression — objects here are
already small msgpack blobs and zlib handles redundancy well enough; in
exchange the stream is single-pass writable AND single-pass readable, which
is what the promisor fetch path wants (reference: `git fetch --stdin`
pipelining, kart/promisor_utils.py:75-124).
"""

import hashlib
import struct
import zlib

from kart_tpu import faults

MAGIC = b"KARTPACK1\x00"

_TYPE_TO_CODE = {"commit": 1, "tree": 2, "blob": 3, "tag": 4}
_CODE_TO_TYPE = {v: k for k, v in _TYPE_TO_CODE.items()}
_END = 0


class PackFormatError(ValueError):
    pass


def write_pack(fileobj, objects):
    """Stream ``(type_str, content_bytes)`` pairs into fileobj. Returns the
    number of objects written."""
    digest = hashlib.sha256()

    def emit(data):
        digest.update(data)
        fileobj.write(data)

    fault = faults.hook("transport.write.frame")
    emit(MAGIC)
    count = 0
    for obj_type, content in objects:
        if fault is not None:
            fault()
        code = _TYPE_TO_CODE.get(obj_type)
        if code is None:
            raise PackFormatError(f"Unknown object type: {obj_type!r}")
        deflated = zlib.compress(content, 1)
        emit(struct.pack(">BII", code, len(content), len(deflated)))
        emit(deflated)
        count += 1
    emit(struct.pack(">BII", _END, 0, 0))
    fileobj.write(digest.digest())
    return count


def read_pack(fileobj, *, mid_stream=False, consumed=None):
    """Yield ``(type_str, content_bytes)`` from a packstream, verifying the
    checksum trailer.

    ``mid_stream=True`` consumes a stream that begins at a *record
    boundary* rather than at the magic (a byte-range resume of a torn
    transfer, docs/SERVING.md §3): the magic check is skipped and the
    trailer is read but not verified — its digest covers bytes the earlier,
    torn attempt consumed. Integrity holds regardless: every record is
    individually zlib- and length-verified, and receivers recompute oids
    from content.

    ``consumed``: an optional one-element list updated (before each yield)
    with the exact stream bytes consumed through that record — the resume
    offset a ``Range: bytes=N-`` retry needs, tracked here so callers can
    put a read-ahead buffer *under* this reader without miscounting."""
    digest = hashlib.sha256()

    def pull(n):
        data = fileobj.read(n)
        if len(data) != n:
            raise PackFormatError("Truncated packstream")
        digest.update(data)
        return data

    if consumed is not None:
        consumed[0] = 0
    if not mid_stream:
        if pull(len(MAGIC)) != MAGIC:
            raise PackFormatError("Bad packstream magic")
        if consumed is not None:
            consumed[0] = len(MAGIC)
    fault = faults.hook("transport.read.frame")
    while True:
        if fault is not None:
            fault()
        code, raw_len, deflate_len = struct.unpack(">BII", pull(9))
        if code == _END:
            break
        obj_type = _CODE_TO_TYPE.get(code)
        if obj_type is None:
            raise PackFormatError(f"Bad object type code: {code}")
        deflated = pull(deflate_len)
        try:
            content = zlib.decompress(deflated)
        except zlib.error:
            # the declared escape for crafted bytes is PackFormatError;
            # zlib.error leaking here broke the wire-fuzz contract
            raise PackFormatError(
                "Corrupt deflate stream in packstream"
            ) from None
        if len(content) != raw_len:
            raise PackFormatError("Object length mismatch in packstream")
        if consumed is not None:
            consumed[0] += 9 + deflate_len
        yield obj_type, content
    expected = digest.digest()
    trailer = fileobj.read(32)
    if len(trailer) != 32:
        raise PackFormatError("Packstream checksum mismatch")
    if not mid_stream and trailer != expected:
        raise PackFormatError("Packstream checksum mismatch")
