"""Distributed communication backend: content-addressed object exchange.

The reference's "network stack" is the git smart protocol driven via
subprocess (kart/cli.py:211-253 pass-through push/fetch, kart/clone.py,
kart/promisor_utils.py).  Here the same capabilities — clone / fetch / push /
pull, shallow clone, spatially-filtered partial clone with promisor
semantics, on-demand promised-blob fetch — are a first-class subsystem built
on a length-prefixed object packstream (:mod:`kart_tpu.transport.pack`) and a
want/have reachability negotiation (:mod:`kart_tpu.transport.protocol`).

Remotes are URLs; local filesystem paths (and ``file://``) are fully
supported (the reference's own test strategy uses local directories as
remotes, SURVEY.md §4).  Network transports plug in behind the same
:class:`Transport` interface.
"""

from kart_tpu.transport.remote import (
    Remote,
    RemoteError,
    add_remote,
    clone,
    fetch,
    fetch_promised_blobs,
    open_remote,
    push,
    remove_remote,
)
from kart_tpu.transport.protocol import ObjectEnumerator
from kart_tpu.transport.pack import read_pack, write_pack

__all__ = [
    "Remote",
    "RemoteError",
    "add_remote",
    "remove_remote",
    "clone",
    "fetch",
    "push",
    "fetch_promised_blobs",
    "open_remote",
    "ObjectEnumerator",
    "read_pack",
    "write_pack",
]
