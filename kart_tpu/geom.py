"""Ragged vertex columns + exact intersection predicates (ISSUE 20).

Every query and serving surface used to stop at envelopes; this module
lifts the real geometry into the columnar world. A :class:`VertexColumn`
is the ragged per-feature shape store — per feature a range of rings, per
ring a range of vertices — extracted once from GPKG-WKB blobs
(:mod:`kart_tpu.geometry`) and persisted in the KCOL sidecar as a
``geom_bytes`` section (docs/FORMAT.md §3.4), encoded with the KTB2
stream ladder (:mod:`kart_tpu.tiles.streams`: delta/varint coords, RLE
kinds).

Quantization — the exactness contract
-------------------------------------

Coordinates are stored as int32 in units of 1e-5 degree
(``COORD_SCALE``), ~1.1 m at the equator. The payoff is that every hot
predicate below is **exact integer arithmetic**: |coord| <= 1.8e7 < 2^25,
so a coordinate difference fits 26 bits and any product of two
differences fits 52 bits — no rounding anywhere, in int64 on the host
*or* on a device. The sharded refine kernel
(:func:`kart_tpu.diff.backend.refine_intersects`) evaluates the same
formulas in jnp int64 and is bit-identical to the numpy twin by
construction — not by fused-multiply-add luck (docs/DEVICE.md §6).

Fail-open policy
----------------

Extraction never fails a feature into a wrong verdict: NULL geometry,
undecodable WKB, non-finite or out-of-world coordinates, and
GeometryCollections all become ``kind 0`` rows (no rings). The query
refine stage leaves kind-0 rows at their envelope verdict, which keeps
the monotonicity invariant (exact matches are a subset of bbox matches)
structural rather than hoped-for.

Kinds: 0 = none, 1 = point set, 2 = polyline set, 3 = polygon (rings,
even-odd). Multi* parts flatten into extra rings; a point ring holds one
vertex.
"""

import os

import numpy as np

from kart_tpu import faults
from kart_tpu import telemetry as tm
from kart_tpu.geometry import (
    LINESTRING,
    MULTILINESTRING,
    MULTIPOINT,
    MULTIPOLYGON,
    POINT,
    POLYGON,
    Geometry,
    parse_wkb,
)
from kart_tpu.tiles.streams import TileEncodeError, decode_stream, encode_stream

#: int32 vertex units per degree (1e-5 deg ~ 1.1 m). 180 * COORD_SCALE =
#: 1.8e7 < 2^25, which is what makes every predicate product exact.
COORD_SCALE = 100_000

WORLD_X = 180 * COORD_SCALE
WORLD_Y = 90 * COORD_SCALE

KIND_NONE, KIND_POINT, KIND_LINE, KIND_POLY = 0, 1, 2, 3

#: wire version byte of an encoded vertex column (docs/FORMAT.md §3.4)
GEOM_WIRE_VERSION = 1

#: default candidate pairs per refine round (host chunk / device batch)
DEFAULT_GEOM_BATCH_ROWS = 4096


def geom_batch_rows():
    """Candidate pairs per exact-refine round (``KART_GEOM_BATCH_ROWS``,
    docs/OBSERVABILITY.md §7): bounds the (pairs x segA x segB) predicate
    slab on either execution layer. Malformed values fall back to the
    default — tuning knobs must never kill a query."""
    try:
        return max(
            int(os.environ.get("KART_GEOM_BATCH_ROWS",
                               str(DEFAULT_GEOM_BATCH_ROWS))),
            1,
        )
    except ValueError:
        return DEFAULT_GEOM_BATCH_ROWS

def geom_refine_enabled():
    """``KART_GEOM_REFINE`` (docs/OBSERVABILITY.md §7): the process-wide
    exact-refine switch. Default on — spatial queries answer with real
    geometry wherever a vertex column exists; ``0`` pins every query to
    the envelope-only (``--approx``) semantics."""
    return os.environ.get("KART_GEOM_REFINE", "1") != "0"


_BASE_KIND = {
    POINT: KIND_POINT,
    MULTIPOINT: KIND_POINT,
    LINESTRING: KIND_LINE,
    MULTILINESTRING: KIND_LINE,
    POLYGON: KIND_POLY,
    MULTIPOLYGON: KIND_POLY,
}


def _gather_ranges(lo, hi):
    """Concatenated ``arange(lo[i], hi[i])`` without a Python loop
    -> (indices int64 (sum(hi-lo),), counts int64 (len(lo),))."""
    lo = np.asarray(lo, dtype=np.int64)
    hi = np.asarray(hi, dtype=np.int64)
    counts = hi - lo
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64), counts
    offs = np.concatenate(([0], np.cumsum(counts)[:-1]))
    idx = np.arange(total, dtype=np.int64)
    return idx - np.repeat(offs - lo, counts), counts


class VertexColumn:
    """Ragged per-feature vertex store, block-row order.

    ``feat_offsets`` int64 (N+1,) — ring index range of feature i is
    ``[feat_offsets[i], feat_offsets[i+1])``; ``ring_offsets`` int64
    (R+1,) — vertex index range per ring; ``x``/``y`` int32 (V,)
    quantized lon/lat; ``kinds`` uint8 (N,). Kind-0 rows own zero rings.
    """

    __slots__ = ("kinds", "feat_offsets", "ring_offsets", "x", "y",
                 "_seg_table")

    def __init__(self, kinds, feat_offsets, ring_offsets, x, y):
        self.kinds = np.ascontiguousarray(kinds, dtype=np.uint8)
        self.feat_offsets = np.ascontiguousarray(feat_offsets, dtype=np.int64)
        self.ring_offsets = np.ascontiguousarray(ring_offsets, dtype=np.int64)
        self.x = np.ascontiguousarray(x, dtype=np.int32)
        self.y = np.ascontiguousarray(y, dtype=np.int32)
        self._seg_table = None

    def __len__(self):
        return len(self.kinds)

    @classmethod
    def empty(cls, n):
        """n all-kind-0 rows (a sidecar with no usable geometry)."""
        return cls(
            np.zeros(n, np.uint8),
            np.zeros(n + 1, np.int64),
            np.zeros(1, np.int64),
            np.zeros(0, np.int32),
            np.zeros(0, np.int32),
        )

    @property
    def n_rings(self):
        return len(self.ring_offsets) - 1

    @property
    def n_vertices(self):
        return len(self.x)

    def usable(self):
        """bool (N,): rows the refine stage may trust (kind != 0)."""
        return self.kinds != KIND_NONE

    def take(self, indices):
        """Row-gather -> new VertexColumn (sidecar sort order, derive's
        kept-row slice). Fully vectorized."""
        idx = np.asarray(indices, dtype=np.int64)
        ring_idx, ring_counts = _gather_ranges(
            self.feat_offsets[idx], self.feat_offsets[idx + 1]
        )
        vert_idx, vert_counts = _gather_ranges(
            self.ring_offsets[ring_idx], self.ring_offsets[ring_idx + 1]
        )
        return VertexColumn(
            self.kinds[idx],
            np.concatenate(([0], np.cumsum(ring_counts))),
            np.concatenate(([0], np.cumsum(vert_counts))),
            self.x[vert_idx],
            self.y[vert_idx],
        )

    @classmethod
    def concat(cls, cols):
        """Row-concatenate (derive: kept rows + freshly extracted adds)."""
        cols = list(cols)
        kinds = np.concatenate([c.kinds for c in cols])
        ring_counts = np.concatenate(
            [np.diff(c.feat_offsets) for c in cols]
        )
        vert_counts = np.concatenate(
            [np.diff(c.ring_offsets) for c in cols]
        )
        return cls(
            kinds,
            np.concatenate(([0], np.cumsum(ring_counts))),
            np.concatenate(([0], np.cumsum(vert_counts))),
            np.concatenate([c.x for c in cols]),
            np.concatenate([c.y for c in cols]),
        )

    def rings(self, i):
        """Feature i -> list of (x int32 (k,), y (k,)) vertex rings."""
        out = []
        for r in range(int(self.feat_offsets[i]), int(self.feat_offsets[i + 1])):
            v0, v1 = int(self.ring_offsets[r]), int(self.ring_offsets[r + 1])
            out.append((self.x[v0:v1], self.y[v0:v1]))
        return out

    def segments(self, i):
        """Feature i -> (x0, y0, x1, y1) int64 segment endpoint arrays.

        A k-vertex ring yields its k-1 consecutive segments; polygon
        rings always get the closing edge (zero-length when the WKB ring
        already repeats its first vertex — harmless: a zero-length
        segment behaves as an on-boundary point in every predicate). A
        1-vertex ring (a point) is one zero-length segment, which is how
        point rows ride the same segment tests."""
        poly = self.kinds[i] == KIND_POLY
        x0s, y0s, x1s, y1s = [], [], [], []
        for xs, ys in self.rings(i):
            if len(xs) == 1:
                x0s.append(xs)
                y0s.append(ys)
                x1s.append(xs)
                y1s.append(ys)
                continue
            if poly:
                x0s.append(xs)
                y0s.append(ys)
                x1s.append(np.roll(xs, -1))
                y1s.append(np.roll(ys, -1))
            else:
                x0s.append(xs[:-1])
                y0s.append(ys[:-1])
                x1s.append(xs[1:])
                y1s.append(ys[1:])
        if not x0s:
            z = np.zeros(0, np.int64)
            return z, z, z, z
        return tuple(
            np.concatenate(parts).astype(np.int64)
            for parts in (x0s, y0s, x1s, y1s)
        )

    def segment_table(self):
        """Whole-column flat segment endpoints, built once and cached.

        Returns ``(x0, y0, x1, y1, offs)``: int32 (S,) endpoint arrays
        holding every feature's segments contiguously in exactly
        :meth:`segments` order, plus ``offs`` int64 (N+1,) so feature
        i's segments are the slice ``[offs[i], offs[i+1])``. The pair
        packer gathers from this instead of calling ``segments(i)`` per
        feature — at join scale that loop (one Python frame + np.roll
        per ring) dominated the whole refine stage."""
        if self._seg_table is not None:
            return self._seg_table
        n_feat = len(self.kinds)
        ring_counts = np.diff(self.feat_offsets)
        k = np.diff(self.ring_offsets)  # vertices per ring
        ring_feat = np.repeat(np.arange(n_feat, dtype=np.int64), ring_counts)
        poly_ring = self.kinds[ring_feat] == KIND_POLY
        # segments per ring: 1-vertex ring -> one zero-length segment;
        # polygon ring -> k (closing edge); line ring -> k-1
        segc = np.where(
            k == 1, 1, np.where(poly_ring, k, np.maximum(k - 1, 0))
        ).astype(np.int64)
        start, _ = _gather_ranges(
            self.ring_offsets[:-1], self.ring_offsets[:-1] + segc
        )
        ring_of = np.repeat(np.arange(len(k), dtype=np.int64), segc)
        base = self.ring_offsets[:-1][ring_of]
        local = start - base
        kk = k[ring_of]
        end_local = np.where(
            kk <= 1, local,
            np.where(poly_ring[ring_of], (local + 1) % np.maximum(kk, 1),
                     local + 1),
        )
        end = base + end_local
        per_ring_offs = np.concatenate(([0], np.cumsum(segc)))
        offs = per_ring_offs[self.feat_offsets]
        self._seg_table = (
            self.x[start], self.y[start], self.x[end], self.y[end], offs
        )
        return self._seg_table


# ---------------------------------------------------------------------------
# extraction: GPKG blobs -> VertexColumn (import / derive / fallback path)
# ---------------------------------------------------------------------------


def _value_rings(value):
    """GeomValue -> (kind, list of point-lists) or (0, []) when the shape
    has no columnar form (GeometryCollection, empties)."""
    base = value.base_type
    kind = _BASE_KIND.get(base)
    if kind is None:
        return KIND_NONE, []
    payload = value.payload
    if base == POINT:
        rings = [] if payload is None else [[payload]]
    elif base == MULTIPOINT:
        rings = [[c.payload] for c in payload if c.payload is not None]
    elif base == LINESTRING:
        rings = [payload] if payload else []
    elif base == MULTILINESTRING:
        rings = [c.payload for c in payload if c.payload]
    elif base == POLYGON:
        rings = [r for r in payload if r]
    else:  # MULTIPOLYGON
        rings = [r for c in payload for r in c.payload if r]
    if not rings:
        return KIND_NONE, []
    return kind, rings


def _quantize_rings(rings):
    """point-lists -> (x int32 chunks, y chunks, vertex counts) or None
    when any coordinate is non-finite or outside the world range (the
    whole feature fails open to kind 0)."""
    xs, ys, counts = [], [], []
    for ring in rings:
        pts = np.asarray([(p[0], p[1]) for p in ring], dtype=np.float64)
        if not np.isfinite(pts).all():
            return None
        q = np.rint(pts * COORD_SCALE)
        if (
            np.abs(q[:, 0]).max(initial=0) > WORLD_X
            or np.abs(q[:, 1]).max(initial=0) > WORLD_Y
        ):
            return None
        xs.append(q[:, 0].astype(np.int32))
        ys.append(q[:, 1].astype(np.int32))
        counts.append(len(ring))
    return xs, ys, counts


def vertex_column_from_blobs(blobs):
    """Iterable of GPKG geometry blobs (or None) -> VertexColumn, one row
    per blob in order. The import/derive extraction entry point —
    ``KART_FAULTS=geom.extract:<n>`` fires here, before any rows are
    built, so an armed extraction publishes nothing."""
    hook = faults.hook("geom.extract")
    if hook is not None:
        hook()
    kinds, ring_counts, vert_counts = [], [], []
    x_chunks, y_chunks = [], []
    n_failed = 0
    for blob in blobs:
        kind = KIND_NONE
        rings = []
        if blob:
            try:
                g = Geometry.of(bytes(blob))
                if g is not None and not g.is_empty:
                    kind, rings = _value_rings(parse_wkb(g.to_wkb()))
            except Exception:
                n_failed += 1
                kind, rings = KIND_NONE, []
        if kind != KIND_NONE:
            q = _quantize_rings(rings)
            if q is None:
                kind, rings = KIND_NONE, []
            else:
                xs, ys, counts = q
                x_chunks.extend(xs)
                y_chunks.extend(ys)
                vert_counts.extend(counts)
        kinds.append(kind)
        ring_counts.append(len(rings) if kind != KIND_NONE else 0)
    if n_failed:
        tm.incr("geom.extract_failed", rows=n_failed)
    return VertexColumn(
        np.asarray(kinds, np.uint8),
        np.concatenate(([0], np.cumsum(np.asarray(ring_counts, np.int64)))),
        np.concatenate(([0], np.cumsum(np.asarray(vert_counts, np.int64)))),
        np.concatenate(x_chunks) if x_chunks else np.zeros(0, np.int32),
        np.concatenate(y_chunks) if y_chunks else np.zeros(0, np.int32),
    )


# ---------------------------------------------------------------------------
# wire codec: the sidecar's `geom_bytes` section (docs/FORMAT.md §3.4)
# ---------------------------------------------------------------------------


def encode_vertex_column(col):
    """VertexColumn -> section bytes: a version byte, then five KTB2
    streams — kinds, rings-per-feature, vertices-per-ring, x, y. Counts
    (not offsets) go on the wire so monotonicity is by construction;
    coords delta-code well because ring vertices are spatially local."""
    ring_counts = np.diff(col.feat_offsets)
    vert_counts = np.diff(col.ring_offsets)
    return b"".join(
        (
            bytes([GEOM_WIRE_VERSION]),
            encode_stream(col.kinds.astype(np.int64), "i8"),
            encode_stream(ring_counts, "i8"),
            encode_stream(vert_counts, "i8"),
            encode_stream(col.x.astype(np.int64), "i4"),
            encode_stream(col.y.astype(np.int64), "i4"),
        )
    )


def decode_vertex_column(data, count, pos=0):
    """Section bytes at ``pos`` -> (VertexColumn of ``count`` rows, next
    pos). Taint boundary (registry.TAINT_SOURCES, fuzzed): only
    :class:`TileEncodeError` may escape. Ceilings: kinds in [0, 3] with
    kind 0 <=> zero rings, ring/vertex counts positive where required and
    totalling <= MAX_DECODE_ROWS (summed in Python — no int64 wrap),
    coords inside the world range, every stream canonical/consume-exact
    (:func:`kart_tpu.tiles.streams.decode_stream`)."""
    from kart_tpu.tiles.encode import MAX_DECODE_ROWS

    if count < 0 or count > MAX_DECODE_ROWS:
        raise TileEncodeError(f"Vertex column row count {count} out of range")
    if pos + 1 > len(data):
        raise TileEncodeError("Truncated vertex column: no version byte")
    version = data[pos]
    if version != GEOM_WIRE_VERSION:
        raise TileEncodeError(f"Unknown vertex column version {version}")
    pos += 1
    kinds, pos = decode_stream(data, count, "i8", pos)
    if len(kinds) and (int(kinds.min()) < 0 or int(kinds.max()) > KIND_POLY):
        raise TileEncodeError("Vertex column kind outside [0, 3]")
    ring_counts, pos = decode_stream(data, count, "i8", pos)
    if np.any((kinds == KIND_NONE) != (ring_counts == 0)):
        raise TileEncodeError("Vertex column kind/ring-count mismatch")
    if len(ring_counts) and int(ring_counts.min()) < 0:
        raise TileEncodeError("Negative ring count")
    n_rings = sum(int(c) for c in ring_counts)  # non-wrapping total
    if n_rings > MAX_DECODE_ROWS:
        raise TileEncodeError(
            f"Vertex column holds {n_rings} rings (cap {MAX_DECODE_ROWS})"
        )
    vert_counts, pos = decode_stream(data, n_rings, "i8", pos)
    if len(vert_counts) and int(vert_counts.min()) < 1:
        raise TileEncodeError("Vertex ring with fewer than 1 vertex")
    n_verts = sum(int(c) for c in vert_counts)
    if n_verts > MAX_DECODE_ROWS:
        raise TileEncodeError(
            f"Vertex column holds {n_verts} vertices (cap {MAX_DECODE_ROWS})"
        )
    x, pos = decode_stream(data, n_verts, "i4", pos)
    y, pos = decode_stream(data, n_verts, "i4", pos)
    if len(x) and (
        int(np.abs(x.astype(np.int64)).max()) > WORLD_X
        or int(np.abs(y.astype(np.int64)).max()) > WORLD_Y
    ):
        raise TileEncodeError("Vertex coordinate outside world range")
    return (
        VertexColumn(
            kinds.astype(np.uint8),
            np.concatenate(([0], np.cumsum(ring_counts))),
            np.concatenate(([0], np.cumsum(vert_counts))),
            x,
            y,
        ),
        pos,
    )


# ---------------------------------------------------------------------------
# exact predicates — operator-only int64 formulas, shared by the numpy
# host path and the jnp device kernel (docs/DEVICE.md §6)
# ---------------------------------------------------------------------------


def seg_pairs_intersect(ax0, ay0, ax1, ay1, bx0, by0, bx1, by1):
    """Elementwise/broadcast inclusive segment-intersection predicate,
    int64 in -> bool out. Straddle test + collinear/endpoint touch, all
    exact (products fit 52 bits). A zero-length segment degrades to a
    point: point-on-segment and point==point fall out of the touch term.
    Operator-only on purpose — numpy and jnp evaluate the identical
    expression tree, so host and device verdicts are bit-identical."""
    d1 = (bx1 - bx0) * (ay0 - by0) - (by1 - by0) * (ax0 - bx0)
    d2 = (bx1 - bx0) * (ay1 - by0) - (by1 - by0) * (ax1 - bx0)
    d3 = (ax1 - ax0) * (by0 - ay0) - (ay1 - ay0) * (bx0 - ax0)
    d4 = (ax1 - ax0) * (by1 - ay0) - (ay1 - ay0) * (bx1 - ax0)
    straddle = (((d1 > 0) & (d2 < 0)) | ((d1 < 0) & (d2 > 0))) & (
        ((d3 > 0) & (d4 < 0)) | ((d3 < 0) & (d4 > 0))
    )
    # collinear touch: d == 0 puts the point on the carrier line; the
    # products (sx0-px)(sx1-px) <= 0 pin it inside the segment's span
    t1 = (d1 == 0) & ((bx0 - ax0) * (bx1 - ax0) <= 0) & (
        (by0 - ay0) * (by1 - ay0) <= 0
    )
    t2 = (d2 == 0) & ((bx0 - ax1) * (bx1 - ax1) <= 0) & (
        (by0 - ay1) * (by1 - ay1) <= 0
    )
    t3 = (d3 == 0) & ((ax0 - bx0) * (ax1 - bx0) <= 0) & (
        (ay0 - by0) * (ay1 - by0) <= 0
    )
    t4 = (d4 == 0) & ((ax0 - bx1) * (ax1 - bx1) <= 0) & (
        (ay0 - by1) * (ay1 - by1) <= 0
    )
    return straddle | t1 | t2 | t3 | t4


def ray_crossings(px, py, sx0, sy0, sx1, sy1):
    """Elementwise/broadcast upward-ray crossing indicator for the
    even-odd rule, int64 in -> bool out. Half-open vertex rule
    ``(sy0 <= py) != (sy1 <= py)`` counts each boundary vertex once;
    the left-of test is the exact integer cross product. Callers reduce
    (sum over segments, parity per point). Operator-only — see
    :func:`seg_pairs_intersect`."""
    upward = (sy0 <= py) != (sy1 <= py)
    cr = (sx1 - sx0) * (py - sy0) - (sy1 - sy0) * (px - sx0)
    left = ((sy1 > sy0) & (cr > 0)) | ((sy1 < sy0) & (cr < 0))
    return upward & left


def points_in_rings(px, py, sx0, sy0, sx1, sy1):
    """(V,) int64 points vs (S,) int64 ring segments -> bool (V,)
    even-odd containment (host reduction of :func:`ray_crossings`).
    Summing crossings over *all* rings of a feature is the even-odd rule
    with holes and disjoint parts handled for free."""
    if not len(sx0) or not len(px):
        return np.zeros(len(px), dtype=bool)
    hits = ray_crossings(
        px[:, None], py[:, None], sx0[None, :], sy0[None, :],
        sx1[None, :], sy1[None, :],
    )
    return (hits.sum(axis=1) & 1).astype(bool)


def pair_intersects(segs_a, a_poly, segs_b, b_poly):
    """One exact pair verdict from pre-built segment arrays: any segment
    contact, else any A vertex inside polygon B, else any B vertex inside
    polygon A. Vertex tests use segment start points — ring closure makes
    starts cover every polygon vertex, and a part wholly inside the other
    side always has its start inside (anything else crosses a boundary
    and is caught by the segment term)."""
    ax0, ay0, ax1, ay1 = segs_a
    bx0, by0, bx1, by1 = segs_b
    if not len(ax0) or not len(bx0):
        return False
    hit = seg_pairs_intersect(
        ax0[:, None], ay0[:, None], ax1[:, None], ay1[:, None],
        bx0[None, :], by0[None, :], bx1[None, :], by1[None, :],
    )
    if hit.any():
        return True
    if b_poly and points_in_rings(ax0, ay0, bx0, by0, bx1, by1).any():
        return True
    if a_poly and points_in_rings(bx0, by0, ax0, ay0, ax1, ay1).any():
        return True
    return False


def boxes_vertex_column(env):
    """(N, 4) wsen degree envelopes -> VertexColumn of one 5-point box
    polygon per row, vectorized (no per-row WKB walk). Non-finite or
    wrapping (e < w) rows become kind 0 — fail open, same policy as blob
    extraction. Coordinates clip to the world range first, which keeps
    the quantized values in int32 and is lossless for any feature that
    can exist. The synthetic layers' vertex source
    (:func:`kart_tpu.synth.synth_repo`) and the scan refine's
    query-rectangle builder."""
    env = np.asarray(env, dtype=np.float64)
    n = len(env)
    if not n:
        return VertexColumn.empty(0)
    ok = np.isfinite(env).all(axis=1) & (env[:, 2] >= env[:, 0])
    qw = np.rint(np.clip(env[:, 0], -180.0, 180.0) * COORD_SCALE).astype(np.int64)
    qs = np.rint(np.clip(env[:, 1], -90.0, 90.0) * COORD_SCALE).astype(np.int64)
    qe = np.rint(np.clip(env[:, 2], -180.0, 180.0) * COORD_SCALE).astype(np.int64)
    qn = np.rint(np.clip(env[:, 3], -90.0, 90.0) * COORD_SCALE).astype(np.int64)
    idx = np.flatnonzero(ok)
    x = np.stack([qw, qe, qe, qw, qw], axis=1)[idx].ravel().astype(np.int32)
    y = np.stack([qs, qs, qn, qn, qs], axis=1)[idx].ravel().astype(np.int32)
    kinds = np.where(ok, KIND_POLY, KIND_NONE).astype(np.uint8)
    return VertexColumn(
        kinds,
        np.concatenate(([0], np.cumsum(ok.astype(np.int64)))),
        np.arange(len(idx) + 1, dtype=np.int64) * 5,
        x,
        y,
    )


def bbox_vertex_column(query):
    """``--bbox`` wsen rectangle -> 1-row polygon VertexColumn for the
    refine kernels, or None for an anti-meridian wrap (e < w) — the
    cyclic test stays with the envelope stage (fail open: wrapped-query
    scans keep bbox semantics)."""
    col = boxes_vertex_column(np.asarray(query, dtype=np.float64)[None, :])
    return col if col.kinds[0] != KIND_NONE else None


def refine_pairs_host(col_a, ia, col_b, ib):
    """Host exact-refine: candidate pair index arrays -> bool verdicts.
    Evaluates the same padded (P, SA, SB) predicate slabs the sharded
    kernel reduces (:func:`kart_tpu.diff.device_batch.pack_geom_pairs`)
    — one numpy broadcast per chunk instead of a Python loop per pair —
    with chunk rows shrunk under the same element budget so one huge
    polygon can't blow host memory. Bit-identical to the device kernel
    by shared source: both reduce the identical operator-only
    expressions over the identical padded slabs."""
    from kart_tpu.diff.device_batch import pack_geom_pairs

    ia = np.asarray(ia, dtype=np.int64)
    ib = np.asarray(ib, dtype=np.int64)
    total = len(ia)
    out = np.zeros(total, dtype=bool)
    if not total:
        return out
    batch = geom_batch_rows()
    for lo in range(0, total, batch):
        hi = min(lo + batch, total)
        pack = pack_geom_pairs(col_a, ia[lo:hi], col_b, ib[lo:hi])
        sa = pack["a"][0].shape[1]
        sb = pack["b"][0].shape[1]
        rows = max(min(hi - lo, (1 << 24) // max(sa * sb, 1)), 1)
        for r0 in range(0, hi - lo, rows):
            r1 = min(r0 + rows, hi - lo)
            sl = slice(r0, r1)
            a = [c[sl].astype(np.int64) for c in pack["a"]]
            b = [c[sl].astype(np.int64) for c in pack["b"]]
            am = np.arange(sa)[None, :] < pack["a_n"][sl][:, None]
            bm = np.arange(sb)[None, :] < pack["b_n"][sl][:, None]
            pm = am[:, :, None] & bm[:, None, :]
            down = [v[:, :, None] for v in a]  # A segments down the matrix
            across = [v[:, None, :] for v in b]  # B segments across
            seg_any = (seg_pairs_intersect(*down, *across) & pm).any(
                axis=(1, 2)
            )
            cnt_ab = (ray_crossings(down[0], down[1], *across) & pm).sum(
                axis=2
            )
            a_in_b = (((cnt_ab & 1) == 1) & am).any(axis=1)
            cnt_ba = (ray_crossings(across[0], across[1], *down) & pm).sum(
                axis=1
            )
            b_in_a = (((cnt_ba & 1) == 1) & bm).any(axis=1)
            out[lo + r0 : lo + r1] = (
                seg_any
                | (pack["b_poly"][sl] & a_in_b)
                | (pack["a_poly"][sl] & b_in_a)
            )
    return out
