"""NTv2 datum grid shifts.

The reference gets grid-shift datums (NTv2 ``.gsb``) from PROJ
(kart/crs_util.py:17-32 via OSR). Here the format is read natively and
applied as a vectorized bilinear interpolation; grids plug into the
Transform datum-shift stage through a registry.

No grids ship with the framework (they are distribution-restricted
datasets); point ``KART_NTV2_GRID_DIR`` at a directory of ``.gsb`` files,
or call :func:`register_grid` programmatically. A registered grid applies
when a Transform's source datum name matches the grid's ``SYSTEM_F`` (or
the name it was registered under); otherwise the Helmert/TOWGS84 path runs
as before.

NTv2 layout (binary, little- or big-endian, detected from NUM_OREC):
  overview header: 11 records x 16 bytes ("NUM_OREC" i32, "NUM_SREC",
  "NUM_FILE", "GS_TYPE ", "VERSION ", "SYSTEM_F", "SYSTEM_T", "MAJOR_F"
  f64, "MINOR_F", "MAJOR_T", "MINOR_T")
  per subgrid: 11 records ("SUB_NAME", "PARENT", "CREATED", "UPDATED",
  "S_LAT" f64, "N_LAT", "E_LONG", "W_LONG", "LAT_INC", "LONG_INC",
  "GS_COUNT" i32) then GS_COUNT nodes of 4 float32 (lat shift, lon shift,
  accuracies) in seconds. Longitude values are positive WEST; nodes run
  south-to-north rows, east-to-west within a row.
"""

import os
import struct

import numpy as np


class GridShiftError(ValueError):
    pass


class SubGrid:
    __slots__ = (
        "name",
        "parent",
        "s_lat",
        "n_lat",
        "e_long",
        "w_long",
        "lat_inc",
        "lon_inc",
        "lat_shift",
        "lon_shift",
        "n_rows",
        "n_cols",
    )


class NTv2Grid:
    """A parsed .gsb file: subgrids + vectorized bilinear lookup."""

    def __init__(self, system_from, system_to, subgrids):
        self.system_from = system_from
        self.system_to = system_to
        # Process coarse->fine so finer (child) subgrids overwrite their
        # parents in shift(). The format does NOT guarantee parents are
        # listed first (PROJ resolves the hierarchy via the PARENT field),
        # so order by hierarchy depth — stable, so sibling file order is
        # kept. Unknown/cyclic parents are treated as roots.
        depth_memo = {}
        by_name = {sg.name: sg for sg in subgrids}

        def depth(sg, seen=()):
            if sg.name in depth_memo:
                return depth_memo[sg.name]
            parent = by_name.get(getattr(sg, "parent", "NONE"))
            d = (
                0
                if parent is None or sg.name in seen
                else depth(parent, seen + (sg.name,)) + 1
            )
            depth_memo[sg.name] = d
            return d

        self.subgrids = sorted(subgrids, key=depth)

    @classmethod
    def open(cls, path):
        with open(path, "rb") as f:
            data = f.read()
        if len(data) < 11 * 16:
            raise GridShiftError(f"{path}: too short for an NTv2 overview header")

        # endianness: NUM_OREC's value is a small int (11)
        for endian in ("<", ">"):
            (n_orec,) = struct.unpack_from(endian + "i", data, 8)
            if 0 < n_orec < 1000:
                break
        else:
            raise GridShiftError(f"{path}: cannot determine NTv2 endianness")

        def rec_name(off):
            return data[off : off + 8].decode("ascii", "replace").strip()

        def rec_i32(off):
            return struct.unpack_from(endian + "i", data, off + 8)[0]

        def rec_f64(off):
            return struct.unpack_from(endian + "d", data, off + 8)[0]

        def rec_str(off):
            return data[off + 8 : off + 16].decode("ascii", "replace").strip()

        if rec_name(0) != "NUM_OREC":
            raise GridShiftError(f"{path}: not an NTv2 file")
        n_srec = rec_i32(16)
        n_file = rec_i32(32)
        gs_type = rec_str(3 * 16).upper()
        if gs_type != "SECONDS":
            # MINUTES/DEGREES grids exist in the wild; silently scaling them
            # as seconds would be 60x/3600x wrong — fail loudly (PROJ does)
            raise GridShiftError(
                f"{path}: GS_TYPE {gs_type!r} not supported (SECONDS only)"
            )
        system_f = rec_str(5 * 16)
        system_t = rec_str(6 * 16)

        pos = n_orec * 16
        subgrids = []
        for _ in range(n_file):
            fields = {}
            for r in range(n_srec):
                off = pos + r * 16
                name = rec_name(off)
                if name in ("S_LAT", "N_LAT", "E_LONG", "W_LONG", "LAT_INC", "LONG_INC"):
                    fields[name] = rec_f64(off)
                elif name == "GS_COUNT":
                    fields[name] = rec_i32(off)
                else:
                    fields[name] = rec_str(off)
            pos += n_srec * 16
            count = fields["GS_COUNT"]
            try:
                nodes = np.frombuffer(
                    data, dtype=endian + "f4", count=count * 4, offset=pos
                ).reshape(count, 4)
            except ValueError as err:
                # truncated node section: keep the module's error contract
                raise GridShiftError(
                    f"{path}: truncated node data in subgrid "
                    f"{fields.get('SUB_NAME', '?')!r}: {err}"
                )
            pos += count * 16

            sg = SubGrid()
            sg.name = fields.get("SUB_NAME", "")
            sg.parent = fields.get("PARENT", "NONE")
            sg.s_lat = fields["S_LAT"]
            sg.n_lat = fields["N_LAT"]
            sg.e_long = fields["E_LONG"]
            sg.w_long = fields["W_LONG"]
            sg.lat_inc = fields["LAT_INC"]
            sg.lon_inc = fields["LONG_INC"]
            sg.n_cols = int(round((sg.w_long - sg.e_long) / sg.lon_inc)) + 1
            sg.n_rows = int(round((sg.n_lat - sg.s_lat) / sg.lat_inc)) + 1
            if sg.n_rows * sg.n_cols != count:
                raise GridShiftError(
                    f"{path}: subgrid {sg.name!r} node count mismatch "
                    f"({sg.n_rows}x{sg.n_cols} != {count})"
                )
            sg.lat_shift = nodes[:, 0].reshape(sg.n_rows, sg.n_cols)
            sg.lon_shift = nodes[:, 1].reshape(sg.n_rows, sg.n_cols)
            subgrids.append(sg)
        return cls(system_f, system_t, subgrids)

    def shift(self, lon_deg, lat_deg, inverse=False):
        """Apply the grid: source-datum lon/lat (degrees, east-positive) ->
        target datum. Points outside every subgrid pass through unchanged
        (fail open, like PROJ). ``inverse`` applies target->source with
        three fixed-point refinement rounds."""
        lon = np.asarray(lon_deg, dtype=np.float64)
        lat = np.asarray(lat_deg, dtype=np.float64)
        if inverse:
            # first guess: subtract the forward shift at the target point,
            # then refine so forward(result) lands back on the input
            glon, glat = lon, lat
            for _ in range(3):
                flon, flat = self.shift(glon, glat)
                glon = glon - (flon - lon)
                glat = glat - (flat - lat)
            return glon, glat

        dlat = np.zeros_like(lat)
        dlon = np.zeros_like(lon)
        done = np.zeros(lat.shape, dtype=bool)
        # NTv2 longitudes are positive WEST
        lon_w = -lon
        # later (finer, child) subgrids win: subgrids are hierarchy-ordered
        # at construction (roots first), so children overwrite parents
        for sg in self.subgrids:
            inside = (
                (lat >= sg.s_lat / 3600.0)
                & (lat <= sg.n_lat / 3600.0)
                & (lon_w * 3600.0 >= sg.e_long)
                & (lon_w * 3600.0 <= sg.w_long)
            )
            if not np.any(inside):
                continue
            row = (lat * 3600.0 - sg.s_lat) / sg.lat_inc
            col = (lon_w * 3600.0 - sg.e_long) / sg.lon_inc
            r0 = np.clip(np.floor(row).astype(np.int64), 0, sg.n_rows - 2)
            c0 = np.clip(np.floor(col).astype(np.int64), 0, sg.n_cols - 2)
            fr = np.clip(row - r0, 0.0, 1.0)
            fc = np.clip(col - c0, 0.0, 1.0)

            def interp(table):
                v00 = table[r0, c0]
                v01 = table[r0, c0 + 1]
                v10 = table[r0 + 1, c0]
                v11 = table[r0 + 1, c0 + 1]
                return (
                    v00 * (1 - fr) * (1 - fc)
                    + v01 * (1 - fr) * fc
                    + v10 * fr * (1 - fc)
                    + v11 * fr * fc
                )

            dlat = np.where(inside, interp(sg.lat_shift), dlat)
            dlon = np.where(inside, interp(sg.lon_shift), dlon)
            done |= inside

        out_lat = lat + np.where(done, dlat / 3600.0, 0.0)
        # shifts are positive west: an eastward-positive longitude decreases
        out_lon = lon - np.where(done, dlon / 3600.0, 0.0)
        return out_lon, out_lat


# -- registry ---------------------------------------------------------------

_REGISTRY = {}  # normalised datum/system name -> NTv2Grid
_dir_scanned = False


def _norm(name):
    return "".join(ch for ch in (name or "").upper() if ch.isalnum())


def register_grid(name, grid):
    """Make ``grid`` apply to Transforms whose source datum matches
    ``name`` (case/punctuation-insensitive)."""
    _REGISTRY[_norm(name)] = grid


def clear_grids():
    global _dir_scanned
    _REGISTRY.clear()
    _dir_scanned = False


def _scan_env_dir():
    global _dir_scanned
    if _dir_scanned:
        return
    _dir_scanned = True
    d = os.environ.get("KART_NTV2_GRID_DIR")
    if not d or not os.path.isdir(d):
        return
    import logging

    for fn in sorted(os.listdir(d)):
        if not fn.lower().endswith(".gsb"):
            continue
        try:
            grid = NTv2Grid.open(os.path.join(d, fn))
        except Exception as e:
            # truncated/corrupt files raise ValueError/struct.error from the
            # binary decode — one bad grid must not poison every Transform
            logging.getLogger(__name__).warning(
                "ignoring NTv2 grid %s: %s", fn, e
            )
            continue
        # registered under the declared source system AND the filename stem,
        # so alternate datum spellings can be aliased by naming the file;
        # explicit register_grid() calls made before the lazy scan win
        _REGISTRY.setdefault(_norm(grid.system_from), grid)
        _REGISTRY.setdefault(_norm(os.path.splitext(fn)[0]), grid)


def grid_for_datum(datum_name):
    """-> NTv2Grid for the datum, or None. Scans $KART_NTV2_GRID_DIR once."""
    _scan_env_dir()
    if not _REGISTRY:
        return None
    return _REGISTRY.get(_norm(datum_name))
