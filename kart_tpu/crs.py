"""CRS handling (reference: kart/crs_util.py, kart/wkt_lexer.py).

The reference delegates to OSR/PROJ. This rebuild is PROJ-free: a small WKT
parser extracts authority identifiers and projection parameters, and the
transforms needed by the spatial-filter / envelope-index hot paths (geographic
<-> Transverse Mercator / Web Mercator on a WGS84/GRS80 ellipsoid) are
implemented directly over numpy arrays — which makes batch envelope
reprojection a single vectorized call instead of a per-feature OSR round trip.
Datum shifts ARE applied when the CRS declares them: WKT1 TOWGS84 3/7-param
Helmert (``Transform`` below) and NTv2 grid shifts (kart_tpu/gridshift.py,
loaded from $KART_NTV2_GRID_DIR); a CRS with neither is treated as
WGS84-equivalent (within ~1m for modern datums, and the envelope index pads
by a buffer anyway — see kart_tpu/spatial_filter/index.py). Bare EPSG codes
resolve through the built-in parameter registry (kart_tpu/epsg.py).
"""

import math
import re

import numpy as np


class CrsError(ValueError):
    pass


# ---------------------------------------------------------------------------
# WKT node parsing — WKT1 and WKT2 both have the shape NAME[arg, arg, ...]
# ---------------------------------------------------------------------------


class WktNode:
    __slots__ = ("keyword", "args")

    def __init__(self, keyword, args):
        self.keyword = keyword
        self.args = args

    def find(self, *keywords, recursive=True):
        """First descendant node with one of the given keywords (case-insensitive)."""
        kws = {k.upper() for k in keywords}
        for a in self.args:
            if isinstance(a, WktNode):
                if a.keyword.upper() in kws:
                    return a
                if recursive:
                    found = a.find(*keywords)
                    if found is not None:
                        return found
        return None

    def find_all(self, *keywords):
        kws = {k.upper() for k in keywords}
        out = []
        for a in self.args:
            if isinstance(a, WktNode):
                if a.keyword.upper() in kws:
                    out.append(a)
                out.extend(a.find_all(*keywords))
        return out

    def str_args(self):
        return [a for a in self.args if isinstance(a, str)]

    def num_args(self):
        return [a for a in self.args if isinstance(a, (int, float))]

    def __repr__(self):
        return f"WktNode({self.keyword}, {self.args!r})"


_WKT_TOKENS = re.compile(
    r"""\s*(
        "(?:[^"]|"")*"          # quoted string
      | [A-Za-z_][A-Za-z0-9_]*  # keyword
      | [-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?  # number
      | [\[\](),]
    )""",
    re.VERBOSE,
)


def parse_wkt_crs(wkt):
    """WKT string -> WktNode tree. Accepts WKT1 and WKT2 ('[' or '(')."""
    tokens = _WKT_TOKENS.findall(wkt)
    if not tokens:
        raise CrsError("Empty CRS definition")
    node, pos = _parse_node(tokens, 0)
    return node


def _parse_node(tokens, pos):
    keyword = tokens[pos]
    pos += 1
    if pos >= len(tokens) or tokens[pos] not in "[(":
        return keyword, pos
    pos += 1
    args = []
    while tokens[pos] not in ")]":
        tok = tokens[pos]
        if tok == ",":
            pos += 1
            continue
        if tok.startswith('"'):
            args.append(tok[1:-1].replace('""', '"'))
            pos += 1
        elif re.fullmatch(r"[-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?", tok):
            num = float(tok)
            args.append(int(num) if num == int(num) and "." not in tok else num)
            pos += 1
        else:
            child, pos = _parse_node(tokens, pos)
            if isinstance(child, WktNode):
                args.append(child)
            else:
                args.append(child)  # bare keyword (e.g. AXIS direction NORTH)
    return WktNode(keyword, args), pos + 1


def _write_node(node, indent=0, pretty=True):
    if not isinstance(node, WktNode):
        if isinstance(node, str):
            escaped = node.replace('"', '""')
            return f'"{escaped}"'
        if isinstance(node, float) and node == int(node):
            return str(node)
        return repr(node) if isinstance(node, float) else str(node)
    parts = [_write_node(a, indent + 1, pretty) for a in node.args]
    if pretty and any(isinstance(a, WktNode) for a in node.args):
        pad = "    " * (indent + 1)
        inner = (",\n" + pad).join(parts)
        return f"{node.keyword}[\n{pad}{inner}]"
    return f"{node.keyword}[{', '.join(parts)}]"


def normalise_wkt(wkt):
    """Canonical whitespace/indentation form (reference: crs_util.py uses a
    pygments lexer for the same purpose)."""
    if not wkt or not wkt.strip():
        return wkt
    try:
        return _write_node(parse_wkt_crs(wkt)) + "\n"
    except Exception:
        return wkt


# ---------------------------------------------------------------------------
# Authority identifiers & naming
# ---------------------------------------------------------------------------


def get_authority(wkt_or_node):
    """-> (authority_name, code) from the outermost AUTHORITY/ID node, or
    (None, None)."""
    node = (
        wkt_or_node
        if isinstance(wkt_or_node, WktNode)
        else parse_wkt_crs(wkt_or_node)
    )
    # The *last* top-level AUTHORITY node identifies the whole CRS in WKT1;
    # nested ones identify datums/units. Search direct children first.
    direct = [
        a
        for a in node.args
        if isinstance(a, WktNode) and a.keyword.upper() in ("AUTHORITY", "ID")
    ]
    found = direct[-1] if direct else node.find("AUTHORITY", "ID")
    if found is None:
        return None, None
    sargs = found.str_args() + [str(a) for a in found.num_args()]
    if len(sargs) >= 2:
        return sargs[0], sargs[1]
    return None, None


# Reserved code range for CRS with no real authority id
# (reference: crs_util.py:151-153).
MIN_CUSTOM_ID = 200000
MAX_CUSTOM_ID = 209199
_CUSTOM_RANGE = MAX_CUSTOM_ID - MIN_CUSTOM_ID + 1


def _generate_identifier_int(crs):
    """Stable custom code in [MIN_CUSTOM_ID, MAX_CUSTOM_ID], hashed from the
    normalised WKT so whitespace variants agree (reference: crs_util.py:156-176)."""
    from kart_tpu.core.serialise import uint32hash

    text = crs if isinstance(crs, str) else _write_node(crs)
    return MIN_CUSTOM_ID + uint32hash(normalise_wkt(text)) % _CUSTOM_RANGE


def get_identifier_str(crs):
    """Authority string like ``EPSG:4326``, or ``CUSTOM:<code>`` for CRS
    without an authority. The custom code matches get_identifier_int
    (reference: crs_util.py:102-110)."""
    auth, code = get_authority(crs)
    if auth and code:
        return f"{auth}:{code}"
    return f"CUSTOM:{_generate_identifier_int(crs)}"


def get_identifier_int(crs):
    """Integer id for srs_id fields: the authority code when known, else the
    same stable custom code as get_identifier_str."""
    auth, code = get_authority(crs)
    if code is not None and str(code).isdigit():
        return int(code)
    return _generate_identifier_int(crs)


def parse_name(crs):
    node = crs if isinstance(crs, WktNode) else parse_wkt_crs(crs)
    sargs = node.str_args()
    return sargs[0] if sargs else None


def parse_subcrs_name(wkt, keyword):
    node = parse_wkt_crs(wkt).find(keyword)
    if node is None:
        return None
    sargs = node.str_args()
    return sargs[0] if sargs else None


# ---------------------------------------------------------------------------
# Well-known CRS definitions (no PROJ database available)
# ---------------------------------------------------------------------------

WGS84_WKT = (
    'GEOGCS["WGS 84",DATUM["WGS_1984",SPHEROID["WGS 84",6378137,298.257223563,'
    'AUTHORITY["EPSG","7030"]],AUTHORITY["EPSG","6326"]],'
    'PRIMEM["Greenwich",0,AUTHORITY["EPSG","8901"]],'
    'UNIT["degree",0.0174532925199433,AUTHORITY["EPSG","9122"]],'
    'AUTHORITY["EPSG","4326"]]'
)

WEB_MERCATOR_WKT = (
    'PROJCS["WGS 84 / Pseudo-Mercator",GEOGCS["WGS 84",DATUM["WGS_1984",'
    'SPHEROID["WGS 84",6378137,298.257223563,AUTHORITY["EPSG","7030"]],'
    'AUTHORITY["EPSG","6326"]],PRIMEM["Greenwich",0,AUTHORITY["EPSG","8901"]],'
    'UNIT["degree",0.0174532925199433,AUTHORITY["EPSG","9122"]],'
    'AUTHORITY["EPSG","4326"]],PROJECTION["Mercator_1SP"],'
    'PARAMETER["central_meridian",0],PARAMETER["scale_factor",1],'
    'PARAMETER["false_easting",0],PARAMETER["false_northing",0],'
    'UNIT["metre",1,AUTHORITY["EPSG","9001"]],AUTHORITY["EPSG","3857"]]'
)

NZTM_WKT = (
    'PROJCS["NZGD2000 / New Zealand Transverse Mercator 2000",'
    'GEOGCS["NZGD2000",DATUM["New_Zealand_Geodetic_Datum_2000",'
    'SPHEROID["GRS 1980",6378137,298.257222101,AUTHORITY["EPSG","7019"]],'
    'AUTHORITY["EPSG","6167"]],PRIMEM["Greenwich",0,AUTHORITY["EPSG","8901"]],'
    'UNIT["degree",0.0174532925199433,AUTHORITY["EPSG","9122"]],'
    'AUTHORITY["EPSG","4167"]],PROJECTION["Transverse_Mercator"],'
    'PARAMETER["latitude_of_origin",0],PARAMETER["central_meridian",173],'
    'PARAMETER["scale_factor",0.9996],PARAMETER["false_easting",1600000],'
    'PARAMETER["false_northing",10000000],UNIT["metre",1,'
    'AUTHORITY["EPSG","9001"]],AUTHORITY["EPSG","2193"]]'
)

NZGD2000_WKT = (
    'GEOGCS["NZGD2000",DATUM["New_Zealand_Geodetic_Datum_2000",'
    'SPHEROID["GRS 1980",6378137,298.257222101,AUTHORITY["EPSG","7019"]],'
    'AUTHORITY["EPSG","6167"]],PRIMEM["Greenwich",0,AUTHORITY["EPSG","8901"]],'
    'UNIT["degree",0.0174532925199433,AUTHORITY["EPSG","9122"]],'
    'AUTHORITY["EPSG","4167"]]'
)

_WELL_KNOWN = {
    4326: WGS84_WKT,
    3857: WEB_MERCATOR_WKT,
    2193: NZTM_WKT,
    4167: NZGD2000_WKT,
}


def make_crs(user_input):
    """User input (WKT, 'EPSG:n') -> CRS object (reference: crs_util.py:17-32).

    Bare EPSG codes resolve first against the curated WKT strings above,
    then the built-in parameter registry (kart_tpu/epsg.py: common
    geographic + projected CRSes and whole UTM families, synthesized to
    WKT1). Codes outside the registry raise a CrsError that lists the
    coverage — the reference resolves these via OSR/PROJ's database, which
    this build deliberately doesn't carry."""
    if isinstance(user_input, CRS):
        return user_input
    text = user_input.strip()
    m = re.fullmatch(r"(?i)EPSG:(\d+)", text)
    if m:
        code = int(m.group(1))
        if code in _WELL_KNOWN:
            return CRS(_WELL_KNOWN[code])
        from kart_tpu import epsg

        wkt = epsg.epsg_wkt(code)
        if wkt is not None:
            return CRS(wkt)
        raise CrsError(
            f"EPSG:{code} is not in the built-in CRS registry (this build "
            f"carries no PROJ database); supply the full WKT definition "
            f"instead. Built-in coverage — {epsg.registry_summary()}"
        )
    return CRS(text)


class CRS:
    """A parsed CRS: enough structure to identify it and to run the built-in
    transforms. Unknown projections parse fine but refuse to transform."""

    def __init__(self, wkt):
        self.wkt = wkt
        self.node = parse_wkt_crs(wkt)
        kw = self.node.keyword.upper()
        self.is_geographic = kw in ("GEOGCS", "GEOGCRS", "GEODCRS")
        self.is_projected = kw in ("PROJCS", "PROJCRS")
        self.name = parse_name(self.node)
        self.authority, self.code = get_authority(self.node)

        sph = self.node.find("SPHEROID", "ELLIPSOID")
        if sph is not None:
            nums = sph.num_args()
            self.semi_major = float(nums[0]) if nums else 6378137.0
            inv_f = float(nums[1]) if len(nums) > 1 else 298.257223563
            self.inv_flattening = inv_f
        else:
            self.semi_major, self.inv_flattening = 6378137.0, 298.257223563

        self.projection = None
        self.params = {}
        if self.is_projected:
            proj = self.node.find("PROJECTION")
            if proj is not None:
                sargs = proj.str_args()
                self.projection = sargs[0] if sargs else None
            for p in self.node.find_all("PARAMETER"):
                sargs = p.str_args()
                nums = p.num_args()
                if sargs and nums:
                    self.params[sargs[0].lower()] = float(nums[0])
            # Web-mercator WKT1 exports commonly claim Mercator_1SP but the
            # method is the *spherical* pseudo-mercator. Recognise it by
            # authority code, CRS name, or a PROJ4 EXTENSION forcing the
            # sphere (+b == +a / +nadgrids=@null)
            if (self.projection or "").lower() == "mercator_1sp":
                ext = self.node.find("EXTENSION")
                ext_text = " ".join(ext.str_args()) if ext is not None else ""
                is_web_mercator = (
                    str(self.code) in ("3857", "3785", "900913", "102100", "102113")
                    or "pseudo-mercator" in (self.name or "").lower()
                    or "+nadgrids=@null" in ext_text
                    or "+b=6378137" in ext_text
                )
                if is_web_mercator:
                    self.projection = "popular_visualisation_pseudo_mercator"

        # datum shift to WGS84 (WKT1 TOWGS84): 3- or 7-parameter Helmert,
        # (dx, dy, dz[, rx, ry, rz, scale_ppm]); None = datum treated as
        # WGS84-equivalent (the pre-round-2 behavior, within ~1m for modern
        # datums)
        self.towgs84 = None
        tw = self.node.find("TOWGS84")
        if tw is not None:
            nums = [float(v) for v in tw.num_args()]
            if len(nums) >= 3:
                self.towgs84 = tuple((nums + [0.0] * 7)[:7])
        datum = self.node.find("DATUM")
        self.datum_name = (
            datum.str_args()[0] if datum is not None and datum.str_args() else None
        )

    @property
    def identifier_str(self):
        return get_identifier_str(self.node)

    @property
    def identifier_int(self):
        return get_identifier_int(self.node)

    def __eq__(self, other):
        return isinstance(other, CRS) and normalise_wkt(self.wkt) == normalise_wkt(
            other.wkt
        )

    def __hash__(self):
        return hash(normalise_wkt(self.wkt))

    def __repr__(self):
        return f"CRS({self.identifier_str} {self.name!r})"


# ---------------------------------------------------------------------------
# Transforms (vectorized numpy)
# ---------------------------------------------------------------------------


def _tm_constants(a, inv_f):
    f = 1.0 / inv_f
    e2 = f * (2 - f)
    n = f / (2 - f)
    # series coefficients for the Krueger transverse mercator (order n^4)
    A = a / (1 + n) * (1 + n**2 / 4 + n**4 / 64)
    alpha = np.array(
        [
            n / 2 - 2 * n**2 / 3 + 5 * n**3 / 16 + 41 * n**4 / 180,
            13 * n**2 / 48 - 3 * n**3 / 5 + 557 * n**4 / 1440,
            61 * n**3 / 240 - 103 * n**4 / 140,
            49561 * n**4 / 161280,
        ]
    )
    beta = np.array(
        [
            n / 2 - 2 * n**2 / 3 + 37 * n**3 / 96 - 1 * n**4 / 360,
            1 * n**2 / 48 + 1 * n**3 / 15 - 437 * n**4 / 1440,
            17 * n**3 / 480 - 37 * n**4 / 840,
            4397 * n**4 / 161280,
        ]
    )
    delta = np.array(
        [
            2 * n - 2 * n**2 / 3 - 2 * n**3 + 116 * n**4 / 45,
            7 * n**2 / 3 - 8 * n**3 / 5 - 227 * n**4 / 45,
            56 * n**3 / 15 - 136 * n**4 / 35,
            4279 * n**4 / 630,
        ]
    )
    return e2, A, alpha, beta, delta


def _tm_forward(crs, lon_deg, lat_deg):
    a, inv_f = crs.semi_major, crs.inv_flattening
    e2, A, alpha, _, _ = _tm_constants(a, inv_f)
    e = math.sqrt(e2)
    k0 = crs.params.get("scale_factor", 1.0)
    lat0 = math.radians(crs.params.get("latitude_of_origin", 0.0))
    lon0 = math.radians(crs.params.get("central_meridian", 0.0))
    fe = crs.params.get("false_easting", 0.0)
    fn = crs.params.get("false_northing", 0.0)

    lon = np.radians(np.asarray(lon_deg, dtype=np.float64))
    lat = np.radians(np.asarray(lat_deg, dtype=np.float64))

    # conformal latitude
    t = np.sinh(
        np.arctanh(np.sin(lat)) - e * np.arctanh(e * np.sin(lat))
    )
    xi_p = np.arctan2(t, np.cos(lon - lon0))
    eta_p = np.arctanh(np.sin(lon - lon0) / np.sqrt(1 + t**2))

    j = np.arange(1, 5)
    xi = xi_p + np.sum(
        alpha[None, :]
        * np.sin(2 * j[None, :] * xi_p[..., None])
        * np.cosh(2 * j[None, :] * eta_p[..., None]),
        axis=-1,
    )
    eta = eta_p + np.sum(
        alpha[None, :]
        * np.cos(2 * j[None, :] * xi_p[..., None])
        * np.sinh(2 * j[None, :] * eta_p[..., None]),
        axis=-1,
    )

    # meridian distance from equator to lat0
    if lat0 != 0.0:
        t0 = math.sinh(
            math.atanh(math.sin(lat0)) - e * math.atanh(e * math.sin(lat0))
        )
        xi0 = math.atan2(t0, 1.0)
        m0 = A * (
            xi0
            + float(np.sum(alpha * np.sin(2 * np.arange(1, 5) * xi0)))
        )
    else:
        m0 = 0.0

    x = fe + k0 * A * eta
    y = fn + k0 * (A * xi - m0)
    return x, y


def _tm_inverse(crs, x, y):
    a, inv_f = crs.semi_major, crs.inv_flattening
    e2, A, alpha, beta, delta = _tm_constants(a, inv_f)
    e = math.sqrt(e2)
    k0 = crs.params.get("scale_factor", 1.0)
    lat0 = math.radians(crs.params.get("latitude_of_origin", 0.0))
    lon0 = math.radians(crs.params.get("central_meridian", 0.0))
    fe = crs.params.get("false_easting", 0.0)
    fn = crs.params.get("false_northing", 0.0)

    if lat0 != 0.0:
        t0 = math.sinh(
            math.atanh(math.sin(lat0)) - e * math.atanh(e * math.sin(lat0))
        )
        xi0 = math.atan2(t0, 1.0)
        m0 = A * (xi0 + float(np.sum(alpha * np.sin(2 * np.arange(1, 5) * xi0))))
    else:
        m0 = 0.0

    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    xi = (y - fn + k0 * m0) / (k0 * A)
    eta = (x - fe) / (k0 * A)

    j = np.arange(1, 5)
    xi_p = xi - np.sum(
        beta[None, :]
        * np.sin(2 * j[None, :] * xi[..., None])
        * np.cosh(2 * j[None, :] * eta[..., None]),
        axis=-1,
    )
    eta_p = eta - np.sum(
        beta[None, :]
        * np.cos(2 * j[None, :] * xi[..., None])
        * np.sinh(2 * j[None, :] * eta[..., None]),
        axis=-1,
    )
    chi = np.arcsin(np.sin(xi_p) / np.cosh(eta_p))
    lat = chi + np.sum(
        delta[None, :] * np.sin(2 * j[None, :] * chi[..., None]), axis=-1
    )
    lon = lon0 + np.arctan2(np.sinh(eta_p), np.cos(xi_p))
    return np.degrees(lon), np.degrees(lat)


def _webmerc_forward(crs, lon_deg, lat_deg):
    """Spherical (web) mercator — EPSG 1024, used by 3857."""
    a = crs.semi_major
    lon0 = math.radians(crs.params.get("central_meridian", 0.0))
    fe = crs.params.get("false_easting", 0.0)
    fn = crs.params.get("false_northing", 0.0)
    lon = np.radians(np.asarray(lon_deg, dtype=np.float64)) - lon0
    lat = np.radians(np.clip(np.asarray(lat_deg, dtype=np.float64), -89.9999, 89.9999))
    return fe + a * lon, fn + a * np.log(np.tan(np.pi / 4 + lat / 2))


def _webmerc_inverse(crs, x, y):
    a = crs.semi_major
    lon0 = crs.params.get("central_meridian", 0.0)
    fe = crs.params.get("false_easting", 0.0)
    fn = crs.params.get("false_northing", 0.0)
    lon = lon0 + np.degrees((np.asarray(x, dtype=np.float64) - fe) / a)
    lat = np.degrees(
        2 * np.arctan(np.exp((np.asarray(y, dtype=np.float64) - fn) / a)) - np.pi / 2
    )
    return lon, lat


def _mercator_k0(crs):
    """1SP: explicit scale factor. 2SP: k0 = m(standard_parallel_1)."""
    if "standard_parallel_1" in crs.params:
        sp1 = math.radians(crs.params["standard_parallel_1"])
        e2 = _e2_of(crs)
        return math.cos(sp1) / math.sqrt(1 - e2 * math.sin(sp1) ** 2)
    return crs.params.get("scale_factor", 1.0)


def _mercator_forward(crs, lon_deg, lat_deg):
    """Ellipsoidal Mercator (EPSG 9804 1SP / 9805 2SP) — e.g. EPSG:3832
    PDC Mercator (central_meridian 150) and EPSG:3994."""
    a = crs.semi_major
    e = math.sqrt(_e2_of(crs))
    k0 = _mercator_k0(crs)
    lon0 = math.radians(crs.params.get("central_meridian", 0.0))
    fe = crs.params.get("false_easting", 0.0)
    fn = crs.params.get("false_northing", 0.0)
    lon = np.radians(np.asarray(lon_deg, dtype=np.float64)) - lon0
    lat = np.radians(np.clip(np.asarray(lat_deg, dtype=np.float64), -89.9999, 89.9999))
    sin_lat = np.sin(lat)
    x = fe + a * k0 * lon
    y = fn + a * k0 * np.log(
        np.tan(np.pi / 4 + lat / 2)
        * ((1 - e * sin_lat) / (1 + e * sin_lat)) ** (e / 2)
    )
    return x, y


def _mercator_inverse(crs, x, y):
    a = crs.semi_major
    e = math.sqrt(_e2_of(crs))
    k0 = _mercator_k0(crs)
    lon0 = crs.params.get("central_meridian", 0.0)
    fe = crs.params.get("false_easting", 0.0)
    fn = crs.params.get("false_northing", 0.0)
    lon = lon0 + np.degrees((np.asarray(x, dtype=np.float64) - fe) / (a * k0))
    t = np.exp(-(np.asarray(y, dtype=np.float64) - fn) / (a * k0))
    lat = np.pi / 2 - 2 * np.arctan(t)
    for _ in range(6):
        sin_lat = np.sin(lat)
        lat = np.pi / 2 - 2 * np.arctan(
            t * ((1 - e * sin_lat) / (1 + e * sin_lat)) ** (e / 2)
        )
    return lon, np.degrees(lat)


def _lcc_setup(crs):
    """Shared constants for Lambert Conformal Conic (Snyder 1987, §15;
    EPSG methods 9801 1SP / 9802 2SP). 1SP is the 2SP degenerate case with
    both standard parallels at latitude_of_origin and k0 applied."""
    a = crs.semi_major
    e2 = _e2_of(crs)  # treats inv_flattening == 0 as a sphere (e2 = 0)
    e = math.sqrt(e2)

    def m(phi):
        return math.cos(phi) / math.sqrt(1 - e2 * math.sin(phi) ** 2)

    def t(phi):
        return math.tan(math.pi / 4 - phi / 2) / (
            (1 - e * math.sin(phi)) / (1 + e * math.sin(phi))
        ) ** (e / 2)

    p = crs.params
    lat0 = math.radians(p.get("latitude_of_origin", 0.0))
    lon0 = math.radians(p.get("central_meridian", 0.0))
    fe = p.get("false_easting", 0.0)
    fn = p.get("false_northing", 0.0)
    sp1 = math.radians(p.get("standard_parallel_1", math.degrees(lat0)))
    sp2 = math.radians(p.get("standard_parallel_2", math.degrees(sp1)))
    k0 = p.get("scale_factor", 1.0)

    if abs(sp1 - sp2) > 1e-12:
        n = (math.log(m(sp1)) - math.log(m(sp2))) / (
            math.log(t(sp1)) - math.log(t(sp2))
        )
    else:
        n = math.sin(sp1)
    F = m(sp1) / (n * t(sp1) ** n)
    rho0 = a * k0 * F * t(lat0) ** n
    return a, e, n, F * k0, rho0, lat0, lon0, fe, fn


def _lcc_forward(crs, lon_deg, lat_deg):
    a, e, n, Fk, rho0, lat0, lon0, fe, fn = _lcc_setup(crs)
    lon = np.radians(np.asarray(lon_deg, dtype=np.float64))
    lat = np.radians(
        np.clip(np.asarray(lat_deg, dtype=np.float64), -89.9999, 89.9999)
    )
    t = np.tan(np.pi / 4 - lat / 2) / (
        (1 - e * np.sin(lat)) / (1 + e * np.sin(lat))
    ) ** (e / 2)
    # southern-hemisphere cones have n, F (and so rho) negative — the
    # standard formulas handle that with no special-casing (Snyder p.107)
    rho = a * Fk * t**n
    theta = n * (lon - lon0)
    x = fe + rho * np.sin(theta)
    y = fn + rho0 - rho * np.cos(theta)
    return x, y


def _lcc_inverse(crs, x, y):
    a, e, n, Fk, rho0, lat0, lon0, fe, fn = _lcc_setup(crs)
    x = np.asarray(x, dtype=np.float64) - fe
    y = rho0 - (np.asarray(y, dtype=np.float64) - fn)
    rho = np.sign(n) * np.sqrt(x**2 + y**2)
    theta = np.arctan2(np.sign(n) * x, np.sign(n) * y)
    with np.errstate(divide="ignore", invalid="ignore"):
        tp = (rho / (a * Fk)) ** (1.0 / n)
    # iterate the conformal-latitude inversion (converges in a few rounds)
    phi = np.pi / 2 - 2 * np.arctan(tp)
    for _ in range(8):
        phi = np.pi / 2 - 2 * np.arctan(
            tp * ((1 - e * np.sin(phi)) / (1 + e * np.sin(phi))) ** (e / 2)
        )
    lon = theta / n + lon0
    return np.degrees(lon), np.degrees(phi)


def _q_of(e, e2, sin_lat):
    """Snyder's authalic q (3-12); works on scalars and arrays."""
    if e == 0:
        return 2 * sin_lat
    return (1 - e2) * (
        sin_lat / (1 - e2 * sin_lat**2)
        - (1 / (2 * e)) * np.log((1 - e * sin_lat) / (1 + e * sin_lat))
    )


def _albers_setup(crs):
    """Albers Equal-Area Conic constants (Snyder 1987 §14; EPSG 9822)."""
    a = crs.semi_major
    e2 = _e2_of(crs)
    e = math.sqrt(e2)

    def m(phi):
        return math.cos(phi) / math.sqrt(1 - e2 * math.sin(phi) ** 2)

    def q(phi):
        return float(_q_of(e, e2, math.sin(phi)))

    p = crs.params
    lat0 = math.radians(p.get("latitude_of_origin", p.get("latitude_of_center", 0.0)))
    lon0 = math.radians(p.get("central_meridian", p.get("longitude_of_center", 0.0)))
    fe = p.get("false_easting", 0.0)
    fn = p.get("false_northing", 0.0)
    sp1 = math.radians(p.get("standard_parallel_1", math.degrees(lat0)))
    sp2 = math.radians(p.get("standard_parallel_2", math.degrees(sp1)))

    if abs(sp1 - sp2) > 1e-12:
        n = (m(sp1) ** 2 - m(sp2) ** 2) / (q(sp2) - q(sp1))
    else:
        n = math.sin(sp1)
    C = m(sp1) ** 2 + n * q(sp1)
    rho0 = a * math.sqrt(max(C - n * q(lat0), 0.0)) / n
    return a, e, e2, n, C, rho0, lon0, fe, fn


def _albers_forward(crs, lon_deg, lat_deg):
    a, e, e2, n, C, rho0, lon0, fe, fn = _albers_setup(crs)
    lon = np.radians(np.asarray(lon_deg, dtype=np.float64))
    lat = np.radians(np.asarray(lat_deg, dtype=np.float64))
    q = _q_of(e, e2, np.sin(lat))
    rho = a * np.sqrt(np.maximum(C - n * q, 0.0)) / n
    theta = n * (lon - lon0)
    x = fe + rho * np.sin(theta)
    y = fn + rho0 - rho * np.cos(theta)
    return x, y


def _albers_inverse(crs, x, y):
    a, e, e2, n, C, rho0, lon0, fe, fn = _albers_setup(crs)
    x = np.asarray(x, dtype=np.float64) - fe
    y = rho0 - (np.asarray(y, dtype=np.float64) - fn)
    rho = np.sign(n) * np.sqrt(x**2 + y**2)
    theta = np.arctan2(np.sign(n) * x, np.sign(n) * y)
    q = (C - (rho * n / a) ** 2) / n
    if e == 0:
        phi = np.arcsin(np.clip(q / 2, -1.0, 1.0))
    else:
        # iterate Snyder (3-16); q at the pole is qp = q(pi/2)
        qp = _q_of(e, e2, 1.0)
        phi = np.arcsin(np.clip(q / 2, -1.0, 1.0))
        for _ in range(8):
            s = np.sin(phi)
            # Snyder (3-16): the bracket is (q - q(phi)) / (1 - e2)
            phi = phi + (1 - e2 * s**2) ** 2 / (2 * np.cos(phi)) * (
                (q - _q_of(e, e2, s)) / (1 - e2)
            )
        # exactly-polar q would divide by cos(phi)=0 above; clamp handles it
        phi = np.where(np.abs(q) >= np.abs(qp) - 1e-12, np.sign(q) * np.pi / 2, phi)
    lon = lon0 + theta / n
    return np.degrees(lon), np.degrees(phi)


def _polar_stereo_setup(crs):
    """Polar Stereographic (Snyder 1987 §21; EPSG 9810 variant A via
    scale_factor at the pole, 9829 variant B via a standard parallel)."""
    a = crs.semi_major
    e2 = _e2_of(crs)
    e = math.sqrt(e2)
    p = crs.params
    lat0 = p.get("latitude_of_origin", p.get("standard_parallel_1", 90.0))
    south = lat0 < 0
    lon0 = math.radians(p.get("central_meridian", p.get("longitude_of_origin", 0.0)))
    fe = p.get("false_easting", 0.0)
    fn = p.get("false_northing", 0.0)
    k0 = p.get("scale_factor", 1.0)

    def t_of(phi):
        return math.tan(math.pi / 4 - phi / 2) / (
            (1 - e * math.sin(phi)) / (1 + e * math.sin(phi))
        ) ** (e / 2)

    if abs(abs(lat0) - 90.0) > 1e-9:
        # variant B: the scale is set by the standard parallel
        phi_f = math.radians(abs(lat0))
        m_f = math.cos(phi_f) / math.sqrt(1 - e2 * math.sin(phi_f) ** 2)
        rho_factor = a * m_f / t_of(phi_f)
    else:
        rho_factor = (
            2 * a * k0 / math.sqrt((1 + e) ** (1 + e) * (1 - e) ** (1 - e))
        )
    return a, e, south, lon0, fe, fn, rho_factor


def _polar_stereo_forward(crs, lon_deg, lat_deg):
    a, e, south, lon0, fe, fn, rho_factor = _polar_stereo_setup(crs)
    lon = np.radians(np.asarray(lon_deg, dtype=np.float64))
    lat = np.radians(np.asarray(lat_deg, dtype=np.float64))
    if south:
        lat = -lat
        lon = -(lon - lon0)
    else:
        lon = lon - lon0
    t = np.tan(np.pi / 4 - lat / 2) / (
        (1 - e * np.sin(lat)) / (1 + e * np.sin(lat))
    ) ** (e / 2)
    rho = rho_factor * t
    x = rho * np.sin(lon)
    y = -rho * np.cos(lon)
    if south:
        x, y = -x, -y
    return fe + x, fn + y


def _polar_stereo_inverse(crs, x, y):
    a, e, south, lon0, fe, fn, rho_factor = _polar_stereo_setup(crs)
    x = np.asarray(x, dtype=np.float64) - fe
    y = np.asarray(y, dtype=np.float64) - fn
    if south:
        x, y = -x, -y
    rho = np.sqrt(x**2 + y**2)
    t = rho / rho_factor
    phi = np.pi / 2 - 2 * np.arctan(t)
    for _ in range(8):
        phi = np.pi / 2 - 2 * np.arctan(
            t * ((1 - e * np.sin(phi)) / (1 + e * np.sin(phi))) ** (e / 2)
        )
    lon = np.arctan2(x, -y)
    if south:
        phi = -phi
        lon = lon0 - lon
    else:
        lon = lon0 + lon
    return np.degrees(lon), np.degrees(phi)


def _oblique_stereo_setup(crs):
    """Oblique (double) Stereographic — EPSG 9809, the RD New / Amersfoort
    method: conformal-sphere projection of the conformal latitude (EPSG
    Guidance Note 7-2 §3.2.2.1)."""
    a = crs.semi_major
    e2 = _e2_of(crs)
    e = math.sqrt(e2)
    p = crs.params
    phi0 = math.radians(p.get("latitude_of_origin", 0.0))
    lam0 = math.radians(p.get("central_meridian", 0.0))
    fe = p.get("false_easting", 0.0)
    fn = p.get("false_northing", 0.0)
    k0 = p.get("scale_factor", 1.0)

    s0 = math.sin(phi0)
    rho0 = a * (1 - e2) / (1 - e2 * s0 * s0) ** 1.5
    nu0 = a / math.sqrt(1 - e2 * s0 * s0)
    R = math.sqrt(rho0 * nu0)
    n = math.sqrt(1 + e2 * math.cos(phi0) ** 4 / (1 - e2))

    S1 = (1 + s0) / (1 - s0)
    S2 = (1 - e * s0) / (1 + e * s0)
    w1 = (S1 * S2**e) ** n
    sin_chi00 = (w1 - 1) / (w1 + 1)
    c = (n + s0) * (1 - sin_chi00) / ((n - s0) * (1 + sin_chi00))
    w2 = c * w1
    chi0 = math.asin((w2 - 1) / (w2 + 1))
    return e, n, c, R, k0, chi0, phi0, lam0, fe, fn


def _oblique_stereo_forward(crs, lon_deg, lat_deg):
    e, n, c, R, k0, chi0, phi0, lam0, fe, fn = _oblique_stereo_setup(crs)
    lam = np.radians(np.asarray(lon_deg, dtype=np.float64))
    # exact poles make (1+sin)/(1-sin) blow up; same clamp as mercator/lcc
    phi = np.radians(
        np.clip(np.asarray(lat_deg, dtype=np.float64), -89.9999, 89.9999)
    )
    s = np.sin(phi)
    Sa = (1 + s) / (1 - s)
    Sb = (1 - e * s) / (1 + e * s)
    w = c * (Sa * Sb**e) ** n
    chi = np.arcsin((w - 1) / (w + 1))
    dlam = n * (lam - lam0)
    B = 1 + np.sin(chi) * np.sin(chi0) + np.cos(chi) * np.cos(chi0) * np.cos(dlam)
    x = fe + 2 * R * k0 * np.cos(chi) * np.sin(dlam) / B
    y = fn + 2 * R * k0 * (
        np.sin(chi) * np.cos(chi0) - np.cos(chi) * np.sin(chi0) * np.cos(dlam)
    ) / B
    return x, y


def _oblique_stereo_inverse(crs, x, y):
    e, n, c, R, k0, chi0, phi0, lam0, fe, fn = _oblique_stereo_setup(crs)
    xp = np.asarray(x, dtype=np.float64) - fe
    yp = np.asarray(y, dtype=np.float64) - fn
    g = 2 * R * k0 * math.tan(math.pi / 4 - chi0 / 2)
    h = 4 * R * k0 * math.tan(chi0) + g
    i = np.arctan2(xp, h + yp)
    j = np.arctan2(xp, g - yp) - i
    chi = chi0 + 2 * np.arctan((yp - xp * np.tan(j / 2)) / (2 * R * k0))
    dlam = j + 2 * i
    lam = dlam / n + lam0
    # isometric latitude of the conformal sphere -> ellipsoidal latitude
    psi = 0.5 * np.log((1 + np.sin(chi)) / (c * (1 - np.sin(chi)))) / n
    phi = 2 * np.arctan(np.exp(psi)) - np.pi / 2
    for _ in range(8):
        s = np.sin(phi)
        psi_i = np.log(
            np.tan(phi / 2 + np.pi / 4) * ((1 - e * s) / (1 + e * s)) ** (e / 2)
        )
        phi = phi - (psi_i - psi) * np.cos(phi) * (1 - e**2 * s**2) / (1 - e**2)
    return np.degrees(lam), np.degrees(phi)


def _laea_setup(crs):
    """Lambert Azimuthal Equal Area, oblique/equatorial aspect (EPSG method
    9820, Guidance Note 7-2 §3.2.2; Snyder 1987 §24). The polar aspect
    (|lat0| = 90) has a different formula set and is refused loudly."""
    a = crs.semi_major
    e2 = _e2_of(crs)
    e = math.sqrt(e2)
    p = crs.params
    lat0 = math.radians(p.get("latitude_of_origin", p.get("latitude_of_center", 0.0)))
    lon0 = math.radians(p.get("central_meridian", p.get("longitude_of_center", 0.0)))
    fe = p.get("false_easting", 0.0)
    fn = p.get("false_northing", 0.0)
    if abs(abs(lat0) - math.pi / 2) < 1e-9:
        raise CrsError(
            "Polar-aspect Lambert Azimuthal Equal Area is not supported by "
            "the built-in transform engine"
        )
    qp = float(_q_of(e, e2, 1.0))
    q0 = float(_q_of(e, e2, math.sin(lat0)))
    beta0 = math.asin(q0 / qp)
    rq = a * math.sqrt(qp / 2.0)
    d = (
        a
        * (math.cos(lat0) / math.sqrt(1 - e2 * math.sin(lat0) ** 2))
        / (rq * math.cos(beta0))
    )
    return a, e, e2, qp, beta0, rq, d, lon0, fe, fn


def _laea_forward(crs, lon_deg, lat_deg):
    a, e, e2, qp, beta0, rq, d, lon0, fe, fn = _laea_setup(crs)
    lon = np.radians(np.asarray(lon_deg, dtype=np.float64))
    lat = np.radians(
        np.clip(np.asarray(lat_deg, dtype=np.float64), -89.9999, 89.9999)
    )
    q = _q_of(e, e2, np.sin(lat))
    beta = np.arcsin(np.clip(q / qp, -1.0, 1.0))
    dlon = lon - lon0
    denom = 1.0 + math.sin(beta0) * np.sin(beta) + math.cos(beta0) * np.cos(
        beta
    ) * np.cos(dlon)
    b = rq * np.sqrt(2.0 / np.maximum(denom, 1e-12))
    x = fe + (b * d) * np.cos(beta) * np.sin(dlon)
    y = fn + (b / d) * (
        math.cos(beta0) * np.sin(beta)
        - math.sin(beta0) * np.cos(beta) * np.cos(dlon)
    )
    return x, y


def _laea_inverse(crs, x, y):
    a, e, e2, qp, beta0, rq, d, lon0, fe, fn = _laea_setup(crs)
    xs = (np.asarray(x, dtype=np.float64) - fe) / d
    ys = (np.asarray(y, dtype=np.float64) - fn) * d
    rho = np.sqrt(xs**2 + ys**2)
    c = 2.0 * np.arcsin(np.clip(rho / (2.0 * rq), -1.0, 1.0))
    with np.errstate(divide="ignore", invalid="ignore"):
        beta_p = np.arcsin(
            np.clip(
                np.cos(c) * math.sin(beta0)
                + np.where(rho == 0, 0.0, ys * np.sin(c) * math.cos(beta0) / rho),
                -1.0,
                1.0,
            )
        )
    # EPSG GN7-2: atan2((E-FE) sinC, D rho cosB0 cosC - D^2 (N-FN) sinB0 sinC)
    # with xs = (E-FE)/D and ys = D (N-FN), both args divide by D:
    lon = lon0 + np.arctan2(
        xs * np.sin(c),
        rho * math.cos(beta0) * np.cos(c)
        - ys * math.sin(beta0) * np.sin(c),
    )
    # authalic -> geodetic latitude series (Snyder 3-18)
    e4 = e2 * e2
    e6 = e4 * e2
    phi = (
        beta_p
        + (e2 / 3 + 31 * e4 / 180 + 517 * e6 / 5040) * np.sin(2 * beta_p)
        + (23 * e4 / 360 + 251 * e6 / 3780) * np.sin(4 * beta_p)
        + (761 * e6 / 45360) * np.sin(6 * beta_p)
    )
    phi = np.where(rho == 0, _lat0_of(crs), phi)
    lon = np.where(rho == 0, lon0, lon)
    return np.degrees(lon), np.degrees(phi)


def _lat0_of(crs):
    p = crs.params
    return math.radians(
        p.get("latitude_of_origin", p.get("latitude_of_center", 0.0))
    )


def _cea_setup(crs):
    """Lambert Cylindrical Equal Area (EPSG method 9835; Snyder 1987 §10,
    ellipsoidal, normal aspect with a standard parallel)."""
    a = crs.semi_major
    e2 = _e2_of(crs)
    e = math.sqrt(e2)
    p = crs.params
    lat_ts = math.radians(
        p.get("standard_parallel_1", p.get("latitude_of_origin", 0.0))
    )
    lon0 = math.radians(p.get("central_meridian", p.get("longitude_of_center", 0.0)))
    fe = p.get("false_easting", 0.0)
    fn = p.get("false_northing", 0.0)
    k0 = math.cos(lat_ts) / math.sqrt(1 - e2 * math.sin(lat_ts) ** 2)
    qp = float(_q_of(e, e2, 1.0))
    return a, e, e2, qp, k0, lon0, fe, fn


def _cea_forward(crs, lon_deg, lat_deg):
    a, e, e2, qp, k0, lon0, fe, fn = _cea_setup(crs)
    lon = np.radians(np.asarray(lon_deg, dtype=np.float64))
    lat = np.radians(
        np.clip(np.asarray(lat_deg, dtype=np.float64), -89.9999, 89.9999)
    )
    q = _q_of(e, e2, np.sin(lat))
    x = fe + a * k0 * (lon - lon0)
    y = fn + a * q / (2.0 * k0)
    return x, y


def _cea_inverse(crs, x, y):
    a, e, e2, qp, k0, lon0, fe, fn = _cea_setup(crs)
    xs = np.asarray(x, dtype=np.float64) - fe
    ys = np.asarray(y, dtype=np.float64) - fn
    lon = lon0 + xs / (a * k0)
    beta = np.arcsin(np.clip(2.0 * ys * k0 / (a * qp), -1.0, 1.0))
    e4 = e2 * e2
    e6 = e4 * e2
    phi = (
        beta
        + (e2 / 3 + 31 * e4 / 180 + 517 * e6 / 5040) * np.sin(2 * beta)
        + (23 * e4 / 360 + 251 * e6 / 3780) * np.sin(4 * beta)
        + (761 * e6 / 45360) * np.sin(6 * beta)
    )
    return np.degrees(lon), np.degrees(phi)


def _somerc_setup(crs):
    """Swiss Oblique Mercator (EPSG method 9814, PROJ ``somerc``): the
    double projection ellipsoid -> conformal sphere -> oblique equatorial
    Mercator used by CH1903 / CH1903+ (LV03/LV95). Constants per the
    swisstopo projection formulae."""
    a = crs.semi_major
    e2 = _e2_of(crs)
    e = math.sqrt(e2)
    p = crs.params
    # the Swiss double projection equals Hotine Oblique Mercator
    # (azimuth-center variant) only for azimuth = rectified angle = 90°
    # (how CH1903 WKT1 is exported); a general-azimuth HOM (Malaysia RSO,
    # Alaska zone 1) is a different construction — refuse loudly
    for angle in ("azimuth", "rectified_grid_angle"):
        if angle in p and abs(p[angle] - 90.0) > 1e-6:
            raise CrsError(
                f"Hotine Oblique Mercator with {angle}={p[angle]} is not "
                f"supported by the built-in transform engine (only the "
                f"Swiss azimuth=90 form)"
            )
    lat0 = math.radians(p.get("latitude_of_origin", p.get("latitude_of_center", 0.0)))
    lon0 = math.radians(p.get("central_meridian", p.get("longitude_of_center", 0.0)))
    k0 = p.get("scale_factor", 1.0)
    fe = p.get("false_easting", 0.0)
    fn = p.get("false_northing", 0.0)
    s0 = math.sin(lat0)
    alpha = math.sqrt(1 + e2 * math.cos(lat0) ** 4 / (1 - e2))
    r = a * k0 * math.sqrt(1 - e2) / (1 - e2 * s0 * s0)
    b0 = math.asin(s0 / alpha)
    big_k = (
        math.log(math.tan(math.pi / 4 + b0 / 2))
        - alpha
        * (
            math.log(math.tan(math.pi / 4 + lat0 / 2))
            - (e / 2) * math.log((1 + e * s0) / (1 - e * s0))
        )
    )
    return e, alpha, r, b0, big_k, lon0, fe, fn


def _somerc_forward(crs, lon_deg, lat_deg):
    e, alpha, r, b0, big_k, lon0, fe, fn = _somerc_setup(crs)
    lon = np.radians(np.asarray(lon_deg, dtype=np.float64))
    lat = np.radians(
        np.clip(np.asarray(lat_deg, dtype=np.float64), -89.9999, 89.9999)
    )
    s = np.sin(lat)
    big_s = (
        alpha
        * (
            np.log(np.tan(np.pi / 4 + lat / 2))
            - (e / 2) * np.log((1 + e * s) / (1 - e * s))
        )
        + big_k
    )
    b = 2 * (np.arctan(np.exp(big_s)) - np.pi / 4)
    ell = alpha * (lon - lon0)
    b_bar = np.arcsin(
        np.clip(
            np.cos(b0) * np.sin(b) - np.sin(b0) * np.cos(b) * np.cos(ell),
            -1.0,
            1.0,
        )
    )
    l_bar = np.arctan2(
        np.cos(b) * np.sin(ell),
        np.sin(b0) * np.sin(b) + np.cos(b0) * np.cos(b) * np.cos(ell),
    )
    y = r * l_bar
    x = (r / 2) * np.log((1 + np.sin(b_bar)) / (1 - np.sin(b_bar)))
    return fe + y, fn + x


def _somerc_inverse(crs, x, y):
    e, alpha, r, b0, big_k, lon0, fe, fn = _somerc_setup(crs)
    yy = np.asarray(x, dtype=np.float64) - fe  # easting axis
    xx = np.asarray(y, dtype=np.float64) - fn  # northing axis
    l_bar = yy / r
    b_bar = 2 * (np.arctan(np.exp(xx / r)) - np.pi / 4)
    b = np.arcsin(
        np.clip(
            np.cos(b0) * np.sin(b_bar) + np.sin(b0) * np.cos(b_bar) * np.cos(l_bar),
            -1.0,
            1.0,
        )
    )
    ell = np.arctan2(
        np.cos(b_bar) * np.sin(l_bar),
        -np.sin(b0) * np.sin(b_bar) + np.cos(b0) * np.cos(b_bar) * np.cos(l_bar),
    )
    lon = lon0 + ell / alpha
    # sphere -> ellipsoid latitude: fixed-point on the conformal relation
    lat = b.copy()
    for _ in range(8):
        s = np.sin(lat)
        big_s = (
            np.log(np.tan(np.pi / 4 + b / 2)) - big_k
        ) / alpha + e * np.log(np.tan(np.pi / 4 + np.arcsin(e * s) / 2))
        lat = 2 * np.arctan(np.exp(big_s)) - np.pi / 2
    return np.degrees(lon), np.degrees(lat)


def _hom_setup(crs, variant_b):
    """Hotine Oblique Mercator (EPSG method 9812 variant A / 9815 variant
    B): constants per EPSG Guidance Note 7-2. Variant B references
    false coordinates to the projection centre (Ec, Nc); variant A to the
    natural origin (intersection of the aposphere equator and centre
    line)."""
    a = crs.semi_major
    e2 = _e2_of(crs)
    e = math.sqrt(e2)
    p = crs.params
    phic = math.radians(
        p.get("latitude_of_center", p.get("latitude_of_origin", 0.0))
    )
    lonc = math.radians(
        p.get("longitude_of_center", p.get("central_meridian", 0.0))
    )
    alphac = math.radians(p.get("azimuth", 90.0))
    gammac = math.radians(p.get("rectified_grid_angle", p.get("azimuth", 90.0)))
    kc = p.get("scale_factor", 1.0)
    fe = p.get("false_easting", 0.0)
    fn = p.get("false_northing", 0.0)
    sc = math.sin(phic)
    big_b = math.sqrt(1 + e2 * math.cos(phic) ** 4 / (1 - e2))
    big_a = a * big_b * kc * math.sqrt(1 - e2) / (1 - e2 * sc * sc)
    t0 = math.tan(math.pi / 4 - phic / 2) / (
        (1 - e * sc) / (1 + e * sc)
    ) ** (e / 2)
    big_d = big_b * math.sqrt(1 - e2) / (
        math.cos(phic) * math.sqrt(1 - e2 * sc * sc)
    )
    d2 = max(big_d * big_d, 1.0)
    sign = 1.0 if phic >= 0 else -1.0
    big_f = big_d + math.sqrt(d2 - 1) * sign
    big_h = big_f * t0**big_b
    big_g = (big_f - 1 / big_f) / 2
    gamma0 = math.asin(min(1.0, max(-1.0, math.sin(alphac) / big_d)))
    lon0 = lonc - math.asin(
        min(1.0, max(-1.0, big_g * math.tan(gamma0)))
    ) / big_b
    uc = 0.0
    if variant_b:
        if abs(abs(alphac) - math.pi / 2) < 1e-12:
            uc = big_a * (lonc - lon0)
        else:
            uc = (big_a / big_b) * math.atan2(
                math.sqrt(d2 - 1), math.cos(alphac)
            ) * sign
    return e, e2, big_a, big_b, big_h, gamma0, gammac, lon0, uc, fe, fn, sign


def _hom_forward(crs, lon_deg, lat_deg, variant_b):
    e, e2, A, B, H, g0, gc, lon0, uc, fe, fn, sign = _hom_setup(crs, variant_b)
    lon = np.radians(np.asarray(lon_deg, dtype=np.float64))
    lat = np.radians(
        np.clip(np.asarray(lat_deg, dtype=np.float64), -89.9999, 89.9999)
    )
    s = np.sin(lat)
    t = np.tan(np.pi / 4 - lat / 2) / ((1 - e * s) / (1 + e * s)) ** (e / 2)
    Q = H / t**B
    S = (Q - 1 / Q) / 2
    T = (Q + 1 / Q) / 2
    dlon = B * (lon - lon0)
    V = np.sin(dlon)
    U = (-V * np.cos(g0) + S * np.sin(g0)) / T
    v = A * np.log((1 - U) / (1 + U)) / (2 * B)
    u = A * np.arctan2(S * np.cos(g0) + V * np.sin(g0), np.cos(dlon)) / B
    if variant_b:
        u = u - abs(uc) * sign
    easting = v * math.cos(gc) + u * math.sin(gc) + fe
    northing = u * math.cos(gc) - v * math.sin(gc) + fn
    return easting, northing


def _hom_inverse(crs, x, y, variant_b):
    e, e2, A, B, H, g0, gc, lon0, uc, fe, fn, sign = _hom_setup(crs, variant_b)
    de = np.asarray(x, dtype=np.float64) - fe
    dn = np.asarray(y, dtype=np.float64) - fn
    v = de * math.cos(gc) - dn * math.sin(gc)
    u = dn * math.cos(gc) + de * math.sin(gc)
    if variant_b:
        u = u + abs(uc) * sign
    Q = np.exp(-B * v / A)
    S = (Q - 1 / Q) / 2
    T = (Q + 1 / Q) / 2
    V = np.sin(B * u / A)
    U = (V * np.cos(g0) + S * np.sin(g0)) / T
    t = (H / np.sqrt((1 + U) / (1 - U))) ** (1 / B)
    chi = np.pi / 2 - 2 * np.arctan(t)
    e4 = e2 * e2
    e6 = e4 * e2
    e8 = e6 * e2
    lat = (
        chi
        + np.sin(2 * chi) * (e2 / 2 + 5 * e4 / 24 + e6 / 12 + 13 * e8 / 360)
        + np.sin(4 * chi) * (7 * e4 / 48 + 29 * e6 / 240 + 811 * e8 / 11520)
        + np.sin(6 * chi) * (7 * e6 / 120 + 81 * e8 / 1120)
        + np.sin(8 * chi) * (4279 * e8 / 161280)
    )
    lon = lon0 - np.arctan2(
        S * np.cos(g0) - V * np.sin(g0), np.cos(B * u / A)
    ) / B
    return np.degrees(lon), np.degrees(lat)


def _hom_a_forward(crs, lon_deg, lat_deg):
    return _hom_forward(crs, lon_deg, lat_deg, False)


def _hom_a_inverse(crs, x, y):
    return _hom_inverse(crs, x, y, False)


def _is_swiss_case(crs):
    # azimuth = rectified angle = 90 is the Swiss double-projection special
    # case with its own proven implementation (swisstopo formulae); any
    # other combination takes the general EPSG 9815 path
    p = crs.params
    return (
        abs(p.get("azimuth", 90.0) - 90.0) < 1e-9
        and abs(p.get("rectified_grid_angle", 90.0) - 90.0) < 1e-9
    )


def _hom_b_forward(crs, lon_deg, lat_deg):
    if _is_swiss_case(crs):
        return _somerc_forward(crs, lon_deg, lat_deg)
    return _hom_forward(crs, lon_deg, lat_deg, True)


def _hom_b_inverse(crs, x, y):
    if _is_swiss_case(crs):
        return _somerc_inverse(crs, x, y)
    return _hom_inverse(crs, x, y, True)


_FERRO_OFFSET_DEG = 17 + 40 / 60  # Ferro meridian: 17°40' west of Greenwich


def _krovak_setup(crs):
    """Krovak oblique conformal conic (EPSG method 9819) — S-JTSK, the
    Czech/Slovak national projection. Constants per EPSG Guidance Note 7-2.

    The EPSG 'longitude of origin' is 42°30' east of Ferro = 24°50' east of
    Greenwich; Greenwich-primed WKT1 (GDAL style, EPSG 5514) carries 24.8333
    and needs no shift. A longitude_of_center above 30° (a Ferro-referenced
    42.5 carried verbatim) is shifted by the Ferro offset — no real Krovak
    origin is east of 25°E Greenwich. NOTE: input/output grid coordinates
    are always in the 'Krovak East North' (EPSG 5514) axis convention
    (east = -westing, north = -southing); positive-southing/westing data
    (EPSG 2065 convention) must be negated by the caller."""
    a = crs.semi_major
    e2 = _e2_of(crs)
    e = math.sqrt(e2)
    p = crs.params
    phic = math.radians(
        p.get("latitude_of_center", p.get("latitude_of_origin", 49.5))
    )
    lon0_deg = p.get(
        "longitude_of_center", p.get("central_meridian", 24 + 50 / 60)
    )
    if lon0_deg > 30.0:
        lon0_deg -= _FERRO_OFFSET_DEG
    lon0 = math.radians(lon0_deg)
    alphac = math.radians(p.get("azimuth", 30.28813972222222))
    phip = math.radians(p.get("pseudo_standard_parallel_1", 78.5))
    kp = p.get("scale_factor", 0.9999)
    fe = p.get("false_easting", 0.0)
    fn = p.get("false_northing", 0.0)
    sc = math.sin(phic)
    big_a = a * math.sqrt(1 - e2) / (1 - e2 * sc * sc)
    big_b = math.sqrt(1 + e2 * math.cos(phic) ** 4 / (1 - e2))
    gamma0 = math.asin(sc / big_b)
    t0 = (
        math.tan(math.pi / 4 + gamma0 / 2)
        * ((1 + e * sc) / (1 - e * sc)) ** (e * big_b / 2)
        / math.tan(math.pi / 4 + phic / 2) ** big_b
    )
    n = math.sin(phip)
    r0 = kp * big_a / math.tan(phip)
    return e, big_b, t0, n, r0, alphac, phip, lon0, fe, fn


def _krovak_forward(crs, lon_deg, lat_deg):
    e, B, t0, n, r0, ac, phip, lon0, fe, fn = _krovak_setup(crs)
    lon = np.radians(np.asarray(lon_deg, dtype=np.float64))
    lat = np.radians(
        np.clip(np.asarray(lat_deg, dtype=np.float64), -89.9999, 89.9999)
    )
    s = np.sin(lat)
    U = 2 * (
        np.arctan(
            t0
            * np.tan(lat / 2 + np.pi / 4) ** B
            / ((1 + e * s) / (1 - e * s)) ** (e * B / 2)
        )
        - np.pi / 4
    )
    V = B * (lon0 - lon)
    T = np.arcsin(
        np.clip(
            np.cos(ac) * np.sin(U) + np.sin(ac) * np.cos(U) * np.cos(V),
            -1.0,
            1.0,
        )
    )
    D = np.arcsin(np.clip(np.cos(U) * np.sin(V) / np.cos(T), -1.0, 1.0))
    theta = n * D
    r = (
        r0
        * math.tan(math.pi / 4 + phip / 2) ** n
        / np.tan(T / 2 + np.pi / 4) ** n
    )
    southing = r * np.cos(theta) + fn
    westing = r * np.sin(theta) + fe
    # 'Krovak East North' (EPSG 5514) axes: east = -westing, north = -southing
    return -westing, -southing


def _krovak_inverse(crs, x, y):
    e, B, t0, n, r0, ac, phip, lon0, fe, fn = _krovak_setup(crs)
    westing = -np.asarray(x, dtype=np.float64) - fe
    southing = -np.asarray(y, dtype=np.float64) - fn
    r = np.sqrt(southing**2 + westing**2)
    theta = np.arctan2(westing, southing)
    D = theta / n
    T = 2 * (
        np.arctan(
            (r0 / r) ** (1 / n) * math.tan(math.pi / 4 + phip / 2)
        )
        - np.pi / 4
    )
    U = np.arcsin(
        np.clip(
            np.cos(ac) * np.sin(T) - np.sin(ac) * np.cos(T) * np.cos(D),
            -1.0,
            1.0,
        )
    )
    V = np.arcsin(np.clip(np.cos(T) * np.sin(D) / np.cos(U), -1.0, 1.0))
    lon = lon0 - V / B
    # ellipsoid latitude: fixed-point on the conformal relation
    lat = U.copy()
    for _ in range(8):
        s = np.sin(lat)
        lat = 2 * (
            np.arctan(
                t0 ** (-1 / B)
                * np.tan(U / 2 + np.pi / 4) ** (1 / B)
                * ((1 + e * s) / (1 - e * s)) ** (e / 2)
            )
            - np.pi / 4
        )
    return np.degrees(lon), np.degrees(lat)


_PROJ_IMPLS = {
    "lambert_azimuthal_equal_area": (_laea_forward, _laea_inverse),
    "hotine_oblique_mercator": (_hom_a_forward, _hom_a_inverse),
    "hotine_oblique_mercator_azimuth_center": (_hom_b_forward, _hom_b_inverse),
    "krovak": (_krovak_forward, _krovak_inverse),
    "swiss_oblique_cylindrical": (_somerc_forward, _somerc_inverse),
    "swiss_oblique_mercator": (_somerc_forward, _somerc_inverse),
    "cylindrical_equal_area": (_cea_forward, _cea_inverse),
    "lambert_cylindrical_equal_area": (_cea_forward, _cea_inverse),
    "lambert_cylindrical_equal_area_spherical": (_cea_forward, _cea_inverse),
    "transverse_mercator": (_tm_forward, _tm_inverse),
    "mercator_1sp": (_mercator_forward, _mercator_inverse),
    "mercator_2sp": (_mercator_forward, _mercator_inverse),
    "mercator": (_mercator_forward, _mercator_inverse),
    "mercator_auxiliary_sphere": (_webmerc_forward, _webmerc_inverse),
    "popular_visualisation_pseudo_mercator": (_webmerc_forward, _webmerc_inverse),
    "lambert_conformal_conic_2sp": (_lcc_forward, _lcc_inverse),
    "lambert_conformal_conic_1sp": (_lcc_forward, _lcc_inverse),
    "lambert_conformal_conic": (_lcc_forward, _lcc_inverse),
    "albers_conic_equal_area": (_albers_forward, _albers_inverse),
    "albers": (_albers_forward, _albers_inverse),
    "polar_stereographic": (_polar_stereo_forward, _polar_stereo_inverse),
    "polar_stereographic_variant_a": (_polar_stereo_forward, _polar_stereo_inverse),
    "polar_stereographic_variant_b": (_polar_stereo_forward, _polar_stereo_inverse),
    "oblique_stereographic": (_oblique_stereo_forward, _oblique_stereo_inverse),
    "double_stereographic": (_oblique_stereo_forward, _oblique_stereo_inverse),
    "stereographic_north_pole": (_polar_stereo_forward, _polar_stereo_inverse),
    "stereographic_south_pole": (_polar_stereo_forward, _polar_stereo_inverse),
}


# -- datum shifts (7-parameter Helmert via geocentric coordinates) ----------


def _geodetic_to_geocentric(a, e2, lon_deg, lat_deg):
    lon = np.radians(lon_deg)
    lat = np.radians(lat_deg)
    sin_lat = np.sin(lat)
    nu = a / np.sqrt(1 - e2 * sin_lat**2)
    x = nu * np.cos(lat) * np.cos(lon)
    y = nu * np.cos(lat) * np.sin(lon)
    z = nu * (1 - e2) * sin_lat
    return x, y, z


def _geocentric_to_geodetic(a, e2, x, y, z):
    lon = np.arctan2(y, x)
    p = np.sqrt(x**2 + y**2)
    # iterate latitude (converges to sub-mm in a few rounds)
    lat = np.arctan2(z, p * (1 - e2))
    for _ in range(6):
        sin_lat = np.sin(lat)
        nu = a / np.sqrt(1 - e2 * sin_lat**2)
        lat = np.arctan2(z + e2 * nu * sin_lat, p)
    return np.degrees(lon), np.degrees(lat)


def _helmert(params, x, y, z, inverse=False):
    """Position-vector 7-parameter transformation (EPSG 9606): rotations in
    arc-seconds, scale in ppm. The method is sign-reversible: the inverse
    applies the negated parameters (error ~ rotation², negligible at
    arc-second scale)."""
    if inverse:
        params = tuple(-v for v in params)
    dx, dy, dz, rx, ry, rz, s_ppm = params
    arc = math.pi / (180.0 * 3600.0)
    rx, ry, rz = rx * arc, ry * arc, rz * arc
    m = 1.0 + s_ppm * 1e-6
    nx = dx + m * (x - rz * y + ry * z)
    ny = dy + m * (rz * x + y - rx * z)
    nz = dz + m * (-ry * x + rx * y + z)
    return nx, ny, nz


_NULL_SHIFT = (0.0,) * 7


def _e2_of(crs):
    """Ellipsoid eccentricity²; inv_flattening == 0 encodes a sphere."""
    if not crs.inv_flattening:
        return 0.0
    f = 1.0 / crs.inv_flattening
    return f * (2 - f)


_WGS84_A = 6378137.0
_WGS84_E2 = (1.0 / 298.257223563) * (2 - 1.0 / 298.257223563)


def _datum_shift(src, dst, lon, lat):
    """Geographic coordinates on src datum -> dst datum via WGS84, using the
    CRSes' TOWGS84 parameters. No-op when the declared shifts are equal
    (same datum under any name spelling, or both WGS84-equivalent).

    NTv2 grids registered via kart_tpu.gridshift (or $KART_NTV2_GRID_DIR)
    take precedence over Helmert parameters for their datum — PROJ's own
    priority — and compose with the other side's Helmert (grid src ->
    WGS84 -> Helmert dst and vice versa). A datum that appears under more
    than one spelling should be registered under every alias, or the
    same-datum no-op can't recognise it."""
    if src.datum_name is not None and src.datum_name == dst.datum_name:
        return lon, lat
    from kart_tpu import gridshift

    src_grid = gridshift.grid_for_datum(src.datum_name)
    dst_grid = gridshift.grid_for_datum(dst.datum_name)
    src_tw = src.towgs84 if src.towgs84 != _NULL_SHIFT else None
    dst_tw = dst.towgs84 if dst.towgs84 != _NULL_SHIFT else None

    if src_grid is None and dst_grid is None:
        if src_tw == dst_tw:  # includes None == None
            return lon, lat
        x, y, z = _geodetic_to_geocentric(src.semi_major, _e2_of(src), lon, lat)
        if src_tw is not None:
            x, y, z = _helmert(src_tw, x, y, z)
        if dst_tw is not None:
            x, y, z = _helmert(dst_tw, x, y, z, inverse=True)
        return _geocentric_to_geodetic(dst.semi_major, _e2_of(dst), x, y, z)

    if src_grid is not None and src_grid is dst_grid:
        return lon, lat  # same datum registered under both spellings

    # to WGS84
    if src_grid is not None:
        lon, lat = src_grid.shift(lon, lat)
    elif src_tw is not None:
        x, y, z = _geodetic_to_geocentric(src.semi_major, _e2_of(src), lon, lat)
        x, y, z = _helmert(src_tw, x, y, z)
        lon, lat = _geocentric_to_geodetic(_WGS84_A, _WGS84_E2, x, y, z)
    # from WGS84
    if dst_grid is not None:
        lon, lat = dst_grid.shift(lon, lat, inverse=True)
    elif dst_tw is not None:
        x, y, z = _geodetic_to_geocentric(_WGS84_A, _WGS84_E2, lon, lat)
        x, y, z = _helmert(dst_tw, x, y, z, inverse=True)
        lon, lat = _geocentric_to_geodetic(dst.semi_major, _e2_of(dst), x, y, z)
    return lon, lat


class Transform:
    """Vectorized coordinate transform between two CRS. Datum shifts are
    applied when either side declares TOWGS84 (7-parameter Helmert, EPSG
    9606); datums without one are treated as WGS84-equivalent (within ~1m
    for modern datums — the envelope index pads by a buffer anyway)."""

    def __init__(self, src, dst):
        self.src = make_crs(src) if not isinstance(src, CRS) else src
        self.dst = make_crs(dst) if not isinstance(dst, CRS) else dst
        self.is_identity = normalise_wkt(self.src.wkt) == normalise_wkt(self.dst.wkt)

    def _impl(self, crs):
        if crs.is_geographic:
            return None
        name = (crs.projection or "").lower()
        impl = _PROJ_IMPLS.get(name)
        if impl is None:
            raise CrsError(
                f"Projection {crs.projection!r} is not supported by the built-in "
                f"transform engine"
            )
        return impl

    def transform(self, xs, ys):
        """(xs, ys) arrays in src CRS -> (xs, ys) in dst CRS."""
        xs = np.asarray(xs, dtype=np.float64)
        ys = np.asarray(ys, dtype=np.float64)
        if self.is_identity:
            return xs, ys
        src_impl = self._impl(self.src)
        dst_impl = self._impl(self.dst)
        if src_impl is not None:
            xs, ys = src_impl[1](self.src, xs, ys)  # -> lon/lat
        xs, ys = _datum_shift(self.src, self.dst, xs, ys)
        if dst_impl is not None:
            xs, ys = dst_impl[0](self.dst, xs, ys)  # lon/lat -> projected
        return xs, ys

    def transform_envelope(self, env, densify=5):
        """(min-x, max-x, min-y, max-y) -> transformed envelope, densifying
        each edge so curvature is captured (reference:
        spatial_filter/index.py transforms envelopes the same way)."""
        x0, x1, y0, y1 = env
        t = np.linspace(0.0, 1.0, densify)
        xs = np.concatenate(
            [x0 + (x1 - x0) * t, np.full(densify, x1), x1 + (x0 - x1) * t, np.full(densify, x0)]
        )
        ys = np.concatenate(
            [np.full(densify, y0), y0 + (y1 - y0) * t, np.full(densify, y1), y1 + (y0 - y1) * t]
        )
        tx, ty = self.transform(xs, ys)
        return (float(tx.min()), float(tx.max()), float(ty.min()), float(ty.max()))
