"""ctypes binding for the native spatial-filter core (native/spatial_filter.cpp).

The library is optional: :func:`load` returns None when it isn't built and
every caller falls back to the numpy implementation with identical
semantics (the same CPU-reference-path discipline the TPU kernels follow).
Build with ``make -C native`` — :func:`ensure_built` does it on demand when
a toolchain is available.
"""

import ctypes
import logging
import os
import subprocess

import numpy as np

L = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_NAME = "libkart_sf.so"
_ABI_VERSION = 1

_lib = None
_load_attempted = False


def _lib_path():
    override = os.environ.get("KART_TPU_NATIVE_LIB")
    if override:
        return override
    return os.path.abspath(os.path.join(_NATIVE_DIR, _LIB_NAME))


def load():
    """-> configured ctypes.CDLL, or None when unavailable."""
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    path = _lib_path()
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.sf_abi_version.restype = ctypes.c_int
        if lib.sf_abi_version() != _ABI_VERSION:
            L.warning("native lib %s has wrong ABI version; ignoring", path)
            return None
        lib.sf_decode_envelopes.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
        ]
        lib.sf_bbox_intersects.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        lib.sf_bbox_intersects.restype = ctypes.c_int64
        lib.sf_filter_packed.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        lib.sf_filter_packed.restype = ctypes.c_int64
        _lib = lib
    except (OSError, AttributeError) as e:
        # AttributeError: a stale/foreign .so without the expected symbols
        L.warning("could not load native lib %s: %s", path, e)
    return _lib


def ensure_built():
    """Build the library if a compiler is available; -> loaded lib or None."""
    global _load_attempted
    if load() is not None:
        return _lib
    makefile_dir = os.path.abspath(_NATIVE_DIR)
    if not os.path.exists(os.path.join(makefile_dir, "Makefile")):
        return None
    try:
        subprocess.run(
            ["make", "-C", makefile_dir],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        L.info("native build unavailable: %s", e)
        return None
    _load_attempted = False
    return load()


# -- high-level API (native with numpy fallback) ----------------------------


def decode_envelopes(packed):
    """(N, 10) uint8 packed envelopes -> (N, 4) float64 wsen."""
    packed = np.ascontiguousarray(packed, dtype=np.uint8)
    n = packed.shape[0]
    lib = load()
    if lib is not None:
        out = np.empty((n, 4), dtype=np.float64)
        lib.sf_decode_envelopes(
            packed.ctypes.data, n, out.ctypes.data
        )
        return out
    from kart_tpu.ops.envelope_codec import EnvelopeCodec

    return EnvelopeCodec().decode_batch(packed)


def filter_packed(packed, query_wsen):
    """(N, 10) uint8 packed envelopes + (w,s,e,n) query -> bool (N,).
    The server-side partial-clone hot path."""
    packed = np.ascontiguousarray(packed, dtype=np.uint8)
    n = packed.shape[0]
    query = np.asarray(query_wsen, dtype=np.float64)
    lib = load()
    if lib is not None:
        out = np.empty(n, dtype=np.uint8)
        lib.sf_filter_packed(
            packed.ctypes.data, n, query.ctypes.data, out.ctypes.data
        )
        return out.astype(bool)
    from kart_tpu.ops.bbox import bbox_intersects_np

    return bbox_intersects_np(decode_envelopes(packed), query)


def bbox_intersects(envelopes, query_wsen):
    """(N, 4) float64 wsen + query -> bool (N,), native when available."""
    envelopes = np.ascontiguousarray(envelopes, dtype=np.float64)
    query = np.asarray(query_wsen, dtype=np.float64)
    lib = load()
    if lib is not None:
        out = np.empty(envelopes.shape[0], dtype=np.uint8)
        lib.sf_bbox_intersects(
            envelopes.ctypes.data, envelopes.shape[0], query.ctypes.data, out.ctypes.data
        )
        return out.astype(bool)
    from kart_tpu.ops.bbox import bbox_intersects_np

    return bbox_intersects_np(envelopes, query)
