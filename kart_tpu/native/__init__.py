"""ctypes binding for the native spatial-filter core (native/spatial_filter.cpp).

The library is optional: :func:`load` returns None when it isn't built and
every caller falls back to the numpy implementation with identical
semantics (the same CPU-reference-path discipline the TPU kernels follow).
Build with ``make -C native`` — :func:`ensure_built` does it on demand when
a toolchain is available.
"""

import ctypes
import logging
import os
import subprocess

import numpy as np

L = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_NAME = "libkart_sf.so"
_ABI_VERSION = 2  # v2: sf_bbox_blocks_f32

_lib = None
_load_attempted = False


def _lib_path():
    override = os.environ.get("KART_TPU_NATIVE_LIB")
    if override:
        return override
    return os.path.abspath(os.path.join(_NATIVE_DIR, _LIB_NAME))


_autobuild_attempted = False


def _run_make():
    """Compile the native libraries, serialized across processes with a
    lock file (the Makefile links via temp+rename, so readers never see a
    half-written .so). Returns True when make reported success."""
    makefile_dir = os.path.abspath(_NATIVE_DIR)
    if not os.path.exists(os.path.join(makefile_dir, "Makefile")):
        return False
    lock_path = os.path.join(makefile_dir, ".build-lock")
    try:
        import fcntl

        with open(lock_path, "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            result = subprocess.run(
                ["make", "-C", makefile_dir, "-k"],
                capture_output=True,
                timeout=120,
            )
        if result.returncode != 0:
            L.info(
                "native build failed (rc=%d): %s",
                result.returncode,
                result.stderr.decode(errors="replace")[-2000:],
            )
            return False
        return True
    except Exception as e:  # no toolchain / no fcntl / timeout: stay Python
        L.info("native build unavailable: %s", e)
        return False


def _autobuild():
    """One attempt per process to compile the native libraries when a lib
    file is missing (fresh checkouts): a few seconds of g++ buys the fast
    paths for the rest of the process and every later one.
    KART_NO_NATIVE_BUILD=1 disables."""
    global _autobuild_attempted
    if _autobuild_attempted or os.environ.get("KART_NO_NATIVE_BUILD") == "1":
        return
    _autobuild_attempted = True
    _run_make()


def _load_rebuilt(path):
    """CDLL the freshly-rebuilt library at ``path``. dlopen caches handles
    by *pathname* (glibc compares l_name), so re-CDLLing the original path
    after a temp+rename rebuild returns the stale in-process mapping — the
    new inode must be loaded through a one-off pathname. The copy is left
    for the OS tmp reaper: it cannot be unlinked while mapped."""
    import shutil
    import tempfile

    d = tempfile.mkdtemp(prefix="kart-native-")
    fresh = os.path.join(d, os.path.basename(path))
    shutil.copy2(path, fresh)
    return ctypes.CDLL(fresh)


def load():
    """-> configured ctypes.CDLL, or None when unavailable."""
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    path = _lib_path()
    if not os.path.exists(path) and not os.environ.get("KART_TPU_NATIVE_LIB"):
        _autobuild()
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.sf_abi_version.restype = ctypes.c_int
        if lib.sf_abi_version() != _ABI_VERSION:
            # stale build from an older checkout: rebuild, then load the
            # new inode through a fresh pathname (see _load_rebuilt)
            L.warning("native lib %s has stale ABI; rebuilding", path)
            if os.environ.get("KART_TPU_NATIVE_LIB") or not _run_make():
                return None
            lib = _load_rebuilt(path)
            lib.sf_abi_version.restype = ctypes.c_int
            if lib.sf_abi_version() != _ABI_VERSION:
                return None
        lib.sf_decode_envelopes.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
        ]
        lib.sf_bbox_intersects.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        lib.sf_bbox_intersects.restype = ctypes.c_int64
        lib.sf_filter_packed.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        lib.sf_filter_packed.restype = ctypes.c_int64
        if hasattr(lib, "sf_bbox_intersects_f32"):
            lib.sf_bbox_intersects_f32.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_void_p,
            ]
            lib.sf_bbox_intersects_f32.restype = ctypes.c_int64
        if hasattr(lib, "sf_bbox_blocks_f32"):
            lib.sf_bbox_blocks_f32.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_void_p,
            ]
            lib.sf_bbox_blocks_f32.restype = ctypes.c_int64
        _lib = lib
    except (OSError, AttributeError) as e:
        # AttributeError: a stale/foreign .so without the expected symbols
        L.warning("could not load native lib %s: %s", path, e)
    return _lib


def ensure_built():
    """Build the libraries if a compiler is available; -> loaded sf lib or
    None. Each library is independent: a build failure of one (e.g. no zlib
    headers for the IO core) never blocks loading the other."""
    global _load_attempted, _io_load_attempted, _autobuild_attempted
    # suppress load()'s own autobuild below: one make run per ensure_built
    _autobuild_attempted = True
    if load() is not None and load_io() is not None:
        return _lib
    _run_make()
    _load_attempted = False
    _io_load_attempted = False
    load_io()
    return load()


# -- object-store IO core (native/kart_io.cpp) ------------------------------

_IO_LIB_NAME = "libkart_io.so"
_IO_ABI_VERSION = 7  # v7: io_leaf_payloads leaf-tree kernel

_io_lib = None
_io_load_attempted = False


def load_io():
    """-> configured ctypes.CDLL for the IO core, or None."""
    global _io_lib, _io_load_attempted
    if _io_lib is not None or _io_load_attempted:
        return _io_lib
    _io_load_attempted = True
    override = os.environ.get("KART_TPU_NATIVE_IO_LIB")
    path = override or os.path.abspath(
        os.path.join(_NATIVE_DIR, _IO_LIB_NAME)
    )
    if not os.path.exists(path) and not override:
        _autobuild()
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.io_abi_version.restype = ctypes.c_int
        if lib.io_abi_version() != _IO_ABI_VERSION:
            # a stale build from an older checkout: rebuild, then load the
            # new inode through a fresh pathname (see _load_rebuilt —
            # re-CDLLing the same path returns the stale cached mapping)
            L.warning("native IO lib %s has stale ABI; rebuilding", path)
            if override or not _run_make():
                return None
            lib = _load_rebuilt(path)
            lib.io_abi_version.restype = ctypes.c_int
            if lib.io_abi_version() != _IO_ABI_VERSION:
                return None
        lib.io_pack_ptrs.restype = ctypes.c_int64
        lib.io_pack_ptrs.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p,
        ]
        lib.io_pack_records.restype = ctypes.c_int64
        lib.io_pack_records.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.io_classify_sorted.restype = ctypes.c_int64
        lib.io_classify_sorted.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.io_tree_diff.restype = ctypes.c_int64
        lib.io_tree_diff.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.io_inflate_batch.restype = ctypes.c_int64
        lib.io_inflate_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.io_gpkg_open.restype = ctypes.c_void_p
        lib.io_gpkg_open.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
        ]
        lib.io_gpkg_next.restype = ctypes.c_int64
        lib.io_gpkg_next.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.io_gpkg_close.restype = None
        lib.io_gpkg_close.argtypes = [ctypes.c_void_p]
        lib.io_leaf_payloads.restype = ctypes.c_int64
        lib.io_leaf_payloads.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        _io_lib = lib
    except (OSError, AttributeError) as e:
        L.warning("could not load native IO lib %s: %s", path, e)
    return _io_lib


def classify_sorted(old_keys, old_oids_u8, new_keys, new_oids_u8):
    """Native merge-join diff classify over key-sorted columns; -> (old_class
    int8 (n_old,), new_class (n_new,), counts dict) or None when the IO lib
    isn't available. Bit-identical to the numpy reference twin (tested)."""
    lib = load_io()
    if lib is None:
        return None
    n_old, n_new = len(old_keys), len(new_keys)
    old_keys = np.ascontiguousarray(old_keys, dtype=np.int64)
    new_keys = np.ascontiguousarray(new_keys, dtype=np.int64)
    old_oids_u8 = np.ascontiguousarray(old_oids_u8, dtype=np.uint8)
    new_oids_u8 = np.ascontiguousarray(new_oids_u8, dtype=np.uint8)
    old_class = np.zeros(n_old, dtype=np.int8)
    new_class = np.zeros(n_new, dtype=np.int8)
    counts = np.zeros(3, dtype=np.int64)
    rc = lib.io_classify_sorted(
        old_keys.ctypes.data, old_oids_u8.ctypes.data, n_old,
        new_keys.ctypes.data, new_oids_u8.ctypes.data, n_new,
        old_class.ctypes.data, new_class.ctypes.data, counts.ctypes.data,
    )
    if rc != 0:
        return None
    return (
        old_class,
        new_class,
        {
            "inserts": int(counts[0]),
            "updates": int(counts[1]),
            "deletes": int(counts[2]),
        },
    )


def tree_diff_raw(a_content, b_content):
    """Raw git tree payloads -> list of differing entries
    ``(name, oid_a_hex|None, oid_b_hex|None, a_is_tree, b_is_tree)``, or
    None when the lib is unavailable / input malformed (callers fall back
    to the parse-both-trees Python path with identical results — tested).
    Only the differing entries are materialised: at 1%-edit scale ~99% of
    a touched tree's entries are equal, and the Python path paid per-entry
    object + hex costs for all of them."""
    lib = load_io()
    if lib is None:
        return None
    # worst case: every entry one-sided — each output record (43 + name)
    # bytes against (27 + name) input bytes, so 2x input covers it
    cap = 2 * (len(a_content) + len(b_content)) + 64
    out = np.empty(cap, dtype=np.uint8)
    total = lib.io_tree_diff(
        a_content, len(a_content), b_content, len(b_content),
        out.ctypes.data, cap,
    )
    if total < 0:
        return None
    result = []
    buf = out[:total].tobytes()
    i = 0
    while i < total:
        flags = buf[i]
        name_len = buf[i + 1] | (buf[i + 2] << 8)
        j = i + 3
        name = buf[j : j + name_len].decode("utf8")
        j += name_len
        oid_a = buf[j : j + 20].hex() if flags & 1 else None
        oid_b = buf[j + 20 : j + 40].hex() if flags & 2 else None
        result.append((name, oid_a, oid_b, bool(flags & 4), bool(flags & 8)))
        i = j + 40
    return result


def pack_records_batch(obj_type, type_code, contents, level=1):
    """Batch hash + deflate + pack-record framing: -> (oids (n,20) uint8,
    crcs (n,) uint32, records np.uint8 buffer, offsets (n+1) int64) —
    record i is ``records[offsets[i]:offsets[i+1]]``, complete with varint
    head, ready to append to the pack stream. None when unavailable."""
    lib = load_io()
    if lib is None or not contents:
        return None
    n = len(contents)
    try:
        joined = b"".join(contents)  # one memcpy pass beats a per-element
        # ctypes pointer-array conversion (~1us each)
    except TypeError:
        return None
    lens = np.fromiter((len(c) for c in contents), dtype=np.int64, count=n)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    payload_total = len(joined)

    oids = np.empty((n, 20), dtype=np.uint8)
    crcs = np.empty(n, dtype=np.uint32)
    # zlib worst case + stored overhead + 10-byte heads, all inside 80*n
    cap = payload_total + payload_total // 512 + 80 * n + 1024
    out = np.empty(cap, dtype=np.uint8)
    out_offsets = np.empty(n + 1, dtype=np.int64)
    total = lib.io_pack_records(
        joined, offsets.ctypes.data, n, obj_type.encode(), int(type_code),
        int(level), _store_max(),
        oids.ctypes.data, crcs.ctypes.data, out.ctypes.data, cap,
        out_offsets.ctypes.data,
    )
    if total < 0:
        L.warning("native pack records failed (%d); falling back", total)
        return None
    return oids, crcs, out[:total], out_offsets


def pack_records_base(obj_type, type_code, base_u8, offsets, level=1):
    """:func:`pack_records_batch` over payloads that are ALREADY one
    contiguous buffer + offsets (the native GPKG encoder's output, or a
    tree-payload batch) — no join, no bytes objects, zero per-payload
    Python. -> same (oids, crcs, records, out_offsets) tuple, or None."""
    lib = load_io()
    if lib is None:
        return None
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    n = len(offsets) - 1
    if n <= 0:
        return None
    base_u8 = np.ascontiguousarray(base_u8, dtype=np.uint8)
    payload_total = int(offsets[n])
    oids = np.empty((n, 20), dtype=np.uint8)
    crcs = np.empty(n, dtype=np.uint32)
    cap = payload_total + payload_total // 512 + 80 * n + 1024
    out = np.empty(cap, dtype=np.uint8)
    out_offsets = np.empty(n + 1, dtype=np.int64)
    total = lib.io_pack_records(
        base_u8.ctypes.data_as(ctypes.c_char_p), offsets.ctypes.data, n,
        obj_type.encode(), int(type_code), int(level), _store_max(),
        oids.ctypes.data, crcs.ctypes.data, out.ctypes.data, cap,
        out_offsets.ctypes.data,
    )
    if total < 0:
        L.warning("native pack records (base) failed (%d); falling back", total)
        return None
    return oids, crcs, out[:total], out_offsets


def leaf_payloads(pks, oids_u8, branches, pk_limit):
    """Native leaf-tree payload build (io_leaf_payloads): strictly ascending
    non-negative int64 ``pks`` below ``pk_limit`` (``branches**(levels+1)``
    — above it leaf ids would need the encoder's max_trees wrap) + their
    (n, 20) blob oids -> (buf uint8, offsets int64 (n_leaves+1,), leaf_ids
    int64) where leaf k's git tree payload is
    ``buf[offsets[k]:offsets[k+1]]`` — bit-identical to the numpy plan
    path (property-tested). None when the lib is unavailable or the pks
    don't qualify (caller falls back to the Python build)."""
    lib = load_io()
    if lib is None:
        return None
    pks = np.ascontiguousarray(pks, dtype=np.int64)
    n = len(pks)
    if n == 0:
        return None
    oids_u8 = np.ascontiguousarray(oids_u8, dtype=np.uint8)
    # entry <= 7 + 16-char name + NUL + 20-byte oid = 44 bytes
    cap = n * 44 + 64
    out = np.empty(cap, dtype=np.uint8)
    offsets = np.empty(n + 1, dtype=np.int64)
    leaf_ids = np.empty(n, dtype=np.int64)
    n_leaves = ctypes.c_int64(0)
    total = lib.io_leaf_payloads(
        pks.ctypes.data, oids_u8.ctypes.data, n, int(branches),
        int(pk_limit), out.ctypes.data, cap, offsets.ctypes.data,
        leaf_ids.ctypes.data, ctypes.byref(n_leaves),
    )
    if total < 0:
        return None
    k = n_leaves.value
    return out[:total], offsets[: k + 1], leaf_ids[:k]


class GpkgReaderFallback(Exception):
    """The native GPKG encoder met a row it cannot produce bit-identically
    (geometry needing the full re-encode path, unexpected storage class):
    the caller must re-stream through the Python encoder."""


class GpkgNativeReader:
    """Native fused read+encode over a GPKG table (io_gpkg_*): each
    :meth:`next_batch` steps the prepared SELECT and returns
    ``(pks int64 (n,), buf uint8, offsets int64 (n+1,))`` — blob i is
    ``buf[offsets[i]:offsets[i+1]]``, bit-identical to the Python
    ``batch_row_encoder`` blobs. The ctypes call releases the GIL for the
    whole batch. Raises :class:`GpkgReaderFallback` on rows the native
    encoder can't handle. Use :func:`open_gpkg_reader` (returns None when
    the native lib or sqlite3 runtime is unavailable)."""

    def __init__(self, handle, lib, est_row_bytes):
        self._h = handle
        self._lib = lib
        # grown on demand (-5): start from the caller's estimate
        self._row_bytes = max(64, int(est_row_bytes))

    def next_batch(self, max_rows):
        """-> (pks, buf, offsets) or None at EOF."""
        if self._h is None:
            return None
        lib = self._lib
        while True:
            pks = np.empty(max_rows, dtype=np.int64)
            cap = max_rows * self._row_bytes + 4096
            buf = np.empty(cap, dtype=np.uint8)
            offsets = np.empty(max_rows + 1, dtype=np.int64)
            n = lib.io_gpkg_next(
                self._h, max_rows, pks.ctypes.data, buf.ctypes.data, cap,
                offsets.ctypes.data,
            )
            if n == -5:  # a single row outgrew the buffer: double and retry
                self._row_bytes *= 2
                continue
            if n == -6:
                self.close()
                raise GpkgReaderFallback()
            if n < 0:
                self.close()
                raise OSError(f"native GPKG reader failed (rc={n})")
            if n == 0:
                self.close()
                return None
            return pks[:n], buf, offsets[: n + 1]

    def close(self):
        if self._h is not None:
            self._lib.io_gpkg_close(self._h)
            self._h = None

    def __del__(self):
        self.close()


def open_gpkg_reader(db_path, sql, val_cols, kinds, pk_col, prefix,
                     geom_ext_code, est_row_bytes=256):
    """-> :class:`GpkgNativeReader` or None when the native IO lib (or the
    sqlite3 runtime it dlopens) is unavailable. ``val_cols``/``kinds``: per
    blob value (legend non-pk order) the SELECT column index and encode
    kind (0 plain / 1 geometry / 2 bool / 3 float / 4 timestamp);
    ``prefix``: the constant msgpack head every feature blob starts with."""
    lib = load_io()
    if lib is None:
        return None
    val_cols = np.ascontiguousarray(val_cols, dtype=np.int32)
    kinds_u8 = np.ascontiguousarray(kinds, dtype=np.uint8)
    n_vals = len(kinds_u8)
    prefix = bytes(prefix)
    handle = lib.io_gpkg_open(
        os.fsencode(db_path), sql.encode(), n_vals,
        val_cols.ctypes.data, kinds_u8.ctypes.data, int(pk_col),
        prefix, len(prefix), int(geom_ext_code),
    )
    if not handle:
        return None
    return GpkgNativeReader(handle, lib, est_row_bytes)


def inflate_pack_batch(pack_buf, offsets, max_total=None):
    """Bulk pack reads: mmap/bytes of a whole packfile + record offsets ->
    (n_consumed, types uint8 (n_consumed,), payload uint8 array,
    payload_offsets int64 (n_consumed+1,)), or None when the lib is
    unavailable / the pack is malformed. Non-delta records inflate with one
    reused z_stream; delta records come back as type 0 with an empty slot
    (the caller's per-object path resolves the chain).

    max_total bounds the payload buffer: only the longest record PREFIX
    whose inflated payload fits (always at least one record) is consumed —
    callers loop over the remainder, so a batch of large blobs can't
    materialise unbounded memory in one native call."""
    lib = load_io()
    if lib is None:
        return None
    buf = np.frombuffer(pack_buf, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    n = len(offsets)
    types = np.zeros(n, dtype=np.uint8)
    cum = np.zeros(n + 1, dtype=np.int64)
    total = lib.io_inflate_batch(
        buf.ctypes.data, len(buf), offsets.ctypes.data, n,
        None, 0, cum.ctypes.data, types.ctypes.data,
    )
    if total < 0:
        return None
    take = n
    if max_total is not None and total > max_total:
        take = max(1, int(np.searchsorted(cum, max_total, side="right")) - 1)
        total = int(cum[take])
        offsets = offsets[:take]
        types = types[:take]
    out_offsets = np.zeros(take + 1, dtype=np.int64)
    if total == 0 and not types.any():
        # every record is a delta (heavily-repacked git packs): nothing to
        # inflate, skip the second native pass entirely
        return take, types, np.empty(0, dtype=np.uint8), out_offsets
    out = np.empty(int(total), dtype=np.uint8)
    rc = lib.io_inflate_batch(
        buf.ctypes.data, len(buf), offsets.ctypes.data, take,
        out.ctypes.data, int(total), out_offsets.ctypes.data,
        types.ctypes.data,
    )
    if rc < 0:
        return None
    return take, types, out, out_offsets


def _store_max():
    """Payloads at or below this many bytes are written as STORED zlib
    streams (see kart_io.cpp io_pack_ptrs): feature blobs are ~100-150B of
    msgpack that level-1 deflate barely shrinks but costs ~9us each on this
    zlib. 0 disables (always deflate)."""
    try:
        return int(os.environ.get("KART_PACK_STORE_MAX", 256))
    except ValueError:
        return 256


def pack_objects_batch(obj_type, contents, level=1):
    """Batch hash+deflate WITHOUT record framing: obj_type str, contents
    list[bytes] -> (oids (n,20) uint8, deflated list[bytes]), or None when
    the library isn't available.

    Production pack writing goes through :func:`pack_records_batch` (framed
    records, one write per batch); this unframed variant remains as the
    reference twin the native tests cross-check stream-level behavior
    against, and for callers that need streams outside pack framing.

    Zero-copy: the C side reads the bytes objects' own buffers through a
    pointer array and composes the git object headers itself."""
    lib = load_io()
    if lib is None or not contents:
        return None
    n = len(contents)
    try:
        ptrs = (ctypes.c_char_p * n)(*contents)
    except TypeError:
        # a non-bytes sneaked in: let the Python path raise the right error
        return None
    lens = np.fromiter((len(c) for c in contents), dtype=np.int64, count=n)
    payload_total = int(lens.sum())

    oids = np.empty((n, 20), dtype=np.uint8)
    # zlib worst case ~ src + src/1000 + 12 per stream; stored streams add
    # 11 + 5 per 64KB block, covered by the same 64*n headroom
    cap = payload_total + payload_total // 512 + 64 * n + 1024
    out = np.empty(cap, dtype=np.uint8)
    out_offsets = np.empty(n + 1, dtype=np.int64)
    total = lib.io_pack_ptrs(
        ptrs, lens.ctypes.data, n, obj_type.encode(), int(level),
        _store_max(),
        oids.ctypes.data, out.ctypes.data, cap, out_offsets.ctypes.data,
    )
    if total < 0:
        L.warning("native pack batch failed (%d); falling back", total)
        return None
    streams = [
        out[out_offsets[i] : out_offsets[i + 1]].tobytes() for i in range(n)
    ]
    return oids, streams


# -- high-level API (native with numpy fallback) ----------------------------


def decode_envelopes(packed):
    """(N, 10) uint8 packed envelopes -> (N, 4) float64 wsen."""
    packed = np.ascontiguousarray(packed, dtype=np.uint8)
    n = packed.shape[0]
    lib = load()
    if lib is not None:
        out = np.empty((n, 4), dtype=np.float64)
        lib.sf_decode_envelopes(
            packed.ctypes.data, n, out.ctypes.data
        )
        return out
    from kart_tpu.ops.envelope_codec import EnvelopeCodec

    return EnvelopeCodec().decode_batch(packed)


def filter_packed(packed, query_wsen):
    """(N, 10) uint8 packed envelopes + (w,s,e,n) query -> bool (N,).
    The server-side partial-clone hot path."""
    packed = np.ascontiguousarray(packed, dtype=np.uint8)
    n = packed.shape[0]
    query = np.asarray(query_wsen, dtype=np.float64)
    lib = load()
    if lib is not None:
        out = np.empty(n, dtype=np.uint8)
        lib.sf_filter_packed(
            packed.ctypes.data, n, query.ctypes.data, out.ctypes.data
        )
        return out.astype(bool)
    from kart_tpu.ops.bbox import bbox_intersects_np

    return bbox_intersects_np(decode_envelopes(packed), query)


def bbox_intersects(envelopes, query_wsen):
    """(N, 4) float64 wsen + query -> bool (N,), native when available."""
    envelopes = np.ascontiguousarray(envelopes, dtype=np.float64)
    query = np.asarray(query_wsen, dtype=np.float64)
    lib = load()
    if lib is not None:
        out = np.empty(envelopes.shape[0], dtype=np.uint8)
        lib.sf_bbox_intersects(
            envelopes.ctypes.data, envelopes.shape[0], query.ctypes.data, out.ctypes.data
        )
        return out.astype(bool)
    from kart_tpu.ops.bbox import bbox_intersects_np

    return bbox_intersects_np(envelopes, query)


def bbox_intersects_f32(envelopes_f32, query_wsen):
    """(N, 4) float32 wsen (e.g. the sidecar envelope mmap, zero copies) +
    query -> bool (N,). Falls back to the f64 path when the native lib is
    missing or predates the f32 entry point."""
    query = np.asarray(query_wsen, dtype=np.float64)
    lib = load()
    if lib is not None and hasattr(lib, "sf_bbox_intersects_f32"):
        env = np.ascontiguousarray(envelopes_f32, dtype=np.float32)
        out = np.empty(env.shape[0], dtype=np.uint8)
        lib.sf_bbox_intersects_f32(
            env.ctypes.data, env.shape[0], query.ctypes.data, out.ctypes.data
        )
        return out.view(bool)  # 0/1 bytes: reinterpret, no copy
    return bbox_intersects(np.asarray(envelopes_f32, dtype=np.float64), query)


def bbox_blocks_f32(envelopes_f32, agg_f32, flags_u8, block_rows, query_wsen):
    """Block-pruned f32 scan: (N, 4) float32 envelopes + their (nb, 4)
    float32 block aggregates / nb flag bytes (sidecar block-aggregate
    records) + query -> bool (N,). All-out blocks are classified from the
    aggregate alone — their envelope pages are never read. Bit-identical to
    :func:`bbox_intersects_f32` over the same rows (fuzz-tested); falls back
    to the numpy block scan, then to the unpruned scan."""
    query = np.asarray(query_wsen, dtype=np.float64)
    lib = load()
    if lib is not None and hasattr(lib, "sf_bbox_blocks_f32"):
        env = np.ascontiguousarray(envelopes_f32, dtype=np.float32)
        agg = np.ascontiguousarray(agg_f32, dtype=np.float32)
        flags = np.ascontiguousarray(flags_u8, dtype=np.uint8)
        n = env.shape[0]
        out = np.empty(n, dtype=np.uint8)
        rc = lib.sf_bbox_blocks_f32(
            env.ctypes.data, n, agg.ctypes.data, flags.ctypes.data,
            agg.shape[0], int(block_rows), query.ctypes.data, out.ctypes.data,
        )
        if rc >= 0:
            return out.view(bool)
    from kart_tpu.ops.bbox import bbox_blocks_np

    return bbox_blocks_np(envelopes_f32, agg_f32, flags_u8, block_rows, query)
