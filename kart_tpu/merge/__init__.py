"""3-way merge engine (reference: kart/merge.py + kart/merge_util.py).

The reference delegates tree merging to libgit2 (`repo.merge_trees`,
`kart/merge.py:99-100`) and inherits per-feature conflicts from the
one-feature-one-blob layout. Here the same semantics are computed directly:
feature sets go through the vectorized 3-way kernel
(`kart_tpu/ops/merge_kernel.py`) — one jitted classification of the whole
PK-space union per dataset — and the small residue (meta items, attachments)
through an identical host-side rule. Clean changes are written to a merged
tree immediately; conflicts become a MergeIndex and move the repo to the
MERGING state, exactly like the reference's state machine
(`kart/repo.py:53-72`).
"""

import json
import logging

import numpy as np

from kart_tpu.core.repo import (
    MERGE_BRANCH,
    MERGE_HEAD,
    MERGE_INDEX,
    MERGE_MSG,
    InvalidOperation,
    KartRepoState,
)
from kart_tpu.core.structure import RepoStructure
from kart_tpu.core.tree_builder import TreeBuilder
from kart_tpu.merge.index import (
    AncestorOursTheirs,
    ColumnarConflicts,
    CombinedConflicts,
    ConflictEntry,
    EncodedPkPaths,
    MergeIndex,
    PkLabels,
    RowPaths,
)
from kart_tpu.ops.blocks import FeatureBlock, unpack_oid_hex
from kart_tpu.ops.merge_kernel import (
    CONFLICT,
    KEEP_OURS,
    TAKE_THEIRS,
    merge_classify,
)


class MergeResult:
    """Outcome of do_merge."""

    def __init__(
        self,
        *,
        commit_oid=None,
        fast_forward=False,
        already_merged=False,
        merge_index=None,
        dry_run=False,
        stats=None,
        merging=False,
        merged_tree=None,
    ):
        self.commit_oid = commit_oid
        self.fast_forward = fast_forward
        self.already_merged = already_merged
        self.merge_index = merge_index
        self.dry_run = dry_run
        self.stats = stats or {}
        self.merging = merging
        self.merged_tree = merged_tree

    @property
    def has_conflicts(self):
        return self.merge_index is not None and bool(self.merge_index.conflicts)


def _dataset_blocks(structures, ds_path):
    """Per-version FeatureBlock for ds_path (absent dataset -> empty block)."""
    blocks = []
    datasets = []
    for structure in structures:
        ds = structure.datasets.get(ds_path) if structure.tree is not None else None
        datasets.append(ds)
        if ds is None:
            blocks.append(
                FeatureBlock.from_arrays(
                    np.zeros(0, dtype=np.int64), np.zeros((0, 5), np.uint32), []
                )
            )
        else:
            blocks.append(FeatureBlock.from_dataset(ds))
    return blocks, datasets


def _keys_to_block_rows(block, keys):
    """union keys (K,) -> row index into block for each key, or -1 when the
    key is absent. One batched searchsorted, no per-key Python."""
    real = block.keys[: block.count]
    idx = np.searchsorted(real, keys)
    idxc = np.minimum(idx, max(block.count - 1, 0))
    found = (
        (real[idxc] == keys) & (idx < block.count)
        if block.count
        else np.zeros(len(keys), dtype=bool)
    )
    return np.where(found, idxc, -1)


def _feature_label(ds_path, datasets, rel_paths):
    """Conflict label `<ds>:feature:<pk>` (reference RichConflict labels,
    kart/merge_util.py:508-540)."""
    for ds, rel in zip(datasets, rel_paths):
        if ds is not None and rel is not None:
            try:
                pks = ds.decode_path_to_pks(rel)
                pk_part = ",".join(str(pk) for pk in pks)
                return f"{ds_path}:feature:{pk_part}"
            except Exception:
                continue
    rel = next((r for r in rel_paths if r), "?")
    return f"{ds_path}:feature:{rel}"


def _merge_dataset_features(ds_path, structures, tree_builder):
    """Vectorized per-feature 3-way for one dataset. Mutates tree_builder with
    clean theirs-changes; -> (conflicts dict, stats)."""
    blocks, datasets = _dataset_blocks(structures, ds_path)
    a_block, o_block, t_block = blocks

    if any(b.has_key_collisions() for b in blocks):
        # hash-keyed identity collided (~1e-4 probability at 1e8 features):
        # host path with identical semantics
        return _merge_dataset_features_host(ds_path, blocks, datasets, tree_builder)

    union, decision, presence, stats = merge_classify(a_block, o_block, t_block)

    take_idx = np.nonzero(decision == TAKE_THEIRS)[0]
    conflict_idx = np.nonzero(decision == CONFLICT)[0]

    inner = None
    for ds in datasets:
        if ds is not None:
            inner = ds.inner_path
            break
    if inner is None:
        return {}, stats

    # apply clean theirs-changes in batch: one searchsorted per side, then a
    # straight zip over the changed rows only
    take_keys = union[take_idx]
    t_rows = _keys_to_block_rows(t_block, take_keys)
    o_rows = _keys_to_block_rows(o_block, take_keys)
    present = t_rows >= 0
    if np.any(present):
        rows = t_rows[present]
        oid_hexes = unpack_oid_hex(t_block.oids[rows])
        for row, oid in zip(rows, oid_hexes):
            tree_builder.insert(f"{inner}/feature/{t_block.paths[row]}", oid)
    for row in o_rows[~present]:
        if row >= 0:
            tree_builder.remove(f"{inner}/feature/{o_block.paths[row]}")

    conflicts = materialise_conflicts(
        ds_path, blocks, datasets, inner, union, conflict_idx
    )
    return conflicts, stats


def materialise_conflicts(ds_path, blocks, datasets, inner, union, conflict_idx):
    """Conflict rows -> ColumnarConflicts (a {label: AncestorOursTheirs}
    mapping stored as numpy columns). BASELINE config #5 scale: a
    1M-conflict merge builds three (present, oids) column pairs with one
    searchsorted + one gather each — labels, paths and entry objects stay
    lazy until something actually reads them (serialisation reads the
    columns in batch)."""
    if not len(conflict_idx):
        return {}
    conflict_keys = union[conflict_idx]
    n = len(conflict_keys)
    prefix = f"{inner}/feature/"

    versions = []
    rows_per_block = []
    pk_path_cols = {}  # encoder id -> shared EncodedPkPaths (encode once)
    for block, ds in zip(blocks, datasets):
        rows = _keys_to_block_rows(block, conflict_keys)
        present = rows >= 0
        rows_per_block.append(rows)
        oids_u8 = np.zeros((n, 20), dtype=np.uint8)
        if np.any(present):
            sel = np.ascontiguousarray(block.oids[rows[present]])
            oids_u8[present] = sel.view(np.uint8).reshape(-1, 20)
        encoder = getattr(ds, "path_encoder", None)
        if encoder is not None and getattr(encoder, "scheme", None) == "int":
            # int-pk: the path is a pure function of the pk — versions with
            # the same encoder share one lazy column
            paths = pk_path_cols.get(id(encoder))
            if paths is None:
                paths = EncodedPkPaths(prefix, encoder, conflict_keys)
                pk_path_cols[id(encoder)] = paths
        else:
            paths = RowPaths(prefix, block.paths, rows)
        versions.append((present, oids_u8, paths))

    schemes = {
        getattr(getattr(ds, "path_encoder", None), "scheme", None)
        for ds in datasets
        if ds is not None
    }
    if schemes == {"int"}:
        # every version int-pk: keys ARE the pks, labels derive from the key
        # column. Mixed-encoder datasets (pk type change) must decode each
        # conflict with the encoder of a version that actually holds it.
        labels = PkLabels(ds_path, conflict_keys)
    else:
        labels = _DeferredLabels(ds_path, datasets, blocks, rows_per_block, n)
    return ColumnarConflicts(labels, versions)


class _DeferredLabels:
    """Label column for hash-keyed datasets: path-decode runs only when the
    labels are first read (serialisation / conflict listing)."""

    __slots__ = ("ds_path", "datasets", "blocks", "rows_per_block", "n")

    def __init__(self, ds_path, datasets, blocks, rows_per_block, n):
        self.ds_path = ds_path
        self.datasets = datasets
        self.blocks = blocks
        self.rows_per_block = rows_per_block
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return self.batch()[i]  # single lookups are rare; batch is cached upstream

    def batch(self):
        per_block = [
            (rows.tolist(), (rows >= 0).tolist(), None)
            for rows in self.rows_per_block
        ]
        return _conflict_labels_batch(
            self.ds_path, self.datasets, self.blocks, per_block, self.n
        )


def _conflict_labels_batch(ds_path, datasets, blocks, per_block, n):
    """Labels `<ds>:feature:<pk>` for every conflict. Each conflict's rel
    path is decoded with the encoder of the version it came from (versions
    of one dataset can carry different encoders, e.g. after a pk type
    change); int-pk versions decode all their paths in one vectorized
    call, others fall back per-path."""
    labels = [None] * n
    for v, ((rows, found, _), block) in enumerate(zip(per_block, blocks)):
        pending = [i for i in range(n) if labels[i] is None and found[i]]
        if not pending:
            continue
        rels = [block.paths[rows[i]] for i in pending]
        ds = datasets[v]
        encoder = getattr(ds, "path_encoder", None)
        done = False
        if encoder is not None and hasattr(encoder, "decode_paths_batch"):
            try:
                pks = encoder.decode_paths_batch(rels)
                for i, pk in zip(pending, pks):
                    labels[i] = f"{ds_path}:feature:{pk}"
                done = True
            except Exception as e:
                # undecodable batch: the per-path loop below re-derives
                # every label individually
                logging.getLogger(__name__).debug(
                    "batch path decode failed for %s: %s", ds_path, e
                )
        if not done:
            version_datasets = [None] * len(blocks)
            version_datasets[v] = ds
            for i, rel in zip(pending, rels):
                rel_row = [None] * len(blocks)
                rel_row[v] = rel
                labels[i] = _feature_label(ds_path, version_datasets, rel_row)
    for i in range(n):
        if labels[i] is None:
            labels[i] = f"{ds_path}:feature:?"
    return labels


def _merge_dataset_features_host(ds_path, blocks, datasets, tree_builder):
    """Fallback with dict semantics when hash keys collide."""
    def index(block):
        hexes = unpack_oid_hex(block.oids[: block.count])
        return dict(zip(block.paths, hexes))

    a, o, t = (index(b) for b in blocks)
    inner = next((ds.inner_path for ds in datasets if ds is not None), None)
    conflicts = {}
    stats = {"conflicts": 0, "take_theirs": 0}
    for rel in sorted(set(a) | set(o) | set(t)):
        av, ov, tv = a.get(rel), o.get(rel), t.get(rel)
        if ov == tv or tv == av:
            continue
        if ov == av:
            stats["take_theirs"] += 1
            if tv is not None:
                tree_builder.insert(f"{inner}/feature/{rel}", tv)
            else:
                tree_builder.remove(f"{inner}/feature/{rel}")
        else:
            stats["conflicts"] += 1
            label = _feature_label(ds_path, datasets, [rel] * 3)
            conflicts[label] = AncestorOursTheirs(
                *(
                    ConflictEntry(f"{inner}/feature/{rel}", v) if v is not None else None
                    for v in (av, ov, tv)
                )
            )
    return conflicts, stats


def _non_feature_items(structure):
    """{repo_path: oid} for every blob that is not a feature blob (meta items,
    version blob, attachments). Walks only the dataset inner trees' non-feature
    subtrees plus everything outside dataset trees — never descends into
    feature/ (which holds the ~all of the repo's blobs)."""
    out = {}
    tree = structure.tree
    if tree is None:
        return out

    dataset_dirnames = {".table-dataset", ".sno-dataset"}

    def walk(node, prefix):
        for entry in node.entries():
            path = f"{prefix}{entry.name}"
            if not entry.is_tree:
                out[path] = entry.oid
                continue
            if entry.name in dataset_dirnames:
                inner = structure.repo.odb.tree(entry.oid)
                for inner_entry in inner.entries():
                    if inner_entry.name == "feature":
                        continue
                    if inner_entry.is_tree:
                        walk(
                            structure.repo.odb.tree(inner_entry.oid),
                            f"{path}/{inner_entry.name}/",
                        )
                    else:
                        out[f"{path}/{inner_entry.name}"] = inner_entry.oid
            else:
                walk(structure.repo.odb.tree(entry.oid), f"{path}/")

    walk(tree, "")
    return out


def _label_for_non_feature(structure_list, path):
    for structure in structure_list:
        if structure.tree is None:
            continue
        ds_path, part, item = structure.decode_path(path)
        if part == "meta":
            return f"{ds_path}:meta:{item}"
        break
    return f"<root>:attachment:{path}"


def _merge_non_features(structures, tree_builder):
    a_items, o_items, t_items = (_non_feature_items(s) for s in structures)
    conflicts = {}
    for path in sorted(set(a_items) | set(o_items) | set(t_items)):
        av, ov, tv = a_items.get(path), o_items.get(path), t_items.get(path)
        if ov == tv or tv == av:
            continue
        if ov == av:
            if tv is not None:
                tree_builder.insert(path, tv)
            else:
                tree_builder.remove(path)
        else:
            label = _label_for_non_feature(structures, path)
            conflicts[label] = AncestorOursTheirs(
                *(
                    ConflictEntry(path, v) if v is not None else None
                    for v in (av, ov, tv)
                )
            )
    return conflicts


def merge_trees_vectorized(repo, ancestor_struct, ours_struct, theirs_struct):
    """-> (merged_tree_oid, conflicts dict, stats). The merged tree contains
    every clean change; conflicted paths keep their `ours` content until
    resolved."""
    structures = (ancestor_struct, ours_struct, theirs_struct)
    tb = TreeBuilder(repo.odb, ours_struct.tree_oid)
    all_conflicts = CombinedConflicts()
    total_stats = {"take_theirs": 0, "conflicts": 0}

    ds_paths = set()
    for structure in structures:
        if structure.tree is not None:
            ds_paths.update(structure.datasets.paths())
    for ds_path in sorted(ds_paths):
        conflicts, stats = _merge_dataset_features(ds_path, structures, tb)
        all_conflicts.add(conflicts)
        for k in total_stats:
            total_stats[k] += stats.get(k, 0)

    all_conflicts.add(_merge_non_features(structures, tb))
    merged_tree = tb.flush() if tb else ours_struct.tree_oid
    return merged_tree, all_conflicts, total_stats


def do_merge(repo, theirs_refish, *, message=None, dry_run=False, ff=True, ff_only=False):
    """Merge `theirs_refish` into HEAD (reference: kart/merge.py:45-158)."""
    if repo.state != KartRepoState.NORMAL:
        raise InvalidOperation(
            KartRepoState.bad_state_message(repo.state, (KartRepoState.NORMAL,))
        )
    ours_oid = repo.head_commit_oid
    if ours_oid is None:
        raise InvalidOperation("Repository has no commits yet")
    theirs_oid, theirs_ref = _resolve_commit_and_ref(repo, theirs_refish)
    if theirs_oid is None:
        raise InvalidOperation(f"Cannot resolve {theirs_refish!r}")

    ancestor_oid = repo.merge_base(ours_oid, theirs_oid)
    if ancestor_oid is None:
        raise InvalidOperation("Commits have no common ancestor")

    if ancestor_oid == theirs_oid:
        return MergeResult(already_merged=True, commit_oid=ours_oid, dry_run=dry_run)
    if ancestor_oid == ours_oid and ff:
        # fast-forward
        if not dry_run:
            _update_head_to(repo, theirs_oid)
        return MergeResult(commit_oid=theirs_oid, fast_forward=True, dry_run=dry_run)
    if ff_only:
        raise InvalidOperation(
            "Can't resolve as a fast-forward merge and --ff-only specified"
        )

    ancestor_struct = RepoStructure(repo, ancestor_oid)
    ours_struct = RepoStructure(repo, ours_oid)
    theirs_struct = RepoStructure(repo, theirs_oid)

    merged_tree, conflicts, stats = merge_trees_vectorized(
        repo, ancestor_struct, ours_struct, theirs_struct
    )

    branch_name = _branch_shorthand(repo, theirs_refish, theirs_ref)
    if message is None:
        message = f'Merge branch "{branch_name}"' if branch_name else (
            f"Merge {theirs_oid[:8]}"
        )

    if conflicts:
        merge_index = MergeIndex(merged_tree, conflicts)
        if not dry_run:
            merge_index.write_to_repo(repo)
            repo.write_gitdir_file(MERGE_HEAD, theirs_oid)
            repo.write_gitdir_file(MERGE_MSG, message)
            if branch_name:
                repo.write_gitdir_file(MERGE_BRANCH, branch_name)
        return MergeResult(
            merge_index=merge_index,
            dry_run=dry_run,
            stats=stats,
            merging=not dry_run,
            merged_tree=merged_tree,
        )

    if dry_run:
        return MergeResult(dry_run=True, stats=stats, merged_tree=merged_tree)

    commit_oid = _create_merge_commit(repo, merged_tree, message, [ours_oid, theirs_oid])
    _reset_wc(repo)
    return MergeResult(commit_oid=commit_oid, stats=stats, merged_tree=merged_tree)


def complete_merging_state(repo, *, message=None):
    """`kart merge --continue` (reference: kart/merge.py:183-236)."""
    if repo.state != KartRepoState.MERGING:
        raise InvalidOperation("No merge is ongoing")
    merge_index = MergeIndex.read_from_repo(repo)
    unresolved = merge_index.unresolved_labels
    if unresolved:
        raise InvalidOperation(
            f"Merge is not yet complete - {len(unresolved)} conflicts "
            'still need resolving. See "kart conflicts" / "kart resolve"'
        )
    theirs_oid = repo.read_gitdir_file(MERGE_HEAD).strip()
    message = message or repo.read_gitdir_file(MERGE_MSG) or "Merge"
    final_tree = merge_index.write_resolved_tree(repo.odb)
    commit_oid = _create_merge_commit(
        repo, final_tree, message, [repo.head_commit_oid, theirs_oid]
    )
    abort_merging_state(repo)
    _reset_wc(repo)
    return commit_oid


def abort_merging_state(repo):
    """Delete MERGE_* state files (reference: kart/merge.py:161-180).
    Robust: removes whatever subset exists."""
    for name in (MERGE_HEAD, MERGE_INDEX, MERGE_BRANCH, MERGE_MSG):
        repo.remove_gitdir_file(name)


def _resolve_commit_and_ref(repo, refish):
    oid, ref = repo.resolve_refish(refish)
    if oid is not None:
        oid = repo._peel_to_commit_oid(oid)
    return oid, ref


def _branch_shorthand(repo, refish, ref):
    if ref and ref.startswith("refs/heads/"):
        return ref[len("refs/heads/") :]
    if ref and ref.startswith("refs/remotes/"):
        return ref[len("refs/remotes/") :]
    if isinstance(refish, str) and not all(
        c in "0123456789abcdef" for c in refish.lower()
    ):
        return refish
    return None


def _update_head_to(repo, commit_oid):
    branch = repo.head_branch
    if branch:
        repo.refs.set(branch, commit_oid, log_message="merge: fast-forward")
    else:
        repo.refs.set_head(commit_oid, log_message="merge: fast-forward")
    _reset_wc(repo)


def _create_merge_commit(repo, tree_oid, message, parents):
    ref = repo.head_branch or "HEAD"
    return repo.create_commit(ref, tree_oid, message, parents)


def _reset_wc(repo):
    from kart_tpu.workingcopy import get_working_copy

    wc = get_working_copy(repo)
    if wc is not None:
        wc.reset(RepoStructure(repo, "HEAD"), force=True)
