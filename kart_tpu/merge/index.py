"""Persistent merge state — MergeIndex (reference: kart/merge_util.py:68-346).

The reference serialises an entire libgit2 index (entries + `.conflicts/…` +
`.resolves/…` paths) to the MERGE_INDEX file. Here the clean merge result is
already a written tree (the kernel emitted it before conflicts were known),
so the index only needs the *conflicts* — each one a named
ancestor/ours/theirs triple of (path, oid) entries — and the user's resolves.

Two encodings of `<gitdir>/MERGE_INDEX`, detected by content:
  * JSON (human-inspectable) below _BINARY_THRESHOLD conflicts;
  * a columnar binary block ("KMIX1") above it — a 1M-conflict merge
    (BASELINE config #5) would otherwise write ~350MB of JSON and pay ~10s
    of parsing on every `kart conflicts`/`kart resolve` invocation.
"""

import json
import struct

import numpy as np

from kart_tpu.core.repo import MERGE_INDEX

VERSION_NAMES = ("ancestor", "ours", "theirs")

_BINARY_THRESHOLD = 10_000
_BINARY_MAGIC = b"KMIX1\n"


class AncestorOursTheirs:
    """Named triple (reference: kart/merge_util.py:28-65)."""

    __slots__ = ("ancestor", "ours", "theirs")

    def __init__(self, ancestor=None, ours=None, theirs=None):
        self.ancestor = ancestor
        self.ours = ours
        self.theirs = theirs

    @classmethod
    def partial(cls, **kwargs):
        return cls(**kwargs)

    def get(self, name):
        if name not in VERSION_NAMES:
            raise KeyError(name)
        return getattr(self, name)

    def map(self, fn):
        return AncestorOursTheirs(
            *(fn(v) if v is not None else None for v in self)
        )

    def __iter__(self):
        yield self.ancestor
        yield self.ours
        yield self.theirs

    def as_dict(self):
        return {n: self.get(n) for n in VERSION_NAMES}

    def __repr__(self):
        return f"AOT(a={self.ancestor!r}, o={self.ours!r}, t={self.theirs!r})"


class ConflictEntry:
    """One version of one conflicted item: a (path, oid) pair."""

    __slots__ = ("path", "oid")

    def __init__(self, path, oid):
        self.path = path
        self.oid = oid

    def to_json(self):
        return {"path": self.path, "oid": self.oid}

    @classmethod
    def from_json(cls, d):
        return cls(d["path"], d["oid"]) if d else None


class MergeIndex:
    """Conflicts + resolves for an in-progress merge.

    ``conflicts``: label -> AncestorOursTheirs of ConflictEntry|None.
    ``resolves``: label -> list[ConflictEntry] (empty list = resolved as
    delete).
    ``merged_tree``: oid of the tree with all *clean* changes applied.
    """

    def __init__(self, merged_tree, conflicts=None, resolves=None):
        self.merged_tree = merged_tree
        self.conflicts = conflicts or {}
        self.resolves = resolves or {}

    # -- persistence ---------------------------------------------------------

    def to_json(self):
        return {
            "kart.merge_index/v1": {
                "mergedTree": self.merged_tree,
                "conflicts": {
                    label: {
                        name: (entry.to_json() if entry else None)
                        for name, entry in aot.as_dict().items()
                    }
                    for label, aot in self.conflicts.items()
                },
                "resolves": {
                    label: [e.to_json() for e in entries]
                    for label, entries in self.resolves.items()
                },
            }
        }

    @classmethod
    def from_json(cls, data):
        body = data["kart.merge_index/v1"]
        conflicts = {
            label: AncestorOursTheirs(
                **{
                    name: ConflictEntry.from_json(entry)
                    for name, entry in versions.items()
                }
            )
            for label, versions in body["conflicts"].items()
        }
        resolves = {
            label: [ConflictEntry.from_json(e) for e in entries]
            for label, entries in body["resolves"].items()
        }
        return cls(body["mergedTree"], conflicts, resolves)

    # -- binary encoding (columnar, for large conflict sets) ----------------

    def _to_binary(self):
        """KMIX1: magic, u32 header length, JSON header {mergedTree,
        resolves, n}, then per column: u64 byte length + payload. Columns:
        NUL-joined label bytes, then per version (a/o/t) a present mask,
        (n,20) oids, and NUL-joined path bytes (empty for absent)."""
        labels = list(self.conflicts.keys())
        n = len(labels)
        header = json.dumps(
            {
                "mergedTree": self.merged_tree,
                "n": n,
                "resolves": {
                    label: [e.to_json() for e in entries]
                    for label, entries in self.resolves.items()
                },
            }
        ).encode()

        blocks = ["\x00".join(labels).encode()]
        aots = list(self.conflicts.values())
        for name in VERSION_NAMES:
            present = np.zeros(n, dtype=np.uint8)
            oids = np.zeros((n, 20), dtype=np.uint8)
            paths = []
            for i, aot in enumerate(aots):
                entry = aot.get(name)
                if entry is not None:
                    present[i] = 1
                    oids[i] = np.frombuffer(bytes.fromhex(entry.oid), np.uint8)
                    paths.append(entry.path)
                else:
                    paths.append("")
            blocks += [
                present.tobytes(),
                oids.tobytes(),
                "\x00".join(paths).encode(),
            ]

        out = [_BINARY_MAGIC, struct.pack("<I", len(header)), header]
        for block in blocks:
            out.append(struct.pack("<Q", len(block)))
            out.append(block)
        return b"".join(out)

    @classmethod
    def _from_binary(cls, raw):
        pos = len(_BINARY_MAGIC)
        (hlen,) = struct.unpack_from("<I", raw, pos)
        pos += 4
        header = json.loads(raw[pos : pos + hlen].decode())
        pos += hlen
        n = header["n"]

        def block():
            nonlocal pos
            (blen,) = struct.unpack_from("<Q", raw, pos)
            pos += 8
            data = raw[pos : pos + blen]
            pos += blen
            return data

        def unpack_strs(data_b):
            return data_b.decode().split("\x00") if n else []

        labels = unpack_strs(block())
        versions = []
        for _ in VERSION_NAMES:
            present = np.frombuffer(block(), dtype=np.uint8)
            oids = np.frombuffer(block(), dtype=np.uint8).reshape(n, 20)
            paths = unpack_strs(block())
            versions.append((present, oids, paths))

        conflicts = {}
        for i, label in enumerate(labels):
            entries = []
            for present, oids, paths in versions:
                if present[i]:
                    entries.append(ConflictEntry(paths[i], bytes(oids[i]).hex()))
                else:
                    entries.append(None)
            conflicts[label] = AncestorOursTheirs(*entries)
        resolves = {
            label: [ConflictEntry.from_json(e) for e in entries]
            for label, entries in header["resolves"].items()
        }
        return cls(header["mergedTree"], conflicts, resolves)

    # -- repo persistence ----------------------------------------------------

    def write_to_repo(self, repo):
        import os

        path = repo.gitdir_file(MERGE_INDEX)
        if len(self.conflicts) >= _BINARY_THRESHOLD:
            tmp = path + f".tmp{os.getpid()}"
            try:
                with open(tmp, "wb") as f:
                    f.write(self._to_binary())
                os.replace(tmp, path)
            except BaseException:
                if os.path.exists(tmp):
                    os.remove(tmp)
                raise
        else:
            repo.write_gitdir_file(MERGE_INDEX, json.dumps(self.to_json()))

    @classmethod
    def read_from_repo(cls, repo):
        import os

        path = repo.gitdir_file(MERGE_INDEX)
        if not os.path.exists(path):
            from kart_tpu.core.repo import InvalidOperation

            raise InvalidOperation(
                "Repository is in 'merging' state but MERGE_INDEX is missing - "
                'run "kart merge --abort" to recover'
            )
        with open(path, "rb") as f:
            raw = f.read()
        if raw.startswith(_BINARY_MAGIC):
            return cls._from_binary(raw)
        return cls.from_json(json.loads(raw.decode()))

    # -- resolution ----------------------------------------------------------

    @property
    def unresolved_labels(self):
        return [l for l in self.conflicts if l not in self.resolves]

    def add_resolve(self, label, entries):
        if label not in self.conflicts:
            raise KeyError(label)
        self.resolves[label] = entries

    def write_resolved_tree(self, odb):
        """All conflicts resolved -> final tree oid
        (reference: kart/merge_util.py:294-315)."""
        assert not self.unresolved_labels
        from kart_tpu.core.tree_builder import TreeBuilder

        tb = TreeBuilder(odb, self.merged_tree)
        for label, aot in self.conflicts.items():
            # clear every version's path, then write the resolution
            for entry in aot:
                if entry is not None:
                    tb.remove(entry.path)
            for entry in self.resolves.get(label, ()):
                tb.insert(entry.path, entry.oid)
        return tb.flush()
