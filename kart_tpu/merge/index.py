"""Persistent merge state — MergeIndex (reference: kart/merge_util.py:68-346).

The reference serialises an entire libgit2 index (entries + `.conflicts/…` +
`.resolves/…` paths) to the MERGE_INDEX file. Here the clean merge result is
already a written tree (the kernel emitted it before conflicts were known),
so the index only needs the *conflicts* — each one a named
ancestor/ours/theirs triple of (path, oid) entries — and the user's resolves.

Two encodings of `<gitdir>/MERGE_INDEX`, detected by content:
  * JSON (human-inspectable) below _BINARY_THRESHOLD conflicts;
  * a columnar binary block ("KMIX2"; "KMIX1" still reads) above it — a
    1M-conflict merge
    (BASELINE config #5) would otherwise write ~350MB of JSON and pay ~10s
    of parsing on every `kart conflicts`/`kart resolve` invocation.
"""

import json
import struct
from collections.abc import Mapping

import numpy as np

from kart_tpu.core.repo import MERGE_INDEX

VERSION_NAMES = ("ancestor", "ours", "theirs")

_BINARY_THRESHOLD = 10_000
_BINARY_MAGIC_V1 = b"KMIX1\n"
_BINARY_MAGIC = b"KMIX2\n"
# KMIX2 path-block dedup: a path block whose u64 length is this sentinel is
# followed by a u64 version index whose path bytes it shares (the three
# versions of a tree conflict usually carry identical path columns)
_PATH_REF_SENTINEL = 0xFFFFFFFFFFFFFFFF
# KMIX2 derived path block: for int-pk datasets the path column is a pure
# function of the pks, so the block stores {prefix, encoder spec} + the raw
# int64 pk array (8 bytes/row) instead of ~35 bytes/row of path strings —
# the reader rebuilds the same lazy column, nothing materialises until a
# path is touched
_PATH_DERIVED_SENTINEL = 0xFFFFFFFFFFFFFFFE
# same idea for the label column: "<ds>:feature:<pk>" is derivable from
# {ds_path} + the pk array
_LABEL_DERIVED_SENTINEL = 0xFFFFFFFFFFFFFFFD


class AncestorOursTheirs:
    """Named triple (reference: kart/merge_util.py:28-65)."""

    __slots__ = ("ancestor", "ours", "theirs")

    def __init__(self, ancestor=None, ours=None, theirs=None):
        self.ancestor = ancestor
        self.ours = ours
        self.theirs = theirs

    @classmethod
    def partial(cls, **kwargs):
        return cls(**kwargs)

    def get(self, name):
        if name not in VERSION_NAMES:
            raise KeyError(name)
        return getattr(self, name)

    def map(self, fn):
        return AncestorOursTheirs(
            *(fn(v) if v is not None else None for v in self)
        )

    def __iter__(self):
        yield self.ancestor
        yield self.ours
        yield self.theirs

    def as_dict(self):
        return {n: self.get(n) for n in VERSION_NAMES}

    def __repr__(self):
        return f"AOT(a={self.ancestor!r}, o={self.ours!r}, t={self.theirs!r})"


class ConflictEntry:
    """One version of one conflicted item: a (path, oid) pair."""

    __slots__ = ("path", "oid")

    def __init__(self, path, oid):
        self.path = path
        self.oid = oid

    def to_json(self):
        return {"path": self.path, "oid": self.oid}

    @classmethod
    def from_json(cls, d):
        return cls(d["path"], d["oid"]) if d else None


class EncodedPkPaths:
    """Lazy path column for int-pk conflicts: the feature path is a pure
    function of the pk, so nothing is stored — single lookups encode one
    path, ``batch()`` uses the vectorized whole-column encoder (memoised:
    ancestor/ours/theirs share one instance, so the column encodes once)."""

    __slots__ = ("prefix", "encoder", "keys", "_batch")

    def __init__(self, prefix, encoder, keys):
        self.prefix = prefix
        self.encoder = encoder
        self.keys = keys
        self._batch = None

    def __len__(self):
        return len(self.keys)

    def __getitem__(self, i):
        if self._batch is not None:
            return self._batch[i]
        return self.prefix + self.encoder.encode_pks_to_path((int(self.keys[i]),))

    def batch(self):
        if self._batch is None:
            self._batch = [
                self.prefix + p for p in self.encoder.encode_paths_batch(self.keys)
            ]
        return self._batch

    def joined_bytes(self, sep=b"\x00"):
        """NUL-joined full-path bytes for serialisation, bypassing per-path
        strings entirely; None when the encoder can't (writer falls back)."""
        fn = getattr(self.encoder, "encode_paths_joined_bytes", None)
        if fn is None:
            return None
        return fn(self.keys, prefix=self.prefix.encode(), sep=sep)


class RowPaths:
    """Lazy path column backed by a block's path list + per-conflict row
    indices (hash-keyed datasets, where paths aren't derivable)."""

    __slots__ = ("prefix", "paths", "rows")

    def __init__(self, prefix, paths, rows):
        self.prefix = prefix
        self.paths = paths
        self.rows = rows

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, i):
        return self.prefix + self.paths[self.rows[i]]

    def batch(self):
        paths = self.paths
        prefix = self.prefix
        return [prefix + paths[r] if r >= 0 else "" for r in self.rows.tolist()]


class PkLabels:
    """Lazy label column `<ds>:feature:<pk>` from the conflict pk array."""

    __slots__ = ("ds_path", "keys")

    def __init__(self, ds_path, keys):
        self.ds_path = ds_path
        self.keys = keys

    def __len__(self):
        return len(self.keys)

    def __getitem__(self, i):
        return f"{self.ds_path}:feature:{int(self.keys[i])}"

    def batch(self):
        head = f"{self.ds_path}:feature:"
        return [head + str(k) for k in self.keys.tolist()]

    def joined_bytes(self, sep=b"\x00"):
        """Serialised column in one pass: the int->str conversion runs as a
        vectorized numpy astype instead of 1M Python str() calls."""
        if len(self.keys) == 0:
            return b""
        head = f"{self.ds_path}:feature:"
        strs = self.keys.astype("U21").tolist()
        return (head + (sep.decode() + head).join(strs)).encode()


class JoinedStrs:
    """Lazy string column over NUL-joined bytes (the KMIX1 on-disk form):
    reading a 1M-conflict index is O(1) until a column is actually touched."""

    __slots__ = ("raw", "n", "_list")

    def __init__(self, raw, n):
        self.raw = raw
        self.n = n
        self._list = None

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return self.batch()[i]

    def batch(self):
        if self._list is None:
            self._list = self.raw.decode().split("\x00") if self.n else []
        return self._list

    def joined_bytes(self, sep=b"\x00"):
        """Read->rewrite roundtrip (resolve flow): the on-disk bytes are
        already the serialised column."""
        return self.raw if sep == b"\x00" else None


def _materialise_col(src):
    """Path/label column -> list[str]."""
    if isinstance(src, list):
        return src
    return src.batch() if hasattr(src, "batch") else list(src)


def _derived_path_block(paths):
    """KMIX2 derived-block payload for an :class:`EncodedPkPaths` column
    (u32 spec length + JSON {prefix, encoder} + raw little-endian int64
    pks), or None when the column isn't pk-derivable."""
    if not isinstance(paths, EncodedPkPaths):
        return None
    to_dict = getattr(paths.encoder, "to_dict", None)
    if to_dict is None:
        return None
    spec = json.dumps(
        {"prefix": paths.prefix, "encoder": to_dict()}
    ).encode()
    keys = np.ascontiguousarray(paths.keys, dtype="<i8")
    return struct.pack("<I", len(spec)) + spec + keys.tobytes()


def _paths_from_derived_block(payload, n):
    """Inverse of :func:`_derived_path_block`."""
    from kart_tpu.models.paths import PathEncoder

    (slen,) = struct.unpack_from("<I", payload, 0)
    spec = json.loads(payload[4 : 4 + slen].decode())
    keys = np.frombuffer(payload[4 + slen :], dtype="<i8")
    if len(keys) != n:
        raise ValueError(
            f"Corrupt derived path block: {len(keys)} pks for {n} conflicts"
        )
    return EncodedPkPaths(spec["prefix"], PathEncoder.get(**spec["encoder"]), keys)


class ColumnarConflicts(Mapping):
    """Column-oriented conflict set: numpy presence/oid columns plus lazy
    label/path columns. Behaves as the {label: AncestorOursTheirs} mapping
    the rest of the engine expects, but a 1M-conflict merge stores ~60MB of
    arrays instead of 4M Python objects, and serialisation reads the columns
    directly (BASELINE config #5; reference: kart/merge_util.py:68-346).

    ``versions``: one (present bool (n,), oids_u8 (n, 20), paths) triple per
    ancestor/ours/theirs, where paths is a list or a lazy column
    (:class:`EncodedPkPaths` / :class:`RowPaths`). ``labels`` likewise."""

    __slots__ = ("n", "_labels_src", "versions", "_labels", "_where")

    def __init__(self, labels, versions):
        self.n = len(labels)
        self._labels_src = labels
        self.versions = list(versions)
        self._labels = labels if isinstance(labels, list) else None
        self._where = None

    @property
    def labels(self):
        if self._labels is None:
            self._labels = _materialise_col(self._labels_src)
        return self._labels

    def _label_index(self, label):
        if self._where is None:
            self._where = {l: i for i, l in enumerate(self.labels)}
        return self._where.get(label)

    def _entry(self, v, i):
        present, oids_u8, paths = self.versions[v]
        if not present[i]:
            return None
        return ConflictEntry(paths[i], bytes(oids_u8[i]).hex())

    def _aot(self, i):
        return AncestorOursTheirs(*(self._entry(v, i) for v in range(3)))

    # -- Mapping protocol ----------------------------------------------------

    def __len__(self):
        return self.n

    def __iter__(self):
        return iter(self.labels)

    def __contains__(self, label):
        return self._label_index(label) is not None

    def __getitem__(self, label):
        i = self._label_index(label)
        if i is None:
            raise KeyError(label)
        return self._aot(i)

    def items(self):
        labels = self.labels
        return ((labels[i], self._aot(i)) for i in range(self.n))

    def values(self):
        return (self._aot(i) for i in range(self.n))

    def to_columns(self):
        """-> (labels, [(present, oids_u8, paths)] x3); labels and paths stay
        lazy column objects so the serialiser can use their batch/joined-bytes
        fast paths."""
        labels = self._labels if self._labels is not None else self._labels_src
        return labels, list(self.versions)

    def summary_counts(self):
        """``{(ds_path, part): count}`` — the ``-ss`` conflict summary as
        raw counts. A :class:`PkLabels` column (the common int-pk dataset)
        answers from its shape alone — no label strings materialise, so a
        1M-conflict rejection report costs O(1), not a million f-strings."""
        src = self._labels_src if self._labels is None else self._labels
        if isinstance(src, PkLabels):
            return {(src.ds_path, "feature"): self.n} if self.n else {}
        counts = {}
        for label in self.labels:
            key = tuple(label.split(":", 2)[:2])
            counts[key] = counts.get(key, 0) + 1
        return counts


class CombinedConflicts(Mapping):
    """Ordered chain of conflict mappings (one ColumnarConflicts per dataset
    + a plain dict for meta/attachment conflicts) presenting as one mapping.
    Keeps each part columnar so serialisation never flattens to objects."""

    __slots__ = ("parts",)

    def __init__(self, parts=None):
        self.parts = [p for p in (parts or []) if len(p)]

    def add(self, part):
        if len(part):
            self.parts.append(part)

    def __len__(self):
        return sum(len(p) for p in self.parts)

    def __iter__(self):
        for p in self.parts:
            yield from p

    def __contains__(self, label):
        return any(label in p for p in self.parts)

    def __getitem__(self, label):
        for p in self.parts:
            if label in p:
                return p[label]
        raise KeyError(label)

    def items(self):
        for p in self.parts:
            yield from p.items()

    def values(self):
        for p in self.parts:
            yield from p.values()

    def summary_counts(self):
        """Aggregate ``{(ds_path, part): count}`` over every part, using
        each columnar part's fast path and a label loop for plain dicts."""
        counts = {}
        for p in self.parts:
            sub = getattr(p, "summary_counts", None)
            if sub is not None:
                for key, n in sub().items():
                    counts[key] = counts.get(key, 0) + n
                continue
            for label in p:
                key = tuple(label.split(":", 2)[:2])
                counts[key] = counts.get(key, 0) + 1
        return counts


def _conflicts_as_columns(conflicts):
    """Any conflict mapping -> (labels list, [(present, oids_u8, paths)] x3)
    columns. The common single-dataset case passes the lazy path columns
    straight through (the serialiser uses their batch fast paths); multi-part
    and plain-dict conflict sets are concatenated, looping per item only for
    dict parts."""
    parts = (
        conflicts.parts
        if isinstance(conflicts, CombinedConflicts)
        else [conflicts]
    )
    if len(parts) == 1 and isinstance(parts[0], ColumnarConflicts):
        return parts[0].to_columns()

    labels = []
    cols = [([], [], []) for _ in VERSION_NAMES]  # (present, oids, paths)
    for part in parts:
        if isinstance(part, ColumnarConflicts):
            part_labels, part_versions = part.to_columns()
            labels.extend(_materialise_col(part_labels))
            for v, (present, oids_u8, paths) in enumerate(part_versions):
                cols[v][0].append(np.asarray(present, dtype=np.uint8))
                cols[v][1].append(oids_u8)
                cols[v][2].extend(_materialise_col(paths))
            continue
        n = len(part)
        for v_name, col in zip(VERSION_NAMES, cols):
            present = np.zeros(n, dtype=np.uint8)
            oids = np.zeros((n, 20), dtype=np.uint8)
            paths = []
            for i, aot in enumerate(part.values()):
                entry = aot.get(v_name)
                if entry is not None:
                    present[i] = 1
                    oids[i] = np.frombuffer(bytes.fromhex(entry.oid), np.uint8)
                    paths.append(entry.path)
                else:
                    paths.append("")
            col[0].append(present)
            col[1].append(oids)
            col[2].extend(paths)
        labels.extend(part.keys())
    out = []
    for present_chunks, oid_chunks, paths in cols:
        present = (
            np.concatenate(present_chunks)
            if present_chunks
            else np.zeros(0, dtype=np.uint8)
        )
        oids = (
            np.concatenate(oid_chunks)
            if oid_chunks
            else np.zeros((0, 20), dtype=np.uint8)
        )
        out.append((present, oids, paths))
    return labels, out


class MergeIndex:
    """Conflicts + resolves for an in-progress merge.

    ``conflicts``: label -> AncestorOursTheirs of ConflictEntry|None.
    ``resolves``: label -> list[ConflictEntry] (empty list = resolved as
    delete).
    ``merged_tree``: oid of the tree with all *clean* changes applied.
    """

    def __init__(self, merged_tree, conflicts=None, resolves=None):
        self.merged_tree = merged_tree
        self.conflicts = conflicts or {}
        self.resolves = resolves or {}

    # -- persistence ---------------------------------------------------------

    def to_json(self):
        return {
            "kart.merge_index/v1": {
                "mergedTree": self.merged_tree,
                "conflicts": {
                    label: {
                        name: (entry.to_json() if entry else None)
                        for name, entry in aot.as_dict().items()
                    }
                    for label, aot in self.conflicts.items()
                },
                "resolves": {
                    label: [e.to_json() for e in entries]
                    for label, entries in self.resolves.items()
                },
            }
        }

    @classmethod
    def from_json(cls, data):
        body = data["kart.merge_index/v1"]
        conflicts = {
            label: AncestorOursTheirs(
                **{
                    name: ConflictEntry.from_json(entry)
                    for name, entry in versions.items()
                }
            )
            for label, versions in body["conflicts"].items()
        }
        resolves = {
            label: [ConflictEntry.from_json(e) for e in entries]
            for label, entries in body["resolves"].items()
        }
        return cls(body["mergedTree"], conflicts, resolves)

    # -- binary encoding (columnar, for large conflict sets) ----------------

    def _binary_chunks(self):
        """Yield the KMIX2 byte chunks: magic, u32 header length, JSON header
        {mergedTree, resolves, n}, then per column: u64 byte length +
        payload. Columns: NUL-joined label bytes, then per version (a/o/t) a
        present mask, (n,20) oids, and a path block. A path block is one of:
        plain NUL-joined path bytes (empty for absent rows); a
        _PATH_REF_SENTINEL length + u64 version index sharing an earlier
        version's block; or a _PATH_DERIVED_SENTINEL length + u64 payload
        length + payload ({prefix, encoder spec} + raw int64 pks — int-pk
        paths are recomputed, not stored).

        Columnar conflict sets serialise column-to-column (no per-conflict
        objects); plain dicts are looped in _conflicts_as_columns. Chunked so
        write_to_repo streams to disk without joining a second in-memory
        copy."""
        labels, version_cols = _conflicts_as_columns(self.conflicts)
        n = len(labels)
        header = json.dumps(
            {
                "mergedTree": self.merged_tree,
                "n": n,
                "resolves": {
                    label: [e.to_json() for e in entries]
                    for label, entries in self.resolves.items()
                },
            }
        ).encode()

        yield _BINARY_MAGIC
        yield struct.pack("<I", len(header))
        yield header
        if isinstance(labels, PkLabels):
            spec = json.dumps({"ds_path": labels.ds_path}).encode()
            keys = np.ascontiguousarray(labels.keys, dtype="<i8")
            payload = struct.pack("<I", len(spec)) + spec + keys.tobytes()
            yield struct.pack("<QQ", _LABEL_DERIVED_SENTINEL, len(payload))
            yield payload
        else:
            label_jb = getattr(labels, "joined_bytes", None)
            label_bytes = label_jb() if label_jb is not None else None
            if label_bytes is None:
                label_bytes = "\x00".join(_materialise_col(labels)).encode()
            yield struct.pack("<Q", len(label_bytes))
            yield label_bytes
        # versions routinely share one path column (a tree conflict keeps the
        # same feature path in ancestor/ours/theirs) — encode AND write those
        # bytes once, later versions reference the earlier block (~1/3 the
        # file at 1M conflicts)
        written_paths = {}  # id(path column) -> version index written at
        for v, (present, oids, paths) in enumerate(version_cols):
            yield struct.pack(
                "<Q", len(present)
            )
            yield np.ascontiguousarray(present, dtype=np.uint8).tobytes()
            oid_bytes = np.ascontiguousarray(oids, dtype=np.uint8).tobytes()
            yield struct.pack("<Q", len(oid_bytes))
            yield oid_bytes
            if np.all(present):
                ref = written_paths.get(id(paths))
                if ref is not None:
                    yield struct.pack("<QQ", _PATH_REF_SENTINEL, ref)
                    continue
                derived = _derived_path_block(paths)
                if derived is not None:
                    yield struct.pack(
                        "<QQ", _PATH_DERIVED_SENTINEL, len(derived)
                    )
                    yield derived
                    written_paths[id(paths)] = v
                    continue
                jb = getattr(paths, "joined_bytes", None)
                path_bytes = jb() if jb is not None else None
                if path_bytes is None:
                    path_bytes = "\x00".join(_materialise_col(paths)).encode()
                written_paths[id(paths)] = v
            else:
                # absent rows must serialise with an empty path (padding rows
                # of lazy columns can carry junk paths; mask them out)
                lst = _materialise_col(paths)
                path_bytes = "\x00".join(
                    p if ok else "" for p, ok in zip(lst, present)
                ).encode()
            yield struct.pack("<Q", len(path_bytes))
            yield path_bytes

    def _to_binary(self):
        return b"".join(self._binary_chunks())

    @classmethod
    def _from_binary(cls, raw):
        v2 = raw.startswith(_BINARY_MAGIC)
        pos = len(_BINARY_MAGIC if v2 else _BINARY_MAGIC_V1)
        (hlen,) = struct.unpack_from("<I", raw, pos)
        pos += 4
        header = json.loads(raw[pos : pos + hlen].decode())
        pos += hlen
        n = header["n"]

        def block():
            nonlocal pos
            (blen,) = struct.unpack_from("<Q", raw, pos)
            pos += 8
            if v2 and blen == _PATH_REF_SENTINEL:
                (ref,) = struct.unpack_from("<Q", raw, pos)
                pos += 8
                return ref  # back-reference to version `ref`'s path block
            if v2 and blen in (_PATH_DERIVED_SENTINEL, _LABEL_DERIVED_SENTINEL):
                (plen,) = struct.unpack_from("<Q", raw, pos)
                pos += 8
                payload = raw[pos : pos + plen]
                pos += plen
                kind = "derived" if blen == _PATH_DERIVED_SENTINEL else "labels"
                return (kind, payload)
            data = raw[pos : pos + blen]
            pos += blen
            return data

        label_block = block()
        if isinstance(label_block, tuple):
            (slen,) = struct.unpack_from("<I", label_block[1], 0)
            spec = json.loads(label_block[1][4 : 4 + slen].decode())
            keys = np.frombuffer(label_block[1][4 + slen :], dtype="<i8")
            if len(keys) != n:
                raise ValueError(
                    f"Corrupt derived label block: {len(keys)} pks for {n}"
                )
            labels = PkLabels(spec["ds_path"], keys)
        else:
            labels = JoinedStrs(label_block, n)
        versions = []
        for _ in VERSION_NAMES:
            present = np.frombuffer(block(), dtype=np.uint8)
            oids = np.frombuffer(block(), dtype=np.uint8).reshape(n, 20)
            path_block = block()
            if isinstance(path_block, int):
                paths = versions[path_block][2]  # shared column object
            elif isinstance(path_block, tuple):
                paths = _paths_from_derived_block(path_block[1], n)
            else:
                paths = JoinedStrs(path_block, n)
            versions.append((present, oids, paths))

        # stays columnar on read: `kart conflicts`/`kart resolve` on a
        # 1M-conflict index materialise only the entries they actually touch
        conflicts = ColumnarConflicts(labels, versions)
        resolves = {
            label: [ConflictEntry.from_json(e) for e in entries]
            for label, entries in header["resolves"].items()
        }
        return cls(header["mergedTree"], conflicts, resolves)

    # -- repo persistence ----------------------------------------------------

    def write_to_repo(self, repo):
        import os

        path = repo.gitdir_file(MERGE_INDEX)
        if len(self.conflicts) >= _BINARY_THRESHOLD:
            tmp = path + f".tmp{os.getpid()}"
            try:
                with open(tmp, "wb") as f:
                    for chunk in self._binary_chunks():
                        f.write(chunk)
                os.replace(tmp, path)
            except BaseException:
                if os.path.exists(tmp):
                    os.remove(tmp)
                raise
        else:
            repo.write_gitdir_file(MERGE_INDEX, json.dumps(self.to_json()))

    @classmethod
    def read_from_repo(cls, repo):
        import os

        path = repo.gitdir_file(MERGE_INDEX)
        if not os.path.exists(path):
            from kart_tpu.core.repo import InvalidOperation

            raise InvalidOperation(
                "Repository is in 'merging' state but MERGE_INDEX is missing - "
                'run "kart merge --abort" to recover'
            )
        with open(path, "rb") as f:
            raw = f.read()
        if raw.startswith(_BINARY_MAGIC) or raw.startswith(_BINARY_MAGIC_V1):
            return cls._from_binary(raw)
        return cls.from_json(json.loads(raw.decode()))

    # -- resolution ----------------------------------------------------------

    @property
    def unresolved_labels(self):
        return [l for l in self.conflicts if l not in self.resolves]

    def add_resolve(self, label, entries):
        if label not in self.conflicts:
            raise KeyError(label)
        self.resolves[label] = entries

    def write_resolved_tree(self, odb):
        """All conflicts resolved -> final tree oid
        (reference: kart/merge_util.py:294-315)."""
        assert not self.unresolved_labels
        from kart_tpu.core.tree_builder import TreeBuilder

        tb = TreeBuilder(odb, self.merged_tree)
        for label, aot in self.conflicts.items():
            # clear every version's path, then write the resolution
            for entry in aot:
                if entry is not None:
                    tb.remove(entry.path)
            for entry in self.resolves.get(label, ()):
                tb.insert(entry.path, entry.oid)
        return tb.flush()
