"""Persistent merge state — MergeIndex (reference: kart/merge_util.py:68-346).

The reference serialises an entire libgit2 index (entries + `.conflicts/…` +
`.resolves/…` paths) to the MERGE_INDEX file. Here the clean merge result is
already a written tree (the kernel emitted it before conflicts were known),
so the index only needs the *conflicts* — each one a named
ancestor/ours/theirs triple of (path, oid) entries — and the user's resolves.
Stored as JSON in `<gitdir>/MERGE_INDEX`.
"""

import json

from kart_tpu.core.repo import MERGE_INDEX

VERSION_NAMES = ("ancestor", "ours", "theirs")


class AncestorOursTheirs:
    """Named triple (reference: kart/merge_util.py:28-65)."""

    __slots__ = ("ancestor", "ours", "theirs")

    def __init__(self, ancestor=None, ours=None, theirs=None):
        self.ancestor = ancestor
        self.ours = ours
        self.theirs = theirs

    @classmethod
    def partial(cls, **kwargs):
        return cls(**kwargs)

    def get(self, name):
        if name not in VERSION_NAMES:
            raise KeyError(name)
        return getattr(self, name)

    def map(self, fn):
        return AncestorOursTheirs(
            *(fn(v) if v is not None else None for v in self)
        )

    def __iter__(self):
        yield self.ancestor
        yield self.ours
        yield self.theirs

    def as_dict(self):
        return {n: self.get(n) for n in VERSION_NAMES}

    def __repr__(self):
        return f"AOT(a={self.ancestor!r}, o={self.ours!r}, t={self.theirs!r})"


class ConflictEntry:
    """One version of one conflicted item: a (path, oid) pair."""

    __slots__ = ("path", "oid")

    def __init__(self, path, oid):
        self.path = path
        self.oid = oid

    def to_json(self):
        return {"path": self.path, "oid": self.oid}

    @classmethod
    def from_json(cls, d):
        return cls(d["path"], d["oid"]) if d else None


class MergeIndex:
    """Conflicts + resolves for an in-progress merge.

    ``conflicts``: label -> AncestorOursTheirs of ConflictEntry|None.
    ``resolves``: label -> list[ConflictEntry] (empty list = resolved as
    delete).
    ``merged_tree``: oid of the tree with all *clean* changes applied.
    """

    def __init__(self, merged_tree, conflicts=None, resolves=None):
        self.merged_tree = merged_tree
        self.conflicts = conflicts or {}
        self.resolves = resolves or {}

    # -- persistence ---------------------------------------------------------

    def to_json(self):
        return {
            "kart.merge_index/v1": {
                "mergedTree": self.merged_tree,
                "conflicts": {
                    label: {
                        name: (entry.to_json() if entry else None)
                        for name, entry in aot.as_dict().items()
                    }
                    for label, aot in self.conflicts.items()
                },
                "resolves": {
                    label: [e.to_json() for e in entries]
                    for label, entries in self.resolves.items()
                },
            }
        }

    @classmethod
    def from_json(cls, data):
        body = data["kart.merge_index/v1"]
        conflicts = {
            label: AncestorOursTheirs(
                **{
                    name: ConflictEntry.from_json(entry)
                    for name, entry in versions.items()
                }
            )
            for label, versions in body["conflicts"].items()
        }
        resolves = {
            label: [ConflictEntry.from_json(e) for e in entries]
            for label, entries in body["resolves"].items()
        }
        return cls(body["mergedTree"], conflicts, resolves)

    def write_to_repo(self, repo):
        repo.write_gitdir_file(MERGE_INDEX, json.dumps(self.to_json()))

    @classmethod
    def read_from_repo(cls, repo):
        text = repo.read_gitdir_file(MERGE_INDEX)
        if text is None:
            from kart_tpu.core.repo import InvalidOperation

            raise InvalidOperation(
                "Repository is in 'merging' state but MERGE_INDEX is missing - "
                'run "kart merge --abort" to recover'
            )
        return cls.from_json(json.loads(text))

    # -- resolution ----------------------------------------------------------

    @property
    def unresolved_labels(self):
        return [l for l in self.conflicts if l not in self.resolves]

    def add_resolve(self, label, entries):
        if label not in self.conflicts:
            raise KeyError(label)
        self.resolves[label] = entries

    def write_resolved_tree(self, odb):
        """All conflicts resolved -> final tree oid
        (reference: kart/merge_util.py:294-315)."""
        assert not self.unresolved_labels
        from kart_tpu.core.tree_builder import TreeBuilder

        tb = TreeBuilder(odb, self.merged_tree)
        for label, aot in self.conflicts.items():
            # clear every version's path, then write the resolution
            for entry in aot:
                if entry is not None:
                    tb.remove(entry.path)
            for entry in self.resolves.get(label, ()):
                tb.insert(entry.path, entry.oid)
        return tb.flush()
