"""Built-in EPSG parameter registry — PROJ-free `make_crs("EPSG:n")`.

The reference hands any user CRS string to OSR, which resolves EPSG codes
against the full PROJ database (reference: kart/crs_util.py:17-32). This
rebuild carries no PROJ, so the common codes are synthesized from a compact
parameter table instead: ellipsoids, geographic CRSes (datum + optional
TOWGS84 Helmert), individually-listed projected CRSes, and *families* of
projected CRSes computed from the code (UTM zones for several datums).
Every entry expands to ordinary WKT1 consumed by the same parser/transform
engine as user-supplied WKT, so a table entry behaves exactly like pasting
the full definition.

Scope is deliberate: the projections here are exactly the ones the
transform engine implements (kart_tpu/crs.py `_PROJ_IMPLS` — including
LAEA, Krovak, and both Hotine oblique Mercator variants); a code whose
method the engine lacks is *not* listed — asking for it gives the same
graceful "supply full WKT" error as a truly unknown code, with the
supported families spelled out. tests/test_crs.py's registry-consistency
test enforces that every registered projected CRS resolves AND transforms,
so this contract cannot silently rot.

TOWGS84 values are the standard EPSG single-transformation parameters;
for datums whose official transformation is region-dependent (NAD27, ED50,
SAD69) the well-known single-mean values are used, same as a PROJ
`+towgs84` fallback.
"""

# -- ellipsoids: EPSG code -> (name, semi-major a, inverse flattening) ------

ELLIPSOIDS = {
    7030: ("WGS 84", 6378137.0, 298.257223563),
    7019: ("GRS 1980", 6378137.0, 298.257222101),
    7001: ("Airy 1830", 6377563.396, 299.3249646),
    7004: ("Bessel 1841", 6377397.155, 299.1528128),
    7008: ("Clarke 1866", 6378206.4, 294.978698213898),
    7011: ("Clarke 1880 (IGN)", 6378249.2, 293.4660212936269),
    7022: ("International 1924", 6378388.0, 297.0),
    7024: ("Krassowsky 1940", 6378245.0, 298.3),
    7043: ("WGS 72", 6378135.0, 298.26),
    7050: ("GRS 1967 Modified", 6378160.0, 298.25),
    7016: ("Everest 1830 (1967 Definition)", 6377298.556, 300.8017),
    1024: ("CGCS2000", 6378137.0, 298.257222101),
}

# -- geographic CRSes: EPSG code ->
#    (name, datum name, datum code, ellipsoid code, towgs84|None) ----------

GEOGRAPHIC = {
    4326: ("WGS 84", "WGS_1984", 6326, 7030, None),
    4322: ("WGS 72", "WGS_1972", 6322, 7043, (0, 0, 4.5, 0, 0, 0.554, 0.2263)),
    4258: ("ETRS89", "European_Terrestrial_Reference_System_1989", 6258, 7019, (0, 0, 0)),
    4269: ("NAD83", "North_American_Datum_1983", 6269, 7019, (0, 0, 0)),
    4267: ("NAD27", "North_American_Datum_1927", 6267, 7008, (-8, 160, 176)),
    4283: ("GDA94", "Geocentric_Datum_of_Australia_1994", 6283, 7019, (0, 0, 0)),
    7844: ("GDA2020", "Geocentric_Datum_of_Australia_2020", 1168, 7019, (0, 0, 0)),
    4167: ("NZGD2000", "New_Zealand_Geodetic_Datum_2000", 6167, 7019, (0, 0, 0)),
    4272: (
        "NZGD49",
        "New_Zealand_Geodetic_Datum_1949",
        6272,
        7022,
        (59.47, -5.04, 187.44, 0.47, -0.1, 1.024, -4.5993),
    ),
    4277: (
        "OSGB 1936",
        "OSGB_1936",
        6277,
        7001,
        (446.448, -125.157, 542.06, 0.15, 0.247, 0.842, -20.489),
    ),
    4171: ("RGF93", "Reseau_Geodesique_Francais_1993", 6171, 7019, (0, 0, 0)),
    4230: ("ED50", "European_Datum_1950", 6230, 7022, (-87, -98, -121)),
    4301: ("Tokyo", "Tokyo", 6301, 7004, (-146.414, 507.337, 680.507)),
    4612: ("JGD2000", "Japanese_Geodetic_Datum_2000", 6612, 7019, (0, 0, 0)),
    6668: ("JGD2011", "Japanese_Geodetic_Datum_2011", 1128, 7019, (0, 0, 0)),
    4490: ("China Geodetic Coordinate System 2000", "China_2000", 1043, 1024, None),
    4674: ("SIRGAS 2000", "Sistema_de_Referencia_Geocentrico_para_las_AmericaS_2000", 6674, 7019, (0, 0, 0)),
    4618: ("SAD69", "South_American_Datum_1969", 6618, 7050, (-57, 1, -41)),
    4202: (
        "AGD66",
        "Australian_Geodetic_Datum_1966",
        6202,
        7003,
        (-117.808, -51.536, 137.784, 0.303, 0.446, 0.234, -0.29),
    ),
    4203: (
        "AGD84",
        "Australian_Geodetic_Datum_1984",
        6203,
        7003,
        (-117.763, -51.51, 139.061, -0.292, -0.443, -0.277, -0.191),
    ),
    4312: (
        "MGI",
        "Militar_Geographische_Institut",
        6312,
        7004,
        (577.326, 90.129, 463.919, 5.137, 1.474, 5.297, 2.4232),
    ),
}
# Australian National Spheroid, used by AGD66/84 only
ELLIPSOIDS[7003] = ("Australian National Spheroid", 6378160.0, 298.25)

# -- individually-listed projected CRSes: EPSG code ->
#    (name, geographic code, projection method, {parameter: value}) --------
# Methods are the WKT1 names kart_tpu.crs._PROJ_IMPLS dispatches on.

PROJECTED = {
    3857: (
        "WGS 84 / Pseudo-Mercator",
        4326,
        "Popular_Visualisation_Pseudo_Mercator",
        {"central_meridian": 0, "scale_factor": 1, "false_easting": 0, "false_northing": 0},
    ),
    2193: (
        "NZGD2000 / New Zealand Transverse Mercator 2000",
        4167,
        "Transverse_Mercator",
        {
            "latitude_of_origin": 0,
            "central_meridian": 173,
            "scale_factor": 0.9996,
            "false_easting": 1600000,
            "false_northing": 10000000,
        },
    ),
    27700: (
        "OSGB 1936 / British National Grid",
        4277,
        "Transverse_Mercator",
        {
            "latitude_of_origin": 49,
            "central_meridian": -2,
            "scale_factor": 0.9996012717,
            "false_easting": 400000,
            "false_northing": -100000,
        },
    ),
    2154: (
        "RGF93 / Lambert-93",
        4171,
        "Lambert_Conformal_Conic_2SP",
        {
            "standard_parallel_1": 49,
            "standard_parallel_2": 44,
            "latitude_of_origin": 46.5,
            "central_meridian": 3,
            "false_easting": 700000,
            "false_northing": 6600000,
        },
    ),
    31370: (
        "Belge 1972 / Belgian Lambert 72",
        4313,
        "Lambert_Conformal_Conic_2SP",
        {
            "standard_parallel_1": 51.16666723333333,
            "standard_parallel_2": 49.8333339,
            "latitude_of_origin": 90,
            "central_meridian": 4.367486666666666,
            "false_easting": 150000.013,
            "false_northing": 5400088.438,
        },
    ),
    28992: (
        "Amersfoort / RD New",
        4289,
        "Oblique_Stereographic",
        {
            "latitude_of_origin": 52.15616055555555,
            "central_meridian": 5.38763888888889,
            "scale_factor": 0.9999079,
            "false_easting": 155000,
            "false_northing": 463000,
        },
    ),
    3577: (
        "GDA94 / Australian Albers",
        4283,
        "Albers_Conic_Equal_Area",
        {
            "standard_parallel_1": -18,
            "standard_parallel_2": -36,
            "latitude_of_center": 0,
            "longitude_of_center": 132,
            "false_easting": 0,
            "false_northing": 0,
        },
    ),
    3112: (
        "GDA94 / Geoscience Australia Lambert",
        4283,
        "Lambert_Conformal_Conic_2SP",
        {
            "standard_parallel_1": -18,
            "standard_parallel_2": -36,
            "latitude_of_origin": 0,
            "central_meridian": 134,
            "false_easting": 0,
            "false_northing": 0,
        },
    ),
    5070: (
        "NAD83 / Conus Albers",
        4269,
        "Albers_Conic_Equal_Area",
        {
            "standard_parallel_1": 29.5,
            "standard_parallel_2": 45.5,
            "latitude_of_center": 23,
            "longitude_of_center": -96,
            "false_easting": 0,
            "false_northing": 0,
        },
    ),
    3005: (
        "NAD83 / BC Albers",
        4269,
        "Albers_Conic_Equal_Area",
        {
            "standard_parallel_1": 50,
            "standard_parallel_2": 58.5,
            "latitude_of_center": 45,
            "longitude_of_center": -126,
            "false_easting": 1000000,
            "false_northing": 0,
        },
    ),
    3347: (
        "NAD83 / Statistics Canada Lambert",
        4269,
        "Lambert_Conformal_Conic_2SP",
        {
            "standard_parallel_1": 49,
            "standard_parallel_2": 77,
            "latitude_of_origin": 63.390675,
            "central_meridian": -91.86666666666666,
            "false_easting": 6200000,
            "false_northing": 3000000,
        },
    ),
    3031: (
        "WGS 84 / Antarctic Polar Stereographic",
        4326,
        "Polar_Stereographic_Variant_B",
        {
            "standard_parallel_1": -71,
            "central_meridian": 0,
            "false_easting": 0,
            "false_northing": 0,
        },
    ),
    3413: (
        "WGS 84 / NSIDC Sea Ice Polar Stereographic North",
        4326,
        "Polar_Stereographic_Variant_B",
        {
            "standard_parallel_1": 70,
            "central_meridian": -45,
            "false_easting": 0,
            "false_northing": 0,
        },
    ),
    32661: (
        "WGS 84 / UPS North (N,E)",
        4326,
        "Polar_Stereographic",
        {
            "latitude_of_origin": 90,
            "central_meridian": 0,
            "scale_factor": 0.994,
            "false_easting": 2000000,
            "false_northing": 2000000,
        },
    ),
    32761: (
        "WGS 84 / UPS South (N,E)",
        4326,
        "Polar_Stereographic",
        {
            "latitude_of_origin": -90,
            "central_meridian": 0,
            "scale_factor": 0.994,
            "false_easting": 2000000,
            "false_northing": 2000000,
        },
    ),
    2056: (
        "CH1903+ / LV95",
        4150,
        "Hotine_Oblique_Mercator_Azimuth_Center",
        {
            "latitude_of_center": 46.952405555555565,
            "longitude_of_center": 7.439583333333333,
            "azimuth": 90,
            "rectified_grid_angle": 90,
            "scale_factor": 1,
            "false_easting": 2600000,
            "false_northing": 1200000,
        },
    ),
    21781: (
        "CH1903 / LV03",
        4149,
        "Hotine_Oblique_Mercator_Azimuth_Center",
        {
            "latitude_of_center": 46.952405555555565,
            "longitude_of_center": 7.439583333333333,
            "azimuth": 90,
            "rectified_grid_angle": 90,
            "scale_factor": 1,
            "false_easting": 600000,
            "false_northing": 200000,
        },
    ),
    6933: (
        "WGS 84 / NSIDC EASE-Grid 2.0 Global",
        4326,
        "Lambert_Cylindrical_Equal_Area",
        {
            "standard_parallel_1": 30,
            "central_meridian": 0,
            "false_easting": 0,
            "false_northing": 0,
        },
    ),
    3035: (
        "ETRS89-extended / LAEA Europe",
        4258,
        "Lambert_Azimuthal_Equal_Area",
        {
            "latitude_of_center": 52,
            "longitude_of_center": 10,
            "false_easting": 4321000,
            "false_northing": 3210000,
        },
    ),
    2180: (
        "ETRS89 / Poland CS92",
        4258,
        "Transverse_Mercator",
        {
            "latitude_of_origin": 0,
            "central_meridian": 19,
            "scale_factor": 0.9993,
            "false_easting": 500000,
            "false_northing": -5300000,
        },
    ),
    5514: (
        "S-JTSK / Krovak East North",
        4156,
        "Krovak",
        {
            "latitude_of_center": 49.5,
            "longitude_of_center": 24.833333333333332,
            "azimuth": 30.288139722222223,
            "pseudo_standard_parallel_1": 78.5,
            "scale_factor": 0.9999,
            "false_easting": 0,
            "false_northing": 0,
        },
    ),
    29873: (
        "Timbalai 1948 / RSO Borneo (m)",
        4298,
        "Hotine_Oblique_Mercator_Azimuth_Center",
        {
            "latitude_of_center": 4,
            "longitude_of_center": 115,
            "azimuth": 53.31582047222222,
            "rectified_grid_angle": 53.13010236111111,
            "scale_factor": 0.99984,
            "false_easting": 590476.87,
            "false_northing": 442857.65,
        },
    ),
    3375: (
        "GDM2000 / Peninsula RSO",
        4742,
        "Hotine_Oblique_Mercator",
        {
            "latitude_of_center": 4,
            "longitude_of_center": 102.25,
            "azimuth": 323.0257964666666,
            "rectified_grid_angle": 323.1301023611111,
            "scale_factor": 0.99984,
            "false_easting": 804671,
            "false_northing": 0,
        },
    ),
}
# aliases resolving to the same definition
PROJECTED[3785] = PROJECTED[3857]  # deprecated Popular Visualisation CRS
PROJECTED[900913] = PROJECTED[3857]  # the original "google" code
# geographic CRSes referenced only by the singles above
GEOGRAPHIC[4313] = (
    "Belge 1972",
    "Reseau_National_Belge_1972",
    6313,
    7022,
    (-106.8686, 52.2978, -103.7239, 0.3366, -0.457, 1.8422, -1.2747),
)
GEOGRAPHIC[4289] = (
    "Amersfoort",
    "Amersfoort",
    6289,
    7004,
    (565.417, 50.3319, 465.552, -0.398957, 0.343988, -1.8774, 4.0725),
)
GEOGRAPHIC[4150] = (
    "CH1903+",
    "CH1903+",
    6150,
    7004,
    (674.374, 15.056, 405.346),
)
GEOGRAPHIC[4149] = (
    "CH1903",
    "CH1903",
    6149,
    7004,
    (674.4, 15.1, 405.3),
)
GEOGRAPHIC[4156] = (
    "S-JTSK",
    "System_Jednotne_Trigonometricke_Site_Katastralni",
    6156,
    7004,
    (589, 76, 480),
)
GEOGRAPHIC[4298] = (
    "Timbalai 1948",
    "Timbalai_1948",
    6298,
    7016,
    (-679, 669, -48),
)
GEOGRAPHIC[4742] = (
    "GDM2000",
    "Geodetic_Datum_of_Malaysia_2000",
    6742,
    7019,
    (0, 0, 0),
)

# -- UTM families: (low, high) code range ->
#    (geographic code, zone offset, south?) — zone = code - offset ---------

UTM_FAMILIES = [
    ((32601, 32660), 4326, 32600, False),  # WGS 84 north
    ((32701, 32760), 4326, 32700, True),  # WGS 84 south
    ((25828, 25838), 4258, 25800, False),  # ETRS89
    ((26901, 26923), 4269, 26900, False),  # NAD83
    ((26701, 26722), 4267, 26700, False),  # NAD27 (Clarke 1866)
    ((23028, 23038), 4230, 23000, False),  # ED50 (International 1924)
    ((28348, 28358), 4283, 28300, True),  # GDA94 / MGA
    ((7846, 7859), 7844, 7800, True),  # GDA2020 / MGA
]


def _fmt(v):
    """Float -> shortest exact WKT literal."""
    if isinstance(v, int) or (isinstance(v, float) and v == int(v)):
        return str(int(v))
    return repr(float(v))


def geographic_wkt(code):
    """EPSG geographic code -> WKT1 string, or None when unlisted."""
    entry = GEOGRAPHIC.get(code)
    if entry is None:
        return None
    name, datum, datum_code, ell_code, towgs84 = entry
    ell_name, a, invf = ELLIPSOIDS[ell_code]
    tw = ""
    if towgs84 is not None:
        vals = tuple(towgs84) + (0,) * (7 - len(towgs84))
        tw = f",TOWGS84[{','.join(_fmt(v) for v in vals)}]"
    return (
        f'GEOGCS["{name}",DATUM["{datum}",'
        f'SPHEROID["{ell_name}",{_fmt(a)},{_fmt(invf)},'
        f'AUTHORITY["EPSG","{ell_code}"]]{tw},'
        f'AUTHORITY["EPSG","{datum_code}"]],'
        f'PRIMEM["Greenwich",0,AUTHORITY["EPSG","8901"]],'
        f'UNIT["degree",0.0174532925199433,AUTHORITY["EPSG","9122"]],'
        f'AUTHORITY["EPSG","{code}"]]'
    )


def _projected_wkt(code, name, geog_code, method, params):
    geog = geographic_wkt(geog_code)
    if geog is None:
        return None
    param_wkt = "".join(
        f'PARAMETER["{k}",{_fmt(v)}],' for k, v in params.items()
    )
    return (
        f'PROJCS["{name}",{geog},PROJECTION["{method}"],{param_wkt}'
        f'UNIT["metre",1,AUTHORITY["EPSG","9001"]],'
        f'AUTHORITY["EPSG","{code}"]]'
    )


def _utm_family_wkt(code):
    for (lo, hi), geog_code, offset, south in UTM_FAMILIES:
        if lo <= code <= hi:
            zone = code - offset
            geog_name = GEOGRAPHIC[geog_code][0]
            return _projected_wkt(
                code,
                f"{geog_name} / UTM zone {zone}{'S' if south else 'N'}",
                geog_code,
                "Transverse_Mercator",
                {
                    "latitude_of_origin": 0,
                    "central_meridian": -183 + 6 * zone,
                    "scale_factor": 0.9996,
                    "false_easting": 500000,
                    "false_northing": 10000000 if south else 0,
                },
            )
    return None


def epsg_wkt(code):
    """EPSG code -> WKT1 string, or None when not in the registry."""
    got = geographic_wkt(code)
    if got is not None:
        return got
    entry = PROJECTED.get(code)
    if entry is not None:
        return _projected_wkt(code, *entry)
    return _utm_family_wkt(code)


def registry_summary():
    """Human-readable coverage list for the unknown-code error message."""
    geo = ",".join(str(c) for c in sorted(GEOGRAPHIC))
    proj = ",".join(str(c) for c in sorted(set(PROJECTED)))
    fams = "; ".join(
        f"{lo}-{hi} ({GEOGRAPHIC[g][0]} UTM)" for (lo, hi), g, _, _ in UTM_FAMILIES
    )
    return (
        f"geographic: {geo}; projected: {proj}; UTM families: {fams}"
    )
