"""GPKG working copy over stdlib sqlite3
(reference: kart/working_copy/gpkg.py + base.py).

The working copy is a *derived cache* of one commit's datasets, materialised
as GPKG tables. Change tracking is trigger-based: every user edit records the
row's pk in ``gpkg_kart_track``; ``gpkg_kart_state`` stores the tree id the
copy was checked out from, so ``status``/``diff``/``commit`` only ever look at
tracked rows — never a full table scan (reference: base.py:118-158).

The feature compare (WC row vs dataset row) batches tracked rows and compares
value tuples; at GPKG scale the tracked set is the user's edit set, which is
small relative to the dataset, so this stays on the host path — the columnar
device compare handles the bulk reset/import directions.
"""

import contextlib
import os
import sqlite3

from kart_tpu.adapters import gpkg as adapter
from kart_tpu.core.odb import ObjectPromised
from kart_tpu.core.repo import InvalidOperation, NotFound
from kart_tpu.crs import get_identifier_int, get_identifier_str
from kart_tpu.diff.structs import (
    WORKING_COPY_EDIT,
    DatasetDiff,
    Delta,
    DeltaDiff,
    KeyValue,
)
from kart_tpu.geometry import Geometry
from kart_tpu.models.schema import Schema
from kart_tpu.workingcopy import WorkingCopyStatus, checkout_features

STATE_TABLE = "gpkg_kart_state"
TRACK_TABLE = "gpkg_kart_track"

_GPKG_BASE_DDL = """
CREATE TABLE IF NOT EXISTS gpkg_contents (
    table_name TEXT NOT NULL PRIMARY KEY, data_type TEXT NOT NULL,
    identifier TEXT UNIQUE, description TEXT DEFAULT '',
    last_change DATETIME NOT NULL DEFAULT (strftime('%Y-%m-%dT%H:%M:%fZ','now')),
    min_x DOUBLE, min_y DOUBLE, max_x DOUBLE, max_y DOUBLE, srs_id INTEGER);
CREATE TABLE IF NOT EXISTS gpkg_geometry_columns (
    table_name TEXT NOT NULL, column_name TEXT NOT NULL,
    geometry_type_name TEXT NOT NULL, srs_id INTEGER NOT NULL,
    z TINYINT NOT NULL, m TINYINT NOT NULL,
    CONSTRAINT pk_geom_cols PRIMARY KEY (table_name, column_name));
CREATE TABLE IF NOT EXISTS gpkg_spatial_ref_sys (
    srs_name TEXT NOT NULL, srs_id INTEGER NOT NULL PRIMARY KEY,
    organization TEXT NOT NULL, organization_coordsys_id INTEGER NOT NULL,
    definition TEXT NOT NULL, description TEXT);
CREATE TABLE IF NOT EXISTS gpkg_kart_state (
    table_name TEXT NOT NULL, key TEXT NOT NULL, value TEXT NULL,
    CONSTRAINT _kart_state_pk PRIMARY KEY (table_name, key));
CREATE TABLE IF NOT EXISTS gpkg_kart_track (
    table_name TEXT NOT NULL, pk TEXT NULL,
    CONSTRAINT _kart_track_pk PRIMARY KEY (table_name, pk));
"""

_DEFAULT_SRS = [
    ("Undefined cartesian SRS", -1, "NONE", -1, "undefined", None),
    ("Undefined geographic SRS", 0, "NONE", 0, "undefined", None),
]


class Mismatch(InvalidOperation):
    def __init__(self, wc_tree, expected_tree):
        super().__init__(
            f"Working copy is out of sync with repository: working copy has tree "
            f"{wc_tree}, repository expects {expected_tree}. "
            f'Use "kart checkout --force HEAD" to reset the working copy.'
        )
        self.wc_tree = wc_tree
        self.expected_tree = expected_tree


def _geom_envelope(value, _memo=None):
    """GPKG blob -> (minx, maxx, miny, maxy) or None for NULL/empty/garbage.

    _memo: optional per-connection one-slot [blob, envelope] cache — the
    rtree triggers call ST_MinX/MaxX/MinY/MaxY (+IsEmpty) on the SAME blob
    for each row, and a bulk checkout fires them a million times. The memo
    is scoped to one sqlite connection (created in
    _register_gpkg_functions), so concurrent connections can't cross-read
    each other's slot."""
    if value is None:
        return None
    b = bytes(value)
    if _memo is not None and _memo[0] == b:
        return _memo[1]
    try:
        env = Geometry.of(b).envelope()
    except Exception:
        env = None
    if _memo is not None:
        _memo[0] = b
        _memo[1] = env
    return env


def _register_gpkg_functions(con):
    """The GPKG rtree-extension triggers call ST_IsEmpty/ST_MinX/... —
    provided by spatialite/GDAL in other clients; here backed by our own
    envelope parser so the triggers fire correctly on our connections."""
    memo = [None, None]  # per-connection: sqlite is serial per connection

    def st_is_empty(value):
        return 1 if _geom_envelope(value, memo) is None else 0

    def bound(i):
        def f(value):
            env = _geom_envelope(value, memo)
            return env[i] if env is not None else None

        return f

    con.create_function("ST_IsEmpty", 1, st_is_empty, deterministic=True)
    con.create_function("ST_MinX", 1, bound(0), deterministic=True)
    con.create_function("ST_MaxX", 1, bound(1), deterministic=True)
    con.create_function("ST_MinY", 1, bound(2), deterministic=True)
    con.create_function("ST_MaxY", 1, bound(3), deterministic=True)


class GpkgWorkingCopy:
    def __init__(self, repo, location):
        self.repo = repo
        # {ds_path: [pks]} filled during WC diffs on a filtered clone
        self.spatial_filter_pk_conflicts = {}
        self.location = str(location)
        if os.path.isabs(self.location) or repo.workdir is None:
            self.full_path = self.location
        else:
            self.full_path = os.path.join(repo.workdir, self.location)

    @property
    def clean_location(self):
        return self.location

    def __str__(self):
        return self.location

    # -- connection ----------------------------------------------------------

    @contextlib.contextmanager
    def session(self):
        con = sqlite3.connect(self.full_path)
        con.row_factory = sqlite3.Row
        _register_gpkg_functions(con)
        con.execute("PRAGMA foreign_keys = OFF;")
        try:
            con.execute("BEGIN")
            yield con
            con.commit()
        except Exception:
            con.rollback()
            raise
        finally:
            con.close()

    # -- status / state ------------------------------------------------------

    def status(self):
        result = 0
        if not os.path.exists(self.full_path):
            return WorkingCopyStatus.NON_EXISTENT
        result |= WorkingCopyStatus.CREATED
        try:
            with self.session() as con:
                has_state = con.execute(
                    "SELECT count(*) FROM sqlite_master WHERE name = ?",
                    (STATE_TABLE,),
                ).fetchone()[0]
                if has_state:
                    result |= WorkingCopyStatus.INITIALISED
                tables = con.execute(
                    "SELECT count(*) FROM sqlite_master WHERE type='table' "
                    "AND name NOT LIKE 'gpkg_%' AND name NOT LIKE 'sqlite_%'"
                ).fetchone()[0]
                if tables:
                    result |= WorkingCopyStatus.HAS_DATA
        except sqlite3.DatabaseError:
            result |= WorkingCopyStatus.UNCONNECTABLE
        return result

    def create_and_initialise(self):
        os.makedirs(os.path.dirname(self.full_path) or ".", exist_ok=True)
        with self.session() as con:
            con.executescript(_GPKG_BASE_DDL)
            for row in _DEFAULT_SRS:
                con.execute(
                    "INSERT OR IGNORE INTO gpkg_spatial_ref_sys VALUES (?,?,?,?,?,?)",
                    row,
                )

    def delete(self):
        if os.path.exists(self.full_path):
            os.remove(self.full_path)

    def get_db_tree(self):
        with self.session() as con:
            try:
                row = con.execute(
                    f"SELECT value FROM {STATE_TABLE} WHERE table_name = '*' AND key = 'tree'"
                ).fetchone()
            except sqlite3.OperationalError:
                return None
            return row[0] if row else None

    def assert_db_tree_match(self, expected_tree_oid):
        wc_tree = self.get_db_tree()
        expected = expected_tree_oid.oid if hasattr(expected_tree_oid, "oid") else expected_tree_oid
        if wc_tree != expected:
            raise Mismatch(wc_tree, expected)

    def _update_state_tree(self, con, tree_oid):
        con.execute(
            f"INSERT OR REPLACE INTO {STATE_TABLE} (table_name, key, value) "
            f"VALUES ('*', 'tree', ?)",
            (tree_oid,),
        )

    # -- table naming --------------------------------------------------------

    @staticmethod
    def _table_name(ds_path):
        """dataset path -> GPKG table name (slashes become underscores)."""
        return ds_path.replace("/", "__")

    def _ds_path_for_table(self, table_name, ds_paths):
        for p in ds_paths:
            if self._table_name(p) == table_name:
                return p
        return None

    # -- checkout (write_full) ----------------------------------------------

    def write_full(self, target_structure, *datasets):
        """Bulk checkout of datasets into the WC; records the target tree
        (reference: base.py:899-966)."""
        if not (self.status() & WorkingCopyStatus.INITIALISED):
            self.create_and_initialise()
        with self.session() as con:
            for ds in datasets:
                self._write_one_dataset(con, ds)
            self._update_state_tree(con, target_structure.tree_oid)

    def _write_one_dataset(self, con, ds):
        table = self._table_name(ds.path)
        schema = ds.schema
        crs_id = 0
        geom_col = schema.first_geometry_column
        crs_defs = {}
        for ident in ds.crs_identifiers():
            crs_defs[ident] = ds.get_crs_definition(ident)
        if geom_col is not None and crs_defs:
            first_wkt = next(iter(crs_defs.values()))
            crs_id = get_identifier_int(first_wkt)

        # register CRS
        for ident, wkt in crs_defs.items():
            srs_id = get_identifier_int(wkt)
            org, _, code = ident.partition(":")
            con.execute(
                "INSERT OR REPLACE INTO gpkg_spatial_ref_sys "
                "(srs_name, srs_id, organization, organization_coordsys_id, definition) "
                "VALUES (?,?,?,?,?)",
                (ident, srs_id, org or "NONE", int(code) if code.isdigit() else srs_id, wkt),
            )

        con.execute(f"DROP TABLE IF EXISTS {adapter.quote(table)}")
        self._drop_spatial_index(con, table)
        con.execute(
            f"CREATE TABLE {adapter.quote(table)} ({adapter.v2_schema_to_sql_spec(schema)})"
        )

        title = ds.get_meta_item("title") or table
        description = ds.get_meta_item("description") or ""
        data_type = "features" if geom_col is not None else "attributes"
        con.execute(
            "INSERT OR REPLACE INTO gpkg_contents "
            "(table_name, data_type, identifier, description, srs_id) VALUES (?,?,?,?,?)",
            (table, data_type, title, description, crs_id if geom_col is not None else None),
        )
        if geom_col is not None:
            gtype = geom_col.extra_type_info.get("geometryType", "GEOMETRY").split(" ")
            has_z = 1 if "Z" in gtype[1:] or "ZM" in gtype[1:] else 0
            has_m = 1 if "M" in gtype[1:] or "ZM" in gtype[1:] else 0
            con.execute(
                "INSERT OR REPLACE INTO gpkg_geometry_columns VALUES (?,?,?,?,?,?)",
                (table, geom_col.name, gtype[0], crs_id, has_z, has_m),
            )

        # bulk insert in chunks
        col_names = [c.name for c in schema.columns]
        placeholders = ",".join("?" for _ in col_names)
        quoted_cols = ",".join(adapter.quote(c) for c in col_names)
        insert_sql = (
            f"INSERT INTO {adapter.quote(table)} ({quoted_cols}) VALUES ({placeholders})"
        )
        batch = []
        for feature in checkout_features(self.repo, ds):
            batch.append(
                tuple(
                    adapter.value_from_v2(feature[c.name], c, crs_id=crs_id)
                    for c in schema.columns
                )
            )
            if len(batch) >= 10000:
                con.executemany(insert_sql, batch)
                batch.clear()
        if batch:
            con.executemany(insert_sql, batch)

        # autoincrement sequence: next insert gets an unused pk
        pk_cols = schema.pk_columns
        if len(pk_cols) == 1 and pk_cols[0].data_type == "integer":
            row = con.execute(
                f"SELECT MAX({adapter.quote(pk_cols[0].name)}) FROM {adapter.quote(table)}"
            ).fetchone()
            if row[0] is not None:
                con.execute(
                    "INSERT OR REPLACE INTO sqlite_sequence (name, seq) VALUES (?, ?)",
                    (table, row[0]),
                )

        if (
            geom_col is not None
            and len(pk_cols) == 1
            and pk_cols[0].data_type == "integer"
        ):
            self._create_spatial_index(con, table, geom_col.name, pk_cols[0].name)

        self._create_triggers(con, table, schema)

    def _drop_spatial_index(self, con, table):
        """Drop the rtree index of a previous checkout of this table (DROP
        TABLE on the base table doesn't cascade to the rtree). The exact
        index names come from gpkg_extensions/gpkg_geometry_columns — a
        prefix match would hit another table like '<table>_old'. Dropping
        the virtual table drops its shadow _node/_rowid/_parent tables."""
        geom_cols = set()
        if self._table_exists_in_master(con, "gpkg_extensions"):
            geom_cols.update(
                row[0]
                for row in con.execute(
                    "SELECT column_name FROM gpkg_extensions "
                    "WHERE table_name = ? AND extension_name = 'gpkg_rtree_index'",
                    (table,),
                ).fetchall()
                if row[0]
            )
        if self._table_exists_in_master(con, "gpkg_geometry_columns"):
            geom_cols.update(
                row[0]
                for row in con.execute(
                    "SELECT column_name FROM gpkg_geometry_columns "
                    "WHERE table_name = ?",
                    (table,),
                ).fetchall()
            )
        for col in geom_cols:
            name = f"rtree_{table}_{col}"
            if self._table_exists_in_master(con, name):
                con.execute(f"DROP TABLE IF EXISTS {adapter.quote(name)}")
        if self._table_exists_in_master(con, "gpkg_extensions"):
            con.execute(
                "DELETE FROM gpkg_extensions WHERE table_name = ? "
                "AND extension_name = 'gpkg_rtree_index'",
                (table,),
            )

    @staticmethod
    def _table_exists_in_master(con, name):
        return (
            con.execute(
                "SELECT 1 FROM sqlite_master WHERE name = ?", (name,)
            ).fetchone()
            is not None
        )

    def _create_spatial_index(self, con, table, geom_name, pk_name):
        """GPKG rtree spatial index: the standard gpkg_rtree_index extension
        (rtree virtual table + sync triggers), so spatial clients get fast
        bbox queries on the WC (reference: gpkgAddSpatialIndex,
        kart/working_copy/gpkg.py:432-476)."""
        rtree = adapter.quote(f"rtree_{table}_{geom_name}")
        qt = adapter.quote(table)
        qg = adapter.quote(geom_name)
        qi = adapter.quote(pk_name)

        con.execute(
            f"CREATE VIRTUAL TABLE {rtree} USING rtree(id, minx, maxx, miny, maxy)"
        )
        con.execute(
            f"INSERT OR REPLACE INTO {rtree} "
            f"SELECT {qi}, ST_MinX({qg}), ST_MaxX({qg}), ST_MinY({qg}), ST_MaxY({qg}) "
            f"FROM {qt} WHERE {qg} NOT NULL AND NOT ST_IsEmpty({qg})"
        )

        con.execute(
            """CREATE TABLE IF NOT EXISTS gpkg_extensions (
                table_name TEXT, column_name TEXT, extension_name TEXT NOT NULL,
                definition TEXT NOT NULL, scope TEXT NOT NULL,
                CONSTRAINT ge_tce UNIQUE (table_name, column_name, extension_name))"""
        )
        con.execute(
            "INSERT OR REPLACE INTO gpkg_extensions VALUES "
            "(?, ?, 'gpkg_rtree_index', "
            "'http://www.geopackage.org/spec120/#extension_rtree', 'write-only')",
            (table, geom_name),
        )

        # the six sync triggers from the GPKG spec (Annex F.3)
        def trig(suffix):
            return adapter.quote(f"rtree_{table}_{geom_name}_{suffix}")

        not_empty = f"(NEW.{qg} NOT NULL AND NOT ST_IsEmpty(NEW.{qg}))"
        is_empty = f"(NEW.{qg} ISNULL OR ST_IsEmpty(NEW.{qg}))"
        upsert = (
            f"INSERT OR REPLACE INTO {rtree} VALUES (NEW.{qi}, "
            f"ST_MinX(NEW.{qg}), ST_MaxX(NEW.{qg}), "
            f"ST_MinY(NEW.{qg}), ST_MaxY(NEW.{qg}));"
        )
        con.execute(
            f"CREATE TRIGGER {trig('insert')} AFTER INSERT ON {qt} "
            f"WHEN {not_empty} BEGIN {upsert} END;"
        )
        con.execute(
            f"CREATE TRIGGER {trig('update1')} AFTER UPDATE OF {qg} ON {qt} "
            f"WHEN OLD.{qi} = NEW.{qi} AND {not_empty} BEGIN {upsert} END;"
        )
        con.execute(
            f"CREATE TRIGGER {trig('update2')} AFTER UPDATE OF {qg} ON {qt} "
            f"WHEN OLD.{qi} = NEW.{qi} AND {is_empty} "
            f"BEGIN DELETE FROM {rtree} WHERE id = OLD.{qi}; END;"
        )
        con.execute(
            f"CREATE TRIGGER {trig('update3')} AFTER UPDATE ON {qt} "
            f"WHEN OLD.{qi} != NEW.{qi} AND {not_empty} "
            f"BEGIN DELETE FROM {rtree} WHERE id = OLD.{qi}; {upsert} END;"
        )
        con.execute(
            f"CREATE TRIGGER {trig('update4')} AFTER UPDATE ON {qt} "
            f"WHEN OLD.{qi} != NEW.{qi} AND {is_empty} "
            f"BEGIN DELETE FROM {rtree} WHERE id IN (OLD.{qi}, NEW.{qi}); END;"
        )
        con.execute(
            f"CREATE TRIGGER {trig('delete')} AFTER DELETE ON {qt} "
            f"BEGIN DELETE FROM {rtree} WHERE id = OLD.{qi}; END;"
        )

    def _create_triggers(self, con, table, schema):
        """Edit tracking (reference: gpkg.py:498-554)."""
        pk = adapter.quote(schema.pk_columns[0].name) if schema.pk_columns else "rowid"
        qt = adapter.quote(table)
        lit = adapter.string_literal(table)
        for suffix in ("ins", "upd", "del"):
            con.execute(
                f"DROP TRIGGER IF EXISTS "
                f"{adapter.quote(f'trigger_kart_{table}_{suffix}')}"
            )
        con.execute(
            f"CREATE TRIGGER {adapter.quote(f'trigger_kart_{table}_ins')} "
            f"AFTER INSERT ON {qt} BEGIN "
            f"INSERT OR REPLACE INTO {TRACK_TABLE} (table_name, pk) VALUES ({lit}, NEW.{pk}); END;"
        )
        con.execute(
            f"CREATE TRIGGER {adapter.quote(f'trigger_kart_{table}_upd')} "
            f"AFTER UPDATE ON {qt} BEGIN "
            f"INSERT OR REPLACE INTO {TRACK_TABLE} (table_name, pk) VALUES ({lit}, NEW.{pk}); "
            f"INSERT OR REPLACE INTO {TRACK_TABLE} (table_name, pk) VALUES ({lit}, OLD.{pk}); END;"
        )
        con.execute(
            f"CREATE TRIGGER {adapter.quote(f'trigger_kart_{table}_del')} "
            f"AFTER DELETE ON {qt} BEGIN "
            f"INSERT OR REPLACE INTO {TRACK_TABLE} (table_name, pk) VALUES ({lit}, OLD.{pk}); END;"
        )

    @contextlib.contextmanager
    def _suspended_triggers(self, con, table):
        """Disable tracking while kart itself writes (reference: base.py uses
        a session-level flag; sqlite needs drop/recreate)."""
        for suffix in ("ins", "upd", "del"):
            con.execute(
                f"DROP TRIGGER IF EXISTS "
                f"{adapter.quote(f'trigger_kart_{table}_{suffix}')}"
            )
        yield
        # recreated by caller via _create_triggers

    # -- reading the WC ------------------------------------------------------

    def _wc_schema_for_table(self, con, table):
        """Current table DDL -> V2 schema (ids are fresh; align against the
        dataset schema before diffing)."""
        geom_info = None
        row = con.execute(
            "SELECT column_name, geometry_type_name, srs_id, z, m "
            "FROM gpkg_geometry_columns WHERE table_name = ?",
            (table,),
        ).fetchone()
        crs_identifier = None
        if row:
            srs = con.execute(
                "SELECT * FROM gpkg_spatial_ref_sys WHERE srs_id = ?",
                (row["srs_id"],),
            ).fetchone()
            if srs and srs["srs_id"] > 0:
                crs_identifier = (
                    f"{srs['organization']}:{srs['organization_coordsys_id']}"
                    if srs["organization"] and srs["organization"] != "NONE"
                    else get_identifier_str(srs["definition"])
                )
            geom_info = {**dict(row), "crs_identifier": crs_identifier}

        from kart_tpu.models.schema import ColumnSchema

        cols = []
        for info in con.execute(f"PRAGMA table_info({adapter.quote(table)})"):
            name = info["name"]
            is_geom = geom_info is not None and name == geom_info["column_name"]
            data_type, extra = adapter.sqlite_type_to_v2(
                info["type"], geom_info=geom_info if is_geom else None
            )
            pk_index = info["pk"] - 1 if info["pk"] > 0 else None
            if pk_index is not None and data_type == "integer":
                extra = {**extra, "size": 64}
            cols.append(
                ColumnSchema(ColumnSchema.new_id(), name, data_type, pk_index, extra)
            )
        return Schema(cols)

    def _wc_meta_items(self, con, table, aligned_schema, dataset_title=None):
        out = {"schema.json": aligned_schema.to_column_dicts()}
        row = con.execute(
            "SELECT identifier, description, srs_id FROM gpkg_contents WHERE table_name = ?",
            (table,),
        ).fetchone()
        if row:
            # identifier falls back to the table name on write when the
            # dataset has no title: reading that default back is not a user
            # edit — but a dataset title that legitimately *equals* the table
            # name must still roundtrip (reference: gpkg.py:298-390
            # title/identifier approximation fixups)
            if row["identifier"]:
                if row["identifier"] != table or dataset_title == table:
                    out["title"] = row["identifier"]
            if row["description"]:
                out["description"] = row["description"]
        geom = con.execute(
            "SELECT srs_id FROM gpkg_geometry_columns WHERE table_name = ?", (table,)
        ).fetchone()
        if geom is not None:
            srs = con.execute(
                "SELECT * FROM gpkg_spatial_ref_sys WHERE srs_id = ?",
                (geom["srs_id"],),
            ).fetchone()
            if srs and srs["srs_id"] > 0:
                ident = (
                    f"{srs['organization']}:{srs['organization_coordsys_id']}"
                    if srs["organization"] and srs["organization"] != "NONE"
                    else get_identifier_str(srs["definition"])
                )
                out[f"crs/{ident}.wkt"] = srs["definition"]
        return out

    # -- diffing -------------------------------------------------------------

    def diff_dataset_to_working_copy(self, dataset, ds_filter=None, workdir_diff_cache=None):
        """DatasetDiff dataset -> current WC state. Only tracked rows are
        examined (reference: base.py:498-768)."""
        table = self._table_name(dataset.path)
        result = DatasetDiff()
        with self.session() as con:
            exists = con.execute(
                "SELECT count(*) FROM sqlite_master WHERE name = ?", (table,)
            ).fetchone()[0]
            if not exists:
                return result
            result["meta"] = self._diff_meta(con, dataset, table)
            new_schema = dataset.schema
            if "schema.json" in result["meta"]:
                new_schema = Schema.from_column_dicts(
                    result["meta"]["schema.json"].new_value
                )
            result["feature"] = self._diff_features(
                con, dataset, table, new_schema, ds_filter
            )
        from kart_tpu.workingcopy import can_find_renames, find_renames

        if can_find_renames(dataset, result["meta"]):
            find_renames(result["feature"], dataset)
        result.prune()
        return result

    def _diff_meta(self, con, dataset, table):
        wc_schema = self._wc_schema_for_table(con, table)
        aligned = dataset.schema.align_to_self(
            wc_schema, roundtrip_ctx=adapter.GpkgRoundtripContext
        )
        ds_items = dataset.meta_items()
        wc_items = self._wc_meta_items(
            con, table, aligned, dataset_title=ds_items.get("title")
        )
        out = DeltaDiff()
        for name in sorted(set(ds_items) | set(wc_items)):
            if name == "metadata.xml":
                continue  # attachments don't roundtrip through the WC
            old = ds_items.get(name)
            new = wc_items.get(name)
            if old == new:
                continue
            out.add_delta(
                Delta(
                    KeyValue((name, old)) if old is not None else None,
                    KeyValue((name, new)) if new is not None else None,
                    flags=WORKING_COPY_EDIT,
                )
            )
        return out

    def _diff_features(self, con, dataset, table, wc_schema, ds_filter):
        feature_filter = ds_filter["feature"] if ds_filter is not None else None
        out = DeltaDiff()
        pk_col = dataset.schema.pk_columns[0]
        geom_cols = {c.name for c in wc_schema.columns if c.data_type == "geometry"}
        tracked = [
            row["pk"]
            for row in con.execute(
                f"SELECT pk FROM {TRACK_TABLE} WHERE table_name = ?", (table,)
            )
        ]
        if not tracked:
            return out
        quoted = adapter.quote(pk_col.name)
        for chunk_start in range(0, len(tracked), 500):
            chunk = tracked[chunk_start : chunk_start + 500]
            placeholders = ",".join("?" for _ in chunk)
            rows = {
                row[pk_col.name]: row
                for row in con.execute(
                    f"SELECT * FROM {adapter.quote(table)} WHERE {quoted} IN ({placeholders})",
                    chunk,
                )
            }
            for raw_pk in chunk:
                pk = dataset.schema.sanitise_pks(raw_pk)[0]
                key = pk
                if feature_filter is not None and key not in feature_filter:
                    continue
                try:
                    old_feature = dataset.get_feature([pk])
                except ObjectPromised:
                    # pk collides with an out-of-filter (promised) feature:
                    # committing would overwrite it (reference: spatial
                    # filter PK conflict, kart/commit.py:40-74)
                    old_feature = None
                    self.spatial_filter_pk_conflicts.setdefault(
                        dataset.path, []
                    ).append(pk)
                except KeyError:
                    old_feature = None
                row = rows.get(pk)
                new_feature = None
                if row is not None:
                    new_feature = {
                        c.name: adapter.value_to_v2(row[c.name], c)
                        for c in wc_schema.columns
                        if c.name in row.keys()
                    }
                    for g in geom_cols & set(new_feature):
                        if isinstance(new_feature[g], Geometry):
                            new_feature[g] = new_feature[g].normalised()
                if old_feature is None and new_feature is None:
                    continue
                if old_feature == new_feature:
                    continue
                out.add_delta(
                    Delta(
                        KeyValue((key, old_feature)) if old_feature is not None else None,
                        KeyValue((key, new_feature)) if new_feature is not None else None,
                        flags=WORKING_COPY_EDIT,
                    )
                )
        return out

    def is_dirty(self):
        if not (self.status() & WorkingCopyStatus.INITIALISED):
            return False
        tree = self.get_db_tree()
        if tree is None:
            return False
        try:
            rs = self.repo.structure(tree)
        except NotFound:
            return False
        for ds in rs.datasets:
            if self.diff_dataset_to_working_copy(ds):
                return True
        return False

    # -- state updates after commit/checkout ----------------------------------

    def reset_tracking_table(self, repo_key_filter=None):
        with self.session() as con:
            if repo_key_filter is None or repo_key_filter.match_all:
                con.execute(f"DELETE FROM {TRACK_TABLE}")
            else:
                for ds_path in repo_key_filter.ds_paths():
                    ds_filter = repo_key_filter[ds_path]
                    table = self._table_name(ds_path)
                    feature_filter = ds_filter["feature"]
                    if ds_filter.match_all or feature_filter.match_all:
                        con.execute(
                            f"DELETE FROM {TRACK_TABLE} WHERE table_name = ?", (table,)
                        )
                    else:
                        for pk in feature_filter.keys:
                            con.execute(
                                f"DELETE FROM {TRACK_TABLE} WHERE table_name = ? AND pk = ?",
                                (table, str(pk)),
                            )

    def update_state_table_tree(self, tree_oid):
        with self.session() as con:
            self._update_state_tree(con, tree_oid)

    # -- reset / checkout ------------------------------------------------------

    def reset(self, target_structure, *, force=False, repo_key_filter=None,
              track_changes_as_dirty=False):
        """Move the WC to target revision. Without force, uncommitted tracked
        changes for unaffected features are preserved; structural changes use
        drop-and-rewrite (reference: base.py:1099-1338)."""
        from kart_tpu.diff.engine import get_dataset_diff

        current_tree = self.get_db_tree()
        if current_tree is None:
            self.write_full(target_structure, *target_structure.datasets)
            return
        if force:
            self.write_full(target_structure, *target_structure.datasets)
            with self.session() as con:
                con.execute(f"DELETE FROM {TRACK_TABLE}")
            return

        base_rs = self.repo.structure(current_tree)
        base_paths = set(base_rs.datasets.paths())
        target_paths = set(target_structure.datasets.paths())

        with self.session() as con:
            # datasets removed in target
            for ds_path in sorted(base_paths - target_paths):
                table = self._table_name(ds_path)
                self._drop_spatial_index(con, table)
                con.execute(f"DROP TABLE IF EXISTS {adapter.quote(table)}")
                con.execute("DELETE FROM gpkg_contents WHERE table_name = ?", (table,))
                con.execute(
                    "DELETE FROM gpkg_geometry_columns WHERE table_name = ?", (table,)
                )
                con.execute(f"DELETE FROM {TRACK_TABLE} WHERE table_name = ?", (table,))
            # new datasets
            for ds_path in sorted(target_paths - base_paths):
                self._write_one_dataset(con, target_structure.datasets[ds_path])
            # changed datasets: apply the tree diff as SQL
            for ds_path in sorted(base_paths & target_paths):
                base_ds = base_rs.datasets[ds_path]
                target_ds = target_structure.datasets[ds_path]
                ds_diff = get_dataset_diff(base_rs, target_structure, ds_path)
                if not ds_diff:
                    continue
                if "meta" in ds_diff and ds_diff["meta"]:
                    # schema/meta changed: simplest correct behaviour is rewrite
                    self._write_one_dataset(con, target_ds)
                    con.execute(
                        f"DELETE FROM {TRACK_TABLE} WHERE table_name = ?",
                        (self._table_name(ds_path),),
                    )
                    continue
                self._apply_feature_diff_sql(
                    con, target_ds, ds_diff.get("feature", {}),
                    track_changes_as_dirty=track_changes_as_dirty,
                )
            self._update_state_tree(con, target_structure.tree_oid)

    def _apply_feature_diff_sql(self, con, dataset, feature_diff, *,
                                track_changes_as_dirty=False):
        table = self._table_name(dataset.path)
        schema = dataset.schema
        crs_id = 0
        crs_ids = dataset.crs_identifiers()
        if schema.first_geometry_column is not None and crs_ids:
            crs_id = get_identifier_int(dataset.get_crs_definition(crs_ids[0]))
        pk_col = schema.pk_columns[0]
        if not track_changes_as_dirty:
            # suspend triggers so kart's own writes aren't tracked
            for suffix in ("ins", "upd", "del"):
                con.execute(
                    f"DROP TRIGGER IF EXISTS "
                    f"{adapter.quote(f'trigger_kart_{table}_{suffix}')}"
                )
        try:
            col_names = [c.name for c in schema.columns]
            quoted_cols = ",".join(adapter.quote(c) for c in col_names)
            placeholders = ",".join("?" for _ in col_names)
            for delta in feature_diff.values():
                if delta.new is None:
                    con.execute(
                        f"DELETE FROM {adapter.quote(table)} WHERE {adapter.quote(pk_col.name)} = ?",
                        (delta.old_key,),
                    )
                else:
                    try:
                        new_value = delta.new_value
                    except ObjectPromised:
                        # partial clone: the target feature is out-of-filter
                        # -> it must not be materialised; drop any stale row
                        con.execute(
                            f"DELETE FROM {adapter.quote(table)} WHERE {adapter.quote(pk_col.name)} = ?",
                            (delta.new_key,),
                        )
                        continue
                    values = tuple(
                        adapter.value_from_v2(new_value[c.name], c, crs_id=crs_id)
                        for c in schema.columns
                    )
                    con.execute(
                        f"INSERT OR REPLACE INTO {adapter.quote(table)} "
                        f"({quoted_cols}) VALUES ({placeholders})",
                        values,
                    )
        finally:
            if not track_changes_as_dirty:
                self._create_triggers(con, table, schema)

    def soft_reset_after_commit(self, new_tree_oid, repo_key_filter=None):
        """After committing WC changes: clear tracking, bump state tree."""
        self.reset_tracking_table(repo_key_filter)
        self.update_state_table_tree(new_tree_oid)
