"""Working copies: mutable database mirrors of the ODB state
(reference: kart/working_copy/).

The GPKG working copy (stdlib sqlite3) is the default; server-DB working
copies (PostGIS / SQL Server / MySQL) are gated on their drivers being
installed.
"""

from enum import Enum, IntFlag


class WorkingCopyType(Enum):
    GPKG = "gpkg"
    POSTGIS = "postgis"
    SQL_SERVER = "sqlserver"
    MYSQL = "mysql"

    @classmethod
    def from_location(cls, location):
        location = str(location)
        if location.startswith("postgresql:"):
            return cls.POSTGIS
        if location.startswith("mssql:"):
            return cls.SQL_SERVER
        if location.startswith("mysql:"):
            return cls.MYSQL
        if location.lower().endswith(".gpkg"):
            return cls.GPKG
        from kart_tpu.core.repo import InvalidOperation

        raise InvalidOperation(
            f"Unrecognised working copy location: {location!r} "
            f"(expected a .gpkg path or a postgresql://, mssql://, mysql:// URL)"
        )


class WorkingCopyStatus(IntFlag):
    UNCONNECTABLE = 0x1
    NON_EXISTENT = 0x2
    CREATED = 0x4
    INITIALISED = 0x8
    HAS_DATA = 0x10
    DIRTY = 0x20


def checkout_features(repo, ds):
    """Features to materialise in a working copy: the repo's spatial filter
    applied, promised (out-of-filter) blobs skipped — a filtered clone's WC
    holds only in-filter features (reference: kart/checkout.py +
    kart/working_copy/base.py write_full)."""
    from kart_tpu.spatial_filter import ResolvedSpatialFilterSpec

    spec = ResolvedSpatialFilterSpec.from_repo_config(repo)
    sf = spec.resolve_for_dataset(ds)
    return ds.features(
        spatial_filter=sf if sf else None,
        skip_promised=repo.has_promisor_remote(),
    )


def get_working_copy(repo, allow_uncreated=False):
    """-> the repo's working copy instance, or None when no location is
    configured (bare repos) or nothing exists there yet."""
    from kart_tpu.core.repo import KartConfigKeys

    location = repo.config.get(KartConfigKeys.KART_WORKINGCOPY_LOCATION)
    if location is None and not repo.is_bare:
        location = default_location(repo)
    if location is None:
        return None
    wc_type = WorkingCopyType.from_location(location)
    if wc_type is WorkingCopyType.GPKG:
        from kart_tpu.workingcopy.gpkg import GpkgWorkingCopy

        wc = GpkgWorkingCopy(repo, location)
    elif wc_type is WorkingCopyType.POSTGIS:
        from kart_tpu.workingcopy.postgis import PostgisWorkingCopy

        wc = PostgisWorkingCopy(repo, location)
    elif wc_type is WorkingCopyType.SQL_SERVER:
        from kart_tpu.workingcopy.sqlserver import SqlServerWorkingCopy

        wc = SqlServerWorkingCopy(repo, location)
    else:
        from kart_tpu.workingcopy.mysql import MySqlWorkingCopy

        wc = MySqlWorkingCopy(repo, location)
    if not allow_uncreated and not (wc.status() & (WorkingCopyStatus.INITIALISED)):
        return None
    return wc


def default_location(repo):
    import os

    if repo.workdir is None:
        return None
    name = os.path.basename(repo.workdir) or "data"
    return f"{name}.gpkg"
