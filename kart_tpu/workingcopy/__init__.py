"""Working copies: mutable database mirrors of the ODB state
(reference: kart/working_copy/).

The GPKG working copy (stdlib sqlite3) is the default; server-DB working
copies (PostGIS / SQL Server / MySQL) are gated on their drivers being
installed.
"""

from enum import Enum, IntFlag


class WorkingCopyType(Enum):
    GPKG = "gpkg"
    POSTGIS = "postgis"
    SQL_SERVER = "sqlserver"
    MYSQL = "mysql"

    @classmethod
    def from_location(cls, location):
        location = str(location)
        if location.startswith("postgresql:"):
            return cls.POSTGIS
        if location.startswith("mssql:"):
            return cls.SQL_SERVER
        if location.startswith("mysql:"):
            return cls.MYSQL
        if location.lower().endswith(".gpkg"):
            return cls.GPKG
        from kart_tpu.core.repo import InvalidOperation

        raise InvalidOperation(
            f"Unrecognised working copy location: {location!r} "
            f"(expected a .gpkg path or a postgresql://, mssql://, mysql:// URL)"
        )


class WorkingCopyStatus(IntFlag):
    UNCONNECTABLE = 0x1
    NON_EXISTENT = 0x2
    CREATED = 0x4
    INITIALISED = 0x8
    HAS_DATA = 0x10
    DIRTY = 0x20


MAX_RENAME_SEARCH = 400  # reference: working_copy/base.py find_renames cap


def can_find_renames(dataset, meta_diff):
    """Rename detection is only meaningful while the schema is unchanged
    (reference: working_copy/base.py:812-827 — type-width updates are
    tolerated, any other schema edit disables it)."""
    if meta_diff is None or "schema.json" not in meta_diff:
        return True
    delta = meta_diff["schema.json"]
    if delta.old_value is None or delta.new_value is None:
        return False
    from kart_tpu.models.schema import Schema

    old_schema = Schema.from_column_dicts(delta.old_value)
    new_schema = Schema.from_column_dicts(delta.new_value)
    counts = dict(old_schema.diff_type_counts(new_schema))
    counts.pop("type_updates", None)
    return sum(counts.values()) == 0


def find_renames(feature_diff, dataset):
    """Pair matching insert+delete deltas into pk-rename updates, in place:
    a feature whose pk changed in the working copy hashes identically
    without its pk, and the paired delta renders as
    ``--- ds:feature:old / +++ ds:feature:new`` with only the pk line
    differing (reference: working_copy/base.py:829-854). At most one
    insert/delete merges per content hash; bounded by MAX_RENAME_SEARCH
    insert+delete deltas (content hashing is per-feature Python)."""
    from kart_tpu.diff.structs import Delta

    candidates = [
        d for d in feature_diff.values() if d.type in ("insert", "delete")
    ]
    if not candidates or len(candidates) > MAX_RENAME_SEARCH:
        return
    schema = dataset.schema
    inserts = {}
    deletes = {}
    for delta in candidates:
        if delta.type == "insert":
            inserts[schema.hash_feature(delta.new_value, without_pk=True)] = delta
        else:
            deletes[schema.hash_feature(delta.old_value, without_pk=True)] = delta
    for h, delete_delta in deletes.items():
        insert_delta = inserts.get(h)
        if insert_delta is None:
            continue
        del feature_diff[delete_delta.key]
        del feature_diff[insert_delta.key]
        merged = Delta(
            delete_delta.old, insert_delta.new, flags=delete_delta.flags
        )
        feature_diff.add_delta(merged)


def checkout_features(repo, ds):
    """Features to materialise in a working copy: the repo's spatial filter
    applied, promised (out-of-filter) blobs skipped — a filtered clone's WC
    holds only in-filter features (reference: kart/checkout.py +
    kart/working_copy/base.py write_full)."""
    from kart_tpu.spatial_filter import ResolvedSpatialFilterSpec

    spec = ResolvedSpatialFilterSpec.from_repo_config(repo)
    sf = spec.resolve_for_dataset(ds)
    return ds.features(
        spatial_filter=sf if sf else None,
        skip_promised=repo.has_promisor_remote(),
    )


def get_working_copy(repo, allow_uncreated=False):
    """-> the repo's working copy instance, or None when no location is
    configured (bare repos) or nothing exists there yet."""
    from kart_tpu.core.repo import KartConfigKeys

    location = repo.config.get(KartConfigKeys.KART_WORKINGCOPY_LOCATION)
    if location is None and not repo.is_bare:
        location = default_location(repo)
    if location is None:
        return None
    wc_type = WorkingCopyType.from_location(location)
    if wc_type is WorkingCopyType.GPKG:
        from kart_tpu.workingcopy.gpkg import GpkgWorkingCopy

        wc = GpkgWorkingCopy(repo, location)
    elif wc_type is WorkingCopyType.POSTGIS:
        from kart_tpu.workingcopy.postgis import PostgisWorkingCopy

        wc = PostgisWorkingCopy(repo, location)
    elif wc_type is WorkingCopyType.SQL_SERVER:
        from kart_tpu.workingcopy.sqlserver import SqlServerWorkingCopy

        wc = SqlServerWorkingCopy(repo, location)
    else:
        from kart_tpu.workingcopy.mysql import MySqlWorkingCopy

        wc = MySqlWorkingCopy(repo, location)
    if not allow_uncreated and not (wc.status() & (WorkingCopyStatus.INITIALISED)):
        return None
    return wc


def default_location(repo):
    import os

    if repo.workdir is None:
        return None
    name = os.path.basename(repo.workdir) or "data"
    return f"{name}.gpkg"
