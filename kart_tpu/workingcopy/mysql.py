"""MySQL working copy (reference: kart/working_copy/mysql.py).

In MySQL a "schema" *is* a database, so the working copy is one database
(URL: ``mysql://HOST[:PORT]/DBNAME``) holding the feature tables plus
``_kart_state`` / ``_kart_track``. Connection is via pymysql or
MySQLdb when installed (driver-gated).
"""

from kart_tpu.adapters.mysql import MySqlAdapter
from kart_tpu.core.repo import NotFound
from kart_tpu.crs import get_identifier_str, normalise_wkt
from kart_tpu.workingcopy.db_server import DatabaseServerWorkingCopy


class MySqlWorkingCopy(DatabaseServerWorkingCopy):
    URI_SCHEME = "mysql"
    URI_PATH_PARTS = 1
    WORKING_COPY_TYPE_NAME = "MySQL"
    ADAPTER = MySqlAdapter
    PARAMSTYLE = "%s"

    def _connect(self):
        driver = None
        try:
            import pymysql as driver
        except ImportError:
            try:
                import MySQLdb as driver
            except ImportError:
                pass
        if driver is None:
            raise NotFound(
                "MySQL working copies require the pymysql (or mysqlclient) "
                "driver, which is not installed in this environment. Use a "
                "GPKG working copy, or install pymysql."
            )
        return driver.connect(
            host=self.host,
            port=self.port or 3306,
            user=self.username,
            password=self.password or "",
        )

    def _schema_exists(self, con):
        cur = self._execute(
            con,
            "SELECT 1 FROM information_schema.schemata WHERE schema_name = %s",
            (self.db_schema,),
        )
        return cur.fetchone() is not None

    def _has_feature_tables(self, con):
        cur = self._execute(
            con,
            "SELECT count(*) FROM information_schema.tables "
            "WHERE table_schema = %s AND table_name NOT LIKE '\\_kart\\_%%'",
            (self.db_schema,),
        )
        return cur.fetchone()[0] > 0

    def _drop_container_sql(self):
        return f"DROP DATABASE IF EXISTS {self.ADAPTER.quote(self.db_schema)}"

    def _table_exists(self, con, table):
        cur = self._execute(
            con,
            "SELECT 1 FROM information_schema.tables "
            "WHERE table_schema = %s AND table_name = %s",
            (self.db_schema, table),
        )
        return cur.fetchone() is not None

    def _table_columns(self, con, table):
        """(reference: adapter/mysql.py all_v2_meta_items table query)."""
        cur = self._execute(
            con,
            """
            SELECT C.column_name, C.data_type, C.column_type,
                   C.character_maximum_length, C.numeric_precision,
                   C.numeric_scale, C.column_key, C.srs_id
            FROM information_schema.columns C
            WHERE C.table_schema = %s AND C.table_name = %s
            ORDER BY C.ordinal_position
            """,
            (self.db_schema, table),
        )
        pk_counter = 0
        for (name, data_type, column_type, char_len, num_prec, num_scale,
             column_key, srs_id) in cur.fetchall():
            if isinstance(data_type, bytes):
                data_type = data_type.decode()
            sql_type = (data_type or "").upper()
            pk_index = None
            if column_key == "PRI":
                pk_index = pk_counter
                pk_counter += 1
            if sql_type in self.ADAPTER.GEOMETRY_TYPES:
                info = {}
                if sql_type != "GEOMETRY":
                    info["geometryType"] = sql_type
                if srs_id:
                    crs = self._crs_name_for_srs_id(con, srs_id)
                    if crs:
                        info["geometryCRS"] = crs
                yield name, "GEOMETRY", pk_index, info
                continue
            if sql_type in ("VARCHAR", "CHAR") and char_len:
                sql_type = f"VARCHAR({char_len})"
            elif sql_type == "VARBINARY" and char_len:
                sql_type = f"VARBINARY({char_len})"
            elif sql_type in ("NUMERIC", "DECIMAL") and num_prec:
                sql_type = (
                    f"NUMERIC({num_prec},{num_scale})"
                    if num_scale
                    else f"NUMERIC({num_prec})"
                )
            yield name, sql_type, pk_index, None

    def _crs_name_for_srs_id(self, con, srs_id):
        cur = self._execute(
            con,
            "SELECT organization, organization_coordsys_id "
            "FROM information_schema.st_spatial_reference_systems "
            "WHERE srs_id = %s",
            (srs_id,),
        )
        row = cur.fetchone()
        if row and row[0]:
            return f"{row[0]}:{row[1]}"
        return f"CUSTOM:{srs_id}"

    def _extra_meta_items(self, con, table):
        out = {}
        cur = self._execute(
            con,
            "SELECT SRS.definition FROM information_schema.columns C "
            "INNER JOIN information_schema.st_spatial_reference_systems SRS "
            "ON C.srs_id = SRS.srs_id "
            "WHERE C.table_schema = %s AND C.table_name = %s",
            (self.db_schema, table),
        )
        for (definition,) in cur.fetchall():
            if definition:
                out[f"crs/{get_identifier_str(definition)}.wkt"] = normalise_wkt(
                    definition
                )
        return out

    def _post_write_dataset(self, con, ds, table, crs_id):
        # No spatial index: MySQL requires the geometry column to be made
        # generic GEOMETRY NOT NULL for one, which discards the typed column
        # (geometryType would never roundtrip — a fresh checkout would show a
        # spurious schema edit) and forbids NULL geometries in later edits.
        # The reference skips it for exactly this reason
        # (kart/working_copy/mysql.py:126-133).
        pass
