"""SQL Server working copy (reference: kart/working_copy/sqlserver.py).

One SQL Server *database schema* (URL: ``mssql://HOST[:PORT]/DBNAME/DBSCHEMA``)
holds the feature tables plus ``_kart_state`` / ``_kart_track``. Connection is
via pyodbc + the MS ODBC driver when installed (driver-gated).
"""

import logging

from kart_tpu.adapters.sqlserver import SqlServerAdapter
from kart_tpu.core.repo import NotFound
from kart_tpu.workingcopy.db_server import DatabaseServerWorkingCopy


class SqlServerWorkingCopy(DatabaseServerWorkingCopy):
    URI_SCHEME = "mssql"
    URI_PATH_PARTS = 2
    WORKING_COPY_TYPE_NAME = "SQL Server"
    ADAPTER = SqlServerAdapter
    PARAMSTYLE = "?"

    def _connect(self):
        try:
            import pyodbc
        except ImportError:
            raise NotFound(
                "SQL Server working copies require the pyodbc driver and the "
                "Microsoft ODBC driver for SQL Server, which are not installed "
                "in this environment. Use a GPKG working copy, or install them."
            )
        server = self.host or "localhost"
        if self.port:
            server = f"{server},{self.port}"
        parts = [
            "DRIVER={ODBC Driver 17 for SQL Server}",
            f"SERVER={server}",
            f"DATABASE={self.db_name}",
        ]
        if self.username:
            parts.append(f"UID={self.username}")
            parts.append(f"PWD={self.password or ''}")
        else:
            parts.append("Trusted_Connection=yes")
        return pyodbc.connect(";".join(parts))

    def _schema_exists(self, con):
        cur = self._execute(
            con,
            "SELECT 1 FROM sys.schemas WHERE name = ?",
            (self.db_schema,),
        )
        return cur.fetchone() is not None

    def _has_feature_tables(self, con):
        cur = self._execute(
            con,
            "SELECT count(*) FROM information_schema.tables "
            "WHERE table_schema = ? AND table_name NOT LIKE '[_]kart[_]%'",
            (self.db_schema,),
        )
        return cur.fetchone()[0] > 0

    def _drop_container_sql(self):
        # SQL Server has no DROP SCHEMA CASCADE; tables must go first. This
        # statement drops all tables in the schema then the schema itself.
        return f"""
            DECLARE @sql NVARCHAR(max) = '';
            SELECT @sql = @sql + 'DROP TABLE ' + QUOTENAME(table_schema)
                + '.' + QUOTENAME(table_name) + ';'
            FROM information_schema.tables
            WHERE table_schema = {self.ADAPTER.string_literal(self.db_schema)};
            EXEC sp_executesql @sql;
            DROP SCHEMA IF EXISTS {self.ADAPTER.quote(self.db_schema)};
        """

    def _table_exists(self, con, table):
        cur = self._execute(
            con,
            "SELECT 1 FROM information_schema.tables "
            "WHERE table_schema = ? AND table_name = ?",
            (self.db_schema, table),
        )
        return cur.fetchone() is not None

    def _table_columns(self, con, table):
        """(reference: adapter/sqlserver.py all_v2_meta_items table query).
        Geometry columns show up with data_type GEOMETRY; their SRID lives on
        the values, sampled from the first row."""
        cur = self._execute(
            con,
            """
            SELECT C.column_name, C.data_type,
                   C.character_maximum_length, C.numeric_precision,
                   C.numeric_scale, PK.ordinal_position
            FROM information_schema.columns C
            LEFT OUTER JOIN (
                SELECT KCU.table_schema, KCU.table_name, KCU.column_name,
                       KCU.ordinal_position
                FROM information_schema.key_column_usage KCU
                INNER JOIN information_schema.table_constraints TC
                ON KCU.constraint_schema = TC.constraint_schema
                AND KCU.constraint_name = TC.constraint_name
                WHERE TC.constraint_type = 'PRIMARY KEY'
            ) PK ON PK.table_schema = C.table_schema
                AND PK.table_name = C.table_name
                AND PK.column_name = C.column_name
            WHERE C.table_schema = ? AND C.table_name = ?
            ORDER BY C.ordinal_position
            """,
            (self.db_schema, table),
        )
        for (name, data_type, char_len, num_prec, num_scale,
             pk_pos) in cur.fetchall():
            pk_index = pk_pos - 1 if pk_pos is not None else None
            sql_type = (data_type or "").upper()
            if sql_type in ("GEOMETRY", "GEOGRAPHY"):
                yield name, "GEOMETRY", pk_index, {}
                continue
            if sql_type in ("NVARCHAR", "VARCHAR", "NCHAR", "CHAR") and char_len and char_len > 0:
                sql_type = f"{sql_type}({char_len})"
            elif sql_type == "VARBINARY" and char_len and char_len > 0:
                sql_type = f"VARBINARY({char_len})"
            elif sql_type in ("NUMERIC", "DECIMAL") and num_prec:
                sql_type = (
                    f"NUMERIC({num_prec},{num_scale})"
                    if num_scale
                    else f"NUMERIC({num_prec})"
                )
            yield name, sql_type, pk_index, None

    # SQL Server stores no CRS definitions at all — only SRIDs on values —
    # so geometryCRS and crs/*.wkt can't roundtrip (reference:
    # adapter/sqlserver.py "geometryType is not roundtripped" note).
    UNSUPPORTED_META_ITEMS = (
        "title", "description", "metadata.xml",
    )

    def _diff_meta(self, con, dataset, table):
        out = super()._diff_meta(con, dataset, table)
        # geometry extra info (type/CRS) doesn't roundtrip: suppress
        # schema-only deltas whose every change is on geometry extras
        if "schema.json" in out:
            delta = out["schema.json"]
            if delta.old is not None and delta.new is not None:
                old_cols = delta.old_value
                new_cols = delta.new_value
                if self._same_modulo_geometry_extras(old_cols, new_cols):
                    del out["schema.json"]
        return out

    @staticmethod
    def _same_modulo_geometry_extras(old_cols, new_cols):
        if len(old_cols) != len(new_cols):
            return False
        strip = ("geometryType", "geometryCRS")
        for o, n in zip(old_cols, new_cols):
            if o.get("dataType") == "geometry" and n.get("dataType") == "geometry":
                o = {k: v for k, v in o.items() if k not in strip}
                n = {k: v for k, v in n.items() if k not in strip}
            if o != n:
                return False
        return True

    def _post_write_dataset(self, con, ds, table, crs_id):
        schema = ds.schema
        geom_col = schema.first_geometry_column
        if geom_col is not None and schema.pk_columns:
            # spatial index needs an explicit bounding box; use the dataset
            # extent when available, else the whole world in the dataset CRS
            try:
                self._execute(
                    con,
                    f'CREATE SPATIAL INDEX "{table}_idx_geom" ON '
                    f"{self._table_identifier(table)} "
                    f"({self.ADAPTER.quote(geom_col.name)}) "
                    f"WITH (BOUNDING_BOX = (-180, -90, 180, 90))",
                )
            except Exception as e:
                # the index is an optimisation; the data is already correct
                # (common cause: restricted CREATE INDEX permissions)
                logging.getLogger(__name__).debug(
                    "spatial index on %s not created: %s", table, e
                )
