"""Server-database working copies: shared base
(reference: kart/working_copy/db_server.py + base.py).

A server working copy lives in one *database schema* (PostGIS / SQL Server)
or one *database* (MySQL) of a server the user points us at with a URL:

    postgresql://HOST[:PORT]/DBNAME/DBSCHEMA
    mssql://HOST[:PORT]/DBNAME/DBSCHEMA
    mysql://HOST[:PORT]/DBNAME

The contract is identical to GpkgWorkingCopy (status / write_full / diff /
reset / tracking); the SQL is produced by the backend's adapter and executed
over the backend's plain DBAPI driver. Drivers are not baked into this
environment, so construction is *driver-gated*: everything up to connecting —
URL parsing, SQL generation — works without a driver, and `_connect()` raises
a clear NotFound when the driver is missing (the reference gates the same way
via vendored psycopg2/pyodbc, skipping tests unless KART_*_URL is set).
"""

import contextlib
from urllib.parse import urlsplit, unquote

from kart_tpu import telemetry as tm
from kart_tpu.adapters.base import KART_STATE, KART_TRACK
from kart_tpu.core.odb import ObjectPromised
from kart_tpu.core.repo import InvalidOperation, NotFound
from kart_tpu.crs import get_identifier_int, get_identifier_str
from kart_tpu.diff.structs import (
    WORKING_COPY_EDIT,
    DatasetDiff,
    Delta,
    DeltaDiff,
    KeyValue,
)
from kart_tpu.models.schema import ColumnSchema, Schema
from kart_tpu.workingcopy import WorkingCopyStatus, checkout_features


class Mismatch(InvalidOperation):
    def __init__(self, wc_tree, expected_tree):
        super().__init__(
            f"Working copy is out of sync with repository: working copy has tree "
            f"{wc_tree}, repository expects {expected_tree}. "
            f'Use "kart checkout --force HEAD" to reset the working copy.'
        )
        self.wc_tree = wc_tree
        self.expected_tree = expected_tree


class DatabaseServerWorkingCopy:
    """Base for PostGIS / SQL Server / MySQL working copies."""

    URI_SCHEME = None        # "postgresql" | "mssql" | "mysql"
    # path parts after the host: ("dbname", "dbschema") or ("dbname",)
    URI_PATH_PARTS = 2
    WORKING_COPY_TYPE_NAME = None
    ADAPTER = None           # BaseAdapter subclass
    PARAMSTYLE = "%s"        # DBAPI placeholder ("%s" or "?")

    def __init__(self, repo, location):
        self.repo = repo
        # {ds_path: [pks]} filled during WC diffs on a filtered clone
        self.spatial_filter_pk_conflicts = {}
        self.location = str(location)
        (
            self.host,
            self.port,
            self.db_name,
            self.db_schema,
            self.username,
            self.password,
        ) = self._parse_url(self.location)

    @classmethod
    def _parse_url(cls, location):
        url = urlsplit(location)
        if url.scheme != cls.URI_SCHEME:
            raise InvalidOperation(
                f"Expecting URI in form: {cls.URI_SCHEME}://HOST[:PORT]/"
                + "/".join(p.upper() for p in cls._path_part_names())
            )
        parts = [p for p in url.path.split("/") if p]
        if len(parts) != cls.URI_PATH_PARTS:
            expected = "/".join(p.upper() for p in cls._path_part_names())
            raise InvalidOperation(
                f"Invalid {cls.WORKING_COPY_TYPE_NAME} URI - URI path must have "
                f"{cls.URI_PATH_PARTS} part(s): "
                f"expecting {cls.URI_SCHEME}://HOST[:PORT]/{expected}"
            )
        db_name = unquote(parts[0])
        db_schema = unquote(parts[1]) if cls.URI_PATH_PARTS > 1 else db_name
        username = unquote(url.username) if url.username else None
        password = unquote(url.password) if url.password else None
        return url.hostname, url.port, db_name, db_schema, username, password

    @classmethod
    def _path_part_names(cls):
        return ("dbname", "dbschema")[: cls.URI_PATH_PARTS]

    @property
    def clean_location(self):
        """Location with any password redacted."""
        url = urlsplit(self.location)
        if url.password is None:
            return self.location
        netloc = url.hostname or ""
        if url.username:
            netloc = f"{url.username}@{netloc}"
        if url.port:
            netloc = f"{netloc}:{url.port}"
        return url._replace(netloc=netloc).geturl()

    def __str__(self):
        return self.clean_location

    # -- connection (driver-gated) -------------------------------------------

    def _connect(self):
        raise NotImplementedError

    @contextlib.contextmanager
    def session(self):
        con = self._connect()
        try:
            yield con
            con.commit()
        except Exception:
            con.rollback()
            raise
        finally:
            con.close()

    def _execute(self, con, sql, params=()):
        tm.incr("wc.statements", backend=self.WORKING_COPY_TYPE_NAME or "db")
        cur = con.cursor()
        cur.execute(sql, params)
        return cur

    def _ph(self, n=1):
        return ", ".join([self.PARAMSTYLE] * n)

    # -- naming --------------------------------------------------------------

    @staticmethod
    def _table_name(ds_path):
        """dataset path -> table name; nested paths flatten with '__'."""
        return ds_path.replace("/", "__")

    def _table_identifier(self, table_name):
        return self.ADAPTER.quote_table(table_name, self.db_schema)

    # -- status / state ------------------------------------------------------

    def status(self):
        result = 0
        try:
            with self.session() as con:
                result |= WorkingCopyStatus.CREATED
                if self._schema_exists(con):
                    result |= WorkingCopyStatus.INITIALISED
                    if self._has_feature_tables(con):
                        result |= WorkingCopyStatus.HAS_DATA
        except NotFound:
            raise
        except Exception:
            result |= WorkingCopyStatus.UNCONNECTABLE
        return result

    def _schema_exists(self, con):
        raise NotImplementedError

    def _has_feature_tables(self, con):
        raise NotImplementedError

    def _list_feature_tables(self, con):
        """All non-kart tables in the WC container (information_schema works
        for PostGIS/MySQL/SQL Server; the _kart_ filter is done host-side to
        dodge per-dialect LIKE-escape rules)."""
        cur = self._execute(
            con,
            "SELECT table_name FROM information_schema.tables "
            f"WHERE table_schema = {self.PARAMSTYLE}",
            (self.db_schema,),
        )
        return [r[0] for r in cur.fetchall() if not r[0].startswith("_kart_")]

    def create_and_initialise(self):
        with self.session() as con:
            for stmt in self.ADAPTER.base_ddl(self.db_schema):
                self._execute(con, stmt)

    def delete(self):
        """Drop the whole WC container schema/database."""
        with self.session() as con:
            self._execute(con, self._drop_container_sql())

    def _drop_container_sql(self):
        raise NotImplementedError

    def get_db_tree(self):
        with self.session() as con:
            try:
                cur = self._execute(
                    con,
                    f"SELECT value FROM {self._table_identifier(KART_STATE)} "
                    f"WHERE table_name = '*' AND {self._state_key_col()} = 'tree'",
                )
            except Exception:
                return None
            row = cur.fetchone()
            return row[0] if row else None

    def _state_key_col(self):
        return self.ADAPTER.quote("key")

    def assert_db_tree_match(self, expected_tree_oid):
        wc_tree = self.get_db_tree()
        expected = (
            expected_tree_oid.oid
            if hasattr(expected_tree_oid, "oid")
            else expected_tree_oid
        )
        if wc_tree != expected:
            raise Mismatch(wc_tree, expected)

    def _update_state_tree(self, con, tree_oid):
        state = self._table_identifier(KART_STATE)
        self._execute(
            con,
            f"DELETE FROM {state} WHERE table_name = '*' "
            f"AND {self._state_key_col()} = 'tree'",
        )
        self._execute(
            con,
            f"INSERT INTO {state} (table_name, {self._state_key_col()}, value) "
            f"VALUES ('*', 'tree', {self.PARAMSTYLE})",
            (str(tree_oid),),
        )

    def update_state_table_tree(self, tree_oid):
        with self.session() as con:
            self._update_state_tree(con, tree_oid)

    # -- checkout (write_full) -----------------------------------------------

    def write_full(self, target_structure, *datasets):
        with tm.span("wc.write_full", datasets=len(datasets)):
            if not (self.status() & WorkingCopyStatus.INITIALISED):
                self.create_and_initialise()
            with self.session() as con:
                for ds in datasets:
                    self._write_one_dataset(con, ds)
                self._update_state_tree(con, target_structure.tree_oid)

    def _dataset_crs_id(self, ds):
        schema = ds.schema
        if schema.first_geometry_column is None:
            return 0
        idents = ds.crs_identifiers()
        if not idents:
            return 0
        return get_identifier_int(ds.get_crs_definition(idents[0]))

    def _write_one_dataset(self, con, ds):
        table = self._table_name(ds.path)
        schema = ds.schema
        crs_id = self._dataset_crs_id(ds)

        for ident in ds.crs_identifiers():
            wkt = ds.get_crs_definition(ident)
            org, _, code = ident.partition(":")
            stmt = self.ADAPTER.register_crs_sql(
                get_identifier_int(wkt), org or "NONE",
                int(code) if code.isdigit() else 0, wkt,
            )
            if stmt is not None:
                with contextlib.suppress(Exception):
                    # best-effort: the SRS may exist / the def may be
                    # unsupported by this server; features still store SRIDs
                    self._execute(con, stmt[0], stmt[1])

        tbl = self._table_identifier(table)
        self._execute(con, f"DROP TABLE IF EXISTS {tbl}")
        spec = self.ADAPTER.v2_schema_to_sql_spec(schema, crs_id=crs_id or None)
        self._execute(con, f"CREATE TABLE {tbl} ({spec})")
        self._write_meta(con, ds, table)

        col_names = [c.name for c in schema.columns]
        quoted_cols = ", ".join(self.ADAPTER.quote(c) for c in col_names)
        placeholders = ", ".join(
            self.ADAPTER.insert_placeholder(c, crs_id) for c in schema.columns
        )
        insert_sql = f"INSERT INTO {tbl} ({quoted_cols}) VALUES ({placeholders})"
        batch = []
        rows = 0
        cur = con.cursor()
        for feature in checkout_features(self.repo, ds):
            batch.append(
                tuple(
                    self.ADAPTER.value_from_v2(feature[c.name], c, crs_id=crs_id)
                    for c in schema.columns
                )
            )
            if len(batch) >= 10000:
                cur.executemany(insert_sql, batch)
                rows += len(batch)
                batch.clear()
        if batch:
            cur.executemany(insert_sql, batch)
            rows += len(batch)
        tm.incr("wc.rows_written", rows)

        self._post_write_dataset(con, ds, table, crs_id)
        self._create_triggers(con, table, schema)

    def _write_meta(self, con, ds, table):
        """Backend hook: titles/comments/spatial indexes."""

    def _post_write_dataset(self, con, ds, table, crs_id):
        """Backend hook: spatial index, sequence fixup."""

    def _create_triggers(self, con, table, schema):
        pk_name = schema.pk_columns[0].name if schema.pk_columns else None
        if pk_name is None:
            return
        stmts = self.ADAPTER.create_trigger_sql(self.db_schema, table, pk_name)
        if isinstance(stmts, str):
            stmts = [stmts]
        for stmt in stmts:
            self._execute(con, stmt)

    def _drop_triggers(self, con, table):
        stmts = self.ADAPTER.drop_trigger_sql(self.db_schema, table)
        if isinstance(stmts, str):
            stmts = [stmts]
        for stmt in stmts:
            self._execute(con, stmt)

    @contextlib.contextmanager
    def _suspended_triggers(self, con, table, schema):
        pk_name = schema.pk_columns[0].name if schema.pk_columns else None
        suspend = self.ADAPTER.suspend_trigger_sql(self.db_schema, table)
        if isinstance(suspend, str):
            suspend = [suspend]
        for stmt in suspend:
            self._execute(con, stmt)
        try:
            yield
        finally:
            resume = self.ADAPTER.resume_trigger_sql(self.db_schema, table, pk_name)
            if isinstance(resume, str):
                resume = [resume]
            for stmt in resume:
                self._execute(con, stmt)

    # -- reading the WC schema back ------------------------------------------

    def _wc_schema_for_table(self, con, table):
        """information_schema -> V2 schema (fresh ids; align before diff)."""
        cols = []
        for (name, sql_type, pk_index, geom_info) in self._table_columns(con, table):
            if geom_info is not None:
                data_type, extra = "geometry", dict(geom_info)
            else:
                data_type, extra = self.ADAPTER.sql_type_to_v2(sql_type)
            if pk_index is not None and data_type == "integer":
                extra = {**extra, "size": extra.get("size", 64)}
            cols.append(
                ColumnSchema(ColumnSchema.new_id(), name, data_type, pk_index, extra)
            )
        return Schema(cols)

    def _table_columns(self, con, table):
        """Backend hook -> iterable of (name, sql_type, pk_index, geom_info)."""
        raise NotImplementedError

    def _wc_meta_items(self, con, table, aligned_schema):
        out = {"schema.json": aligned_schema.to_column_dicts()}
        out.update(self._extra_meta_items(con, table))
        return out

    def _extra_meta_items(self, con, table):
        return {}

    # Items a backend has nowhere to store; excluded from the meta diff
    # (reference: postgis.py _UNSUPPORTED_META_ITEMS).
    UNSUPPORTED_META_ITEMS = ("title", "description", "metadata.xml")

    # -- diffing -------------------------------------------------------------

    def diff_dataset_to_working_copy(self, dataset, ds_filter=None,
                                     workdir_diff_cache=None):
        table = self._table_name(dataset.path)
        result = DatasetDiff()
        with tm.span("wc.diff", dataset=dataset.path), self.session() as con:
            if not self._table_exists(con, table):
                return result
            result["meta"] = self._diff_meta(con, dataset, table)
            new_schema = dataset.schema
            if "schema.json" in result["meta"]:
                new_schema = Schema.from_column_dicts(
                    result["meta"]["schema.json"].new_value
                )
            result["feature"] = self._diff_features(
                con, dataset, table, new_schema, ds_filter
            )
        from kart_tpu.workingcopy import can_find_renames, find_renames

        if can_find_renames(dataset, result["meta"]):
            find_renames(result["feature"], dataset)
        result.prune()
        return result

    def _table_exists(self, con, table):
        raise NotImplementedError

    def _diff_meta(self, con, dataset, table):
        wc_schema = self._wc_schema_for_table(con, table)
        aligned = dataset.schema.align_to_self(
            wc_schema, roundtrip_ctx=self.ADAPTER
        )
        wc_items = self._wc_meta_items(con, table, aligned)
        ds_items = dataset.meta_items()
        out = DeltaDiff()
        for name in sorted(set(ds_items) | set(wc_items)):
            if name in self.UNSUPPORTED_META_ITEMS and name not in wc_items:
                continue
            if name.startswith("crs/") and name not in wc_items:
                # CRS defs don't roundtrip byte-exactly through server SRS
                # tables; absence in the WC is not an edit
                continue
            old = ds_items.get(name)
            new = wc_items.get(name)
            if old == new:
                continue
            out.add_delta(
                Delta(
                    KeyValue((name, old)) if old is not None else None,
                    KeyValue((name, new)) if new is not None else None,
                    flags=WORKING_COPY_EDIT,
                )
            )
        return out

    def _diff_features(self, con, dataset, table, wc_schema, ds_filter):
        feature_filter = ds_filter["feature"] if ds_filter is not None else None
        out = DeltaDiff()
        pk_col = dataset.schema.pk_columns[0]
        track = self._table_identifier(KART_TRACK)
        cur = self._execute(
            con,
            f"SELECT pk FROM {track} WHERE table_name = {self.PARAMSTYLE}",
            (table,),
        )
        tracked = [row[0] for row in cur.fetchall()]
        if not tracked:
            return out
        tbl = self._table_identifier(table)
        select_cols = ", ".join(
            self.ADAPTER.select_expression(c) for c in wc_schema.columns
        )
        quoted_pk = self.ADAPTER.quote(pk_col.name)
        names = [c.name for c in wc_schema.columns]
        for chunk_start in range(0, len(tracked), 500):
            chunk = tracked[chunk_start : chunk_start + 500]
            cur = self._execute(
                con,
                f"SELECT {select_cols} FROM {tbl} "
                f"WHERE {quoted_pk} IN ({self._ph(len(chunk))})",
                tuple(chunk),
            )
            rows = {}
            pk_pos = names.index(pk_col.name)
            for row in cur.fetchall():
                rows[dataset.schema.sanitise_pks(row[pk_pos])[0]] = row
            for raw_pk in chunk:
                pk = dataset.schema.sanitise_pks(raw_pk)[0]
                if feature_filter is not None and pk not in feature_filter:
                    continue
                try:
                    old_feature = dataset.get_feature([pk])
                except ObjectPromised:
                    # pk collides with an out-of-filter (promised) feature
                    old_feature = None
                    self.spatial_filter_pk_conflicts.setdefault(
                        dataset.path, []
                    ).append(pk)
                except KeyError:
                    old_feature = None
                row = rows.get(pk)
                new_feature = None
                if row is not None:
                    new_feature = {
                        c.name: self.ADAPTER.value_to_v2(row[i], c)
                        for i, c in enumerate(wc_schema.columns)
                    }
                if old_feature is None and new_feature is None:
                    continue
                if old_feature == new_feature:
                    continue
                out.add_delta(
                    Delta(
                        KeyValue((pk, old_feature)) if old_feature is not None else None,
                        KeyValue((pk, new_feature)) if new_feature is not None else None,
                        flags=WORKING_COPY_EDIT,
                    )
                )
        return out

    def is_dirty(self):
        status = self.status()
        if not (status & WorkingCopyStatus.INITIALISED):
            return False
        tree = self.get_db_tree()
        if tree is None:
            return False
        try:
            rs = self.repo.structure(tree)
        except NotFound:
            return False
        for ds in rs.datasets:
            if self.diff_dataset_to_working_copy(ds):
                return True
        return False

    # -- state updates -------------------------------------------------------

    def reset_tracking_table(self, repo_key_filter=None):
        track = self._table_identifier(KART_TRACK)
        with self.session() as con:
            if repo_key_filter is None or repo_key_filter.match_all:
                self._execute(con, f"DELETE FROM {track}")
                return
            for ds_path in repo_key_filter.ds_paths():
                ds_filter = repo_key_filter[ds_path]
                table = self._table_name(ds_path)
                feature_filter = ds_filter["feature"]
                if ds_filter.match_all or feature_filter.match_all:
                    self._execute(
                        con,
                        f"DELETE FROM {track} WHERE table_name = {self.PARAMSTYLE}",
                        (table,),
                    )
                else:
                    for pk in feature_filter.keys:
                        self._execute(
                            con,
                            f"DELETE FROM {track} WHERE table_name = "
                            f"{self.PARAMSTYLE} AND pk = {self.PARAMSTYLE}",
                            (table, str(pk)),
                        )

    def soft_reset_after_commit(self, new_tree_oid, repo_key_filter=None):
        self.reset_tracking_table(repo_key_filter)
        self.update_state_table_tree(new_tree_oid)

    # -- reset / checkout ----------------------------------------------------

    def reset(self, target_structure, *, force=False, repo_key_filter=None,
              track_changes_as_dirty=False):
        from kart_tpu.diff.engine import get_dataset_diff

        current_tree = self.get_db_tree()
        if current_tree is None or force:
            # tables from datasets absent in the target would otherwise
            # linger in the schema and still count as WC data
            target_tables = {
                self._table_name(p) for p in target_structure.datasets.paths()
            }
            with self.session() as con:
                if self._schema_exists(con):
                    for table in self._list_feature_tables(con):
                        if table not in target_tables:
                            self._execute(
                                con,
                                f"DROP TABLE IF EXISTS "
                                f"{self._table_identifier(table)}",
                            )
            self.write_full(target_structure, *target_structure.datasets)
            if force:
                with self.session() as con:
                    self._execute(
                        con, f"DELETE FROM {self._table_identifier(KART_TRACK)}"
                    )
            return

        base_rs = self.repo.structure(current_tree)
        base_paths = set(base_rs.datasets.paths())
        target_paths = set(target_structure.datasets.paths())

        with self.session() as con:
            track = self._table_identifier(KART_TRACK)
            for ds_path in sorted(base_paths - target_paths):
                table = self._table_name(ds_path)
                self._execute(
                    con, f"DROP TABLE IF EXISTS {self._table_identifier(table)}"
                )
                self._execute(
                    con,
                    f"DELETE FROM {track} WHERE table_name = {self.PARAMSTYLE}",
                    (table,),
                )
            for ds_path in sorted(target_paths - base_paths):
                self._write_one_dataset(con, target_structure.datasets[ds_path])
            for ds_path in sorted(base_paths & target_paths):
                target_ds = target_structure.datasets[ds_path]
                ds_diff = get_dataset_diff(base_rs, target_structure, ds_path)
                if not ds_diff:
                    continue
                if "meta" in ds_diff and ds_diff["meta"]:
                    self._write_one_dataset(con, target_ds)
                    self._execute(
                        con,
                        f"DELETE FROM {track} WHERE table_name = {self.PARAMSTYLE}",
                        (self._table_name(ds_path),),
                    )
                    continue
                self._apply_feature_diff_sql(
                    con, target_ds, ds_diff.get("feature", {}),
                    track_changes_as_dirty=track_changes_as_dirty,
                )
            self._update_state_tree(con, target_structure.tree_oid)

    def _apply_feature_diff_sql(self, con, dataset, feature_diff, *,
                                track_changes_as_dirty=False):
        table = self._table_name(dataset.path)
        schema = dataset.schema
        crs_id = self._dataset_crs_id(dataset)
        pk_col = schema.pk_columns[0]
        col_names = [c.name for c in schema.columns]
        pk_names = [c.name for c in schema.pk_columns]
        upsert = self.ADAPTER.upsert_sql(
            self.db_schema, table, col_names, pk_names, crs_id=crs_id, schema=schema
        )
        tbl = self._table_identifier(table)
        ctx = (
            contextlib.nullcontext()
            if track_changes_as_dirty
            else self._suspended_triggers(con, table, schema)
        )
        with ctx:
            for delta in feature_diff.values():
                if delta.new is None:
                    self._execute(
                        con,
                        f"DELETE FROM {tbl} WHERE "
                        f"{self.ADAPTER.quote(pk_col.name)} = {self.PARAMSTYLE}",
                        (delta.old_key,),
                    )
                else:
                    try:
                        new_value = delta.new_value
                    except ObjectPromised:
                        # partial clone: target feature is out-of-filter —
                        # remove any stale row rather than materialising it
                        self._execute(
                            con,
                            f"DELETE FROM {tbl} WHERE "
                            f"{self.ADAPTER.quote(pk_col.name)} = {self.PARAMSTYLE}",
                            (delta.new_key,),
                        )
                        continue
                    values = tuple(
                        self.ADAPTER.value_from_v2(
                            new_value[c.name], c, crs_id=crs_id
                        )
                        for c in schema.columns
                    )
                    self._execute(con, upsert, values)
