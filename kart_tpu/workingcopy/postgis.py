"""PostGIS working copy (reference: kart/working_copy/postgis.py).

One PostgreSQL *database schema* holds the feature tables plus the
``_kart_state`` / ``_kart_track`` tables and the shared tracking trigger
procedure. Connection is via psycopg2 when installed (driver-gated — see
db_server.py module docstring).
"""

from kart_tpu.adapters.postgis import PostgisAdapter
from kart_tpu.core.repo import NotFound
from kart_tpu.crs import get_identifier_str, normalise_wkt
from kart_tpu.workingcopy.db_server import DatabaseServerWorkingCopy


def read_table_columns(con, db_schema, table):
    """information_schema + geometry_columns -> (name, sql_type, pk_index,
    geom_info) per column. Shared by the working copy and the Postgres
    import source (reference: adapter/postgis.py:146-180 table_info_sql)."""
    cur = con.cursor()
    cur.execute(
        """
        SELECT C.column_name, C.data_type, C.udt_name,
               C.character_maximum_length, C.numeric_precision, C.numeric_scale,
               PK.ordinal_position AS pk_ordinal_position
        FROM information_schema.columns C
        LEFT OUTER JOIN (
            SELECT KCU.table_schema, KCU.table_name, KCU.column_name,
                   KCU.ordinal_position
            FROM information_schema.key_column_usage KCU
            INNER JOIN information_schema.table_constraints TC
            ON KCU.constraint_schema = TC.constraint_schema
            AND KCU.constraint_name = TC.constraint_name
            WHERE TC.constraint_type = 'PRIMARY KEY'
        ) PK ON PK.table_schema = C.table_schema
            AND PK.table_name = C.table_name
            AND PK.column_name = C.column_name
        WHERE C.table_schema = %s AND C.table_name = %s
        ORDER BY C.ordinal_position
        """,
        (db_schema, table),
    )
    col_rows = cur.fetchall()
    geom_cols = {}
    cur.execute(
        "SELECT GC.f_geometry_column, GC.type, GC.srid, SRS.srtext "
        "FROM geometry_columns GC "
        "LEFT OUTER JOIN spatial_ref_sys SRS ON GC.srid = SRS.srid "
        "WHERE GC.f_table_schema = %s AND GC.f_table_name = %s",
        (db_schema, table),
    )
    for (col_name, gtype, srid, srtext) in cur.fetchall():
        info = {}
        if gtype and gtype.upper() != "GEOMETRY":
            info["geometryType"] = gtype.upper()
        if srtext:
            info["geometryCRS"] = get_identifier_str(srtext)
        geom_cols[col_name] = info

    for (name, data_type, udt_name, char_len, num_prec, num_scale,
         pk_pos) in col_rows:
        pk_index = pk_pos - 1 if pk_pos is not None else None
        if name in geom_cols:
            yield name, "GEOMETRY", pk_index, geom_cols[name]
            continue
        sql_type = (data_type or "").upper()
        if sql_type not in PostgisAdapter.SQL_TYPE_TO_V2:
            sql_type = (udt_name or "").upper()
        if sql_type in ("CHARACTER VARYING", "VARCHAR") and char_len:
            sql_type = f"VARCHAR({char_len})"
        elif sql_type in ("NUMERIC", "DECIMAL") and num_prec:
            sql_type = (
                f"NUMERIC({num_prec},{num_scale})"
                if num_scale
                else f"NUMERIC({num_prec})"
            )
        yield name, sql_type, pk_index, None


class PostgisWorkingCopy(DatabaseServerWorkingCopy):
    URI_SCHEME = "postgresql"
    URI_PATH_PARTS = 2
    WORKING_COPY_TYPE_NAME = "PostGIS"
    ADAPTER = PostgisAdapter
    PARAMSTYLE = "%s"

    def _connect(self):
        try:
            import psycopg2
        except ImportError:
            raise NotFound(
                "PostGIS working copies require the psycopg2 driver, which is "
                "not installed in this environment. Use a GPKG working copy, "
                "or install psycopg2."
            )
        con = psycopg2.connect(
            host=self.host,
            port=self.port or 5432,
            dbname=self.db_name,
            user=self.username,
            password=self.password,
        )
        # intervals must stringify as ISO-8601 durations — the only form the
        # V2 schema accepts (reference: sqlalchemy/postgis.py:18)
        with con.cursor() as cur:
            cur.execute("SET intervalstyle = 'iso_8601'")
        return con

    def _schema_exists(self, con):
        cur = self._execute(
            con,
            "SELECT 1 FROM information_schema.schemata WHERE schema_name = %s",
            (self.db_schema,),
        )
        return cur.fetchone() is not None

    def _has_feature_tables(self, con):
        cur = self._execute(
            con,
            "SELECT count(*) FROM information_schema.tables "
            "WHERE table_schema = %s AND table_name NOT LIKE '\\_kart\\_%%'",
            (self.db_schema,),
        )
        return cur.fetchone()[0] > 0

    def _drop_container_sql(self):
        return f"DROP SCHEMA IF EXISTS {self.ADAPTER.quote(self.db_schema)} CASCADE"

    def _table_exists(self, con, table):
        cur = self._execute(
            con,
            "SELECT 1 FROM information_schema.tables "
            "WHERE table_schema = %s AND table_name = %s",
            (self.db_schema, table),
        )
        return cur.fetchone() is not None

    def _table_columns(self, con, table):
        return read_table_columns(con, self.db_schema, table)

    def _extra_meta_items(self, con, table):
        out = {}
        cur = self._execute(
            con,
            "SELECT SRS.srtext FROM geometry_columns GC "
            "INNER JOIN spatial_ref_sys SRS ON GC.srid = SRS.srid "
            "WHERE GC.f_table_schema = %s AND GC.f_table_name = %s",
            (self.db_schema, table),
        )
        for (srtext,) in cur.fetchall():
            if srtext:
                out[f"crs/{get_identifier_str(srtext)}.wkt"] = normalise_wkt(srtext)
        return out

    def _post_write_dataset(self, con, ds, table, crs_id):
        schema = ds.schema
        geom_col = schema.first_geometry_column
        if geom_col is not None:
            # GiST spatial index (reference: postgis.py write_meta)
            self._execute(
                con,
                f'CREATE INDEX IF NOT EXISTS "{table}_idx_geom" ON '
                f"{self._table_identifier(table)} USING GIST "
                f"({self.ADAPTER.quote(geom_col.name)})",
            )
        pk_cols = schema.pk_columns
        if len(pk_cols) == 1 and pk_cols[0].data_type == "integer":
            # align the SERIAL sequence past existing pks
            q_pk = self.ADAPTER.quote(pk_cols[0].name)
            tbl = self._table_identifier(table)
            self._execute(
                con,
                f"SELECT setval(pg_get_serial_sequence(%s, %s), "
                f"(SELECT COALESCE(MAX({q_pk}), 0) + 1 FROM {tbl}), false)",
                (tbl, pk_cols[0].name),
            )
