"""PostGIS working copy (reference: kart/working_copy/postgis.py).

Requires psycopg2, which is not part of this environment's baked dependency
set — the class is import-gated: construction raises a clear error unless the
driver is installed. The schema mapping mirrors the GPKG working copy with a
db-schema-scoped namespace and procedure-based tracking triggers.
"""


class PostgisWorkingCopy:
    def __init__(self, repo, location):
        try:
            import psycopg2  # noqa: F401
        except ImportError:
            from kart_tpu.core.repo import NotFound

            raise NotFound(
                "PostGIS working copies require the psycopg2 driver, which is "
                "not installed in this environment. Use a GPKG working copy, "
                "or install psycopg2."
            )
        raise NotImplementedError(
            "PostGIS working copy support is not implemented yet"
        )
