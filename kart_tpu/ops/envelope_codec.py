"""Bit-packed envelope codec, vectorized
(reference: kart/spatial_filter/index.py:485-548 EnvelopeEncoder and its C++
mirror vendor/spatial-filter/spatial_filter.cpp:30-152).

An envelope (w, s, e, n) in EPSG:4326 packs to 4 x 20-bit fixed-point values
(floor for w/s, ceil for e/n — the stored envelope always *contains* the real
one) concatenated big-endian into 10 bytes. Byte-compatible with the
reference's feature_envelopes.db so either implementation can read the other's
index. Scalar API matches the reference class; the batch API runs the whole
table as numpy uint64 lane math.
"""

import math

import numpy as np

DEFAULT_BITS_PER_VALUE = 20


class EnvelopeCodec:
    def __init__(self, bits_per_value=DEFAULT_BITS_PER_VALUE):
        assert bits_per_value % 2 == 0
        self.bits = bits_per_value
        self.value_max = 2**bits_per_value - 1
        self.nbytes = bits_per_value // 2  # 4 values * bits / 8

    # -- scalar (reference-identical) ---------------------------------------

    def encode(self, envelope):
        w, s, e, n = envelope
        integer = self._encode_value(w, -180, 180, math.floor)
        integer = (integer << self.bits) | self._encode_value(s, -90, 90, math.floor)
        integer = (integer << self.bits) | self._encode_value(e, -180, 180, math.ceil)
        integer = (integer << self.bits) | self._encode_value(n, -90, 90, math.ceil)
        return integer.to_bytes(self.nbytes, "big")

    def _encode_value(self, value, lo, hi, round_fn):
        assert lo <= value <= hi, (value, lo, hi)
        return round_fn((value - lo) / (hi - lo) * self.value_max)

    def decode(self, data):
        integer = int.from_bytes(data, "big")
        n = self._decode_value(integer & self.value_max, -90, 90)
        integer >>= self.bits
        e = self._decode_value(integer & self.value_max, -180, 180)
        integer >>= self.bits
        s = self._decode_value(integer & self.value_max, -90, 90)
        integer >>= self.bits
        w = self._decode_value(integer & self.value_max, -180, 180)
        return w, s, e, n

    def _decode_value(self, encoded, lo, hi):
        return encoded / self.value_max * (hi - lo) + lo

    # -- batch (numpy) -------------------------------------------------------

    def encode_batch(self, envelopes):
        """(N,4) float64 w,s,e,n -> (N, nbytes) uint8, identical bytes to the
        scalar path. Raises on out-of-range / NaN values (the scalar path
        asserts; silent uint64 wraparound would corrupt the shared index)."""
        env = np.asarray(envelopes, dtype=np.float64)
        lo = np.array([-180.0, -90.0, -180.0, -90.0])
        hi = np.array([180.0, 90.0, 180.0, 90.0])
        bad = ~((env >= lo) & (env <= hi))  # NaN compares False on both
        if bad.any():
            rows = np.nonzero(bad.any(axis=1))[0][:5]
            raise ValueError(
                f"Envelope values out of range at rows {rows.tolist()}: "
                f"{env[rows].tolist()}"
            )
        vmax = np.float64(self.value_max)
        w = np.floor((env[:, 0] + 180.0) / 360.0 * vmax).astype(np.uint64)
        s = np.floor((env[:, 1] + 90.0) / 180.0 * vmax).astype(np.uint64)
        e = np.ceil((env[:, 2] + 180.0) / 360.0 * vmax).astype(np.uint64)
        n = np.ceil((env[:, 3] + 90.0) / 180.0 * vmax).astype(np.uint64)
        bits = np.uint64(self.bits)
        hi = (w << bits) | s  # 2*bits wide
        lo = (e << bits) | n
        half_bytes = self.nbytes // 2
        out = np.empty((env.shape[0], self.nbytes), dtype=np.uint8)
        for i in range(half_bytes):
            shift = np.uint64(8 * (half_bytes - 1 - i))
            out[:, i] = ((hi >> shift) & np.uint64(0xFF)).astype(np.uint8)
            out[:, half_bytes + i] = ((lo >> shift) & np.uint64(0xFF)).astype(np.uint8)
        return out

    def decode_batch(self, data):
        """(N, nbytes) uint8 -> (N,4) float64 w,s,e,n."""
        data = np.asarray(data, dtype=np.uint8)
        half_bytes = self.nbytes // 2
        hi = np.zeros(data.shape[0], dtype=np.uint64)
        lo = np.zeros(data.shape[0], dtype=np.uint64)
        for i in range(half_bytes):
            hi = (hi << np.uint64(8)) | data[:, i].astype(np.uint64)
            lo = (lo << np.uint64(8)) | data[:, half_bytes + i].astype(np.uint64)
        bits = np.uint64(self.bits)
        mask = np.uint64(self.value_max)
        vmax = np.float64(self.value_max)
        w = ((hi >> bits) & mask).astype(np.float64) / vmax * 360.0 - 180.0
        s = (hi & mask).astype(np.float64) / vmax * 180.0 - 90.0
        e = ((lo >> bits) & mask).astype(np.float64) / vmax * 360.0 - 180.0
        n = (lo & mask).astype(np.float64) / vmax * 180.0 - 90.0
        return np.stack([w, s, e, n], axis=1)
