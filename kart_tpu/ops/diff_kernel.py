"""Vectorized diff classification — reference hot loop #1 as one jitted
merge-join (SURVEY.md §3.1, rich_base_dataset.py:205-300).

Given two FeatureBlocks (sorted key+oid arrays, padded), classification is a
pair of ``searchsorted`` joins plus an elementwise oid compare — no Python
per-feature work, no data-dependent control flow, static shapes: exactly the
program XLA fuses into a few device loops. The same jitted function runs on
TPU and CPU with identical results (the tests' bit-compat contract).

Classes: 0 = unchanged, 1 = insert, 2 = update, 3 = delete.
"""

import jax
import jax.numpy as jnp
import numpy as np

UNCHANGED = 0
INSERT = 1
UPDATE = 2
DELETE = 3


@jax.jit
def _classify_padded(old_keys, old_oids, new_keys, new_oids, old_count, new_count):
    """Core join. Padded inputs; counts are *dynamic* scalars so only the
    padded (bucket) shapes drive compilation — each (old_bucket, new_bucket)
    pair compiles exactly once."""
    n_old = old_keys.shape[0]
    n_new = new_keys.shape[0]
    old_valid = jnp.arange(n_old) < old_count
    new_valid = jnp.arange(n_new) < new_count

    # old -> new join
    idx_in_new = jnp.searchsorted(new_keys, old_keys)
    idx_in_new_c = jnp.minimum(idx_in_new, n_new - 1)
    old_found = (new_keys[idx_in_new_c] == old_keys) & (idx_in_new < n_new)
    old_found &= idx_in_new_c < new_count
    oid_same = jnp.all(
        old_oids == new_oids[idx_in_new_c], axis=1
    )
    old_class = jnp.where(
        old_valid,
        jnp.where(
            old_found,
            jnp.where(oid_same, UNCHANGED, UPDATE),
            DELETE,
        ),
        UNCHANGED,
    ).astype(jnp.int8)

    # new -> old join (only inserts remain to be found)
    idx_in_old = jnp.searchsorted(old_keys, new_keys)
    idx_in_old_c = jnp.minimum(idx_in_old, n_old - 1)
    new_found = (old_keys[idx_in_old_c] == new_keys) & (idx_in_old < n_old)
    new_found &= idx_in_old_c < old_count
    new_class = jnp.where(
        new_valid,
        jnp.where(new_found, UNCHANGED, INSERT),
        UNCHANGED,
    ).astype(jnp.int8)
    # mark updates on the new side too (same classification, new-row view)
    new_oid_same = jnp.all(new_oids == old_oids[idx_in_old_c], axis=1)
    new_class = jnp.where(
        new_valid & new_found & ~new_oid_same, UPDATE, new_class
    ).astype(jnp.int8)

    counts = jnp.stack(
        [
            jnp.sum(new_class == INSERT),
            jnp.sum(old_class == UPDATE),
            jnp.sum(old_class == DELETE),
        ]
    )
    return old_class, new_class, idx_in_new_c, counts


def classify_blocks(old_block, new_block):
    """FeatureBlock x2 -> (old_class np.int8 (n_old,), new_class (n_new,),
    counts dict). Host wrapper: unpads and returns numpy."""
    old_class, new_class, _, counts = _classify_padded(
        jnp.asarray(old_block.keys),
        jnp.asarray(old_block.oids),
        jnp.asarray(new_block.keys),
        jnp.asarray(new_block.oids),
        old_block.count,
        new_block.count,
    )
    old_class = np.asarray(old_class)[: old_block.count]
    new_class = np.asarray(new_class)[: new_block.count]
    counts = np.asarray(counts)
    return (
        old_class,
        new_class,
        {"inserts": int(counts[0]), "updates": int(counts[1]), "deletes": int(counts[2])},
    )


def classify_blocks_reference(old_block, new_block):
    """Pure-numpy reference with identical semantics, for bit-compat tests."""
    old_keys = old_block.keys[: old_block.count]
    new_keys = new_block.keys[: new_block.count]
    old_oids = old_block.oids[: old_block.count]
    new_oids = new_block.oids[: new_block.count]

    idx = np.searchsorted(new_keys, old_keys)
    idxc = np.minimum(idx, max(len(new_keys) - 1, 0))
    if len(new_keys):
        found = (new_keys[idxc] == old_keys) & (idx < len(new_keys))
        oid_same = np.all(old_oids == new_oids[idxc], axis=1)
    else:
        found = np.zeros(len(old_keys), dtype=bool)
        oid_same = found
    old_class = np.where(
        found, np.where(oid_same, UNCHANGED, UPDATE), DELETE
    ).astype(np.int8)

    idx2 = np.searchsorted(old_keys, new_keys)
    idx2c = np.minimum(idx2, max(len(old_keys) - 1, 0))
    if len(old_keys):
        found2 = (old_keys[idx2c] == new_keys) & (idx2 < len(old_keys))
        oid_same2 = np.all(new_oids == old_oids[idx2c], axis=1)
    else:
        found2 = np.zeros(len(new_keys), dtype=bool)
        oid_same2 = found2
    new_class = np.where(
        found2, np.where(oid_same2, UNCHANGED, UPDATE), INSERT
    ).astype(np.int8)
    return old_class, new_class


def changed_indices(old_class, new_class):
    """-> (old_changed_idx, new_changed_idx): row indices whose values need
    materialising (everything except UNCHANGED)."""
    return (
        np.nonzero(old_class != UNCHANGED)[0],
        np.nonzero(new_class != UNCHANGED)[0],
    )


@jax.jit
def columnar_equal(old_cols, new_cols, null_mask_old, null_mask_new):
    """Row equality over aligned columnar attribute data (the working-copy
    compare, reference hot loop #2 base.py:722): all columns equal and same
    null pattern. cols: (C, N) arrays (numeric/hash-encoded), masks (C, N)."""
    return jnp.all((old_cols == new_cols) & (null_mask_old == null_mask_new), axis=0)
