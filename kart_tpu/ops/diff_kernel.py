"""Vectorized diff classification — reference hot loop #1 as one jitted
merge-join (SURVEY.md §3.1, rich_base_dataset.py:205-300).

Given two FeatureBlocks (sorted key+oid arrays, padded), classification runs
entirely on device with no Python per-feature work, no data-dependent control
flow, and static shapes. Two device kernels with identical semantics:

- ``_classify_padded`` (the flagship, default on accelerators): one 3-operand
  ``lax.sort`` of the concatenated keys (with concat position for stability
  and a 64-bit oid fold as the payload) brings every old/new pair of the same
  key adjacent, then neighbour compares classify all keys at once and a
  scatter returns classes to block order. TPU's bitonic sort network is ~20x
  faster than the log(n) serial gather rounds a binary search lowers to, and
  streaming the folded oid through the sort beats a post-sort random gather
  of (n,5) oid rows ~2x: 2 linear passes over HBM (sort, scatter).
- ``_classify_padded_binsearch``: a pair of ``searchsorted`` joins — faster
  on CPU where binary search doesn't serialise. Bit-identical to the sort
  path: both compare full 160-bit oids (the sort path re-verifies its
  64-bit fold matches via a monotonic partner gather), as does the numpy
  reference below.

Classes: 0 = unchanged, 1 = insert, 2 = update, 3 = delete.
"""

import os

import numpy as np

from kart_tpu.ops._lazy import lazy_jit

UNCHANGED = 0
INSERT = 1
UPDATE = 2
DELETE = 3


def _fold_oids(oids):
    """(n, 5) uint32 sha1 words -> (n,) int64 mixed fold. Object identity is
    already a content hash; folding 160 -> 64 bits keeps equality testing
    exact to within a 2^-64 per-pair collision (far below the sha1 trust
    the reference's own content addressing extends). The multiply/xor-shift
    mix stops structured oid differences from cancelling in the fold."""
    import jax.numpy as jnp

    a = oids.astype(jnp.uint64)
    h = a[:, 0] ^ (a[:, 1] << 32)
    h2 = a[:, 2] ^ (a[:, 3] << 32)
    h = (h * jnp.uint64(0x9E3779B97F4A7C15)) ^ h2
    h = h ^ (h >> 29)
    h = h * jnp.uint64(0xBF58476D1CE4E5B9)
    h = h ^ a[:, 4]
    return h.astype(jnp.int64)


def _classify_mergesort_core(
    old_keys, old_oids, new_keys, new_oids, old_count, new_count
):
    """Traceable core of the sort-based join (shared by the single-chip jit
    and the shard_map body). Padded inputs; counts are *dynamic* scalars so
    only the padded (bucket) shapes drive compilation.

    Keys are unique within each side (PKs / path hashes), so after a stable
    sort of concat(old, new) each key appears once or twice, old first —
    classification is a neighbour compare. Padding (PAD_KEY) sorts last and
    is masked out of the classes by the count mask at the end.

    The 160-bit oids travel through the sort as a 64-bit fold
    (:func:`_fold_oids`) — a third sort operand streams sequentially through
    the sort network, where gathering (n,5) oid rows by the sorted
    permutation afterwards is a large random HBM access pattern (measured
    ~3x slower end-to-end on TPU v5e at 10M rows).
    """
    import jax
    import jax.numpy as jnp

    n_old = old_keys.shape[0]
    n_new = new_keys.shape[0]
    total = n_old + n_new

    keys = jnp.concatenate([old_keys, new_keys])
    gidx = jnp.arange(total, dtype=jnp.int32)
    vals = jnp.concatenate([_fold_oids(old_oids), _fold_oids(new_oids)])
    # 2nd sort key = concat position: stable old-before-new on equal keys
    sk, sg, sv = jax.lax.sort((keys, gidx, vals), num_keys=2)
    is_old = sg < n_old

    pair = (sk[:-1] == sk[1:]) & is_old[:-1] & ~is_old[1:]
    pair_eq = pair & (sv[:-1] == sv[1:])
    false1 = jnp.zeros(1, dtype=bool)
    matched_left = jnp.concatenate([pair, false1])
    eq_left = jnp.concatenate([pair_eq, false1])
    matched_right = jnp.concatenate([false1, pair])
    eq_right = jnp.concatenate([false1, pair_eq])

    cls_sorted = jnp.where(
        is_old,
        jnp.where(matched_left, jnp.where(eq_left, UNCHANGED, UPDATE), DELETE),
        jnp.where(matched_right, jnp.where(eq_right, UNCHANGED, UPDATE), INSERT),
    ).astype(jnp.int8)
    out = jnp.zeros(total, jnp.int8).at[sg].set(cls_sorted)
    old_class = jnp.where(
        jnp.arange(n_old) < old_count, out[:n_old], UNCHANGED
    ).astype(jnp.int8)
    new_class = jnp.where(
        jnp.arange(n_new) < new_count, out[n_old:], UNCHANGED
    ).astype(jnp.int8)

    # partner row in `new` for each matched old row (0 when unmatched)
    partner_sorted = jnp.where(
        matched_left, jnp.roll(sg, -1) - n_old, 0
    ).astype(jnp.int32)
    partner_full = jnp.zeros(total, jnp.int32).at[sg].set(partner_sorted)
    idx_in_new = partner_full[:n_old]

    # Exactness restore: a pair the fold called equal is re-checked against
    # the full 160-bit oids. Both blocks are key-sorted so idx_in_new is
    # monotonic — this gather streams, unlike the random post-sort gather
    # the fold exists to avoid. A fold collision therefore surfaces as an
    # UPDATE instead of a silent diff miss.
    full_eq = jnp.all(old_oids == new_oids[idx_in_new], axis=1)
    collide = (
        (old_class == UNCHANGED) & (jnp.arange(n_old) < old_count) & ~full_eq
    )
    old_class = jnp.where(collide, UPDATE, old_class).astype(jnp.int8)
    new_class = new_class.at[jnp.where(collide, idx_in_new, 0)].max(
        jnp.where(collide, UPDATE, 0).astype(jnp.int8)
    )

    counts = jnp.stack(
        [
            jnp.sum(new_class == INSERT),
            jnp.sum(old_class == UPDATE),
            jnp.sum(old_class == DELETE),
        ]
    )
    return old_class, new_class, idx_in_new, counts


_classify_padded = lazy_jit(_classify_mergesort_core)


def _classify_binsearch_core(
    old_keys, old_oids, new_keys, new_oids, old_count, new_count
):
    """Binary-search join: the CPU-backend variant."""
    import jax.numpy as jnp

    n_old = old_keys.shape[0]
    n_new = new_keys.shape[0]
    old_valid = jnp.arange(n_old) < old_count
    new_valid = jnp.arange(n_new) < new_count

    # old -> new join
    idx_in_new = jnp.searchsorted(new_keys, old_keys)
    idx_in_new_c = jnp.minimum(idx_in_new, n_new - 1)
    old_found = (new_keys[idx_in_new_c] == old_keys) & (idx_in_new < n_new)
    old_found &= idx_in_new_c < new_count
    oid_same = jnp.all(
        old_oids == new_oids[idx_in_new_c], axis=1
    )
    old_class = jnp.where(
        old_valid,
        jnp.where(
            old_found,
            jnp.where(oid_same, UNCHANGED, UPDATE),
            DELETE,
        ),
        UNCHANGED,
    ).astype(jnp.int8)

    # new -> old join (only inserts remain to be found)
    idx_in_old = jnp.searchsorted(old_keys, new_keys)
    idx_in_old_c = jnp.minimum(idx_in_old, n_old - 1)
    new_found = (old_keys[idx_in_old_c] == new_keys) & (idx_in_old < n_old)
    new_found &= idx_in_old_c < old_count
    new_class = jnp.where(
        new_valid,
        jnp.where(new_found, UNCHANGED, INSERT),
        UNCHANGED,
    ).astype(jnp.int8)
    # mark updates on the new side too (same classification, new-row view)
    new_oid_same = jnp.all(new_oids == old_oids[idx_in_old_c], axis=1)
    new_class = jnp.where(
        new_valid & new_found & ~new_oid_same, UPDATE, new_class
    ).astype(jnp.int8)

    counts = jnp.stack(
        [
            jnp.sum(new_class == INSERT),
            jnp.sum(old_class == UPDATE),
            jnp.sum(old_class == DELETE),
        ]
    )
    return old_class, new_class, idx_in_new_c, counts


_classify_padded_binsearch = lazy_jit(_classify_binsearch_core)

def _env_int(name, default):
    """Tolerant env knob: a malformed value must never kill the CLI."""
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        import logging

        logging.getLogger("kart_tpu.ops").warning(
            "ignoring malformed %s=%r", name, os.environ[name]
        )
        return default


# below this row count the numpy twin beats the device round trip (and never
# touches backend init / compile — a `kart diff` of a small repo must be
# instant even when the accelerator is wedged or cold). Measured e2e on a
# tunneled v5e: numpy 0.35s vs device 1.85s at 1M rows (transfer-dominated);
# the device wins decisively by 10M. Hosts with local PCIe-attached chips
# can lower this via the env knob.
DEVICE_MIN_ROWS = _env_int("KART_DEVICE_MIN_ROWS", 2_000_000)

# above this row count the accelerator path streams the blocks chunk-wise so
# host->HBM transfer of chunk i+1 overlaps the sort of chunk i (SURVEY §2.3
# "pipelined lazy diff streaming") instead of paying one monolithic upload
STREAM_MIN_ROWS = _env_int("KART_STREAM_MIN_ROWS", 16_000_000)
STREAM_CHUNK_ROWS = _env_int("KART_STREAM_CHUNK_ROWS", 8_000_000)


def device_profitable(n_rows):
    """Cost-model routing for the classify kernels: True when the device
    round trip is expected to beat the host engine.

    - Below DEVICE_MIN_ROWS the host path wins on any backend (no backend
      init, no compile, no transfer) — and the check runs before any jax
      import, so small diffs stay instant even with a wedged accelerator.
    - On an XLA-**CPU** backend the host engine wins at *every* size: the
      native C++ merge-join is sequential-scan bound (~1.1 s at 100M rows)
      where the XLA join lost 13.6x at 100M (measured r3: 65.3 s vs 4.8 s),
      and even the numpy twin beats XLA-CPU. XLA-CPU exists for correctness
      twins and virtual-mesh tests, not as a production diff engine.
    - On a real accelerator, size is the only question.

    KART_DIFF_DEVICE=1/0 forces the answer (tests, experiments)."""
    mode = os.environ.get("KART_DIFF_DEVICE", "auto")
    if mode == "0":
        return False
    if n_rows < DEVICE_MIN_ROWS and mode != "1":
        return False
    from kart_tpu.runtime import default_backend, jax_ready

    if not jax_ready():
        return False
    return mode == "1" or default_backend() != "cpu"


def classify_blocks(old_block, new_block):
    """FeatureBlock x2 -> (old_class np.int8 (n_old,), new_class (n_new,),
    counts dict). Host wrapper: unpads and returns numpy. Routing is a cost
    model (:func:`device_profitable`): the host engine owns small blocks,
    CPU backends and wedged accelerators; real accelerators get the sort-join
    kernel — streamed in double-buffered chunks at north-star scale so
    transfer overlaps compute. Bit-identical results on every route (the
    sort path device-verifies its oid fold against full oids)."""
    from kart_tpu.runtime import default_backend

    n_rows = max(old_block.count, new_block.count)
    if not device_profitable(n_rows):
        # the host merge-join reads count-sliced views directly — callers
        # may pass unpadded (mmap-backed) blocks with no copy at all
        return classify_blocks_host(old_block, new_block)
    try:
        if n_rows >= STREAM_MIN_ROWS and default_backend() != "cpu":
            return classify_blocks_streamed(old_block, new_block)
        kernel = (
            _classify_padded_binsearch
            if default_backend() == "cpu"
            else _classify_padded
        )
        ok, oo = _padded_arrays(old_block)
        nk, no = _padded_arrays(new_block)
        old_class, new_class, _, counts = kernel(
            ok,
            oo,
            nk,
            no,
            old_block.count,
            new_block.count,
        )
    except Exception as e:
        # device OOM / tunnel failure mid-call: the CLI must still complete
        # (north-star scale can exceed a single chip's HBM)
        import logging

        logging.getLogger("kart_tpu.ops").warning(
            "device classify failed (%s: %s); using host path",
            type(e).__name__,
            e,
        )
        return classify_blocks_host(old_block, new_block)
    old_class = np.asarray(old_class)[: old_block.count]
    new_class = np.asarray(new_class)[: new_block.count]
    counts = np.asarray(counts)
    return (
        old_class,
        new_class,
        {"inserts": int(counts[0]), "updates": int(counts[1]), "deletes": int(counts[2])},
    )


def stream_chunk_splits(key_arrays, chunk_rows):
    """Key-space chunking for the streamed device paths: sorted key arrays
    (one per block side) -> (per-side split-point arrays, n_chunks), where
    chunk c of side s is rows ``splits[s][c]:splits[s][c+1]``. A key falls
    in the same chunk on every side, so merge-joins stay chunk-local.

    Boundaries balance the *combined* population: quantiles of one side
    alone collapse under key-range skew (e.g. a renumbered-PK revision
    whose new keys all exceed the old range would pile every new row into
    one chunk). Candidate keys are fine-grained quantiles of each side;
    each target combined-rank picks the nearest candidate."""
    chunk_rows = max(int(chunk_rows), 1)
    n_chunks = max(1, -(-max(len(k) for k in key_arrays) // chunk_rows))
    total = sum(len(k) for k in key_arrays)

    def _quantile_keys(keys, m):
        if not len(keys) or m <= 0:
            return keys[:0]
        return keys[(np.arange(1, m) * len(keys)) // m]

    cand = np.unique(
        np.concatenate([_quantile_keys(k, 4 * n_chunks) for k in key_arrays])
    )
    if len(cand):
        ranks = sum(np.searchsorted(k, cand) for k in key_arrays)
        targets = (np.arange(1, n_chunks) * total) // n_chunks
        picks = np.searchsorted(ranks, targets)
        bounds = np.unique(cand[np.minimum(picks, len(cand) - 1)])
    else:
        bounds = cand
    splits = tuple(
        np.concatenate(([0], np.searchsorted(k, bounds), [len(k)]))
        for k in key_arrays
    )
    return splits, len(bounds) + 1


def classify_blocks_streamed(old_block, new_block, chunk_rows=None):
    """Double-buffered chunked device classify for blocks too large to ship
    to HBM as one upload (SURVEY §2.3 "pipelined lazy diff streaming").

    Both blocks are key-sorted, so splitting the *key space* at common
    boundary values (quantiles of the larger side) partitions the merge-join
    into independent chunk-local joins: a key falls in the same chunk on both
    sides, and no old/new pair ever straddles a boundary. Each chunk is
    padded to one shared bucket size (a single compiled shape), transferred
    with ``jax.device_put`` — which is asynchronous — and dispatched
    immediately; with two chunks in flight, chunk i+1's host->HBM copy
    overlaps chunk i's on-device sort. Results drain back in order.

    Semantics identical to the monolithic kernel (tested); counts are the
    sum of per-chunk count vectors."""
    import jax

    from collections import deque

    from kart_tpu.ops.blocks import PAD_KEY, bucket_size as _bucket

    if chunk_rows is None:
        chunk_rows = max(STREAM_CHUNK_ROWS, 1)
    n_old, n_new = old_block.count, new_block.count
    old_keys = old_block.keys[:n_old]
    new_keys = new_block.keys[:n_new]
    (old_splits, new_splits), n_chunks = stream_chunk_splits(
        (old_keys, new_keys), chunk_rows
    )
    max_len = max(
        int(np.max(np.diff(old_splits))), int(np.max(np.diff(new_splits))), 1
    )
    bucket = _bucket(max_len)

    def _padded(keys, oids, lo, hi):
        k = np.full(bucket, PAD_KEY, dtype=np.int64)
        o = np.zeros((bucket, 5), dtype=np.uint32)
        k[: hi - lo] = keys[lo:hi]
        o[: hi - lo] = oids[lo:hi]
        return k, o

    old_class = np.empty(n_old, dtype=np.int8)
    new_class = np.empty(n_new, dtype=np.int8)
    totals = np.zeros(3, dtype=np.int64)
    in_flight = deque()

    def _drain():
        out, (olo, ohi), (nlo, nhi) = in_flight.popleft()
        oc, nc, _, counts = out
        old_class[olo:ohi] = np.asarray(oc)[: ohi - olo]
        new_class[nlo:nhi] = np.asarray(nc)[: nhi - nlo]
        totals[:] += np.asarray(counts)

    for c in range(n_chunks):
        olo, ohi = int(old_splits[c]), int(old_splits[c + 1])
        nlo, nhi = int(new_splits[c]), int(new_splits[c + 1])
        ok, oo = _padded(old_keys, old_block.oids, olo, ohi)
        nk, no = _padded(new_keys, new_block.oids, nlo, nhi)
        dev = [jax.device_put(a) for a in (ok, oo, nk, no)]
        out = _classify_padded(dev[0], dev[1], dev[2], dev[3], ohi - olo, nhi - nlo)
        in_flight.append((out, (olo, ohi), (nlo, nhi)))
        if len(in_flight) >= 2:
            _drain()
    while in_flight:
        _drain()
    return (
        old_class,
        new_class,
        {
            "inserts": int(totals[0]),
            "updates": int(totals[1]),
            "deletes": int(totals[2]),
        },
    )


def _padded_arrays(block):
    """(keys, oids) padded to the bucket size the monolithic device kernels
    compile for; a no-op view when the block is already padded (only the
    device route pays the copy — the host engine and the streamed/sharded
    paths take count-sliced views)."""
    from kart_tpu.ops.blocks import PAD_KEY, bucket_size

    n = block.count
    size = bucket_size(max(n, 1))
    if len(block.keys) >= size:
        return block.keys, block.oids
    keys = np.full(size, PAD_KEY, dtype=np.int64)
    keys[:n] = block.keys[:n]
    oids = np.zeros((size, 5), dtype=np.uint32)
    oids[:n] = block.oids[:n]
    return keys, oids


def classify_blocks_host(old_block, new_block):
    """Host-engine classify: the native C++ merge-join when the IO lib is
    built (sequential scans — 1.1s at 100M rows, where numpy's searchsorted
    pays a cache miss per probe), the numpy twin otherwise. Bit-identical
    to classify_blocks_reference either way (tested)."""
    from kart_tpu import native

    n_old, n_new = old_block.count, new_block.count
    res = native.classify_sorted(
        old_block.keys[:n_old],
        old_block.oids[:n_old].view(np.uint8).reshape(n_old, 20),
        new_block.keys[:n_new],
        new_block.oids[:n_new].view(np.uint8).reshape(n_new, 20),
    )
    if res is not None:
        return res
    old_class, new_class = classify_blocks_reference(old_block, new_block)
    return (
        old_class,
        new_class,
        {
            "inserts": int(np.sum(new_class == INSERT)),
            "updates": int(np.sum(old_class == UPDATE)),
            "deletes": int(np.sum(old_class == DELETE)),
        },
    )


def classify_blocks_reference(old_block, new_block):
    """Pure-numpy reference with identical semantics, for bit-compat tests."""
    old_keys = old_block.keys[: old_block.count]
    new_keys = new_block.keys[: new_block.count]
    old_oids = old_block.oids[: old_block.count]
    new_oids = new_block.oids[: new_block.count]

    idx = np.searchsorted(new_keys, old_keys)
    idxc = np.minimum(idx, max(len(new_keys) - 1, 0))
    if len(new_keys):
        found = (new_keys[idxc] == old_keys) & (idx < len(new_keys))
        oid_same = np.all(old_oids == new_oids[idxc], axis=1)
    else:
        found = np.zeros(len(old_keys), dtype=bool)
        oid_same = found
    old_class = np.where(
        found, np.where(oid_same, UNCHANGED, UPDATE), DELETE
    ).astype(np.int8)

    idx2 = np.searchsorted(old_keys, new_keys)
    idx2c = np.minimum(idx2, max(len(old_keys) - 1, 0))
    if len(old_keys):
        found2 = (old_keys[idx2c] == new_keys) & (idx2 < len(old_keys))
        oid_same2 = np.all(new_oids == old_oids[idx2c], axis=1)
    else:
        found2 = np.zeros(len(new_keys), dtype=bool)
        oid_same2 = found2
    new_class = np.where(
        found2, np.where(oid_same2, UNCHANGED, UPDATE), INSERT
    ).astype(np.int8)
    return old_class, new_class


def changed_indices(old_class, new_class):
    """-> (old_changed_idx, new_changed_idx): row indices whose values need
    materialising (everything except UNCHANGED)."""
    return (
        np.nonzero(old_class != UNCHANGED)[0],
        np.nonzero(new_class != UNCHANGED)[0],
    )


def _columnar_equal_core(old_cols, new_cols, null_mask_old, null_mask_new):
    """Row equality over aligned columnar attribute data (the working-copy
    compare, reference hot loop #2 base.py:722): all columns equal and same
    null pattern. cols: (C, N) arrays (numeric/hash-encoded), masks (C, N)."""
    import jax.numpy as jnp

    return jnp.all((old_cols == new_cols) & (null_mask_old == null_mask_new), axis=0)


columnar_equal = lazy_jit(_columnar_equal_core)
