"""TPU compute kernels: columnar feature blocks, diff classification,
bbox intersection, envelope codec.

x64 is enabled here: feature identity keys are int64 (pks can exceed 2^31 and
hash keys use the full 63 bits); without this JAX silently downcasts to int32,
wrapping the pad sentinel and corrupting every sorted-join. The compute-heavy
kernels (bbox, envelope) still use explicit f32/int8 — x64 only widens what is
already 64-bit on the host.

jax itself is NOT imported here (that costs ~1.8s per process — see
ops/_lazy.py): the env var covers the not-yet-imported case and the config
update covers callers that imported jax first (tests, the runtime probe).
"""

import os
import sys

os.environ.setdefault("JAX_ENABLE_X64", "True")
if "jax" in sys.modules:
    sys.modules["jax"].config.update("jax_enable_x64", True)
