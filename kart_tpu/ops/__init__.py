"""TPU compute kernels: columnar feature blocks, diff classification,
bbox intersection, envelope codec.

x64 is enabled here: feature identity keys are int64 (pks can exceed 2^31 and
hash keys use the full 63 bits); without this JAX silently downcasts to int32,
wrapping the pad sentinel and corrupting every sorted-join. The compute-heavy
kernels (bbox, envelope) still use explicit f32/int8 — x64 only widens what is
already 64-bit on the host.
"""

import jax

jax.config.update("jax_enable_x64", True)
