"""Vectorized 3-way merge classification (reference: the libgit2 tree merge
behind `kart/merge.py:99-100` + per-feature conflict semantics of
`kart/merge_util.py`).

Kart gets per-feature merge "for free" because one feature == one blob at a
PK-determined path, and libgit2 merges trees path-by-path. Here the same
semantics run as one jitted kernel over the *union* key array of the
(ancestor, ours, theirs) FeatureBlocks: three searchsorted joins produce
per-key (present, oid) triples, then the classic 3-way rule classifies every
key at once — no per-feature Python, no data-dependent control flow.

Per-key decision for versions a/o/t (absent = not present):
    o == t           -> KEEP_OURS   (same change both sides, incl. both absent)
    o == a           -> TAKE_THEIRS (only theirs changed)
    t == a           -> KEEP_OURS   (only ours changed)
    otherwise        -> CONFLICT

Codes: 0 = KEEP_OURS, 1 = TAKE_THEIRS, 2 = CONFLICT.
"""

import numpy as np

from kart_tpu.ops._lazy import lazy_jit
from kart_tpu.ops.blocks import PAD_KEY, bucket_size

KEEP_OURS = 0
TAKE_THEIRS = 1
CONFLICT = 2


def _join(version_keys, version_oids, version_count, union_keys):
    """For each union key: (present (bool), oid (5,) uint32 or 0)."""
    import jax.numpy as jnp

    n = version_keys.shape[0]
    idx = jnp.searchsorted(version_keys, union_keys)
    idxc = jnp.minimum(idx, n - 1)
    present = (version_keys[idxc] == union_keys) & (idx < n) & (idxc < version_count)
    oids = jnp.where(present[:, None], version_oids[idxc], 0)
    return present, oids


def _merge_classify_padded_core(
    a_keys, a_oids, a_count,
    o_keys, o_oids, o_count,
    t_keys, t_oids, t_count,
    union_keys, union_count,
):
    import jax.numpy as jnp

    union_valid = jnp.arange(union_keys.shape[0]) < union_count
    a_pres, a_oid = _join(a_keys, a_oids, a_count, union_keys)
    o_pres, o_oid = _join(o_keys, o_oids, o_count, union_keys)
    t_pres, t_oid = _join(t_keys, t_oids, t_count, union_keys)

    def same(p1, oid1, p2, oid2):
        both_absent = ~p1 & ~p2
        both_same = p1 & p2 & jnp.all(oid1 == oid2, axis=1)
        return both_absent | both_same

    o_eq_t = same(o_pres, o_oid, t_pres, t_oid)
    o_eq_a = same(o_pres, o_oid, a_pres, a_oid)
    t_eq_a = same(t_pres, t_oid, a_pres, a_oid)

    decision = jnp.where(
        o_eq_t,
        KEEP_OURS,
        jnp.where(
            o_eq_a,
            TAKE_THEIRS,
            jnp.where(t_eq_a, KEEP_OURS, CONFLICT),
        ),
    )
    decision = jnp.where(union_valid, decision, KEEP_OURS).astype(jnp.int8)
    n_conflicts = jnp.sum(decision == CONFLICT)
    n_take_theirs = jnp.sum(decision == TAKE_THEIRS)
    presence = (
        a_pres.astype(jnp.int8)
        + 2 * o_pres.astype(jnp.int8)
        + 4 * t_pres.astype(jnp.int8)
    )
    return decision, presence, n_conflicts, n_take_theirs


_merge_classify_padded = lazy_jit(_merge_classify_padded_core)


def merge_classify(ancestor_block, ours_block, theirs_block):
    """FeatureBlock x3 -> (union_keys (U,) int64 np, decision (U,) int8 np,
    presence (U,) int8 np with bits a=1/o=2/t=4, stats dict).

    Union keys are computed host-side (cheap, sorted inputs) and padded to a
    bucket so jit shapes are reused.
    """
    from kart_tpu.parallel.sharded_diff import should_shard

    n_max = max(ancestor_block.count, ours_block.count, theirs_block.count)
    if should_shard(n_max):
        # >1 device: shard-local 3-way classify over the mesh (block-cyclic
        # PK partition; only the count vector crosses ICI)
        from kart_tpu.parallel.sharded_merge import sharded_merge_classify

        try:
            return sharded_merge_classify(
                ancestor_block, ours_block, theirs_block
            )
        except Exception as e:
            import logging

            logging.getLogger("kart_tpu.parallel").warning(
                "mesh-sharded merge classify failed (%s: %s); using "
                "single-chip path",
                type(e).__name__,
                e,
            )

    from kart_tpu.ops.diff_kernel import STREAM_MIN_ROWS, device_profitable

    if n_max >= STREAM_MIN_ROWS and device_profitable(n_max):
        from kart_tpu.runtime import default_backend

        if default_backend() != "cpu":
            # accelerator at north-star scale: chunked double-buffered
            # upload instead of one monolithic 3-block transfer
            try:
                return merge_classify_streamed(
                    ancestor_block, ours_block, theirs_block
                )
            except Exception as e:
                import logging

                logging.getLogger("kart_tpu.ops").warning(
                    "streamed merge classify failed (%s: %s); using "
                    "monolithic path",
                    type(e).__name__,
                    e,
                )

    a_real = ancestor_block.keys[: ancestor_block.count]
    o_real = ours_block.keys[: ours_block.count]
    t_real = theirs_block.keys[: theirs_block.count]
    union = np.union1d(np.union1d(a_real, o_real), t_real).astype(np.int64)
    u = len(union)

    # same cost model as classify_blocks: small merges never pay backend
    # init / compile, and XLA-CPU backends route to the host path (where the
    # native/numpy engines win at every size)
    if not device_profitable(u):
        decision, presence = _merge_classify_np(
            ancestor_block, ours_block, theirs_block, union
        )
        return (
            union,
            decision,
            presence,
            {
                "conflicts": int(np.sum(decision == CONFLICT)),
                "take_theirs": int(np.sum(decision == TAKE_THEIRS)),
            },
        )

    size = bucket_size(max(u, 1))
    union_padded = np.full(size, PAD_KEY, dtype=np.int64)
    union_padded[:u] = union

    try:
        decision, presence, n_conf, n_theirs = _merge_classify_padded(
            ancestor_block.keys, ancestor_block.oids, ancestor_block.count,
            ours_block.keys, ours_block.oids, ours_block.count,
            theirs_block.keys, theirs_block.oids, theirs_block.count,
            union_padded, u,
        )
    except Exception as e:
        # device OOM / tunnel failure mid-call: the merge must still
        # complete (same guarantee classify_blocks gives the diff path)
        import logging

        logging.getLogger("kart_tpu.ops").warning(
            "device merge classify failed (%s: %s); using host path",
            type(e).__name__,
            e,
        )
        decision, presence = _merge_classify_np(
            ancestor_block, ours_block, theirs_block, union
        )
        return (
            union,
            decision,
            presence,
            {
                "conflicts": int(np.sum(decision == CONFLICT)),
                "take_theirs": int(np.sum(decision == TAKE_THEIRS)),
            },
        )
    return (
        union,
        np.asarray(decision)[:u],
        np.asarray(presence)[:u],
        {"conflicts": int(n_conf), "take_theirs": int(n_theirs)},
    )


def merge_classify_streamed(
    ancestor_block, ours_block, theirs_block, chunk_rows=None
):
    """Double-buffered chunked device merge classify — the merge analog of
    ``diff_kernel.classify_blocks_streamed`` (SURVEY §2.3 pipelined
    streaming): north-star-scale merges must not ship three whole blocks to
    HBM as one upload. Key-space chunks keep every 3-way decision
    chunk-local; per-chunk unions concatenate (in order) into the exact
    global sorted union, so output is identical to ``merge_classify``
    (tested). With two chunks in flight, chunk i+1's host->HBM copy
    overlaps chunk i's joins."""
    import jax

    from collections import deque

    from kart_tpu.ops.diff_kernel import STREAM_CHUNK_ROWS, stream_chunk_splits

    if chunk_rows is None:
        chunk_rows = max(STREAM_CHUNK_ROWS, 1)
    blocks = (ancestor_block, ours_block, theirs_block)
    reals = tuple(
        (b.keys[: b.count], b.oids[: b.count]) for b in blocks
    )
    splits, n_chunks = stream_chunk_splits(
        tuple(keys for keys, _ in reals), chunk_rows
    )
    # per-chunk unions first: all chunks share one union bucket (one
    # compiled shape), and their ordered concatenation IS the global union
    unions = []
    for c in range(n_chunks):
        parts = [
            reals[s][0][splits[s][c] : splits[s][c + 1]] for s in range(3)
        ]
        unions.append(
            np.union1d(np.union1d(parts[0], parts[1]), parts[2]).astype(
                np.int64
            )
        )
    side_max = max(
        (
            int(np.max(np.diff(splits[s])))
            for s in range(3)
            if len(splits[s]) > 1
        ),
        default=1,
    )
    b_bucket = bucket_size(max(side_max, 1))
    u_bucket = bucket_size(max(max((len(u) for u in unions), default=1), 1))

    def _padded(keys, oids, lo, hi):
        k = np.full(b_bucket, PAD_KEY, dtype=np.int64)
        o = np.zeros((b_bucket, 5), dtype=np.uint32)
        k[: hi - lo] = keys[lo:hi]
        o[: hi - lo] = oids[lo:hi]
        return k, o

    out_decision = []
    out_presence = []
    totals = np.zeros(2, dtype=np.int64)
    in_flight = deque()

    def _drain():
        out, u_count = in_flight.popleft()
        decision, presence, n_conf, n_theirs = out
        out_decision.append(np.asarray(decision)[:u_count])
        out_presence.append(np.asarray(presence)[:u_count])
        totals[0] += int(n_conf)
        totals[1] += int(n_theirs)

    for c in range(n_chunks):
        args = []
        for s in range(3):
            lo, hi = int(splits[s][c]), int(splits[s][c + 1])
            k, o = _padded(reals[s][0], reals[s][1], lo, hi)
            args.extend((jax.device_put(k), jax.device_put(o), hi - lo))
        u = unions[c]
        u_padded = np.full(u_bucket, PAD_KEY, dtype=np.int64)
        u_padded[: len(u)] = u
        args.extend((jax.device_put(u_padded), len(u)))
        out = _merge_classify_padded(*args)
        in_flight.append((out, len(u)))
        if len(in_flight) >= 2:
            _drain()
    while in_flight:
        _drain()
    union = (
        np.concatenate(unions) if unions else np.zeros(0, dtype=np.int64)
    )
    decision = (
        np.concatenate(out_decision)
        if out_decision
        else np.zeros(0, dtype=np.int8)
    )
    presence = (
        np.concatenate(out_presence)
        if out_presence
        else np.zeros(0, dtype=np.int8)
    )
    return (
        union,
        decision,
        presence,
        {"conflicts": int(totals[0]), "take_theirs": int(totals[1])},
    )


def _join_np(block, union_keys):
    """Vectorized numpy twin of ``_join`` (unpadded)."""
    keys = block.keys[: block.count]
    oids = block.oids[: block.count]
    if not len(keys):
        return (
            np.zeros(len(union_keys), dtype=bool),
            np.zeros((len(union_keys), 5), dtype=np.uint32),
        )
    idx = np.searchsorted(keys, union_keys)
    idxc = np.minimum(idx, len(keys) - 1)
    present = (keys[idxc] == union_keys) & (idx < len(keys))
    out = np.where(present[:, None], oids[idxc], 0).astype(np.uint32)
    return present, out


def _merge_classify_np(ancestor_block, ours_block, theirs_block, union):
    """Vectorized numpy fallback with identical semantics to the jitted
    kernel (used when no jax backend is usable)."""
    a_pres, a_oid = _join_np(ancestor_block, union)
    o_pres, o_oid = _join_np(ours_block, union)
    t_pres, t_oid = _join_np(theirs_block, union)

    def same(p1, oid1, p2, oid2):
        return (~p1 & ~p2) | (p1 & p2 & np.all(oid1 == oid2, axis=1))

    o_eq_t = same(o_pres, o_oid, t_pres, t_oid)
    o_eq_a = same(o_pres, o_oid, a_pres, a_oid)
    t_eq_a = same(t_pres, t_oid, a_pres, a_oid)
    decision = np.where(
        o_eq_t,
        KEEP_OURS,
        np.where(o_eq_a, TAKE_THEIRS, np.where(t_eq_a, KEEP_OURS, CONFLICT)),
    ).astype(np.int8)
    presence = (
        a_pres.astype(np.int8)
        + 2 * o_pres.astype(np.int8)
        + 4 * t_pres.astype(np.int8)
    )
    return decision, presence


def merge_classify_reference(ancestor_block, ours_block, theirs_block):
    """Pure-numpy implementation of identical semantics (bit-compat tests)."""
    def index(block):
        return {
            int(k): bytes(block.oids[i].tobytes())
            for i, k in enumerate(block.keys[: block.count])
        }

    a, o, t = index(ancestor_block), index(ours_block), index(theirs_block)
    union = sorted(set(a) | set(o) | set(t))
    decisions = []
    for k in union:
        av, ov, tv = a.get(k), o.get(k), t.get(k)
        if ov == tv:
            decisions.append(KEEP_OURS)
        elif ov == av:
            decisions.append(TAKE_THEIRS)
        elif tv == av:
            decisions.append(KEEP_OURS)
        else:
            decisions.append(CONFLICT)
    return np.asarray(union, dtype=np.int64), np.asarray(decisions, dtype=np.int8)
