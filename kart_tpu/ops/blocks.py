"""Columnar feature blocks — the bridge from blob-world to HBM
(SURVEY.md §7 step 2).

A FeatureBlock is the SoA form of one dataset version's feature identity:

    keys : int64 (N,)   — int pk, or the top 64 bits of the path hash for
                          hash-encoded datasets (uniformly distributed;
                          collisions are detected host-side and disambiguated
                          before device work)
    oids : uint32 (N,5) — the feature blob's 20-byte content id, packed

sorted by key. Two blocks of the same dataset at different revisions align by
key, which is exactly the alignment git's tree layout provides for free via
PK-determined paths (reference: dataset3_paths.py) — re-created here as sorted
arrays so classification runs as one vectorized merge-join on device instead
of a per-feature Python loop (reference hot loop #1, rich_base_dataset.py:205).

Blocks are padded to bucketed sizes so jit traces are reused across calls
(XLA compiles per shape). The pad sentinel key is int64.max, which sorts last
and never equals a real key.
"""

import hashlib
import threading
from collections import OrderedDict

import numpy as np

PAD_KEY = np.int64(2**63 - 1)

#: decoded vertex columns keyed by (sha1 of the raw section bytes, row
#: count) — the sidecar is content-addressed, so repeated loads of one
#: file hand back identical bytes and a digest key can never go stale
#: (docs/FORMAT.md §3.4); the bound reclaims memory. Hashing the section
#: costs milliseconds where the KTB2 decode costs hundreds — without the
#: memo every exact spatial query re-pays the full-column decode, because
#: the scan loads a fresh FeatureBlock per request.
_VERTEX_MEMO = OrderedDict()
_vertex_memo_lock = threading.Lock()
_VERTEX_MEMO_ENTRIES = 8


def bucket_size(n, minimum=1024):
    """Next 1/8-step pseudo-power-of-two >= n (>= minimum): sizes of the form
    (8..15) * 2^k. Bounds the number of distinct shapes XLA ever compiles for
    (8 per octave) while capping padding waste at 12.5% — matters because the
    classify kernel's sort cost scales with the padded size."""
    if n <= minimum:
        return minimum
    k = max((n - 1).bit_length() - 4, 0)
    step = 1 << k
    return ((n + step - 1) // step) * step


def pack_oid_hex(oids_hex):
    """list of 40-hex oids -> (N, 5) uint32 array."""
    if not len(oids_hex):
        return np.zeros((0, 5), dtype=np.uint32)
    raw = np.frombuffer(bytes.fromhex("".join(oids_hex)), dtype=np.uint8)
    return raw.reshape(-1, 5, 4).view(np.uint32).reshape(-1, 5).copy()


def unpack_oid_hex(oid_rows):
    """(N, 5) uint32 -> list of 40-hex oids. One buffer-level hex + string
    slices: the per-row bytes().hex() loop cost ~1us/row at 1M-changed
    materialisation scale."""
    if not len(oid_rows):
        return []
    h = np.ascontiguousarray(oid_rows).astype("<u4").view(np.uint8).tobytes().hex()
    return [h[i : i + 40] for i in range(0, len(h), 40)]


def unpack_oid_bytes(oid_rows):
    """(N, 5) uint32 -> list of 20-byte shas (one buffer copy + slices)."""
    if not len(oid_rows):
        return []
    b = np.ascontiguousarray(oid_rows).astype("<u4").view(np.uint8).tobytes()
    return [b[i : i + 20] for i in range(0, len(b), 20)]


def hash_keys_for_paths(paths):
    """Feature paths (hash-encoded datasets) -> int64 identity keys: the first
    8 bytes (big-endian, sign-cleared) of sha256 of the blob *filename*.
    Uniform over [0, 2^63): collision probability at 100M keys ~ 5e-4; the
    caller must check `has_key_collisions` and disambiguate via paths."""
    n = len(paths)
    out = np.empty(n, dtype=np.int64)
    for i, p in enumerate(paths):
        name = p.rsplit("/", 1)[-1]
        digest = hashlib.sha256(name.encode()).digest()
        out[i] = int.from_bytes(digest[:8], "big") >> 1
    return out


class FeatureBlock:
    """One dataset version as sorted (key, oid) arrays + the path strings
    (kept host-side for value materialisation of changed rows only)."""

    __slots__ = ("keys", "oids", "paths", "count", "envelopes", "env_blocks",
                 "geom_raw", "_vertices")

    def __init__(self, keys, oids, paths, count, envelopes=None,
                 env_blocks=None, geom_raw=None, vertices=None):
        self.keys = keys
        self.oids = oids
        self.paths = paths  # list[str], in the same (sorted) order, len == count
        self.count = count
        # optional (count, 4) float32 wsen envelope columns (sidecar-backed;
        # unpadded) — the spatially-filtered diff's prefilter input
        self.envelopes = envelopes
        # optional (agg (nb,4) f32, flags (nb,) u8, block_rows) aggregate
        # records over the envelope column — the block-pruned prefilter's
        # input; None for pre-aggregate sidecars (full scan fallback)
        self.env_blocks = env_blocks
        # optional encoded vertex-column section bytes (sidecar "geom_bytes",
        # docs/FORMAT.md §3.4), decoded on first vertex_column() call —
        # diff loads must not pay the decode they never use
        self.geom_raw = geom_raw
        self._vertices = vertices

    def vertex_column(self):
        """Lazily decoded :class:`kart_tpu.geom.VertexColumn` for the
        block's ``count`` rows, or None when the sidecar has no geometry
        section. Fail open: a corrupt section decodes to None once (the
        refine stage then keeps envelope verdicts) rather than failing
        the whole block load."""
        if self._vertices is None and self.geom_raw is not None:
            raw, self.geom_raw = self.geom_raw, None
            from kart_tpu.geom import decode_vertex_column

            # bytes() copy: the stream codecs index scalars out of the
            # buffer and an mmap view would hand them np.uint8s
            data = bytes(raw)
            memo_key = (hashlib.sha1(data).digest(), self.count)
            with _vertex_memo_lock:
                hit = _VERTEX_MEMO.get(memo_key)
                if hit is not None:
                    _VERTEX_MEMO.move_to_end(memo_key)
            if hit is not None:
                self._vertices = hit
                return hit
            try:
                self._vertices, _ = decode_vertex_column(data, self.count)
            except Exception:
                self._vertices = None
            if self._vertices is not None:
                with _vertex_memo_lock:
                    _VERTEX_MEMO[memo_key] = self._vertices
                    _VERTEX_MEMO.move_to_end(memo_key)
                    while len(_VERTEX_MEMO) > _VERTEX_MEMO_ENTRIES:
                        _VERTEX_MEMO.popitem(last=False)
        return self._vertices

    @classmethod
    def from_dataset(cls, dataset, pad=True):
        paths, pk_arr, oid_u8 = dataset.feature_index()
        oid_rows = (
            oid_u8.reshape(-1, 5, 4).view(np.uint32).reshape(-1, 5)
            if len(paths)
            else np.zeros((0, 5), dtype=np.uint32)
        )
        if pk_arr is not None:
            keys = pk_arr.astype(np.int64)
        else:
            keys = hash_keys_for_paths(paths)
        return cls.from_arrays(keys, oid_rows, paths, pad=pad)

    @classmethod
    def from_arrays(cls, keys, oid_rows, paths, pad=True):
        n = len(keys)
        order = np.argsort(keys, kind="stable")
        keys = keys[order]
        oid_rows = oid_rows[order]
        paths = [paths[i] for i in order]
        if pad:
            size = bucket_size(max(n, 1))
            if size > n:
                keys = np.concatenate([keys, np.full(size - n, PAD_KEY, dtype=np.int64)])
                oid_rows = np.concatenate(
                    [oid_rows, np.zeros((size - n, 5), dtype=np.uint32)]
                )
        return cls(keys, oid_rows, paths, n)

    @property
    def padded_size(self):
        return len(self.keys)

    def has_key_collisions(self):
        real = self.keys[: self.count]
        return bool(np.any(real[1:] == real[:-1])) if self.count > 1 else False

    def path_for_index(self, i):
        return self.paths[i]

    def __len__(self):
        return self.count

    def __repr__(self):
        return f"FeatureBlock(count={self.count}, padded={self.padded_size})"
