"""Deferred jax loading.

``import jax`` costs ~1.8s of pure import time — paid by every CLI
invocation even when the numpy twin handles the whole command (small repos,
wedged accelerators). Kernels defined with :func:`lazy_jit` keep jax out of
module import; the real ``jax.jit`` happens on the first *call*.
"""


class _LazyJit:
    __slots__ = ("_fn", "_jitted")

    def __init__(self, fn):
        self._fn = fn
        self._jitted = None

    @property
    def __wrapped__(self):
        return self._fn

    def __call__(self, *args, **kwargs):
        if self._jitted is None:
            import jax

            # unconditional, matching the pre-lazy invariant: int64 feature
            # keys and the PAD_KEY sentinel corrupt silently under x32, and
            # an inherited JAX_ENABLE_X64=0 must not defeat that
            jax.config.update("jax_enable_x64", True)
            self._jitted = jax.jit(self._fn)
        return self._jitted(*args, **kwargs)


def lazy_jit(fn):
    """jax.jit that defers the jax import to the first call."""
    return _LazyJit(fn)
