"""Bounding-box intersection kernels — the spatial-filter hot path
(reference: the C++ git object filter, vendor/spatial-filter/spatial_filter.cpp:187-260,
and the Python fast path, kart/spatial_filter/__init__.py:709-734).

Envelopes are (w, s, e, n) with longitudes cyclic over the anti-meridian:
``e < w`` means the range wraps (reference spatial_filter.cpp handles the same
encoding); ``w <= e`` is an ordinary range — including the full-width
``(-180, 180)`` which must match everything. Intersection of cyclic
longitude ranges:

    len = e - w          when w <= e   (ordinary, up to 360)
          (e - w) mod 360 otherwise    (wrapping)
    overlap  <=>  (w2 - w1) mod 360 <= len1  or  (w1 - w2) mod 360 <= len2

Three implementations with identical semantics:
* ``bbox_intersects_np``    — numpy reference (host, tests)
* ``bbox_intersects_jnp``   — jitted XLA (any backend)
* ``bbox_intersects_pallas``— TPU Pallas kernel, tiled (8, 128) f32 over VMEM
``bbox_intersects`` picks the best available for the current backend.
"""

from functools import partial

import os
import threading

import numpy as np

from kart_tpu.ops._lazy import lazy_jit


def _range_len_np(w, e):
    return np.where(e >= w, e - w, np.mod(e - w, 360.0))


def _cyclic_overlap_np(w1, e1, w2, e2):
    len1 = _range_len_np(w1, e1)
    len2 = _range_len_np(w2, e2)
    return (np.mod(w2 - w1, 360.0) <= len1) | (np.mod(w1 - w2, 360.0) <= len2)


def bbox_intersects_np(envelopes, query):
    """envelopes (N,4) float, query (4,) -> bool (N,). numpy reference."""
    envelopes = np.asarray(envelopes, dtype=np.float64)
    w, s, e, n = (envelopes[:, i] for i in range(4))
    qw, qs, qe, qn = (float(query[i]) for i in range(4))
    lat_ok = (s <= qn) & (qs <= n)
    lon_ok = _cyclic_overlap_np(w, e, np.float64(qw), np.float64(qe))
    return lat_ok & lon_ok


#: block classes for the pruned scan (mirrors classify_block in
#: native/spatial_filter.cpp)
BLOCK_ALL_OUT, BLOCK_ALL_IN, BLOCK_BOUNDARY = 0, 1, 2


def classify_env_blocks_np(agg, flags, query):
    """Sidecar block aggregates (nb,4) f32 union bboxes + nb flag bytes +
    query (4,) -> int8 (nb,) of BLOCK_* classes. numpy twin of the native
    classify_block: all-out when the union bbox misses the query (no member
    can intersect), all-in when it is contained in the query and the
    aggregate is tight (flags == 0), boundary otherwise."""
    agg = np.asarray(agg, dtype=np.float64)
    w, s, e, n = (agg[:, i] for i in range(4))
    qw, qs, qe, qn = (float(query[i]) for i in range(4))
    # the cyclic lon math is NaN on non-finite bounds (mod(inf) = nan): a
    # non-finite union (an inf member widened the block) is boundary unless
    # the latitude compares — well-defined for +-inf — already rule it out
    lon_finite = np.isfinite(w) & np.isfinite(e)
    with np.errstate(invalid="ignore"):
        lon_out = ~_cyclic_overlap_np(w, e, np.float64(qw), np.float64(qe))
        if qe >= qw:
            lon_in = (w >= qw) & (e <= qe)
        else:  # wrapping query: contained in [qw, 180] or [-180, qe]
            lon_in = (w >= qw) | (e <= qe)
    out = (n < qs) | (s > qn) | (lon_finite & lon_out)
    all_in = (
        ~out
        & (np.asarray(flags) == 0)
        & lon_finite
        & np.isfinite(s)
        & np.isfinite(n)
        & (s >= qs)
        & (n <= qn)
        & lon_in
    )
    cls = np.full(len(agg), BLOCK_BOUNDARY, dtype=np.int8)
    cls[out] = BLOCK_ALL_OUT
    cls[all_in] = BLOCK_ALL_IN
    return cls


def bbox_blocks_np(envelopes, agg, flags, block_rows, query):
    """numpy twin of the native sf_bbox_blocks_f32: classify blocks from
    their aggregates, fine-scan only boundary blocks. Bit-identical to
    bbox_intersects_np over the f32 envelopes."""
    n = len(envelopes)
    block_rows = int(block_rows)
    cls = classify_env_blocks_np(agg, flags, query)
    out = np.zeros(n, dtype=bool)
    for b in np.nonzero(cls != BLOCK_ALL_OUT)[0]:
        lo = int(b) * block_rows
        hi = min(lo + block_rows, n)
        if cls[b] == BLOCK_ALL_IN:
            out[lo:hi] = True
        else:
            out[lo:hi] = bbox_intersects_np(envelopes[lo:hi], query)
    return out


def _bbox_intersects_jnp_core(w, s, e, n, query):
    """Columns (N,) f32 + query (4,) -> bool (N,). XLA path."""
    import jax.numpy as jnp

    qw, qs, qe, qn = query[0], query[1], query[2], query[3]
    lat_ok = (s <= qn) & (qs <= n)
    len1 = jnp.where(e >= w, e - w, jnp.mod(e - w, 360.0))
    len2 = jnp.where(qe >= qw, qe - qw, jnp.mod(qe - qw, 360.0))
    lon_ok = (jnp.mod(qw - w, 360.0) <= len1) | (jnp.mod(w - qw, 360.0) <= len2)
    return lat_ok & lon_ok


bbox_intersects_jnp = lazy_jit(_bbox_intersects_jnp_core)


def _bbox_kernel(query_ref, w_ref, s_ref, e_ref, n_ref, out_ref):
    import jax.numpy as jnp

    qw = query_ref[0]
    qs = query_ref[1]
    qe = query_ref[2]
    qn = query_ref[3]
    w = w_ref[:, :]
    s = s_ref[:, :]
    e = e_ref[:, :]
    n = n_ref[:, :]
    lat_ok = (s <= qn) & (qs <= n)
    len1 = jnp.where(e >= w, e - w, jnp.mod(e - w, 360.0))
    len2 = jnp.where(qe >= qw, qe - qw, jnp.mod(qe - qw, 360.0))
    lon_ok = (jnp.mod(qw - w, 360.0) <= len1) | (jnp.mod(w - qw, 360.0) <= len2)
    out_ref[:, :] = (lat_ok & lon_ok).astype(jnp.int8)


def bbox_intersects_pallas(w, s, e, n, query):
    """TPU Pallas path. Inputs (N,) f32 with N a multiple of 1024; reshaped to
    (N/128, 128) tiles. query (4,) f32 prefetched to SMEM.

    Runs with x64 disabled: the package-level x64 (needed for int64 identity
    keys) would make grid index maps emit i64, which Mosaic can't legalize —
    and everything in this kernel is f32/int8 anyway.
    """
    import jax

    with jax.enable_x64(False):
        return _bbox_pallas_inner(w, s, e, n, query)


def _bbox_pallas_inner_core(w, s, e, n, query):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_items = w.shape[0]
    rows = n_items // 128
    shape2d = (rows, 128)
    # pad_envelopes guarantees rows is a multiple of 8 (small inputs) or 512
    # (large inputs), so the grid always divides exactly — a non-dividing
    # grid would silently skip the tail rows
    block_rows = 512 if rows % 512 == 0 else 8
    assert rows % block_rows == 0, (rows, block_rows)
    grid = (rows // block_rows,)

    def index_map(i):
        return (i, 0)

    spec = pl.BlockSpec((block_rows, 128), index_map, memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _bbox_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            spec,
            spec,
            spec,
            spec,
        ],
        out_specs=pl.BlockSpec((block_rows, 128), index_map, memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(shape2d, jnp.int8),
    )(
        query,
        w.reshape(shape2d),
        s.reshape(shape2d),
        e.reshape(shape2d),
        n.reshape(shape2d),
    )
    return out.reshape(n_items).astype(jnp.bool_)


_bbox_pallas_inner = lazy_jit(_bbox_pallas_inner_core)


def pad_envelopes(envelopes, multiple=None):
    """(N,4) -> (w,s,e,n) float32 columns padded to a multiple (1024 items =
    8 rows for small inputs, 65536 items = 512 rows for large, keeping the
    Pallas grid evenly divisible); padded rows get an empty range at latitude
    91 (matches nothing)."""
    n = envelopes.shape[0]
    if multiple is None:
        multiple = 65536 if n > 65536 else 1024
    padded_n = ((n + multiple - 1) // multiple) * multiple if n else multiple
    cols = np.full((4, padded_n), 91.0, dtype=np.float32)
    if n:
        cols[:, :n] = np.asarray(envelopes, dtype=np.float32).T
    return cols[0], cols[1], cols[2], cols[3], n


from kart_tpu.ops.diff_kernel import _env_int

# below this count the numpy path wins outright and never touches jax
# measured crossover on TPU v5e: numpy wins to ~1M envelopes, the device
# kernel is ~7x faster at 10M
DEVICE_MIN_ENVELOPES = _env_int("KART_DEVICE_MIN_ENVELOPES", 1_000_000)

# the resident cache routes to the device at the same crossover as one-shot
# dispatch (same float32 rounding trade, so adding a cache_key never changes
# results) — its win is skipping the transfer on repeats; lower via the env
# knob on hosts where the kernel-only crossover (~100k) is worth float32
RESIDENT_MIN_ENVELOPES = _env_int("KART_RESIDENT_MIN_ENVELOPES", DEVICE_MIN_ENVELOPES)

_RESIDENT_CACHE = {}  # cache_key -> (w, s, e, n device arrays, count)
_RESIDENT_CACHE_MAX = 4
_RESIDENT_LOCK = threading.Lock()  # the HTTP server filters concurrently


def _resident_columns(cache_key, envelopes):
    """Device-resident padded envelope columns for ``cache_key``, uploading
    on first use. Keyed by the caller's identity for the envelope set (e.g.
    (db path, mtime) for the envelope index) — repeat spatial queries hit
    the kernel without re-paying the transfer (VERDICT r2 weak #3: e2e
    4.6s vs 0.119s kernel at 10M was all transfer)."""
    import jax

    with _RESIDENT_LOCK:
        entry = _RESIDENT_CACHE.get(cache_key)
        if entry is not None and entry[4] == len(envelopes):
            return entry
    w, s, e, nn, count = pad_envelopes(np.asarray(envelopes))
    entry = (
        jax.device_put(w),
        jax.device_put(s),
        jax.device_put(e),
        jax.device_put(nn),
        count,
    )
    with _RESIDENT_LOCK:
        while len(_RESIDENT_CACHE) >= _RESIDENT_CACHE_MAX and cache_key not in _RESIDENT_CACHE:
            _RESIDENT_CACHE.pop(next(iter(_RESIDENT_CACHE)), None)
        _RESIDENT_CACHE[cache_key] = entry
    return entry


def bbox_intersects(envelopes, query, *, cache_key=None):
    """Best-available backend dispatch; envelopes (N,4), query (4,) ->
    bool numpy (N,). Small inputs and unusable jax backends take the host
    path (native C++ merge scan, or numpy).

    cache_key: stable identity of the envelope set; enables the
    device-resident column cache so repeat queries skip the transfer."""
    n = len(envelopes)
    if n == 0:
        return np.zeros(0, dtype=bool)
    from kart_tpu.runtime import default_backend, jax_ready

    min_rows = RESIDENT_MIN_ENVELOPES if cache_key is not None else DEVICE_MIN_ENVELOPES
    if n < min_rows or not jax_ready():
        return _bbox_host(envelopes, query)
    backend = default_backend()
    if cache_key is not None:
        w, s, e, nn, count = _resident_columns(cache_key, envelopes)
    else:
        w, s, e, nn, count = pad_envelopes(np.asarray(envelopes))
    q = np.asarray(query, dtype=np.float32)
    if backend == "tpu":
        mask = bbox_intersects_pallas(w, s, e, nn, q)
    else:
        mask = bbox_intersects_jnp(w, s, e, nn, q)
    return np.asarray(mask)[:count]


def _bbox_host(envelopes, query):
    """Host path: the native C++ scan when built, numpy otherwise (the
    native wrapper handles its own fallback)."""
    from kart_tpu import native

    return native.bbox_intersects(np.asarray(envelopes, dtype=np.float64), query)
