"""Apply JSON patches (reference: kart/apply.py).

A patch is the JSON diff format (``kart.diff/v1+hexwkb``) plus an optional
``kart.patch/v1`` header carrying the original commit's message/author/base.
Minimal patches carry ``*`` deltas (no old values); they are resolved against
the ``base`` commit recorded in the header (reference: apply.py:180-309).
"""

from kart_tpu.core.repo import InvalidOperation, NotFound
from kart_tpu.core.structure import PatchApplyError
from kart_tpu.core.objects import Signature
from kart_tpu.diff.structs import DatasetDiff, Delta, DeltaDiff, KeyValue, RepoDiff
from kart_tpu.geometry import Geometry
from kart_tpu.models.schema import Schema


def _feature_from_json(feature_json, schema):
    out = {}
    for col in schema.columns:
        value = feature_json.get(col.name)
        if value is not None and col.data_type == "geometry":
            value = Geometry.from_hex_wkb(value)
        elif value is not None and col.data_type == "blob":
            value = bytes.fromhex(value)
        out[col.name] = value
    return out


def _pk_of(feature_json, schema):
    pks = tuple(feature_json[c.name] for c in schema.pk_columns)
    return pks[0] if len(pks) == 1 else pks


def parse_patch(repo, patch_json, ref="HEAD"):
    """-> (RepoDiff, header dict). ref: revision the patch is parsed
    against (minimal-patch `*` deltas resolve old values from here when
    the patch carries no base)."""
    try:
        diff_json = patch_json["kart.diff/v1+hexwkb"]
    except KeyError:
        raise PatchApplyError(
            "Patch is missing the 'kart.diff/v1+hexwkb' key — is this a Kart patch?"
        )
    header = patch_json.get("kart.patch/v1", {})
    base_rs = None
    if header.get("base"):
        try:
            base_rs = repo.structure(header["base"])
        except NotFound:
            base_rs = None

    head_rs = repo.structure(ref) if not repo.head_is_unborn else None
    repo_diff = RepoDiff()
    for ds_path, ds_json in diff_json.items():
        ds_diff = DatasetDiff()
        ds = head_rs.datasets.get(ds_path) if head_rs is not None else None

        meta_json = ds_json.get("meta", {})
        if meta_json:
            meta_diff = DeltaDiff()
            for name, change in meta_json.items():
                if "*" in change:
                    if ds is None:
                        raise PatchApplyError(
                            f"Minimal patch for unknown dataset {ds_path!r}"
                        )
                    old_value = ds.meta_items().get(name)
                    change = {"-": old_value, "+": change["*"]}
                old = KeyValue((name, change["-"])) if change.get("-") is not None else None
                new = KeyValue((name, change["+"])) if change.get("+") is not None else None
                meta_diff.add_delta(Delta(old, new))
            ds_diff["meta"] = meta_diff

        # figure out the schema for decoding features
        if "schema.json" in meta_json and meta_json["schema.json"].get("+"):
            schema = Schema.from_column_dicts(meta_json["schema.json"]["+"])
        elif ds is not None:
            schema = ds.schema
        else:
            raise PatchApplyError(
                f"Patch contains features for unknown dataset {ds_path!r} "
                f"and no schema"
            )
        old_schema = ds.schema if ds is not None else schema

        features_json = ds_json.get("feature", [])
        if features_json:
            feature_diff = DeltaDiff()
            for change in features_json:
                minus = change.get("-")
                plus = change.get("+")
                star = change.get("*")
                if star is not None:
                    # minimal patch: resolve old value from base
                    new_feature = _feature_from_json(star, schema)
                    pk = _pk_of(star, schema)
                    base_ds = base_rs.datasets.get(ds_path) if base_rs else None
                    if base_ds is None:
                        raise PatchApplyError(
                            "Minimal patch requires its base commit "
                            f"({header.get('base', 'unknown')}) to be present"
                        )
                    old_feature = base_ds.get_feature(
                        base_ds.schema.sanitise_pks(pk if isinstance(pk, tuple) else [pk])
                    )
                    feature_diff.add_delta(
                        Delta.update(KeyValue((pk, old_feature)), KeyValue((pk, new_feature)))
                    )
                    continue
                old = None
                new = None
                if minus is not None:
                    old_feature = _feature_from_json(minus, old_schema)
                    old = KeyValue((_pk_of(minus, old_schema), old_feature))
                if plus is not None:
                    new_feature = _feature_from_json(plus, schema)
                    new = KeyValue((_pk_of(plus, schema), new_feature))
                feature_diff.add_delta(Delta(old, new))
            ds_diff["feature"] = feature_diff
        repo_diff[ds_path] = ds_diff
    return repo_diff, header


def apply_patch(repo, patch_json, *, no_commit=False, allow_empty=False,
                ref="HEAD"):
    """-> new commit oid (or None with no_commit). ref: which ref the patch
    commit lands on (reference: kart/apply.py --ref; HEAD also updates the
    working copy, any other ref leaves it untouched)."""
    if ref != "HEAD":
        if no_commit:
            raise InvalidOperation("--no-commit and --ref are incompatible")
        if not ref.startswith("refs/"):
            ref = f"refs/heads/{ref}"
        if not ref.startswith("refs/heads/"):
            # only branches may move (a tag/remote ref must never be
            # silently rewritten; same restriction as the reference)
            raise InvalidOperation(f"--ref must name a branch, not {ref!r}")
        if not repo.refs.exists(ref):
            raise NotFound(f"No such ref: {ref}")
        if ref == repo.refs.head_branch():
            # the named branch IS the checked-out one: take the HEAD path so
            # the working copy rolls forward with it instead of desyncing
            ref = "HEAD"
    repo_diff, header = parse_patch(repo, patch_json, ref=ref)
    head_rs = repo.structure(ref)
    wc = repo.working_copy if ref == "HEAD" else None
    if wc is not None:
        wc.assert_db_tree_match(head_rs.tree_oid)

    if no_commit:
        if wc is None:
            raise InvalidOperation("--no-commit requires a working copy")
        with wc.session() as con:
            for ds_path, ds_diff in repo_diff.items():
                ds = head_rs.datasets.get(ds_path)
                if ds is None:
                    raise PatchApplyError(
                        f"Cannot apply new-dataset patch to working copy only"
                    )
                wc._apply_feature_diff_sql(
                    con, ds, ds_diff.get("feature", DeltaDiff()),
                    track_changes_as_dirty=True,
                )
        return None

    author = None
    if header.get("authorName"):
        import re as _re

        ts = 0
        offset = 0
        when = header.get("authorTime")
        if when:
            from datetime import datetime, timezone

            try:
                ts = int(
                    datetime.strptime(when, "%Y-%m-%dT%H:%M:%SZ")
                    .replace(tzinfo=timezone.utc)
                    .timestamp()
                )
            except ValueError:
                ts = 0
        off_text = header.get("authorTimeOffset")
        if off_text:
            m = _re.fullmatch(r"([+-])(\d{2}):?(\d{2})", off_text)
            if m:
                offset = int(m.group(2)) * 60 + int(m.group(3))
                if m.group(1) == "-":
                    offset = -offset
        if ts:
            author = Signature(
                header["authorName"], header.get("authorEmail", ""), ts, offset
            )
    message = header.get("message") or "Apply patch"
    commit_oid = head_rs.commit_diff(
        repo_diff, message, allow_empty=allow_empty, author=author, ref=ref
    )
    if wc is not None:
        new_tree = repo.odb.read_commit(commit_oid).tree
        target = repo.structure(commit_oid)
        wc.reset(target, force=True)
    return commit_oid
