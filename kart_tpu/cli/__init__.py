"""The ``kart`` command surface (reference: kart/cli.py + per-command modules).

Run as ``python -m kart_tpu.cli`` (or ``python -m kart_tpu``). Commands are
grouped in modules and registered lazily so startup stays fast.
"""

import importlib
import os
import sys

import click

import kart_tpu

# command name -> module (lazy loading, reference: cli.py:21-43)
_COMMANDS = {
    "init": "kart_tpu.cli.repo_cmds",
    "import": "kart_tpu.cli.repo_cmds",
    "commit": "kart_tpu.cli.repo_cmds",
    "status": "kart_tpu.cli.repo_cmds",
    "checkout": "kart_tpu.cli.repo_cmds",
    "switch": "kart_tpu.cli.repo_cmds",
    "restore": "kart_tpu.cli.repo_cmds",
    "reset": "kart_tpu.cli.repo_cmds",
    "create-workingcopy": "kart_tpu.cli.repo_cmds",
    "diff": "kart_tpu.cli.diff_cmds",
    "log": "kart_tpu.cli.diff_cmds",
    "show": "kart_tpu.cli.diff_cmds",
    "create-patch": "kart_tpu.cli.diff_cmds",
    "apply": "kart_tpu.cli.diff_cmds",
    "branch": "kart_tpu.cli.ref_cmds",
    "tag": "kart_tpu.cli.ref_cmds",
    "config": "kart_tpu.cli.ref_cmds",
    "gc": "kart_tpu.cli.ref_cmds",
    "fsck": "kart_tpu.cli.ref_cmds",
    "reflog": "kart_tpu.cli.ref_cmds",
    "git": "kart_tpu.cli.ref_cmds",
    "data": "kart_tpu.cli.data_cmds",
    "query": "kart_tpu.cli.query_cmds",
    "meta": "kart_tpu.cli.data_cmds",
    "merge": "kart_tpu.cli.merge_cmds",
    "conflicts": "kart_tpu.cli.merge_cmds",
    "resolve": "kart_tpu.cli.merge_cmds",
    "clone": "kart_tpu.cli.remote_cmds",
    "push": "kart_tpu.cli.remote_cmds",
    "pull": "kart_tpu.cli.remote_cmds",
    "fetch": "kart_tpu.cli.remote_cmds",
    "remote": "kart_tpu.cli.remote_cmds",
    "serve": "kart_tpu.cli.remote_cmds",
    "serve-stdio": "kart_tpu.cli.remote_cmds",
    "spatial-filter": "kart_tpu.cli.spatial_cmds",
    "upgrade": "kart_tpu.cli.upgrade_cmds",
    "upgrade-to-kart": "kart_tpu.cli.upgrade_cmds",
    "upgrade-to-tidy": "kart_tpu.cli.upgrade_cmds",
    "commit-files": "kart_tpu.cli.data_cmds",
    "build-annotations": "kart_tpu.cli.data_cmds",
    "stats": "kart_tpu.cli.stats_cmds",
    "top": "kart_tpu.cli.top_cmds",
    "watch": "kart_tpu.cli.watch_cmds",
    "fleet": "kart_tpu.cli.fleet_cmds",
    "lint": "kart_tpu.cli.lint_cmds",
    "export": "kart_tpu.cli.tile_cmds",
}


class CliError(click.ClickException):
    exit_code = 2


class Context:
    """Lazily opens the repo for commands that need one
    (reference: kart/context.py)."""

    def __init__(self):
        self.repo_path = os.environ.get("KART_REPO", ".")
        self.user_agent = f"kart_tpu/{kart_tpu.__version__}"

    @property
    def repo(self):
        from kart_tpu.core.repo import KartRepo, NotFound

        try:
            return KartRepo(self.repo_path)
        except NotFound as e:
            raise click.UsageError(str(e))

    def require_state(self, *allowed):
        repo = self.repo
        if repo.state not in allowed:
            from kart_tpu.core.repo import KartRepoState

            raise CliError(KartRepoState.bad_state_message(repo.state, allowed))
        return repo


class KartGroup(click.Group):
    def list_commands(self, ctx):
        return sorted(set(super().list_commands(ctx)) | set(_COMMANDS))

    def get_command(self, ctx, name):
        cmd = super().get_command(ctx, name)
        if cmd is not None:
            return cmd
        module_name = _COMMANDS.get(name)
        if module_name is None:
            return None
        try:
            importlib.import_module(module_name)
        except ImportError as e:
            raise CliError(f"Command {name!r} is unavailable: {e}")
        return super().get_command(ctx, name)


@click.group(cls=KartGroup)
@click.option(
    "-C",
    "repo_dir",
    metavar="PATH",
    default=None,
    help="Run as if started in PATH instead of the current directory",
)
@click.version_option(version=kart_tpu.__version__, prog_name="kart (kart_tpu)")
@click.option("-v", "--verbose", count=True, help="Increase verbosity (-v, -vv)")
@click.option(
    "--trace",
    "trace_flag",
    is_flag=True,
    help="Record a Chrome trace of this command (written on exit; "
    "KART_TRACE=<path> picks the file)",
)
@click.option(
    "--reprobe",
    "reprobe_flag",
    is_flag=True,
    help="Drop the persisted accelerator-probe verdict and probe afresh "
    "(equivalent to KART_JAX_REPROBE=1; see docs/DEVICE.md)",
)
@click.pass_context
def cli(ctx, repo_dir, verbose, trace_flag, reprobe_flag):
    """kart_tpu — TPU-native distributed version control for geospatial data."""
    from kart_tpu import telemetry

    ctx.obj = Context()
    if repo_dir:
        ctx.obj.repo_path = repo_dir
    if reprobe_flag:
        from kart_tpu import runtime

        removed = runtime.invalidate_probe_cache()
        # also re-key every probe this process makes, so the fresh verdict
        # is a real probe even if some library path already consulted it
        os.environ["KART_JAX_REPROBE"] = "1"
        if removed:
            click.echo(f"Dropped cached backend probe verdict ({removed})", err=True)
    # always configured (not only on -v): one kart_tpu logger, one format,
    # KART_LOG honoured for level — servers and library re-entry included
    telemetry.configure_logging(verbose)
    telemetry.enable_from_env()
    if trace_flag and not telemetry.tracing_enabled():
        telemetry.enable(trace=True, trace_path=telemetry.default_trace_path())
    if verbose:
        telemetry.enable(spans=True)  # feeds the end-of-command summary
    # one command = one trace: every transport verb this command issues
    # inherits this root context's trace id, and the wire carries it to
    # the servers (docs/OBSERVABILITY.md §8)
    telemetry.set_root_request(verb=ctx.invoked_subcommand)
    if ctx.invoked_subcommand:
        telemetry.incr("cli.commands", cmd=ctx.invoked_subcommand)

    @ctx.call_on_close
    def _flush_telemetry():
        from kart_tpu.telemetry import sinks

        if telemetry.tracing_enabled():
            dropped = telemetry.events_dropped_count()
            path = sinks.write_chrome_trace()
            if path:
                note = (
                    f" ({dropped} span events dropped at the buffer cap)"
                    if dropped
                    else ""
                )
                click.echo(f"Trace written to {path}{note}", err=True)
        if verbose:
            summary = sinks.phase_summary_text()
            if summary:
                click.echo(summary, err=True)


def add_command(name, fn):
    cli.add_command(fn, name=name)


def entrypoint():
    """Translate internal exceptions into clean one-line errors with stable
    exit codes (reference: kart/cli.py entrypoint + kart/exceptions.py)."""
    import sys

    from kart_tpu import exceptions
    from kart_tpu.core.repo import InvalidOperation, NotFound, RepoError
    from kart_tpu.importer import ImportSourceError

    try:
        cli(standalone_mode=True)
    except NotFound as e:
        click.echo(f"Error: {e}", err=True)
        sys.exit(getattr(e, "exit_code", exceptions.NOT_FOUND))
    except ImportSourceError as e:
        click.echo(f"Error: {e}", err=True)
        sys.exit(exceptions.NO_IMPORT_SOURCE)
    except (InvalidOperation, RepoError) as e:
        click.echo(f"Error: {e}", err=True)
        sys.exit(getattr(e, "exit_code", exceptions.INVALID_OPERATION))


if __name__ == "__main__":
    entrypoint()
