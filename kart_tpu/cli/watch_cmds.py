"""``kart watch`` — stream a server's live-update events as JSON lines
(docs/EVENTS.md §5).

Subscribes to the target's event feed (``GET /api/v1/events`` long-poll
over HTTP; the ``events`` op over ssh) and prints one JSON line per
announced ref transition: sequence number, ref, old/new tips, and the
exact per-dataset dirty-tile summary the CDC computed — everything a map
viewer needs to invalidate precisely and re-fetch only what changed.
Resume is by sequence: ``--since`` replays from a known position, and a
dropped connection reconnects where it left off.
"""

import json as _json
import sys
import time

import click

from kart_tpu.cli import CliError, cli
from kart_tpu.cli.stats_cmds import _resolve_target


def _emit(event, dataset):
    if dataset is not None:
        dirty = event.get("dirty")
        if isinstance(dirty, dict) and dataset not in dirty:
            return False
    click.echo(_json.dumps(event, sort_keys=True))
    sys.stdout.flush()
    return True


@cli.command()
@click.argument("target")
@click.option("--dataset", default=None,
              help="Only print events touching this dataset path.")
@click.option("--since", type=int, default=None,
              help="Replay from this event sequence number "
                   "(default: transitions from now on).")
@click.option("-n", "--count", type=int, default=0,
              help="Exit after printing this many events (0 = forever).")
@click.option("--timeout", type=float, default=None,
              help="Exit 0 after this many seconds without an event "
                   "(default $KART_WATCH_TIMEOUT; 0 = watch forever).")
@click.pass_obj
def watch(ctx, target, dataset, since, count, timeout):
    """Stream live-update events from a server as JSON lines.

    TARGET is an http(s):// or ssh:// URL, or a configured remote name.
    Each line is one announced ref transition with its exact dirty-tile
    summary (docs/EVENTS.md): viewers invalidate those tiles, re-fetch
    them commit-addressed, and are current — no re-polling every tile.
    """
    from kart_tpu.events.stream import (
        EventStreamUnsupported,
        iter_events,
        watch_timeout,
    )
    from kart_tpu.transport.http import HttpTransportError
    from kart_tpu.transport.remote import is_http_url
    from kart_tpu.transport.stdio import StdioRemote, is_ssh_url

    url = _resolve_target(ctx, target)
    if timeout is None:
        timeout = watch_timeout()
    printed = 0
    try:
        if is_http_url(url):
            stream = iter_events(
                url, since=since, idle_timeout=timeout or None
            )
            for event in stream:
                if _emit(event, dataset):
                    printed += 1
                if count and printed >= count:
                    return
        elif is_ssh_url(url):
            # each ssh exchange is one bounded poll (the stdio server
            # holds no long streams); resume state is the same sequence
            remote = StdioRemote(url)
            try:
                if since is None:
                    since = int(remote.events().get("head", 0))
                idle_since = time.monotonic()
                while True:
                    doc = remote.events(since, timeout=5.0)
                    for event in doc.get("events", ()):
                        if _emit(event, dataset):
                            printed += 1
                        idle_since = time.monotonic()
                        if count and printed >= count:
                            return
                    since = max(since, int(doc.get("head", since)))
                    if timeout and time.monotonic() - idle_since > timeout:
                        return
            finally:
                remote.close()
        else:
            raise CliError(
                f"Cannot watch {url!r}: expected an http(s):// or ssh:// "
                f"URL (or a configured remote name)"
            )
    except EventStreamUnsupported as e:
        raise CliError(
            f"{e} — the server predates live-update events or runs with "
            f"KART_SERVE_EVENTS=0"
        )
    except OSError as e:
        raise CliError(f"Event stream lost: {e}")
    except HttpTransportError as e:
        # the stdio path's error frames (incl. a KART_SERVE_EVENTS=0
        # server answering the events op with an error) arrive as
        # transport errors, not HTTP statuses — same friendly exit
        raise CliError(f"Event stream failed: {e}")
