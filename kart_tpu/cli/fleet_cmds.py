"""``kart fleet`` — operate a serving fleet (docs/FLEET.md).

``kart fleet status <member...>`` polls each member's structured stats
document (the same ``/api/v1/stats?format=json`` ``kart top`` reads) and
renders the fleet operator's one-screen staleness view: role, replication
lag, sync cycles/errors, proxied writes, read-your-writes decisions and
peer-cache effectiveness — per member, without any new server surface.
"""

import click

from kart_tpu.cli import CliError, cli
from kart_tpu.cli.stats_cmds import _resolve_target
from kart_tpu.cli.top_cmds import fetch_stats_json


def _counter(snapshot, name):
    return sum(v for n, _l, v in snapshot.get("counters", ()) if n == name)


def member_status(payload):
    """Flatten one member's stats document into the status row fields."""
    snap = payload.get("snapshot", {})
    fleet = payload.get("fleet") or {}
    hits = _counter(snap, "fleet.peer_cache.hits")
    misses = _counter(snap, "fleet.peer_cache.misses")
    lookups = hits + misses
    return {
        "role": fleet.get("role", "primary"),
        "primary": fleet.get("primary"),
        "lag_seconds": fleet.get("lag_seconds"),
        "last_sync_utc": fleet.get("last_sync_utc"),
        "sync_cycles": fleet.get("sync_cycles", 0),
        "sync_errors": fleet.get("sync_errors", 0),
        "last_error": fleet.get("last_error"),
        "proxied_writes": fleet.get("proxied_writes", 0),
        "ryw_stalls": fleet.get("ryw_stalls", 0),
        "ryw_pins": fleet.get("ryw_pins", 0),
        "peer_hit_rate": (hits / lookups) if lookups else None,
        "inflight": payload.get("inflight", 0),
        "tiles_served": _counter(snap, "tiles.served"),
        "requests": _counter(snap, "transport.server.requests"),
    }


def render_status(rows):
    """The fleet status table: one line per member."""
    lines = [
        f"{'member':<36}{'role':<9}{'lag':>7}{'syncs':>7}{'errs':>6}"
        f"{'proxied':>9}{'ryw s/p':>9}{'peer hit':>10}{'reqs':>8}"
        f"{'tiles':>8}"
    ]
    for url, status in rows:
        if status is None:
            lines.append(f"{url:<36}{'(unreachable)'}")
            continue
        lag = status["lag_seconds"]
        peer = status["peer_hit_rate"]
        ryw = f"{status['ryw_stalls']}/{status['ryw_pins']}"
        lines.append(
            f"{url:<36}{status['role']:<9}"
            f"{(f'{lag:.1f}s' if lag is not None else '-'):>7}"
            f"{status['sync_cycles']:>7}{status['sync_errors']:>6}"
            f"{status['proxied_writes']:>9}"
            f"{ryw:>9}"
            f"{(f'{peer:.0%}' if peer is not None else '-'):>10}"
            f"{status['requests']:>8.0f}{status['tiles_served']:>8.0f}"
        )
        if status["last_error"]:
            lines.append(f"{'':<36}  last sync error: {status['last_error']}")
    return "\n".join(lines)


@cli.group()
def fleet():
    """Operate a scale-out serving fleet (docs/FLEET.md)."""


@fleet.command("status")
@click.argument("targets", nargs=-1, required=True)
@click.option("-o", "output_format", type=click.Choice(["text", "json"]),
              default="text", show_default=True)
@click.pass_obj
def fleet_status(ctx, targets, output_format):
    """Show replication lag, proxied writes and peer-cache effectiveness
    for every fleet member named (http(s):// URLs or configured remotes).

    The primary appears as role ``primary`` with no lag; each replica
    reports how far its view trails (seconds since its last successful
    sync cycle), its proxied-write count and read-your-writes decisions
    (stalled locally vs pinned to the primary).
    """
    import json as _json

    rows = []
    for target in targets:
        url = _resolve_target(ctx, target)
        try:
            payload = fetch_stats_json(url)
        except (OSError, ValueError) as e:
            click.echo(f"warning: {target!r}: {e}", err=True)
            rows.append((url, None))
            continue
        rows.append((url, member_status(payload)))
    if output_format == "json":
        click.echo(
            _json.dumps(
                {url: status for url, status in rows}, indent=2, default=str
            )
        )
        return
    if all(status is None for _url, status in rows):
        raise CliError("No fleet member was reachable")
    click.echo(render_status(rows))
