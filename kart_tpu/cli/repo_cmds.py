"""init / import / commit / status / checkout / switch / restore / reset
(reference: kart/init.py, commit.py, checkout.py, status.py)."""

import os

import click

from kart_tpu.cli import CliError, cli
from kart_tpu.core.repo import InvalidOperation, KartRepo, KartRepoState
from kart_tpu.diff.key_filters import RepoKeyFilter
from kart_tpu.diff.output import dump_json_output
from kart_tpu.diff.structs import DeltaDiff


def _do_checkout(repo, refish=None, *, force=False):
    """Reset the working copy to the given revision (creating it if needed)."""
    from kart_tpu.workingcopy import get_working_copy

    structure = repo.structure(refish or "HEAD")
    wc = get_working_copy(repo, allow_uncreated=True)
    if wc is None:
        return None
    wc.reset(structure, force=force)
    return wc


@cli.command("init", context_settings={"ignore_unknown_options": True})
@click.argument("directory", type=click.Path(), required=False, default=".")
@click.option("--import", "import_from", help="Import from this data source immediately")
@click.option("--bare", is_flag=True, help="Create a bare repository (no working copy)")
@click.option(
    "--workingcopy-location",
    "--workingcopy-path",
    "--workingcopy",
    "wc_location",
    help="Location of the working copy (e.g. data.gpkg)",
)
@click.option("-b", "--initial-branch", default="main", help="Initial branch name")
@click.option("--message", "-m", help="Commit message for the initial import")
@click.pass_context
def init(ctx, directory, import_from, bare, wc_location, initial_branch, message):
    """Create an empty repository, or import an existing data source."""
    repo = KartRepo.init_repository(
        directory, bare=bare, initial_branch=initial_branch
    )
    click.echo(f"Initialized empty Kart repository in {repo.gitdir}")
    if wc_location and not bare:
        from kart_tpu.core.repo import KartConfigKeys

        repo.config[KartConfigKeys.KART_WORKINGCOPY_LOCATION] = wc_location
    if import_from:
        ctx.obj.repo_path = directory
        ctx.invoke(import_, sources=(import_from,), message=message)


@cli.command("import")
@click.argument("sources", nargs=-1, required=True)
@click.option("--message", "-m", help="Commit message")
@click.option("--table", "-t", help="Only import this table from the source")
@click.option("--dest-path", help="Dataset path to import into")
@click.option("--replace-existing", is_flag=True, help="Replace existing dataset(s)")
@click.option(
    "--replace-ids",
    help=(
        "Replace only features with the given IDs (one per line; use "
        "@filename.txt to read them from a file). Implies --replace-existing. "
        "A listed ID missing from the source is deleted from the dataset; an "
        "empty value replaces no features."
    ),
)
@click.option("--no-checkout", is_flag=True, help="Don't update the working copy")
@click.option(
    "--all-tables", "-a", is_flag=True,
    help="Import all tables from the source (the default when no --table "
         "is given; accepted for reference-CLI compatibility)",
)
@click.option(
    "--list", "do_list", is_flag=True,
    help="List the tables present in the source and exit",
)
@click.option(
    "-o", "--output-format", type=click.Choice(["text", "json"]),
    default="text", help="Output format for --list",
)
@click.option(
    "--primary-key",
    help="Use this (existing, unique) column as the primary key",
)
@click.option(
    "--crs",
    "crs_override",
    help=(
        "CRS of the source data, e.g. 'EPSG:27700' or full WKT — for "
        "sources that don't carry one (GeoJSON, CSV, shapefile without "
        ".prj). EPSG codes resolve via the built-in registry."
    ),
)
@click.pass_obj
def import_(
    ctx, sources, message, table, dest_path, replace_existing, replace_ids,
    no_checkout, all_tables, do_list, output_format, primary_key,
    crs_override,
):
    """Import data into the repository as new dataset(s)."""
    from kart_tpu.importer import ImportSource
    from kart_tpu.importer.importer import import_sources

    if do_list:
        if table or all_tables:
            raise CliError("--list cannot be combined with --table/--all-tables")
        body = {}
        for spec in sources:
            for src in ImportSource.open(spec):
                try:
                    title = src.meta_items().get("title")
                except Exception:
                    title = None
                body[src.dest_path] = title or ""
        if output_format == "json":
            from kart_tpu.diff.output import dump_json_output

            dump_json_output({"kart.tables/v1": body}, "-")
        else:
            for name, title in sorted(body.items()):
                click.echo(f"{name} - {title}" if title else name)
        return
    if all_tables and table:
        raise CliError("--all-tables cannot be combined with --table")

    repo = ctx.repo
    ids = None
    if replace_ids is not None:
        if replace_ids.startswith("@"):
            try:
                with open(replace_ids[1:]) as f:
                    replace_ids = f.read()
            except OSError as e:
                raise CliError(f"Cannot read --replace-ids file: {e}")
        ids = [line.strip() for line in replace_ids.splitlines() if line.strip()]
    if crs_override:
        # resolve eagerly so a bad code/WKT fails before any import work,
        # with the registry-coverage message
        from kart_tpu.crs import CrsError, make_crs

        try:
            make_crs(crs_override)
        except CrsError as e:
            raise CliError(str(e))
    all_sources = []
    for spec in sources:
        opened = ImportSource.open(spec, table=table)
        if crs_override:
            from kart_tpu.crs import make_crs

            for src in opened:
                if hasattr(src, "crs"):
                    src.crs = crs_override
                elif getattr(src, "crs_wkt", "n/a") is None:
                    # shapefile with no .prj sidecar
                    src.crs_wkt = make_crs(crs_override).wkt
                else:
                    raise CliError(
                        f"--crs does not apply to {spec!r}: the source "
                        f"carries its own CRS definition"
                    )
        all_sources.extend(opened)
    if primary_key:
        # ImportSourceError propagates: the entrypoint maps it to the
        # documented NO_IMPORT_SOURCE exit code like every other source error
        all_sources = [
            src.with_primary_key(primary_key) for src in all_sources
        ]
    if dest_path:
        if len(all_sources) != 1:
            raise CliError("--dest-path requires a single table import")
        all_sources[0].dest_path = dest_path
    import_sources(
        repo,
        all_sources,
        message=message,
        replace_existing=replace_existing,
        replace_ids=ids,
        log=lambda m: click.echo(m, err=True),
    )
    if not no_checkout and not repo.is_bare:
        _do_checkout(repo, "HEAD", force=True)


def _commit_message_from_editor(repo_diff):
    """No -m given: open $EDITOR on a template summarising the pending
    changes; '#' lines are stripped, an empty result aborts (reference:
    kart/commit.py:192-260)."""
    lines = [
        "",
        "# Please enter the commit message for your changes.",
        "# Lines starting with '#' will be ignored, and an empty",
        "# message aborts the commit.",
        "#",
        "# Changes to be committed:",
        "#",
    ]
    for ds_path in sorted(repo_diff):
        ds_diff = repo_diff[ds_path]
        n_features = len(ds_diff.get("feature") or ())
        n_meta = len(ds_diff.get("meta") or ())
        parts = []
        if n_meta:
            parts.append(f"{n_meta} meta item(s)")
        if n_features:
            parts.append(f"{n_features} feature(s)")
        lines.append(f"#   {ds_path}: {', '.join(parts) or 'no changes'}")
    text = click.edit("\n".join(lines) + "\n")
    if text is None:
        return None
    stripped = "\n".join(
        line for line in text.splitlines() if not line.startswith("#")
    ).strip()
    return stripped or None


@cli.command()
@click.option("--message", "-m", multiple=True, help="Commit message")
@click.option(
    "--allow-empty", is_flag=True, help="Allow a commit with no changes"
)
@click.option(
    "-o", "--output-format", type=click.Choice(["text", "json"]),
    default="text",
)
@click.argument("filters", nargs=-1)
@click.pass_obj
def commit(ctx, message, allow_empty, output_format, filters):
    """Record changes from the working copy to the repository."""
    repo = ctx.require_state(KartRepoState.NORMAL)
    wc = repo.working_copy
    if wc is None:
        raise CliError("No working copy — nothing to commit")
    target_rs = repo.structure("HEAD")
    wc.assert_db_tree_match(target_rs.tree_oid)

    from kart_tpu.diff.engine import get_repo_diff

    key_filter = RepoKeyFilter.build_from_user_patterns(filters)
    repo_diff = get_repo_diff(
        target_rs, target_rs, repo_key_filter=key_filter, include_wc_diff=True
    )
    if not repo_diff and not allow_empty:
        raise CliError("No changes to commit")

    msg = "\n\n".join(message) if message else None
    if not msg:
        msg = _commit_message_from_editor(repo_diff)
    if not msg:
        raise CliError("Aborting commit due to empty commit message")
    new_commit = target_rs.commit_diff(repo_diff, msg, allow_empty=allow_empty)
    wc.soft_reset_after_commit(repo.odb.read_commit(new_commit).tree, key_filter)
    commit_obj = repo.odb.read_commit(new_commit)
    branch = repo.head_branch
    branch_name = branch.rsplit("/", 1)[-1] if branch else "HEAD"
    if output_format == "json":
        # reference envelope (kart/commit.py:263-281)
        from datetime import datetime, timedelta, timezone

        author = commit_obj.author
        when = datetime.fromtimestamp(author.time, timezone.utc)
        off = commit_obj.committer.offset
        changes = {
            ds_path: ds_diff.type_counts()
            for ds_path, ds_diff in repo_diff.items()
        }
        dump_json_output(
            {
                "kart.commit/v1": {
                    "commit": new_commit,
                    "abbrevCommit": new_commit[:7],
                    "author": author.email,
                    "committer": commit_obj.committer.email,
                    "branch": branch_name,
                    "message": commit_obj.message,
                    "changes": changes,
                    "commitTime": when.strftime("%Y-%m-%dT%H:%M:%SZ"),
                    "commitTimeOffset": f"{'+' if off >= 0 else '-'}"
                    f"{abs(off) // 60:02d}:{abs(off) % 60:02d}",
                }
            },
            "-",
        )
        return
    click.echo(
        f"[{branch_name} {new_commit[:7]}] {commit_obj.message_summary}"
    )


@cli.command()
@click.option(
    "--output-format", "-o", type=click.Choice(["text", "json"]), default="text"
)
@click.pass_obj
def status(ctx, output_format):
    """Show the working copy status."""
    repo = ctx.repo
    state = repo.state
    branch = repo.head_branch
    head = repo.head_commit_oid

    changes = {}
    wc = repo.working_copy
    if wc is not None and head is not None:
        from kart_tpu.diff.engine import get_repo_diff

        target_rs = repo.structure("HEAD")
        diff = get_repo_diff(
            target_rs, target_rs, include_wc_diff=True
        )
        for ds_path, ds_diff in diff.items():
            counts = ds_diff.type_counts()
            changes[ds_path] = counts

    if output_format == "json":
        body = {
            "commit": head,
            "abbrevCommit": head[:7] if head else None,
            "branch": branch.rsplit("/", 1)[-1] if branch else None,
            "upstream": None,
            "state": state,
            "spatialFilter": repo.spatial_filter_spec(),
        }
        if state == KartRepoState.MERGING:
            # reference shape: merging context + summarise=2 conflict
            # counts (kart/status.py:33-39)
            from kart_tpu.cli.merge_cmds import _conflict_summary
            from kart_tpu.merge.index import MergeIndex

            mi = MergeIndex.read_from_repo(repo)
            merge_head = (repo.read_gitdir_file("MERGE_HEAD") or "").strip()
            merge_branch = (repo.read_gitdir_file("MERGE_BRANCH") or "").strip()
            body["merging"] = {
                "ancestor": None,
                "ours": {
                    "branch": branch.rsplit("/", 1)[-1] if branch else None,
                    "commit": head,
                    "abbrevCommit": head[:7] if head else None,
                },
                "theirs": {
                    "branch": merge_branch or None,
                    "commit": merge_head or None,
                    "abbrevCommit": merge_head[:7] if merge_head else None,
                },
            }
            body["conflicts"] = _conflict_summary(
                {
                    label: aot
                    for label, aot in mi.conflicts.items()
                    if label not in mi.resolves
                }
            )
        else:
            body["workingCopy"] = (
                {"path": str(wc), "changes": changes or None} if wc else None
            )
        # the reference 0.10.x envelope (scripts parse this key)
        dump_json_output({"kart.status/v1": body}, "-")
        return

    if branch:
        click.echo(f"On branch {branch.rsplit('/', 1)[-1]}")
    elif head:
        click.echo(f"HEAD detached at {head[:7]}")
    if head is None:
        click.echo("\nNo commits yet")
        return
    if state == KartRepoState.MERGING:
        click.echo('\nRepository is in "merging" state.')
        click.echo('View conflicts with "kart conflicts" and resolve them with "kart resolve".')
        return
    if not changes:
        click.echo("\nNothing to commit, working copy clean")
    else:
        click.echo("\nChanges in working copy:")
        click.echo('  (use "kart commit" to commit)')
        click.echo('  (use "kart checkout -- ." to discard changes)\n')
        for ds_path, counts in changes.items():
            click.echo(f"  {ds_path}:")
            for part, part_counts in counts.items():
                for change, n in part_counts.items():
                    click.echo(f"    {part}:\n      {n} {change}" if False else f"      {part}: {n} {change}")


@cli.command()
@click.option("-b", "new_branch", help="Create a new branch and switch to it")
@click.option("--force", "-f", is_flag=True, help="Discard local changes")
@click.option(
    "--spatial-filter",
    "spatial_filter_text",
    default=None,
    help="Change the repo's spatial filter: '<crs>;<geometry>', @file, or "
         "'none' to clear — the working copy is rebuilt to match "
         "(reference: kart checkout --spatial-filter)",
)
@click.argument("refish", required=False)
@click.pass_obj
def checkout(ctx, new_branch, force, refish, spatial_filter_text=None):
    """Switch branches or restore working copy files."""
    repo = ctx.require_state(KartRepoState.NORMAL)
    if spatial_filter_text is not None:
        from kart_tpu.core.repo import KartConfigKeys
        from kart_tpu.spatial_filter import ResolvedSpatialFilterSpec

        spec = ResolvedSpatialFilterSpec.from_spec_string(spatial_filter_text)
        old_spec = ResolvedSpatialFilterSpec.from_repo_config(repo)
        if spec.match_all:
            for key in (
                KartConfigKeys.KART_SPATIALFILTER_GEOMETRY,
                KartConfigKeys.KART_SPATIALFILTER_CRS,
            ):
                repo.del_config(key)
        else:
            repo.config.set_many(spec.config_items())
        if not (spec.match_all and old_spec.match_all):
            # the WC must contain exactly the in-filter features: full
            # rebuild (reference: checkout.py do_switch_spatial_filter)
            from kart_tpu.workingcopy import get_working_copy

            wc = get_working_copy(repo, allow_uncreated=True)
            if wc is not None and repo.head_commit_oid is not None:
                if wc.is_dirty() and not force:
                    raise InvalidOperation(
                        "You have uncommitted changes in your working copy. "
                        "Commit or discard first (use --force to discard)."
                    )
                target = repo.structure(refish or "HEAD")
                full_path = getattr(wc, "full_path", None)
                if full_path and os.path.exists(full_path):
                    os.remove(full_path)
                wc.create_and_initialise()
                wc.write_full(target, *target.datasets)
        if refish is None and new_branch is None:
            return
    if new_branch:
        start = refish or "HEAD"
        oid, _ = repo.resolve_refish(start)
        repo.refs.set(f"refs/heads/{new_branch}", oid, log_message=f"branch: created from {start}")
        repo.refs.set_head(f"refs/heads/{new_branch}", log_message=f"checkout: moving to {new_branch}")
        _do_checkout(repo, "HEAD", force=force)
        click.echo(f"Switched to a new branch '{new_branch}'")
        return
    if refish:
        wc = repo.working_copy
        if wc is not None and wc.is_dirty() and not force:
            raise InvalidOperation(
                "You have uncommitted changes in your working copy. "
                "Commit or discard first (use --force to discard)."
            )
        try:
            oid, ref = repo.resolve_refish(refish)
        except Exception:
            # guess: a bare name matching exactly one remote branch creates
            # a local tracking branch (reference: checkout.py --guess)
            matches = [
                (r, o)
                for r, o in repo.refs.iter_refs("refs/remotes/")
                if r.split("/", 3)[-1] == refish and not r.endswith("/HEAD")
            ]
            if len(matches) > 1:
                remotes = ", ".join(sorted(r.split("/")[2] for r, _ in matches))
                raise InvalidOperation(
                    f"{refish!r} matches branches on multiple remotes "
                    f"({remotes}) — check out the remote-qualified name "
                    f"explicitly"
                )
            if not matches:
                raise
            remote_ref, oid = matches[0]
            remote_name = remote_ref.split("/")[2]
            local = f"refs/heads/{refish}"
            repo.refs.set(
                local, oid, log_message=f"branch: created from {remote_ref}"
            )
            repo.config.set_many({
                f"branch.{refish}.remote": remote_name,
                f"branch.{refish}.merge": f"refs/heads/{refish}",
            })
            repo.refs.set_head(local, log_message=f"checkout: moving to {refish}")
            _do_checkout(repo, "HEAD", force=True)
            click.echo(
                f"Switched to a new branch '{refish}' tracking "
                f"'{remote_name}/{refish}'"
            )
            return
        if ref and ref.startswith("refs/heads/"):
            repo.refs.set_head(ref, log_message=f"checkout: moving to {refish}")
            click.echo(f"Switched to branch '{refish}'")
        else:
            repo.refs.set_head(oid, log_message=f"checkout: moving to {oid[:7]}")
            click.echo(f"HEAD is now detached at {oid[:7]}")
        _do_checkout(repo, "HEAD", force=True)
    else:
        _do_checkout(repo, "HEAD", force=force)


@cli.command()
@click.option("-c", "--create", "create_branch", help="Create and switch to this branch")
@click.option("--discard-changes", "--force", "-f", "force", is_flag=True)
@click.argument("branch", required=False)
@click.pass_context
def switch(click_ctx, create_branch, force, branch):
    """Switch branches."""
    ctx = click_ctx.obj
    if create_branch:
        click_ctx.invoke(checkout, new_branch=create_branch, force=force, refish=branch)
    else:
        if not branch:
            raise CliError("Specify a branch to switch to")
        click_ctx.invoke(checkout, new_branch=None, force=force, refish=branch)


@cli.command()
@click.option("--source", "-s", default="HEAD", help="Revision to restore from")
@click.argument("filters", nargs=-1)
@click.pass_obj
def restore(ctx, source, filters):
    """Restore working copy features to their committed state."""
    repo = ctx.repo
    wc = repo.working_copy
    if wc is None:
        raise CliError("No working copy")
    structure = repo.structure(source)
    key_filter = RepoKeyFilter.build_from_user_patterns(filters)
    if key_filter.match_all:
        wc.reset(structure, force=True)
    else:
        # restore only the filtered features: apply the WC->source diff subset
        from kart_tpu.diff.engine import get_repo_diff

        head_rs = repo.structure("HEAD")
        diff = get_repo_diff(
            structure, head_rs, repo_key_filter=key_filter, include_wc_diff=True
        )
        with wc.session() as con:
            for ds_path, ds_diff in diff.items():
                ds = structure.datasets.get(ds_path)
                if ds is None:
                    continue
                inverted = ~ds_diff.get("feature", DeltaDiff())
                wc._apply_feature_diff_sql(con, ds, inverted)
        wc.reset_tracking_table(key_filter)
    click.echo(f"Restored working copy from {source}")


@cli.command()
@click.option("--discard-changes", "--hard", "discard", is_flag=True)
@click.argument("refish", required=False, default="HEAD")
@click.pass_obj
def reset(ctx, discard, refish):
    """Move the current branch tip (and working copy) to another revision."""
    repo = ctx.require_state(KartRepoState.NORMAL)
    wc = repo.working_copy
    if wc is not None and wc.is_dirty() and not discard:
        raise InvalidOperation(
            "You have uncommitted changes; use --discard-changes to discard them."
        )
    oid, _ = repo.resolve_refish(refish)
    branch = repo.head_branch
    if branch:
        repo.refs.set(branch, oid, log_message=f"reset: moving to {refish}")
    else:
        repo.refs.set_head(oid, log_message=f"reset: moving to {refish}")
    _do_checkout(repo, "HEAD", force=True)
    click.echo(f"HEAD is now at {oid[:7]}")


@cli.command("create-workingcopy")
@click.option("--delete-existing/--no-delete-existing", default=False)
@click.argument("location", required=False)
@click.pass_obj
def create_workingcopy(ctx, delete_existing, location):
    """(Re)create the working copy from the current HEAD."""
    from kart_tpu.core.repo import KartConfigKeys
    from kart_tpu.workingcopy import get_working_copy

    repo = ctx.repo
    if location:
        repo.config[KartConfigKeys.KART_WORKINGCOPY_LOCATION] = location
    wc = get_working_copy(repo, allow_uncreated=True)
    if wc is None:
        raise CliError("No working copy location configured")
    if delete_existing:
        wc.delete()
    structure = repo.structure("HEAD")
    wc.write_full(structure, *structure.datasets)
    click.echo(f"Created working copy at {wc}")
