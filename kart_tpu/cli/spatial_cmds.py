"""kart spatial-filter — envelope indexing + filter inspection
(reference: kart/spatial_filter/index.py CLI, kart/spatial_filter/__init__.py)."""

import json

import click

from kart_tpu.cli import CliError, cli
from kart_tpu.diff.output import dump_json_output


@cli.group("spatial-filter")
def spatial_filter():
    """Work with spatial filters and the feature envelope index."""


@spatial_filter.command("index")
@click.option("--clear", is_flag=True, help="Discard the index and rebuild from scratch")
@click.option("--dry-run", is_flag=True, help="Index but don't save the result")
@click.pass_obj
def spatial_filter_index(ctx, clear, dry_run):
    """Build or update the feature envelope index (enables fast
    spatially-filtered clones from this repo)."""
    from kart_tpu.spatial_filter.index import update_spatial_filter_index

    repo = ctx.repo
    n_features, n_commits = update_spatial_filter_index(
        repo, clear=clear, dry_run=dry_run
    )
    click.echo(f"Indexed {n_features} feature envelopes over {n_commits} new commits")


@spatial_filter.command("resolve")
@click.option(
    "-o", "--output-format", type=click.Choice(["text", "json"]), default="text"
)
@click.argument("spec", required=False)
@click.pass_obj
def spatial_filter_resolve(ctx, spec, output_format):
    """Resolve a spatial filter spec (or this repo's configured filter) and
    show its geometry, CRS and EPSG:4326 envelope."""
    from kart_tpu.spatial_filter import (
        ResolvedSpatialFilterSpec,
        SpatialFilterError,
    )

    try:
        if spec:
            resolved = ResolvedSpatialFilterSpec.from_spec_string(spec)
        else:
            resolved = ResolvedSpatialFilterSpec.from_repo_config(ctx.repo)
    except SpatialFilterError as e:
        raise CliError(str(e))

    if resolved.match_all:
        if output_format == "json":
            dump_json_output({"kart.spatialfilter/v1": None}, "-")
        else:
            click.echo("No spatial filter is configured (all features match)")
        return

    w, s, e, n = resolved.envelope_wsen_4326
    if output_format == "json":
        dump_json_output(
            {
                "kart.spatialfilter/v1": {
                    "crs": resolved.crs_spec,
                    "geometry": resolved.geometry.to_wkt(),
                    "envelope4326": {"w": w, "s": s, "e": e, "n": n},
                }
            },
            "-",
        )
    else:
        click.echo(f"CRS: {resolved.crs_spec}")
        click.echo(f"Geometry: {resolved.geometry.to_wkt()[:120]}")
        click.echo(f"Envelope (EPSG:4326 w,s,e,n): {w:.7f},{s:.7f},{e:.7f},{n:.7f}")
