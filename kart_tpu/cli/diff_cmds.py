"""diff / log / show / create-patch / apply (reference: kart/diff.py, log.py,
show.py, apply.py)."""

import json

import click

from kart_tpu.cli import CliError, cli
from kart_tpu.diff.estimation import ACCURACY_CHOICES
from kart_tpu.diff.output import dump_json_output
from kart_tpu.diff.writers import BaseDiffWriter

OUTPUT_FORMATS = [
    "text",
    "json",
    "geojson",
    "json-lines",
    "quiet",
    "feature-count",
    "html",
]


@cli.command()
@click.option(
    "--output-format", "-o", type=click.Choice(OUTPUT_FORMATS), default="text"
)
@click.option("--output", "output_path", default="-", help="Output file (- for stdout)")
@click.option(
    "--json-style",
    type=click.Choice(["extracompact", "compact", "pretty"]),
    default="pretty",
)
@click.option("--crs", "target_crs", help="Reproject geometries to this CRS for output")
@click.option(
    "--exit-code",
    is_flag=True,
    help="Exit 1 when there are differences, 0 otherwise",
)
@click.option(
    "--only-feature-count",
    type=click.Choice(ACCURACY_CHOICES),
    default=None,
    help="Skip the diff; print an estimated changed-feature count per "
    "dataset at the given accuracy (sampled subtree estimation)",
)
@click.argument("args", nargs=-1)
@click.pass_obj
def diff(
    ctx,
    output_format,
    output_path,
    json_style,
    target_crs,
    exit_code,
    only_feature_count,
    args,
):
    """Show changes between commits, or between a commit and the working copy.

    ARGS: an optional commit spec (A, A..B or A...B) followed by optional
    dataset[:pk] filters.
    """
    repo = ctx.repo
    commit_spec, filters = _split_diff_args(repo, args)
    if only_feature_count:
        has_changes = _print_estimated_counts(
            repo,
            commit_spec,
            only_feature_count,
            output_format,
            output_path,
            filters,
        )
        if exit_code:
            raise SystemExit(1 if has_changes else 0)
        return
    writer_class = BaseDiffWriter.get_diff_writer_class(output_format)
    writer = writer_class(
        repo,
        commit_spec,
        filters,
        output_path,
        json_style=json_style,
        target_crs=target_crs,
    )
    has_changes = writer.write_diff()
    if exit_code or output_format == "quiet":
        raise SystemExit(1 if has_changes else 0)


def _print_estimated_counts(
    repo, commit_spec, accuracy, output_format, output_path, filters=()
):
    """kart diff --only-feature-count (reference: diff.py + diff_estimation.py).
    Returns True when any counted changes exist (for --exit-code)."""
    from kart_tpu.diff.estimation import estimate_diff_feature_counts

    base_rs, target_rs, working_copy = BaseDiffWriter.parse_diff_commit_spec(
        repo, commit_spec
    )
    if working_copy is not None:
        # the WC side has no trees to sample; fall back to counting the diff
        writer = BaseDiffWriter.get_diff_writer_class("feature-count")(
            repo, commit_spec, filters, output_path
        )
        return writer.write_diff()
    wanted = {f.split(":", 1)[0] for f in filters} if filters else None
    counts = estimate_diff_feature_counts(
        repo, base_rs, target_rs, accuracy=accuracy, ds_paths=wanted
    )
    if output_format == "json":
        dump_json_output({"kart.diff/v1+feature-count": counts}, output_path)
    else:
        lines = []
        for ds_path, count in sorted(counts.items()):
            lines.append(f"{ds_path}:")
            lines.append(f"\t{count} features changed")
        text = "\n".join(lines)
        if output_path and output_path != "-":
            with open(output_path, "w") as f:
                f.write(text + "\n")
        elif text:
            click.echo(text)
    return any(counts.values())


def _split_diff_args(repo, args):
    """First arg is a commit spec if it resolves (or contains '..'); the rest
    are filters."""
    from kart_tpu.core.repo import NotFound

    args = list(args)
    if not args:
        return "HEAD", []
    first = args[0]
    if ".." in first:
        return first, args[1:]
    try:
        repo.resolve_refish(first.split("...")[0])
        return first, args[1:]
    except NotFound:
        return "HEAD", args


@cli.command()
@click.option(
    "--output-format", "-o", type=click.Choice(["text", "json", "json-lines"]), default="text"
)
@click.option("--oneline", is_flag=True)
@click.option("-n", "--max-count", type=int, default=None)
@click.option("--json-style", type=click.Choice(["extracompact", "compact", "pretty"]), default="pretty")
@click.argument("refish", required=False, default="HEAD")
@click.argument("filters", nargs=-1)
@click.pass_obj
def log(ctx, output_format, oneline, max_count, json_style, refish, filters):
    """Show the commit log."""
    from kart_tpu.core.repo import NotFound
    from kart_tpu.diff.engine import get_repo_diff
    from kart_tpu.diff.key_filters import RepoKeyFilter

    repo = ctx.repo
    try:
        start, _ = repo.resolve_refish(refish)
    except NotFound:
        if refish != "HEAD":
            raise CliError(f"No such revision: {refish}")
        start = None
    if start is None:
        return

    key_filter = RepoKeyFilter.build_from_user_patterns(filters)

    entries = []
    count = 0
    for oid, commit in repo.walk_commits(start):
        if max_count is not None and count >= max_count:
            break
        if not key_filter.match_all:
            # filter by datasets touched in this commit
            parent = commit.parents[0] if commit.parents else None
            diff = get_repo_diff(
                repo.structure(parent) if parent else None,
                repo.structure(oid),
                repo_key_filter=key_filter,
            )
            if not diff:
                continue
        entries.append((oid, commit))
        count += 1

    if output_format in ("json", "json-lines"):
        out = [_commit_json(oid, c) for oid, c in entries]
        if output_format == "json":
            dump_json_output(out, "-", json_style=json_style)
        else:
            import sys

            for item in out:
                json.dump(item, sys.stdout, separators=(",", ":"))
                sys.stdout.write("\n")
        return

    for oid, commit in entries:
        if oneline:
            click.echo(f"{oid[:7]} {commit.message_summary}")
        else:
            from datetime import datetime, timedelta, timezone

            tz = timezone(timedelta(minutes=commit.author.offset))
            when = datetime.fromtimestamp(commit.author.time, timezone.utc).astimezone(tz)
            click.secho(f"commit {oid}", fg="yellow")
            click.echo(f"Author: {commit.author.name} <{commit.author.email}>")
            click.echo(f"Date:   {when.strftime('%a %b %d %H:%M:%S %Y %z')}")
            click.echo()
            for line in commit.message.splitlines():
                click.echo(f"    {line}")
            click.echo()


def _commit_json(oid, commit):
    from datetime import datetime, timedelta, timezone

    tz = timezone(timedelta(minutes=commit.author.offset))
    when = datetime.fromtimestamp(commit.author.time, timezone.utc).astimezone(tz)
    return {
        "commit": oid,
        "abbrevCommit": oid[:7],
        "message": commit.message,
        "refs": [],
        "authorName": commit.author.name,
        "authorEmail": commit.author.email,
        "authorTime": when.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "parents": list(commit.parents),
        "abbrevParents": [p[:7] for p in commit.parents],
    }


class _CommitForShow:
    def __init__(self, oid, commit):
        self.oid = oid
        self.author = commit.author
        self.message = commit.message


@cli.command()
@click.option(
    "--output-format", "-o", type=click.Choice(OUTPUT_FORMATS), default="text"
)
@click.option("--json-style", type=click.Choice(["extracompact", "compact", "pretty"]), default="pretty")
@click.option("--crs", "target_crs", help="Reproject geometries for output")
@click.argument("refish", required=False, default="HEAD")
@click.argument("filters", nargs=-1)
@click.pass_obj
def show(ctx, output_format, json_style, target_crs, refish, filters):
    """Show the changes introduced by a commit."""
    repo = ctx.repo
    oid, _ = repo.resolve_refish(refish)
    commit = repo.odb.read_commit(oid)
    writer_class = BaseDiffWriter.get_diff_writer_class(output_format)
    writer = writer_class(
        repo,
        f"{oid}^?...{oid}",
        filters,
        "-",
        json_style=json_style,
        target_crs=target_crs,
        commit=_CommitForShow(oid, commit),
    )
    writer.write_diff()


@cli.command("create-patch")
@click.option("--json-style", type=click.Choice(["extracompact", "compact", "pretty"]), default="pretty")
@click.option(
    "--patch-type",
    type=click.Choice(["full", "minimal"]),
    default="full",
    help="minimal patches omit unchanged old values (needs the base commit to apply)",
)
@click.option("--output", "output_path", default="-")
@click.argument("refish", required=True)
@click.pass_obj
def create_patch(ctx, json_style, patch_type, output_path, refish):
    """Write a JSON patch of the changes introduced by a commit."""
    from kart_tpu.diff.writers import JsonDiffWriter

    repo = ctx.repo
    oid, _ = repo.resolve_refish(refish)
    commit = repo.odb.read_commit(oid)
    writer = JsonDiffWriter(
        repo,
        f"{oid}^?...{oid}",
        (),
        output_path,
        json_style=json_style,
        commit=_CommitForShow(oid, commit),
        patch_type=patch_type,
        include_patch_header=True,
    )
    writer.write_diff()


@cli.command("apply")
@click.option("--no-commit", is_flag=True, help="Apply to the working copy only")
@click.option("--allow-empty", is_flag=True)
@click.argument("patch_file", type=click.File("r"))
@click.pass_obj
def apply_(ctx, no_commit, allow_empty, patch_file):
    """Apply a JSON patch (as written by create-patch)."""
    from kart_tpu.apply import apply_patch

    repo = ctx.repo
    commit_oid = apply_patch(
        repo, json.load(patch_file), no_commit=no_commit, allow_empty=allow_empty
    )
    if commit_oid:
        click.echo(f"Commit {commit_oid[:7]}")
    else:
        click.echo("Applied patch to working copy")
