"""diff / log / show / create-patch / apply (reference: kart/diff.py, log.py,
show.py, apply.py)."""

import json

import click

from kart_tpu.cli import CliError, cli
from kart_tpu.diff.estimation import ACCURACY_CHOICES
from kart_tpu.diff.output import dump_json_output
from kart_tpu.diff.writers import BaseDiffWriter

OUTPUT_FORMATS = [
    "text",
    "json",
    "geojson",
    "json-lines",
    "quiet",
    "feature-count",
    "html",
]


@cli.command()
@click.option(
    "--output-format", "-o", type=click.Choice(OUTPUT_FORMATS), default="text"
)
@click.option("--output", "output_path", default="-", help="Output file (- for stdout)")
@click.option(
    "--json-style",
    type=click.Choice(["extracompact", "compact", "pretty"]),
    default="pretty",
)
@click.option("--crs", "target_crs", help="Reproject geometries to this CRS for output")
@click.option(
    "--exit-code",
    is_flag=True,
    help="Exit 1 when there are differences, 0 otherwise",
)
@click.option(
    "--only-feature-count",
    type=click.Choice(ACCURACY_CHOICES),
    default=None,
    help="Skip the diff; print an estimated changed-feature count per "
    "dataset at the given accuracy (sampled subtree estimation)",
)
@click.argument("args", nargs=-1)
@click.pass_obj
def diff(
    ctx,
    output_format,
    output_path,
    json_style,
    target_crs,
    exit_code,
    only_feature_count,
    args,
):
    """Show changes between commits, or between a commit and the working copy.

    ARGS: an optional commit spec (A, A..B or A...B) followed by optional
    dataset[:pk] filters.
    """
    repo = ctx.repo
    commit_spec, filters = _split_diff_args(repo, args)
    if only_feature_count:
        has_changes = _print_estimated_counts(
            repo,
            commit_spec,
            only_feature_count,
            output_format,
            output_path,
            filters,
        )
        if exit_code:
            raise SystemExit(1 if has_changes else 0)
        return
    writer_class = BaseDiffWriter.get_diff_writer_class(output_format)
    writer = writer_class(
        repo,
        commit_spec,
        filters,
        output_path,
        json_style=json_style,
        target_crs=target_crs,
    )
    has_changes = writer.write_diff()
    if exit_code or output_format == "quiet":
        raise SystemExit(1 if has_changes else 0)


def _print_estimated_counts(
    repo, commit_spec, accuracy, output_format, output_path, filters=()
):
    """kart diff --only-feature-count (reference: diff.py + diff_estimation.py).
    Returns True when any counted changes exist (for --exit-code)."""
    from kart_tpu.diff.estimation import estimate_diff_feature_counts

    base_rs, target_rs, working_copy = BaseDiffWriter.parse_diff_commit_spec(
        repo, commit_spec
    )
    if working_copy is not None:
        # the WC side has no trees to sample; fall back to counting the diff
        writer = BaseDiffWriter.get_diff_writer_class("feature-count")(
            repo, commit_spec, filters, output_path
        )
        return writer.write_diff()
    wanted = {f.split(":", 1)[0] for f in filters} if filters else None
    counts = estimate_diff_feature_counts(
        repo, base_rs, target_rs, accuracy=accuracy, ds_paths=wanted
    )
    if output_format == "json":
        dump_json_output({"kart.diff/v1+feature-count": counts}, output_path)
    else:
        lines = []
        for ds_path, count in sorted(counts.items()):
            lines.append(f"{ds_path}:")
            lines.append(f"\t{count} features changed")
        text = "\n".join(lines)
        if output_path and output_path != "-":
            with open(output_path, "w") as f:
                f.write(text + "\n")
        elif text:
            click.echo(text)
    return any(counts.values())


def _split_diff_args(repo, args):
    """First arg is a commit spec if it resolves (or contains '..'); the rest
    are filters."""
    from kart_tpu.core.repo import NotFound

    args = list(args)
    if not args:
        return "HEAD", []
    first = args[0]
    if ".." in first:
        return first, args[1:]
    try:
        repo.resolve_refish(first.split("...")[0])
        return first, args[1:]
    except NotFound:
        return "HEAD", args


def _parse_log_date(value, option):
    """Git-ish date input -> unix timestamp: ISO 8601 ('2024-01-02',
    '2024-01-02T03:04:05+01:00'), a unix epoch ('@1700000000' or bare
    digits), or relative '<n> <unit>[s] ago' (seconds/minutes/hours/days/
    weeks/months/years)."""
    import re as _re
    import time as _time
    from datetime import datetime, timezone

    text = value.strip()
    if text.startswith("@") and text[1:].isdigit():
        return int(text[1:])
    if text.isdigit() and len(text) >= 9:  # a bare epoch, not a year
        return int(text)
    m = _re.fullmatch(
        r"(\d+)\s+(second|minute|hour|day|week|month|year)s?\s+ago", text
    )
    if m:
        unit_s = {
            "second": 1, "minute": 60, "hour": 3600, "day": 86400,
            "week": 7 * 86400, "month": 30 * 86400, "year": 365 * 86400,
        }[m.group(2)]
        return int(_time.time()) - int(m.group(1)) * unit_s
    try:
        dt = datetime.fromisoformat(text)
    except ValueError:
        raise CliError(
            f"Cannot parse {option} date {value!r}: use ISO 8601, a unix "
            f"epoch, or '<n> days ago'"
        )
    if dt.tzinfo is None:
        dt = dt.astimezone()  # git semantics: naive dates are local time
    return int(dt.timestamp())


def _effective_parents(oid, parent_map, displayed):
    """Parents of ``oid`` remapped to the nearest DISPLAYED ancestors:
    filtered-out commits (--grep/--since/--skip/path filters) are followed
    through transparently so the graph never forks a lane for a commit
    that will never be rendered."""
    out = []
    seen = set()
    stack = list(parent_map.get(oid, ()))
    while stack:
        p = stack.pop(0)
        if p in seen:
            continue
        seen.add(p)
        if p in displayed:
            if p not in out:
                out.append(p)
        else:
            stack.extend(parent_map.get(p, ()))
    return out


def _graph_rows(entries, parent_map):
    """Lane-tracking commit graph (git log --graph style): -> list of
    (prefix_str, oid, commit) rows plus continuation rows
    ((prefix, None, None)) for lane shuffles. Lanes hold the next expected
    commit oid; a commit collapses every lane expecting it and forks one
    lane per (displayed-ancestor) parent."""
    displayed = {oid for oid, _ in entries}
    lanes = []
    rows = []
    for oid, commit in entries:
        if oid not in lanes:
            lanes.append(oid)
        idx = lanes.index(oid)
        cells = ["*" if i == idx else "|" for i in range(len(lanes))]
        rows.append((" ".join(cells), oid, commit))
        # collapse other lanes that expected this same commit (merge point)
        dup = [i for i, l in enumerate(lanes) if l == oid and i != idx]
        for i in reversed(dup):
            lanes.pop(i)
        parents = _effective_parents(oid, parent_map, displayed)
        if not parents:
            lanes.pop(idx)
        else:
            lanes[idx] = parents[0]
            for extra in parents[1:]:
                if extra not in lanes:
                    lanes.insert(idx + 1, extra)
                    rows.append(
                        (
                            " ".join(
                                "|\\"[min(i - idx, 1)] if idx <= i <= idx + 1 else "|"
                                for i in range(len(lanes))
                            ),
                            None,
                            None,
                        )
                    )
    return rows


@cli.command()
@click.option(
    "--output-format", "-o", type=click.Choice(["text", "json", "json-lines"]), default="text"
)
@click.option("--oneline", is_flag=True)
@click.option("-n", "--max-count", type=int, default=None)
@click.option("--skip", type=int, default=None, help="Skip this many commits first")
@click.option("--since", "--after", "since", help="Only commits after this date")
@click.option("--until", "--before", "until", help="Only commits before this date")
@click.option("--author", multiple=True, help="Only commits by this author (regex, repeatable)")
@click.option("--committer", multiple=True, help="Only commits by this committer (regex, repeatable)")
@click.option("--grep", multiple=True, help="Only commits whose message matches (regex, repeatable)")
@click.option("--graph", is_flag=True, help="Draw an ASCII commit graph (text output)")
@click.option("--first-parent", is_flag=True, help="Follow only first parents at merges")
@click.option(
    "--with-dataset-changes",
    "dataset_changes",
    is_flag=True,
    help="List the datasets changed by each commit",
)
@click.option(
    "--with-feature-count",
    "feature_count_accuracy",
    type=click.Choice(["veryfast", "fast", "medium", "good", "exact"]),
    default=None,
    help=(
        "Add a featureChanges count per dataset to JSON output at the "
        "given estimation accuracy (reference: log --with-feature-count)"
    ),
)
@click.option("--json-style", type=click.Choice(["extracompact", "compact", "pretty"]), default="pretty")
@click.argument("refish", required=False, default="HEAD")
@click.argument("filters", nargs=-1)
@click.pass_obj
def log(
    ctx, output_format, oneline, max_count, skip, since, until, author,
    committer, grep, graph, first_parent, dataset_changes,
    feature_count_accuracy, json_style, refish, filters,
):
    """Show the commit log.

    FILTERS restrict output to commits touching the given datasets or
    features ('mylayer', 'mylayer:feature:123'), matching the reference's
    pathspec behavior (/root/reference/kart/log.py parse_extra_args)."""
    import re as _re

    from kart_tpu.core.repo import NotFound
    from kart_tpu.diff.engine import get_repo_diff
    from kart_tpu.diff.key_filters import RepoKeyFilter

    repo = ctx.repo
    try:
        start, _ = repo.resolve_refish(refish)
    except NotFound:
        start = None
        if refish != "HEAD":
            # reference behavior (log.py get_arg_type): an arg that doesn't
            # resolve as a commit-ish is a path filter — but only when it
            # actually names a dataset, so a typo'd branch still errors
            # instead of silently printing an empty history
            ds_part = refish.split(":", 1)[0]
            try:
                start, _ = repo.resolve_refish("HEAD")
                known = set(repo.structure("HEAD").datasets.paths())
            except NotFound:
                known = set()
            if ds_part not in known:
                raise CliError(f"No such revision or dataset: {refish}")
            filters = (refish,) + tuple(filters)
    if start is None:
        return

    since_ts = _parse_log_date(since, "--since") if since else None
    until_ts = _parse_log_date(until, "--until") if until else None
    author_res = [_re.compile(a) for a in author]
    committer_res = [_re.compile(c) for c in committer]
    grep_res = [_re.compile(g) for g in grep]

    key_filter = RepoKeyFilter.build_from_user_patterns(filters)

    def _touched_datasets(oid, commit):
        parent = commit.parents[0] if commit.parents else None
        diff = get_repo_diff(
            repo.structure(parent) if parent else None,
            repo.structure(oid),
            repo_key_filter=key_filter,
        )
        return sorted(diff.keys()) if diff else []

    entries = []
    parent_map = {}  # every walked commit, for graph lane remapping
    count = 0
    skipped = 0
    for oid, commit in repo.walk_commits(start, first_parent=first_parent):
        parent_map[oid] = (
            commit.parents[:1] if first_parent else commit.parents
        )
        if max_count is not None and count >= max_count:
            break
        when = commit.committer.time
        if until_ts is not None and when > until_ts:
            continue
        if since_ts is not None and when < since_ts:
            continue
        sig = f"{commit.author.name} <{commit.author.email}>"
        if author_res and not any(r.search(sig) for r in author_res):
            continue
        csig = f"{commit.committer.name} <{commit.committer.email}>"
        if committer_res and not any(r.search(csig) for r in committer_res):
            continue
        if grep_res and not any(r.search(commit.message) for r in grep_res):
            continue
        changed = None
        if not key_filter.match_all:
            changed = _touched_datasets(oid, commit)
            if not changed:
                continue
        if skip is not None and skipped < skip:
            skipped += 1
            continue
        if dataset_changes and changed is None:
            # only for commits actually displayed — a full repo diff per
            # commit is too expensive to spend on skipped ones
            changed = _touched_datasets(oid, commit)
        entries.append((oid, commit, changed))
        count += 1

    if output_format in ("json", "json-lines"):
        out = []
        for oid, c, changed in entries:
            item = _commit_json(oid, c)
            if dataset_changes:
                item["datasetChanges"] = changed
            if feature_count_accuracy:
                from kart_tpu.diff.estimation import (
                    estimate_diff_feature_counts,
                )

                parent = c.parents[0] if c.parents else None
                # respect the command's dataset filters: counts must cover
                # the same datasets the rest of the output does
                ds_paths = (
                    {f.split(":", 1)[0] for f in filters} if filters else None
                )
                item["featureChanges"] = estimate_diff_feature_counts(
                    repo,
                    repo.structure(parent) if parent else None,
                    repo.structure(oid),
                    accuracy=feature_count_accuracy,
                    ds_paths=ds_paths,
                )
            out.append(item)
        if output_format == "json":
            dump_json_output(out, "-", json_style=json_style)
        else:
            import sys

            for item in out:
                json.dump(item, sys.stdout, separators=(",", ":"))
                sys.stdout.write("\n")
        return

    if graph:
        rows = _graph_rows([(oid, c) for oid, c, _ in entries], parent_map)
        changed_by_oid = {oid: ch for oid, _, ch in entries}
        for prefix, oid, commit in rows:
            if oid is None:
                click.echo(prefix)
            else:
                suffix = ""
                if dataset_changes and changed_by_oid.get(oid):
                    suffix = f"  ({', '.join(changed_by_oid[oid])})"
                click.echo(f"{prefix} {oid[:7]} {commit.message_summary}{suffix}")
        return

    for oid, commit, changed in entries:
        if oneline:
            suffix = f"  ({', '.join(changed)})" if dataset_changes and changed else ""
            click.echo(f"{oid[:7]} {commit.message_summary}{suffix}")
        else:
            from datetime import datetime, timedelta, timezone

            tz = timezone(timedelta(minutes=commit.author.offset))
            when = datetime.fromtimestamp(commit.author.time, timezone.utc).astimezone(tz)
            click.secho(f"commit {oid}", fg="yellow")
            click.echo(f"Author: {commit.author.name} <{commit.author.email}>")
            click.echo(f"Date:   {when.strftime('%a %b %d %H:%M:%S %Y %z')}")
            if dataset_changes and changed:
                click.echo(f"Datasets: {', '.join(changed)}")
            click.echo()
            for line in commit.message.splitlines():
                click.echo(f"    {line}")
            click.echo()


def _iso_utc(ts):
    from datetime import datetime, timezone

    return datetime.fromtimestamp(ts, timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


def _iso_tz(minutes):
    sign = "+" if minutes >= 0 else "-"
    return f"{sign}{abs(minutes) // 60:02d}:{abs(minutes) % 60:02d}"


def _commit_json(oid, commit):
    """The reference's commit json shape (kart/log.py:408-445): UTC times
    with the zone carried separately."""
    author = commit.author
    committer = commit.committer
    return {
        "commit": oid,
        "abbrevCommit": oid[:7],
        "message": commit.message,
        "refs": [],
        "authorName": author.name,
        "authorEmail": author.email,
        "authorTime": _iso_utc(author.time),
        "authorTimeOffset": _iso_tz(author.offset),
        "committerEmail": committer.email,
        "committerName": committer.name,
        "commitTime": _iso_utc(committer.time),
        "commitTimeOffset": _iso_tz(committer.offset),
        "parents": list(commit.parents),
        "abbrevParents": [p[:7] for p in commit.parents],
    }


class _CommitForShow:
    def __init__(self, oid, commit):
        self.oid = oid
        self.author = commit.author
        self.message = commit.message


@cli.command()
@click.option(
    "--output-format", "-o", type=click.Choice(OUTPUT_FORMATS), default="text"
)
@click.option("--json-style", type=click.Choice(["extracompact", "compact", "pretty"]), default="pretty")
@click.option("--crs", "target_crs", help="Reproject geometries for output")
@click.argument("refish", required=False, default="HEAD")
@click.argument("filters", nargs=-1)
@click.pass_obj
def show(ctx, output_format, json_style, target_crs, refish, filters):
    """Show the changes introduced by a commit."""
    repo = ctx.repo
    oid, _ = repo.resolve_refish(refish)
    commit = repo.odb.read_commit(oid)
    writer_class = BaseDiffWriter.get_diff_writer_class(output_format)
    writer = writer_class(
        repo,
        f"{oid}^?...{oid}",
        filters,
        "-",
        json_style=json_style,
        target_crs=target_crs,
        commit=_CommitForShow(oid, commit),
    )
    writer.write_diff()


@cli.command("create-patch")
@click.option("--json-style", type=click.Choice(["extracompact", "compact", "pretty"]), default="pretty")
@click.option(
    "--patch-type",
    type=click.Choice(["full", "minimal"]),
    default="full",
    help="minimal patches omit unchanged old values (needs the base commit to apply)",
)
@click.option("--output", "output_path", default="-")
@click.argument("refish", required=True)
@click.pass_obj
def create_patch(ctx, json_style, patch_type, output_path, refish):
    """Write a JSON patch of the changes introduced by a commit."""
    from kart_tpu.diff.writers import JsonDiffWriter

    repo = ctx.repo
    oid, _ = repo.resolve_refish(refish)
    commit = repo.odb.read_commit(oid)
    writer = JsonDiffWriter(
        repo,
        f"{oid}^?...{oid}",
        (),
        output_path,
        json_style=json_style,
        commit=_CommitForShow(oid, commit),
        patch_type=patch_type,
        include_patch_header=True,
    )
    writer.write_diff()


@cli.command("apply")
@click.option("--no-commit", is_flag=True, help="Apply to the working copy only")
@click.option("--allow-empty", is_flag=True)
@click.option("--ref", default="HEAD",
              help="Which branch to apply the patch onto (default: HEAD)")
@click.argument("patch_file", type=click.File("r"))
@click.pass_obj
def apply_(ctx, no_commit, allow_empty, ref, patch_file):
    """Apply a JSON patch (as written by create-patch)."""
    from kart_tpu.apply import apply_patch

    repo = ctx.repo
    commit_oid = apply_patch(
        repo, json.load(patch_file), no_commit=no_commit,
        allow_empty=allow_empty, ref=ref,
    )
    if commit_oid:
        click.echo(f"Commit {commit_oid[:7]}")
    else:
        click.echo("Applied patch to working copy")
