"""``kart top`` — a live view of a running transport server
(docs/OBSERVABILITY.md §11).

Polls the server's structured stats document
(``GET /api/v1/stats?format=json`` over HTTP, the ``stats`` op with
``format: "json"`` over ssh) and renders request rates over the configured
windows, per-verb latency percentiles from the server's own bucketed
histograms, inflight/queue depth, shed and cache counters, and the newest
slow-request exemplars — the operational picture of a storm from the
server's side, live.
"""

import json as _json
import time

import click

from kart_tpu.cli import CliError, cli
from kart_tpu.cli.stats_cmds import _resolve_target


def fetch_stats_json(url):
    """-> the parsed stats document of the server at ``url``."""
    from kart_tpu.transport.http import API, http_timeout
    from kart_tpu.transport.remote import is_http_url
    from kart_tpu.transport.stdio import StdioRemote, is_ssh_url

    if is_http_url(url):
        from urllib.request import Request, urlopen

        with urlopen(
            Request(url.rstrip("/") + f"{API}/stats?format=json"),
            timeout=http_timeout(),
        ) as resp:
            return _json.loads(resp.read().decode())
    if is_ssh_url(url):
        remote = StdioRemote(url)
        try:
            resp, _ = remote._rpc({"op": "stats", "format": "json"})
        finally:
            remote.close()
        return resp.get("stats", {})
    raise CliError(
        f"Cannot fetch stats from {url!r}: expected an http(s):// or "
        f"ssh:// URL (or a configured remote name)"
    )


def _hist_by_verb(snapshot, name):
    """{verb: hist dict} for a labelled histogram family."""
    out = {}
    for n, labels, h in snapshot.get("histograms", ()):
        if n == name and "verb" in labels:
            out[labels["verb"]] = h
    return out


def _rate_of(rates_window, name, verb=None):
    total = 0.0
    hit = False
    for n, labels, rate in rates_window:
        if n != name:
            continue
        if verb is not None and labels.get("verb") != verb:
            continue
        total += rate
        hit = True
    return total if hit else 0.0


def _counter_total(snapshot, name):
    return sum(v for n, _l, v in snapshot.get("counters", ()) if n == name)


def _gauge(snapshot, name):
    for n, _l, v in snapshot.get("gauges", ()):
        if n == name:
            return v
    return 0


def render_top(payload, url):
    """One text frame of the live view."""
    snap = payload.get("snapshot", {})
    rates = payload.get("rates", {})
    windows = sorted(rates, key=lambda w: float(w.rstrip("s")))
    hists = _hist_by_verb(snap, "server.request_seconds")

    lines = [
        f"kart top — {url}",
        f"inflight {payload.get('inflight', _gauge(snap, 'server.inflight'))}"
        f"  queue depth {_gauge(snap, 'server.merge_queue.depth')}"
        f"  shed {_counter_total(snap, 'server.shed'):.0f}"
        f"  slow {_counter_total(snap, 'server.slow_requests'):.0f}"
        f"  trace drops {payload.get('events_dropped', 0)}",
    ]
    fleet = payload.get("fleet")
    if fleet:
        # the fleet operator's staleness line (docs/FLEET.md §3): how far
        # this replica's view trails, and where its writes/reads went
        lag = fleet.get("lag_seconds")
        hits = _counter_total(snap, "fleet.peer_cache.hits")
        misses = _counter_total(snap, "fleet.peer_cache.misses")
        peer = (
            f"  peer cache {hits / (hits + misses):.0%} hit"
            if hits + misses
            else ""
        )
        lines.append(
            f"{fleet.get('role', '?')} of {fleet.get('primary') or '-'}"
            f"  lag {f'{lag:.1f}s' if lag is not None else '-'}"
            f"  proxied writes {fleet.get('proxied_writes', 0)}"
            f"  ryw stalls/pins {fleet.get('ryw_stalls', 0)}"
            f"/{fleet.get('ryw_pins', 0)}{peer}"
        )
    events = payload.get("events")
    if events:
        # the live-update path at a glance (docs/EVENTS.md §7): who is
        # listening, how far the log has advanced, how fast the last
        # announcement fanned out, and whether the warmer is keeping up
        fanout = events.get("last_fanout_seconds")
        warm = events.get("last_warm") or {}
        lines.append(
            f"events  watchers {events.get('watchers', 0)}"
            f"  head seq {events.get('head_seq', 0)}"
            f"  warm queue {events.get('queue_depth', 0)}"
            f"  last fanout "
            f"{f'{fanout * 1000:.0f}ms' if fanout is not None else '-'}"
            f"  last warm {warm.get('tiles', 0)} tiles"
            f"/{warm.get('errors', 0)} err"
        )
    query = payload.get("query")
    if query:
        # the query engine at a glance (docs/QUERY.md §7): how much work
        # ran, how much the pushdown pruned away, and whether the scatter
        # and cache tiers are earning their keep
        lines.append(
            f"query  scans {query.get('scans', 0)}"
            f"  joins {query.get('joins', 0)}"
            f"  blocks pruned {query.get('blocks_pruned', 0)}"
            f"  pairs {query.get('pairs_emitted', 0)}"
            f"  scatter parts {query.get('scatter_parts', 0)}"
            f"  cache {query.get('cache_hits', 0)}h"
            f"/{query.get('cache_misses', 0)}m"
        )
    lines.append("")
    rate_heads = "".join(f"  req/s({w})" for w in windows)
    lines.append(
        f"{'verb':<14}{rate_heads}  {'count':>7}  {'p50':>8}  {'p90':>8}  "
        f"{'p99':>8}  {'max':>8}"
    )
    verbs = sorted(
        set(hists)
        | {
            labels.get("verb")
            for n, labels, _v in snap.get("counters", ())
            if n == "transport.server.requests" and labels.get("verb")
        }
    )
    for verb in verbs:
        h = hists.get(verb)
        cells = "".join(
            f"  {_rate_of(rates.get(w, ()), 'transport.server.requests', verb):>10.2f}"
            for w in windows
        )
        if h:
            lines.append(
                f"{verb:<14}{cells}  {h['count']:>7d}  {h['p50']:>8.3f}  "
                f"{h['p90']:>8.3f}  {h['p99']:>8.3f}  {h['max']:>8.3f}"
            )
        else:
            lines.append(f"{verb:<14}{cells}  {0:>7}  {'-':>8}  {'-':>8}  {'-':>8}  {'-':>8}")
    tiles_rates = "".join(
        f"  {_rate_of(rates.get(w, ()), 'tiles.served'):>10.2f}" for w in windows
    )
    if any(n == "tiles.served" for n, _l, _v in snap.get("counters", ())):
        lines.append(f"{'tiles/s':<14}{tiles_rates}")
    exemplars = payload.get("exemplars") or []
    if exemplars:
        lines.append("")
        lines.append(f"slow requests (last {len(exemplars)}):")
        for ex in exemplars[-3:]:
            spans = sorted(
                ex.get("spans", ()), key=lambda s: -s.get("dur", 0)
            )
            frames = ", ".join(
                f"{s['name']} {s['dur']:.3f}s" for s in spans[:3]
            )
            lines.append(
                f"  {ex.get('verb', '?'):<13} {ex.get('seconds', 0):>8.3f}s"
                f"  id={ex.get('request_id', '-')}"
                + (f"  [{frames}]" if frames else "")
            )
    return "\n".join(lines)


@cli.command()
@click.option(
    "--interval",
    "-i",
    type=click.FLOAT,
    default=2.0,
    show_default=True,
    help="Refresh interval (seconds)",
)
@click.option(
    "--once", is_flag=True, help="Print one frame and exit (scripts/tests)"
)
@click.argument("target")
@click.pass_obj
def top(ctx, target, interval, once):
    """Live server dashboard: request rates, latency percentiles, queue
    depth, shed/cache counters and slow-request exemplars.

    TARGET: an http(s):// or ssh:// server URL, or a configured remote
    name. Rates and percentiles are the *server's own* (bucketed
    histograms + windowed counter samples) — not client-side estimates.

    The meaningful target is a long-lived `kart serve` (HTTP) process. An
    ssh target works but reports the just-spawned single-connection
    serve-stdio process — real client traffic accumulates in *other*
    processes, so expect an empty view (useful only to verify wiring).
    """
    url = _resolve_target(ctx, target)
    while True:
        try:
            payload = fetch_stats_json(url)
        except OSError as e:
            raise CliError(f"Cannot reach {target!r}: {e}")
        except ValueError as e:
            # a pre-JSON server or a proxy error page answered the stats
            # query with non-JSON: name the problem, don't stack-trace
            raise CliError(
                f"{target!r} did not return the JSON stats document "
                f"(old server version, or a proxy in the way?): {e}"
            )
        frame = render_top(payload, url)
        if once:
            click.echo(frame)
            return
        click.clear()
        click.echo(frame)
        time.sleep(max(0.2, interval))
