"""``kart lint`` — run the static-analysis suite (docs/ANALYSIS.md).

With no PATHS: the full tree (kart_tpu/ + bench.py) including the
cross-file registry round-trip checks; with PATHS (files or directories):
per-file checks only — the fast pre-commit mode. ``--changed [REF]`` lints
only files touched vs a git ref (default HEAD) — the diff-driven CI entry
point. Exit 0 = clean."""

import click

from kart_tpu.cli import cli


@cli.command()
@click.argument("paths", nargs=-1, type=click.Path(exists=True))
@click.option(
    "-o",
    "--format",
    "fmt",
    type=click.Choice(["text", "json", "sarif"]),
    default="text",
    help="Output format (json and sarif are stable schemas for external CI)",
)
@click.option(
    "--changed",
    "changed_ref",
    is_flag=False,
    flag_value="HEAD",
    metavar="[REF]",
    help="Lint only files touched vs REF (default HEAD): the pre-commit/"
    "CI diff mode. Mutually exclusive with PATHS.",
)
@click.option(
    "--rules",
    "list_rules",
    is_flag=True,
    help="List the rule catalogue and exit",
)
def lint(paths, fmt, changed_ref, list_rules):
    """Check the tree against the repo's cross-cutting contracts."""
    from kart_tpu import analysis

    if list_rules:
        for r in analysis.rule_catalogue():
            click.echo(f"{r['id']}  {r['name']}: {r['description']}")
        return
    if changed_ref is not None:
        if paths:
            raise click.UsageError("--changed and PATHS are mutually exclusive")
        try:
            targets = analysis.changed_targets(ref=changed_ref)
        except ValueError as e:
            raise click.UsageError(str(e))
        # an empty target set still reports through the requested format
        # (CI pipelines parse the json/sarif document on docs-only diffs)
        report = analysis.run_lint(targets)
        if not targets and fmt == "text":
            click.echo(f"ok: no lint targets changed vs {changed_ref}")
            return
    else:
        report = analysis.run_lint(list(paths) or None)
    if fmt == "json":
        click.echo(analysis.to_json(report, indent=2))
    elif fmt == "sarif":
        click.echo(analysis.to_sarif(report, indent=2))
    else:
        click.echo(analysis.to_text(report))
    if not report.ok:
        raise SystemExit(1)
