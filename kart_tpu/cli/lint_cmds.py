"""``kart lint`` — run the static-analysis suite (docs/ANALYSIS.md).

With no PATHS: the full tree (kart_tpu/ + bench.py) including the
cross-file registry round-trip checks; with PATHS (files or directories):
per-file checks only — the fast pre-commit mode. ``--changed [REF]`` lints
only files touched vs a git ref (default HEAD) — the diff-driven CI entry
point. ``--install-hook`` writes the fail-closed pre-commit hook. Exit
0 = clean."""

import os
import stat

import click

from kart_tpu.cli import cli


@cli.command()
@click.argument("paths", nargs=-1, type=click.Path(exists=True))
@click.option(
    "-o",
    "--format",
    "fmt",
    type=click.Choice(["text", "json", "sarif"]),
    default="text",
    help="Output format (json and sarif are stable schemas for external CI)",
)
@click.option(
    "--changed",
    "changed_ref",
    is_flag=False,
    flag_value="HEAD",
    metavar="[REF]",
    help="Lint only files touched vs REF (default HEAD): the pre-commit/"
    "CI diff mode. Mutually exclusive with PATHS.",
)
@click.option(
    "--rules",
    "list_rules",
    is_flag=True,
    help="List the rule catalogue (numeric KTL order, with family) and exit",
)
@click.option(
    "--install-hook",
    "install_hook",
    is_flag=True,
    help="Write the fail-closed pre-commit hook (`kart lint --changed`) "
    "into .git/hooks/pre-commit and exit; refuses to clobber a hook it "
    "did not write",
)
def lint(paths, fmt, changed_ref, list_rules, install_hook):
    """Check the tree against the repo's cross-cutting contracts."""
    from kart_tpu import analysis

    if list_rules:
        for r in analysis.rule_catalogue():
            click.echo(
                f"{r['id']}  [{r['family']}] {r['name']}: {r['description']}"
            )
        return
    if install_hook:
        click.echo(_install_pre_commit_hook(analysis.repo_root()))
        return
    if changed_ref is not None:
        if paths:
            raise click.UsageError("--changed and PATHS are mutually exclusive")
        try:
            targets = analysis.changed_targets(ref=changed_ref)
        except ValueError as e:
            raise click.UsageError(str(e))
        # an empty target set still reports through the requested format
        # (CI pipelines parse the json/sarif document on docs-only diffs)
        report = analysis.run_lint(targets)
        if not targets and fmt == "text":
            click.echo(f"ok: no lint targets changed vs {changed_ref}")
            return
    else:
        report = analysis.run_lint(list(paths) or None)
    if fmt == "json":
        click.echo(analysis.to_json(report, indent=2))
    elif fmt == "sarif":
        click.echo(analysis.to_sarif(report, indent=2))
    else:
        click.echo(analysis.to_text(report))
    if not report.ok:
        raise SystemExit(1)


#: the marker is the clobber contract: a hook carrying it was written by
#: us and may be rewritten in place; anything else is the user's and is
#: never touched.
HOOK_MARKER = "installed by `kart lint --install-hook`"

HOOK_SCRIPT = f"""#!/bin/sh
# pre-commit hook {HOOK_MARKER} (docs/ANALYSIS.md).
# Lints the files this commit touches. Any finding — or the linter
# failing to run at all — blocks the commit: fail closed.
exec python -m kart_tpu.analysis --changed HEAD
"""


def _install_pre_commit_hook(root):
    hooks_dir = os.path.join(root, ".git", "hooks")
    if not os.path.isdir(os.path.join(root, ".git")):
        raise click.ClickException(f"{root} is not a git repository")
    path = os.path.join(hooks_dir, "pre-commit")
    if os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            existing = f.read()
        if HOOK_MARKER not in existing:
            raise click.ClickException(
                f"{path} exists and was not written by `kart lint "
                "--install-hook` — refusing to clobber it; chain "
                "`python -m kart_tpu.analysis --changed HEAD` from your "
                "hook instead"
            )
        if existing == HOOK_SCRIPT:
            return f"pre-commit hook already current: {path}"
    os.makedirs(hooks_dir, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(HOOK_SCRIPT)
    st = os.stat(path)
    os.chmod(
        path, st.st_mode | stat.S_IXUSR | stat.S_IXGRP | stat.S_IXOTH
    )
    return f"pre-commit hook installed: {path}"
