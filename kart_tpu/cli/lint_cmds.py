"""``kart lint`` — run the static-analysis suite (docs/ANALYSIS.md).

With no PATHS: the full tree (kart_tpu/ + bench.py) including the
cross-file registry round-trip checks; with PATHS (files or directories):
per-file checks only — the fast pre-commit mode. Exit 0 = clean."""

import click

from kart_tpu.cli import cli


@cli.command()
@click.argument("paths", nargs=-1, type=click.Path(exists=True))
@click.option(
    "-o",
    "--format",
    "fmt",
    type=click.Choice(["text", "json"]),
    default="text",
    help="Output format (json is a stable schema for external CI)",
)
@click.option(
    "--rules",
    "list_rules",
    is_flag=True,
    help="List the rule catalogue and exit",
)
def lint(paths, fmt, list_rules):
    """Check the tree against the repo's cross-cutting contracts."""
    from kart_tpu import analysis

    if list_rules:
        for r in analysis.rule_catalogue():
            click.echo(f"{r['id']}  {r['name']}: {r['description']}")
        return
    report = analysis.run_lint(list(paths) or None)
    if fmt == "json":
        click.echo(analysis.to_json(report, indent=2))
    else:
        click.echo(analysis.to_text(report))
    if not report.ok:
        raise SystemExit(1)
