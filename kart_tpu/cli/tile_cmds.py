"""`kart export` — batch tile export off the columnar store
(docs/TILES.md §5).

``kart export tiles <refish>`` walks a zoom pyramid over one dataset at
any commit and writes every non-empty tile payload to disk — the offline
twin of the ``GET /api/v1/tiles/...`` endpoint (same wire format, same
pruning, byte-identical payloads for the same commit)."""

import click

from kart_tpu.cli import CliError, cli


@cli.group()
def export():
    """Export repository data into derived read-serving artifacts."""


@export.command("tiles")
@click.argument("refish", default="HEAD")
@click.option(
    "--dataset",
    "ds_path",
    default=None,
    help="Dataset to export (default: the repo's only dataset).",
)
@click.option(
    "--zoom",
    "zoom_spec",
    default="0-4",
    show_default=True,
    help="Zoom level or range (Z or Z0-Z1).",
)
@click.option(
    "--output",
    "-o",
    "out_dir",
    type=click.Path(file_okay=False),
    default=None,
    help="Output directory (default: ./tiles-<short-oid>). Tiles land as "
    "<output>/<z>/<x>/<y>.ktile.",
)
@click.option(
    "--layers",
    default=None,
    help="Comma-separated layers to include: bin,geojson,ktb2,mvt,props "
    "(default: the server's negotiated default — bin,geojson, or "
    "KART_TILE_ENCODING). geojson/props need feature blobs locally; a "
    "partial clone exports --layers bin, ktb2 or mvt.",
)
@click.option(
    "--max-features",
    type=click.INT,
    default=None,
    help="Per-tile feature ceiling; over-full tiles are skipped (counted). "
    "Overrides KART_TILE_MAX_FEATURES; 0 = unlimited.",
)
@click.option(
    "--workers",
    type=click.INT,
    default=None,
    help="Parallel encode workers (default: KART_EXPORT_WORKERS, else the "
    "core count on a >=4-core box). 1 = serial in-process, which routes "
    "encode batches through the device mesh when one is live.",
)
@click.option(
    "--strict",
    is_flag=True,
    help="Fail (non-zero exit, listing the skipped tiles) if any tile "
    "exceeded the feature ceiling — by default skips are only counted, "
    "which can leave a silently incomplete pyramid.",
)
@click.pass_obj
def export_tiles(ctx, refish, ds_path, zoom_spec, out_dir, layers,
                 max_features, workers, strict):
    """Export a zoom pyramid of vector tiles for REFISH (any commit).

    No working copy and no GDAL involved: tiles are built straight from
    the commit's KCOL sidecar columns, block-pruned by the per-block
    union-bbox aggregates, and are byte-identical to what `kart serve`
    answers for the same commit (docs/TILES.md).
    """
    import os

    from kart_tpu import tiles
    from kart_tpu.tiles.grid import TileAddressError, parse_zoom_spec
    from kart_tpu.tiles.pyramid import export_pyramid

    repo = ctx.repo
    try:
        zooms = parse_zoom_spec(zoom_spec)
        commit_oid = tiles.resolve_tile_commit(repo, refish)
        if ds_path is None:
            paths = repo.structure(refish).datasets.paths()
            if len(paths) != 1:
                raise CliError(
                    f"Repo has {len(paths)} datasets; pick one with --dataset "
                    f"({', '.join(paths) or 'none'})"
                )
            ds_path = paths[0]
        source = tiles.source_for(repo, commit_oid, ds_path)
        out_dir = out_dir or os.path.join(".", f"tiles-{commit_oid[:12]}")
        stats = export_pyramid(
            source, zooms, out_dir,
            layers=tiles.normalise_layers(layers),
            max_features=max_features,
            workers=workers,
        )
    except (tiles.TileAddressError, tiles.TileEncodeError,
            tiles.TileSourceError, TileAddressError) as e:
        raise CliError(str(e))
    skipped = stats["tiles_skipped"]
    if skipped and strict:
        shown = ", ".join(f"{z}/{x}/{y}" for z, x, y in skipped[:20])
        more = f" (+{len(skipped) - 20} more)" if len(skipped) > 20 else ""
        raise CliError(
            f"--strict: {len(skipped)} tiles exceeded the feature ceiling "
            f"and were skipped — the pyramid is incomplete: {shown}{more}. "
            f"Raise --max-features / KART_TILE_MAX_FEATURES or export "
            f"deeper zooms."
        )
    click.echo(
        f"Exported {stats['tiles_written']} tiles "
        f"({stats['features_out']} features, {stats['bytes_out']} bytes) "
        f"of {ds_path}@{commit_oid[:12]} to {out_dir} "
        f"[z{zooms[0]}-z{zooms[-1]}; {stats['tiles_empty']} empty, "
        f"{stats['tiles_too_large']} over the feature ceiling; "
        f"{stats['export_workers']} workers]"
    )
    if skipped:
        click.echo(
            f"warning: {len(skipped)} tiles skipped over the feature "
            f"ceiling — the pyramid is incomplete (use --strict to fail, "
            f"--max-features 0 to lift)",
            err=True,
        )
