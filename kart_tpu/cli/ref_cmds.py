"""branch / tag / config / gc / fsck (reference: kart/branch.py plus git
pass-through commands, kart/fsck.py)."""

import os

import click

from kart_tpu.cli import CliError, cli
from kart_tpu.core.repo import InvalidOperation
from kart_tpu.diff.output import dump_json_output


@cli.command()
@click.option("-d", "--delete", "delete_branch", help="Delete this branch")
@click.option("-f", "--force", is_flag=True)
@click.option("--output-format", "-o", type=click.Choice(["text", "json"]), default="text")
@click.argument("name", required=False)
@click.argument("start_point", required=False, default="HEAD")
@click.pass_obj
def branch(ctx, delete_branch, force, output_format, name, start_point):
    """List, create or delete branches."""
    repo = ctx.repo
    if delete_branch:
        ref = f"refs/heads/{delete_branch}"
        if not repo.refs.exists(ref):
            raise CliError(f"No such branch: {delete_branch}")
        if repo.head_branch == ref:
            raise InvalidOperation(f"Cannot delete the current branch {delete_branch}")
        if not force:
            oid = repo.refs.get(ref)
            head = repo.head_commit_oid
            if head and not repo.is_ancestor(oid, head):
                raise InvalidOperation(
                    f"Branch {delete_branch} is not fully merged — use -f to delete anyway"
                )
        repo.refs.delete(ref)
        click.echo(f"Deleted branch {delete_branch}")
        return
    if name:
        oid, _ = repo.resolve_refish(start_point)
        ref = f"refs/heads/{name}"
        if repo.refs.exists(ref) and not force:
            raise InvalidOperation(f"Branch already exists: {name}")
        repo.refs.set(ref, oid, log_message=f"branch: created from {start_point}")
        return
    current = repo.head_branch
    branches = list(repo.refs.iter_refs("refs/heads/"))
    if output_format == "json":
        dump_json_output(
            {
                "kart.branch/v1": {
                    "current": current.rsplit("/", 1)[-1] if current else None,
                    "branches": {
                        ref[len("refs/heads/"):]: {"commit": oid, "abbrevCommit": oid[:7]}
                        for ref, oid in branches
                    },
                }
            },
            "-",
        )
        return
    for ref, oid in branches:
        short = ref[len("refs/heads/"):]
        marker = "*" if ref == current else " "
        click.echo(f"{marker} {short}")


@cli.command()
@click.option("-d", "--delete", "delete_tag", help="Delete this tag")
@click.option("-m", "--message", help="Create an annotated tag with this message")
@click.argument("name", required=False)
@click.argument("target", required=False, default="HEAD")
@click.pass_obj
def tag(ctx, delete_tag, message, name, target):
    """List, create or delete tags."""
    repo = ctx.repo
    if delete_tag:
        ref = f"refs/tags/{delete_tag}"
        if not repo.refs.exists(ref):
            raise CliError(f"No such tag: {delete_tag}")
        repo.refs.delete(ref)
        click.echo(f"Deleted tag {delete_tag}")
        return
    if name:
        oid, _ = repo.resolve_refish(target)
        repo.create_tag(name, oid, message=message)
        return
    for ref, _ in repo.refs.iter_refs("refs/tags/"):
        click.echo(ref[len("refs/tags/"):])


@cli.command()
@click.argument("key")
@click.argument("value", required=False)
@click.option("--unset", is_flag=True)
@click.pass_obj
def config(ctx, key, value, unset):
    """Get or set repository configuration."""
    repo = ctx.repo
    if unset:
        del repo.config[key]
        return
    if value is not None:
        repo.config[key] = value
        return
    current = repo.config.get(key)
    if current is None:
        raise SystemExit(1)
    click.echo(current)


@cli.command(context_settings={"ignore_unknown_options": True})
@click.argument("args", nargs=-1, type=click.UNPROCESSED)
@click.pass_obj
def gc(ctx, args):
    """Clean up the object store: pack loose objects, sweep crash leftovers
    (stale ``*.tmp``/``*.lock`` files, abandoned push quarantines).
    ``--auto`` only repacks above the loose-object threshold; ``--grace=N``
    sets the leftover age threshold in seconds (default 3600, env
    KART_GC_GRACE); ``--prune-now`` sweeps leftovers regardless of age."""
    stats = ctx.repo.gc(*args)
    if stats and (stats.get("packed") or stats.get("pruned")):
        click.echo(
            f"Packed {stats.get('packed', 0)} loose objects; "
            f"pruned {stats.get('pruned', 0)} temp files."
        )
    else:
        click.echo("Nothing to do.")


@cli.command()
@click.option("--reset-datasets", is_flag=True, hidden=True)
@click.pass_obj
def fsck(ctx, reset_datasets):
    """Verify repository integrity: object store, refs, dataset structure,
    working copy sync (reference: kart/fsck.py)."""
    repo = ctx.repo
    errors = []

    # object store: every object parses and hashes to its name
    click.echo("Checking object store...")
    count = 0
    for oid in repo.odb.iter_oids():
        try:
            obj_type, content = repo.odb.read_raw(oid)
            from kart_tpu.core.objects import hash_object

            if hash_object(obj_type, content) != oid:
                errors.append(f"Object {oid} content does not match its id")
        except Exception as e:
            errors.append(f"Object {oid} is corrupt: {e}")
        count += 1
    click.echo(f"  {count} objects")

    # refs point at real commits
    click.echo("Checking refs...")
    for ref, oid in repo.refs.iter_refs():
        if not repo.odb.contains(oid):
            errors.append(f"Ref {ref} points at missing object {oid}")

    # crash leftovers: stale lock/temp files and abandoned push quarantines
    # are debris, not corruption — report them (gc sweeps them)
    click.echo("Checking for stale crash leftovers...")
    stale = list(repo.find_stale_leftovers())
    if stale:
        click.echo(
            f"  {len(stale)} stale lock/temp leftover(s) from a crashed "
            f"process — run `kart gc` to sweep:"
        )
        for path in stale[:5]:
            click.echo(f"    {os.path.relpath(path, repo.gitdir)}")
        if len(stale) > 5:
            click.echo(f"    ... and {len(stale) - 5} more")

    # dataset structure at HEAD
    if not repo.head_is_unborn:
        click.echo("Checking datasets...")
        for ds in repo.datasets():
            try:
                ds.schema
                n = ds.feature_count
                click.echo(f"  {ds.path}: {n} features")
            except Exception as e:
                errors.append(f"Dataset {ds.path} is corrupt: {e}")

    # columnar sidecars mirror their feature trees exactly — a corrupt
    # sidecar would silently wrong every columnar diff, so fsck rebuilds
    # the (pk, oid) columns from the tree and compares
    if not repo.head_is_unborn:
        click.echo("Checking columnar sidecars...")
        import numpy as np

        from kart_tpu.diff import sidecar as sidecar_mod
        from kart_tpu.ops.blocks import FeatureBlock

        for ds in repo.datasets():
            try:
                if ds.feature_tree is None or not sidecar_mod.has_sidecar(
                    repo, ds
                ):
                    continue
                block = sidecar_mod.load_block(repo, ds)
                tree_block = FeatureBlock.from_dataset(ds, pad=False)
                ok = (
                    block is not None
                    and block.count == tree_block.count
                    and np.array_equal(
                        block.keys[: block.count],
                        tree_block.keys[: tree_block.count],
                    )
                    and np.array_equal(
                        block.oids[: block.count],
                        tree_block.oids[: tree_block.count],
                    )
                )
                if ok:
                    click.echo(f"  {ds.path}: sidecar OK ({block.count} rows)")
                else:
                    errors.append(
                        f"Dataset {ds.path}: columnar sidecar does not "
                        f"match the feature tree"
                    )
            except Exception as e:
                errors.append(f"Dataset {ds.path}: sidecar check failed: {e}")

    # working copy state
    wc = repo.working_copy
    if wc is not None:
        click.echo("Checking working copy...")
        tree = wc.get_db_tree()
        head_tree = repo.head_tree_oid
        if tree != head_tree:
            errors.append(
                f"Working copy tree {tree} does not match HEAD tree {head_tree}"
            )

    if errors:
        for e in errors:
            click.secho(f"error: {e}", fg="red", err=True)
        raise SystemExit(1)
    click.echo("No errors found.")


@cli.command()
@click.argument("ref", required=False, default="HEAD")
@click.pass_obj
def reflog(ctx, ref):
    """Show the log of where REF has pointed (reference: the pass-through
    `kart reflog`, kart/cli.py:211-305)."""
    repo = ctx.repo
    entries = []
    if ref == "HEAD" or ref.startswith("refs/"):
        candidates = [ref]
    else:
        # short names resolve like git: heads, then tags, then remotes
        candidates = [
            f"refs/heads/{ref}",
            f"refs/tags/{ref}",
            f"refs/remotes/{ref}",
        ]
    for candidate in candidates:
        entries = repo.refs.read_reflog(candidate)
        if entries:
            ref = candidate
            break
    if not entries:
        click.echo(f"No reflog for {ref}")
        return
    short = ref if ref == "HEAD" else ref.split("/", 2)[-1]
    for i, entry in enumerate(reversed(entries)):
        new = entry.get("new") or "0" * 40
        click.echo(f"{new[:7]} {short}@{{{i}}}: {entry.get('message', '')}")


@cli.command("git", context_settings={"ignore_unknown_options": True})
@click.argument("args", nargs=-1, type=click.UNPROCESSED)
@click.pass_obj
def git_passthrough(ctx, args):
    """Run a git command against this repository (reference: the raw-git
    passthrough, kart/cli.py:211-305). The object store, refs, and packs
    are git-compatible; the locked index deliberately stops stock git from
    touching the working copy."""
    import os
    import shutil
    import subprocess
    import sys

    git_bin = shutil.which("git")
    if git_bin is None:
        raise CliError("git is not installed on this system")
    repo = ctx.repo
    env = dict(os.environ, GIT_DIR=repo.gitdir)
    if repo.workdir is not None:
        env["GIT_WORK_TREE"] = repo.workdir
    proc = subprocess.run([git_bin, *args], env=env)
    sys.exit(proc.returncode)
