"""``kart stats`` — dump telemetry metrics (reference analog: none; this is
the operational window the reference gets from git's trace2, exposed here
as a Prometheus-style text exposition, docs/OBSERVABILITY.md §4).

Against a *target* (an http(s):// or ssh:// URL, or a configured remote
name) it asks the running transport server for its live metric registry —
request counts per verb, bytes shipped, fetch resumes, receive-pack
outcomes, retry/watchdog counters. With no target it dumps this process's
own registry (useful after ``KART_METRICS=1 kart …`` in scripts/tests).
"""

import json as _json

import click

from kart_tpu.cli import CliError, cli


def _resolve_target(ctx, target):
    """remote name -> its configured URL (needs a repo); URLs pass
    through."""
    from kart_tpu.transport.remote import is_http_url
    from kart_tpu.transport.stdio import is_ssh_url

    if is_http_url(target) or is_ssh_url(target):
        return target
    repo = ctx.repo  # raises a UsageError outside a repo
    url = repo.remote_url(target)
    if url is None:
        raise CliError(f"No such remote: {target!r}")
    return url


def fetch_remote_stats(url):
    """-> the Prometheus text exposition of the server at ``url``."""
    from kart_tpu.transport.http import API, http_timeout
    from kart_tpu.transport.remote import is_http_url
    from kart_tpu.transport.stdio import StdioRemote, is_ssh_url

    if is_http_url(url):
        from urllib.request import Request, urlopen

        with urlopen(
            Request(url.rstrip("/") + f"{API}/stats"), timeout=http_timeout()
        ) as resp:
            return resp.read().decode()
    if is_ssh_url(url):
        remote = StdioRemote(url)
        try:
            resp, _ = remote._rpc({"op": "stats"})
        finally:
            remote.close()
        return resp.get("metrics", "")
    raise CliError(
        f"Cannot fetch stats from {url!r}: expected an http(s):// or "
        f"ssh:// URL (or a configured remote name)"
    )


@cli.command()
@click.option(
    "--output-format",
    "-o",
    type=click.Choice(["text", "json"]),
    default="text",
    help="text = Prometheus exposition; json = structured snapshot "
    "(local registry only)",
)
@click.argument("target", required=False)
@click.pass_obj
def stats(ctx, output_format, target):
    """Dump telemetry metrics.

    TARGET: an http(s):// or ssh:// server URL, or a configured remote
    name — the running server's metrics are fetched and printed. Without
    TARGET, this process's own metric registry is dumped (enable with
    KART_METRICS=1).
    """
    from kart_tpu import telemetry
    from kart_tpu.telemetry import sinks

    if target:
        try:
            text = fetch_remote_stats(_resolve_target(ctx, target))
        except OSError as e:
            raise CliError(f"Cannot reach {target!r}: {e}")
        click.echo(text.rstrip("\n"))
        return
    if output_format == "json":
        click.echo(_json.dumps(telemetry.snapshot(), indent=2, default=str))
        return
    text = sinks.prometheus_text()
    if text:
        click.echo(text.rstrip("\n"))
    else:
        click.echo(
            "# no metrics recorded in this process "
            "(enable with KART_METRICS=1, or pass a server URL)"
        )
