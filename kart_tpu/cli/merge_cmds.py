"""merge / conflicts / resolve (reference: kart/merge.py, kart/conflicts.py,
kart/resolve.py)."""

import json
import sys

import click

from kart_tpu.cli import CliError, cli
from kart_tpu.core.repo import InvalidOperation, KartRepoState, NotFound
from kart_tpu.diff.output import dump_json_output


def _merge_json(result, repo):
    body = {}
    if result.already_merged:
        body["noOp"] = True
        body["message"] = "Already up to date"
    elif result.fast_forward:
        body["fastForward"] = True
        body["commit"] = result.commit_oid
    elif result.has_conflicts:
        conflicts = result.merge_index.conflicts
        body["conflicts"] = _conflict_summary(conflicts)
        body["state"] = "merging"
    else:
        body["commit"] = result.commit_oid
        body["merging"] = False
    if result.dry_run:
        body["dryRun"] = True
    return {"kart.merge/v1": body}


def _conflict_kind(aot):
    """ancestor/ours/theirs presence -> 'edit/edit' | 'add/add' |
    'delete/edit' | 'edit/delete' (reference: kart/merge_util.py conflict
    labelling)."""
    if aot.ancestor is None:
        return "add/add"
    if aot.ours is None:
        return "delete/edit"
    if aot.theirs is None:
        return "edit/delete"
    return "edit/edit"


def _conflict_summary(conflicts):
    """label dict -> nested {ds_path: {'featureConflicts': {...}} } summary
    (reference: conflicts output shape, kart/conflicts.py)."""
    summary = {}
    for label, aot in conflicts.items():
        parts = label.split(":", 2)
        ds_path = parts[0]
        kind = parts[1] if len(parts) > 1 else "feature"
        ds_summary = summary.setdefault(ds_path, {})
        key = "featureConflicts" if kind == "feature" else "metaConflicts"
        bucket = ds_summary.setdefault(key, {})
        how = _conflict_kind(aot)
        bucket[how] = bucket.get(how, 0) + 1
    return summary


@cli.command("merge")
@click.argument("refish", required=False)
@click.option("--message", "-m", help="Commit message for the merge commit")
@click.option("--dry-run", is_flag=True, help="Show what would be merged, don't do it")
@click.option("--ff/--no-ff", default=True, help="Allow/forbid fast-forward")
@click.option("--ff-only", is_flag=True, help="Refuse non-fast-forward merges")
@click.option("--continue", "continue_", is_flag=True, help="Complete an in-progress merge")
@click.option("--abort", "abort_", is_flag=True, help="Abort an in-progress merge")
@click.option(
    "-o", "--output-format", type=click.Choice(["text", "json"]), default="text"
)
@click.pass_context
def merge(ctx, refish, message, dry_run, ff, ff_only, continue_, abort_, output_format):
    """Incorporate changes from the named commit into the current branch."""
    from kart_tpu.merge import (
        abort_merging_state,
        complete_merging_state,
        do_merge,
    )

    repo = ctx.obj.repo
    try:
        if abort_:
            repo_state = repo.state
            if repo_state != KartRepoState.MERGING:
                raise CliError("Repository is not in 'merging' state")
            abort_merging_state(repo)
            from kart_tpu.core.structure import RepoStructure
            from kart_tpu.workingcopy import get_working_copy

            wc = get_working_copy(repo)
            if wc is not None:
                wc.reset(RepoStructure(repo, "HEAD"), force=True)
            click.echo("Merge aborted")
            return
        if continue_:
            commit_oid = complete_merging_state(repo, message=message)
            if output_format == "json":
                dump_json_output({"kart.merge/v1": {"commit": commit_oid}}, "-")
            else:
                click.echo(f"Merge committed as {commit_oid}")
            return
        if not refish:
            raise CliError("Missing argument: COMMIT")
        result = do_merge(
            repo, refish, message=message, dry_run=dry_run, ff=ff, ff_only=ff_only
        )
    except (InvalidOperation, NotFound) as e:
        raise CliError(str(e))

    if output_format == "json":
        dump_json_output(_merge_json(result, repo), "-")
        return

    if result.already_merged:
        click.echo("Already up to date")
    elif result.fast_forward:
        click.echo(f"Fast-forward to {result.commit_oid}")
    elif result.has_conflicts:
        n = len(result.merge_index.conflicts)
        if result.dry_run:
            click.echo(f"Merge would result in {n} conflicts (dry run)")
        else:
            click.echo(f"Merge resulted in {n} conflicts.")
            click.echo(
                'Repository is now in "merging" state. View conflicts with '
                '"kart conflicts", resolve with "kart resolve", then '
                '"kart merge --continue" (or "kart merge --abort").'
            )
            # entering the merging state is a *successful* outcome
            # (reference: tests/test_merge.py asserts exit 0 here)
    elif result.dry_run:
        click.echo("Merge is possible with no conflicts (dry run)")
    else:
        click.echo(f"Merged and committed as {result.commit_oid}")


class _ConflictDecoder:
    """Decodes conflict entries to output values. Resolves the candidate
    revisions and per-(revision, dataset) objects once per command, not per
    entry."""

    def __init__(self, repo):
        from kart_tpu.core.structure import RepoStructure

        self.repo = repo
        self.structures = []
        merge_head = repo.read_gitdir_file("MERGE_HEAD")
        for refish in ("HEAD", merge_head and merge_head.strip()):
            if not refish:
                continue
            try:
                self.structures.append(RepoStructure(repo, refish))
            except Exception:
                pass
        self._ds_cache = {}

    def _datasets_for(self, ds_path):
        if ds_path not in self._ds_cache:
            found = []
            for structure in self.structures:
                ds = structure.datasets.get(ds_path)
                if ds is not None:
                    found.append(ds)
            self._ds_cache[ds_path] = found
        return self._ds_cache[ds_path]

    def versions_json(self, aot):
        """AncestorOursTheirs of entries -> {version: feature-or-meta json}."""
        out = {}
        for name in ("ancestor", "ours", "theirs"):
            entry = aot.get(name)
            if entry is None:
                continue
            out[name] = self.entry_value_json(entry)
        return out

    def entry_value_json(self, entry):
        if not self.structures:
            return {"$blob": entry.oid}
        ds_path, part, item = self.structures[0].decode_path(entry.path)
        data = self.repo.odb.read_blob(entry.oid)
        if part == "feature":
            for ds in self._datasets_for(ds_path):
                try:
                    return ds.get_feature(path=item, data=data)
                except Exception:
                    continue
            return {"$blob": entry.oid}
        # meta item / attachment: the item name determines the encoding
        # (reference: meta_items.py — *.json are json, everything else text)
        if item.endswith(".json"):
            try:
                return json.loads(data)
            except Exception:
                return {"$blob": entry.oid}
        try:
            return data.decode("utf-8")
        except UnicodeDecodeError:
            return {"$blob": entry.oid}


@cli.command("conflicts")
@click.option(
    "-o",
    "--output-format",
    type=click.Choice(["text", "json", "quiet"]),
    default="text",
)
@click.option(
    "-s", "--summarise", "--summarize", count=True,
    help="Summarise rather than list each conflict (-ss for even shorter)",
)
@click.pass_context
def conflicts(ctx, output_format, summarise):
    """List or summarise the conflicts of an in-progress merge."""
    repo = ctx.obj.repo
    if repo.state != KartRepoState.MERGING:
        raise CliError(
            "Repository is not in 'merging' state - there are no conflicts"
        )
    from kart_tpu.merge.index import MergeIndex

    merge_index = MergeIndex.read_from_repo(repo)
    unresolved = {
        label: aot
        for label, aot in merge_index.conflicts.items()
        if label not in merge_index.resolves
    }

    if output_format == "quiet":
        sys.exit(1 if unresolved else 0)

    decoder = _ConflictDecoder(repo)
    if output_format == "json":
        if summarise:
            body = _conflict_summary(unresolved)
        else:
            body = {
                label: decoder.versions_json(aot)
                for label, aot in sorted(unresolved.items())
            }
        dump_json_output({"kart.conflicts/v1": body}, "-")
        return

    if not unresolved:
        click.echo("No conflicts!")
        return
    if summarise:
        for ds_path, summary in sorted(_conflict_summary(unresolved).items()):
            click.echo(f"{ds_path}:")
            for kind, buckets in summary.items():
                for how, n in buckets.items():
                    click.echo(f"    {kind} {how}: {n}")
    else:
        from kart_tpu.diff.output import feature_as_text

        for label in sorted(unresolved):
            click.echo(f"=== {label} ===")
            versions = decoder.versions_json(unresolved[label])
            is_feature = ":feature:" in label
            for name in ("ancestor", "ours", "theirs"):
                if name in versions:
                    click.echo(f"--- {name}")
                    value = versions[name]
                    if (
                        is_feature
                        and isinstance(value, dict)
                        and value.keys() != {"$blob"}
                    ):
                        # readable geometry/blob summaries, like diff text
                        # output (reference prints "POINT(...)" not bytes)
                        click.echo(feature_as_text(value, prefix="    "))
                    elif isinstance(value, (dict, list)):
                        click.echo(json.dumps(value, indent=4))
                    else:
                        click.echo(f"    {value}")
            click.echo()
    click.echo(f"{len(unresolved)} unresolved conflicts")
    # listing conflicts is not a failure (reference exit semantics; use
    # --output-format quiet for an exit-code signal)


@cli.command("resolve")
@click.argument("label")
@click.option(
    "--with",
    "with_version",
    type=click.Choice(["ancestor", "ours", "theirs", "delete"]),
    help="Resolve the conflict with the named version (or delete the feature)",
)
@click.option(
    "--with-file",
    "with_file",
    type=click.Path(exists=True),
    help="Resolve the conflict with feature(s) from a GeoJSON file",
)
@click.pass_context
def resolve(ctx, label, with_version, with_file):
    """Resolve one conflict of an in-progress merge."""
    if not with_version and not with_file:
        raise CliError("Must supply either --with or --with-file")
    if with_version and with_file:
        raise CliError("--with and --with-file are mutually exclusive")
    repo = ctx.obj.repo
    if repo.state != KartRepoState.MERGING:
        raise CliError("Repository is not in 'merging' state")
    from kart_tpu.merge.index import ConflictEntry, MergeIndex

    merge_index = MergeIndex.read_from_repo(repo)
    if label not in merge_index.conflicts:
        # allow numeric-free fuzzy help
        known = ", ".join(sorted(merge_index.conflicts)[:5])
        raise CliError(f"No such conflict {label!r}. Known conflicts: {known} ...")
    if label in merge_index.resolves:
        raise CliError(f"Conflict {label!r} is already resolved")

    aot = merge_index.conflicts[label]
    if with_file:
        entries = _entries_from_file(repo, label, aot, with_file)
    elif with_version == "delete":
        entries = []
    else:
        entry = aot.get(with_version)
        entries = [entry] if entry is not None else []
    merge_index.add_resolve(label, entries)
    merge_index.write_to_repo(repo)
    remaining = len(merge_index.unresolved_labels)
    click.echo(
        f"Resolved 1 conflict. {remaining} conflicts to go."
        if remaining
        else 'Resolved 1 conflict. All conflicts resolved - run "kart merge --continue"'
    )


def _entries_from_file(repo, label, aot, path):
    """GeoJSON file -> resolution entries (reference: kart/resolve.py:22-66)."""
    from kart_tpu.core.structure import RepoStructure
    from kart_tpu.merge.index import ConflictEntry

    with open(path) as f:
        data = json.load(f)
    if data.get("type") == "FeatureCollection":
        geo_features = data["features"]
    elif data.get("type") == "Feature":
        geo_features = [data]
    else:
        raise CliError(f"{path}: not a GeoJSON Feature or FeatureCollection")

    sample = next((e for e in aot if e is not None), None)
    structure = RepoStructure(repo, "HEAD")
    ds_path, part, item = structure.decode_path(sample.path)
    if part != "feature":
        raise CliError("--with-file can only resolve feature conflicts")
    merge_head = repo.read_gitdir_file("MERGE_HEAD")
    ds = None
    for refish in ("HEAD", merge_head and merge_head.strip()):
        if not refish:
            continue
        ds = RepoStructure(repo, refish).datasets.get(ds_path)
        if ds is not None:
            break
    if ds is None:
        raise CliError(f"Cannot find dataset {ds_path!r}")

    from kart_tpu.geometry import geojson_to_geometry

    entries = []
    for geo_feature in geo_features:
        feature = dict(geo_feature.get("properties") or {})
        geom_col = ds.geom_column_name
        if geom_col and geo_feature.get("geometry") is not None:
            feature[geom_col] = geojson_to_geometry(geo_feature["geometry"])
        pk_cols = [c.name for c in ds.schema.pk_columns]
        for pk_col in pk_cols:
            if pk_col not in feature and geo_feature.get("id") is not None:
                feature[pk_col] = geo_feature["id"]
        full_path, blob = ds.encode_feature(feature)
        oid = repo.odb.write_blob(blob)
        entries.append(ConflictEntry(full_path, oid))
    return entries
