"""merge / conflicts / resolve (reference: kart/merge.py, kart/conflicts.py,
kart/resolve.py)."""

import json
import logging
import sys

import click

from kart_tpu.cli import CliError, cli
from kart_tpu.core.repo import InvalidOperation, KartRepoState, NotFound
from kart_tpu.diff.output import dump_json_output


def _merge_json(result, repo):
    if result.has_conflicts and result.dry_run and not result.already_merged:
        # the document the server's structured conflict rejection also
        # carries — one builder, so the two can never drift
        return merge_conflict_report(result.merge_index.conflicts)
    body = {}
    if result.already_merged:
        body["noOp"] = True
        body["message"] = "Already up to date"
    elif result.fast_forward:
        body["fastForward"] = True
        body["commit"] = result.commit_oid
    elif result.has_conflicts:
        conflicts = result.merge_index.conflicts
        body["conflicts"] = _conflict_summary(conflicts)
        body["state"] = "merging"
    else:
        body["commit"] = result.commit_oid
        body["merging"] = False
    if result.dry_run:
        body["dryRun"] = True
    return {"kart.merge/v1": body}


def _conflict_summary(conflicts):
    """label dict -> {ds_path: {part: count}} — the reference merge
    output's conflict summary (list_conflicts(..., summarise=2);
    kart/merge.py:105-106, e.g. {"layer": {"feature": 4}}).

    Columnar conflict sets short-circuit through ``summary_counts()``: a
    1M-conflict server-side rebase rejection summarises from the key
    column without materialising a million label strings (same output,
    parity-tested)."""
    counts = getattr(conflicts, "summary_counts", None)
    if counts is not None:
        out = {}
        for parts, n in sorted(counts().items()):
            _set_value_at_path(out, parts, n)
        return out
    out = {}
    for label in conflicts:
        _set_value_at_path(out, tuple(label.split(":", 2)), _CONFLICT_PLACEHOLDER)
    return _summarise_tree(out, 2)


def merge_conflict_report(conflicts):
    """The exact ``kart merge <theirs> --dry-run -o json`` document for a
    conflicted merge — the single source of truth shared by the local CLI
    and the server's structured conflict rejection (docs/SERVING.md §6),
    so the report a rejected push carries is byte-identical JSON to what
    the losing client would compute locally."""
    return {
        "kart.merge/v1": {
            "conflicts": _conflict_summary(conflicts),
            "state": "merging",
            "dryRun": True,
        }
    }


def conflict_report_as_text(summary):
    """Render a conflict summary tree as the hierarchical text a local
    ``kart conflicts -ss`` prints (shared renderer for the push-rejection
    report)."""
    return _conflicts_json_as_text(summary)


@cli.command("merge")
@click.argument("refish", required=False)
@click.option("--message", "-m", help="Commit message for the merge commit")
@click.option("--dry-run", is_flag=True, help="Show what would be merged, don't do it")
@click.option("--ff/--no-ff", default=True, help="Allow/forbid fast-forward")
@click.option("--ff-only", is_flag=True, help="Refuse non-fast-forward merges")
@click.option("--continue", "continue_", is_flag=True, help="Complete an in-progress merge")
@click.option("--abort", "abort_", is_flag=True, help="Abort an in-progress merge")
@click.option(
    "-o", "--output-format", type=click.Choice(["text", "json"]), default="text"
)
@click.pass_context
def merge(ctx, refish, message, dry_run, ff, ff_only, continue_, abort_, output_format):
    """Incorporate changes from the named commit into the current branch."""
    from kart_tpu.merge import (
        abort_merging_state,
        complete_merging_state,
        do_merge,
    )

    repo = ctx.obj.repo
    try:
        if abort_:
            repo_state = repo.state
            if repo_state != KartRepoState.MERGING:
                raise CliError("Repository is not in 'merging' state")
            abort_merging_state(repo)
            from kart_tpu.core.structure import RepoStructure
            from kart_tpu.workingcopy import get_working_copy

            wc = get_working_copy(repo)
            if wc is not None:
                wc.reset(RepoStructure(repo, "HEAD"), force=True)
            click.echo("Merge aborted")
            return
        if continue_:
            commit_oid = complete_merging_state(repo, message=message)
            if output_format == "json":
                dump_json_output({"kart.merge/v1": {"commit": commit_oid}}, "-")
            else:
                click.echo(f"Merge committed as {commit_oid}")
            return
        if not refish:
            raise CliError("Missing argument: COMMIT")
        result = do_merge(
            repo, refish, message=message, dry_run=dry_run, ff=ff, ff_only=ff_only
        )
    except (InvalidOperation, NotFound) as e:
        raise CliError(str(e))

    if output_format == "json":
        dump_json_output(_merge_json(result, repo), "-")
        return

    if result.already_merged:
        click.echo("Already up to date")
    elif result.fast_forward:
        click.echo(f"Fast-forward to {result.commit_oid}")
    elif result.has_conflicts:
        n = len(result.merge_index.conflicts)
        if result.dry_run:
            click.echo(f"Merge would result in {n} conflicts (dry run)")
        else:
            click.echo(f"Merge resulted in {n} conflicts.")
            click.echo(
                'Repository is now in "merging" state. View conflicts with '
                '"kart conflicts", resolve with "kart resolve", then '
                '"kart merge --continue" (or "kart merge --abort").'
            )
            # entering the merging state is a *successful* outcome
            # (reference: tests/test_merge.py asserts exit 0 here)
    elif result.dry_run:
        click.echo("Merge is possible with no conflicts (dry run)")
    else:
        click.echo(f"Merged and committed as {result.commit_oid}")


class _ConflictDecoder:
    """Decodes conflict entries to output values. Resolves the candidate
    revisions and per-(revision, dataset) objects once per command, not per
    entry."""

    def __init__(self, repo):
        from kart_tpu.core.structure import RepoStructure

        self.repo = repo
        self.structures = []
        merge_head = repo.read_gitdir_file("MERGE_HEAD")
        for refish in ("HEAD", merge_head and merge_head.strip()):
            if not refish:
                continue
            try:
                self.structures.append(RepoStructure(repo, refish))
            except Exception as e:
                # a vanished/corrupt side of the merge: conflict labels
                # fall back to whichever structures did resolve
                logging.getLogger(__name__).debug(
                    "skipping unreadable ref %r: %s", refish, e
                )
        self._ds_cache = {}

    def _datasets_for(self, ds_path):
        if ds_path not in self._ds_cache:
            found = []
            for structure in self.structures:
                ds = structure.datasets.get(ds_path)
                if ds is not None:
                    found.append(ds)
            self._ds_cache[ds_path] = found
        return self._ds_cache[ds_path]

    def versions_json(self, aot):
        """AncestorOursTheirs of entries -> {version: feature-or-meta json}."""
        out = {}
        for name in ("ancestor", "ours", "theirs"):
            entry = aot.get(name)
            if entry is None:
                continue
            out[name] = self.entry_value_json(entry)
        return out

    def entry_value_json(self, entry):
        if not self.structures:
            return {"$blob": entry.oid}
        ds_path, part, item = self.structures[0].decode_path(entry.path)
        data = self.repo.odb.read_blob(entry.oid)
        if part == "feature":
            for ds in self._datasets_for(ds_path):
                try:
                    return ds.get_feature(path=item, data=data)
                except Exception:
                    continue
            return {"$blob": entry.oid}
        # meta item / attachment: the item name determines the encoding
        # (reference: meta_items.py — *.json are json, everything else text)
        if item.endswith(".json"):
            try:
                return json.loads(data)
            except Exception:
                return {"$blob": entry.oid}
        try:
            return data.decode("utf-8")
        except UnicodeDecodeError:
            return {"$blob": entry.oid}


_CONFLICT_PLACEHOLDER = object()


def _path_part_sort_key(part):
    """Reference sort: numbers numerically, meta before feature, compound
    keys last (kart/conflicts.py:_path_part_sort_key)."""
    if isinstance(part, str) and part.isdigit():
        part = int(part)
    if part == "meta":
        return ("A", part)
    if part == "feature":
        return ("B", part)
    if isinstance(part, str) and "," in part:
        return ("Z", part)
    if isinstance(part, int):
        return ("N", "", part)
    return ("N", part)


def _path_sort_key(path):
    if isinstance(path, str) and ":" in path:
        return tuple(_path_part_sort_key(p) for p in path.split(":"))
    return _path_part_sort_key(path)


def _set_value_at_path(root, path, value):
    cur = root
    for p in path[:-1]:
        cur = cur.setdefault(p, {})
    cur[path[-1]] = value


def _summarise_tree(node, summarise):
    """Nested conflicts dict with placeholder leaves -> names (-s) or
    counts (-ss) at the version-dict level (reference: summarise_conflicts)."""
    first = next(iter(node.values())) if node else None
    if first is _CONFLICT_PLACEHOLDER:
        if summarise == 1:
            return sorted(node.keys(), key=_path_sort_key)
        return len(node)
    for k, v in node.items():
        node[k] = _summarise_tree(v, summarise)
    return node


def _filter_conflicts(unresolved, filters):
    """Label-prefix filtering ('ds', 'ds:feature', 'ds:feature:3')."""
    if not filters:
        return unresolved
    prefixes = [f.rstrip(":") for f in filters]
    return {
        label: aot
        for label, aot in unresolved.items()
        if any(label == p or label.startswith(p + ":") for p in prefixes)
    }


def _build_conflicts_output(repo, unresolved, output_format, *, summarise=0,
                            flat=False, target_crs=None):
    """Unresolved (already-filtered) conflicts -> the reference's output
    structure for the requested format: nested dicts (or --flat
    label-keyed), values rendered per format (feature text blocks /
    json+hexwkb / geojson features)."""
    from kart_tpu.diff.output import (
        feature_as_geojson,
        feature_as_json,
        feature_as_text,
    )

    decoder = _ConflictDecoder(repo)
    if output_format == "geojson":
        flat, summarise = True, 0

    tx_cache = {}

    def transform_for(ds_path):
        if target_crs is None:
            return None
        if ds_path not in tx_cache:
            from kart_tpu.diff.output import geometry_transform_for_dataset

            tx = None
            for ds in decoder._datasets_for(ds_path):
                # an invalid --crs raises here (same policy as diff --crs)
                tx = geometry_transform_for_dataset(ds, target_crs)
                break
            tx_cache[ds_path] = tx
        return tx_cache[ds_path]

    def render(value, label, parts):
        is_feature = len(parts) > 1 and parts[1] == "feature"
        if is_feature and isinstance(value, dict) and "$blob" not in value:
            pk = parts[2] if len(parts) > 2 else None
            if output_format == "text":
                return feature_as_text(value)
            if output_format == "geojson":
                return feature_as_geojson(value, pk, None, transform_for(parts[0]))
            return feature_as_json(value, pk, transform_for(parts[0]))
        # meta item / undecodable blob
        if output_format == "text":
            return value if isinstance(value, str) else json.dumps(value)
        return value

    out = {}
    for label in sorted(unresolved, key=_path_sort_key):
        parts = tuple(label.split(":", 2))
        if summarise:
            if flat:
                out[label] = _CONFLICT_PLACEHOLDER
            else:
                _set_value_at_path(out, parts, _CONFLICT_PLACEHOLDER)
            continue
        versions = decoder.versions_json(unresolved[label])
        leaf = {
            name: render(value, label, parts)
            for name, value in versions.items()
        }
        if flat:
            for name, value in leaf.items():
                out[f"{label}:{name}"] = value
        else:
            _set_value_at_path(out, parts, leaf)
    if summarise:
        out = _summarise_tree(out, summarise)
    if output_format == "geojson":
        features = []
        for key, feature in out.items():
            if isinstance(feature, dict) and feature.get("type") == "Feature":
                feature["id"] = key
                features.append(feature)
        return {"type": "FeatureCollection", "features": features}
    return out


def _conflicts_json_as_text(json_obj):
    """The reference's hierarchical text rendering
    (kart/conflicts.py:conflicts_json_as_text), byte-compatible: each level
    indents 4, keys join with ':', version headers coloured."""

    def style_key_text(key_text, level):
        indent = "    " * level
        style = {}
        if key_text.endswith(":ancestor:"):
            style["fg"] = "red"
        elif key_text.endswith(":ours:"):
            style["fg"] = "green"
        elif key_text.endswith(":theirs:"):
            style["fg"] = "cyan"
        return click.style(indent + key_text, **style)

    def value_to_text(value, path, level):
        if isinstance(value, str):
            return f"{value}\n"
        if isinstance(value, int):
            return f"{value} conflicts\n"
        if isinstance(value, dict):
            separator = "\n" if level == 0 else ""
            return separator.join(
                item_to_text(k, v, path, level)
                for k, v in sorted(
                    value.items(), key=lambda kv: _path_sort_key(kv[0])
                )
            )
        if isinstance(value, list):
            indent = "    " * level
            return "".join(f"{indent}{path}{item}\n" for item in value)
        return f"{value}\n"

    def item_to_text(key, value, path, level):
        key_text = f"{path}{key}:"
        styled = style_key_text(key_text, level)
        value_text = value_to_text(value, key_text, level + 1)
        if isinstance(value, int):
            return f"{styled} {value_text}"
        return f"{styled}\n{value_text}"

    return value_to_text(json_obj, "", 0)


@cli.command("conflicts")
@click.option(
    "-o",
    "--output-format",
    type=click.Choice(["text", "json", "geojson", "quiet"]),
    default="text",
)
@click.option(
    "--exit-code",
    is_flag=True,
    help="Exit with 1 if there are conflicts, 0 if there are none",
)
@click.option(
    "--json-style",
    type=click.Choice(["extracompact", "compact", "pretty"]),
    default="pretty",
)
@click.option(
    "-s", "--summarise", "--summarize", count=True,
    help="Summarise rather than list each conflict (-ss for even shorter)",
)
@click.option(
    "--flat", is_flag=True, hidden=True,
    help="All conflicts in a flat list instead of a hierarchy",
)
@click.option(
    "--crs", "target_crs",
    help="Reproject geometries into the given CRS (EPSG:<code> or WKT)",
)
@click.argument("filters", nargs=-1)
@click.pass_context
def conflicts(ctx, output_format, exit_code, json_style, summarise, flat,
              target_crs, filters):
    """List or summarise the conflicts of an in-progress merge
    (output shape per the reference: kart.conflicts/v1 —
    {dataset: {"feature": {pk: {version: value}}}}; kart/conflicts.py)."""
    repo = ctx.obj.repo
    if repo.state != KartRepoState.MERGING:
        raise CliError(
            "Repository is not in 'merging' state - there are no conflicts"
        )
    from kart_tpu.merge.index import MergeIndex

    merge_index = MergeIndex.read_from_repo(repo)
    unresolved = _filter_conflicts(
        {
            label: aot
            for label, aot in merge_index.conflicts.items()
            if label not in merge_index.resolves
        },
        filters,
    )

    if output_format == "quiet":
        sys.exit(1 if unresolved else 0)

    body = _build_conflicts_output(
        repo, unresolved, output_format,
        summarise=summarise, flat=flat, target_crs=target_crs,
    )
    if output_format == "json":
        dump_json_output({"kart.conflicts/v1": body}, "-", json_style=json_style)
    elif output_format == "geojson":
        dump_json_output(body, "-", json_style=json_style)
    else:
        text = _conflicts_json_as_text(body)
        if text:
            click.echo(text)  # echo's newline = the reference's trailing blank
    if exit_code:
        sys.exit(1 if unresolved else 0)


@cli.command("resolve")
@click.argument("label")
@click.option(
    "--with",
    "with_version",
    type=click.Choice(["ancestor", "ours", "theirs", "delete"]),
    help="Resolve the conflict with the named version (or delete the feature)",
)
@click.option(
    "--with-file",
    "with_file",
    type=click.Path(exists=True),
    help="Resolve the conflict with feature(s) from a GeoJSON file",
)
@click.pass_context
def resolve(ctx, label, with_version, with_file):
    """Resolve one conflict of an in-progress merge."""
    if not with_version and not with_file:
        raise CliError("Must supply either --with or --with-file")
    if with_version and with_file:
        raise CliError("--with and --with-file are mutually exclusive")
    repo = ctx.obj.repo
    if repo.state != KartRepoState.MERGING:
        raise CliError("Repository is not in 'merging' state")
    from kart_tpu.merge.index import ConflictEntry, MergeIndex

    merge_index = MergeIndex.read_from_repo(repo)
    if label not in merge_index.conflicts:
        # allow numeric-free fuzzy help
        known = ", ".join(sorted(merge_index.conflicts)[:5])
        raise CliError(f"No such conflict {label!r}. Known conflicts: {known} ...")
    if label in merge_index.resolves:
        raise CliError(f"Conflict {label!r} is already resolved")

    aot = merge_index.conflicts[label]
    if with_file:
        entries = _entries_from_file(repo, label, aot, with_file)
    elif with_version == "delete":
        entries = []
    else:
        entry = aot.get(with_version)
        entries = [entry] if entry is not None else []
    merge_index.add_resolve(label, entries)
    merge_index.write_to_repo(repo)
    remaining = len(merge_index.unresolved_labels)
    click.echo(
        f"Resolved 1 conflict. {remaining} conflicts to go."
        if remaining
        else 'Resolved 1 conflict. All conflicts resolved - run "kart merge --continue"'
    )


def _entries_from_file(repo, label, aot, path):
    """GeoJSON file -> resolution entries (reference: kart/resolve.py:22-66)."""
    from kart_tpu.core.structure import RepoStructure
    from kart_tpu.merge.index import ConflictEntry

    with open(path) as f:
        data = json.load(f)
    if data.get("type") == "FeatureCollection":
        geo_features = data["features"]
    elif data.get("type") == "Feature":
        geo_features = [data]
    else:
        raise CliError(f"{path}: not a GeoJSON Feature or FeatureCollection")

    sample = next((e for e in aot if e is not None), None)
    structure = RepoStructure(repo, "HEAD")
    ds_path, part, item = structure.decode_path(sample.path)
    if part != "feature":
        raise CliError("--with-file can only resolve feature conflicts")
    merge_head = repo.read_gitdir_file("MERGE_HEAD")
    ds = None
    for refish in ("HEAD", merge_head and merge_head.strip()):
        if not refish:
            continue
        ds = RepoStructure(repo, refish).datasets.get(ds_path)
        if ds is not None:
            break
    if ds is None:
        raise CliError(f"Cannot find dataset {ds_path!r}")

    from kart_tpu.geometry import geojson_to_geometry

    entries = []
    for geo_feature in geo_features:
        feature = dict(geo_feature.get("properties") or {})
        geom_col = ds.geom_column_name
        if geom_col and geo_feature.get("geometry") is not None:
            feature[geom_col] = geojson_to_geometry(geo_feature["geometry"])
        pk_cols = [c.name for c in ds.schema.pk_columns]
        for pk_col in pk_cols:
            if pk_col not in feature and geo_feature.get("id") is not None:
                feature[pk_col] = geo_feature["id"]
        full_path, blob = ds.encode_feature(feature)
        oid = repo.odb.write_blob(blob)
        entries.append(ConflictEntry(full_path, oid))
    return entries
