"""data ls / data version / meta get / meta set (reference: kart/data.py,
kart/meta.py)."""

import json

import click

from kart_tpu.cli import CliError, cli
from kart_tpu.core.repo import KartRepoState
from kart_tpu.diff.output import dump_json_output


@cli.group()
def data():
    """Information about the datasets in the repository."""


@data.command("ls")
@click.option("--output-format", "-o", type=click.Choice(["text", "json"]), default="text")
@click.option("--with-dataset-types", is_flag=True)
@click.argument("refish", required=False, default="HEAD")
@click.pass_obj
def data_ls(ctx, output_format, with_dataset_types, refish):
    """List datasets."""
    repo = ctx.repo
    if repo.head_is_unborn:
        paths = []
        datasets = []
    else:
        datasets = list(repo.datasets(refish))
        paths = [ds.path for ds in datasets]
    if output_format == "json":
        if with_dataset_types:
            # dataset-type annotations arrived with the v2 envelope
            value = [
                {"path": ds.path, "type": "table", "version": ds.VERSION}
                for ds in datasets
            ]
            dump_json_output({"kart.data.ls/v2": value}, "-")
        else:
            # reference 0.10.x shape: a plain path list under v1
            dump_json_output({"kart.data.ls/v1": paths}, "-")
        return
    if not paths:
        click.echo("Empty repository.", err=True)
        click.echo('  (use "kart import" to add some data)', err=True)
        return
    for p in paths:
        click.echo(p)


@data.command("version")
@click.option("--output-format", "-o", type=click.Choice(["text", "json"]), default="text")
@click.pass_obj
def data_version(ctx, output_format):
    """Show the repository structure version."""
    repo = ctx.repo
    version = repo.version
    if output_format == "json":
        dump_json_output(
            {"repostructure.version": version, "localconfig.branding": "kart"}, "-"
        )
        return
    click.echo(f"This Kart repo uses Datasets v{version}")


@cli.group()
def meta():
    """Read and update metadata for datasets."""


@meta.command("get")
@click.option("--output-format", "-o", type=click.Choice(["text", "json"]), default="text")
@click.option("--ref", default="HEAD")
@click.argument("dataset", required=True)
@click.argument("keys", nargs=-1)
@click.pass_obj
def meta_get(ctx, output_format, ref, dataset, keys):
    """Print meta items for a dataset."""
    repo = ctx.repo
    ds = repo.datasets(ref).get(dataset)
    if ds is None:
        raise CliError(f"No dataset {dataset!r} at {ref}")
    items = ds.meta_items()
    if keys:
        missing = [k for k in keys if k not in items]
        if missing:
            raise CliError(f"Couldn't find items: {', '.join(missing)}")
        items = {k: items[k] for k in keys}
    if output_format == "json":
        dump_json_output({dataset: items}, "-")
        return
    for name, value in items.items():
        click.secho(name, bold=True)
        if isinstance(value, (dict, list)):
            click.echo(json.dumps(value, indent=2))
        else:
            click.echo(str(value))
        click.echo()


@meta.command("set")
@click.option("--message", "-m", help="Commit message")
@click.argument("dataset")
@click.argument("assignments", nargs=-1, required=True)
@click.pass_obj
def meta_set(ctx, message, dataset, assignments):
    """Commit changes to meta items: kart meta set DATASET key=value ..."""
    from kart_tpu.diff.structs import (
        DatasetDiff,
        Delta,
        DeltaDiff,
        KeyValue,
        RepoDiff,
    )

    repo = ctx.repo
    structure = repo.structure("HEAD")
    ds = structure.datasets.get(dataset)
    if ds is None:
        raise CliError(f"No dataset {dataset!r}")
    items = ds.meta_items()
    meta_diff = DeltaDiff()
    for assignment in assignments:
        if "=" not in assignment:
            raise CliError(f"Expected key=value, got {assignment!r}")
        key, _, value = assignment.partition("=")
        if value.startswith("@"):
            with open(value[1:]) as f:
                value = f.read()
        if key.endswith(".json"):
            value = json.loads(value)
        old = items.get(key)
        meta_diff.add_delta(
            Delta(
                KeyValue((key, old)) if old is not None else None,
                KeyValue((key, value)),
            )
        )
    ds_diff = DatasetDiff()
    ds_diff["meta"] = meta_diff
    repo_diff = RepoDiff()
    repo_diff[dataset] = ds_diff
    msg = message or f"Update metadata for {dataset}"
    oid = structure.commit_diff(repo_diff, msg)
    wc = repo.working_copy
    if wc is not None:
        # non-force: only the dataset whose meta changed is rewritten;
        # uncommitted edits elsewhere survive
        wc.reset(repo.structure(oid))
    click.echo(f"Commit {oid[:7]}")


@cli.command("build-annotations")
@click.option("--all-reachable", is_flag=True)
@click.pass_obj
def build_annotations(ctx, all_reachable):
    """Pre-compute diff feature-count annotations for commits."""
    from kart_tpu.annotations import DiffAnnotations

    repo = ctx.repo
    annotations = DiffAnnotations(repo)
    built = annotations.build_all(all_reachable=all_reachable)
    click.echo(f"Built annotations for {built} commit(s)")


@cli.command("commit-files")
@click.option("--message", "-m", required=True, help="Commit message")
@click.option("--ref", default="HEAD", help="Branch/ref to commit to")
@click.option("--allow-empty", is_flag=True, help="Commit even with no changes")
@click.option(
    "--remove-empty-files",
    is_flag=True,
    help="KEY= (empty value) removes the file instead of writing it empty",
)
@click.argument("items", nargs=-1, required=True)
@click.pass_obj
def commit_files(ctx, message, ref, allow_empty, remove_empty_files, items):
    """Commit arbitrary repository files: kart commit-files -m MSG KEY=VALUE...
    (VALUE may be @filename; reference: kart/meta.py commit-files)."""
    from kart_tpu.core.tree_builder import TreeBuilder

    repo = ctx.require_state(KartRepoState.NORMAL)
    parent_oid, ref_name = repo.resolve_refish(ref)
    if parent_oid is None:
        raise CliError(
            "Using commit-files to create the initial commit is not supported"
        )
    # commit to the *resolved* ref, or HEAD itself — passing a bare branch
    # name would write a stray gitdir/<name> file; and only branches may
    # move (resolve_refish also matches tags/remote-tracking refs, which a
    # commit must never silently repoint)
    commit_to = "HEAD" if ref == "HEAD" else ref_name
    if commit_to is None or (
        commit_to != "HEAD" and not commit_to.startswith("refs/heads/")
    ):
        raise CliError(f"{ref!r} is not a branch that can be committed to")
    parent = repo.odb.read_commit(parent_oid)

    tb = TreeBuilder(repo.odb, parent.tree)
    for item in items:
        if "=" not in item:
            raise CliError(f"Expected KEY=VALUE, got {item!r}")
        key, _, value = item.partition("=")
        segments = key.split("/")
        if not key or any(seg in ("", ".", "..") for seg in segments):
            # an empty/"."/".." path segment would write a tree git rejects
            raise CliError(f"Invalid repository path: {key!r}")
        if value.startswith("@"):
            try:
                with open(value[1:], "rb") as f:
                    data = f.read()
            except OSError as e:
                raise CliError(f"Cannot read {value[1:]!r}: {e}")
        else:
            data = value.encode()
        if remove_empty_files and not data:
            tb.remove(key)
        else:
            tb.insert(key, repo.odb.write_blob(data))
    new_tree = tb.flush()
    if new_tree == parent.tree and not allow_empty:
        raise CliError("No changes to commit")
    new_commit = repo.create_commit(commit_to, new_tree, message, [parent_oid])
    # keep the working copy's recorded tree in sync when HEAD moved —
    # non-force: uncommitted WC edits survive (the commit touched no
    # dataset features unless the user targeted one deliberately)
    if commit_to == "HEAD" or repo.head_branch == commit_to:
        wc = repo.working_copy
        if wc is not None:
            wc.reset(repo.structure(new_commit))
    click.echo(f"Committed {new_commit[:7]}")
