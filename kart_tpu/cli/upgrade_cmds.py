"""kart upgrade (reference: kart/upgrade/__init__.py CLI)."""

import click

from kart_tpu.cli import CliError, cli


@cli.command()
@click.option(
    "--in-place",
    is_flag=True,
    help="Upgrade the repository in place (V2→V3 reuses all feature blobs)",
)
@click.argument("source", type=click.Path(exists=True))
@click.argument("dest", type=click.Path(), required=False)
def upgrade(source, dest, in_place):
    """Upgrade a repository to the latest repo structure version (V3).

    SOURCE is the existing repo; DEST is the directory for the upgraded copy
    (omit with --in-place)."""
    from kart_tpu.core.repo import KartRepo, RepoError
    from kart_tpu.upgrade import UpgradeError, upgrade_in_place, upgrade_repo

    def progress(i, total):
        if i == total or i % 10 == 0:
            click.echo(f"  upgraded commit {i}/{total}")

    try:
        if in_place:
            if dest:
                raise CliError("--in-place takes no DEST argument")
            repo = KartRepo(source)
            commit_map = upgrade_in_place(repo, progress=progress)
            click.echo(f"Upgraded {len(commit_map)} commits in place to V3")
        else:
            if not dest:
                raise CliError("Missing argument: DEST (or use --in-place)")
            _, commit_map = upgrade_repo(source, dest, progress=progress)
            click.echo(f"Upgraded {len(commit_map)} commits into {dest}")
    except (UpgradeError, RepoError) as e:
        raise CliError(str(e))


@cli.command("upgrade-to-kart")
@click.argument("source", type=click.Path(exists=True, file_okay=False))
def upgrade_to_kart(source):
    """Upgrade in-place a Sno-branded repository to Kart branding: the .sno
    gitdir becomes .kart, sno.* config keys become kart.*, SNO_README.txt
    becomes KART_README.txt, and the working copy is recreated with
    kart-named state tables (reference: kart/upgrade upgrade-to-kart).
    History is untouched."""
    import os

    from kart_tpu.core.repo import KartConfigKeys, KartRepo, RepoError

    try:
        repo = KartRepo(source)
    except RepoError as e:
        raise CliError(str(e))

    gitdir = repo.gitdir
    workdir = repo.workdir
    basename = os.path.basename(gitdir)
    if basename == ".kart":
        raise CliError("Repository is already Kart-branded")
    config = repo.config
    if basename != ".sno" and config.get(
        KartConfigKeys.SNO_REPOSTRUCTURE_VERSION
    ) is None:
        raise CliError("Repository is already Kart-branded")

    # config keys first (the dir rename invalidates `repo`)
    renames = {
        KartConfigKeys.SNO_REPOSTRUCTURE_VERSION:
            KartConfigKeys.KART_REPOSTRUCTURE_VERSION,
        KartConfigKeys.SNO_WORKINGCOPY_PATH:
            KartConfigKeys.KART_WORKINGCOPY_LOCATION,
    }
    for old_key, new_key in renames.items():
        value = config.get(old_key)
        if value is not None:
            config[new_key] = value
            del config[old_key]

    if basename == ".sno":
        new_gitdir = os.path.join(os.path.dirname(gitdir), ".kart")
        os.rename(gitdir, new_gitdir)
        KartRepo._write_locked_index(new_gitdir)

    if workdir is not None:
        old_readme = os.path.join(workdir, "SNO_README.txt")
        if os.path.exists(old_readme):
            os.rename(old_readme, os.path.join(workdir, "KART_README.txt"))

    # recreate the working copy so its state tables use kart names (a
    # sno-era WC has sno-named tables, which get_working_copy treats as
    # uninitialised — hence allow_uncreated)
    from kart_tpu.workingcopy import get_working_copy

    repo = KartRepo(source)
    wc = get_working_copy(repo, allow_uncreated=True)
    if wc is not None and repo.head_commit_oid is not None:
        structure = repo.structure("HEAD")
        wc.create_and_initialise()
        wc.write_full(structure, *structure.datasets)
    click.echo(f"Upgraded {source} to Kart branding")


@cli.command("upgrade-to-tidy")
@click.argument("source", type=click.Path(exists=True, file_okay=False))
def upgrade_to_tidy(source):
    """Upgrade in-place a bare-style repository (gitdir contents directly in
    the repo directory) to tidy-style (a .kart subdirectory), leaving
    contents and version untouched (reference: kart/upgrade
    upgrade-to-tidy)."""
    import os

    from kart_tpu.core.repo import KartRepo, RepoError

    try:
        repo = KartRepo(source)
    except RepoError as e:
        raise CliError(str(e))
    if repo.workdir is not None:
        raise CliError("Cannot upgrade in-place - repo is already tidy-style")
    if repo.config.get_bool("core.bare"):
        raise CliError(
            "Repo is a true bare repo (core.bare=true), not bare-style; "
            "tidy layout needs a working directory"
        )

    gitdir = repo.gitdir
    new_gitdir = os.path.join(gitdir, ".kart")
    os.makedirs(new_gitdir, exist_ok=False)
    # move only git internals: user files (working-copy .gpkg, READMEs)
    # stay at the top level, which becomes the workdir
    internal = {
        "objects", "refs", "logs", "HEAD", "config", "packed-refs",
        "index", "shallow", "columnar", "annotations.db",
        "feature_envelopes.db", "MERGE_HEAD", "MERGE_MSG", "MERGE_BRANCH",
        "MERGE_INDEX", "info", "description", "hooks",
        # state files stock git creates (kart git fetch/reset/...)
        "FETCH_HEAD", "ORIG_HEAD", "COMMIT_EDITMSG", "branches",
    }
    for name in os.listdir(gitdir):
        if name in internal:
            os.rename(os.path.join(gitdir, name), os.path.join(new_gitdir, name))
    KartRepo._write_locked_index(new_gitdir)
    repo = KartRepo(source)
    repo.config["core.bare"] = "false"
    click.echo(f"Upgraded {source} to tidy-style")
