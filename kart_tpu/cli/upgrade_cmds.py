"""kart upgrade (reference: kart/upgrade/__init__.py CLI)."""

import click

from kart_tpu.cli import CliError, cli


@cli.command()
@click.option(
    "--in-place",
    is_flag=True,
    help="Upgrade the repository in place (V2→V3 reuses all feature blobs)",
)
@click.argument("source", type=click.Path(exists=True))
@click.argument("dest", type=click.Path(), required=False)
def upgrade(source, dest, in_place):
    """Upgrade a repository to the latest repo structure version (V3).

    SOURCE is the existing repo; DEST is the directory for the upgraded copy
    (omit with --in-place)."""
    from kart_tpu.core.repo import KartRepo, RepoError
    from kart_tpu.upgrade import UpgradeError, upgrade_in_place, upgrade_repo

    def progress(i, total):
        if i == total or i % 10 == 0:
            click.echo(f"  upgraded commit {i}/{total}")

    try:
        if in_place:
            if dest:
                raise CliError("--in-place takes no DEST argument")
            repo = KartRepo(source)
            commit_map = upgrade_in_place(repo, progress=progress)
            click.echo(f"Upgraded {len(commit_map)} commits in place to V3")
        else:
            if not dest:
                raise CliError("Missing argument: DEST (or use --in-place)")
            _, commit_map = upgrade_repo(source, dest, progress=progress)
            click.echo(f"Upgraded {len(commit_map)} commits into {dest}")
    except (UpgradeError, RepoError) as e:
        raise CliError(str(e))
